#!/usr/bin/env python
"""Headline benchmark: ALS training throughput at the ML-25M north star.

Workload (BASELINE.md): MovieLens-25M shape — 162,541 users x 59,047 items
x 25M ratings (zipf item popularity), rank 64, explicit ALS-WR.  Data is
generated deterministically (no dataset egress in this environment);
shapes, sparsity and skew match ML-25M.  ``PIO_BENCH_SCALE=0.04`` shrinks
everything proportionally for smoke runs; ``PIO_MESH`` runs the sharded
path.

Measurement is the SLOPE method: two full trainings that differ only in
iteration count, timed to a forced host read-back.  (T(I2) - T(I1)) /
(I2 - I1) cancels every fixed cost — host bucketing, H2D transfer,
dispatch and sync round-trips (hundreds of ms each through the remote-TPU
tunnel, and `jax.block_until_ready` does NOT actually block there) — and
yields pure per-iteration device throughput.  End-to-end wall time is
reported alongside.

MFU accounting (useful FLOPs only): per iteration, both sides —
gram+rhs builds 2*nnz_padded*K^2 + 2*nnz_padded*K, solves K^3/3 per
entity (Cholesky-equivalent; the GJ kernel's extra arithmetic is not
credited).  Peak = 197 TF/s (v5e bf16 headline).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
``vs_baseline`` compares against REF_BASELINE_SAMPLES_PER_SEC — a
measured-once Spark-local MLlib ALS figure of order 1e5 rating-updates/s
(no published reference number exists, BASELINE.md).  Extra keys record
MFU, end-to-end time, and the serving benchmark (recs/sec, p50/p99 for
python + native frontends — BASELINE.md metrics 2-3).
"""

import json
import os
import time

import numpy as np

# Persistent XLA compilation cache: the device-side prep program is large
# (hundreds of seconds to compile cold at the full shape) but identical
# across bench invocations; cache it on disk so only the first-ever run
# pays.  Applies to every jitted program in the process.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")

REF_BASELINE_SAMPLES_PER_SEC = 250_000.0  # Spark-local MLlib ALS, ML scale
PEAK_FLOPS = 197e12  # TPU v5e bf16 headline

SCALE = float(os.environ.get("PIO_BENCH_SCALE", "1.0"))
N_USERS = max(64, int(162_541 * SCALE))
N_ITEMS = max(64, int(59_047 * SCALE))
N_RATINGS = max(4096, int(25_000_000 * SCALE))
RANK = 64
I1, I2 = 2, 12


def synth_ml25m(seed=0):
    rng = np.random.default_rng(seed)
    users = rng.integers(0, N_USERS, N_RATINGS)
    items = (rng.zipf(1.25, size=N_RATINGS) % N_ITEMS).astype(np.int64)
    # Half-star ratings 0.5..5.0 like ML-25M.
    ratings = (rng.integers(1, 11, N_RATINGS) * 0.5).astype(np.float32)
    return users, items, ratings


def useful_flops_per_iter(inputs):
    """Padded-nnz gram/rhs + Cholesky-equivalent solve FLOPs, both sides.

    Counted off the ACTUAL device buckets (incl. mesh row padding and HBM
    chunk padding) so the reported MFU matches the dispatched program.
    """
    total = 0.0
    for buckets in (inputs.user_buckets, inputs.item_buckets):
        padded_nnz = 0
        n_solved = 0
        for kind, idx, *rest in buckets:
            padded_nnz += idx.size
            n_solved += (rest[-1].shape[0] if kind == "merged"
                         else idx.shape[0])
        total += 2 * padded_nnz * RANK * RANK + 2 * padded_nnz * RANK
        total += n_solved * RANK ** 3 / 3
    return total


def _barrier_all(*args):
    """True completion barrier (block_until_ready does not block through
    the remote-TPU tunnel): force a scalar host read per array."""
    import jax.numpy as jnp

    *arrs, t0 = args
    for a in arrs:
        float(jnp.sum(a.astype(jnp.float32)))
    return time.perf_counter() - t0


def _barrier_inputs(inputs, t0):
    import jax.numpy as jnp

    tot = 0.0
    for buckets in (inputs.user_buckets, inputs.item_buckets):
        for _, idx, *rest in buckets:
            tot += float(jnp.sum(idx[0].astype(jnp.float32)))
    tot += float(jnp.sum(inputs.uf0[0]))
    return time.perf_counter() - t0


def train_bench():
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.models.als import (
        ALSConfig, prepare_als_inputs, train_als_prepared,
    )
    from predictionio_tpu.parallel.mesh import mesh_from_spec

    mesh = mesh_from_spec(os.environ.get("PIO_MESH", ""))
    users, items, ratings = synth_ml25m()
    # Run-unique jitter defeats any result caching between bench invocations
    # (the remote-TPU tunnel memoizes identical program+input executions);
    # identical shapes, different values.
    ratings = ratings + np.float32((time.time_ns() % 997) * 1e-6)

    cfg = ALSConfig(rank=RANK, iterations=I1, reg=0.01, seed=1)
    # Compact COO up once (12 B/rating); the layout transform runs on the
    # device (ops/device_prep.py).  h2d_coo_s is reported separately from
    # prep: this harness reaches the TPU through a ~9 MB/s tunnel (measured
    # with plain jnp.asarray of a 256 MB block), so the 300 MB COO upload
    # costs ~30 s HERE while the same transfer rides PCIe in production
    # (<0.1 s at >10 GB/s).  prep_upload_s is the algorithmic cost: device
    # bucketing + factor init, warm (compile cached; retrains reuse it).
    t0 = time.perf_counter()
    du = jnp.asarray(users.astype(np.int32))
    di = jnp.asarray(items.astype(np.int32))
    dr = jnp.asarray(ratings)
    h2d_s = _barrier_all(du, di, dr, t0)

    t0 = time.perf_counter()
    inputs = prepare_als_inputs(du, di, dr, N_USERS, N_ITEMS, cfg, mesh=mesh)
    prep_cold_s = _barrier_inputs(inputs, t0)
    t0 = time.perf_counter()
    inputs = prepare_als_inputs(du, di, dr, N_USERS, N_ITEMS, cfg, mesh=mesh)
    prep_s = _barrier_inputs(inputs, t0)

    def sync(m):
        return float(jnp.sum(m.user_factors))  # host read = real barrier

    def run(iters):
        cfg = ALSConfig(rank=RANK, iterations=iters, reg=0.01, seed=1)
        t0 = time.perf_counter()
        m = train_als_prepared(inputs, cfg)
        sync(m)
        return time.perf_counter() - t0, m

    run(I1)  # compile (iteration count is a dynamic loop bound: one compile)
    # Slope over device-resident inputs: identical fixed costs, the only
    # difference between the runs is I2 - I1 device iterations.
    t1, _ = run(I1)
    t2, m = run(I2)
    per_iter = max((t2 - t1) / (I2 - I1), 1e-9)

    n_chips = max(1, len(jax.devices()))
    samples_per_sec_chip = N_RATINGS / per_iter / n_chips
    mfu = useful_flops_per_iter(inputs) / per_iter / PEAK_FLOPS
    return {
        "value": round(samples_per_sec_chip, 1),
        "per_iter_ms": round(per_iter * 1e3, 2),
        "mfu_pct": round(100 * mfu, 2),
        "prep_upload_s": round(prep_s, 2),
        "prep_cold_s": round(prep_cold_s, 2),
        "h2d_coo_s": round(h2d_s, 2),       # tunnel artifact, see comment
        "e2e_full_train_s": round(h2d_s + prep_s + t2, 2),
        "n_chips": n_chips,
        "shape": f"{N_USERS}x{N_ITEMS}x{N_RATINGS} rank{RANK}",
        "mesh": os.environ.get("PIO_MESH") or None,
    }


def serving_bench():
    """BASELINE.md metrics 2-3, recorded into the round artifact."""
    try:
        import bench_serving

        eng, variant, storage, n_users = bench_serving._setup()
        from predictionio_tpu.server import EngineServer

        out = {}
        srv = EngineServer(eng, variant, storage, host="127.0.0.1", port=0)
        srv.start()
        out["python"] = bench_serving._drive(srv.port, n_users, 16, 1500)
        srv.stop()
        try:
            from predictionio_tpu.native.frontend import NativeFrontend

            fe = NativeFrontend(srv.query_batch, host="127.0.0.1", port=0,
                                max_batch=64, max_wait_us=1000)
            fe.start()
            out["native"] = bench_serving._drive(fe.port, n_users, 16, 1500)
            fe.stop()
        except RuntimeError as e:
            out["native"] = {"error": str(e)}
        return out
    except Exception as e:  # serving bench must never sink the train bench
        return {"error": f"{type(e).__name__}: {e}"}


def main():
    train = train_bench()
    serving = serving_bench()
    value = train.pop("value")
    print(json.dumps({
        "metric": "als_train_samples_per_sec_per_chip",
        "value": value,
        "unit": "ratings*iters/sec/chip",
        "vs_baseline": round(value / REF_BASELINE_SAMPLES_PER_SEC, 3),
        "train": train,
        "serving": serving,
    }))


if __name__ == "__main__":
    main()
