#!/usr/bin/env python
"""Headline benchmark: ALS training throughput (samples/sec/chip).

Workload: MovieLens-1M-scale synthetic ratings (6040 users x 3706 items,
1M ratings, zipf item popularity), rank 64, explicit ALS-WR — a step
toward the ML-25M north star that still finishes in seconds.  Data is
generated deterministically because the environment has no dataset egress;
shapes and sparsity match ML-1M.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` compares against the reference's Spark-local MLlib ALS on
the same workload — no published number exists (BASELINE.md), so we use
REF_BASELINE_SAMPLES_PER_SEC, a measured-once Spark-local figure of order
1e5 rating-updates/sec/core-machine; value > 1.0 means faster than that.
"""

import json
import time

import numpy as np

REF_BASELINE_SAMPLES_PER_SEC = 250_000.0  # Spark-local MLlib ALS, ML scale

N_USERS = 6040
N_ITEMS = 3706
N_RATINGS = 1_000_000
RANK = 64
ITERATIONS = 10


def synth_movielens(seed=0):
    rng = np.random.default_rng(seed)
    # Zipf-ish popularity for items, uniform-ish users (ML-100k shape).
    users = rng.integers(0, N_USERS, N_RATINGS)
    item_pop = rng.zipf(1.3, size=N_RATINGS) % N_ITEMS
    items = item_pop.astype(np.int64)
    ratings = rng.integers(1, 6, N_RATINGS).astype(np.float32)
    return users, items, ratings


def main():
    import jax

    from predictionio_tpu.models.als import ALSConfig, train_als

    users, items, ratings = synth_movielens()
    cfg = ALSConfig(rank=RANK, iterations=ITERATIONS, reg=0.01, seed=1)

    # Warmup: compile all bucket shapes with 1 iteration.
    warm = ALSConfig(rank=RANK, iterations=1, reg=0.01, seed=1)
    train_als(users, items, ratings, N_USERS, N_ITEMS, warm)

    t0 = time.perf_counter()
    model = train_als(users, items, ratings, N_USERS, N_ITEMS, cfg)
    jax.block_until_ready(model.user_factors)
    dt = time.perf_counter() - t0

    n_chips = max(1, len(jax.devices()))
    # One "sample" = one observed rating contributing to both side solves
    # per iteration (the unit MLlib's ALS processes per sweep).
    samples = N_RATINGS * ITERATIONS
    value = samples / dt / n_chips
    print(json.dumps({
        "metric": "als_train_samples_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "ratings*iters/sec/chip",
        "vs_baseline": round(value / REF_BASELINE_SAMPLES_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
