#!/usr/bin/env python
"""Headline benchmark: ALS training throughput at the ML-25M north star.

Workload (BASELINE.md): MovieLens-25M shape — 162,541 users x 59,047 items
x 25M ratings (zipf item popularity), rank 64, explicit ALS-WR.  Data is
generated deterministically (no dataset egress in this environment);
shapes, sparsity and skew match ML-25M.  ``PIO_BENCH_SCALE=0.04`` shrinks
everything proportionally for smoke runs; ``PIO_MESH`` runs the sharded
path.

Measurement is the SLOPE method: two full trainings that differ only in
iteration count, timed to a forced host read-back.  (T(I2) - T(I1)) /
(I2 - I1) cancels every fixed cost — host bucketing, H2D transfer,
dispatch and sync round-trips (hundreds of ms each through the remote-TPU
tunnel, and `jax.block_until_ready` does NOT actually block there) — and
yields pure per-iteration device throughput.  End-to-end wall time is
reported alongside.

MFU accounting (useful FLOPs only): per iteration, both sides —
gram+rhs builds 2*nnz_padded*K^2 + 2*nnz_padded*K, solves K^3/3 per
entity (Cholesky-equivalent; the GJ kernel's extra arithmetic is not
credited).  Peak = 197 TF/s (v5e bf16 headline).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
``vs_baseline`` is the per-iteration speedup vs THIS framework's own
round-3 measurement (250.4 ms/iter at the full ML-25M shape,
BENCH_r03.json) — a reproducible yardstick, unlike the earlier ratio
against a one-off Spark-local MLlib figure no one can re-run (round-3
verdict item 8; the hardware-honest headline numbers are ``mfu_pct`` and
``phase_ms``).  Extra keys record MFU, end-to-end time, and the serving
benchmark (recs/sec, p50/p99 for python + native frontends — BASELINE.md
metrics 2-3).
"""

import json
import os
import time

import numpy as np

# Persistent XLA compilation cache: the device-side prep program is large
# (hundreds of seconds to compile cold at the full shape) but identical
# across bench invocations; cache it on disk so only the first-ever run
# pays.  Applies to every jitted program in the process.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")

# Round-3 per-iteration time at the full ML-25M shape (BENCH_r03.json) —
# the self-baseline vs_baseline is computed against.  Only meaningful at
# SCALE=1; smoke runs report vs_baseline=None.
R3_PER_ITER_MS = 250.39
PEAK_FLOPS = 197e12  # TPU v5e bf16 headline

SCALE = float(os.environ.get("PIO_BENCH_SCALE", "1.0"))
N_USERS = max(64, int(162_541 * SCALE))
N_ITEMS = max(64, int(59_047 * SCALE))
N_RATINGS = max(4096, int(25_000_000 * SCALE))
RANK = 64
# Slope iteration counts: at small smoke scales a 10-iteration delta
# sinks below the tunnel's timing noise (~100 ms), so widen the gap.
I1 = 2
I2 = 12 if SCALE >= 0.2 else 102


def synth_ml25m(seed=0):
    rng = np.random.default_rng(seed)
    users = rng.integers(0, N_USERS, N_RATINGS)
    items = (rng.zipf(1.25, size=N_RATINGS) % N_ITEMS).astype(np.int64)
    # Half-star ratings 0.5..5.0 like ML-25M.
    ratings = (rng.integers(1, 11, N_RATINGS) * 0.5).astype(np.float32)
    return users, items, ratings


def useful_flops_per_iter(inputs):
    """Padded-nnz gram/rhs + Cholesky-equivalent solve FLOPs, both sides.

    Counted off the device bucket arrays (incl. mesh row padding; the
    in-graph HBM chunk expansion adds a little more row padding that is
    NOT credited here, so MFU is if anything slightly under-reported).
    """
    total = 0.0
    for buckets in (inputs.user_buckets, inputs.item_buckets):
        padded_nnz = 0
        n_solved = 0
        for kind, idx, *rest in buckets:
            padded_nnz += idx.size
            n_solved += (rest[-1].shape[0] if kind == "merged"
                         else idx.shape[0])
        total += 2 * padded_nnz * RANK * RANK + 2 * padded_nnz * RANK
        total += n_solved * RANK ** 3 / 3
    return total


def _barrier_all(*args):
    """True completion barrier (block_until_ready does not block through
    the remote-TPU tunnel): force a scalar host read per array."""
    import jax.numpy as jnp

    *arrs, t0 = args
    for a in arrs:
        float(jnp.sum(a.astype(jnp.float32)))
    return time.perf_counter() - t0


def _barrier_inputs(inputs, t0):
    import jax.numpy as jnp

    # ONE fused readback: each float() through the tunnel costs ~80 ms,
    # and there are ~60 buckets — per-bucket reads would bill ~5 s of
    # measurement overhead to prep.
    parts = [inputs.uf0[0, 0]]
    for buckets in (inputs.user_buckets, inputs.item_buckets):
        for _, idx, *rest in buckets:
            parts.append(idx[0, 0].astype(jnp.float32))
    float(jnp.sum(jnp.stack(parts)))
    return time.perf_counter() - t0


def store_bench():
    """The event STORE in the north-star loop (VERDICT r4 item 1): 25M
    synthetic rate events are bulk-ingested into a parquet event store
    (``Events.insert_columnar`` — the columnar half of ``pio import``),
    scanned back through the recommendation template's EXACT read path
    (``RecommendationDataSource.read_training`` → unordered projected
    ``find_columnar`` → dictionary-encoded COO extraction), verified
    row-for-row against the source arrays, and the scanned COO feeds the
    headline train bench — "train + serve end-to-end, no Spark" with the
    store actually in the loop.  The streamed JSONL ``pio import`` path
    is rated on a sample (its per-line JSON parse is the known cost; the
    columnar path exists precisely to skip it)."""
    import shutil
    import tempfile

    import pyarrow as pa

    from predictionio_tpu.config import load_config
    from predictionio_tpu.controller.base import RuntimeContext
    from predictionio_tpu.data.storage import Storage
    from predictionio_tpu.data.storage.base import App
    from predictionio_tpu.data.store import EventStore
    from predictionio_tpu.templates.recommendation.engine import (
        DataSourceParams, RecommendationDataSource,
    )

    users, items, ratings = synth_ml25m()
    home = tempfile.mkdtemp(prefix="pio_bench_store_")
    out = {"n_events": int(N_RATINGS)}
    try:
        cfg = load_config(env={
            "PIO_HOME": home,
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "PARQUET",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEMORY",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEMORY",
        })
        storage = Storage(cfg)
        app_id = storage.get_apps().insert(App(id=None, name="bench"))
        events = storage.get_events()
        events.init(app_id)

        # --- streamed JSONL `pio import` path, rated on a sample (into
        # its own app so the bulk scan below sees exactly the 25M set)
        sample_app = storage.get_apps().insert(App(id=None, name="benchjl"))
        events.init(sample_app)
        sample = min(N_RATINGS, 200_000)
        jl = os.path.join(home, "events.jsonl")
        with open(jl, "w") as f:
            for k in range(sample):
                f.write(json.dumps({
                    "event": "rate", "entityType": "user",
                    "entityId": f"u{users[k]}", "targetEntityType": "item",
                    "targetEntityId": f"i{items[k]}",
                    "properties": {"rating": float(ratings[k])},
                    "eventTime": "2026-07-01T00:00:00.000Z"}) + "\n")
        from predictionio_tpu.data.json_support import event_from_json

        t0 = time.perf_counter()
        chunk = []
        imported = 0
        with open(jl) as f:
            for line in f:
                chunk.append(event_from_json(json.loads(line)))
                if len(chunk) >= 50_000:
                    imported += len(events.insert_batch(
                        chunk, sample_app, None))
                    chunk = []
        if chunk:
            imported += len(events.insert_batch(chunk, sample_app, None))
        jsonl_s = time.perf_counter() - t0
        out["import_jsonl_events_per_sec"] = round(imported / jsonl_s, 1)
        events.remove(sample_app)

        # --- bulk columnar ingest: ids/properties as dictionary columns
        # (162k/59k/10 uniques over 25M rows — index width per row)
        t0 = time.perf_counter()

        def dcol(idx, vals):
            return pa.DictionaryArray.from_arrays(
                pa.array(idx, type=pa.int32()), pa.array(vals))

        n = N_RATINGS
        zeros = np.zeros(n, np.int32)
        table = pa.table({
            "event": dcol(zeros, ["rate"]),
            "entity_type": dcol(zeros, ["user"]),
            "entity_id": dcol(users.astype(np.int32),
                              [f"u{i}" for i in range(N_USERS)]),
            "target_entity_type": dcol(zeros, ["item"]),
            "target_entity_id": dcol(items.astype(np.int32),
                                     [f"i{i}" for i in range(N_ITEMS)]),
            "properties_json": dcol(
                (ratings * 2).astype(np.int32) - 1,
                ['{"rating": %.1f}' % (k * 0.5) for k in range(1, 11)]),
            "event_time_us": pa.array(
                np.arange(n, dtype=np.int64) + 1_750_000_000_000_000),
        })
        build_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        assert events.insert_columnar(table, app_id) == n
        import_s = time.perf_counter() - t0
        del table
        out["import_columnar_s"] = round(build_s + import_s, 2)
        out["import_columnar_events_per_sec"] = round(
            n / (build_s + import_s), 1)

        # --- scan → COO through the template's real read path
        ds = RecommendationDataSource(DataSourceParams(appName="bench"))
        ctx = RuntimeContext(storage=storage,
                             event_store=EventStore(storage))
        t0 = time.perf_counter()
        data = ds.read_training(ctx)
        scan_s = time.perf_counter() - t0
        out["scan_to_coo_s"] = round(scan_s, 2)
        out["scan_to_coo_events_per_sec"] = round(n / scan_s, 1)

        # --- verify the store round-trip bit-for-bit (code → original id)
        uk = np.empty(len(data.user_index), np.int64)
        for k, c in data.user_index.items():
            uk[c] = int(k[1:])
        ik = np.empty(len(data.item_index), np.int64)
        for k, c in data.item_index.items():
            ik[c] = int(k[1:])
        ok = (len(data.ratings) == n
              and np.array_equal(uk[data.user_ids], users)
              and np.array_equal(ik[data.item_ids], items)
              and np.array_equal(data.ratings, ratings))
        out["roundtrip_verified"] = bool(ok)
        if ok:
            out["coo"] = (data.user_ids, data.item_ids, data.ratings,
                          len(data.user_index), len(data.item_index))
    except Exception as e:
        out["error"] = f"{type(e).__name__}: {e}"
    finally:
        shutil.rmtree(home, ignore_errors=True)
    return out


def train_bench(coo=None):
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.models.als import (
        ALSConfig, prepare_als_inputs, train_als_prepared,
    )
    from predictionio_tpu.parallel.mesh import mesh_from_spec

    mesh = mesh_from_spec(os.environ.get("PIO_MESH", ""))
    if coo is not None:
        # the store bench's scanned COO: the north-star train runs on
        # data that went through ingest → store → columnar scan
        users, items, ratings, n_users, n_items = coo
    else:
        users, items, ratings = synth_ml25m()
        n_users, n_items = N_USERS, N_ITEMS
    # Run-unique jitter defeats any result caching between bench invocations
    # (the remote-TPU tunnel memoizes identical program+input executions);
    # identical shapes, different values.
    ratings = ratings + np.float32((time.time_ns() % 997) * 1e-6)

    cfg = ALSConfig(rank=RANK, iterations=I1, reg=0.01, seed=1)
    # Compact COO up once (12 B/rating); the layout transform runs on the
    # device (ops/device_prep.py).  h2d_coo_s is reported separately from
    # prep: this harness reaches the TPU through a ~9 MB/s tunnel (measured
    # with plain jnp.asarray of a 256 MB block), so the 300 MB COO upload
    # costs ~30 s HERE while the same transfer rides PCIe in production
    # (<0.1 s at >10 GB/s).  prep_upload_s is the algorithmic cost: device
    # bucketing + factor init, warm (compile cached; retrains reuse it).
    t0 = time.perf_counter()
    du = jnp.asarray(users.astype(np.int32))
    di = jnp.asarray(items.astype(np.int32))
    dr = jnp.asarray(ratings)
    h2d_s = _barrier_all(du, di, dr, t0)

    t0 = time.perf_counter()
    inputs = prepare_als_inputs(du, di, dr, n_users, n_items, cfg, mesh=mesh,
                                host_ids=(users, items))
    prep_cold_s = _barrier_inputs(inputs, t0)

    def sync(m):
        return float(jnp.sum(m.user_factors))  # host read = real barrier

    # First-ever train: waits on the loop executable the prep pre-warm
    # overlapped (models/als.py); its remaining compile time is the real
    # first-train cost a cold `pio train` pays after prep.
    t0 = time.perf_counter()
    sync(train_als_prepared(inputs, cfg))
    first_train_s = time.perf_counter() - t0

    # Warm re-prep AFTER the loop compile resolved = the steady-state
    # retrain cost (measuring it mid-compile added ~20 s of GIL/tunnel
    # contention that no steady-state retrain sees).
    t0 = time.perf_counter()
    inputs = prepare_als_inputs(du, di, dr, n_users, n_items, cfg, mesh=mesh,
                                host_ids=(users, items))
    prep_s = _barrier_inputs(inputs, t0)

    def run(iters):
        cfg = ALSConfig(rank=RANK, iterations=iters, reg=0.01, seed=1)
        t0 = time.perf_counter()
        m = train_als_prepared(inputs, cfg)
        sync(m)
        return time.perf_counter() - t0, m

    run(I1)  # warm dispatch on the re-prepped inputs
    # Slope over device-resident inputs: identical fixed costs, the only
    # difference between the runs is I2 - I1 device iterations.
    t1, _ = run(I1)
    t2, m = run(I2)
    per_iter = max((t2 - t1) / (I2 - I1), 1e-9)
    phases = phase_profile(inputs)

    n_chips = max(1, len(jax.devices()))
    samples_per_sec_chip = N_RATINGS / per_iter / n_chips
    mfu = useful_flops_per_iter(inputs) / per_iter / PEAK_FLOPS
    return {
        "value": round(samples_per_sec_chip, 1),
        "per_iter_ms": round(per_iter * 1e3, 2),
        "mfu_pct": round(100 * mfu, 2),
        "prep_upload_s": round(prep_s, 2),
        "prep_cold_s": round(prep_cold_s, 2),
        # prep_cold_s CONTAINS the overlapped loop lowering+compile start
        # (rounds ≤3 paid the whole ~75 s loop compile invisibly after
        # prep); first_train_s is the residual wait on that compile, so
        # cold end-to-end = h2d + prep_cold + first_train.
        "first_train_s": round(first_train_s, 2),
        "e2e_cold_s": round(h2d_s + prep_cold_s + first_train_s, 2),
        "h2d_coo_s": round(h2d_s, 2),       # tunnel artifact, see comment
        "e2e_full_train_s": round(h2d_s + prep_s + t2, 2),
        "n_chips": n_chips,
        "phase_ms": phases,   # per-iteration device-time breakdown
        "padding": _padding_stats(inputs),
        "shape": f"{n_users}x{n_items}x{N_RATINGS} rank{RANK}",
        "mesh": os.environ.get("PIO_MESH") or None,
    }


def _padding_stats(inputs):
    """Attribute the residual gather padding (VERDICT r4 item 7): per
    side, padded [R, L] slots vs real nnz, and the dispatch chunk count.
    In-graph HBM chunk expansion adds a little more row padding that is
    not counted here (same convention as useful_flops_per_iter)."""
    out = {}
    specs = inputs.chunk_specs
    for i, (side, buckets) in enumerate((("user", inputs.user_buckets),
                                         ("item", inputs.item_buckets))):
        padded = sum(int(np.prod(b[1].shape)) for b in buckets)
        if specs is not None:
            n_chunks = sum(max(len(s[-1]), 1) for s in specs[i])
        else:
            n_chunks = len(buckets)
        out[f"{side}_padded_slots"] = padded
        out[f"{side}_pad_ratio"] = round(padded / max(N_RATINGS, 1), 3)
        out[f"{side}_chunks"] = n_chunks
    return out


def train_blocked_bench(coo=None):
    """Blocked (factor-sharded) ALS per-iteration on a real mesh — even
    1 device (VERDICT r4 item 3b): the sharded path had only ever been
    equivalence-tested on CPU meshes, never TIMED on the chip.  Slope
    method, same shape as the headline train.  On a 1-device axis the
    windowed gather auto-skips (no cross-shard transient to shrink; its
    second gather level measured ~3% per-iter — 288 vs 280 ms), so
    ``windowed_chunks`` is 0 here.  The blocked-vs-replicated gap itself
    (~280 vs ~177 ms) is the sharded-mode machinery: host-path prep
    layout + GSPMD sharding constraints, the price of a factor state
    that scales 1/n_chips — windows engage from 2 shards up, where they
    are the difference between fitting HBM and not (BASELINE.md)."""
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.models.als import (
        ALSConfig, prepare_als_inputs, train_als_prepared,
    )
    from predictionio_tpu.parallel.mesh import AXIS_DATA, make_mesh

    out = {}
    try:
        if coo is not None:
            users, items, ratings, n_users, n_items = coo
        else:
            users, items, ratings = synth_ml25m()
            n_users, n_items = N_USERS, N_ITEMS
        ratings = ratings + np.float32((time.time_ns() % 991) * 1e-6)
        mesh = make_mesh({AXIS_DATA: max(1, len(jax.devices()))})
        cfg = ALSConfig(rank=RANK, iterations=1, reg=0.01, seed=1,
                        factor_sharding="sharded")
        t0 = time.perf_counter()
        inputs = prepare_als_inputs(users, items, ratings, n_users,
                                    n_items, cfg, mesh=mesh,
                                    host_ids=(users, items))
        # The mesh path buckets on HOST and uploads the padded buckets
        # inside prep (there is no device-prep program for meshes), so
        # prep_s INCLUDES that H2D through the tunnel — not separable
        # here, and ~100x cheaper on a directly-attached host.
        out["prep_s"] = round(_barrier_inputs(inputs, t0), 2)
        out["prep_note"] = "includes padded-bucket H2D (tunnel)"

        def run(iters):
            c = ALSConfig(rank=RANK, iterations=iters, reg=0.01, seed=1,
                          factor_sharding="sharded")
            t0 = time.perf_counter()
            m = train_als_prepared(inputs, c)
            float(jnp.sum(m.user_factors))
            return time.perf_counter() - t0

        i2 = 6 if SCALE >= 0.2 else 51
        run(1)  # compile + warm
        t1, t2 = run(1), run(i2)
        per_iter = max((t2 - t1) / (i2 - 1), 1e-9)
        out["per_iter_ms"] = round(per_iter * 1e3, 2)
        out["n_chips"] = len(mesh.devices.flat)
        out["windowed_chunks"] = sum(
            1 for b in (*inputs.user_buckets, *inputs.item_buckets)
            if b[0].endswith("_w"))
    except Exception as e:
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def phase_profile(inputs, iters=4):
    """Per-phase device-time breakdown of the ALS iteration (round-2
    verdict item 1): capture one jax.profiler trace, aggregate the TPU
    op timeline into gather+gram / solve / copy / scatter / other buckets.
    Needs the tensorflow xplane protos; returns None when unavailable."""
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2  # noqa
    except Exception:
        return None
    import glob
    import re
    import tempfile

    import jax
    import jax.numpy as jnp

    from predictionio_tpu.models.als import ALSConfig, train_als_prepared

    with tempfile.TemporaryDirectory(prefix="pio_trace_") as td:
        with jax.profiler.trace(td):
            cfg = ALSConfig(rank=RANK, iterations=iters, reg=0.01, seed=1)
            m = train_als_prepared(inputs, cfg)
            float(jnp.sum(m.user_factors))
        paths = glob.glob(f"{td}/**/*.xplane.pb", recursive=True)
        if not paths:
            return None
        xs = xplane_pb2.XSpace()
        xs.ParseFromString(open(paths[0], "rb").read())
        tpu = [p for p in xs.planes if p.name.startswith("/device:TPU")]
        if not tpu:
            return None
        evm = {k: v.name for k, v in tpu[0].event_metadata.items()}
        phases = {"gather_gram": 0.0, "solve": 0.0, "copy": 0.0,
                  "scatter_misc": 0.0}
        for line in tpu[0].lines:
            if line.name != "XLA Ops":
                continue
            for ev in line.events:
                name = evm.get(ev.metadata_id, "")
                ms = ev.duration_ps / 1e9
                if name.startswith(("%while", "jit_")):
                    continue
                if "ridge_solve" in name:
                    phases["solve"] += ms
                elif "fused_gram" in name or re.match(r"%fusion", name):
                    # The round-4 Pallas gram custom-call belongs with the
                    # gather fusions: together they are the A/b build.
                    phases["gather_gram"] += ms
                elif re.match(r"%copy", name):
                    phases["copy"] += ms
                else:
                    phases["scatter_misc"] += ms
        return {k: round(v / iters, 2) for k, v in phases.items()}


def _bench_fuse_window(default: int = 8) -> int:
    """Fused steps per pipeline dispatch: ``PIO_FUSE_STEPS`` (the same
    knob the production train loops read), default 8 — the shape r06's
    private windows measured.  ``auto`` keeps the default (the bench is
    a fixed-configuration measurement, not a tuning run)."""
    from predictionio_tpu.data.fusion import fuse_steps_config

    k, auto = fuse_steps_config(default=default)
    return default if auto else k


def _feeder_pipeline(prefix, bs, cache_kwargs, next_batch, prep_batch,
                     run_window, barrier, dev_rate, window=None,
                     n_windows=None, model=None):
    """Shared feeder-in-the-loop measurement (two-tower + DLRM).

    Returns (feeder_examples_per_sec, pipeline_examples_per_sec,
    gap_pct): the feeder's host production rate over one full epoch,
    then the overlapped feeder→H2D→step loop — ``prep_batch`` stages ONE
    raw feeder batch to final host arrays, ``run_window`` dispatches a
    staged superbatch through the models' SHARED K-step fused scan
    (``train_steps_fused``) and returns the carried state, ``barrier``
    forces completion of the final state.

    Since ISSUE 7 this is the production path end-to-end: the ISSUE-5
    ``DevicePrefetcher`` itself assembles the superbatch
    (``fuse_steps=window`` — per-batch prep, K-stacking and H2D all on
    the prep thread, double-buffered under the dispatched windows) and
    the dispatch is the same fused program ``pio train`` runs.  The loop
    runs under a ``PipelineProbe`` with ``steps=K`` per dispatch, so the
    round artifact carries the per-model decomposition AND the fusion
    depth ``tools/attribute_gap.py`` reads."""
    import itertools
    import tempfile

    from predictionio_tpu.data.prefetch import DevicePrefetcher
    from predictionio_tpu.native.feeder import EventFeeder, write_cache
    from predictionio_tpu.obs import PipelineProbe

    window = _bench_fuse_window() if window is None else window
    if n_windows is None:
        # Comparable step totals across fusion depths: ~48 measured
        # steps (r06's shape at window 8), floor of 3 windows.
        n_windows = max(3, 48 // window)

    with tempfile.TemporaryDirectory(prefix=prefix) as td:
        cache = write_cache(f"{td}/c.piof", **cache_kwargs)
        fd = EventFeeder(cache, bs, seed=1)
        try:
            n_fb, t0 = 0, time.perf_counter()
            b = next_batch(fd)
            while b is not None:
                n_fb += len(b[0])
                b = next_batch(fd)
            feeder_rate = round(n_fb / (time.perf_counter() - t0), 1)
        finally:
            fd.close()

        fd2 = EventFeeder(cache, bs, seed=2)
        name = model or prefix.strip("_")
        probe = PipelineProbe(name)
        try:
            def batches():
                while True:
                    b = next_batch(fd2)
                    # epoch wrap (None) and ragged tails are skipped to
                    # keep the window's shapes static
                    if b is not None and len(b[0]) == bs:
                        yield b

            def put(arrays):
                import jax.numpy as jnp

                return tuple(jnp.asarray(a) for a in arrays)

            state, done = None, 0
            t0 = time.perf_counter()
            with DevicePrefetcher(
                    itertools.islice(batches(), n_windows * window),
                    prep_batch, put_fn=put, fuse_steps=window,
                    model=name) as pf:
                for batch in probe.iter_prefetched(pf):
                    probe.sync()  # wait on window N-1: its state carries
                    # async dispatch: the device chews this window while
                    # the prep thread assembles + uploads the next one
                    state = run_window(state, batch.args)
                    probe.dispatched(state, examples=batch.examples,
                                     steps=batch.steps)
                    done += batch.examples
                probe.finish()
                barrier(state)
                dt = time.perf_counter() - t0
        finally:
            fd2.close()
    pipe = round(done / dt, 1)
    gap = round(100 * (1 - pipe / dev_rate), 1) if dev_rate else None
    return feeder_rate, pipe, gap


def tpu_era_bench():
    """Two-tower + DLRM device training throughput (BASELINE.json's
    TPU-era configs).  Slope method over device-resident batches: the
    models' production loops stream per-step from host, which through
    THIS harness's tunnel costs ~150 ms of dispatch per step (measured
    51k ex/s end-to-end — a tunnel number, not a chip number).  A scan
    over staged batches times the chip itself — since ISSUE 7 via the
    models' SHARED fused dispatch (``train_steps_fused``), not a private
    bench-only loop: the ceiling, the pipeline loop, and ``pio train``
    all run the same program."""
    import jax
    import jax.numpy as jnp

    out = {}
    rng = np.random.default_rng(0)
    bs, n_stage = 8192, 8

    def step_slope(run):
        """Per-step device time via the slope method (shared by both
        models): run(n) executes an n-step fused superbatch and
        host-read-barriers.  Each distinct n is its own compiled scan
        program, so both shapes warm before timing.  Median of three
        slope pairs: this shared box swings host-visible timings ±40%
        run-to-run (BASELINE.md), which a single pair turns into a
        garbage ceiling — same policy as the host-side benches."""
        run(2)
        run(52)
        per_iter, _ = _median3_scalar(lambda: (run(52) - run(2)) / 50)
        return round(bs / max(per_iter, 1e-9), 1)
    # Run-unique value jitter: identical program+inputs would let the
    # tunnel's execution memoization serve cached results and collapse
    # the slope to dispatch noise (same defense as train_bench).
    jit_eps = np.float32((time.time_ns() % 997) * 1e-7)
    w_row = np.full(bs, 1.0 + jit_eps, np.float32)  # per-step weights
    try:
        from predictionio_tpu.models.two_tower import (
            TwoTowerConfig, TwoTowerState, init_state, train_steps_fused,
        )

        cfg = TwoTowerConfig(n_users=200_000, n_items=100_000, embed_dim=64,
                             hidden_dims=(128,), out_dim=64, batch_size=bs,
                             seed=0)
        st = init_state(cfg)
        u_h = rng.integers(0, cfg.n_users, (n_stage, bs)).astype(np.int32)
        i_h = rng.integers(0, cfg.n_items, (n_stage, bs)).astype(np.int32)

        def tt_state0():
            # Donation-safe: the fused dispatch consumes its inputs on
            # donation-capable backends, so every run starts from a
            # fresh copy (fixed cost — the slope cancels it).
            p, o, s = jax.tree.map(jnp.copy,
                                   (st.params, st.opt_state, st.step))
            return TwoTowerState(params=p, opt_state=o, step=s)

        def run_tt(n):
            # Stage BEFORE the timer: the [n, B] superbatch copy + H2D is
            # O(n) host work that would NOT cancel in the slope pairs and
            # deflates the chip ceiling (fresh arrays per run keep the
            # donating dispatch safe; the fixed-cost state copy cancels).
            idx = np.arange(n) % n_stage
            args = (jnp.asarray(u_h[idx]), jnp.asarray(i_h[idx]),
                    jnp.asarray(np.tile(w_row, (n, 1))))
            s0 = tt_state0()
            jax.block_until_ready(args)
            t0 = time.perf_counter()
            s, _ = train_steps_fused(s0, *args, cfg)
            float(jnp.sum(s.params["user_embed"][0]))
            return time.perf_counter() - t0

        out["two_tower_examples_per_sec_per_chip"] = step_slope(run_tt)

        # -- feeder in the loop (VERDICT r4 weak-1): the native mmap
        # feeder actually producing the batches the chip consumes.
        # feeder_* = host production rate (the claim that matters: can
        # the loader sustain the chip?); pipeline_* = the measured
        # overlapped feeder→H2D→fused-step loop.
        n_rows = max(bs * 16, int(800_000 * min(SCALE, 1.0)))

        def tt_prep(b):
            return (b[0].astype(np.int32), b[1].astype(np.int32), w_row)

        def tt_run(state, args):
            if state is None:
                state = tt_state0()
            s, _ = train_steps_fused(state, *args, cfg)
            return s

        feeder_rate, pipe, gap = _feeder_pipeline(
            "pio_feed_tt_", bs,
            dict(user_ids=rng.integers(0, cfg.n_users, n_rows),
                 item_ids=rng.integers(0, cfg.n_items, n_rows)),
            lambda fd: fd.next_batch(), tt_prep, tt_run,
            lambda s: float(jnp.sum(s.params["user_embed"][0])),
            out["two_tower_examples_per_sec_per_chip"],
            model="two_tower")
        out["two_tower_feeder_examples_per_sec"] = feeder_rate
        out["two_tower_pipeline_examples_per_sec"] = pipe
        out["two_tower_pipeline_gap_pct"] = gap
    except Exception as e:
        out["two_tower_error"] = f"{type(e).__name__}: {e}"

    try:
        from predictionio_tpu.models.dlrm import (
            DLRMConfig,
            DLRMState,
            init_state as dlrm_init,
            train_steps_fused as dlrm_steps_fused,
        )

        F = 8
        dcfg = DLRMConfig(vocab_sizes=(100_000,) * F, n_dense=13,
                          embed_dim=32, bottom_mlp=(64, 32),
                          top_mlp=(128, 64), batch_size=bs, seed=0)
        dst = dlrm_init(dcfg, None)
        dense_h = (rng.standard_normal((n_stage, bs, 13))
                   + jit_eps).astype(np.float32)
        # Global rows: the step consumes offsets-applied indices (the
        # production train() applies cfg.offsets before stepping).
        cat_h = (rng.integers(0, 100_000, (n_stage, bs, F))
                 + np.asarray(dcfg.offsets)[None, None, :]).astype(np.int32)
        y_h = (rng.random((n_stage, bs)) < 0.25).astype(np.float32)

        def dl_state0():
            p, o, s = jax.tree.map(jnp.copy,
                                   (dst.params, dst.opt_state, dst.step))
            return DLRMState(params=p, opt_state=o, step=s)

        def dl_barrier(s):
            return float(jnp.sum(
                jax.tree_util.tree_leaves(s.params)[0]).astype(jnp.float32))

        def run_dl(n):
            # Same staging-outside-the-timer discipline as run_tt.
            idx = np.arange(n) % n_stage
            args = (jnp.asarray(dense_h[idx]), jnp.asarray(cat_h[idx]),
                    jnp.asarray(y_h[idx]),
                    jnp.asarray(np.tile(w_row, (n, 1))))
            s0 = dl_state0()
            jax.block_until_ready(args)
            t0 = time.perf_counter()
            s, _ = dlrm_steps_fused(s0, *args, dcfg)
            dl_barrier(s)
            return time.perf_counter() - t0

        out["dlrm_examples_per_sec_per_chip"] = step_slope(run_dl)

        # -- feeder in the loop, DLRM shape (F categorical + 13 dense)
        n_rows = max(bs * 16, int(800_000 * min(SCALE, 1.0)))
        off = np.asarray(dcfg.offsets)[None, :]

        def dl_prep(b):
            c, y = b[0], b[1]
            extras = (b[2] if len(b) > 2
                      else np.zeros((len(y), 0), np.float32))
            return (np.asarray(extras, np.float32),
                    (c.astype(np.int64) + off).astype(np.int32),
                    np.asarray(y, np.float32), w_row)

        def dl_run(state, args):
            if state is None:
                state = dl_state0()
            s, _ = dlrm_steps_fused(state, *args, dcfg)
            return s

        feeder_rate, pipe, gap = _feeder_pipeline(
            "pio_feed_dl_", bs,
            dict(cats=rng.integers(0, 100_000,
                                   (n_rows, F)).astype(np.uint32),
                 values=(rng.random(n_rows) < 0.25).astype(np.float32),
                 extras=rng.standard_normal((n_rows, 13)).astype(
                     np.float32)),
            lambda fd: fd.next_batch_cats(), dl_prep, dl_run, dl_barrier,
            out["dlrm_examples_per_sec_per_chip"],
            model="dlrm")
        out["dlrm_feeder_examples_per_sec"] = feeder_rate
        out["dlrm_pipeline_examples_per_sec"] = pipe
        out["dlrm_pipeline_gap_pct"] = gap
    except Exception as e:
        out["dlrm_error"] = f"{type(e).__name__}: {e}"
    return out


def mips_bench():
    """Serving MIPS at a 1M-item corpus (VERDICT r4 item 6): the host
    fast path is right at ML-25M's 59k items and wrong at 1M+ — compare
    host vs device top-k latency per batch size.  Device numbers INCLUDE
    this harness's remote-TPU tunnel round-trip (~100 ms/dispatch, which
    a directly-attached production host does not pay); the crossover the
    table shows is therefore conservative for the device."""
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.ops.topk import host_top_k, top_k_scores

    n_items = 1_000_000 if SCALE >= 1.0 else max(65_536, int(1e6 * SCALE))
    rank, k = 64, 10
    rng = np.random.default_rng(5)
    itf_h = (rng.standard_normal((n_items, rank)) / 8).astype(np.float32)
    uf_h = (rng.standard_normal((64, rank)) / 8).astype(np.float32)
    out = {"n_items": n_items, "rank": rank, "k": k,
           "note": "device latency includes the remote-TPU tunnel RTT"}

    def pcts(lats):
        lats = sorted(lats)
        return (round(lats[len(lats) // 2] * 1e3, 2),
                round(lats[min(len(lats) - 1, int(0.99 * len(lats)))] * 1e3,
                      2))

    try:
        for b, reps in ((1, 20), (8, 10), (64, 3)):
            lats = []
            for _ in range(reps):
                t0 = time.perf_counter()
                host_top_k(uf_h[:b], itf_h, k)
                lats.append(time.perf_counter() - t0)
            p50, p99 = pcts(lats)
            out[f"host_b{b}_p50_ms"] = p50
            out[f"host_b{b}_p99_ms"] = p99
        itf_d = jnp.asarray(itf_h)
        float(jnp.sum(itf_d[0]))  # upload barrier (not billed per query)
        for b, reps in ((1, 20), (8, 10), (64, 10)):
            q = jnp.asarray(uf_h[:b])
            jax.device_get(top_k_scores(q, itf_d, k))  # compile warm
            lats = []
            for _ in range(reps):
                t0 = time.perf_counter()
                jax.device_get(top_k_scores(q, itf_d, k))
                lats.append(time.perf_counter() - t0)
            p50, p99 = pcts(lats)
            out[f"device_b{b}_p50_ms"] = p50
            out[f"device_b{b}_p99_ms"] = p99
    except Exception as e:
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def _median3_scalar(run):
    """Median of three runs + (min, max) spread.  Host-side numbers on
    this shared one-core box swing ±40% run-to-run (BASELINE.md), which
    made regressions < 1.4x invisible; the median of three tightens the
    trend line without pretending the noise away (the spread is
    reported).  One policy for every host-side section — serving wraps
    it for dict-shaped drives below."""
    vals = sorted(run() for _ in range(3))
    return vals[1], (vals[0], vals[2])


def _median_of(drives, key="throughput_rps"):
    """Dict-shaped counterpart of :func:`_median3_scalar`: returns the
    whole run whose ``key`` is the median, spread annotated."""
    runs = sorted([drives() for _ in range(3)],
                  key=lambda r: r.get(key, 0))
    med = dict(runs[1])
    med[f"{key}_spread"] = [runs[0].get(key), runs[2].get(key)]
    return med


def serving_bench():
    """BASELINE.md metrics 2-3, recorded into the round artifact."""
    try:
        import bench_serving

        eng, variant, storage, n_users = bench_serving._setup()
        from predictionio_tpu.server import EngineServer

        out = {}
        srv = EngineServer(eng, variant, storage, host="127.0.0.1", port=0)
        srv.start()
        out["python"] = _median_of(
            lambda: bench_serving._drive(srv.port, n_users, 32, 4000))
        srv.stop()
        fe = None
        try:
            from predictionio_tpu.native.frontend import NativeFrontend

            fe = NativeFrontend(srv.query_batch, host="127.0.0.1", port=0,
                                max_batch=64, max_wait_us=1000)
            fe.start()
            out["native"] = _median_of(
                lambda: bench_serving._drive(fe.port, n_users, 32, 4000))
        except Exception as e:
            # a failed native drive must not discard the (3x as
            # expensive) python result already measured above
            out["native"] = {"error": f"{type(e).__name__}: {e}"}
        finally:
            if fe is not None and fe.port is not None:
                fe.stop()  # leaked C++ threads would outlive the bench
        return out
    except Exception as e:  # serving bench must never sink the train bench
        return {"error": f"{type(e).__name__}: {e}"}


def ingest_bench(n_single=3000, n_batch=400, batch=50):
    """Event-server ingest throughput (round-2 verdict item 8c): real
    HTTP POST /events.json, single and batched, against sqlite-WAL."""
    try:
        import concurrent.futures
        import socket  # raw client; http.client throttled the measurement
        import tempfile
        import threading

        # ALWAYS a throwaway store — never write benchmark events into a
        # real PIO_HOME the user has configured.
        old_home = os.environ.get("PIO_HOME")
        os.environ["PIO_HOME"] = tempfile.mkdtemp(prefix="pio_ingest_")
        from predictionio_tpu.data.storage import (
            App, get_storage, reset_storage,
        )
        from predictionio_tpu.data.storage.base import AccessKey
        from predictionio_tpu.server.event_server import EventServer

        reset_storage()
        storage = get_storage()
        app_id = storage.get_apps().insert(App(id=None, name="ingestapp"))
        storage.get_events().init(app_id)
        key = storage.get_access_keys().insert(
            AccessKey.generate(app_id))
        srv = EventServer(storage, host="127.0.0.1", port=0)
        srv.start()
        url = f"/events.json?accessKey={key}"
        local = threading.local()


        def raw_post(port, attr, path, payload):
            # Persistent per-worker RAW connection: client and server
            # share this one-core host, so http.client machinery throttled
            # the measurement (same finding as the serving bench).
            body = json.dumps(payload).encode()
            raw = (b"POST " + path.encode() + b" HTTP/1.1\r\nHost: b\r\n"
                   b"Content-Type: application/json\r\nContent-Length: "
                   + str(len(body)).encode() + b"\r\n\r\n" + body)
            for attempt in (0, 1):
                try:
                    conn = getattr(local, attr, None)
                    if conn is None:
                        conn = socket.create_connection(
                            ("127.0.0.1", port), timeout=30)
                        conn.setsockopt(socket.IPPROTO_TCP,
                                        socket.TCP_NODELAY, 1)
                        setattr(local, attr, conn)
                    conn.sendall(raw)
                    buf = b""
                    while True:
                        part = conn.recv(65536)
                        if not part:
                            raise OSError("closed")
                        buf += part
                        end = buf.find(b"\r\n\r\n")
                        if end >= 0:
                            break
                    status = int(buf[9:12])
                    if status >= 400:
                        # Status errors are SERVER verdicts: never re-send
                        # (a 5xx after a committed insert would duplicate
                        # the event) — only connection faults retry.  The
                        # body may be partially unread; drop the conn.
                        try:
                            getattr(local, attr).close()
                        except Exception:
                            pass
                        setattr(local, attr, None)
                        raise RuntimeError(
                            f"ingest POST {path.split('?')[0]} -> {status}")
                    head = buf[:end].lower()
                    i = head.find(b"content-length:")
                    if i < 0:
                        # Malformed reply is a SERVER anomaly: drop the
                        # conn and surface it — resending could duplicate
                        # a committed event.
                        try:
                            getattr(local, attr).close()
                        except Exception:
                            pass
                        setattr(local, attr, None)
                        raise RuntimeError(
                            f"no Content-Length in reply: {head[:120]!r}")
                    stop = head.find(b"\r", i)
                    if stop < 0:
                        stop = len(head)
                    need = end + 4 + int(head[i + 15:stop])
                    while len(buf) < need:
                        part = conn.recv(65536)
                        if not part:
                            raise OSError("closed")
                        buf += part
                    return
                except (OSError, ValueError):
                    try:
                        getattr(local, attr).close()
                    except Exception:
                        pass
                    setattr(local, attr, None)
                    if attempt:
                        raise
            raise RuntimeError("ingest POST failed twice (connection)")

        def post(path, payload):
            raw_post(srv.port, "conn", path, payload)

        def ev(i):
            return {"event": "rate", "entityType": "user",
                    "entityId": f"u{i % 997}", "targetEntityType": "item",
                    "targetEntityId": f"i{i % 4999}",
                    "properties": {"rating": 1 + i % 5}}

        def run_single():
            post(url, ev(0))  # warm
            t0 = time.perf_counter()
            with concurrent.futures.ThreadPoolExecutor(8) as ex:
                list(ex.map(lambda i: post(url, ev(i)), range(n_single)))
            return n_single / (time.perf_counter() - t0)

        single_eps, single_spread = _median3_scalar(run_single)
        burl = url.replace("/events.json", "/batch/events.json")

        def run_batch():
            t0 = time.perf_counter()
            with concurrent.futures.ThreadPoolExecutor(4) as ex:
                list(ex.map(
                    lambda b: post(burl, [ev(b * batch + j)
                                          for j in range(batch)]),
                    range(n_batch)))
            return n_batch * batch / (time.perf_counter() - t0)

        batch_eps, batch_spread = _median3_scalar(run_batch)
        srv.stop()

        # Same single-event workload through the C++ frontend
        # (pio eventserver --native): concurrent singles group-commit.
        native_eps = None
        native_spread = None
        fe = None
        try:
            from predictionio_tpu.native.frontend import NativeFrontend

            fe = NativeFrontend(None, host="127.0.0.1", port=0,
                                max_batch=64, max_wait_us=1000,
                                fallback_batch=srv.native_fallback_batch)
            fe.start()

            def npost(i):
                raw_post(fe.port, "nconn", url, ev(i))

            def run_native():
                npost(0)
                t0 = time.perf_counter()
                with concurrent.futures.ThreadPoolExecutor(8) as ex:
                    list(ex.map(npost, range(n_single)))
                return n_single / (time.perf_counter() - t0)

            native_eps, native_spread = _median3_scalar(run_native)
            native_eps = round(native_eps, 1)
        except Exception as e:
            native_eps = f"error: {type(e).__name__}: {e}"
        finally:
            if fe is not None and fe.port is not None:
                fe.stop()  # leaked C++ threads would outlive the storage
        if old_home is None:
            os.environ.pop("PIO_HOME", None)
        else:
            os.environ["PIO_HOME"] = old_home
        reset_storage()
        def _rr(pair):
            return [round(v, 1) for v in pair]

        return {"single_events_per_sec": round(single_eps, 1),
                "single_spread": _rr(single_spread),
                "batch_events_per_sec": round(batch_eps, 1),
                "batch_spread": _rr(batch_spread),
                "native_single_events_per_sec": native_eps,
                "native_single_spread": (_rr(native_spread)
                                         if native_spread else None)}
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def main():
    # Ingest first: it touches no JAX state, and running it last in a
    # long-lived full-scale process measured 4.6k batch ev/s against
    # 18-21k standalone (the ~1 s batch window is poisoned by any
    # transient stall — GC over the train bench's object graph, WAL
    # writeback).  Isolation beats narrating the interference.
    ingest = ingest_bench()
    store = store_bench()
    # The headline train consumes the COO that went ingest → parquet
    # store → columnar scan (north star: store in the loop); a store
    # failure falls back to direct synthesis rather than sinking the
    # headline metric.
    coo = store.pop("coo", None)
    train = train_bench(coo=coo)
    train["from_store"] = coo is not None
    train["blocked"] = train_blocked_bench(coo=coo)
    tpu_era = tpu_era_bench()
    serving = serving_bench()
    serving["mips_1m"] = mips_bench()
    if coo is not None and "scan_to_coo_s" in store:
        store["e2e_scan_prep_train_s"] = round(
            store["scan_to_coo_s"] + train["e2e_full_train_s"], 2)
    # Per-model step-timeline summaries (host_wait/h2d/device_wait) from
    # the probed feeder-in-the-loop runs above: the pipeline-gap
    # attribution input for tools/attribute_gap.py.
    from predictionio_tpu.obs import get_timeline

    tl = get_timeline()
    timeline = {m: tl.summary(m) for m in tl.models()}
    value = train.pop("value")
    # Self-baseline: speedup over round 3's measured per-iteration time at
    # the same shape on the same chip (reproducible, unlike the retired
    # Spark-local constant).  mfu_pct/phase_ms are the absolute metrics.
    vs = (round(R3_PER_ITER_MS / train["per_iter_ms"], 3)
          if SCALE == 1.0 and train.get("per_iter_ms") else None)
    print(json.dumps({
        "metric": "als_train_samples_per_sec_per_chip",
        "value": value,
        "unit": "ratings*iters/sec/chip",
        "vs_baseline": vs,
        "baseline_ref": "r03 per_iter_ms=250.39 @ ML-25M rank64, 1x v5e",
        "train": train,
        "store": store,
        "tpu_era": tpu_era,
        "timeline": timeline,
        "serving": serving,
        "ingest": ingest,
    }))


if __name__ == "__main__":
    main()
