// Continuous-batching serving frontend — the native `pio deploy` hot path.
//
// Reference role (SURVEY.md §2.3/§3.2): the reference serves /queries.json
// from a JVM (akka-http) with per-request predict calls. XLA hates batch-1,
// so the rebuild's native frontend owns the network path and AGGREGATES
// in-flight requests into batches before crossing into the compiled model:
//
//   worker threads ──► pending queue ──► batcher thread ──► predict callback
//        ▲                                 (≤ max_batch, ≤ max_wait_us)
//        └────────────── per-request response signal ◄─────────┘
//
// Concurrency model (round 2 — replaces thread-per-connection, which
// accumulated one unjoined std::thread per request forever): a FIXED pool
// of worker threads pulls accepted sockets from a queue and speaks
// HTTP/1.1 with keep-alive, so a closed-loop client pays connection setup
// once, not per request.  Shutdown drains both queues: queued sockets are
// closed, queued Pending requests are failed with 503 so no worker is left
// blocked on its condition variable (round-1 deadlock).
//
// The predict callback is registered from Python via ctypes (CFUNCTYPE —
// ctypes acquires the GIL on entry); it receives an opaque batch handle and
// reads/writes requests through the pio_batch_* accessors, so no memory
// crosses allocator boundaries.
//
// Endpoints: GET / (status) and GET /metrics (Prometheus text) are answered
// here unless forward_all is set (event-server mode); EVERY other request
// rides the batcher into the Python callback with "METHOD PATH?QUERY"
// routing metadata (pio_batch_route), so the full engine/event APIs work
// behind this frontend.

#include <arpa/inet.h>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cerrno>
#include <cstring>
#include <deque>
#include <mutex>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Pending {
  std::string body;
  std::string route;  // "METHOD PATH?QUERY" — routing metadata for Python
  std::string response;
  std::string ctype = "application/json; charset=UTF-8";
  // CRLF-terminated extra header lines a server plugin injected
  // (pio_batch_respond_ex), e.g. "X-Plugin-Count: 5\r\n".
  std::string extra_headers;
  int status = 500;
  bool done = false;
  std::mutex mu;
  std::condition_variable cv;
};

using BatchCb = void (*)(void* batch_handle, int n);

struct Batch {
  std::vector<Pending*> items;
  // Set by pio_batch_respond (same thread: the callback runs synchronously
  // inside this batch's batcher thread).  A responded Pending may be
  // DESTROYED by its worker the moment respond() releases p->mu — the
  // batcher must never touch it again, so doneness lives here, not in p.
  std::vector<char> responded;
};

struct Frontend {
  int listen_fd = -1;
  int port = 0;
  int max_batch = 8;
  int max_wait_us = 2000;
  int n_batchers = 4;
  bool forward_all = false;  // event-server mode: / and /metrics go to Python
  BatchCb cb = nullptr;

  std::atomic<bool> running{false};
  std::thread acceptor;
  // Batcher POOL: each thread forms a batch and drives the Python callback
  // independently, so several batches are in flight at once — parse,
  // predict, and response writes overlap.  (Round 2 ran ONE batcher whose
  // synchronous callback serialized the whole server; it measured SLOWER
  // than the stdlib Python server.)
  std::vector<std::thread> batchers;
  std::vector<std::thread> workers;

  // accepted sockets awaiting a worker
  std::deque<int> conn_queue;
  std::mutex cmu;
  std::condition_variable ccv;

  // requests awaiting the batcher
  std::deque<Pending*> queue;
  std::mutex qmu;
  std::condition_variable qcv;

  // metrics
  std::atomic<uint64_t> n_requests{0};
  std::atomic<uint64_t> n_errors{0};
  std::atomic<uint64_t> n_batches{0};
  std::atomic<uint64_t> batch_rows{0};
  std::atomic<uint64_t> live_conns{0};
};

Frontend* g_frontend = nullptr;

void write_all(int fd, const char* data, size_t len) {
  size_t off = 0;
  while (off < len) {
    ssize_t w = ::send(fd, data + off, len - off, MSG_NOSIGNAL);
    if (w <= 0) return;
    off += static_cast<size_t>(w);
  }
}

void http_reply(int fd, int status, const char* ctype, const std::string& body,
                bool keep_alive, const std::string& extra_headers = "") {
  const char* reason = status == 200   ? "OK"
                       : status == 201 ? "Created"
                       : status == 400 ? "Bad Request"
                       : status == 401 ? "Unauthorized"
                       : status == 403 ? "Forbidden"
                       : status == 404 ? "Not Found"
                       : status == 500 ? "Internal Server Error"
                       : status == 503 ? "Service Unavailable"
                                       : "Error";
  char head[256];
  int n = snprintf(head, sizeof(head),
                   "HTTP/1.1 %d %s\r\nContent-Type: %s\r\n"
                   "Content-Length: %zu\r\nConnection: %s\r\n",
                   status, reason, ctype, body.size(),
                   keep_alive ? "keep-alive" : "close");
  if (extra_headers.empty()) {
    // hot path: one write for the whole head, no extra syscall
    if (n < (int)sizeof(head) - 2) {
      head[n++] = '\r';
      head[n++] = '\n';
      write_all(fd, head, n);
    } else {
      write_all(fd, head, n);
      write_all(fd, "\r\n", 2);
    }
  } else {
    write_all(fd, head, n);
    write_all(fd, extra_headers.data(), extra_headers.size());
    write_all(fd, "\r\n", 2);
  }
  write_all(fd, body.data(), body.size());
}

// recv that tolerates the 250 ms SO_RCVTIMEO poll while `running`: an idle
// keep-alive connection otherwise pins its worker in a blocking recv and
// pio_frontend_stop joins forever.
ssize_t recv_while_running(int fd, char* buf, size_t len,
                           const std::atomic<bool>& running) {
  for (;;) {
    ssize_t r = ::recv(fd, buf, len, 0);
    if (r >= 0) return r;
    if ((errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) &&
        running.load())
      continue;
    return -1;
  }
}

// Minimal HTTP/1.1 request reader.  `carry` holds bytes of the NEXT
// pipelined/keep-alive request that arrived with a previous read.
bool read_request(int fd, std::string& carry, std::string& method,
                  std::string& path, std::string& body, bool& want_close,
                  const std::atomic<bool>& running) {
  std::string buf;
  buf.swap(carry);
  char tmp[4096];
  size_t header_end = buf.find("\r\n\r\n");
  while (header_end == std::string::npos) {
    ssize_t r = recv_while_running(fd, tmp, sizeof(tmp), running);
    if (r <= 0) return false;
    buf.append(tmp, r);
    header_end = buf.find("\r\n\r\n");
    if (buf.size() > (1u << 20)) return false;  // header flood guard
  }
  const std::string head = buf.substr(0, header_end);
  size_t sp1 = head.find(' ');
  size_t sp2 = head.find(' ', sp1 + 1);
  if (sp1 == std::string::npos || sp2 == std::string::npos) return false;
  method = head.substr(0, sp1);
  path = head.substr(sp1 + 1, sp2 - sp1 - 1);  // query string INCLUDED

  size_t content_length = 0;
  want_close = false;
  bool http10 = head.find("HTTP/1.0") != std::string::npos;
  size_t pos = 0;
  while (pos < head.size()) {
    size_t eol = head.find("\r\n", pos);
    if (eol == std::string::npos) eol = head.size();
    std::string line = head.substr(pos, eol - pos);
    for (auto& c : line)
      if (c >= 'A' && c <= 'Z') c += 32;
    if (line.rfind("content-length:", 0) == 0)
      content_length = strtoul(line.c_str() + 15, nullptr, 10);
    if (line.rfind("connection:", 0) == 0) {
      if (line.find("close") != std::string::npos) want_close = true;
      if (http10 && line.find("keep-alive") != std::string::npos)
        http10 = false;  // explicit keep-alive on 1.0
    }
    pos = eol + 2;
  }
  if (http10) want_close = true;  // HTTP/1.0 default: close
  if (content_length > (64u << 20)) return false;  // 64 MB cap
  body = buf.substr(header_end + 4);
  while (body.size() < content_length) {
    ssize_t r = recv_while_running(fd, tmp, sizeof(tmp), running);
    if (r <= 0) return false;
    body.append(tmp, r);
  }
  if (body.size() > content_length) {
    carry = body.substr(content_length);  // start of the next request
    body.resize(content_length);
  }
  return true;
}

// Serve one request on an open connection.  Returns false when the
// connection should close (error, Connection: close, or shutdown).
bool handle_one(Frontend* fe, int fd, std::string& carry) {
  std::string method, path, body;
  bool want_close = false;
  if (!read_request(fd, carry, method, path, body, want_close, fe->running))
    return false;
  bool keep = !want_close;
  fe->n_requests++;
  std::string bare = path.substr(0, path.find('?'));
  if (!fe->forward_all && method == "GET" && bare == "/") {
    http_reply(fd, 200, "application/json",
               "{\"status\":\"alive\",\"frontend\":\"native\"}", keep);
  } else if (!fe->forward_all && method == "GET" && bare == "/metrics") {
    char m[640];
    uint64_t nb = fe->n_batches.load(), br = fe->batch_rows.load();
    snprintf(m, sizeof(m),
             "# TYPE pio_frontend_requests_total counter\n"
             "pio_frontend_requests_total %llu\n"
             "pio_frontend_errors_total %llu\n"
             "# TYPE pio_frontend_batch_size gauge\n"
             "pio_frontend_batches_total %llu\n"
             "pio_frontend_mean_batch_size %.3f\n"
             "pio_frontend_live_connections %llu\n",
             (unsigned long long)fe->n_requests.load(),
             (unsigned long long)fe->n_errors.load(), (unsigned long long)nb,
             nb ? (double)br / nb : 0.0,
             (unsigned long long)fe->live_conns.load());
    http_reply(fd, 200, "text/plain; version=0.0.4", m, keep);
  } else {
    // Everything else — /queries.json, /events.json, /batch/events.json,
    // webhooks, reload — rides the batcher: concurrent requests aggregate
    // into one Python callback (one GIL entry; the event server turns
    // same-route single-event POSTs into ONE group-committed insert).
    Pending p;
    p.body.swap(body);
    p.route.reserve(method.size() + 1 + path.size());
    p.route.append(method).append(" ").append(path);
    bool queued = false;
    {
      std::lock_guard<std::mutex> lk(fe->qmu);
      // Checked under qmu so shutdown's drain (also under qmu) can never
      // miss a Pending: either we enqueue before the drain, or we observe
      // running == false and 503 immediately.
      if (fe->running.load()) {
        fe->queue.push_back(&p);
        queued = true;
      }
    }
    if (!queued) {
      fe->n_errors++;
      http_reply(fd, 503, "application/json",
                 "{\"message\":\"shutting down\"}", false);
      return false;
    }
    fe->qcv.notify_one();
    {
      std::unique_lock<std::mutex> lk(p.mu);
      p.cv.wait(lk, [&] { return p.done; });
    }
    if (p.status >= 400) fe->n_errors++;
    http_reply(fd, p.status, p.ctype.c_str(), p.response, keep,
               p.extra_headers);
  }
  return keep && fe->running.load();
}

void worker_loop(Frontend* fe) {
  for (;;) {
    int fd = -1;
    {
      std::unique_lock<std::mutex> lk(fe->cmu);
      fe->ccv.wait(lk,
                   [&] { return !fe->conn_queue.empty() || !fe->running; });
      if (fe->conn_queue.empty()) {
        if (!fe->running.load()) return;
        continue;
      }
      fd = fe->conn_queue.front();
      fe->conn_queue.pop_front();
    }
    fe->live_conns++;
    std::string carry;
    while (handle_one(fe, fd, carry)) {
    }
    ::close(fd);
    fe->live_conns--;
  }
}

void batcher_loop(Frontend* fe) {
  while (fe->running.load()) {
    Batch batch;
    {
      std::unique_lock<std::mutex> lk(fe->qmu);
      fe->qcv.wait_for(lk, std::chrono::milliseconds(50),
                       [&] { return !fe->queue.empty() || !fe->running; });
      if (!fe->running.load()) break;
      if (fe->queue.empty()) continue;
      // Continuous batching: take what's there, then linger briefly for
      // stragglers up to max_batch.
      while (!fe->queue.empty() && (int)batch.items.size() < fe->max_batch) {
        batch.items.push_back(fe->queue.front());
        fe->queue.pop_front();
      }
      // Adaptive linger: wait for stragglers only when some OTHER live
      // connection could still contribute one.  Each connection has at
      // most one request in flight (handle_one is sequential per
      // connection), so with live_conns <= batch size every live client
      // is already parked in THIS batch and the linger could only burn
      // its own latency — the unloaded p50 tax round 4 measured
      // (1.7 ms native vs 0.4 python on an idle server).
      if ((int)batch.items.size() < fe->max_batch && fe->max_wait_us > 0 &&
          fe->live_conns.load() > batch.items.size()) {
        auto deadline = std::chrono::steady_clock::now() +
                        std::chrono::microseconds(fe->max_wait_us);
        while ((int)batch.items.size() < fe->max_batch &&
               fe->qcv.wait_until(lk, deadline,
                                  [&] { return !fe->queue.empty(); })) {
          while (!fe->queue.empty() &&
                 (int)batch.items.size() < fe->max_batch) {
            batch.items.push_back(fe->queue.front());
            fe->queue.pop_front();
          }
        }
      }
    }
    fe->n_batches++;
    fe->batch_rows += batch.items.size();
    batch.responded.assign(batch.items.size(), 0);
    if (fe->cb) {
      fe->cb(&batch, (int)batch.items.size());  // → Python (GIL via ctypes)
    }
    // Only UNRESPONDED items may be touched here (their workers are still
    // parked on p->cv; responded Pendings may already be destroyed).
    for (size_t i = 0; i < batch.items.size(); i++) {
      if (batch.responded[i]) continue;
      Pending* p = batch.items[i];
      std::lock_guard<std::mutex> lk(p->mu);
      p->status = 500;
      p->response = "{\"message\":\"no response produced\"}";
      p->done = true;
      p->cv.notify_one();
    }
  }
}

// Shutdown drain (called once after ALL batchers joined): anything still
// queued gets a definite answer so its worker never blocks on p->cv.
void drain_queue(Frontend* fe) {
  std::deque<Pending*> rest;
  {
    std::lock_guard<std::mutex> lk(fe->qmu);
    rest.swap(fe->queue);
  }
  for (Pending* p : rest) {
    std::lock_guard<std::mutex> lk(p->mu);
    p->status = 503;
    p->response = "{\"message\":\"shutting down\"}";
    p->done = true;
    p->cv.notify_one();
  }
}

void acceptor_loop(Frontend* fe) {
  while (fe->running.load()) {
    sockaddr_in peer;
    socklen_t plen = sizeof(peer);
    int fd = ::accept(fe->listen_fd, (sockaddr*)&peer, &plen);
    if (fd < 0) {
      if (!fe->running.load()) break;
      continue;
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    // Bounded recv so workers re-check `running` while a keep-alive
    // connection idles (shutdown liveness, not a request deadline).
    timeval tv{0, 250 * 1000};
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    {
      std::lock_guard<std::mutex> lk(fe->cmu);
      fe->conn_queue.push_back(fd);
    }
    fe->ccv.notify_one();
  }
}

}  // namespace

extern "C" {

int pio_frontend_start(const char* host, int port, int max_batch,
                       int max_wait_us, int n_batchers, int forward_all,
                       BatchCb cb) {
  if (g_frontend) return -1;
  auto* fe = new Frontend();
  fe->forward_all = forward_all != 0;
  fe->max_batch = max_batch > 0 ? max_batch : 8;
  fe->max_wait_us = max_wait_us;
  fe->n_batchers = n_batchers > 0 ? n_batchers : 4;
  fe->cb = cb;
  fe->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fe->listen_fd < 0) {
    delete fe;
    return -2;
  }
  int one = 1;
  setsockopt(fe->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  addr.sin_addr.s_addr = host && *host ? inet_addr(host) : INADDR_ANY;
  if (bind(fe->listen_fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
      listen(fe->listen_fd, 512) != 0) {
    ::close(fe->listen_fd);
    delete fe;
    return -3;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fe->listen_fd, (sockaddr*)&addr, &alen);
  fe->port = ntohs(addr.sin_port);
  fe->running = true;
  // Worker pool bounds concurrent in-flight requests; sized past max_batch
  // so the batcher can actually fill a batch from concurrent clients.
  unsigned hw = std::thread::hardware_concurrency();
  int n_workers = (int)(hw ? hw * 4 : 16);
  if (n_workers < fe->max_batch) n_workers = fe->max_batch;
  if (n_workers > 128) n_workers = 128;
  fe->workers.reserve(n_workers);
  for (int i = 0; i < n_workers; i++)
    fe->workers.emplace_back(worker_loop, fe);
  fe->batchers.reserve(fe->n_batchers);
  for (int i = 0; i < fe->n_batchers; i++)
    fe->batchers.emplace_back(batcher_loop, fe);
  fe->acceptor = std::thread(acceptor_loop, fe);
  g_frontend = fe;
  return fe->port;
}

int pio_frontend_port() { return g_frontend ? g_frontend->port : -1; }

const char* pio_batch_request(void* batch_handle, int i, int* len_out) {
  auto* b = static_cast<Batch*>(batch_handle);
  if (i < 0 || i >= (int)b->items.size()) return nullptr;
  if (len_out) *len_out = (int)b->items[i]->body.size();
  return b->items[i]->body.c_str();
}

const char* pio_batch_route(void* batch_handle, int i, int* len_out) {
  // "METHOD PATH?QUERY" for item i — lets the Python callback dispatch
  // beyond /queries.json (event ingest, webhooks, reload).
  auto* b = static_cast<Batch*>(batch_handle);
  if (i < 0 || i >= (int)b->items.size()) return nullptr;
  if (len_out) *len_out = (int)b->items[i]->route.size();
  return b->items[i]->route.c_str();
}

// Respond with plugin-injected extra header lines (server plugin seam,
// reference: EngineServerPlugin/EventServerPlugin request instrumentation).
// `extra_headers` is zero or more "Name: value" lines joined with CRLF;
// a trailing CRLF is appended if missing.  Lines containing header
// injection (bare CR/LF inside a value) are the CALLER's responsibility
// to sanitize (the Python seam does).
void pio_batch_respond_ex(void* batch_handle, int i, const char* data,
                          int len, int status, const char* ctype,
                          const char* extra_headers) {
  auto* b = static_cast<Batch*>(batch_handle);
  if (i < 0 || i >= (int)b->items.size()) return;
  Pending* p = b->items[i];
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->response.assign(data, len);
    if (ctype && *ctype) p->ctype = ctype;
    if (extra_headers && *extra_headers) {
      p->extra_headers = extra_headers;
      if (p->extra_headers.size() < 2 ||
          p->extra_headers.compare(p->extra_headers.size() - 2, 2,
                                   "\r\n") != 0)
        p->extra_headers += "\r\n";
    }
    p->status = status;
    p->done = true;
    p->cv.notify_one();  // under p->mu: p may be destroyed once we release
  }
  b->responded[i] = 1;  // same thread as the batcher loop — no lock needed
}

void pio_batch_respond(void* batch_handle, int i, const char* data, int len,
                       int status, const char* ctype) {
  pio_batch_respond_ex(batch_handle, i, data, len, status, ctype, nullptr);
}

void pio_frontend_stop() {
  Frontend* fe = g_frontend;
  if (!fe) return;
  fe->running = false;
  ::shutdown(fe->listen_fd, SHUT_RDWR);
  ::close(fe->listen_fd);
  fe->qcv.notify_all();  // wake every batcher
  if (fe->acceptor.joinable()) fe->acceptor.join();
  for (auto& t : fe->batchers)
    if (t.joinable()) t.join();
  drain_queue(fe);  // after ALL batchers are gone: 503 any leftovers
  // Close sockets no worker picked up, then release the pool.
  {
    std::lock_guard<std::mutex> lk(fe->cmu);
    for (int fd : fe->conn_queue) ::close(fd);
    fe->conn_queue.clear();
  }
  fe->ccv.notify_all();
  for (auto& t : fe->workers)
    if (t.joinable()) t.join();
  g_frontend = nullptr;
  delete fe;
}

}  // extern "C"
