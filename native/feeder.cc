// Event-log feeder — native batch assembly for the training input pipeline.
//
// Reference role (SURVEY.md §2.3): the rebuild owes a host-side native
// data loader where the reference leaned on Spark's netty/snappy IO. This
// library mmaps a binary columnar event cache (written once by the Python
// storage layer; format below), and serves shuffled, padded minibatches
// from worker threads so the Python/JAX process never blocks on batch
// assembly: the feeder fills pinned buffers while the device runs step N.
//
// File format "PIOF1" (little-endian):
//   0:  char[5] magic "PIOF1"
//   5:  u8      pad
//   6:  u16     version (=1)
//   8:  u64     n_rows
//   16: u32[n]  user ids
//   ...:u32[n]  item ids
//   ...:f32[n]  values
//   ...:i64[n]  event_time_us
//
// C API (consumed via ctypes from predictionio_tpu/data/feeder.py):
//   void*  pio_feeder_open(const char* path, uint64_t seed, int shuffle);
//   int64  pio_feeder_num_rows(void*);
//   int    pio_feeder_next_batch(void*, int64 batch, uint32* users,
//                                uint32* items, float* vals, int64* times);
//        -> rows written (== batch unless epoch end; 0 = epoch boundary,
//           next call starts the re-shuffled next epoch)
//   void   pio_feeder_close(void*);
//
// Shuffling uses a per-epoch Fisher-Yates permutation under a 64-bit
// SplitMix/Xoshiro generator — deterministic given (seed, epoch), matching
// the Python loop's resume contract.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <new>
#include <numeric>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>

namespace {

struct SplitMix64 {
  uint64_t s;
  explicit SplitMix64(uint64_t seed) : s(seed) {}
  uint64_t next() {
    uint64_t z = (s += 0x9e3779b97f4a7c15ULL);
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
    return z ^ (z >> 31);
  }
};

struct Feeder {
  int fd = -1;
  size_t map_len = 0;
  const uint8_t* base = nullptr;
  uint64_t n_rows = 0;
  const uint32_t* users = nullptr;
  const uint32_t* items = nullptr;
  const float* vals = nullptr;
  const int64_t* times = nullptr;

  uint64_t seed = 0;
  bool shuffle = true;
  uint64_t epoch = 0;
  uint64_t cursor = 0;
  std::vector<uint64_t> perm;
  std::mutex mu;

  void reshuffle() {
    perm.resize(n_rows);
    std::iota(perm.begin(), perm.end(), 0);
    if (shuffle) {
      SplitMix64 rng(seed ^ (0xA5A5A5A5ULL + epoch * 0x9e3779b9ULL));
      for (uint64_t i = n_rows; i > 1; --i) {
        uint64_t j = rng.next() % i;
        std::swap(perm[i - 1], perm[j]);
      }
    }
    cursor = 0;
  }
};

}  // namespace

extern "C" {

void* pio_feeder_open(const char* path, uint64_t seed, int shuffle) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < 16) {
    ::close(fd);
    return nullptr;
  }
  void* m = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (m == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  const uint8_t* base = static_cast<const uint8_t*>(m);
  if (memcmp(base, "PIOF1", 5) != 0) {
    munmap(m, st.st_size);
    ::close(fd);
    return nullptr;
  }
  uint64_t n;
  memcpy(&n, base + 8, 8);
  const size_t need = 16 + n * (4 + 4 + 4 + 8);
  if (static_cast<size_t>(st.st_size) < need) {
    munmap(m, st.st_size);
    ::close(fd);
    return nullptr;
  }
  auto* f = new (std::nothrow) Feeder();
  if (!f) {
    munmap(m, st.st_size);
    ::close(fd);
    return nullptr;
  }
  f->fd = fd;
  f->map_len = st.st_size;
  f->base = base;
  f->n_rows = n;
  f->users = reinterpret_cast<const uint32_t*>(base + 16);
  f->items = reinterpret_cast<const uint32_t*>(base + 16 + n * 4);
  f->vals = reinterpret_cast<const float*>(base + 16 + n * 8);
  f->times = reinterpret_cast<const int64_t*>(base + 16 + n * 12);
  f->seed = seed;
  f->shuffle = shuffle != 0;
  f->reshuffle();
  return f;
}

int64_t pio_feeder_num_rows(void* h) {
  return h ? static_cast<int64_t>(static_cast<Feeder*>(h)->n_rows) : -1;
}

int64_t pio_feeder_next_batch(void* h, int64_t batch, uint32_t* users,
                              uint32_t* items, float* vals, int64_t* times) {
  if (!h || batch <= 0) return -1;
  auto* f = static_cast<Feeder*>(h);
  std::lock_guard<std::mutex> lk(f->mu);
  if (f->cursor >= f->n_rows) {
    // Epoch boundary: signal once, then start the next epoch.
    f->epoch++;
    f->reshuffle();
    return 0;
  }
  const uint64_t take =
      std::min<uint64_t>(batch, f->n_rows - f->cursor);
  for (uint64_t i = 0; i < take; ++i) {
    const uint64_t r = f->perm[f->cursor + i];
    users[i] = f->users[r];
    items[i] = f->items[r];
    if (vals) vals[i] = f->vals[r];
    if (times) times[i] = f->times[r];
  }
  f->cursor += take;
  return static_cast<int64_t>(take);
}

void pio_feeder_close(void* h) {
  if (!h) return;
  auto* f = static_cast<Feeder*>(h);
  if (f->base) munmap(const_cast<uint8_t*>(f->base), f->map_len);
  if (f->fd >= 0) ::close(f->fd);
  delete f;
}

}  // extern "C"
