// Event-log feeder — native batch assembly for the training input pipeline.
//
// Reference role (SURVEY.md §2.3): the rebuild owes a host-side native
// data loader where the reference leaned on Spark's netty/snappy IO. This
// library mmaps a binary columnar event cache (written once by the Python
// storage layer; format below), and serves shuffled, padded minibatches
// from worker threads so the Python/JAX process never blocks on batch
// assembly: the feeder fills pinned buffers while the device runs step N.
//
// File format "PIOF1" (little-endian), version 3:
//   0:  char[5] magic "PIOF1"
//   5:  u8      pad
//   6:  u16     version (=3)
//   8:  u64     n_rows
//   16: u32     n_extra   (extra f32 feature columns, e.g. DLRM dense)
//   20: u32     n_cat     (categorical u32 id columns; v2 wrote 0 here
//                          and always carried exactly 2 — user/item)
//   24: u32[n]  categorical column 0 (user ids in the 2-column case)
//   ...:u32[n]  x (n_cat - 1) further categorical columns
//   ...:f32[n]  values
//   ...:<pad to 8-byte boundary>
//   ...:i64[n]  event_time_us            (8-byte aligned by construction)
//   ...:f32[n] x n_extra feature columns (column-major: col0 rows, col1...)
//
// Version 2 files read as n_cat == 2.  Version 1 files (no n_extra field,
// data at offset 16, times potentially only 4-byte aligned when n is odd)
// are still readable: their times are copied via memcpy, never
// dereferenced as int64* (the round-1 layout made misaligned loads UB on
// strict-alignment targets).
//
// C API (consumed via ctypes from predictionio_tpu/native/feeder.py):
//   void*  pio_feeder_open(const char* path, uint64_t seed, int shuffle);
//   int64  pio_feeder_num_rows(void*);
//   int32  pio_feeder_n_extra(void*);
//   int32  pio_feeder_n_cat(void*);
//   int64  pio_feeder_next_batch(void*, int64 batch, uint32* users,
//                                uint32* items, float* vals, int64* times,
//                                float* extras /* [batch, n_extra] row-major,
//                                                 may be null */);
//        -> rows written (== batch unless epoch end; 0 = epoch boundary,
//           next call starts the re-shuffled next epoch); requires
//           n_cat >= 2 (columns 0/1 ride the user/item pointers)
//   int64  pio_feeder_next_batch_cats(void*, int64 batch,
//                                uint32* cats /* [batch, n_cat] row-major */,
//                                float* vals, int64* times, float* extras);
//   void   pio_feeder_close(void*);
//
// Shuffling uses a per-epoch Fisher-Yates permutation under a SplitMix64
// generator — deterministic given (seed, epoch), matching the Python
// loop's resume contract.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <new>
#include <numeric>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>

namespace {

struct SplitMix64 {
  uint64_t s;
  explicit SplitMix64(uint64_t seed) : s(seed) {}
  uint64_t next() {
    uint64_t z = (s += 0x9e3779b97f4a7c15ULL);
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
    return z ^ (z >> 31);
  }
};

struct Feeder {
  int fd = -1;
  size_t map_len = 0;
  const uint8_t* base = nullptr;
  uint64_t n_rows = 0;
  uint32_t n_extra = 0;
  uint32_t n_cat = 0;
  std::vector<const uint32_t*> cat_cols;
  const float* vals = nullptr;
  const uint8_t* times_raw = nullptr;  // memcpy-read (v1 may be unaligned)
  std::vector<const float*> extras;

  uint64_t seed = 0;
  bool shuffle = true;
  uint64_t epoch = 0;
  uint64_t cursor = 0;
  std::vector<uint64_t> perm;
  std::mutex mu;

  void reshuffle() {
    perm.resize(n_rows);
    std::iota(perm.begin(), perm.end(), 0);
    if (shuffle) {
      SplitMix64 rng(seed ^ (0xA5A5A5A5ULL + epoch * 0x9e3779b9ULL));
      for (uint64_t i = n_rows; i > 1; --i) {
        uint64_t j = rng.next() % i;
        std::swap(perm[i - 1], perm[j]);
      }
    }
    cursor = 0;
  }
};

size_t align8(size_t x) { return (x + 7) & ~size_t(7); }

}  // namespace

extern "C" {

void* pio_feeder_open(const char* path, uint64_t seed, int shuffle) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < 16) {
    ::close(fd);
    return nullptr;
  }
  void* m = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (m == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  const uint8_t* base = static_cast<const uint8_t*>(m);
  uint16_t version = 0;
  memcpy(&version, base + 6, 2);
  if (memcmp(base, "PIOF1", 5) != 0 ||
      (version != 1 && version != 2 && version != 3)) {
    munmap(m, st.st_size);
    ::close(fd);
    return nullptr;
  }
  uint64_t n;
  memcpy(&n, base + 8, 8);
  uint32_t n_extra = 0;
  uint32_t n_cat = 2;
  size_t data_off = 16;
  if (version >= 2) {
    memcpy(&n_extra, base + 16, 4);
    data_off = 24;
  }
  if (version >= 3) {
    memcpy(&n_cat, base + 20, 4);
    if (n_cat < 1 || n_cat > 1024) {
      munmap(m, st.st_size);
      ::close(fd);
      return nullptr;
    }
  }
  // Bound n (and n_extra) before any offset math: a crafted n_rows near
  // 2^64 would wrap `n * row_bytes` back under st_size, pass the size
  // check, and leave the column pointers (and reshuffle's perm.resize)
  // pointing at garbage.  No real cache can exceed the mapped file size
  // in rows or hold more extra columns than bytes.
  const size_t row_bytes = size_t(n_cat) * 4 + 4;
  if (static_cast<size_t>(st.st_size) < data_off || n_extra > 65536 ||
      n > (static_cast<size_t>(st.st_size) - data_off) / row_bytes) {
    munmap(m, st.st_size);
    ::close(fd);
    return nullptr;
  }
  const size_t vals_end = data_off + n * row_bytes;
  const size_t times_off = version >= 2 ? align8(vals_end) : vals_end;
  const size_t extras_off = times_off + n * 8;
  const size_t need = extras_off + size_t(n_extra) * n * 4;
  if (static_cast<size_t>(st.st_size) < need) {
    munmap(m, st.st_size);
    ::close(fd);
    return nullptr;
  }
  auto* f = new (std::nothrow) Feeder();
  if (!f) {
    munmap(m, st.st_size);
    ::close(fd);
    return nullptr;
  }
  f->fd = fd;
  f->map_len = st.st_size;
  f->base = base;
  f->n_rows = n;
  f->n_extra = n_extra;
  f->n_cat = n_cat;
  for (uint32_t c = 0; c < n_cat; ++c)
    f->cat_cols.push_back(reinterpret_cast<const uint32_t*>(
        base + data_off + size_t(c) * n * 4));
  f->vals = reinterpret_cast<const float*>(base + data_off +
                                           size_t(n_cat) * n * 4);
  f->times_raw = base + times_off;
  for (uint32_t c = 0; c < n_extra; ++c)
    f->extras.push_back(
        reinterpret_cast<const float*>(base + extras_off + size_t(c) * n * 4));
  f->seed = seed;
  f->shuffle = shuffle != 0;
  f->reshuffle();
  return f;
}

int64_t pio_feeder_num_rows(void* h) {
  return h ? static_cast<int64_t>(static_cast<Feeder*>(h)->n_rows) : -1;
}

int32_t pio_feeder_n_extra(void* h) {
  return h ? static_cast<int32_t>(static_cast<Feeder*>(h)->n_extra) : -1;
}

int32_t pio_feeder_n_cat(void* h) {
  return h ? static_cast<int32_t>(static_cast<Feeder*>(h)->n_cat) : -1;
}

namespace {

// Shared batch walk: writes either user/item pointers (legacy 2-column
// ABI) or the row-major [batch, n_cat] block.
int64_t next_batch_impl(Feeder* f, int64_t batch, uint32_t* users,
                        uint32_t* items, uint32_t* cats, float* vals,
                        int64_t* times, float* extras) {
  std::lock_guard<std::mutex> lk(f->mu);
  if (f->cursor >= f->n_rows) {
    // Epoch boundary: signal once, then start the next epoch.
    f->epoch++;
    f->reshuffle();
    return 0;
  }
  const uint64_t take = std::min<uint64_t>(batch, f->n_rows - f->cursor);
  const uint32_t ne = f->n_extra;
  const uint32_t nc = f->n_cat;
  for (uint64_t i = 0; i < take; ++i) {
    const uint64_t r = f->perm[f->cursor + i];
    if (users) users[i] = f->cat_cols[0][r];
    if (items) items[i] = f->cat_cols[1][r];
    if (cats)
      for (uint32_t c = 0; c < nc; ++c) cats[i * nc + c] = f->cat_cols[c][r];
    if (vals) vals[i] = f->vals[r];
    if (times)  // memcpy: v1 files may have this column 4-byte aligned only
      memcpy(&times[i], f->times_raw + r * 8, 8);
    if (extras)
      for (uint32_t c = 0; c < ne; ++c)
        extras[i * ne + c] = f->extras[c][r];
  }
  f->cursor += take;
  return static_cast<int64_t>(take);
}

}  // namespace

int64_t pio_feeder_next_batch(void* h, int64_t batch, uint32_t* users,
                              uint32_t* items, float* vals, int64_t* times,
                              float* extras) {
  if (!h || batch <= 0) return -1;
  auto* f = static_cast<Feeder*>(h);
  if (f->n_cat < 2) return -1;  // legacy ABI needs user+item columns
  return next_batch_impl(f, batch, users, items, nullptr, vals, times,
                         extras);
}

int64_t pio_feeder_next_batch_cats(void* h, int64_t batch, uint32_t* cats,
                                   float* vals, int64_t* times,
                                   float* extras) {
  if (!h || batch <= 0 || !cats) return -1;
  return next_batch_impl(static_cast<Feeder*>(h), batch, nullptr, nullptr,
                         cats, vals, times, extras);
}

void pio_feeder_close(void* h) {
  if (!h) return;
  auto* f = static_cast<Feeder*>(h);
  if (f->base) munmap(const_cast<uint8_t*>(f->base), f->map_len);
  if (f->fd >= 0) ::close(f->fd);
  delete f;
}

}  // extern "C"
