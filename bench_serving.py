#!/usr/bin/env python
"""Serving benchmark: recs/sec + predict latency percentiles.

BASELINE.md metrics 2-3: serving throughput (recommendations/sec) and p50
predict latency.  Trains a small ALS engine, then drives both serving
frontends over real HTTP with concurrent closed-loop clients:

- python: stdlib ThreadingHTTPServer (`pio deploy`)
- native: C++ continuous-batching frontend (`pio deploy --native`)

Usage: python bench_serving.py [--clients 16] [--requests 2000]
Prints one JSON line per frontend.

With ``--concurrency "1,8,32"`` (ISSUE 6) the bench switches to SWEEP
mode: one server, several closed-loop concurrency levels, and per level
it records client p50/p99 NEXT TO the serving scheduler's own counters —
dispatches-per-request (coalescing), the batch-size distribution, queue
sheds/rejects, and deadline outcomes (every request carries
``X-PIO-Deadline-Ms``; a deadline that cannot be met must come back 504,
never a late 200).  The same levels are then re-driven against an
unbatched server (``PIO_BATCH_ENABLED=off`` semantics) so the batched
p99 is judged against the per-request-dispatch baseline at identical
load.  ``--engine twotower`` runs the sweep against a deep-model engine
(vectorized ``top_k_scores`` batch predict).  Combined with ``--faults``
the top level is re-driven with the fault plan installed
(BENCH_SERVING_r01.json carries clean + faulted rounds).

With ``--faults SPEC`` (PIO_FAULTS grammar, e.g.
``http.engine:delay:5ms:0.05``) the python frontend is driven TWICE on
the same server — clean, then with the fault plan installed — and the
line carries ``clean`` / ``faulted`` blocks plus the p99 delta, so a
round artifact finally records tail latency under injected partial
failure (ROADMAP resilience follow-on (c)).  The faulted phase also
attempts ``POST /reload`` before and during the drive and counts
predict non-2xx responses: with the store 100% dead
(``storage.find:error:1.0``) the reload must fail closed while serving
continues from the last-good model with zero non-2xx
(BENCH_FAULTS_r02, ISSUE 4).
"""

import argparse
import concurrent.futures
import json
import os
import re
import tempfile
import threading
import time
import urllib.error
import urllib.request

import numpy as np


def _setup(engine_name: str = "als", n_items: int = 4000):
    os.environ.setdefault("PIO_HOME", tempfile.mkdtemp(prefix="pio_bench_"))
    from predictionio_tpu.controller import EngineVariant, RuntimeContext
    from predictionio_tpu.data.event import DataMap, Event
    from predictionio_tpu.data.storage import App, get_storage
    from predictionio_tpu.workflow.core_workflow import run_train

    storage = get_storage()
    ctx = RuntimeContext.create(storage=storage)
    app_id = storage.get_apps().insert(App(id=None, name="benchapp"))
    storage.get_events().init(app_id)
    rng = np.random.default_rng(0)
    n_users = 2000
    users = rng.integers(0, n_users, 100_000)
    items = rng.integers(0, n_items, 100_000)
    events = storage.get_events()
    batch = [
        Event(event="rate", entity_type="user", entity_id=f"u{u}",
              target_entity_type="item", target_entity_id=f"i{i}",
              properties=DataMap({"rating": float(r)}))
        for u, i, r in zip(users, items, rng.integers(1, 6, 100_000))
    ]
    events.insert_batch(batch, app_id)
    if engine_name == "twotower":
        # Deep-model serving: MLP towers + MIPS top-K, the vectorized
        # batch_predict the scheduler's coalescing actually exercises.
        from predictionio_tpu.templates.twotower import engine

        variant = EngineVariant.from_dict({
            "engineFactory": "predictionio_tpu.templates.twotower:engine",
            "datasource": {"params": {"appName": "benchapp"}},
            "algorithms": [{"name": "twotower",
                            "params": {"embedDim": 16, "hiddenDims": [32],
                                       "outDim": 16, "epochs": 2,
                                       "batchSize": 2048}}],
        })
    else:
        from predictionio_tpu.templates.recommendation import engine

        variant = EngineVariant.from_dict({
            "engineFactory":
                "predictionio_tpu.templates.recommendation:engine",
            "datasource": {"params": {"appName": "benchapp"}},
            "algorithms": [{"name": "als",
                            "params": {"rank": 64, "numIterations": 5}}],
        })
    eng = engine()
    run_train(eng, variant, ctx)
    return eng, variant, storage, n_users


def _drive(port: int, n_users: int, clients: int, requests: int,
           count_non_2xx: bool = False):
    """Closed-loop saturation throughput PLUS unloaded latency.

    Workers keep persistent connections (an SDK-shaped client) and speak
    minimal raw-socket HTTP: client and server share this ONE-core bench
    host, so every cycle the client burns is a cycle stolen from the
    server under test — http.client's request/response machinery alone
    capped measured native throughput well below the server's ceiling.
    PRE-RENDERED request bytes + a content-length scan keep the client
    to ~3 syscalls/request.  ``p50_unloaded_ms`` is measured at
    concurrency 1 — BASELINE.md metric 3's actual meaning (round-3
    verdict item 3: the closed-loop p50 is queueing delay).
    """
    import socket
    import threading

    rng = np.random.default_rng(1)
    payloads = [json.dumps({"user": f"u{rng.integers(0, n_users)}",
                            "num": 10}).encode() for _ in range(requests)]
    reqs = [(b"POST /queries.json HTTP/1.1\r\nHost: b\r\n"
             b"Content-Type: application/json\r\nContent-Length: "
             + str(len(p)).encode() + b"\r\n\r\n" + p) for p in payloads]
    local = threading.local()
    _CL = b"content-length:"
    # Faulted mode (ISSUE 4 / BENCH_FAULTS_r02): non-2xx predicts are
    # COUNTED, not retried — the artifact's claim is "zero non-2xx while
    # storage is 100% dead", so the client must see every failure.
    non_2xx = []

    def one(raw):
        t0 = time.perf_counter()
        for attempt in range(3):
            try:
                conn = getattr(local, "conn", None)
                if conn is None:
                    conn = local.conn = socket.create_connection(
                        ("127.0.0.1", port), timeout=30)
                    conn.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY, 1)
                conn.sendall(raw)
                buf = b""
                while True:  # headers
                    part = conn.recv(65536)
                    if not part:
                        raise OSError("closed")
                    buf += part
                    end = buf.find(b"\r\n\r\n")
                    if end >= 0:
                        break
                if not buf.startswith(b"HTTP/1.1 2"):
                    if count_non_2xx:
                        non_2xx.append(buf[:12])
                    else:
                        raise RuntimeError(f"serving returned {buf[:30]!r}")
                head = buf[:end].lower()
                i = head.find(_CL)
                if i < 0:
                    raise RuntimeError(
                        f"response without Content-Length: {head[:200]!r}")
                stop = head.find(b"\r", i)
                if stop < 0:
                    stop = len(head)  # Content-Length was the LAST header
                need = end + 4 + int(head[i + len(_CL):stop])
                while len(buf) < need:
                    part = conn.recv(65536)
                    if not part:
                        raise OSError("closed")
                    buf += part
                break
            except (OSError, ValueError, RuntimeError):
                # RuntimeError = non-200 status: transient 5xx under
                # saturation retries like any connection fault.
                try:
                    conn.close()
                except Exception:
                    pass
                local.conn = None
                if attempt == 2:
                    raise
                time.sleep(0.05 * (attempt + 1))
        return (time.perf_counter() - t0) * 1e3

    # Warmup: sequential (B=1 path), then concurrent bursts so every pow2
    # batch size the continuous batcher can form gets compiled pre-timing.
    for raw in reqs[:5]:
        one(raw)
    unloaded = np.array([one(r) for r in reqs[:300]])
    with concurrent.futures.ThreadPoolExecutor(clients) as ex:
        list(ex.map(one, reqs[: 8 * clients]))
    t0 = time.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(clients) as ex:
        latencies = list(ex.map(one, reqs))
    wall = time.perf_counter() - t0
    lat = np.array(latencies)
    out = {
        "throughput_rps": round(requests / wall, 1),
        "p50_unloaded_ms": round(float(np.percentile(unloaded, 50)), 2),
        "p50_ms": round(float(np.percentile(lat, 50)), 2),
        "p95_ms": round(float(np.percentile(lat, 95)), 2),
        "p99_ms": round(float(np.percentile(lat, 99)), 2),
    }
    if count_non_2xx:
        out["predict_non_2xx"] = len(non_2xx)
    return out


_BUCKET_RE = re.compile(
    r'^pio_query_latency_ms_bucket\{le="([^"]+)"\} (\d+)$')


def _scrape_server_hist(port: int):
    """Server-side latency percentiles from /metrics (the shared-registry
    histogram), emitted NEXT TO the client-side numbers so client/server
    measurement drift is visible in one JSON line.  Bucket-interpolated,
    so expect quantization vs the client's exact percentiles — a LARGE gap
    means one side is measuring the wrong thing."""
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=10) as resp:
        text = resp.read().decode()
    buckets = []  # (le, cumulative_count)
    for line in text.splitlines():
        m = _BUCKET_RE.match(line)
        if m:
            le = float("inf") if m.group(1) == "+Inf" else float(m.group(1))
            buckets.append((le, int(m.group(2))))
    if not buckets or buckets[-1][1] == 0:
        return {}
    total = buckets[-1][1]

    def q(p):
        target = p * total
        prev_le, prev_cum = 0.0, 0
        for le, cum in buckets:
            if cum >= target and cum > prev_cum:
                if le == float("inf"):
                    return prev_le
                frac = (target - prev_cum) / (cum - prev_cum)
                return prev_le + (le - prev_le) * frac
            prev_le, prev_cum = le, cum
        return prev_le

    return {"server_p50_ms": round(q(0.5), 2),
            "server_p95_ms": round(q(0.95), 2),
            "server_p99_ms": round(q(0.99), 2),
            "server_count": total}


# --------------------------------------------------------------------------
# Sweep mode (ISSUE 6): scheduler coalescing vs concurrency level
# --------------------------------------------------------------------------

# Deadline mix for sweep drives: (budget_ms, fraction).  The loose tier
# never sheds; the tight tier exercises the deadline-aware window close +
# queue shed — any tight request that can't make it must 504, not limp to
# a late 200.
_DEADLINE_MIX = ((2000.0, 0.75), (150.0, 0.25))
# Client-side grace when judging "served late": the closed-loop client's
# own scheduling/read overhead rides on top of the server-side latency.
_VIOLATION_GRACE_MS = 50.0

_BATCHER_FAMS = ("pio_batch_dispatch_total", "pio_batch_requests_total",
                 "pio_queue_rejected_total")
_BATCH_METRIC_RE = re.compile(
    r'^(pio_batch_dispatch_total|pio_batch_requests_total|'
    r'pio_queue_rejected_total)\{model="default"\} (\S+)$|'
    r'^pio_batch_size_bucket\{model="default",le="([^"]+)"\} (\d+)$|'
    r'^pio_queue_shed_total\{model="default",reason="([^"]+)"\} (\d+)$')


def _scrape_batcher(port: int):
    """Scheduler flow counters for model "default" (sweep deltas)."""
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=10) as resp:
        text = resp.read().decode()
    out = {"counters": {}, "batch_size_bucket": {}, "shed": {}}
    for line in text.splitlines():
        m = _BATCH_METRIC_RE.match(line)
        if not m:
            continue
        if m.group(1):
            out["counters"][m.group(1)] = float(m.group(2))
        elif m.group(3):
            out["batch_size_bucket"][m.group(3)] = int(m.group(4))
        else:
            out["shed"][m.group(5)] = int(m.group(6))
    return out


def _batcher_delta(before, after):
    counters = {k: after["counters"].get(k, 0) - before["counters"].get(k, 0)
                for k in _BATCHER_FAMS}
    # de-cumulate the le-bucket deltas into per-bin counts
    cum = {le: after["batch_size_bucket"].get(le, 0)
           - before["batch_size_bucket"].get(le, 0)
           for le in after["batch_size_bucket"]}
    hist, prev = {}, 0
    for le in sorted(cum, key=lambda v: float(v.replace("+Inf", "inf"))):
        hist[le] = cum[le] - prev
        prev = cum[le]
    shed = {r: after["shed"].get(r, 0) - before["shed"].get(r, 0)
            for r in set(after["shed"]) | set(before["shed"])}
    dispatches = counters["pio_batch_dispatch_total"]
    requests = counters["pio_batch_requests_total"]
    return {
        "dispatches": int(dispatches),
        "requests": int(requests),
        "dispatches_per_request": (round(dispatches / requests, 4)
                                   if requests else None),
        "mean_batch_size": (round(requests / dispatches, 2)
                            if dispatches else None),
        "batch_size_dist": {le: n for le, n in sorted(
            hist.items(), key=lambda kv: float(kv[0].replace("+Inf", "inf")))
            if n},
        "rejected_429": int(counters["pio_queue_rejected_total"]),
        "shed": {k: v for k, v in sorted(shed.items()) if v},
    }


def _drive_level(port: int, n_users: int, clients: int, requests: int,
                 on_warm=None, users=None, sliced=False):
    """Closed-loop drive at ONE concurrency level; every request carries
    a deadline header.  No retries — every status is an outcome the
    sweep records (a 504 is a shed, not a failure to hide).

    ``on_warm`` fires after the warmup requests, before the measured
    drive — counter scrapes taken there exclude warmup traffic.

    ``users`` (optional) supplies the per-request user ids — the Zipf
    round precomputes one skewed draw and replays the IDENTICAL request
    stream cache-on and cache-off, so the A/B compares the cache, not
    two different workloads.

    ``sliced`` hands each worker thread a strided slice of the request
    list to loop over instead of one executor task per request: at
    sub-millisecond service times (the cache hit path) the per-future
    dispatch overhead of 2000 tasks on a shared-core box otherwise
    *becomes* the measurement."""
    import socket

    rng = np.random.default_rng(2)
    reqs = []
    for i in range(requests):
        uid = users[i] if users is not None else rng.integers(0, n_users)
        payload = json.dumps({"user": f"u{uid}", "num": 10}).encode()
        roll, budget_ms = rng.random(), _DEADLINE_MIX[0][0]
        acc = 0.0
        for ms, frac in _DEADLINE_MIX:
            acc += frac
            if roll < acc:
                budget_ms = ms
                break
        raw = (b"POST /queries.json HTTP/1.1\r\nHost: b\r\n"
               b"Content-Type: application/json\r\n"
               b"X-PIO-Deadline-Ms: " + str(int(budget_ms)).encode()
               + b"\r\nContent-Length: " + str(len(payload)).encode()
               + b"\r\n\r\n" + payload)
        reqs.append((raw, budget_ms))
    local = threading.local()
    _CL = b"content-length:"
    outcomes = []
    lock = threading.Lock()

    def one(item):
        raw, budget_ms = item
        t0 = time.perf_counter()
        for attempt in range(3):
            try:
                conn = getattr(local, "conn", None)
                if conn is None:
                    conn = local.conn = socket.create_connection(
                        ("127.0.0.1", port), timeout=30)
                    conn.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY, 1)
                conn.sendall(raw)
                buf = b""
                while True:
                    part = conn.recv(65536)
                    if not part:
                        raise OSError("closed")
                    buf += part
                    end = buf.find(b"\r\n\r\n")
                    if end >= 0:
                        break
                status = int(buf[9:12])
                head = buf[:end].lower()
                i = head.find(_CL)
                stop = head.find(b"\r", i)
                if stop < 0:
                    stop = len(head)
                need = end + 4 + int(head[i + len(_CL):stop])
                # Deadline attestation: the server reports the budget it
                # had left at its late-shed verdict (the budget header
                # means "remaining budget at receipt"; client wall time
                # additionally carries transport/backlog queueing).  A
                # 200 with remaining <= 0 is a served-late violation.
                j = head.find(b"x-pio-deadline-remaining-ms:")
                remaining_ms = None
                if j >= 0:
                    jstop = head.find(b"\r", j)
                    try:
                        remaining_ms = float(
                            head[j + 28:jstop if jstop > 0 else None])
                    except ValueError:
                        pass
                while len(buf) < need:
                    part = conn.recv(65536)
                    if not part:
                        raise OSError("closed")
                    buf += part
                # Server-attested wall (X-PIO-Server-Ms): the waterfall
                # stage sum is reconciled against its p50 (ISSUE 9).
                j = head.find(b"x-pio-server-ms:")
                server_ms = None
                if j >= 0:
                    jstop = head.find(b"\r", j)
                    try:
                        server_ms = float(
                            head[j + 16:jstop if jstop > 0 else None])
                    except ValueError:
                        pass
                ms = (time.perf_counter() - t0) * 1e3
                with lock:
                    outcomes.append((status, ms, budget_ms, remaining_ms,
                                     server_ms))
                return
            except (OSError, ValueError):
                try:
                    conn.close()
                except Exception:
                    pass
                local.conn = None
                if attempt == 2:
                    raise
                time.sleep(0.05 * (attempt + 1))

    for item in reqs[:5]:   # connection + compile warmup
        one(item)
    with lock:
        outcomes.clear()
    if on_warm is not None:
        on_warm()
    t0 = time.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(clients) as ex:
        if sliced:
            # One long-lived task per worker, each looping a strided
            # slice (stride keeps the deadline mix and user skew evenly
            # spread).  Still closed-loop at `clients` in flight.
            def _run_slice(k):
                for item in reqs[k::clients]:
                    one(item)
            list(ex.map(_run_slice, range(clients)))
        else:
            list(ex.map(one, reqs))
    wall = time.perf_counter() - t0
    ok = np.array([ms for s, ms, _, _, _ in outcomes if s == 200])
    statuses = {}
    for s, _, _, _, _ in outcomes:
        statuses[str(s)] = statuses.get(str(s), 0) + 1
    sent_tight = sum(1 for _, _, b, _, _ in outcomes if b < 1000)
    shed_504 = sum(1 for s, _, _, _, _ in outcomes if s == 504)
    # served_late_200: the server ATTESTS (X-PIO-Deadline-Remaining-Ms)
    # its budget was already spent yet it answered 200 anyway — must be
    # 0 (the transport's late-response shed makes this structural).
    # client_over_budget_200 additionally counts transport queueing the
    # deadline header doesn't cover (context, not a violation).
    served_late = sum(
        1 for s, _, _, rem, _ in outcomes
        if s == 200 and rem is not None and rem < 0)
    client_over = sum(
        1 for s, ms, b, _, _ in outcomes
        if s == 200 and ms > b + _VIOLATION_GRACE_MS)
    attested = sorted(sm for s, _, _, _, sm in outcomes
                      if s == 200 and sm is not None)
    def _pct(p):
        # A level can come back with ZERO 200s (100% fault plans): the
        # record says so via null percentiles, not a percentile crash.
        return round(float(np.percentile(ok, p)), 2) if ok.size else None

    return {
        "throughput_rps": round(len(outcomes) / wall, 1),
        "p50_ms": _pct(50),
        "p95_ms": _pct(95),
        "p99_ms": _pct(99),
        # server-attested wall p50: the waterfall reconciliation anchor
        "server_ms_p50": (round(attested[len(attested) // 2], 2)
                          if attested else None),
        "statuses": statuses,
        "deadlines": {"tight_sent": sent_tight, "shed_504": shed_504,
                      "served_late_200": served_late,
                      "client_over_budget_200": client_over},
    }


def _waterfall_for_level(log_path: str, offset: int, server_ms_p50):
    """Per-stage attribution for the rows the level appended to the
    PIO_REQUEST_LOG wide-event JSONL (ISSUE 9): mean/p50 per stage, the
    dominant stage + recommended attack, and the acceptance
    reconciliation — waterfall stage sum vs the SERVER-ATTESTED
    X-PIO-Server-Ms wall, both at p50 (must agree within 10%)."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent / "tools"))
    import attribute_serve

    # The JSONL line lands after the response bytes reach the client, so
    # poll until the tail stops growing (the slowest handler threads may
    # still be writing their finalize lines).
    deadline = time.monotonic() + 2.0
    text, last_len = "", -1
    while time.monotonic() < deadline:
        try:
            with open(log_path, encoding="utf-8") as f:
                f.seek(offset)
                text = f.read()
        except OSError:
            return None
        if len(text) == last_len:
            break
        last_len = len(text)
        time.sleep(0.05)
    rows = attribute_serve.parse_request_log(text)
    if not rows:
        return None
    out = attribute_serve.attribute_log(rows)
    if server_ms_p50 and out.get("reconciliation"):
        # Cross-check: the CLIENT-observed X-PIO-Server-Ms p50 should
        # match the serverMs the wide events recorded themselves.
        out["reconciliation"]["client_observed_server_p50_ms"] = \
            server_ms_p50
    return out


def _sweep(args) -> None:
    from predictionio_tpu.server import EngineServer
    from predictionio_tpu.serving import SchedulerConfig

    levels = [int(x) for x in args.concurrency.split(",") if x.strip()]
    eng, variant, storage, n_users = _setup(args.engine)
    record = {"mode": "sweep", "engine": args.engine, "levels": levels,
              "requests_per_level": args.requests, "rounds": {}}
    # Per-request wide events (ISSUE 9): every level's rows feed the
    # per-stage waterfall block next to the client percentiles.
    request_log = os.environ.setdefault(
        "PIO_REQUEST_LOG",
        os.path.join(tempfile.mkdtemp(prefix="pio_bench_"),
                     "requests.jsonl"))

    def _log_offset():
        try:
            return os.path.getsize(request_log)
        except OSError:
            return 0

    srv = EngineServer(eng, variant, storage, host="127.0.0.1", port=0)
    srv.start()
    batched = []
    for lvl in levels:
        before = _scrape_batcher(srv.port)
        marks = {}
        res = _drive_level(srv.port, n_users, lvl, args.requests,
                           on_warm=lambda: marks.setdefault(
                               "offset", _log_offset()))
        res["scheduler"] = _batcher_delta(before, _scrape_batcher(srv.port))
        res["knobs"] = {k: srv.scheduler.snapshot()["default"][k]
                        for k in ("windowMs", "maxBatch")}
        res["waterfall"] = _waterfall_for_level(
            request_log, marks.get("offset", 0), res.get("server_ms_p50"))
        batched.append({"concurrency": lvl, **res})
        print(json.dumps({"round": "batched", "concurrency": lvl, **res}))
    record["rounds"]["clean_batched"] = batched
    if args.faults:
        # Faulted round at the TOP level, same server/model — the
        # scheduler must keep coalescing and shedding correctly while
        # the fault plan stresses the transport.
        os.environ["PIO_FAULTS"] = args.faults
        before = _scrape_batcher(srv.port)
        res = _drive_level(srv.port, n_users, levels[-1], args.requests)
        res["scheduler"] = _batcher_delta(before, _scrape_batcher(srv.port))
        os.environ.pop("PIO_FAULTS", None)
        record["rounds"]["faulted_batched"] = {
            "concurrency": levels[-1], "faults": args.faults, **res}
        print(json.dumps({"round": "faulted", **record["rounds"]
                          ["faulted_batched"]}))
    srv.stop()

    # Unbatched baseline: identical engine/levels, per-request dispatch
    # (inline scheduler — admission stays, coalescing goes).
    srv = EngineServer(eng, variant, storage, host="127.0.0.1", port=0,
                       scheduler_config=SchedulerConfig.from_env(
                           enabled=False))
    srv.start()
    unbatched = []
    for lvl in levels:
        marks = {}
        res = _drive_level(srv.port, n_users, lvl, args.requests,
                           on_warm=lambda: marks.setdefault(
                               "offset", _log_offset()))
        res["waterfall"] = _waterfall_for_level(
            request_log, marks.get("offset", 0), res.get("server_ms_p50"))
        unbatched.append({"concurrency": lvl, **res})
        print(json.dumps({"round": "unbatched", "concurrency": lvl,
                          **res}))
    srv.stop()
    record["rounds"]["clean_unbatched"] = unbatched

    for b, u in zip(batched, unbatched):
        if b["p99_ms"] is not None and u["p99_ms"] is not None:
            b["p99_vs_unbatched_ms"] = round(b["p99_ms"] - u["p99_ms"], 2)
    print(json.dumps(record))
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(record, f, indent=1)
        print(f"wrote {args.out}")


# --------------------------------------------------------------------------
# Zipf mode (ISSUE 20): generation-keyed result cache under skewed traffic
# --------------------------------------------------------------------------

_RC_METRIC_RE = re.compile(
    r'^pio_result_cache_(hits_total|misses_total|hit_age_s_sum|'
    r'hit_age_s_count)(?:\{[^}]*\})? (\S+)$')


def _scrape_result_cache(port: int):
    """Result-cache flow counters (hits summed across tiers) for the
    per-level deltas of the Zipf round."""
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=10) as resp:
        text = resp.read().decode()
    out = {"hits_total": 0.0, "misses_total": 0.0,
           "hit_age_s_sum": 0.0, "hit_age_s_count": 0.0}
    for line in text.splitlines():
        m = _RC_METRIC_RE.match(line)
        if m:
            out[m.group(1)] += float(m.group(2))
    return out


def _rc_delta(before, after):
    before = before or {k: 0.0 for k in after}
    d = {k: after[k] - before.get(k, 0.0) for k in after}
    total = d["hits_total"] + d["misses_total"]
    return {
        "hits": int(d["hits_total"]),
        "misses": int(d["misses_total"]),
        "hit_rate": round(d["hits_total"] / total, 4) if total else None,
        # Freshness: mean age of the cached answers actually SERVED.
        # Generation keying bounds it by the promotion cadence — there is
        # no TTL on positive entries to hide behind.
        "mean_hit_age_s": (round(d["hit_age_s_sum"] / d["hit_age_s_count"],
                                 3) if d["hit_age_s_count"] else None),
    }


def _zipf_round(args) -> None:
    """ISSUE 20 round: the generation-keyed result cache vs Zipfian
    traffic on ONE live server.

    Sweeps c=1,8,32,64 twice over the IDENTICAL precomputed request
    stream (user ids drawn Zipf(s), seeded) — cache disabled, then
    enabled cold — recording client rps/p99 next to the cache's own
    hit-rate and served-hit-age (freshness) deltas.  Acceptance at c=64:
    cache-on ≥2x rps OR ≥50% p99 reduction.

    Then the invalidation-by-construction attestation: a background
    Zipf drive saturates the cache, a second trained instance is
    promoted over live HTTP, and every response after the /reload ack
    must carry the POST-swap serve-id generation — zero stale answers,
    zero non-2xx across the swap."""
    import urllib.request as ur

    from predictionio_tpu.controller import RuntimeContext
    from predictionio_tpu.server import EngineServer
    from predictionio_tpu.workflow.core_workflow import run_train

    # De-tuned SLO so closed-loop saturation on a shared core can't trip
    # the burn-rate gate mid-round — same calibration as --quality.  The
    # sweep itself runs at SHIPPED quality-sampling defaults (the ≤5%
    # overhead config); only the attestation server below forces full
    # sampling, because the generation check reads the per-response
    # serve-id.
    os.environ["PIO_SLO_AVAILABILITY"] = "0.9"
    os.environ["PIO_SLO_LATENCY_TARGET_MS"] = "10000"

    # A representative corpus: at the default 4000 items the dispatch is
    # transport-cost and a cache can only add overhead — the regime the
    # cache targets is the BENCH_ANN one, where a miss pays a real MIPS
    # scan over a large item set.
    eng, variant, storage, n_users = _setup(args.engine,
                                            n_items=args.zipf_items)
    levels = [1, 8, 32, 64]
    s = args.zipf_s
    ranks = np.arange(1, n_users + 1, dtype=np.float64)
    probs = ranks ** -s
    probs /= probs.sum()
    # One fresh draw PER LEVEL (seeded, identical across both arms): the
    # cache-on arm starts cold at c=1 and warms across the sweep exactly
    # like a long-running instance — the per-level hit-rate column
    # records the cold→steady-state trajectory instead of re-paying the
    # cold start at every level.
    draws = [np.random.default_rng(7 + i).choice(n_users,
                                                 size=args.requests,
                                                 p=probs)
             for i in range(len(levels))]
    record = {"mode": "zipf", "engine": args.engine, "zipf_s": s,
              "n_items": args.zipf_items,
              "levels": levels, "requests_per_level": args.requests,
              "rounds": {"cache_off": [], "cache_on": []}}

    srv = EngineServer(eng, variant, storage, host="127.0.0.1", port=0)
    srv.start()
    for cache_on in (False, True):
        arm = "cache_on" if cache_on else "cache_off"
        srv.result_cache.set_enabled(cache_on)
        srv.result_cache.clear()    # each ARM starts cold
        for lvl, draw in zip(levels, draws):
            marks = {}
            res = _drive_level(
                srv.port, n_users, lvl, args.requests,
                on_warm=lambda: marks.setdefault(
                    "rc", _scrape_result_cache(srv.port)),
                users=draw, sliced=True)
            res["result_cache"] = _rc_delta(
                marks.get("rc"), _scrape_result_cache(srv.port))
            res["distinct_users_in_stream"] = int(np.unique(draw).size)
            record["rounds"][arm].append({"concurrency": lvl, **res})
            print(json.dumps({"round": arm, "concurrency": lvl, **res}))

    srv.stop()

    # -- promotion attestation -------------------------------------------
    # Fresh server with FULL quality sampling: every 200 carries a
    # serve-id (g<generation>-<nonce>) the staleness check reads.
    os.environ["PIO_QUALITY_SAMPLE"] = "1.0"
    srv = EngineServer(eng, variant, storage, host="127.0.0.1", port=0)
    srv.start()

    def _one(user):
        req = ur.Request(
            f"http://127.0.0.1:{srv.port}/queries.json",
            data=json.dumps({"user": user, "num": 10}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with ur.urlopen(req, timeout=30) as r:
                r.read()
                return r.status, r.headers.get("X-PIO-Serve-Id", "")
        except urllib.error.HTTPError as e:
            e.read()
            return e.code, ""

    # Train the candidate BEFORE the drive starts (one shared core: a
    # retrain under 4 closed-loop threads would be starved for minutes)
    # — the SWAP still lands under live traffic, which is the claim.
    run_train(eng, variant, RuntimeContext.create(storage=storage))

    hot = [f"u{u}" for u in draws[-1][:8]]
    stop = threading.Event()
    bg = {"n": 0, "non_2xx": 0}
    bg_lock = threading.Lock()

    def _bg(k0):
        k = k0
        while not stop.is_set():
            status, _sid = _one(hot[k % len(hot)])
            with bg_lock:
                bg["n"] += 1
                if not 200 <= status < 300:
                    bg["non_2xx"] += 1
            k += 1

    threads = [threading.Thread(target=_bg, args=(k,), daemon=True)
               for k in range(4)]
    for t in threads:
        t.start()
    time.sleep(1.0)     # saturate: the hot set is all cache hits now
    pre_swap = _scrape_result_cache(srv.port)
    req = ur.Request(f"http://127.0.0.1:{srv.port}/reload", data=b"",
                     method="POST")
    with ur.urlopen(req, timeout=120) as r:
        assert r.status == 200
    # After the reload ACK no response may carry the pre-swap generation
    # — a hit on a stale fingerprint key is the corruption the design
    # rules out by construction.
    stale_after_swap = post_non_2xx = 0
    post_gens = set()
    for k in range(32):
        status, sid = _one(hot[k % len(hot)])
        if not 200 <= status < 300:
            post_non_2xx += 1
            continue
        gen = sid.split("-", 1)[0]
        post_gens.add(gen)
        if gen != "g2":
            stale_after_swap += 1
    stop.set()
    for t in threads:
        t.join(timeout=10)
    srv.stop()
    record["promotion"] = {
        "drive_requests": bg["n"],
        "non_2xx_across_swap": bg["non_2xx"] + post_non_2xx,
        "pre_swap_hit_rate": _rc_delta(None, pre_swap)["hit_rate"],
        "post_swap_generations": sorted(post_gens),
        "stale_after_swap": stale_after_swap,
    }

    off64 = record["rounds"]["cache_off"][-1]
    on64 = record["rounds"]["cache_on"][-1]
    speedup = (round(on64["throughput_rps"] / off64["throughput_rps"], 2)
               if off64["throughput_rps"] else None)
    p99_red = (round(100.0 * (1 - on64["p99_ms"] / off64["p99_ms"]), 1)
               if on64["p99_ms"] is not None and off64["p99_ms"] else None)
    record["acceptance"] = {
        "c64_rps_speedup": speedup,
        "c64_p99_reduction_pct": p99_red,
        "c64_hit_rate": on64["result_cache"]["hit_rate"],
        "passed": bool(((speedup or 0) >= 2.0 or (p99_red or 0) >= 50.0)
                       and stale_after_swap == 0
                       and bg["non_2xx"] + post_non_2xx == 0),
    }
    print(json.dumps({"promotion": record["promotion"],
                      "acceptance": record["acceptance"]}))
    out = args.out or "BENCH_ZIPF_r01.json"
    with open(out, "w", encoding="utf-8") as f:
        json.dump(record, f, indent=1)
    print(f"wrote {out}")


# --------------------------------------------------------------------------
# Corpus-scale mode (ISSUE 8): exact vs sharded vs IVF retrieval at
# 1e5/1e6 items, through the PR-6 scheduler path
# --------------------------------------------------------------------------

_RETR_METRIC_RE = re.compile(
    r'^(pio_retrieval_requests_total|pio_retrieval_candidates_total)'
    r'\{([^}]*)\} (\S+)$')

_RECALL_METRIC_RE = re.compile(
    r'^(pio_retrieval_recall(?:_baseline|_scanned_fraction'
    r'|_shortlist_saturation|_cell_miss|_captures_total)?)'
    r'\{([^}]*)\} (\S+)$')


def _scrape_recall(port: int):
    """Online sampled-recall gauges by rung (ISSUE 16) from the live
    exposition — the artifact records what an operator's scrape would
    actually see, not an in-process shortcut."""
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=10) as resp:
        text = resp.read().decode()
    rungs, counts = {}, {}
    for line in text.splitlines():
        m = _RECALL_METRIC_RE.match(line)
        if not m:
            continue
        name, labels, value = m.group(1), dict(
            kv.split("=") for kv in
            m.group(2).replace('"', "").split(",") if "=" in kv), \
            float(m.group(3))
        if name == "pio_retrieval_recall_captures_total":
            counts[labels.get("result", "?")] = int(value)
            continue
        row = rungs.setdefault(labels.get("rung", "?"), {})
        if name == "pio_retrieval_recall":
            row[f"recall_{labels.get('window', '?')}"] = value
            row["k"] = int(labels.get("k", 0))
        elif name == "pio_retrieval_recall_baseline":
            row["baseline"] = value
        else:
            row[name.replace("pio_retrieval_recall_", "")] = value
    return {"rungs": rungs, "captures": counts}


def _scrape_retrieval(port: int):
    """pio_retrieval_* counters by rung (corpus-scale deltas)."""
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=10) as resp:
        text = resp.read().decode()
    out = {}
    for line in text.splitlines():
        m = _RETR_METRIC_RE.match(line)
        if not m:
            continue
        rung = dict(kv.split("=") for kv in
                    m.group(2).replace('"', "").split(",")).get("rung", "?")
        out.setdefault(rung, {})[m.group(1)] = float(m.group(3))
    return out


def _synth_corpus(n_items: int, n_users: int, dim: int, seed: int = 0):
    """Clustered synthetic corpus + queries near members — the IVF
    design target (normalized two-tower-style vectors), built directly
    so the bench measures RETRIEVAL at scales training can't reach in a
    bench budget."""
    rng = np.random.default_rng(seed)
    n_clusters = max(8, int(round(n_items ** 0.5 / 2)))
    centers = rng.normal(size=(n_clusters, dim)).astype(np.float32)
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    assign = rng.integers(0, n_clusters, n_items)
    items = centers[assign] + 0.15 * rng.normal(
        size=(n_items, dim)).astype(np.float32)
    items /= np.linalg.norm(items, axis=1, keepdims=True)
    src = rng.integers(0, n_items, n_users)
    users = items[src] + 0.05 * rng.normal(
        size=(n_users, dim)).astype(np.float32)
    users /= np.linalg.norm(users, axis=1, keepdims=True)
    return users.astype(np.float32), items.astype(np.float32)


def _corpus_scale(args) -> None:
    """One tiny trained twotower server per scale; the serving wrapper
    is swapped for a synthetic N-item corpus and the SAME load is driven
    through the scheduler path with the retrieval rung forced per round
    (exact single-device → IVF → quantized PQ rungs → mesh-sharded; the
    shard staging happens LAST so the exact baseline really is one
    device).  ISSUE 13: above ``_PQ_ONLY_ABOVE`` items the exact/IVF
    brute rounds are skipped (a 1e7 fp32 scan per request would take
    this box minutes per round) — the quantized rungs are the only
    serving shape there, which is exactly the claim under test."""
    from predictionio_tpu.data.event import BiMap
    from predictionio_tpu.parallel.mesh import make_mesh
    from predictionio_tpu.retrieval import Retriever, build_ivf, build_pq
    from predictionio_tpu.server import EngineServer
    from predictionio_tpu.templates.twotower.engine import (
        TwoTowerModelWrapper,
    )

    scales = [int(float(x)) for x in args.corpus_scale.split(",")
              if x.strip()]
    dim, n_users = 32, 2000
    record = {"mode": "corpus_scale", "dim": dim,
              "clients": args.clients,
              "requests_per_round": args.requests, "scales": {}}
    eng, variant, storage, _ = _setup("twotower")
    _PQ_ONLY_ABOVE = 2_000_000
    for n_items in scales:
        users, items = _synth_corpus(n_items, n_users, dim)
        t0 = time.perf_counter()
        ivf = build_ivf(items, force=True)
        build_s = round(time.perf_counter() - t0, 1)
        t0 = time.perf_counter()
        pq = build_pq(items, ivf=ivf)
        pq_build_s = round(time.perf_counter() - t0, 1)
        wrapper = TwoTowerModelWrapper(
            user_vecs=users, item_vecs=items,
            user_index=BiMap({f"u{j}": j for j in range(n_users)}),
            item_index=BiMap({f"i{j}": j for j in range(n_items)}),
            ivf=ivf, pq=pq)
        # Per-scale serving knobs, recorded in the artifact: the PQ
        # shortlist depth scales with cluster density (the 4·k default
        # orders ~40 among thousands of same-cluster neighbors — recall
        # plateaus ~0.8 at 1e6 and ~0.9 at 1e7; re-ranking deeper is
        # nearly free and the measured trade-off table is in the
        # README), and at 1e7 the probe width narrows (recall is
        # shortlist- not probe-limited on this corpus, measured offline
        # below).  The host-MACs ceiling is raised so the quantized
        # rungs serve from the host numpy path — the honest rung for
        # this 1-core CPU box, same argument as r01's
        # IVF-over-sharded call.
        knobs = {"PIO_PQ_RERANK": "256",
                 "PIO_SERVE_HOST_MACS": "100000000000000"}
        if n_items > _PQ_ONLY_ABOVE:
            knobs["PIO_IVF_NPROBE"] = "64"
            knobs["PIO_PQ_RERANK"] = "1024"
        os.environ.update(knobs)
        # Train-time recall scorecard at the SAME serving knobs (ISSUE
        # 16): the baked baseline the online monitor compares against —
        # built here exactly as `pio train` would bake it.
        from predictionio_tpu.obs.recall import build_recall_scorecard

        t0 = time.perf_counter()
        wrapper.recall = build_recall_scorecard(
            users, items, ivf=ivf, pq=pq, sample=64, seed=0,
            name="bench")
        scorecard_build_s = round(time.perf_counter() - t0, 1)
        # Offline recall@10 vs exact on a query sample (the latency
        # rounds below are meaningless if recall collapsed).
        sample = users[:64]
        exact_s = sample @ items.T
        want = np.argsort(-exact_s, axis=1)[:, :10]
        r = wrapper.retriever()

        def _recall_of(rung):
            os.environ["PIO_RETRIEVAL_RUNG"] = rung
            _, ids, info = r.topk(sample, 10)
            rec = sum(len(set(ids[b, :10]) & set(want[b]))
                      for b in range(len(sample))) / want.size
            return rec, info

        recall, info = _recall_of("ivf")
        pq_recall, pq_info = _recall_of("ivf_pq")
        flat_recall, _flat_info = _recall_of("pq_flat")
        srv = EngineServer(eng, variant, storage, host="127.0.0.1",
                           port=0)
        srv.start()
        srv._models = [wrapper]  # serve the synthetic generation
        # Re-arm the recall monitor on the swapped-in synthetic wrapper
        # so the online sampled gauges cover the measured rounds.
        srv.recall.on_generation(srv._generation, [wrapper])
        entry = {"n_items": n_items, "knobs": knobs,
                 "scorecard": (wrapper.recall.summary()
                               if wrapper.recall else None),
                 "scorecard_build_s": scorecard_build_s, "ivf": {
            "nlist": ivf.nlist, "nprobe": info["nprobe"],
            "build_s": build_s, "recall_at_10": round(recall, 4),
            "scanned_fraction": round(
                info["candidates"] / (len(sample) * n_items), 4)},
            "pq": {
            "m": pq.m, "bytes_per_item": pq.bytes_per_item(),
            "exact_bytes_per_item": dim * 4,
            "compression": round(dim * 4 / pq.bytes_per_item(), 1),
            "build_s": pq_build_s, "rerank": pq_info["rerank"],
            "nprobe": pq_info["nprobe"],
            "recall_at_10_ivf_pq": round(pq_recall, 4),
            "recall_at_10_pq_flat": round(flat_recall, 4),
            "scanned_fraction_ivf_pq": round(
                pq_info["candidates"] / (len(sample) * n_items), 4),
        }, "rounds": {}}
        if n_items > _PQ_ONLY_ABOVE:
            rungs = ("ivf_pq",)
            for skipped in ("device", "ivf", "pq_flat", "sharded"):
                entry["rounds"][skipped] = {
                    "skipped": "beyond the exact-serving envelope on "
                               "this box (fp32 scan/full LUT scan per "
                               "request); quantized ivf_pq is the "
                               "serving shape at this scale"}
        else:
            rungs = ("device", "ivf", "ivf_pq", "pq_flat", "sharded")
        # Shard staging LAST: once the corpus is mesh-sharded the
        # "device" rung would no longer be a single-device baseline.
        for rung in rungs:
            if rung == "sharded":
                os.environ["PIO_SERVE_SHARD_ABOVE"] = "1"
                os.environ["PIO_SERVE_HOST_MACS"] = "200000000"
                if not r.maybe_shard(make_mesh({"data": 8})):
                    entry["rounds"]["sharded"] = {
                        "skipped": "mesh unavailable"}
                    continue
            os.environ["PIO_RETRIEVAL_RUNG"] = rung
            # Scrape AFTER warmup so the counter delta covers exactly
            # the measured window's facade traffic.
            before = _scrape_retrieval(srv.port)
            res = _drive_level(srv.port, n_users, args.clients,
                               args.requests,
                               on_warm=lambda: before.update(
                                   _scrape_retrieval(srv.port)))
            after = _scrape_retrieval(srv.port)
            reqs = (after.get(rung, {}).get(
                "pio_retrieval_requests_total", 0)
                - before.get(rung, {}).get(
                    "pio_retrieval_requests_total", 0))
            cand = (after.get(rung, {}).get(
                "pio_retrieval_candidates_total", 0)
                - before.get(rung, {}).get(
                    "pio_retrieval_candidates_total", 0))
            # Denominator = answered queries: shed/non-200 requests never
            # reached the facade, so dividing by requests-sent would
            # understate slow rungs' scan cost exactly when they shed.
            answered = res["statuses"].get("200", 0)
            res["retrieval"] = {
                "facade_calls": int(reqs),
                # scanned rows per answered HTTP query at matched load —
                # the sublinearity claim in one number
                "candidates_per_query": round(cand / max(answered, 1), 1),
            }
            entry["rounds"][rung] = res
            print(json.dumps({"scale": n_items, "rung": rung, **res}))
        # Online sampled recall per approximate rung (ISSUE 16): what a
        # live scrape of the shipped-default monitor actually shows
        # after the measured rounds, next to the offline numbers above.
        entry["online_sampled_recall"] = _scrape_recall(srv.port)
        for k in ("PIO_RETRIEVAL_RUNG", "PIO_SERVE_SHARD_ABOVE",
                  "PIO_PQ_RERANK", "PIO_IVF_NPROBE",
                  "PIO_SERVE_HOST_MACS"):
            os.environ.pop(k, None)
        dev, ivf_r = entry["rounds"].get("device"), \
            entry["rounds"].get("ivf")
        pq_r = entry["rounds"].get("ivf_pq")
        if dev and ivf_r and dev.get("p99_ms") and ivf_r.get("p99_ms"):
            entry["p99_ivf_vs_exact_ms"] = round(
                ivf_r["p99_ms"] - dev["p99_ms"], 2)
        if pq_r and ivf_r and pq_r.get("p99_ms") and ivf_r.get("p99_ms"):
            entry["p99_ivf_pq_vs_ivf_ms"] = round(
                pq_r["p99_ms"] - ivf_r["p99_ms"], 2)
        record["scales"][str(n_items)] = entry
        srv.stop()
    print(json.dumps(record))
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(record, f, indent=1)


# --------------------------------------------------------------------------
# Refresh mode (ISSUE 10): ingest + follow-mode refresh on a live server —
# event→servable staleness, warm vs cold wall, query p99 across a promotion
# --------------------------------------------------------------------------

def _drive_until(port: int, n_users: int, clients: int,
                 stop_event: "threading.Event", tight_budgets: bool = True):
    """Closed-loop drive that runs UNTIL ``stop_event`` (the refresh
    cycle completing) — the percentiles cover exactly the window a
    promotion swaps generations under load.  Every request carries a
    deadline header; a 200 whose server-attested remaining budget is
    negative counts as a served-late violation (must be 0).
    ``tight_budgets=False`` sends only generous budgets — the quality
    round's claim is zero non-2xx across the whole episode, so the
    drive must not shed by design."""
    import socket

    rng = np.random.default_rng(3)
    payload_of = [json.dumps({"user": f"u{u}", "num": 10}).encode()
                  for u in rng.integers(0, n_users, 512)]
    raws = []
    for i, p in enumerate(payload_of):
        budget = 2000 if (i % 4 or not tight_budgets) else 150
        raws.append(b"POST /queries.json HTTP/1.1\r\nHost: b\r\n"
                    b"Content-Type: application/json\r\n"
                    b"X-PIO-Deadline-Ms: " + str(budget).encode()
                    + b"\r\nContent-Length: " + str(len(p)).encode()
                    + b"\r\n\r\n" + p)
    local = threading.local()
    _CL = b"content-length:"
    lock = threading.Lock()
    outcomes = []

    def worker(wid):
        import itertools

        for i in itertools.count(wid):
            if stop_event.is_set():
                return
            raw = raws[i % len(raws)]
            t0 = time.perf_counter()
            try:
                conn = getattr(local, "conn", None)
                if conn is None:
                    conn = local.conn = socket.create_connection(
                        ("127.0.0.1", port), timeout=30)
                    conn.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY, 1)
                conn.sendall(raw)
                buf = b""
                while True:
                    part = conn.recv(65536)
                    if not part:
                        raise OSError("closed")
                    buf += part
                    end = buf.find(b"\r\n\r\n")
                    if end >= 0:
                        break
                status = int(buf[9:12])
                head = buf[:end].lower()
                j = head.find(_CL)
                stop = head.find(b"\r", j)
                need = end + 4 + int(head[j + len(_CL):
                                          stop if stop > 0 else None])
                while len(buf) < need:
                    part = conn.recv(65536)
                    if not part:
                        raise OSError("closed")
                    buf += part
                rem = None
                j = head.find(b"x-pio-deadline-remaining-ms:")
                if j >= 0:
                    jstop = head.find(b"\r", j)
                    try:
                        rem = float(head[j + 28:jstop if jstop > 0
                                         else None])
                    except ValueError:
                        pass
                ms = (time.perf_counter() - t0) * 1e3
                with lock:
                    outcomes.append((status, ms, rem))
            except (OSError, ValueError):
                try:
                    local.conn.close()
                except Exception:
                    pass
                local.conn = None
                time.sleep(0.02)

    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    stop_event.wait()
    for t in threads:
        t.join(5)
    wall = max(time.perf_counter() - t0, 1e-9)
    ok = np.array([ms for s, ms, _ in outcomes if s == 200])
    statuses = {}
    for s, _, _ in outcomes:
        statuses[str(s)] = statuses.get(str(s), 0) + 1
    served_late = sum(1 for s, _, rem in outcomes
                      if s == 200 and rem is not None and rem < 0)

    def _pct(p):
        return round(float(np.percentile(ok, p)), 2) if ok.size else None

    return {"requests": len(outcomes),
            "throughput_rps": round(len(outcomes) / wall, 1),
            "p50_ms": _pct(50), "p99_ms": _pct(99),
            "statuses": statuses,
            "served_late_200": served_late}


def _refresh_round(args) -> None:
    """ISSUE 10 round: a live engine server + a live event server, a
    delta ingested over HTTP, one follow-mode refresh cycle promoting
    through the staged-reload gate — while closed-loop clients keep
    querying and a sampler records event→servable staleness."""
    import datetime as dt

    from predictionio_tpu.data.storage import AccessKey, get_storage
    from predictionio_tpu.refresh import RefreshConfig, staleness_s
    from predictionio_tpu.refresh.daemon import HttpPromoter, RefreshDaemon
    from predictionio_tpu.server import EngineServer, EventServer
    from predictionio_tpu.controller import RuntimeContext
    from predictionio_tpu.workflow.core_workflow import run_train

    eng, variant, storage, n_users = _setup("twotower")
    ctx = RuntimeContext.create(storage=storage)
    app = storage.get_apps().get_by_name("benchapp")
    key = storage.get_access_keys().insert(AccessKey(key="", app_id=app.id))

    # Cold baseline at matched data scale: what a non-incremental loop
    # pays per cycle — a FULL retrain over the whole corpus.  Measured
    # IDLE, like the warm cycle below, so the walls compare.
    t0 = time.perf_counter()
    run_train(eng, variant, ctx)
    cold_s = time.perf_counter() - t0

    # Availability SLO calibrated for THIS drive: the deadline mix
    # intentionally sends 25% tight budgets that SHOULD shed under a
    # co-located train, and a shed counts as an error by design — a
    # 99.9% objective would read the bench's own load shape as an
    # outage.  10% budget means only real breakage trips the canary.
    os.environ["PIO_SLO_AVAILABILITY"] = "0.9"
    esrv = EngineServer(eng, variant, storage, host="127.0.0.1", port=0)
    esrv.start()
    evsrv = EventServer(storage=storage, host="127.0.0.1", port=0)
    evsrv.start()
    base = f"http://127.0.0.1:{esrv.port}"

    # Staleness sampler: ingest high-watermark (store MAX) vs the LIVE
    # server's served data watermark, sampled through the whole round.
    samples = []
    sampler_stop = threading.Event()

    def sample_staleness():
        ev = storage.get_events()
        while not sampler_stop.is_set():
            try:
                latest = ev.latest_event_time(app.id)
                with urllib.request.urlopen(base + "/", timeout=5) as r:
                    wm_raw = json.loads(r.read()).get("dataWatermark")
                wm = dt.datetime.fromisoformat(wm_raw) if wm_raw else None
                s = staleness_s(latest, wm)
                if s is not None:
                    samples.append(s)
            except Exception:
                pass
            time.sleep(0.05)

    sampler = threading.Thread(target=sample_staleness, daemon=True)
    sampler.start()

    # Ingest a delta over the LIVE event server (batched HTTP).
    rng = np.random.default_rng(9)
    n_delta = args.delta_events

    def ingest_delta():
        delta = [{"event": "rate", "entityType": "user",
                  "entityId": f"u{rng.integers(0, n_users)}",
                  "targetEntityType": "item",
                  "targetEntityId": f"i{rng.integers(0, 4600)}",
                  "properties": {"rating": float(rng.integers(1, 6))}}
                 for _ in range(n_delta)]
        t0 = time.perf_counter()
        for start in range(0, n_delta, 50):
            body = json.dumps(delta[start:start + 50]).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{evsrv.port}/batch/events.json?"
                f"accessKey={key}", data=body, method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                assert resp.status == 200
        return time.perf_counter() - t0

    daemon = RefreshDaemon(
        eng, variant, ctx,
        config=RefreshConfig(interval_s=1.0, eval_tolerance=5.0),
        promoter=HttpPromoter(base, canary_window_s=1.0,
                              canary_poll_s=0.2))

    # Cycle 1 — IDLE warm refresh: the wall that compares against the
    # cold retrain above, and the staleness drop when promotion lands.
    ingest1_s = ingest_delta()
    time.sleep(0.3)                     # staleness samples see the gap
    cycle_idle = dict(daemon.run_once())
    time.sleep(0.3)                     # post-promotion samples land
    stale_after_promo = samples[-1] if samples else None

    # Cycle 2 — warm refresh UNDER LOAD: closed-loop clients query
    # across the whole train→promote→canary window; p99 + the
    # served-late attestation are the promotion-transparency record.
    ingest2_s = ingest_delta()
    refresh_done = threading.Event()
    cycle_loaded = {}

    def run_cycle():
        t0 = time.perf_counter()
        try:
            cycle_loaded.update(daemon.run_once())
        finally:
            cycle_loaded["wall_s"] = round(time.perf_counter() - t0, 2)
            refresh_done.set()

    drive_box = {}
    driver = threading.Thread(
        target=lambda: drive_box.update(
            _drive_until(esrv.port, n_users, args.clients, refresh_done)),
        daemon=True)
    driver.start()
    time.sleep(0.5)  # let the drive reach steady state pre-promotion
    run_cycle()
    driver.join(15)
    time.sleep(0.3)  # a post-promotion staleness reading lands
    sampler_stop.set()
    sampler.join(2)

    warm_s = cycle_idle.get("trainS")
    arr = np.array(samples) if samples else np.zeros(1)
    record = {
        "mode": "refresh",
        "engine": "twotower",
        "corpus_events": 100_000,
        "delta_events": n_delta,
        "clients": args.clients,
        "slo_availability_objective": 0.9,
        "ingest": {"events": 2 * n_delta,
                   "wall_s": round(ingest1_s + ingest2_s, 2),
                   "events_per_s": round(
                       2 * n_delta / (ingest1_s + ingest2_s), 1)},
        "cold_retrain_s": round(cold_s, 2),
        "warm_refresh_train_s": warm_s,
        "warm_speedup": (round(cold_s / warm_s, 2)
                         if warm_s else None),
        "refresh_cycle_idle": cycle_idle,
        "staleness_after_first_promotion_s": (
            round(float(stale_after_promo), 2)
            if stale_after_promo is not None else None),
        "refresh_cycle_under_load": cycle_loaded,
        "staleness_s": {
            "samples": len(samples),
            "p50": round(float(np.percentile(arr, 50)), 2),
            "p90": round(float(np.percentile(arr, 90)), 2),
            "p99": round(float(np.percentile(arr, 99)), 2),
            "max": round(float(arr.max()), 2),
            "final": round(float(samples[-1]), 2) if samples else None,
        },
        "query_during_promotion": drive_box,
    }
    esrv.stop()
    evsrv.stop()
    print(json.dumps(record))
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(record, f, indent=1)
        print(f"wrote {args.out}")


def _quality_round(args) -> None:
    """ISSUE 11 round: (a) the serving-overhead record — p99 at c=N with
    the quality layer at its SHIPPED defaults (PIO_QUALITY_SAMPLE=0.1 +
    an armed shadow session) vs PIO_QUALITY_SAMPLE=0 on an identical
    server/model — the ≤5% acceptance; plus an honest worst-case row at
    full sampling (every request sampled AND shadow-eligible — no
    claim, this box shares one core between serving and the shadow
    worker); (b) a DRIVEN drift→rollback episode: a score-shifted
    candidate is promoted through the canary gate under load, the
    QUALITY gate detects it (PSI over threshold on both windows, the
    SLO objectives deliberately de-tuned so only quality can trip) and
    rolls back via /admin/rollback — detection latency and zero
    non-2xx attested."""
    import urllib.request as ur

    from predictionio_tpu.refresh import RefreshConfig
    from predictionio_tpu.refresh.daemon import HttpPromoter, RefreshDaemon
    from predictionio_tpu.server import EngineServer
    from predictionio_tpu.server import engine_server as es_mod
    from predictionio_tpu.controller import RuntimeContext

    # The episode's verdict must come from the QUALITY gate: de-tune the
    # SLO so the bench's own load shape (closed-loop c=32 on one shared
    # core) can never trip the burn-rate rollback first — same
    # calibration discipline as the --refresh round.
    os.environ["PIO_SLO_AVAILABILITY"] = "0.9"
    os.environ["PIO_SLO_LATENCY_TARGET_MS"] = "10000"

    def _server_and_drive(sample: str, reload_first: bool):
        os.environ["PIO_QUALITY_SAMPLE"] = sample
        srv = EngineServer(eng, variant, storage, host="127.0.0.1",
                           port=0)
        srv.start()
        if reload_first:
            # retain a previous generation → the shadow session arms,
            # so sampled requests are also shadow-score-eligible
            req = ur.Request(f"http://127.0.0.1:{srv.port}/reload",
                             data=b"", method="POST")
            with ur.urlopen(req, timeout=60) as r:
                assert r.status == 200
        _drive(srv.port, n_users, args.clients, args.requests)  # warmup
        res = _drive(srv.port, n_users, args.clients, args.requests)
        return srv, res

    # Phase A — baseline: quality sampling OFF (the rate knob, not the
    # kill switch: the per-request draw + sample check stay in).
    eng, variant, storage, n_users = _setup("twotower")
    ctx = RuntimeContext.create(storage=storage)
    srv, off = _server_and_drive("0", reload_first=False)
    srv.stop()

    # Phase B — shipped defaults + armed shadow: THE ≤5% claim.
    srv, on_default = _server_and_drive("0.1", reload_first=True)
    srv.stop()

    # Phase C — full sampling worst case (recorded, no claim).
    srv, on_full = _server_and_drive("1.0", reload_first=True)
    with ur.urlopen(f"http://127.0.0.1:{srv.port}/quality.json",
                    timeout=10) as r:
        qdoc_overhead = json.loads(r.read())

    def _delta(a, b):
        return (round(100.0 * (b["p99_ms"] - a["p99_ms"]) / a["p99_ms"],
                      2) if a.get("p99_ms") else None)

    p99_delta_pct = _delta(off, on_default)
    p99_delta_full_pct = _delta(off, on_full)

    # Phase D — the driven drift→rollback episode on the full-sampling
    # server: poison the candidate load with a user-side 4× scale
    # (scores shift; ranking and the scorecard's item-corpus
    # fingerprint stay intact, so ONLY the drift detector can catch
    # it).
    real_load = es_mod.load_models

    def shifted(engine_, instance, c=None):
        models = real_load(engine_, instance, c)
        models[0].user_vecs = np.asarray(models[0].user_vecs) * 4.0
        return models

    es_mod.load_models = shifted

    class TimedPromoter(HttpPromoter):
        t_promoted = None
        t_rollback = None
        trip_doc = None

        def promote(self, instance_id):
            out = super().promote(instance_id)
            self.t_promoted = time.perf_counter()
            return out

        def quality_state(self):
            doc = super().quality_state()
            if (doc.get("gate") or {}).get("rollback"):
                # the document that tripped — captured BEFORE the
                # rollback re-anchors the detector on the restored
                # generation
                self.trip_doc = doc
            return doc

        def rollback(self):
            self.t_rollback = time.perf_counter()
            super().rollback()

    promoter = TimedPromoter(f"http://127.0.0.1:{srv.port}",
                             canary_window_s=120.0, canary_poll_s=0.2)
    daemon = RefreshDaemon(
        eng, variant, ctx,
        config=RefreshConfig(interval_s=1.0, eval_tolerance=10.0),
        promoter=promoter)
    gen_before = json.loads(ur.urlopen(
        f"http://127.0.0.1:{srv.port}/", timeout=10).read())
    episode_done = threading.Event()
    cycle = {}

    def run_cycle():
        t0 = time.perf_counter()
        try:
            cycle.update(daemon.run_once())
        finally:
            cycle["wall_s"] = round(time.perf_counter() - t0, 2)
            episode_done.set()

    drive_box = {}
    driver = threading.Thread(
        target=lambda: drive_box.update(_drive_until(
            srv.port, n_users, args.clients, episode_done,
            tight_budgets=False)),
        daemon=True)
    driver.start()
    time.sleep(0.5)            # steady state before the promotion
    run_cycle()
    driver.join(30)
    gen_after = json.loads(ur.urlopen(
        f"http://127.0.0.1:{srv.port}/", timeout=10).read())
    srv.stop()
    es_mod.load_models = real_load

    trip = promoter.trip_doc or {}
    non_2xx = sum(n for s, n in drive_box.get("statuses", {}).items()
                  if not s.startswith("2"))
    record = {
        "mode": "quality",
        "engine": "twotower",
        "clients": args.clients,
        "requests_per_phase": args.requests,
        "slo_detuned_for_episode": {
            "PIO_SLO_AVAILABILITY": 0.9,
            "PIO_SLO_LATENCY_TARGET_MS": 10000,
        },
        "overhead": {
            "quality_off": off,
            "quality_defaults_plus_shadow": on_default,
            "quality_full_sampling_plus_shadow": on_full,
            "p99_delta_pct": p99_delta_pct,
            "p99_delta_within_5pct": (p99_delta_pct is not None
                                      and p99_delta_pct <= 5.0),
            "p99_delta_full_sampling_pct": p99_delta_full_pct,
            "sampled_total_full": qdoc_overhead.get("sampling", {})
            .get("sampledTotal"),
            "shadow_scored_full": qdoc_overhead.get("shadow", {})
            .get("scored"),
        },
        "drift_episode": {
            "injection": "user_vecs x4 at candidate load (scores shift, "
                         "ranking + corpus fingerprint intact)",
            "promotion": cycle.get("promotion"),
            "cycle_wall_s": cycle.get("wall_s"),
            "detect_to_rollback_s": (
                round(promoter.t_rollback - promoter.t_promoted, 2)
                if promoter.t_rollback and promoter.t_promoted else None),
            "generation_before": gen_before.get("modelGeneration"),
            "generation_after": gen_after.get("modelGeneration"),
            "served_instance_restored": (
                gen_after.get("engineInstanceId")
                == gen_before.get("engineInstanceId")),
            "gate_reasons_at_trip": (trip.get("gate") or {})
            .get("reasons"),
            "drift_at_trip": trip.get("drift"),
            "query_during_episode": drive_box,
            "non_2xx_during_episode": non_2xx,
        },
    }
    print(json.dumps(record))
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(record, f, indent=1)
        print(f"wrote {args.out}")


def _recall_round(args) -> None:
    """ISSUE 16 round: (a) the sampled-monitoring overhead record — p99
    at c=N with recall monitoring at its SHIPPED defaults
    (PIO_RECALL_SAMPLE=0.05, shadow exact re-rank off-thread) vs
    PIO_RECALL_SAMPLE=0 on an identical server/model — the ≤5%
    acceptance; plus an honest worst-case row at full sampling (every
    request shadow re-ranked exactly — no claim, one shared core); and
    (b) a DRIVEN recall-rot→rollback episode: a candidate whose IVF
    index silently lost most of its inverted-list mass (corpus
    fingerprint intact → index validation passes; scores of returned
    items barely move → score-drift/shadow checks stay quiet; the de-
    tuning below makes that calibration explicit) is promoted through
    the canary gate under load, the RECALL detector trips on both
    windows against the generation's own baked scorecard, and the
    existing gate path rolls it back via /admin/rollback — detection
    latency and zero non-2xx attested."""
    import urllib.request as ur

    from predictionio_tpu.refresh import RefreshConfig
    from predictionio_tpu.refresh.daemon import HttpPromoter, RefreshDaemon
    from predictionio_tpu.server import EngineServer
    from predictionio_tpu.server import engine_server as es_mod
    from predictionio_tpu.controller import RuntimeContext

    # The bench corpus (4000 items) sits below the production IVF
    # threshold: force the approximate rung so there IS a recall surface
    # to monitor — same tiny-corpus escape hatch the tests use.
    os.environ["PIO_IVF"] = "on"
    os.environ["PIO_IVF_MIN_ITEMS"] = "1000"
    os.environ["PIO_RETRIEVAL_RUNG"] = "ivf"
    # The episode's verdict must come from the RECALL gate: de-tune the
    # SLO burn-rate and the PR-11 drift/shadow thresholds so the bench's
    # own load shape (closed-loop on one shared core) and the candidate
    # swap's benign score movement can never trip another gate first —
    # same calibration discipline as the --quality round.
    os.environ["PIO_SLO_AVAILABILITY"] = "0.9"
    os.environ["PIO_SLO_LATENCY_TARGET_MS"] = "10000"
    os.environ["PIO_QUALITY_PSI_THRESHOLD"] = "100"
    os.environ["PIO_SHADOW_MIN_OVERLAP"] = "0"

    def _mk_server(sample: str):
        os.environ["PIO_RECALL_SAMPLE"] = sample
        srv = EngineServer(eng, variant, storage, host="127.0.0.1",
                           port=0)
        srv.start()
        _drive(srv.port, n_users, args.clients, args.requests)  # warmup
        return srv

    def _median_rounds(srv, rounds):
        rounds.sort(key=lambda r: r.get("p99_ms") or 0.0)
        res = dict(rounds[len(rounds) // 2])
        res["p99_ms_rounds"] = sorted(r.get("p99_ms") for r in rounds)
        return res

    eng, variant, storage, n_users = _setup("twotower")
    ctx = RuntimeContext.create(storage=storage)

    # Phases A/B — sampling OFF (the rate knob, not the kill switch:
    # the shared draw + sample check stay in the path) vs the shipped
    # default: THE ≤5% claim.  The closed-loop p99 on this one shared
    # core is queueing delay whose run-to-run jitter drifts MONOTONICALLY
    # over a bench's lifetime (>5% between identical back-to-back
    # drives), so the two configs run on two live servers with their
    # measured drives INTERLEAVED — the drift lands on both sides — and
    # the claim compares median-of-4.
    srv_off = _mk_server("0")
    srv_def = _mk_server("0.05")
    # A 2000-request round's p99 is its 20th-worst sample — scheduling
    # noise; the claim rounds use ≥6000 so the tail statistic itself
    # stabilizes before the pairing cancels drift.
    n_meas = max(args.requests, 6000)
    rounds_off, rounds_def = [], []
    for _ in range(5):
        rounds_off.append(_drive(srv_off.port, n_users, args.clients,
                                 n_meas))
        rounds_def.append(_drive(srv_def.port, n_users, args.clients,
                                 n_meas))
    # The claim estimator is the median PAIRED difference: interleaved
    # round i of the two servers ran back-to-back, so subtracting
    # within the pair cancels the drift that dominates any
    # median-vs-median comparison on this box (a full-sampling phase
    # routinely measures FASTER than sampling-off by medians alone).
    paired = sorted(
        (b.get("p99_ms") or 0.0) - (a.get("p99_ms") or 0.0)
        for a, b in zip(rounds_off, rounds_def))
    paired_delta_ms = paired[len(paired) // 2]
    off = _median_rounds(srv_off, rounds_off)
    on_default = _median_rounds(srv_def, rounds_def)
    srv_off.stop()
    srv_def.stop()

    # Phase C — full sampling worst case (recorded, no claim), and the
    # server the episode runs on: every request feeds the detector, so
    # the trip lands within the canary window instead of a bench-length
    # wait for 0.05-sampled mass.
    srv = _mk_server("1.0")
    on_full = _drive(srv.port, n_users, args.clients, args.requests)
    with ur.urlopen(f"http://127.0.0.1:{srv.port}/quality.json",
                    timeout=10) as r:
        qdoc_overhead = json.loads(r.read())

    def _delta(a, b):
        return (round(100.0 * (b["p99_ms"] - a["p99_ms"]) / a["p99_ms"],
                      2) if a.get("p99_ms") else None)

    p99_delta_pct = (round(100.0 * paired_delta_ms / off["p99_ms"], 2)
                     if off.get("p99_ms") else None)
    p99_delta_full_pct = _delta(off, on_full)
    healthy_row = ((qdoc_overhead.get("recall") or {})
                   .get("rungs") or {}).get("ivf") or {}

    # Phase D — the driven recall-rot episode: the candidate's wrapper
    # unpickles with its healthy baked scorecard, then its IVF index is
    # swapped for one that kept only the head of every inverted list —
    # the fingerprint still names the real corpus, so the facade's
    # index validation passes and only the sampled exact re-rank can
    # see the lost neighbors.
    real_load = es_mod.load_models

    def rotten(engine_, instance, c=None):
        models = real_load(engine_, instance, c)
        import dataclasses as dc

        idx = models[0].ivf
        keep = np.maximum(1, idx.list_lengths // 4).astype(np.int32)
        lists = idx.lists.copy()
        for ci in range(idx.nlist):
            lists[ci, keep[ci]:] = -1
        models[0].ivf = dc.replace(idx, lists=lists, list_lengths=keep)
        return models

    es_mod.load_models = rotten

    class TimedPromoter(HttpPromoter):
        t_promoted = None
        t_rollback = None
        trip_doc = None

        def promote(self, instance_id):
            out = super().promote(instance_id)
            self.t_promoted = time.perf_counter()
            return out

        def quality_state(self):
            doc = super().quality_state()
            if (doc.get("gate") or {}).get("rollback"):
                self.trip_doc = doc
            return doc

        def rollback(self):
            self.t_rollback = time.perf_counter()
            super().rollback()

    promoter = TimedPromoter(f"http://127.0.0.1:{srv.port}",
                             canary_window_s=120.0, canary_poll_s=0.2)
    daemon = RefreshDaemon(
        eng, variant, ctx,
        config=RefreshConfig(interval_s=1.0, eval_tolerance=10.0),
        promoter=promoter)
    gen_before = json.loads(ur.urlopen(
        f"http://127.0.0.1:{srv.port}/", timeout=10).read())
    episode_done = threading.Event()
    cycle = {}

    def run_cycle():
        t0 = time.perf_counter()
        try:
            cycle.update(daemon.run_once())
        finally:
            cycle["wall_s"] = round(time.perf_counter() - t0, 2)
            episode_done.set()

    drive_box = {}
    driver = threading.Thread(
        target=lambda: drive_box.update(_drive_until(
            srv.port, n_users, args.clients, episode_done,
            tight_budgets=False)),
        daemon=True)
    driver.start()
    time.sleep(0.5)            # steady state before the promotion
    run_cycle()
    driver.join(30)
    gen_after = json.loads(ur.urlopen(
        f"http://127.0.0.1:{srv.port}/", timeout=10).read())
    srv.stop()
    es_mod.load_models = real_load

    trip = promoter.trip_doc or {}
    trip_recall = ((trip.get("recall") or {}).get("rungs") or {}) \
        .get("ivf") or {}
    non_2xx = sum(n for s, n in drive_box.get("statuses", {}).items()
                  if not s.startswith("2"))
    record = {
        "mode": "recall",
        "engine": "twotower",
        "clients": args.clients,
        "requests_per_phase": args.requests,
        "gates_detuned_for_episode": {
            "PIO_SLO_AVAILABILITY": 0.9,
            "PIO_SLO_LATENCY_TARGET_MS": 10000,
            "PIO_QUALITY_PSI_THRESHOLD": 100,
            "PIO_SHADOW_MIN_OVERLAP": 0,
        },
        "overhead": {
            "recall_off": off,
            "recall_shipped_default": on_default,
            "recall_full_sampling": on_full,
            "p99_delta_pct": p99_delta_pct,
            "p99_delta_within_5pct": (p99_delta_pct is not None
                                      and p99_delta_pct <= 5.0),
            "p99_paired_delta_ms_rounds": [round(x, 2) for x in paired],
            "p99_delta_full_sampling_pct": p99_delta_full_pct,
        },
        "healthy_online_recall_ivf": healthy_row,
        "recall_rot_episode": {
            "injection": "candidate IVF inverted lists truncated to "
                         "their head quarter at load (corpus "
                         "fingerprint intact → validation passes; "
                         "scorecard baked healthy at train)",
            "promotion": cycle.get("promotion"),
            "cycle_wall_s": cycle.get("wall_s"),
            "detect_to_rollback_s": (
                round(promoter.t_rollback - promoter.t_promoted, 2)
                if promoter.t_rollback and promoter.t_promoted else None),
            "generation_before": gen_before.get("modelGeneration"),
            "generation_after": gen_after.get("modelGeneration"),
            "served_instance_restored": (
                gen_after.get("engineInstanceId")
                == gen_before.get("engineInstanceId")),
            "gate_reasons_at_trip": (trip.get("gate") or {})
            .get("reasons"),
            "recall_at_trip": {
                "baseline": trip_recall.get("baseline"),
                "recall_fast": trip_recall.get("recallFast"),
                "recall_slow": trip_recall.get("recallSlow"),
                "n_fast": trip_recall.get("nFast"),
                "n_slow": trip_recall.get("nSlow"),
                "tripped_both_windows": bool(trip_recall.get("tripped")),
            },
            "query_during_episode": drive_box,
            "non_2xx_during_episode": non_2xx,
        },
    }
    print(json.dumps(record))
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(record, f, indent=1)
        print(f"wrote {args.out}")


def _fleet_rollout_round(args) -> None:
    """ISSUE 15 round: 3 live engine instances behind a wave rollout,
    with a BAD candidate generation injected at model load — wave 1
    promotes the canary, its availability burn trips the fleet gate,
    the controller halts and rolls the canary back.  Measured claims:
    (a) detection→fleet-restored wall (bad generation serving → every
    instance verified back on the pre-promotion generation), and (b)
    zero non-2xx on the NOT-yet-promoted instances for the whole
    episode, attested client-side per instance.

    Single-process caveat (same shape as the PR-9 fleet e2e and the
    PR-11 quality bench): the three servers share one metrics registry,
    so the burn the gate reads is process-global — the per-instance
    isolation claim rests on the CLIENT-side per-instance status
    counts, which are independent by construction."""
    import urllib.request as ur

    from predictionio_tpu.fleet import RolloutConfig, RolloutController
    from predictionio_tpu.server import EngineServer
    from predictionio_tpu.server import engine_server as es_mod
    from predictionio_tpu.controller import RuntimeContext
    from predictionio_tpu.workflow.core_workflow import run_train

    eng, variant, storage, n_users = _setup("als")
    ctx = RuntimeContext.create(storage=storage)
    servers = [EngineServer(eng, variant, storage, host="127.0.0.1",
                            port=0) for _ in range(3)]
    for s in servers:
        s.start()
    urls = [f"http://127.0.0.1:{s.port}" for s in servers]
    gen_before = {u: json.loads(ur.urlopen(u + "/", timeout=10).read())
                  ["engineInstanceId"] for u in urls}

    # The bad candidate: a real COMPLETED train whose LOAD is poisoned —
    # validation passes (no non-finite arrays to reject), every predict
    # 500s.  Only the canary instance ever loads it.
    bad_iid = run_train(eng, variant, ctx)
    real_load = es_mod.load_models

    class _Poisoned:
        """No arrays (finite-validation passes), no serving surface."""

    def poisoned(engine_, instance, c=None):
        if instance.id == bad_iid:
            return [_Poisoned()]
        return real_load(engine_, instance, c)

    es_mod.load_models = poisoned

    # Per-instance closed-loop drivers: statuses counted independently
    # per instance — THE isolation attestation.
    stop = threading.Event()
    per_instance = {u: {} for u in urls}

    def drive(url):
        rng = np.random.default_rng(hash(url) % 2**32)
        counts = per_instance[url]
        while not stop.is_set():
            body = json.dumps({"user": f"u{rng.integers(0, n_users)}",
                               "num": 5}).encode()
            req = ur.Request(url + "/queries.json", data=body,
                             headers={"Content-Type":
                                      "application/json"})
            try:
                with ur.urlopen(req, timeout=30) as resp:
                    st = resp.status
            except urllib.error.HTTPError as e:
                st = e.code
            except OSError:
                st = -1
            counts[st] = counts.get(st, 0) + 1

    drivers = [threading.Thread(target=drive, args=(u,), daemon=True)
               for u in urls for _ in range(2)]
    for t in drivers:
        t.start()
    time.sleep(1.0)  # steady state before the wave

    marks = {}

    class Timed(RolloutController):
        def _promote_instance(self, url, target):
            out = super()._promote_instance(url, target)
            if out[0] == "ok" and "promoted" not in marks:
                marks["promoted"] = time.perf_counter()
                marks["canary"] = url
            return out

        def fleet_tripped(self):
            tripped, reason = super().fleet_tripped()
            if tripped and "tripped" not in marks:
                marks["tripped"] = time.perf_counter()
            return tripped, reason

        def _rollback_instance(self, url):
            out = super()._rollback_instance(url)
            if out[0] == "ok":
                marks["rolled_back"] = time.perf_counter()
            return out

    cfg = RolloutConfig(
        waves="1,100%", bake_s=60.0, poll_s=0.25,
        state_path=os.path.join(os.environ["PIO_HOME"], "rollout.json"))
    ctl = Timed(urls, cfg)
    state = ctl.run(bad_iid)
    # fleet-restored: every instance verified back on its pre-promotion
    # generation (the canary's rollback swap already landed; this is the
    # read-back proof, part of the measured restore wall)
    for u in urls:
        assert ctl.served_instance(u) == gen_before[u], u
    marks["restored"] = time.perf_counter()
    time.sleep(0.5)  # post-restore drive tail on the restored fleet
    stop.set()
    for t in drivers:
        t.join(10)
    es_mod.load_models = real_load
    for s in servers:
        s.stop()

    canary = marks.get("canary")
    others = [u for u in urls if u != canary]
    non2xx_not_promoted = {
        u: sum(n for st, n in per_instance[u].items()
               if not (200 <= st < 300)) for u in others}
    record = {
        "mode": "fleet-rollout",
        "engine": "als",
        "instances": len(urls),
        "waves": cfg.waves,
        "gate_poll_s": cfg.poll_s,
        "injection": "candidate load poisoned on the canary only: "
                     "validation-clean model object with no serving "
                     "surface — every predict 500s",
        "rollout_status": state["status"],
        "halt_reason": state.get("haltReason"),
        "promoted_before_halt": state.get("promoted"),
        "rolled_back": state.get("rolledBack"),
        "detect_s_promote_to_gate_trip": (
            round(marks["tripped"] - marks["promoted"], 3)
            if "tripped" in marks and "promoted" in marks else None),
        "detect_to_fleet_restored_s": (
            round(marks["restored"] - marks["promoted"], 3)
            if "restored" in marks and "promoted" in marks else None),
        "per_instance_statuses": {
            u: {str(k): v for k, v in sorted(c.items())}
            for u, c in per_instance.items()},
        "canary_instance": canary,
        "non_2xx_on_not_yet_promoted_instances": non2xx_not_promoted,
        "zero_non_2xx_attested": all(v == 0 for v in
                                     non2xx_not_promoted.values()),
        "caveat": "single-process bench: one shared metrics registry "
                  "behind all three servers, so the SLO burn the gate "
                  "scrapes is process-global; per-instance isolation "
                  "is attested by the independent client-side status "
                  "counts above",
    }
    print(json.dumps(record))
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(record, f, indent=1)
        print(f"wrote {args.out}")


def _ingest_round(args) -> None:
    """ISSUE 17 round: the crash-safe bulk ingest plane.

    Three claims, each measured live:

    1. Throughput — batched ``POST /batch/events.json`` vs the
       row-at-a-time loop, on sqlite AND memory event backends (the
       ≥100k ev/s acceptance bar rides the batched number).
    2. Warm-refresh delta read — the windowed read the refresh loop
       issues every cycle, timed over a FIXED-size delta at 1x store
       size and again after growing the store 10x: with sealed columnar
       segments serving the covered prefix the wall must stay flat.
    3. With ``--faults``: the crash attestations — a REAL ``kill -9``
       mid-batch with token replay (zero lost / zero duplicated), a
       killed segment writer's torn tail recovered on reopen with every
       sealed claim still readable, a partially-landed batch re-landed
       exactly-once through spill replay, disk-full degrading coverage
       but never ingest, and a saturated plane answering 429 +
       Retry-After.
    """
    import datetime as dt
    import signal
    import subprocess
    import sys

    from predictionio_tpu.data.storage import (
        AccessKey,
        App,
        StorageUnavailable,
        get_storage,
        reset_storage,
    )
    from predictionio_tpu.server import EventServer

    UTC = dt.timezone.utc
    BATCH = 1000
    os.environ["PIO_MAX_BATCH_SIZE"] = str(BATCH)
    # grace 0: a seal claims right up to "now", so the delta-read
    # windows below are fully covered the moment they are sealed
    os.environ["PIO_SEGMENT_GRACE_S"] = "0"
    os.environ.setdefault(
        "PYTHONPATH", os.path.dirname(os.path.abspath(__file__)))

    def _mk_stack(backend, **server_kw):
        home = tempfile.mkdtemp(prefix=f"pio_ing_{backend}_")
        os.environ["PIO_HOME"] = home
        if backend == "memory":
            os.environ["PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE"] = \
                "MEMORY"
        else:
            os.environ.pop(
                "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE", None)
        reset_storage()
        storage = get_storage()
        app_id = storage.get_apps().insert(App(id=None, name="ing"))
        storage.get_events().init(app_id)
        key = storage.get_access_keys().insert(
            AccessKey(key="", app_id=app_id))
        srv = EventServer(storage=storage, host="127.0.0.1", port=0,
                          **server_kw)
        return home, storage, app_id, key, srv

    def _batch_body(n, tag, start=0):
        return json.dumps([
            {"event": "view", "entityType": "user",
             "entityId": f"{tag}u{start + i}",
             "targetEntityType": "item",
             "targetEntityId": f"i{(start + i) % 997}"}
            for i in range(n)]).encode()

    def _post_batches(srv, key, total, tag, start=0):
        params = {"accessKey": [key]}
        t0 = time.perf_counter()
        for off in range(0, total, BATCH):
            status, results = srv.handle(
                "POST", "/batch/events.json", params,
                _batch_body(min(BATCH, total - off), tag, start + off))
            assert status == 200, results
        return time.perf_counter() - t0

    record = {"mode": "ingest", "batch_size": BATCH, "throughput": {}}

    # -- 1. throughput: batched vs row-at-a-time, per backend ---------------
    # The >=100k ev/s acceptance bar is the STORAGE-layer batched commit
    # rate (one create_batch round trip per 1000 events) — that is the
    # group-commit path every producer above it shares.  The server fold
    # (JSON parse + validation + segment tee) and a real-HTTP sample are
    # recorded alongside as the end-to-end context.
    from predictionio_tpu.data.event import DataMap
    from predictionio_tpu.data.event import Event as _BEvent

    for backend in ("sqlite", "memory"):
        n_store, n_srv_batched, n_rows = 60_000, 20_000, 2_000
        n_warm = 6 * BATCH  # untimed: page-cache + allocator first-touch
        t_base = dt.datetime.now(UTC)
        store_evs = [
            _BEvent(event="view", entity_type="user",
                    entity_id=f"stu{i % 4096}",
                    target_entity_type="item",
                    target_entity_id=f"i{i % 997}",
                    properties=DataMap({"rating": float(i % 5)}),
                    event_time=t_base + dt.timedelta(microseconds=i))
            for i in range(n_warm + n_store)]
        # 3 sustained 60k-event trials, each on a FRESH store (the bar is
        # the plane's sustained group-commit rate, not B-tree scaling of
        # a multi-hundred-k-row table); median + max reported so one
        # noisy-neighbor stall doesn't misstate it.
        sb_rates = []
        for _ in range(3):
            _, storage_t, app_id_t, _, srv_t = _mk_stack(backend)
            repo_t = storage_t.get_events()
            for off in range(0, n_warm, BATCH):
                repo_t.create_batch(store_evs[off:off + BATCH], app_id_t)
            t0 = time.perf_counter()
            for off in range(n_warm, n_warm + n_store, BATCH):
                repo_t.create_batch(store_evs[off:off + BATCH], app_id_t)
            sb_rates.append(n_store / (time.perf_counter() - t0))
            srv_t.stop()
        t0 = time.perf_counter()
        for ev in store_evs[:n_rows]:
            repo_t.insert(ev, app_id_t)
        wall_sr = time.perf_counter() - t0
        home, storage, app_id, key, srv = _mk_stack(backend)
        wall_b = _post_batches(srv, key, n_srv_batched, "b")
        params = {"accessKey": [key]}
        t0 = time.perf_counter()
        for i in range(n_rows):
            status, _ = srv.handle(
                "POST", "/events.json", params,
                json.dumps({"event": "view", "entityType": "user",
                            "entityId": f"r{i}", "targetEntityType": "item",
                            "targetEntityId": f"i{i % 997}"}).encode())
            assert status == 201
        wall_r = time.perf_counter() - t0
        # an honest wire sample: real HTTP, single closed-loop client
        srv.start()
        t0 = time.perf_counter()
        for off in range(0, 10_000, BATCH):
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/batch/events.json?"
                f"accessKey={key}", data=_batch_body(BATCH, "h", off),
                method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as resp:
                assert resp.status == 200
        wall_h = time.perf_counter() - t0
        srv.stop()
        sbps, srps = float(np.median(sb_rates)), n_rows / wall_sr
        bps, rps = n_srv_batched / wall_b, n_rows / wall_r
        record["throughput"][backend] = {
            "storage_batched_events_per_s": round(sbps, 1),
            "storage_batched_events_per_s_max": round(
                float(np.max(sb_rates)), 1),
            "storage_row_at_a_time_events_per_s": round(srps, 1),
            "storage_batched_speedup": round(sbps / srps, 1),
            "storage_meets_100k": sbps >= 100_000,
            "server_batched_events_per_s": round(bps, 1),
            "server_row_at_a_time_events_per_s": round(rps, 1),
            "http_batched_events_per_s": round(10_000 / wall_h, 1),
        }
        print(json.dumps({"round": "throughput", "backend": backend,
                          **record["throughput"][backend]}))
        if backend == "memory":
            reset_storage()

    # -- 2. warm-refresh delta read: flat across 10x store growth ----------
    # sqlite stack again, segments on (the default): the windowed read
    # serves the delta from sealed segment slices.
    from predictionio_tpu.data.store import WindowedEventStore

    home, storage, app_id, key, srv = _mk_stack("sqlite")
    delta_rows, base_rows = 1_000, 40_000

    def _timed_delta_read(tag, grown_by):
        _post_batches(srv, key, grown_by, tag)
        mark0 = dt.datetime.now(UTC)
        time.sleep(0.002)
        _post_batches(srv, key, delta_rows, tag + "d")
        time.sleep(0.002)
        mark1 = dt.datetime.now(UTC)
        assert srv.segments is not None
        srv.segments.seal_all()
        walls = []
        for _ in range(5):
            t0 = time.perf_counter()
            tbl = WindowedEventStore(storage, mark0, mark1) \
                .find_columnar("ing")
            walls.append(time.perf_counter() - t0)
            assert tbl.num_rows == delta_rows, tbl.num_rows
        return float(np.median(walls)) * 1e3, mark0, mark1

    ms_1x, _, _ = _timed_delta_read("g1", base_rows)
    ms_10x, mark0_10x, mark1_10x = _timed_delta_read("g2", 9 * base_rows)
    # contrast: the SAME 10x delta window with segments disabled — the
    # primary store materializes per-row Events for the scan.
    os.environ["PIO_SEGMENTS"] = "off"
    walls = []
    for _ in range(3):
        t0 = time.perf_counter()
        tbl = WindowedEventStore(storage, mark0_10x, mark1_10x) \
            .find_columnar("ing")
        walls.append(time.perf_counter() - t0)
        assert tbl.num_rows == delta_rows, tbl.num_rows
    primary_ms = float(np.median(walls)) * 1e3
    os.environ.pop("PIO_SEGMENTS")
    record["delta_read"] = {
        "delta_rows": delta_rows,
        "store_rows_1x": base_rows + delta_rows,
        "store_rows_10x": 10 * base_rows + 2 * delta_rows,
        "segment_read_ms_1x": round(ms_1x, 2),
        "segment_read_ms_10x": round(ms_10x, 2),
        "growth_ratio": round(ms_10x / ms_1x, 2),
        "primary_read_ms_10x": round(primary_ms, 2),
    }
    print(json.dumps({"round": "delta_read", **record["delta_read"]}))
    srv.stop()

    # -- 3. fault round ------------------------------------------------------
    if args.faults:
        from predictionio_tpu.data.columnar import SegmentStore
        from predictionio_tpu.resilience import faults as faults_mod

        att = {}
        # (a) REAL kill -9 mid-batch, then deterministic token replay:
        # the batch ids ARE the dedup keys, so re-issuing every batch
        # after the crash lands exactly the missing rows.
        home, storage, app_id, key, srv = _mk_stack("kill9")
        n_batches, per = 2_000, 20
        child_src = (
            "import os\n"
            "from predictionio_tpu.data.storage import get_storage\n"
            "from predictionio_tpu.data.event import Event\n"
            "ev = get_storage().get_events()\n"
            f"app_id = {app_id}\n"
            f"for b in range({n_batches}):\n"
            "    evs = [Event(event='view', entity_type='user',\n"
            "                 entity_id=f'ku{b}_{j}',\n"
            "                 target_entity_type='item',\n"
            "                 target_entity_id=f'ki{j}')\n"
            f"           for j in range({per})]\n"
            f"    toks = [f'kill{{b}}.{{j}}' for j in range({per})]\n"
            "    ev.create_batch(evs, app_id, tokens=toks)\n"
            "    print(b, flush=True)\n")
        child = subprocess.Popen(
            [sys.executable, "-c", child_src],
            env={**os.environ, "PIO_HOME": home},
            stdout=subprocess.PIPE, text=True)
        committed_seen = 0
        for line in child.stdout:
            committed_seen = int(line)
            if committed_seen >= 25:  # provably mid-stream
                break
        if child.poll() is None:
            os.kill(child.pid, signal.SIGKILL)
        child.wait()
        reset_storage()
        os.environ["PIO_HOME"] = home
        storage = get_storage()
        ev_repo = storage.get_events()
        from predictionio_tpu.data.event import Event as _Event

        landed_before = sum(
            1 for e in ev_repo.find(app_id)
            if e.entity_id.startswith("ku"))
        for b in range(n_batches):  # full replay, crashed batch included
            evs = [_Event(event="view", entity_type="user",
                          entity_id=f"ku{b}_{j}",
                          target_entity_type="item",
                          target_entity_id=f"ki{j}")
                   for j in range(per)]
            ev_repo.create_batch(
                evs, app_id, tokens=[f"kill{b}.{j}" for j in range(per)])
        rows = [e for e in ev_repo.find(app_id)
                if e.entity_id.startswith("ku")]
        ids = {e.entity_id for e in rows}
        att["kill9_mid_batch"] = {
            "batches_killed_after": committed_seen,
            "rows_landed_before_kill": landed_before,
            "rows_expected": n_batches * per,
            "rows_after_replay": len(rows),
            "lost": n_batches * per - len(ids),
            "duplicated": len(rows) - len(ids),
        }
        assert att["kill9_mid_batch"]["lost"] == 0
        assert att["kill9_mid_batch"]["duplicated"] == 0
        srv.stop()

        # (b) kill -9 a live segment writer: reopen must sweep the torn
        # active tail and keep EVERY sealed claim fully readable.
        seg_root = tempfile.mkdtemp(prefix="pio_ing_seg_")
        child_src = (
            "import time\n"
            "from predictionio_tpu.data.columnar import SegmentStore\n"
            "from predictionio_tpu.data.event import Event\n"
            f"st = SegmentStore({seg_root!r}, roll_bytes=1 << 20,\n"
            "                  roll_s=0.05, grace_s=0.0)\n"
            "b = 0\n"
            "while True:\n"
            "    st.append_events(1, None, [\n"
            "        Event(event='view', entity_type='user',\n"
            "              entity_id=f'su{b}_{j}',\n"
            "              target_entity_type='item',\n"
            "              target_entity_id=f'si{j}')\n"
            "        for j in range(50)])\n"
            "    b += 1\n"
            "    print(b, flush=True)\n"
            "    time.sleep(0.005)\n")
        child = subprocess.Popen(
            [sys.executable, "-c", child_src], env=dict(os.environ),
            stdout=subprocess.PIPE, text=True)
        for line in child.stdout:
            if int(line) >= 40:  # several sealed windows exist
                break
        if child.poll() is None:
            os.kill(child.pid, signal.SIGKILL)
        child.wait()
        st = SegmentStore(seg_root)
        st._dir(1, None)  # reopen = recovery: torn tail + orphan sweep
        status = st.status()
        # Every sealed file must be CRC-clean and hold exactly the rows
        # its manifest entry claims; the window read must then return
        # every row inside coverage.  (Rows the writer stamped BEFORE the
        # first window opened sit below floorUs — claimed in the file,
        # excluded from coverage by design, primary store authoritative.)
        from pathlib import Path as _P

        from predictionio_tpu.data.columnar import (
            _payloads_to_table,
            recover_segment_tail,
        )
        seg_dir = _P(seg_root) / "app_1" / "default"
        man = json.loads((seg_dir / "manifest.json").read_text())
        file_rows = below_floor = 0
        for s in man["segments"]:
            info = recover_segment_tail(seg_dir / s["file"], truncate=False)
            assert info["rows"] == s["rows"], (s["file"], info["rows"])
            assert info["torn_bytes"] == 0, s["file"]
            file_rows += info["rows"]
            tbl = _payloads_to_table(info["payloads"])
            below_floor += sum(
                1 for v in tbl.column("event_time_us").to_pylist()
                if v < man["floorUs"])
        # claims end at coveredUntilUs — asking past coverage is a miss
        got = st.read_window(
            1, None, status[0]["floorUs"],
            status[0]["coveredUntilUs"]) if status else None
        att["segment_writer_kill9"] = {
            "sealed_segments_after_recovery": (
                status[0]["segments"] if status else 0),
            "sealed_rows_claimed": status[0]["rows"] if status else 0,
            "sealed_rows_crc_verified": file_rows,
            "rows_below_coverage_floor": below_floor,
            "sealed_rows_read": got[0].num_rows if got else 0,
            "all_sealed_claims_readable": bool(
                status and got and file_rows == status[0]["rows"]
                and got[0].num_rows == file_rows - below_floor),
        }
        assert att["segment_writer_kill9"]["all_sealed_claims_readable"]
        st.close()

        # (c) storage crash AFTER half a batch committed (lost reply):
        # spill carries the sub-tokens; replay lands exactly the missing
        # rows.
        home, storage, app_id, key, srv = _mk_stack(
            "spill", replay_interval_s=3600.0)
        ev_repo = storage.get_events()
        real_cb = type(ev_repo).create_batch
        state = {"calls": 0}

        def flaky(self, evs, app_id_, channel_id=None, tokens=None):
            state["calls"] += 1
            if state["calls"] == 1:
                real_cb(self, evs[: len(evs) // 2], app_id_, channel_id,
                        tokens=list(tokens)[: len(evs) // 2]
                        if tokens else None)
                raise StorageUnavailable("crashed mid-batch")
            return real_cb(self, evs, app_id_, channel_id, tokens=tokens)

        import unittest.mock as mock

        with mock.patch.object(type(ev_repo), "create_batch", flaky):
            status, results = srv.handle(
                "POST", "/batch/events.json",
                {"accessKey": [key], "batchToken": ["attest"]},
                _batch_body(100, "sp"))
            spilled = sum(1 for r in results if r["status"] == 202)
            before = sum(1 for e in ev_repo.find(app_id)
                         if e.entity_id.startswith("sp"))
            drained = srv._replay.drain_once()
        rows = [e for e in ev_repo.find(app_id)
                if e.entity_id.startswith("sp")]
        att["spill_replay_partial_batch"] = {
            "accepted_202": spilled,
            "rows_landed_before_replay": before,
            "replayed": drained,
            "rows_after_replay": len(rows),
            "duplicated": len(rows) - len({e.entity_id for e in rows}),
        }
        assert att["spill_replay_partial_batch"]["rows_after_replay"] == 100
        assert att["spill_replay_partial_batch"]["duplicated"] == 0
        srv.stop()

        # (d) disk-full: coverage stops, ingest does not.
        os.environ["PIO_DISK_MIN_FREE_BYTES"] = str(1 << 60)
        home, storage, app_id, key, srv = _mk_stack("disk")
        status, _ = srv.handle(
            "POST", "/events.json", {"accessKey": [key]},
            json.dumps({"event": "view", "entityType": "user",
                        "entityId": "dx", "targetEntityType": "item",
                        "targetEntityId": "dy"}).encode())
        rstatus, ready = srv.handle("GET", "/ready", {}, b"")
        att["disk_full"] = {
            "ingest_status": status,
            "ready_status": rstatus,
            "ready_state": ready.get("status"),
            "disk_degraded": ready.get("diskDegraded"),
        }
        assert status == 201 and ready.get("diskDegraded") is True
        srv.stop()
        os.environ.pop("PIO_DISK_MIN_FREE_BYTES")

        # (e) saturated plane: oversized batch refused at admission with
        # Retry-After; an in-budget batch still lands.
        os.environ["PIO_INGEST_QUEUE_BUDGET"] = "2"
        home, storage, app_id, key, srv = _mk_stack("sat")
        srv.start()
        base = f"http://127.0.0.1:{srv.port}"
        req = urllib.request.Request(
            f"{base}/batch/events.json?accessKey={key}",
            data=_batch_body(50, "ov"), method="POST",
            headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=30)
            sat_status, retry_after = 200, None
        except urllib.error.HTTPError as e:
            sat_status = e.code
            retry_after = e.headers.get("Retry-After")
        req = urllib.request.Request(
            f"{base}/batch/events.json?accessKey={key}",
            data=_batch_body(1, "ok"), method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            small_status = resp.status
        att["saturation"] = {
            "oversized_batch_status": sat_status,
            "retry_after_s": (float(retry_after)
                              if retry_after is not None else None),
            "in_budget_batch_status": small_status,
        }
        assert sat_status == 429 and retry_after is not None
        srv.stop()
        os.environ.pop("PIO_INGEST_QUEUE_BUDGET")
        faults_mod.clear()

        record["faults"] = att
        print(json.dumps({"round": "faults", **att}))

    print(json.dumps(record))
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(record, f, indent=1)
        print(f"wrote {args.out}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=16)
    ap.add_argument("--requests", type=int, default=2000)
    ap.add_argument("--faults", default=None, metavar="SPEC",
                    nargs="?", const="attest",
                    help="fault-injection plan (PIO_FAULTS grammar, e.g. "
                         "'http.engine:delay:5ms:0.01') to measure tail "
                         "latency under injected partial failure; with "
                         "--ingest a bare --faults runs the crash "
                         "attestation round (kill -9 / torn tail / "
                         "disk-full / saturation)")
    ap.add_argument("--concurrency", default=None, metavar="LEVELS",
                    help="comma-separated concurrency levels — sweep the "
                         "serving scheduler on one server (e.g. "
                         "'1,8,32,64') and record coalescing + p50/p99 "
                         "per level vs the unbatched baseline")
    ap.add_argument("--engine", default="als",
                    choices=("als", "twotower"),
                    help="engine for the sweep (twotower = deep model)")
    ap.add_argument("--corpus-scale", default=None, metavar="SCALES",
                    help="comma-separated item counts (e.g. '1e5,1e6') — "
                         "drive exact vs sharded vs IVF retrieval over a "
                         "synthetic clustered corpus at each scale "
                         "through the scheduler path (ISSUE 8)")
    ap.add_argument("--refresh", action="store_true",
                    help="ISSUE 10 round: ingest a delta on a live event "
                         "server, run one follow-mode warm refresh "
                         "promoted through the staged-reload gate, and "
                         "record event→servable staleness percentiles, "
                         "warm vs cold retrain wall, and query p99 "
                         "across the promotion (late 200s attested = 0)")
    ap.add_argument("--delta-events", dest="delta_events", type=int,
                    default=5000,
                    help="delta events ingested before the warm refresh "
                         "(refresh mode; default 5000 = 5%% of corpus)")
    ap.add_argument("--quality", action="store_true",
                    help="ISSUE 11 round: p99 overhead of full quality "
                         "sampling + an armed shadow session vs "
                         "PIO_QUALITY_SAMPLE=0 (≤5%% attested), then a "
                         "driven drift→rollback episode (score-shifted "
                         "candidate promoted under load, detected by "
                         "the PSI gate, rolled back with zero non-2xx)")
    ap.add_argument("--recall", action="store_true",
                    help="ISSUE 16 round: sampled recall-monitoring "
                         "overhead (shipped defaults vs sampling off, "
                         "the ≤5%% p99 acceptance) + a driven "
                         "recall-rot episode (truncated-list IVF "
                         "candidate promoted under load, the recall "
                         "gate trips on both windows and rolls back "
                         "with zero non-2xx)")
    ap.add_argument("--fleet-rollout", dest="fleet_rollout",
                    action="store_true",
                    help="ISSUE 15 round: 3 live instances, a wave "
                         "rollout promotes an injected bad generation "
                         "to the canary, the fleet gate halts and "
                         "restores everyone — detection-to-restored "
                         "wall + zero non-2xx attested on the "
                         "not-yet-promoted instances")
    ap.add_argument("--ingest", action="store_true",
                    help="ISSUE 17 round: bulk-ingest throughput (batched "
                         "vs row-at-a-time, sqlite + memory backends), "
                         "warm-refresh delta read flatness across 10x "
                         "store growth via columnar segments, and with "
                         "--faults the crash attestations (kill -9 "
                         "mid-batch token replay, torn segment tail, "
                         "partial-batch spill replay, disk-full, "
                         "429+Retry-After saturation)")
    ap.add_argument("--zipf", action="store_true",
                    help="ISSUE 20 round: generation-keyed result cache "
                         "vs Zipfian traffic — c=1,8,32,64 over one "
                         "identical skewed request stream, cache-off vs "
                         "cache-on cold, hit-rate + served-hit-age next "
                         "to rps/p99, then a live promotion attesting "
                         "zero stale-generation answers and zero "
                         "non-2xx across the swap")
    ap.add_argument("--zipf-s", dest="zipf_s", type=float, default=1.1,
                    help="Zipf exponent s for the --zipf user draw "
                         "(default 1.1; higher = hotter head)")
    ap.add_argument("--zipf-items", dest="zipf_items", type=int,
                    default=50_000,
                    help="item-corpus size for the --zipf round "
                         "(default 50000 — a miss pays a real MIPS "
                         "dispatch, the regime the cache targets)")
    ap.add_argument("--out", default=None,
                    help="write the corpus-scale record to this JSON file")
    args = ap.parse_args()

    if args.zipf:
        _zipf_round(args)
        return
    if args.ingest:
        _ingest_round(args)
        return
    if args.fleet_rollout:
        _fleet_rollout_round(args)
        return
    if args.quality:
        _quality_round(args)
        return
    if args.recall:
        _recall_round(args)
        return
    if args.refresh:
        _refresh_round(args)
        return
    if args.corpus_scale:
        # The sharded round needs a multi-device mesh: force the 8-way
        # virtual CPU device split BEFORE anything initializes jax.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        _corpus_scale(args)
        return
    if args.concurrency:
        _sweep(args)
        return

    eng, variant, storage, n_users = _setup()
    from predictionio_tpu.server import EngineServer

    srv = EngineServer(eng, variant, storage, host="127.0.0.1", port=0)
    srv.start()
    res = _drive(srv.port, n_users, args.clients, args.requests)
    res.update(_scrape_server_hist(srv.port))
    if args.faults:
        # Clean drive above, faulted drive below, SAME server/model:
        # the pair is the tail-latency-under-partial-failure record.
        # Installed AFTER setup+clean so the plan targets only the
        # faulted serving phase, not data load / training / baseline.
        # A /reload is attempted before AND during the faulted drive:
        # with the storage faulted the reload must fail CLOSED (503,
        # breaker trips) while every predict keeps answering from the
        # last-good in-memory model — predict_non_2xx records the claim.
        os.environ["PIO_FAULTS"] = args.faults

        def _try_reload():
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/reload", data=b"",
                method="POST")
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    return resp.status
            except urllib.error.HTTPError as e:
                return e.code
            except OSError:
                return -1

        reload_before = _try_reload()
        mid = {}
        timer = threading.Timer(
            0.3, lambda: mid.update(status=_try_reload()))
        timer.start()
        faulted = _drive(srv.port, n_users, args.clients, args.requests,
                         count_non_2xx=True)
        timer.join()
        # Uninstall before the native section below: its line carries no
        # faults marker, so it must actually run clean.
        os.environ.pop("PIO_FAULTS", None)
        gen = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/", timeout=10).read())
        srv.stop()
        delta = {}
        for k in ("p50_ms", "p99_ms"):
            if k in res and k in faulted:
                delta[f"{k}_delta"] = round(faulted[k] - res[k], 2)
        print(json.dumps({
            "frontend": "python", "faults": args.faults,
            "clean": res, "faulted": faulted, **delta,
            "reload_status_before_drive": reload_before,
            "reload_status_mid_drive": mid.get("status"),
            "predict_non_2xx_during_outage": faulted.get("predict_non_2xx"),
            "model_generation": gen.get("modelGeneration"),
            "breaker": gen.get("breaker"),
        }))
    else:
        srv.stop()
        print(json.dumps({"frontend": "python", **res}))

    try:
        from predictionio_tpu.native.frontend import NativeFrontend

        fe = NativeFrontend(srv.query_batch, host="127.0.0.1", port=0,
                            max_batch=64, max_wait_us=1000)
        fe.start()
        res = _drive(fe.port, n_users, args.clients, args.requests)
        fe.stop()
        print(json.dumps({"frontend": "native", **res}))
    except RuntimeError as e:
        print(json.dumps({"frontend": "native", "error": str(e)}))


if __name__ == "__main__":
    main()
