#!/usr/bin/env python
"""Serving-path experiment: drive both frontends with the bench client.

Thin wrapper over bench_serving._drive (which reports saturation
throughput AND concurrency-1 unloaded latency) at a couple of client
counts — used to pick the bench's saturation point.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench_serving


def main():
    eng, variant, storage, n_users = bench_serving._setup()
    from predictionio_tpu.server import EngineServer

    srv = EngineServer(eng, variant, storage, host="127.0.0.1", port=0)
    srv.start()
    print("python 16c:",
          json.dumps(bench_serving._drive(srv.port, n_users, 16, 2000)),
          flush=True)
    from predictionio_tpu.native.frontend import NativeFrontend

    fe = NativeFrontend(srv.query_batch, host="127.0.0.1", port=0,
                        max_batch=64, max_wait_us=1000)
    fe.start()
    for clients in (16, 32):
        print(f"native {clients}c:",
              json.dumps(bench_serving._drive(fe.port, n_users, clients,
                                              3000)), flush=True)
    fe.stop()
    srv.stop()


if __name__ == "__main__":
    main()
