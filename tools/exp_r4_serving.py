#!/usr/bin/env python
"""Serving-path experiments: client cost, unloaded latency, GIL funnel."""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench_serving


def drive_keepalive(port, n_users, clients, requests, unloaded=False):
    import concurrent.futures
    import http.client
    import threading

    rng = np.random.default_rng(1)
    payloads = [json.dumps({"user": f"u{rng.integers(0, n_users)}",
                            "num": 10}).encode() for _ in range(requests)]
    local = threading.local()

    def one(body):
        t0 = time.perf_counter()
        for attempt in (0, 1, 2):
            conn = getattr(local, "conn", None)
            if conn is None:
                conn = local.conn = http.client.HTTPConnection(
                    "127.0.0.1", port, timeout=30)
            try:
                conn.request("POST", "/queries.json", body,
                             {"Content-Type": "application/json"})
                r = conn.getresponse()
                r.read()
                if r.status != 200:
                    raise RuntimeError(f"status {r.status}")
                break
            except (OSError, http.client.HTTPException):
                conn.close()
                local.conn = None
                if attempt == 2:
                    raise
        return (time.perf_counter() - t0) * 1e3

    for body in payloads[:5]:
        one(body)
    if unloaded:
        lat = np.array([one(b) for b in payloads[:400]])
        return {"p50_unloaded_ms": round(float(np.percentile(lat, 50)), 2),
                "p99_unloaded_ms": round(float(np.percentile(lat, 99)), 2)}
    import concurrent.futures
    with concurrent.futures.ThreadPoolExecutor(clients) as ex:
        list(ex.map(one, payloads[: 8 * clients]))
    t0 = time.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(clients) as ex:
        lat = np.array(list(ex.map(one, payloads)))
    wall = time.perf_counter() - t0
    return {"throughput_rps": round(requests / wall, 1),
            "p50_ms": round(float(np.percentile(lat, 50)), 2),
            "p99_ms": round(float(np.percentile(lat, 99)), 2)}


def main():
    eng, variant, storage, n_users = bench_serving._setup()
    from predictionio_tpu.server import EngineServer

    srv = EngineServer(eng, variant, storage, host="127.0.0.1", port=0)
    srv.start()
    print("python unloaded:", drive_keepalive(srv.port, n_users, 1, 500,
                                              unloaded=True), flush=True)
    print("python ka 16c:", drive_keepalive(srv.port, n_users, 16, 3000),
          flush=True)
    from predictionio_tpu.native.frontend import NativeFrontend

    fe = NativeFrontend(srv.query_batch, host="127.0.0.1", port=0,
                        max_batch=64, max_wait_us=1000)
    fe.start()
    print("native unloaded:", drive_keepalive(fe.port, n_users, 1, 500,
                                              unloaded=True), flush=True)
    print("native ka 16c:", drive_keepalive(fe.port, n_users, 16, 3000),
          flush=True)
    print("native ka 32c:", drive_keepalive(fe.port, n_users, 32, 3000),
          flush=True)
    fe.stop()
    srv.stop()


if __name__ == "__main__":
    main()
