#!/usr/bin/env python
"""Compile the fused ALS training loop at the bench shape WITHOUT data and
dump the compiled-HLO op mix — the fast loop for layout experiments.

The round-3 profile says 80 ms/iter (32%) of the ML-25M iteration is XLA
layout copies + scatter overhead.  This tool reconstructs the exact bucket
shapes host-side (same plan_buckets logic the device prep uses), lowers
``_train_loop`` from ShapeDtypeStructs, compiles it on the real TPU
backend, and aggregates the op kinds/shapes so a layout change's effect on
the emitted copies is visible in seconds instead of a full benchmark run.

Usage: PIO_BENCH_SCALE=1.0 python tools/als_hlo.py [out.hlo]
"""
import os
import re
import sys
from collections import defaultdict

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from predictionio_tpu.models import als as als_lib
from predictionio_tpu.ops.device_prep import plan_buckets

SCALE = float(os.environ.get("PIO_BENCH_SCALE", "1.0"))
N_USERS = max(64, int(162_541 * SCALE))
N_ITEMS = max(64, int(59_047 * SCALE))
N_RATINGS = max(4096, int(25_000_000 * SCALE))
RANK = 64


def synth(seed=0):
    rng = np.random.default_rng(seed)
    users = rng.integers(0, N_USERS, N_RATINGS)
    items = (rng.zipf(1.25, size=N_RATINGS) % N_ITEMS).astype(np.int64)
    return users, items


def host_plan(ids, n_rows, cfg):
    """Host-numpy reproduction of _prepare_als_inputs_device's planning."""
    split_above = cfg.split_above or 1 << 20
    counts = np.bincount(ids, minlength=n_rows).astype(np.int64)
    clipped = np.minimum(counts, split_above)
    hist = np.bincount(clipped, minlength=split_above + 1)
    over = counts > split_above
    n_over = int(over.sum())
    n_part = int(np.where(over, (counts + split_above - 1) // split_above,
                          0).sum())
    over_deg = counts[np.nonzero(over)[0]] if n_over else None
    return plan_buckets(hist, n_over, n_part, n_rows,
                        split_above=split_above,
                        bucket_bounds=cfg.bucket_bounds,
                        max_block_floats=cfg.max_block_floats,
                        rank=cfg.rank, over_degrees=over_deg)


def plan_shapes(plan):
    """ShapeDtypeStruct bucket tuples exactly as build_buckets emits them."""
    f32, i32, b_ = jnp.float32, jnp.int32, jnp.bool_
    out, kinds = [], []
    for i, (b, rp) in enumerate(zip(plan.bounds, plan.rows_padded)):
        chunks = plan.plain_chunks[i] if plan.plain_chunks else ((0, rp),)
        for cs, cn in chunks:
            S = jax.ShapeDtypeStruct
            out.append((S((cn, b), i32), S((cn, b), f32), S((cn, b), b_),
                        S((cn,), i32)))
            kinds.append("plain")
    if plan.split_len is not None:
        sl = plan.split_len
        S = jax.ShapeDtypeStruct
        chunks = plan.split_chunks or (
            (0, plan.split_segs, 0, plan.split_rows),)
        for e0, e1, r0, r1 in chunks:
            pad = plan.pad_rows_to
            rr = r1 - r0 + ((-(r1 - r0)) % pad)
            ss = e1 - e0 + ((-(e1 - e0)) % pad)
            out.append((S((rr, sl), i32), S((rr, sl), f32), S((rr, sl), b_),
                        S((rr,), i32), S((ss,), i32)))
            kinds.append("merged")
    return tuple(out), tuple(kinds)


def main():
    users, items = synth()
    cfg = als_lib.ALSConfig(rank=RANK, iterations=2, reg=0.01, seed=1)
    up, uk = plan_shapes(host_plan(users, N_USERS, cfg))
    ip, ik = plan_shapes(host_plan(items, N_ITEMS, cfg))
    S = jax.ShapeDtypeStruct
    uf = S((N_USERS, RANK), jnp.float32)
    itf = S((N_ITEMS, RANK), jnp.float32)
    kinds = (uk, ik)
    use_pallas = os.environ.get("PIO_ALS_PALLAS", "1") == "1"
    pallas_flags = (tuple(use_pallas for _ in uk),
                    tuple(use_pallas for _ in ik))
    gdt = als_lib._resolve_gram_dtype(cfg.gram_dtype)
    solver = os.environ.get("PIO_ALS_SOLVER", "lu")

    print(f"shape {N_USERS}x{N_ITEMS}x{N_RATINGS} rank{RANK} "
          f"buckets u={len(uk)} i={len(ik)} gdt={gdt} solver={solver}",
          file=sys.stderr)
    lowered = jax.jit(als_lib._train_loop, static_argnames=(
        "kinds", "pallas_flags", "implicit", "gram_dtype", "solver")).lower(
        uf, itf, up, ip, S((), jnp.float32), S((), jnp.float32),
        S((), jnp.int32), kinds=kinds, pallas_flags=pallas_flags,
        implicit=False, gram_dtype=gdt, solver=solver)
    compiled = lowered.compile()
    txt = compiled.as_text()
    if len(sys.argv) > 1:
        open(sys.argv[1], "w").write(txt)
        print(f"wrote {sys.argv[1]} ({len(txt)/1e6:.1f} MB)", file=sys.stderr)

    # Aggregate ops by kind; big tensors only.
    agg = defaultdict(lambda: [0, 0.0])  # kind -> [count, total_MB]
    for m in re.finditer(
            r"^\s*(?:ROOT )?%?[\w.\-]+ = ([a-z0-9]+)\[([\d,]*)\][^=]*"
            r"(copy|transpose|scatter|gather|fusion|convert|"
            r"dynamic-update-slice|dynamic-slice|custom-call|reduce|dot)\(",
            txt, re.M):
        dt, shp, kind = m.groups()
        n = 1
        for d in (shp.split(",") if shp else []):
            if d:
                n *= int(d)
        bytes_per = {"f32": 4, "s32": 4, "u32": 4, "bf16": 2, "pred": 1,
                     "f64": 8, "u8": 1, "s8": 1}.get(dt, 4)
        mb = n * bytes_per / 1e6
        agg[kind][0] += 1
        if mb > 1.0:
            agg[kind][1] += mb
    print(f"{'op kind':25s} {'count':>7s} {'MB(>1MB ops)':>14s}")
    for kind, (cnt, mb) in sorted(agg.items(), key=lambda kv: -kv[1][1]):
        print(f"{kind:25s} {cnt:7d} {mb:14.1f}")

    # The biggest copies, with shapes.
    copies = []
    for m in re.finditer(
            r"^\s*%?[\w.\-]+ = ([a-z0-9]+)\[([\d,]*)\][^\n]*?(copy)\(",
            txt, re.M):
        dt, shp, _ = m.groups()
        n = 1
        for d in (shp.split(",") if shp else []):
            if d:
                n *= int(d)
        bytes_per = {"f32": 4, "s32": 4, "bf16": 2, "pred": 1}.get(dt, 4)
        copies.append((n * bytes_per / 1e6, f"{dt}[{shp}]", m.group(0)[:160]))
    copies.sort(reverse=True)
    print("\ntop copies:")
    for mb, shp, line in copies[:12]:
        print(f"  {mb:9.1f} MB {shp}")
        print(f"    {line.strip()[:150]}")


if __name__ == "__main__":
    main()
