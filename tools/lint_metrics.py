#!/usr/bin/env python
"""Static check: the metrics schema stays fleet-merge-stable.

ISSUE 9's fleet aggregation merges ``/metrics`` expositions from N
instances by TYPE: counters sum, histogram buckets add per-``le``,
gauges keep an ``instance`` label.  That merge is only correct while
every instance registers every metric the same way — same kind, same
label set, same bucket bounds — and while names stay statically known.
This lint locks those invariants in (tier-1 test runs it in CI):

1. Every ``<registry>.counter(...)`` / ``.gauge(...)`` /
   ``.histogram(...)`` call in ``predictionio_tpu/`` passes its metric
   name as a STRING LITERAL with the ``pio_`` prefix (a computed name
   can't be schema-checked and breaks the naming convention README
   documents).
2. A name is registered with exactly ONE kind and ONE label set across
   the whole package — the registry's get-or-create would raise at
   runtime on a mismatch, but only on the code path that collides; this
   catches it before it ships.  Label sets must be literal tuples/lists
   of string literals for the same reason as rule 1.
3. Histograms declare schema-stable buckets: either no ``buckets=``
   argument (the module-constant default), or a literal tuple/list of
   numbers, or a reference to a MODULE-LEVEL UPPERCASE constant.  A
   bucket list computed at runtime could differ between instances and
   silently corrupt the fleet's per-``le`` bucket addition.
4. (ISSUE 11) Model-quality metric families — the ``pio_quality_`` and
   ``pio_predict_`` prefixes — may be REGISTERED only in
   ``obs/quality.py``: the ``/quality.json`` fleet merge derives its
   schema from that one module, so a quality series minted elsewhere
   would fork the schema the merge (and the schema-stability test)
   relies on.
5. (ISSUE 16) Retrieval-recall metric families — the
   ``pio_retrieval_recall`` prefix — may be REGISTERED only in
   ``obs/recall.py``, the same single-owner contract as rule 4: the
   recall block of ``/quality.json`` (and its worst-instance fleet
   merge) is derived from that one module.  Note the facade's other
   ``pio_retrieval_*`` families stay where they are — the rule pins
   the ``pio_retrieval_recall`` prefix specifically.

Usage: ``python tools/lint_metrics.py [root]`` — prints violations and
exits non-zero when any exist.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import Dict, List, Optional, Tuple

_REGISTER_FNS = {"counter": "counter", "gauge": "gauge",
                 "histogram": "histogram"}


def _literal_str(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _literal_labelnames(node: Optional[ast.AST]) -> Optional[Tuple[str, ...]]:
    """Labelnames as a tuple of literal strings; None when not literal."""
    if node is None:
        return ()
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            s = _literal_str(el)
            if s is None:
                return None
            out.append(s)
        return tuple(out)
    return None


def _bucket_spec(node: Optional[ast.AST]) -> Optional[str]:
    """A stable string key for a bucket declaration, or None when the
    declaration is not schema-stable (rule 3)."""
    if node is None:
        return "<default>"
    if isinstance(node, ast.Name):
        # Module-level constant by convention: UPPERCASE name.
        return node.id if node.id.isupper() else None
    if isinstance(node, ast.Attribute):
        return node.attr if node.attr.isupper() else None
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(
                    el.value, (int, float)):
                vals.append(repr(float(el.value)))
            else:
                return None
        return "(" + ",".join(vals) + ")"
    return None


def _call_parts(call: ast.Call):
    """(name_node, labelnames_node, buckets_node) for a register call.

    Signature shape: ``fn(name, help="", labelnames=(), [buckets=...])``
    — positional help at index 1, labelnames at index 2."""
    name = call.args[0] if call.args else None
    labelnames = call.args[2] if len(call.args) > 2 else None
    buckets = None
    for kw in call.keywords:
        if kw.arg == "labelnames":
            labelnames = kw.value
        elif kw.arg == "buckets":
            buckets = kw.value
        elif kw.arg == "name":
            name = kw.value
    return name, labelnames, buckets


def check_source(source: str, filename: str,
                 registry: Optional[Dict[str, Dict]] = None) -> List[str]:
    """Violations in one module; ``registry`` accumulates cross-module
    (name → kind/labels/buckets) state for rule 2."""
    registry = registry if registry is not None else {}
    violations: List[str] = []
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as e:
        return [f"{filename}:{e.lineno}: unparseable: {e.msg}"]
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _REGISTER_FNS):
            continue
        kind = _REGISTER_FNS[node.func.attr]
        where = f"{filename}:{node.lineno}"
        name_node, labels_node, buckets_node = _call_parts(node)
        name = _literal_str(name_node)
        if name is None:
            violations.append(
                f"{where}: {kind}() metric name is not a string literal "
                f"— computed names can't be schema-checked")
            continue
        if not name.startswith("pio_"):
            violations.append(
                f"{where}: metric {name!r} missing the pio_ prefix "
                f"(naming convention: pio_<subsystem>_<what>_<unit>)")
        if name.startswith(("pio_quality_", "pio_predict_")) \
                and not filename.replace("\\", "/").endswith(
                    "obs/quality.py"):
            violations.append(
                f"{where}: quality metric {name!r} registered outside "
                f"obs/quality.py — the /quality.json fleet-merge schema "
                f"is owned by that one module (rule 4)")
        if name.startswith("pio_retrieval_recall") \
                and not filename.replace("\\", "/").endswith(
                    "obs/recall.py"):
            violations.append(
                f"{where}: retrieval-recall metric {name!r} registered "
                f"outside obs/recall.py — the recall fleet-merge schema "
                f"is owned by that one module (rule 5)")
        labels = _literal_labelnames(labels_node)
        if labels is None:
            violations.append(
                f"{where}: metric {name!r} labelnames are not a literal "
                f"tuple of strings")
            continue
        bucket_key = None
        if kind == "histogram":
            bucket_key = _bucket_spec(buckets_node)
            if bucket_key is None:
                violations.append(
                    f"{where}: histogram {name!r} buckets are computed at "
                    f"runtime — declare a literal tuple or an UPPERCASE "
                    f"module constant so every instance shares one "
                    f"bucket schema")
        prev = registry.get(name)
        if prev is None:
            registry[name] = {"kind": kind, "labels": labels,
                              "buckets": bucket_key, "where": where}
            continue
        if prev["kind"] != kind:
            violations.append(
                f"{where}: metric {name!r} registered as {kind} but "
                f"already a {prev['kind']} at {prev['where']}")
        if prev["labels"] != labels:
            violations.append(
                f"{where}: metric {name!r} registered with labels "
                f"{labels} but {prev['labels']} at {prev['where']} — one "
                f"(name, label-set) schema per metric")
        if (kind == "histogram" and bucket_key is not None
                and prev.get("buckets") is not None
                and prev["buckets"] != bucket_key):
            violations.append(
                f"{where}: histogram {name!r} buckets {bucket_key} differ "
                f"from {prev['buckets']} at {prev['where']}")
    return violations


def check(root: Path | str | None = None) -> List[str]:
    root = Path(root) if root else Path(__file__).resolve().parents[1]
    pkg = root / "predictionio_tpu"
    registry: Dict[str, Dict] = {}
    violations: List[str] = []
    for path in sorted(pkg.rglob("*.py")):
        violations.extend(check_source(
            path.read_text(encoding="utf-8"), str(path), registry))
    return violations


def main(argv: List[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    violations = check(argv[0] if argv else None)
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} metrics-lint violation(s).",
              file=sys.stderr)
        return 1
    print("lint_metrics: every metric is pio_-prefixed, literally named, "
          "and schema-consistent.")
    return 0


if __name__ == "__main__":
    sys.exit(main())
