#!/usr/bin/env python
"""Top-op timing breakdown of the round-4 ALS iteration (xplane dump)."""
import os
import sys
import time
from collections import defaultdict

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from predictionio_tpu.models.als import (
    ALSConfig, prepare_als_inputs, train_als_prepared,
)

SCALE = float(os.environ.get("PIO_BENCH_SCALE", "1.0"))
N_USERS = max(64, int(162_541 * SCALE))
N_ITEMS = max(64, int(59_047 * SCALE))
N_RATINGS = max(4096, int(25_000_000 * SCALE))
RANK = 64
ITERS = 4


def main():
    rng = np.random.default_rng(0)
    users = rng.integers(0, N_USERS, N_RATINGS)
    items = (rng.zipf(1.25, size=N_RATINGS) % N_ITEMS).astype(np.int64)
    ratings = (rng.integers(1, 11, N_RATINGS) * 0.5).astype(np.float32)
    cfg = ALSConfig(rank=RANK, iterations=2, reg=0.01, seed=1)
    t0 = time.perf_counter()
    du = jnp.asarray(users.astype(np.int32))
    di = jnp.asarray(items.astype(np.int32))
    dr = jnp.asarray(ratings + np.float32((time.time_ns() % 997) * 1e-6))
    inputs = prepare_als_inputs(du, di, dr, N_USERS, N_ITEMS, cfg)
    float(jnp.sum(inputs.uf0))
    print(f"prep+h2d {time.perf_counter()-t0:.0f}s", flush=True)
    m = train_als_prepared(inputs, cfg)  # compile
    float(jnp.sum(m.user_factors))

    import glob
    import tempfile

    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    with tempfile.TemporaryDirectory(prefix="pio_trace_") as td:
        with jax.profiler.trace(td):
            c2 = ALSConfig(rank=RANK, iterations=ITERS, reg=0.01, seed=1)
            m = train_als_prepared(inputs, c2)
            float(jnp.sum(m.user_factors))
        xs = xplane_pb2.XSpace()
        xs.ParseFromString(open(glob.glob(
            f"{td}/**/*.xplane.pb", recursive=True)[0], "rb").read())
        tpu = [p for p in xs.planes if p.name.startswith("/device:TPU")][0]
        evm = {k: v.name for k, v in tpu.event_metadata.items()}
        agg = defaultdict(float)
        for line in tpu.lines:
            if line.name != "XLA Ops":
                continue
            for ev in line.events:
                name = evm.get(ev.metadata_id, "")
                if name.startswith(("%while", "jit_")):
                    continue
                agg[name] += ev.duration_ps / 1e9
        total = sum(agg.values())
        print(f"total device ms over {ITERS} iters: {total:.0f} "
              f"({total/ITERS:.1f}/iter)")
        for name, ms in sorted(agg.items(), key=lambda kv: -kv[1])[:35]:
            print(f"  {ms/ITERS:8.2f} ms/iter  {name[:110]}")


if __name__ == "__main__":
    main()
