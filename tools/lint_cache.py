#!/usr/bin/env python
"""Static check: ONE result-cache seam on the serve path (ISSUE 20).

The result cache is only sound because every ``/queries.json`` answer
flows through exactly one lookup/fill seam keyed by (generation
fingerprint, canonical query).  A handler that memoizes results on the
side — a dict keyed by raw query text, an ``lru_cache`` on a serve
helper — reintroduces the invalidation problem the fingerprint key
design deleted: promotion/rollback would no longer miss by construction.
This lint locks the seam in (tier-1 test runs it in CI):

1. In ``server/engine_server.py``, every function that calls
   ``scheduler.submit_and_wait(...)`` must consult the cache facade
   around it: a ``result_cache.lookup(...)`` BEFORE the submit and a
   ``result_cache.fill(...)`` AFTER it (source order).  Engine query
   results reach the transport only through that seam.
2. No ad-hoc memoization primitives (``functools.lru_cache`` /
   ``functools.cache``) anywhere in ``predictionio_tpu/server/`` or
   ``predictionio_tpu/serving/`` outside the cache module itself —
   those decorators have no generation key and survive a swap.
3. ``pio_result_cache_*`` metric families REGISTER only in
   ``serving/result_cache.py`` — the ``pio status`` line, the
   ``/stats.json`` snapshot, and the fleet merge derive their schema
   from that one module (same single-owner contract the quality and
   recall families live under in ``tools/lint_metrics.py``).

Usage: ``python tools/lint_cache.py [root]`` — prints violations and
exits non-zero when any exist.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import Dict, List, Optional

_CACHE_MODULE = "serving/result_cache.py"
_MEMO_NAMES = {"lru_cache", "cache"}


def _norm(filename: str) -> str:
    return filename.replace("\\", "/")


def _attr_chain(node: ast.AST) -> List[str]:
    """['self', 'result_cache', 'lookup'] for self.result_cache.lookup."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return list(reversed(parts))


def _is_memo_decorator(dec: ast.AST) -> bool:
    """functools.lru_cache / functools.cache, bare or called."""
    if isinstance(dec, ast.Call):
        dec = dec.func
    chain = _attr_chain(dec)
    if not chain:
        return False
    if chain[-1] in _MEMO_NAMES:
        # bare `cache` as a name is too common to flag; require the
        # functools spelling for it, but flag `lru_cache` either way.
        if chain[-1] == "cache":
            return len(chain) > 1 and chain[-2] == "functools"
        return True
    return False


def _check_submit_seam(tree: ast.Module, filename: str) -> List[str]:
    """Rule 1: lookup-before / fill-after around every submit_and_wait."""
    violations: List[str] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        submits: List[int] = []
        lookups: List[int] = []
        fills: List[int] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if not chain:
                continue
            if chain[-1] == "submit_and_wait":
                submits.append(node.lineno)
            elif chain[-1] == "lookup" and "result_cache" in chain:
                lookups.append(node.lineno)
            elif chain[-1] == "fill" and "result_cache" in chain:
                fills.append(node.lineno)
        for line in submits:
            if not any(ln < line for ln in lookups):
                violations.append(
                    f"{filename}:{line}: submit_and_wait() without a "
                    f"result_cache.lookup() before it — engine results "
                    f"must reach the transport through the cache seam "
                    f"(rule 1)")
            if not any(ln > line for ln in fills):
                violations.append(
                    f"{filename}:{line}: submit_and_wait() without a "
                    f"result_cache.fill() after it — a dispatched answer "
                    f"that skips the fill seam starves the cache and "
                    f"invites ad-hoc memoization (rule 1)")
    return violations


def check_source(source: str, filename: str,
                 registry: Optional[Dict[str, str]] = None) -> List[str]:
    """Violations in one module; ``registry`` is unused state kept for
    signature parity with the sibling lints (callers pass {})."""
    registry = registry if registry is not None else {}
    violations: List[str] = []
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as e:
        return [f"{filename}:{e.lineno}: unparseable: {e.msg}"]
    fname = _norm(filename)
    in_cache_module = fname.endswith(_CACHE_MODULE)
    on_serve_path = ("predictionio_tpu/server/" in fname
                     or "predictionio_tpu/serving/" in fname)

    # rule 1: the seam itself, in the engine server only
    if fname.endswith("server/engine_server.py"):
        violations.extend(_check_submit_seam(tree, filename))

    for node in ast.walk(tree):
        # rule 2: no generation-blind memoization on the serve path
        if (on_serve_path and not in_cache_module
                and isinstance(node, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))):
            for dec in node.decorator_list:
                if _is_memo_decorator(dec):
                    violations.append(
                        f"{filename}:{node.lineno}: function "
                        f"{node.name!r} memoized with functools on the "
                        f"serve path — such caches have no generation "
                        f"key and survive a model swap; go through the "
                        f"result-cache facade (rule 2)")
        # rule 3: single-owner pio_result_cache_* family
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("counter", "gauge", "histogram")):
            args = node.args
            name_node = args[0] if args else None
            for kw in node.keywords:
                if kw.arg == "name":
                    name_node = kw.value
            if (isinstance(name_node, ast.Constant)
                    and isinstance(name_node.value, str)
                    and name_node.value.startswith("pio_result_cache")
                    and not in_cache_module):
                violations.append(
                    f"{filename}:{node.lineno}: result-cache metric "
                    f"{name_node.value!r} registered outside "
                    f"{_CACHE_MODULE} — the family schema is owned by "
                    f"that one module (rule 3)")
    return violations


def check(root: Path | str | None = None) -> List[str]:
    root = Path(root) if root else Path(__file__).resolve().parents[1]
    pkg = root / "predictionio_tpu"
    violations: List[str] = []
    for path in sorted(pkg.rglob("*.py")):
        violations.extend(check_source(
            path.read_text(encoding="utf-8"), str(path), {}))
    return violations


def main(argv: List[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    violations = check(argv[0] if argv else None)
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} cache-lint violation(s).",
              file=sys.stderr)
        return 1
    print("lint_cache: engine results flow through the one lookup/fill "
          "seam; no serve-path memoization; result-cache metrics are "
          "single-owner.")
    return 0


if __name__ == "__main__":
    sys.exit(main())
