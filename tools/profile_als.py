#!/usr/bin/env python
"""Per-phase ALS iteration profiler (VERDICT r2 item 1).

Times each phase of one ALS sweep at the bench shape: gather, gram+rhs
build, ridge solve — per bucket, both sides.  Every phase is measured by
the SLOPE method (fori_loop of N reps inside one jit, timed at two rep
counts) because a single host read-back through the remote-TPU tunnel
costs ~100 ms — far more than most phases.  A runtime-zero feedback
term defeats loop-invariant hoisting.  Prints a JSON phase table.
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import functools

import jax
import jax.numpy as jnp

from predictionio_tpu.models.als import (
    ALSConfig, prepare_als_inputs, _gram_pieces, _ridge,
)

SCALE = float(os.environ.get("PIO_BENCH_SCALE", "1.0"))
N_USERS = max(64, int(162_541 * SCALE))
N_ITEMS = max(64, int(59_047 * SCALE))
N_RATINGS = max(4096, int(25_000_000 * SCALE))
RANK = int(os.environ.get("PIO_BENCH_RANK", "64"))
R1, R2 = 2, 10


def synth(seed=0):
    rng = np.random.default_rng(seed)
    users = rng.integers(0, N_USERS, N_RATINGS)
    items = (rng.zipf(1.25, size=N_RATINGS) % N_ITEMS).astype(np.int64)
    ratings = (rng.integers(1, 11, N_RATINGS) * 0.5).astype(np.float32)
    return users, items, ratings


def slope(repeat_fn, *args):
    """ms per rep via (T(R2)-T(R1))/(R2-R1); one host read per run."""
    def run(n):
        t0 = time.perf_counter()
        out = repeat_fn(jnp.int32(n), jnp.float32(0.0), *args)
        float(jnp.sum(out))
        return time.perf_counter() - t0
    run(R1)  # compile
    t1 = run(R1)
    t2 = run(R2)
    return (t2 - t1) / (R2 - R1) * 1e3


@jax.jit
def rep_gather(n, zero, factors, indices):
    def body(_, carry):
        f = (factors + carry * zero)[indices]
        return jnp.float32(f[0, 0, 0])
    c = jax.lax.fori_loop(0, n, body, jnp.float32(0.0))
    return c


@jax.jit
def rep_gram(n, zero, factors, indices, vals, msk):
    def body(_, carry):
        a, b, deg = _gram_pieces(indices, vals + carry * zero, msk, factors,
                                 jnp.float32(1.0), False, False, jnp.float32)
        return jnp.float32(a[0, 0, 0] + b[0, 0] + deg[0])
    return jax.lax.fori_loop(0, n, body, jnp.float32(0.0))


def rep_solve(solver):
    @jax.jit
    def f(n, zero, a, b, regv):
        def body(_, carry):
            x = _ridge(a + carry * zero, b, regv, solver)
            return jnp.float32(x[0, 0])
        return jax.lax.fori_loop(0, n, body, jnp.float32(0.0))
    return f


rep_gj = rep_solve("gj")
rep_ch = rep_solve("cholesky")


def main():
    users, items, ratings = synth()
    cfg = ALSConfig(rank=RANK, iterations=2, reg=0.01, seed=1)
    t0 = time.perf_counter()
    inputs = prepare_als_inputs(users, items, ratings, N_USERS, N_ITEMS, cfg)
    prep_s = time.perf_counter() - t0
    print(f"prep_s={prep_s:.2f}", file=sys.stderr)

    # Bucket-level inputs (round 4): expand the HBM chunks exactly as the
    # training loop does, so per-bucket gathers below stay within the
    # chunk budget instead of materializing a whole jumbo bucket.
    from predictionio_tpu.models.als import _expand_chunks
    import jax as _jax
    if inputs.chunk_specs is not None:
        expand = _jax.jit(_expand_chunks, static_argnames=("specs",))
        u_kinds = []
        for b, spec in zip(inputs.user_buckets, inputs.chunk_specs[0]):
            u_kinds.extend([b[0]] * max(len(spec[-1]), 1))
        i_kinds = []
        for b, spec in zip(inputs.item_buckets, inputs.chunk_specs[1]):
            i_kinds.extend([b[0]] * max(len(spec[-1]), 1))
        ub = expand(tuple(tuple(b[1:]) for b in inputs.user_buckets),
                    specs=inputs.chunk_specs[0])
        ib = expand(tuple(tuple(b[1:]) for b in inputs.item_buckets),
                    specs=inputs.chunk_specs[1])
        inputs.user_buckets = [(k, *a) for k, a in zip(u_kinds, ub)]
        inputs.item_buckets = [(k, *a) for k, a in zip(i_kinds, ib)]
        inputs.chunk_specs = None

    report = {"shape": f"{N_USERS}x{N_ITEMS}x{N_RATINGS} rank{RANK}",
              "prep_s": round(prep_s, 2), "sides": {}}
    reg = jnp.float32(0.01)
    gram_once = jax.jit(lambda i, v, m, f: _gram_pieces(
        i, v, m, f, jnp.float32(1.0), False, False, jnp.float32))

    totals = dict(gather=0.0, gram=0.0, gj=0.0, chol=0.0)
    for side, buckets, factors in (("user", inputs.user_buckets, inputs.itf0),
                                   ("item", inputs.item_buckets, inputs.uf0)):
        rows = []
        for kind, idx, vals, msk, *rest in buckets:
            r, l = idx.shape
            ms_gather = slope(rep_gather, factors, idx)
            ms_gram = slope(rep_gram, factors, idx, vals, msk)
            a, b, deg = gram_once(idx, vals, msk, factors)
            regv = reg * jnp.maximum(deg, 1.0)
            ms_gj = slope(rep_gj, a, b, regv)
            ms_ch = slope(rep_ch, a, b, regv)
            totals["gather"] += ms_gather
            totals["gram"] += ms_gram
            totals["gj"] += ms_gj
            totals["chol"] += ms_ch
            rows.append({"kind": kind, "rows": r, "len": l,
                         "padded_nnz_m": round(idx.size / 1e6, 2),
                         "gather_ms": round(ms_gather, 2),
                         "gram_ms": round(ms_gram, 2),
                         "solve_gj_ms": round(ms_gj, 2),
                         "solve_chol_ms": round(ms_ch, 2)})
        report["sides"][side] = rows
    report["totals_ms"] = {k: round(v, 2) for k, v in totals.items()}

    from predictionio_tpu.models.als import train_als_prepared

    def run(iters):
        c = ALSConfig(rank=RANK, iterations=iters, reg=0.01, seed=1)
        t0 = time.perf_counter()
        m = train_als_prepared(inputs, c)
        float(jnp.sum(m.user_factors))
        return time.perf_counter() - t0

    run(2)
    t1 = run(2)
    t2 = run(6)
    report["per_iter_ms"] = round((t2 - t1) / 4 * 1e3, 2)
    print(json.dumps(report, indent=1))


if __name__ == "__main__":
    main()
