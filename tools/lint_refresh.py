#!/usr/bin/env python
"""Static check: the refresh loop promotes ONLY through the staged-reload
canary gate — never a direct model write or generation swap.

ISSUE 10 builds a daemon that retrains continuously.  The single most
dangerous regression such a loop can grow is a shortcut around the PR-4
promotion machinery: writing a model blob the serving layer will load
without validation, or reaching into a live EngineServer and swapping
its generation state directly (skipping the finite check, the canary
queries, and the retained-rollback slot).  This lint makes the road
structural (a tier-1 test runs it in CI):

1. **Model-store writes** — a ``<x>.get_models().insert(...)`` chain (or
   any ``.insert`` call on a variable bound from ``get_models()``) is
   allowed ONLY in ``workflow/core_workflow.py`` (``_persist_models``,
   the one sanctioned writer) and in ``data/storage`` backends (the
   repositories themselves).  Everything else — the refresh daemon
   especially — trains through ``run_train`` and promotes through
   ``POST /reload``.

2. **Generation-state writes** — assignments to the engine server's
   swap-guarded fields (``_models``, ``_algorithms``, ``_serving``,
   ``_instance``, ``_previous``, ``_generation``) on an object other
   than ``self`` are allowed ONLY in ``server/engine_server.py``.  A
   module that mutates another object's generation state is bypassing
   the staged reload.

3. **Refresh-package discipline** — ``predictionio_tpu/refresh``
   additionally must not call ``load_models``-then-serve shortcuts:
   it may not reference ``validate_model_finite`` (validation belongs
   to the server's gate, not a daemon-side reimplementation) and may
   not call ``get_models`` at all.

4. **Fleet promotion goes through the rollout controller** (ISSUE 15) —
   a ``.promote(...)`` call lexically inside a loop (for/while/
   comprehension) is allowed ONLY inside ``predictionio_tpu/fleet``.
   A bare promote-loop over an instance list has no wave gate, no
   journaled state to resume from, and no whole-fleet unwind; the
   single-instance daemon's one ``promoter.promote(...)`` per cycle
   (not lexically in a loop) stays legal.

Usage: ``python tools/lint_refresh.py [root]`` — prints violations and
exits non-zero when any exist.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List

# Files allowed to write the model store (rule 1).
_MODEL_WRITE_OK = {
    ("workflow", "core_workflow.py"),
}
# Generation-state attributes only engine_server.py may assign on a
# non-self object (rule 2).
_GEN_ATTRS = {"_models", "_algorithms", "_serving", "_instance",
              "_previous", "_generation"}
_GEN_WRITE_OK = {("server", "engine_server.py")}
# Names the refresh package may not touch (rule 3).
_REFRESH_FORBIDDEN = {"get_models", "validate_model_finite"}
# Package whose loops MAY call .promote() (rule 4).
_PROMOTE_LOOP_OK_PKG = "fleet"

_LOOP_NODES = (ast.For, ast.AsyncFor, ast.While, ast.ListComp,
               ast.SetComp, ast.DictComp, ast.GeneratorExp)


def _promote_calls_in_loops(tree: ast.AST) -> List[int]:
    """Line numbers of ``<x>.promote(...)`` calls lexically inside a
    loop or comprehension (rule 4)."""
    out: List[int] = []

    def walk(node: ast.AST, in_loop: bool) -> None:
        for child in ast.iter_child_nodes(node):
            child_in_loop = in_loop or isinstance(child, _LOOP_NODES)
            if (in_loop and isinstance(child, ast.Call)
                    and isinstance(child.func, ast.Attribute)
                    and child.func.attr == "promote"):
                out.append(child.lineno)
            # a nested function body resets the loop context — a helper
            # DEFINED in a loop is not itself a promote loop
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                walk(child, False)
            else:
                walk(child, child_in_loop)

    walk(tree, False)
    return out


def _rel_key(path: Path) -> tuple:
    return (path.parent.name, path.name)


def _is_get_models_chain(call: ast.Call) -> bool:
    """``<anything>.get_models(...).insert(...)`` — the direct chain."""
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr == "insert"
            and isinstance(f.value, ast.Call)
            and isinstance(f.value.func, ast.Attribute)
            and f.value.func.attr == "get_models")


def _get_models_bound_names(tree: ast.AST) -> set:
    """Variables assigned from a ``get_models()`` call anywhere in the
    module — ``repo = storage.get_models(); repo.insert(...)`` must not
    dodge rule 1 by splitting the chain."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            fn = node.value.func
            if isinstance(fn, ast.Attribute) and fn.attr == "get_models":
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        names.add(tgt.id)
    return names


def check_source(source: str, filename: str,
                 rel_key: tuple, in_refresh: bool,
                 in_fleet: bool = False) -> List[str]:
    violations: List[str] = []
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as e:
        return [f"{filename}:{e.lineno}: unparseable: {e.msg}"]

    model_write_ok = rel_key in _MODEL_WRITE_OK \
        or rel_key[0] == "storage"
    bound = _get_models_bound_names(tree)
    # Rule 4: promote loops only inside the fleet package.
    if not in_fleet:
        for lineno in _promote_calls_in_loops(tree):
            violations.append(
                f"{filename}:{lineno}: .promote() inside a loop — "
                f"multi-instance promotion goes through "
                f"fleet.RolloutController (wave gating, journaled "
                f"state, whole-fleet rollback), never a bare promote "
                f"loop over an instance list")
    for node in ast.walk(tree):
        # Rule 1: model-store writes.
        if isinstance(node, ast.Call) and not model_write_ok:
            direct = _is_get_models_chain(node)
            via_name = (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "insert"
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id in bound)
            if direct or via_name:
                violations.append(
                    f"{filename}:{node.lineno}: direct model-store write "
                    f"(get_models().insert) — models are persisted only "
                    f"by workflow.core_workflow and promoted through the "
                    f"staged-reload gate")
        # Rule 2: generation-state assignment on a non-self object.
        if isinstance(node, (ast.Assign, ast.AugAssign)) \
                and rel_key not in _GEN_WRITE_OK:
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                if (isinstance(tgt, ast.Attribute)
                        and tgt.attr in _GEN_ATTRS
                        and not (isinstance(tgt.value, ast.Name)
                                 and tgt.value.id == "self")):
                    violations.append(
                        f"{filename}:{node.lineno}: assigns "
                        f"<obj>.{tgt.attr} — engine-server generation "
                        f"state swaps only inside "
                        f"server/engine_server.py (staged reload / "
                        f"rollback)")
        # Rule 3: refresh-package discipline.
        if in_refresh:
            name = None
            if isinstance(node, ast.Attribute):
                name = node.attr
            elif isinstance(node, ast.Name):
                name = node.id
            if name in _REFRESH_FORBIDDEN:
                violations.append(
                    f"{filename}:{node.lineno}: refresh/ references "
                    f"{name!r} — promotion goes through the serving "
                    f"server's staged-reload gate (POST /reload), never "
                    f"a daemon-side model write or validation shortcut")
    return violations


def check(root: Path | str | None = None) -> List[str]:
    root = Path(root) if root else Path(__file__).resolve().parents[1]
    pkg = root / "predictionio_tpu"
    violations: List[str] = []
    for path in sorted(pkg.rglob("*.py")):
        rel = _rel_key(path)
        in_refresh = path.parent.name == "refresh"
        in_fleet = _PROMOTE_LOOP_OK_PKG in path.parts
        violations.extend(check_source(
            path.read_text(encoding="utf-8"), str(path), rel, in_refresh,
            in_fleet))
    return violations


def main(argv: List[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    violations = check(argv[0] if argv else None)
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} refresh-lint violation(s).",
              file=sys.stderr)
        return 1
    print("lint_refresh: all model promotion rides the staged-reload gate.")
    return 0


if __name__ == "__main__":
    sys.exit(main())
