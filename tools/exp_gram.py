#!/usr/bin/env python
"""Micro-experiments for the ALS gather+gram redesign (scratch)."""
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

R1, R2 = 4, 20


def slope(fn, *args):
    def run(n):
        t0 = time.perf_counter()
        out = fn(jnp.int32(n), jnp.float32(0.0), *args)
        float(jnp.sum(out))
        return time.perf_counter() - t0
    run(R1)
    t1 = run(R1); t2 = run(R2)
    return (t2 - t1) / (R2 - R1) * 1e3


I, K = 59_047, 64
R, L = 20_000, 256          # one representative user bucket: 5.1M nnz slots
rng = np.random.default_rng(0)
Y = jnp.asarray(rng.standard_normal((I, K), dtype=np.float32))
idx = jnp.asarray((rng.zipf(1.25, size=(R, L)) % I).astype(np.int32))
idx_sorted = jnp.sort(idx, axis=1)
w = jnp.asarray(rng.random((R, L), dtype=np.float32))
G = Y[idx] * w[..., None]
NNZ = R * L
GB = NNZ * K * 4 / 1e9
GF = 2 * NNZ * K * K / 1e9


@jax.jit
def rep_gather(n, zero, Y, idx):
    def body(_, c):
        f = (Y + c * zero)[idx]
        return jnp.sum(f) * 1e-20
    return jax.lax.fori_loop(0, n, body, jnp.float32(0.0))


def rep_gram_mat(dtype):
    @jax.jit
    def f(n, zero, G):
        def body(_, c):
            g = (G + c * zero).astype(dtype)
            a = jax.lax.dot_general(g, g, (((1,), (1,)), ((0,), (0,))),
                                    preferred_element_type=jnp.float32)
            return jnp.sum(a) * 1e-20
        return jax.lax.fori_loop(0, n, body, jnp.float32(0.0))
    return f


def rep_fused(dtype):
    @jax.jit
    def f(n, zero, Y, idx, w):
        def body(_, c):
            g = ((Y + c * zero)[idx] * w[..., None]).astype(dtype)
            a = jax.lax.dot_general(g, g, (((1,), (1,)), ((0,), (0,))),
                                    preferred_element_type=jnp.float32)
            return jnp.sum(a) * 1e-20
        return jax.lax.fori_loop(0, n, body, jnp.float32(0.0))
    return f


# --- Pallas: Y resident in VMEM, per-row scalar-loop gather + MXU gram ---
TILE_R = 8


def _gk(idx_ref, w_ref, y_ref, a_ref, scratch):
    # idx/w: [TILE_R, L] (idx in SMEM), y: [I, K] VMEM-resident, a: [TILE_R,K,K]
    l = idx_ref.shape[1]
    for r in range(TILE_R):
        def body(j, _):
            scratch[j] = y_ref[idx_ref[r, j]]
            return 0
        jax.lax.fori_loop(0, l, body, 0)
        g = scratch[:] * w_ref[r][:, None]
        a_ref[r] = jax.lax.dot_general(
            g, scratch[:], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)


@jax.jit
def pallas_vmem_gather_gram(idx, w, y):
    r, l = idx.shape
    return pl.pallas_call(
        _gk,
        grid=(r // TILE_R,),
        in_specs=[
            pl.BlockSpec((TILE_R, l), lambda i: (i, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((TILE_R, l), lambda i: (i, 0)),
            pl.BlockSpec((y.shape[0], y.shape[1]), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((TILE_R, K, K), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((r, K, K), jnp.float32),
        scratch_shapes=[pltpu.VMEM((l, K), jnp.float32)],
    )(idx, w, y)


@jax.jit
def rep_pallas_vmem(n, zero, idx, w, y):
    def body(_, c):
        a = pallas_vmem_gather_gram(idx, w, y + c * zero)
        return jnp.sum(a) * 1e-20
    return jax.lax.fori_loop(0, n, body, jnp.float32(0.0))


def main():
    which = sys.argv[1:] or ["gather", "gram", "fused", "pallas"]
    if "gather" in which:
        ms = slope(rep_gather, Y, idx)
        print(f"gather zipf      : {ms:8.2f} ms  {GB/ms*1e3:7.1f} GB/s")
        ms = slope(rep_gather, Y, idx_sorted)
        print(f"gather sorted    : {ms:8.2f} ms  {GB/ms*1e3:7.1f} GB/s")
    if "gram" in which:
        ms = slope(rep_gram_mat(jnp.float32), G)
        print(f"gram mat f32     : {ms:8.2f} ms  {GF/ms*1e3/1e3:7.2f} TF/s")
        ms = slope(rep_gram_mat(jnp.bfloat16), G)
        print(f"gram mat bf16    : {ms:8.2f} ms  {GF/ms*1e3/1e3:7.2f} TF/s")
    if "fused" in which:
        ms = slope(rep_fused(jnp.float32), Y, idx, w)
        print(f"gather+gram f32  : {ms:8.2f} ms  {GF/ms*1e3/1e3:7.2f} TF/s")
        ms = slope(rep_fused(jnp.bfloat16), Y, idx, w)
        print(f"gather+gram bf16 : {ms:8.2f} ms  {GF/ms*1e3/1e3:7.2f} TF/s")
        ms = slope(rep_fused(jnp.float32), Y, idx_sorted, w)
        print(f"gather+gram srt32: {ms:8.2f} ms  {GF/ms*1e3/1e3:7.2f} TF/s")
    if "pallas" in which:
        ms = slope(rep_pallas_vmem, idx, w, Y)
        print(f"pallas vmem-gthr : {ms:8.2f} ms  {GF/ms*1e3/1e3:7.2f} TF/s "
              f"({NNZ/ms*1e3/1e9:5.2f} Gnnz/s)")


if __name__ == "__main__":
    main()
