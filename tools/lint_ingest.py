#!/usr/bin/env python
"""Static check: the ingest plane stays batched and the segment files
stay behind their one reader/writer.

ISSUE 17 builds a bulk ingest path (``POST /batch/events.json`` →
``Events.create_batch`` — ONE storage round-trip per batch) and an
append-only columnar segment store with a CRC-block wire format and a
crash-safe manifest.  The two regressions such a plane invites are
structural, so this lint makes them tier-1 failures:

1. **No per-row ingest in the serving plane** — inside
   ``predictionio_tpu/server/`` and ``predictionio_tpu/data/webhooks/``:

   - any ``<x>.create_event(...)`` call is banned outright (that is the
     SDK's single-row client verb; server-side code coalesces through
     the batched fold / ``create_batch``), and
   - an ``.insert(...)`` call on an events repository — the direct
     ``get_events().insert(...)`` chain or a variable bound from
     ``get_events()`` — is banned *lexically inside a loop or
     comprehension*.  A row-at-a-time insert loop silently reintroduces
     N round-trips, N journal records, and N segment tees per burst;
     the batch entry points exist precisely so this never comes back.

2. **Segment files are opened only by ``data/columnar.py``** — a raw
   ``open(...)`` (or ``.open(...)``) call whose literal arguments
   mention the ``.seg`` suffix is banned everywhere else.  The segment
   wire format (magic, CRC-framed blocks, torn-tail recovery, manifest
   commit point) has exactly one implementation; a second ad-hoc reader
   or writer would fork the crash-safety contract.

Usage: ``python tools/lint_ingest.py [root]`` — prints violations and
exits non-zero when any exist.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List

# Directories whose modules form the serving-plane ingest path (rule 1):
# (parent-dir name, ...) membership is checked against path.parts.
_INGEST_PLANE_DIRS = ("server", "webhooks")
# The one module allowed to open segment files (rule 2).
_SEGMENT_OK = ("data", "columnar.py")
_SEGMENT_SUFFIX = ".seg"

_LOOP_NODES = (ast.For, ast.AsyncFor, ast.While, ast.ListComp,
               ast.SetComp, ast.DictComp, ast.GeneratorExp)


def _rel_key(path: Path) -> tuple:
    return (path.parent.name, path.name)


def _events_bound_names(tree: ast.AST) -> set:
    """Variables assigned from a ``get_events()`` call anywhere in the
    module — ``repo = storage.get_events(); repo.insert(...)`` must not
    dodge the loop rule by splitting the chain."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            fn = node.value.func
            if isinstance(fn, ast.Attribute) and fn.attr == "get_events":
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        names.add(tgt.id)
    return names


def _is_events_insert(call: ast.Call, bound: set) -> bool:
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr == "insert"):
        return False
    if isinstance(f.value, ast.Call) \
            and isinstance(f.value.func, ast.Attribute) \
            and f.value.func.attr == "get_events":
        return True  # direct get_events().insert chain
    return isinstance(f.value, ast.Name) and f.value.id in bound


def _row_calls_in_loops(tree: ast.AST, bound: set) -> List[tuple]:
    """``(lineno, kind)`` for per-row ingest calls lexically inside a
    loop/comprehension (rule 1's loop half)."""
    out: List[tuple] = []

    def walk(node: ast.AST, in_loop: bool) -> None:
        for child in ast.iter_child_nodes(node):
            child_in_loop = in_loop or isinstance(child, _LOOP_NODES)
            if in_loop and isinstance(child, ast.Call) \
                    and _is_events_insert(child, bound):
                out.append((child.lineno, "insert"))
            # a nested function body resets the loop context — a helper
            # DEFINED in a loop is not itself an ingest loop
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                walk(child, False)
            else:
                walk(child, child_in_loop)

    walk(tree, False)
    return out


def _mentions_segment_suffix(node: ast.AST) -> bool:
    """Any string literal under ``node`` (plain or f-string part)
    containing the segment suffix."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str) \
                and _SEGMENT_SUFFIX in sub.value:
            return True
    return False


def _is_open_call(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name) and f.id == "open":
        return True
    return isinstance(f, ast.Attribute) and f.attr == "open"


def check_source(source: str, filename: str, rel_key: tuple,
                 in_ingest_plane: bool) -> List[str]:
    violations: List[str] = []
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as e:
        return [f"{filename}:{e.lineno}: unparseable: {e.msg}"]

    segment_ok = rel_key == _SEGMENT_OK
    if in_ingest_plane:
        bound = _events_bound_names(tree)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "create_event":
                violations.append(
                    f"{filename}:{node.lineno}: per-row create_event() in "
                    f"the ingest plane — bursts coalesce through the "
                    f"batched fold (POST /batch/events.json → "
                    f"Events.create_batch), never a single-row client "
                    f"verb")
        for lineno, _ in _row_calls_in_loops(tree, bound):
            violations.append(
                f"{filename}:{lineno}: events .insert() inside a loop — "
                f"a row-at-a-time insert loop pays N round-trips and N "
                f"journal records per burst; use create_batch / "
                f"insert_batch (one group commit)")
    if not segment_ok:
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and _is_open_call(node) \
                    and any(_mentions_segment_suffix(a)
                            for a in list(node.args)
                            + [kw.value for kw in node.keywords]):
                violations.append(
                    f"{filename}:{node.lineno}: raw open() on a "
                    f"'{_SEGMENT_SUFFIX}' segment file — the CRC-framed "
                    f"wire format and torn-tail recovery live only in "
                    f"data/columnar.py; read segments through "
                    f"SegmentStore")
    return violations


def check(root: Path | str | None = None) -> List[str]:
    root = Path(root) if root else Path(__file__).resolve().parents[1]
    pkg = root / "predictionio_tpu"
    violations: List[str] = []
    for path in sorted(pkg.rglob("*.py")):
        in_plane = any(part in _INGEST_PLANE_DIRS for part in path.parts)
        violations.extend(check_source(
            path.read_text(encoding="utf-8"), str(path), _rel_key(path),
            in_plane))
    return violations


def main(argv: List[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    violations = check(argv[0] if argv else None)
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} ingest-lint violation(s).",
              file=sys.stderr)
        return 1
    print("lint_ingest: ingest stays batched; segment files stay behind "
          "SegmentStore.")
    return 0


if __name__ == "__main__":
    sys.exit(main())
