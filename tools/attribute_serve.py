#!/usr/bin/env python
"""Attribute served /queries.json latency to its dominant pipeline stage.

The training side has had this since PR 3 (``tools/attribute_gap.py``
reads the step timeline and names the next perf attack); ISSUE 9 gives
the SERVING side the same one-command verdict.  A request now crosses
admission queue → batch window → bind → dispatch (retrieval inside) →
serialize → shed check, and every stage lands in the
``pio_serve_stage_ms{stage}`` histogram family plus the optional
``PIO_REQUEST_LOG`` wide-event JSONL.  This tool reads either and
prints, per stage, its share of the served wall — and the recommended
attack for the dominant one.

Usage::

    # against a live engine server's exposition
    python tools/attribute_serve.py http://127.0.0.1:8000/metrics
    # against a saved exposition
    python tools/attribute_serve.py metrics.txt
    # against a PIO_REQUEST_LOG wide-event file (per-request p50/p95,
    # plus the stage-sum vs server-total reconciliation)
    python tools/attribute_serve.py requests.jsonl

``retrieval`` is a sub-stage of ``dispatch`` and is excluded from the
wall-share denominator; it is reported indented under dispatch with its
own attack when IT dominates the dispatch.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

STAGES = ("ingress", "queue_wait", "batch_wait", "bind", "cache",
          "dispatch", "resume", "retrieval", "serialize", "shed_check")
# Additive stages: their sum ≈ the request's total server wall.
WALL_STAGES = ("ingress", "queue_wait", "batch_wait", "bind", "cache",
               "dispatch", "resume", "serialize", "shed_check")
# The subset the X-PIO-Server-Ms attestation CONTAINS (the header is
# read before the response is written, so serialize lies outside it).
ATTESTED_STAGES = ("ingress", "queue_wait", "batch_wait", "bind", "cache",
                   "dispatch", "resume", "shed_check")

ATTACKS = {
    "ingress": "transport receipt → bind (body read, trace setup, "
               "routing) — per-request handler-thread work; if it "
               "dominates, payloads are huge or handler threads are "
               "starved for the GIL",
    "resume": "post-dispatch thread wake-up — GIL/thread contention as "
              "handler threads resume; fewer concurrent clients per "
              "instance (scale out) or larger batches (fewer wake-up "
              "herds) reduce it",
    "queue_wait": "offered load > capacity — scale out (the /ready SLO "
                  "signal + pio_slo_burn_rate say when the LB should "
                  "rotate instances); raising PIO_QUEUE_DEPTH only "
                  "trades 429s for queueing latency",
    "batch_wait": "the gather window is too wide for this traffic — "
                  "lower PIO_BATCH_P99_TARGET_MS (the autotuner shrinks "
                  "the window to meet it) or PIO_BATCH_WINDOW_MS "
                  "directly; a lone-client stream should already skip "
                  "the window",
    "bind": "query binding — simplify the query_class schema or trim "
            "payload size (bind runs per-request on the handler thread)",
    "cache": "result-cache canonicalization + lookup — sub-millisecond "
             "by design; if it dominates, the traffic is hitting (good: "
             "queue/dispatch are gone from those requests) or queries "
             "are huge (canonicalization is O(payload)); check "
             "pio_result_cache_hit_rate before reading further rows",
    "dispatch": "model execution — grow PIO_BATCH_MAX to amortize more "
                "requests per dispatch (check HBM headroom first), or "
                "attack the model itself; if retrieval dominates the "
                "dispatch (below), attack retrieval instead",
    "retrieval": "retrieval rung — escalate: IVF at train time "
                 "(PIO_IVF=on) or mesh-sharded exact "
                 "(PIO_SERVE_SHARD_ABOVE); pio_retrieval_ms{rung} and "
                 "candidates-per-query name the rung to fix",
    "serialize": "result serialization — trim result size (num / "
                 "payload fields); serialization runs per-request on "
                 "the response path",
    "shed_check": "transport bookkeeping — negligible by design; if it "
                  "dominates, traffic is near-zero or stages are "
                  "missing from the capture",
}

_HIST_RE = re.compile(
    r'^pio_serve_stage_ms_(sum|count)\{stage="([^"]+)"\}\s+(\S+)')


def _read_source(src: str) -> str:
    if src.startswith(("http://", "https://")):
        from urllib.request import urlopen

        url = src if "/metrics" in src else src.rstrip("/") + "/metrics"
        with urlopen(url, timeout=10) as resp:
            return resp.read().decode()
    if src == "-":
        return sys.stdin.read()
    with open(src, encoding="utf-8") as f:
        return f.read()


def parse_metrics(text: str) -> Dict[str, Dict[str, float]]:
    """{stage: {"sum": ms, "count": n}} from a text exposition."""
    out: Dict[str, Dict[str, float]] = {}
    for line in text.splitlines():
        line = line.split(" # ", 1)[0].strip()  # drop exemplar suffixes
        m = _HIST_RE.match(line)
        if not m:
            continue
        kind, stage, raw = m.groups()
        try:
            v = float(raw)
        except ValueError:
            continue
        out.setdefault(stage, {"sum": 0.0, "count": 0.0})[kind] = v
    return out


def parse_request_log(text: str) -> List[Dict[str, Any]]:
    """Wide-event JSONL rows (unparseable lines skipped)."""
    rows = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            doc = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(doc, dict) and isinstance(doc.get("stages"), dict):
            rows.append(doc)
    return rows


def _percentile(vals: List[float], q: float) -> float:
    if not vals:
        return 0.0
    s = sorted(vals)
    return s[min(int(q * len(s)), len(s) - 1)]


def attribute_metrics(stages: Dict[str, Dict[str, float]]
                      ) -> Optional[Dict[str, Any]]:
    """Mean-ms attribution from the histogram family."""
    means = {}
    for stage in STAGES:
        row = stages.get(stage)
        if row and row.get("count"):
            means[stage] = row["sum"] / row["count"]
    return _attribution(means, {s: stages[s]["count"]
                                for s in means}) if means else None


def attribute_log(rows: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Per-request attribution from the wide-event log, plus the
    stage-sum vs server-total reconciliation the acceptance pins."""
    if not rows:
        return None
    per_stage: Dict[str, List[float]] = {}
    sums, totals = [], []
    attested_sums, attested = [], []
    for doc in rows:
        st = doc["stages"]
        for stage, ms in st.items():
            if stage in STAGES:
                per_stage.setdefault(stage, []).append(float(ms))
        wall = sum(float(st.get(s, 0.0)) for s in WALL_STAGES)
        sums.append(wall)
        if isinstance(doc.get("totalMs"), (int, float)):
            totals.append(float(doc["totalMs"]))
        if isinstance(doc.get("serverMs"), (int, float)):
            attested.append(float(doc["serverMs"]))
            attested_sums.append(sum(
                float(st.get(s, 0.0)) for s in ATTESTED_STAGES))
    means = {s: sum(v) / len(v) for s, v in per_stage.items()}
    out = _attribution(means, {s: len(v) for s, v in per_stage.items()})
    out["p50"] = {s: round(_percentile(v, 0.5), 3)
                  for s, v in sorted(per_stage.items())}
    out["p95"] = {s: round(_percentile(v, 0.95), 3)
                  for s, v in sorted(per_stage.items())}
    out["requests"] = len(rows)
    if totals:
        p50_sum = _percentile(sums, 0.5)
        p50_total = _percentile(totals, 0.5)
        out["reconciliation"] = {
            "stage_sum_p50_ms": round(p50_sum, 3),
            "total_p50_ms": round(p50_total, 3),
            "ratio": (round(p50_sum / p50_total, 3) if p50_total else None),
        }
    if attested:
        # The acceptance reconciliation: the stages the X-PIO-Server-Ms
        # wall contains, vs that attested wall — within 10% at p50.
        p50_att_sum = _percentile(attested_sums, 0.5)
        p50_att = _percentile(attested, 0.5)
        out.setdefault("reconciliation", {}).update({
            "attested_stage_sum_p50_ms": round(p50_att_sum, 3),
            "server_attested_p50_ms": round(p50_att, 3),
            "attested_ratio": (round(p50_att_sum / p50_att, 3)
                               if p50_att else None),
        })
    return out


def _attribution(means: Dict[str, float],
                 counts: Dict[str, float]) -> Dict[str, Any]:
    wall = {s: m for s, m in means.items() if s in WALL_STAGES}
    total = sum(wall.values())
    shares = {s: (m / total if total else 0.0) for s, m in wall.items()}
    dominant = max(shares, key=lambda s: shares[s]) if shares else None
    out: Dict[str, Any] = {
        "mean_ms": {s: round(m, 3) for s, m in sorted(means.items())},
        "counts": {s: int(c) for s, c in sorted(counts.items())},
        "wall_share": {s: round(v, 4) for s, v in sorted(shares.items())},
        "dominant": dominant,
        "dominant_share": round(shares[dominant], 4) if dominant else None,
        "attack": ATTACKS[dominant] if dominant else None,
    }
    # retrieval ⊂ dispatch: when the sub-stage is most of its parent,
    # the actionable attack is the retrieval one.
    r, d = means.get("retrieval"), means.get("dispatch")
    if r is not None and d:
        out["retrieval_share_of_dispatch"] = round(min(r / d, 1.0), 4)
        if dominant == "dispatch" and r / d >= 0.5:
            out["attack"] = ATTACKS["retrieval"]
            out["attack_reason"] = (
                "retrieval is ≥50% of the dominant dispatch stage")
    return out


def render(result: Dict[str, Any]) -> str:
    lines = []
    n = result.get("requests") or max(result["counts"].values(), default=0)
    lines.append(f"serving waterfall over {n} request(s):")
    for stage in STAGES:
        m = result["mean_ms"].get(stage)
        if m is None:
            continue
        share = result["wall_share"].get(stage)
        suffix = (f"  ({share * 100:5.1f}% of wall)"
                  if share is not None else "   (⊂ dispatch)")
        p50 = result.get("p50", {}).get(stage)
        p = f"  p50 {p50:g}ms" if p50 is not None else ""
        lines.append(f"  {stage:<11} mean {m:8.3f} ms{p}{suffix}")
    if result.get("retrieval_share_of_dispatch") is not None:
        lines.append(
            f"  retrieval is {result['retrieval_share_of_dispatch'] * 100:.1f}%"
            " of the dispatch stage")
    rec = result.get("reconciliation")
    if rec:
        ratio = rec.get("ratio")
        if "stage_sum_p50_ms" in rec:
            lines.append(
                f"  stage-sum p50 {rec['stage_sum_p50_ms']:g} ms vs "
                f"request total p50 {rec['total_p50_ms']:g} ms"
                + (f" (ratio {ratio:.2f})" if ratio is not None else ""))
        aratio = rec.get("attested_ratio")
        if "attested_stage_sum_p50_ms" in rec:
            lines.append(
                f"  attested-stage sum p50 "
                f"{rec['attested_stage_sum_p50_ms']:g} ms vs "
                f"X-PIO-Server-Ms p50 {rec['server_attested_p50_ms']:g} ms"
                + (f" (ratio {aratio:.2f})" if aratio is not None else ""))
    lines.append(f"dominant: {result['dominant']} "
                 f"({(result['dominant_share'] or 0) * 100:.1f}% of wall)")
    lines.append(f"attack: {result['attack']}")
    if result.get("attack_reason"):
        lines.append(f"  ({result['attack_reason']})")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="attribute served latency to its dominant stage")
    ap.add_argument("source",
                    help="a /metrics URL (or server base URL), a saved "
                         "exposition file, a PIO_REQUEST_LOG .jsonl, or "
                         "'-' for stdin")
    ap.add_argument("--json", action="store_true",
                    help="emit the attribution as JSON instead of text")
    args = ap.parse_args(argv)

    text = _read_source(args.source)
    rows = parse_request_log(text)
    if rows:
        result = attribute_log(rows)
    else:
        result = attribute_metrics(parse_metrics(text))
    if result is None:
        print("no pio_serve_stage_ms data (drive /queries.json traffic "
              "first, or point this at PIO_REQUEST_LOG output)",
              file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(result, indent=2))
    else:
        print(render(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
