#!/usr/bin/env python
"""One-command model-quality verdict from a ``/quality.json`` document.

The perf side has ``tools/attribute_gap.py`` (training) and
``tools/attribute_serve.py`` (serving latency); ISSUE 11 gives
prediction QUALITY the same one-command read.  Feed it a live engine
server (or dashboard) base URL, or a saved document, and it prints the
dominant quality issue plus the recommended response:

Usage::

    # against a live engine server
    python tools/attribute_quality.py http://127.0.0.1:8000
    # against a saved /quality.json document
    python tools/attribute_quality.py quality.json

Verdict order (worst wins): shadow divergence → recall regression
(ISSUE 16 — with the specific knob named from the miss-attribution
gauges: cell-miss dominant → widen ``PIO_IVF_NPROBE``,
shortlist-saturation dominant → raise ``PIO_PQ_RERANK``, neither →
rebuild the index) → drift tripped → reporting-only scorecard → falling
online hit-rate → diversity collapse → insufficient samples (cold app:
pass-through, NEVER a gate) → healthy.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional


def load_quality(source: str) -> Dict[str, Any]:
    if source.startswith(("http://", "https://")):
        from urllib.request import urlopen

        url = source.rstrip("/")
        if not url.endswith("/quality.json"):
            url += "/quality.json"
        with urlopen(url, timeout=10) as resp:
            doc = json.loads(resp.read())
    else:
        with open(source, encoding="utf-8") as f:
            doc = json.load(f)
    # a dashboard's /quality.json (live or saved) wraps the fleet-merged
    # doc — unwrap it on both paths
    if "merged" in doc and isinstance(doc.get("merged"), dict):
        return doc["merged"]
    return doc


def _fmt(v: Optional[float], nd: int = 3) -> str:
    return "-" if v is None else f"{v:.{nd}f}"


def verdict_lines(doc: Dict[str, Any]) -> List[str]:
    """The printed report (pure function — unit-tested)."""
    if not doc.get("enabled", False):
        return ["quality layer disabled (PIO_QUALITY=off) — no verdict; "
                "enable it to observe what this server serves, not just "
                "how fast"]
    out: List[str] = []
    drift = doc.get("drift") or {}
    shadow = doc.get("shadow") or {}
    feedback = doc.get("feedback") or {}
    diversity = doc.get("diversity") or {}
    gate = doc.get("gate") or {}
    out.append(f"generation {doc.get('generation')} — verdict: "
               f"{doc.get('verdict')}"
               + (" [GATE=ROLLBACK]" if gate.get("rollback") else ""))
    psi = drift.get("psi") or {}
    out.append(f"  drift: psi fast={_fmt(psi.get('fast'))} "
               f"slow={_fmt(psi.get('slow'))} "
               f"(threshold {drift.get('threshold')}, "
               f"n={drift.get('nFast', 0)}/{drift.get('nSlow', 0)})")
    out.append(f"  shadow: overlap mean={_fmt(shadow.get('overlapMean'), 2)}"
               f" p10={_fmt(shadow.get('overlapP10'), 2)} over "
               f"{shadow.get('scored', 0)} pairs"
               + (" (no active canary)" if not shadow.get("active")
                  else ""))
    recall = doc.get("recall") or {}
    r_rungs = recall.get("rungs") or {}
    if recall.get("enabled") and r_rungs:
        rows = ", ".join(
            f"{rung}: {_fmt(row.get('recallFast'))}/"
            f"{_fmt(row.get('baseline'))}"
            + ("!" if row.get("tripped") else "")
            for rung, row in sorted(r_rungs.items()))
        out.append(f"  recall@{recall.get('k')}: {rows} "
                   f"(live/baseline per rung; sample "
                   f"{recall.get('sample')})")
    gens = feedback.get("generations") or {}

    def _gen_key(kv):
        # keys are STRINGS of generation numbers: "10" must sort after
        # "9", or old-vs-new comparisons silently invert
        try:
            return (0, int(kv[0]))
        except (TypeError, ValueError):
            return (1, 0)

    if gens:
        rows = ", ".join(
            f"g{g}: {row.get('hitRate')} ({row.get('hits')}h/"
            f"{row.get('misses')}m)"
            for g, row in sorted(gens.items(), key=_gen_key))
        out.append(f"  online hit-rate: {rows}")

    # -- the dominant issue + attack ---------------------------------------
    if shadow.get("divergent"):
        out.append("DOMINANT: shadow divergence — the canary generation "
                   "ranks differently from the generation it replaces "
                   f"(overlap {_fmt(shadow.get('overlapMean'), 2)} < "
                   f"{shadow.get('minOverlap')}).")
        out.append("ATTACK: let the gate roll back (it will, with "
                   "PIO_QUALITY_GATE=on); inspect the refresh window — a "
                   "warm-start over a skewed delta is the usual cause "
                   "(pio_refresh_runs_total{result}).")
    elif recall.get("tripped") and not recall.get("reportingOnly"):
        bad = [(rung, row) for rung, row in sorted(r_rungs.items())
               if row.get("tripped")]
        rungs_s = ", ".join(
            f"{rung} {_fmt(row.get('recallFast'))} vs baseline "
            f"{_fmt(row.get('baseline'))}" for rung, row in bad)
        out.append("DOMINANT: retrieval recall regression — the "
                   "approximate rung(s) no longer return the true top-k "
                   f"this generation's own scorecard promises ({rungs_s}"
                   f", tolerance {recall.get('tolerance')}).")
        # The miss-attribution gauges name the knob: a missed true item
        # whose cell was probed fell off the PQ rerank shortlist; one
        # whose cell was NOT probed never entered the race.
        cell = max((row.get("cellMiss") or 0.0) for _, row in bad)
        shortlist = max((row.get("shortlistSaturation") or 0.0)
                        for _, row in bad)
        if cell > shortlist and cell > 0.05:
            out.append(f"ATTACK: cell-miss dominant ({cell:.0%} of true "
                       f"top-k in unprobed cells) — widen "
                       f"PIO_IVF_NPROBE; the probe ring is too narrow "
                       f"for this corpus.")
        elif shortlist > cell and shortlist > 0.05:
            out.append(f"ATTACK: shortlist saturation dominant "
                       f"({shortlist:.0%} of true top-k probed but "
                       f"outside the rerank shortlist) — raise "
                       f"PIO_PQ_RERANK; quantization error is pushing "
                       f"true items below the cut.")
        else:
            out.append("ATTACK: neither cell-miss nor shortlist "
                       "saturation dominates — the index/codes "
                       "themselves no longer fit the corpus (skewed "
                       "delta-refresh is the usual cause); rebuild by "
                       "retraining.  Inside a canary window the gate "
                       "rolls back first.")
    elif drift.get("tripped"):
        out.append("DOMINANT: score-distribution drift — serving scores "
                   "no longer match the generation's own training-time "
                   "scorecard on both windows.")
        out.append("ATTACK: if inside a canary window the gate rolls "
                   "back; otherwise retrain (the model is stale for "
                   "current traffic) and check fold-in share "
                   "(pio_quality_fold_in_share) — heavy fold-in traffic "
                   "scores through a different path than the baseline.")
    elif drift.get("reportingOnly"):
        out.append(f"DOMINANT: no trusted scorecard "
                   f"({drift.get('reason')}) — drift detection is "
                   "reporting-only and the gate can only act on shadow "
                   "divergence.")
        out.append("ATTACK: retrain with this build (scorecards ride the "
                   "wrapper pickle); a fingerprint_mismatch means the "
                   "corpus was mutated after training — find who.")
    else:
        hit_rates = [row.get("hitRate") for _, row in
                     sorted(gens.items(), key=_gen_key)
                     if row.get("hitRate") is not None]
        top_share = diversity.get("topItemShare")
        if len(hit_rates) >= 2 and hit_rates[-1] < 0.5 * hit_rates[0]:
            out.append("DOMINANT: online hit-rate collapsed across "
                       f"generations ({hit_rates[0]} → {hit_rates[-1]}) "
                       "with score distributions healthy — the model "
                       "drifted from USERS, not from itself.")
            out.append("ATTACK: shorten the refresh cadence or switch "
                       "the daemon to trigger mode "
                       "(PIO_REFRESH_TRIGGER_STALENESS_S / "
                       "_DELTA_COUNT).")
        elif top_share is not None and top_share > 0.5:
            out.append(f"DOMINANT: diversity collapse — one item takes "
                       f"{top_share:.0%} of served slots.")
            out.append("ATTACK: inspect the last warm-start (a collapsed "
                       "embedding table serves one popular row); "
                       "PIO_REFRESH_MAX_DELTA_FRACTION gates how much "
                       "delta a continuation may absorb.")
        elif doc.get("verdict") == "insufficient":
            out.append("DOMINANT: not enough sampled predictions for a "
                       "verdict (cold app) — pass-through by design; "
                       "the gate never blocks on silence.")
            out.append("ATTACK: none needed; raise PIO_QUALITY_SAMPLE "
                       "if this server has traffic but samples too "
                       "thinly.")
        else:
            out.append("DOMINANT: nothing — score distribution, shadow "
                       "overlap, and feedback all healthy.")
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("source",
                    help="engine-server base URL, or a saved "
                         "/quality.json path")
    args = ap.parse_args(argv)
    try:
        doc = load_quality(args.source)
    except Exception as e:  # noqa: BLE001 - CLI surface
        print(f"[error] cannot load {args.source}: {e}", file=sys.stderr)
        return 1
    for line in verdict_lines(doc):
        print(line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
