#!/usr/bin/env python
"""Round-4 ALS measurement: einsum path vs natural-layout Pallas path.

Preps ML-25M-shape inputs once on the device, then slope-times the fused
training loop under both gram/solve configurations and phase-profiles the
winner.  One process so the (uncacheable on this backend) prep compile is
paid once.
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from predictionio_tpu.models.als import (
    ALSConfig, prepare_als_inputs, train_als_prepared,
)

SCALE = float(os.environ.get("PIO_BENCH_SCALE", "1.0"))
N_USERS = max(64, int(162_541 * SCALE))
N_ITEMS = max(64, int(59_047 * SCALE))
N_RATINGS = max(4096, int(25_000_000 * SCALE))
RANK = 64
I1, I2 = 2, 12


def synth(seed=0):
    rng = np.random.default_rng(seed)
    users = rng.integers(0, N_USERS, N_RATINGS)
    items = (rng.zipf(1.25, size=N_RATINGS) % N_ITEMS).astype(np.int64)
    ratings = (rng.integers(1, 11, N_RATINGS) * 0.5).astype(np.float32)
    return users, items, ratings


def main():
    users, items, ratings = synth()
    ratings = ratings + np.float32((time.time_ns() % 997) * 1e-6)
    cfg0 = ALSConfig(rank=RANK, iterations=I1, reg=0.01, seed=1)
    t0 = time.perf_counter()
    du = jnp.asarray(users.astype(np.int32))
    di = jnp.asarray(items.astype(np.int32))
    dr = jnp.asarray(ratings)
    float(jnp.sum(dr))
    print(f"h2d {time.perf_counter()-t0:.1f}s", flush=True)
    t0 = time.perf_counter()
    inputs = prepare_als_inputs(du, di, dr, N_USERS, N_ITEMS, cfg0)
    float(jnp.sum(inputs.uf0))
    print(f"prep {time.perf_counter()-t0:.1f}s", flush=True)

    def run(iters, **kw):
        cfg = ALSConfig(rank=RANK, iterations=iters, reg=0.01, seed=1, **kw)
        t0 = time.perf_counter()
        m = train_als_prepared(inputs, cfg)
        float(jnp.sum(m.user_factors))
        return time.perf_counter() - t0, m

    results = {}
    variants = [
        ("pallas_lu", dict(use_pallas=True, solver="lu")),
        ("pallas_gj", dict(use_pallas=True, solver="gj")),
        ("einsum_lu", dict(use_pallas=False, solver="lu")),
    ]
    ref_model = None
    for name, kw in variants:
        t0 = time.perf_counter()
        _, m = run(I1, **kw)
        compile_s = time.perf_counter() - t0
        t1, _ = run(I1, **kw)
        t2, m = run(I2, **kw)
        per_iter = (t2 - t1) / (I2 - I1) * 1e3
        results[name] = {"per_iter_ms": round(per_iter, 1),
                         "compile_s": round(compile_s, 1)}
        print(f"{name}: {per_iter:.1f} ms/iter (compile {compile_s:.0f}s)",
              flush=True)
        if ref_model is None:
            ref_model = m
        else:
            d = float(jnp.max(jnp.abs(m.user_factors - ref_model.user_factors)))
            s = float(jnp.max(jnp.abs(ref_model.user_factors)))
            results[name]["max_dev_vs_first"] = round(d / s, 5)
            print(f"  rel dev vs pallas_lu: {d/s:.2e}", flush=True)

    # Phase profile of the winner (same machinery as bench.py).
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    phases = bench.phase_profile(inputs)
    results["phase_ms_pallas"] = phases
    print(json.dumps(results), flush=True)


if __name__ == "__main__":
    main()
