#!/usr/bin/env python
"""Where does the ALS cold-start compile time go? (round-4 item 4)

Times, separately: the device-prep build program per side, and the fused
training loop — all via AOT lower().compile() from ShapeDtypeStructs (no
data, no execution), which is exactly the cold cost a first `pio train`
pays on this backend (no persistent compile cache).
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from predictionio_tpu.models import als as als_lib
from predictionio_tpu.ops import device_prep
from tools.als_hlo import N_ITEMS, N_RATINGS, N_USERS, RANK, host_plan, \
    plan_shapes, synth


def main():
    users, items = synth()
    cfg = als_lib.ALSConfig(rank=RANK, iterations=2, reg=0.01, seed=1,
                            max_block_floats=int(os.environ.get(
                                "PIO_ALS_MBF", str(1 << 27))))
    S = jax.ShapeDtypeStruct

    for side, ids, n in (("user", users, N_USERS), ("item", items, N_ITEMS)):
        plan = host_plan(ids, n, cfg)
        t0 = time.perf_counter()
        jax.jit(device_prep.build_buckets, static_argnames=("plan",)).lower(
            S((N_RATINGS,), jnp.int32), S((N_RATINGS,), jnp.int32),
            S((N_RATINGS,), jnp.float32), plan=plan).compile()
        print(f"prep[{side}] compile: {time.perf_counter()-t0:.0f}s "
              f"(buckets={len(plan.bounds)}, "
              f"chunks={sum(len(c) for c in plan.plain_chunks)}"
              f"+{max(len(plan.split_chunks), 1 if plan.split_len else 0)})",
              flush=True)

    up, uk = plan_shapes(host_plan(users, N_USERS, cfg))
    ip, ik = plan_shapes(host_plan(items, N_ITEMS, cfg))
    t0 = time.perf_counter()
    jax.jit(als_lib._train_loop, static_argnames=(
        "kinds", "pallas_flags", "implicit", "gram_dtype", "solver",
        "factor_shardings")).lower(
        S((N_USERS, RANK), jnp.float32), S((N_ITEMS, RANK), jnp.float32),
        up, ip, S((), jnp.float32), S((), jnp.float32), S((), jnp.int32),
        kinds=(uk, ik),
        pallas_flags=(tuple(True for _ in uk), tuple(True for _ in ik)),
        implicit=False, gram_dtype="bfloat16", solver="lu").compile()
    print(f"train_loop compile: {time.perf_counter()-t0:.0f}s "
          f"(bucket steps: {len(uk)}+{len(ik)})", flush=True)


if __name__ == "__main__":
    main()
