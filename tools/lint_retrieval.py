#!/usr/bin/env python
"""Static check: serving reaches the item corpus ONLY via the facade.

ISSUE 8 built ``predictionio_tpu/retrieval/`` — host/device/chunked/
mesh-sharded/IVF routing, per-generation jit+staging caches, retrieval
metrics, and the IVF generation-fingerprint tripwire — and rewired every
template's serving path through it.  That consolidation only stays true
if nothing regresses it: a NEW template (or a refactor) that calls
``ops.topk.top_k_scores`` directly silently forfeits the host fast path,
the compiled-program menu, corpus staging reuse, IVF, sharding, AND the
``pio_retrieval_*`` metrics — and re-grows the per-template routing
forks this PR deleted.  This lint locks the invariant in (same pattern
as ``tools/lint_dispatch.py``; a tier-1 test runs it in CI):

1. No module under ``predictionio_tpu/templates/``, ``server/``, or
   ``serving/`` may import ``predictionio_tpu.ops.topk`` or
   ``predictionio_tpu.ops.pallas_kernels`` (the raw primitives are
   facade internals there).
2. No such module may CALL a retrieval primitive —
   ``top_k_scores`` / ``chunked_top_k`` / ``sharded_top_k`` /
   ``host_top_k`` / ``fused_topk`` / ``fused_topk_pallas`` — by any
   name-or-attribute spelling.
3. Every ``templates/*/engine.py`` that uses the facade's
   :class:`Retriever` must hold it via ``cached_retriever`` (the
   weak-keyed per-generation cache): constructing ``Retriever(...)``
   outside a ``cached_retriever`` build lambda re-stages corpus copies
   and re-traces jit programs per call site.

The allowed homes of the primitives stay ``predictionio_tpu/retrieval/``
and ``predictionio_tpu/ops/`` (and ``models/``, which are training-side
substrate, not serving handlers).

Usage: ``python tools/lint_retrieval.py [root]`` — prints violations and
exits non-zero when any exist.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List

# Directories whose modules are the serving surface (rule scope).
_SCOPES = ("templates", "server", "serving")
# Modules that are facade internals — importing them from the serving
# surface is rule 1's violation.  ``retrieval.pq`` joins the list in
# ISSUE 13: codebooks, LUT builders and PQ searches are reachable only
# through the facade (``Retriever.topk`` / ``build_train_pq``), so the
# fingerprint tripwire and re-rank policy can never be side-stepped.
_BANNED_MODULES = ("predictionio_tpu.ops.topk",
                   "predictionio_tpu.ops.pallas_kernels",
                   "predictionio_tpu.retrieval.pq")
# The retrieval primitives themselves (rule 2) — any call spelled
# ``name(...)`` or ``<anything>.name(...)``.  The PQ set covers the
# kernel, both search flavors, codebook construction and raw
# codebook/LUT access.
_PRIMITIVES = {"top_k_scores", "chunked_top_k", "sharded_top_k",
               "host_top_k", "fused_topk", "fused_topk_pallas",
               "pq_scan", "pq_scan_pallas", "pq_scan_xla",
               "search_pq_host", "search_pq_device",
               "search_ivf_pq_host", "search_ivf_pq_device",
               "build_pq", "lut_tables", "decode_pq"}


def _import_violations(tree: ast.AST, filename: str) -> List[str]:
    out: List[str] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name in _BANNED_MODULES or any(
                        alias.name.startswith(m + ".")
                        for m in _BANNED_MODULES):
                    out.append(
                        f"{filename}:{node.lineno}: imports {alias.name} — "
                        f"serving reaches the corpus via "
                        f"predictionio_tpu.retrieval, never the raw ops")
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod in _BANNED_MODULES or any(
                    mod.startswith(m + ".") for m in _BANNED_MODULES):
                names = ", ".join(a.name for a in node.names)
                out.append(
                    f"{filename}:{node.lineno}: imports {names} from "
                    f"{mod} — serving reaches the corpus via "
                    f"predictionio_tpu.retrieval, never the raw ops")
    return out


def _call_violations(tree: ast.AST, filename: str) -> List[str]:
    out: List[str] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else "")
        if name in _PRIMITIVES:
            out.append(
                f"{filename}:{node.lineno}: calls {name}() directly — "
                f"route through Retriever.topk (predictionio_tpu."
                f"retrieval) so the request gets routing, staging "
                f"caches, IVF, and pio_retrieval_* metrics")
    return out


def _raw_retriever_violations(tree: ast.AST, filename: str) -> List[str]:
    """Rule 3: ``Retriever(...)`` constructions outside a
    ``cached_retriever`` call's argument lambda."""
    inside_cached: set = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "cached_retriever"):
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for sub in ast.walk(arg):
                    inside_cached.add(id(sub))
    out: List[str] = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "Retriever"
                and id(node) not in inside_cached):
            out.append(
                f"{filename}:{node.lineno}: constructs Retriever() "
                f"outside cached_retriever — a fresh retriever per call "
                f"re-stages the corpus and re-traces its jit programs; "
                f"build it inside cached_retriever(owner, lambda: ...)")
    return out


def check_source(source: str, filename: str,
                 engine_module: bool = False) -> List[str]:
    """Violations in one module's source (path:line prefixed strings)."""
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as e:
        return [f"{filename}:{e.lineno}: unparseable: {e.msg}"]
    violations = _import_violations(tree, filename)
    violations += _call_violations(tree, filename)
    if engine_module:
        violations += _raw_retriever_violations(tree, filename)
    return violations


def check(root: Path | str | None = None) -> List[str]:
    """Violations across the serving surface under ``root``."""
    root = Path(root) if root else Path(__file__).resolve().parents[1]
    pkg = root / "predictionio_tpu"
    violations: List[str] = []
    for scope in _SCOPES:
        for path in sorted((pkg / scope).rglob("*.py")):
            violations.extend(check_source(
                path.read_text(encoding="utf-8"), str(path),
                engine_module=(scope == "templates"
                               and path.name == "engine.py")))
    return violations


def main(argv: List[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    violations = check(argv[0] if argv else None)
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} retrieval-lint violation(s).",
              file=sys.stderr)
        return 1
    print("lint_retrieval: serving reaches the corpus via the retrieval "
          "facade only.")
    return 0


if __name__ == "__main__":
    sys.exit(main())
