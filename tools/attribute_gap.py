#!/usr/bin/env python
"""Attribute the feeder-vs-realized pipeline gap to its dominant component.

BENCH_r05 measured feeder-vs-realized training-throughput gaps of 45.9%
(two-tower) and 87.0% (DLRM) with no way to say which side of the
pipeline stalls.  The training loops now decompose every iteration into
host_wait / h2d / device_wait / device_step (obs.pipeline →
obs.runtime.StepTimeline), and bench.py embeds the per-model timeline
summary in its round artifact.  This tool reads a bench round plus that
timeline and prints, per model, the dominant gap component with its
share of step time and the recommended attack — the actionable half of
ROADMAP's "read which component dominates each gap, and attack THAT".

Usage::

    python bench.py > round.json
    python tools/attribute_gap.py round.json
    # or against a live server's ring:
    python tools/attribute_gap.py round.json \\
        --timeline http://127.0.0.1:8000/timeline.json

The bench artifact may be the raw one-line JSON bench.py prints or any
JSON object containing its ``tpu_era`` block; ``--timeline`` overrides
the embedded ``timeline`` block with a file or a ``/timeline.json`` URL.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, Optional

MODELS = ("two_tower", "dlrm")

# Component → the attack the next perf PR should mount (ROADMAP wording).
ATTACKS = {
    "host_wait": "feeder threads / parallel batch assembly "
                 "(the host cannot produce batches fast enough)",
    "h2d": "pinned buffers / double buffering "
           "(stage batch N+1 while step N runs)",
    "device_wait": "step fusion or a larger batch size "
                   "(the device step itself is the bottleneck)",
}

WALL_PHASES = ("host_wait", "h2d", "device_wait")


def load_json(path: str) -> Dict[str, Any]:
    if path == "-":
        return json.load(sys.stdin)
    if path.startswith(("http://", "https://")):
        from urllib.request import urlopen

        with urlopen(path, timeout=10) as resp:
            return json.load(resp)
    with open(path) as f:
        text = f.read()
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        # bench logs sometimes carry stray lines around the JSON object;
        # take the last parseable line (bench.py prints exactly one)
        for line in reversed(text.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    return json.loads(line)
                except json.JSONDecodeError:
                    continue
        raise


def _timeline_summaries(doc: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """Normalize either bench.py's embedded ``timeline`` block or a
    server ``/timeline.json`` payload to {model: summary}."""
    if "models" in doc and isinstance(doc["models"], dict):
        return doc["models"]  # /timeline.json shape
    return {k: v for k, v in doc.items() if isinstance(v, dict)}


def attribute(bench: Dict[str, Any],
              timeline: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Compute the attribution; returns {model: {...}} (None entries for
    models with no usable data)."""
    tpu_era = bench.get("tpu_era", bench)
    summaries = _timeline_summaries(
        timeline if timeline is not None else bench.get("timeline", {}))
    out: Dict[str, Any] = {}
    for model in MODELS:
        gap = tpu_era.get(f"{model}_pipeline_gap_pct")
        feeder = tpu_era.get(f"{model}_feeder_examples_per_sec")
        pipe = tpu_era.get(f"{model}_pipeline_examples_per_sec")
        dev = tpu_era.get(f"{model}_examples_per_sec_per_chip")
        summary = summaries.get(model) or {}
        shares = {p: float(summary.get("phase_share", {}).get(p, 0.0))
                  for p in WALL_PHASES}
        if not any(shares.values()):
            out[model] = None
            continue
        dominant = max(shares, key=lambda p: shares[p])
        out[model] = {
            "gap_pct": gap,
            "feeder_examples_per_sec": feeder,
            "pipeline_examples_per_sec": pipe,
            "device_examples_per_sec": dev,
            "steps": summary.get("steps"),
            "phase_share": shares,
            "phase_ms": summary.get("phase_ms", {}),
            "dominant": dominant,
            "dominant_share": shares[dominant],
            "attack": ATTACKS[dominant],
        }
    return out


def _fmt_rate(v: Any) -> str:
    return f"{v:,.0f} ex/s" if isinstance(v, (int, float)) else "?"


def render(result: Dict[str, Any]) -> str:
    lines = []
    for model in MODELS:
        r = result.get(model)
        if r is None:
            lines.append(f"{model}: no timeline data (run bench.py, or "
                         "point --timeline at a training process's "
                         "/timeline.json)")
            continue
        gap = r["gap_pct"]
        head = f"{model}: pipeline gap " + (
            f"{gap:.1f}%" if isinstance(gap, (int, float)) else "?")
        if r["feeder_examples_per_sec"] or r["pipeline_examples_per_sec"]:
            head += (f" (feeder {_fmt_rate(r['feeder_examples_per_sec'])}"
                     f" -> realized "
                     f"{_fmt_rate(r['pipeline_examples_per_sec'])}"
                     f", device ceiling "
                     f"{_fmt_rate(r['device_examples_per_sec'])})")
        lines.append(head)
        shares = r["phase_share"]
        lines.append("  step-time decomposition: " + " | ".join(
            f"{p} {shares[p] * 100:.1f}%" for p in WALL_PHASES))
        lines.append(f"  dominant: {r['dominant']} "
                     f"({r['dominant_share'] * 100:.1f}% of step wall, "
                     f"over {r['steps']} steps)")
        lines.append(f"  attack: {r['attack']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="attribute the feeder-vs-realized pipeline gap")
    ap.add_argument("bench", nargs="?", default="-",
                    help="bench.py round artifact (JSON file, '-' stdin)")
    ap.add_argument("--timeline", default=None, metavar="FILE|URL",
                    help="step-timeline source overriding the bench "
                         "artifact's embedded block (a /timeline.json "
                         "URL or a saved payload)")
    ap.add_argument("--json", action="store_true",
                    help="emit the attribution as JSON instead of text")
    args = ap.parse_args(argv)

    bench = load_json(args.bench)
    timeline = load_json(args.timeline) if args.timeline else None
    result = attribute(bench, timeline)
    if args.json:
        print(json.dumps(result, indent=2))
    else:
        print(render(result))
    # Non-zero when NOTHING could be attributed: a wired-up bench must
    # never silently print two "no data" stanzas and exit 0.
    return 0 if any(result.get(m) for m in MODELS) else 1


if __name__ == "__main__":
    sys.exit(main())
