#!/usr/bin/env python
"""Attribute the feeder-vs-realized pipeline gap to its dominant component.

BENCH_r05 measured feeder-vs-realized training-throughput gaps of 45.9%
(two-tower) and 87.0% (DLRM) with no way to say which side of the
pipeline stalls.  The training loops now decompose every iteration into
host_wait / h2d / device_wait / device_step (obs.pipeline →
obs.runtime.StepTimeline), and bench.py embeds the per-model timeline
summary in its round artifact.  This tool reads a bench round plus that
timeline and prints, per model, the dominant gap component with its
share of step time and the recommended attack — the actionable half of
ROADMAP's "read which component dominates each gap, and attack THAT".

Usage::

    python bench.py > round.json
    python tools/attribute_gap.py round.json
    # or against a live server's ring:
    python tools/attribute_gap.py round.json \\
        --timeline http://127.0.0.1:8000/timeline.json
    # before/after a perf PR — per-model gap delta + dominant shift:
    python tools/attribute_gap.py --compare BENCH_r05.json BENCH_r06.json

The bench artifact may be the raw one-line JSON bench.py prints, any
JSON object containing its ``tpu_era`` block, or a driver round capture
whose ``tail`` holds the bench stdout; ``--timeline`` overrides the
embedded ``timeline`` block with a file or a ``/timeline.json`` URL.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, Optional

MODELS = ("two_tower", "dlrm")

# Component → the attack the next perf PR should mount (ROADMAP wording).
ATTACKS = {
    "host_wait": "feeder threads / parallel batch assembly "
                 "(the host cannot produce batches fast enough)",
    "h2d": "pinned buffers / double buffering "
           "(stage batch N+1 while step N runs)",
    "dispatch": "step fusion (PIO_FUSE_STEPS / pio train --fuse-steps "
                "auto) or a larger batch size (the step-call wall — on "
                "synchronous-dispatch backends the execution itself — "
                "dominates)",
    "device_wait": "step fusion (PIO_FUSE_STEPS / pio train --fuse-steps "
                   "auto) or a larger batch size "
                   "(the device step itself is the bottleneck)",
}

# dispatch/device_wait with fusion ALREADY active (K>1): re-recommending
# fusion would chase the component that is now mostly honest device
# execution — the remaining levers are batch width and memory headroom.
ATTACK_DEVICE_WAIT_FUSED = (
    "batch-size growth (--batch-autoscale) after an HBM-headroom check "
    "(pio_device_mem_peak_bytes vs bytes_limit) — fusion depth K>1 "
    "already amortizes dispatch, the residual device time is mostly "
    "honest execution")

WALL_PHASES = ("host_wait", "h2d", "dispatch", "device_wait")


def load_json(path: str) -> Dict[str, Any]:
    if path == "-":
        return _unwrap(json.load(sys.stdin))
    if path.startswith(("http://", "https://")):
        from urllib.request import urlopen

        with urlopen(path, timeout=10) as resp:
            return _unwrap(json.load(resp))
    with open(path) as f:
        text = f.read()
    try:
        return _unwrap(json.loads(text))
    except json.JSONDecodeError:
        # bench logs sometimes carry stray lines around the JSON object;
        # take the last parseable line (bench.py prints exactly one)
        doc = _last_json_line(text)
        if doc is None:
            raise
        return _unwrap(doc)


def _last_json_line(text: str) -> Optional[Dict[str, Any]]:
    """The last line of ``text`` that parses as a JSON object, if any."""
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def _unwrap(doc: Any) -> Dict[str, Any]:
    """Committed BENCH_r*.json rounds are driver captures whose ``tail``
    holds the bench stdout — reach through so ``--compare BENCH_r05.json
    BENCH_r06.json`` works on the artifacts as committed.  Tails may be
    truncated mid-object (the driver keeps only the last bytes), so fall
    back to brace-scanning the blocks this tool actually reads."""
    if not (isinstance(doc, dict) and "tpu_era" not in doc
            and isinstance(doc.get("tail"), str)):
        return doc
    tail = doc["tail"]
    inner = _last_json_line(tail)
    if inner is not None:
        return inner
    rescued = {k: v for k in ("tpu_era", "timeline")
               if (v := _extract_obj(tail, k)) is not None}
    return rescued if rescued else doc


def _extract_obj(text: str, key: str) -> Optional[Dict[str, Any]]:
    """Parse the balanced ``{...}`` following ``"key":`` in raw text."""
    i = text.find(f'"{key}"')
    if i < 0:
        return None
    i = text.find("{", i)
    if i < 0:
        return None
    depth = 0
    in_str = esc = False
    for j in range(i, len(text)):
        ch = text[j]
        if in_str:
            if esc:
                esc = False
            elif ch == "\\":
                esc = True
            elif ch == '"':
                in_str = False
            continue
        if ch == '"':
            in_str = True
        elif ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                try:
                    return json.loads(text[i:j + 1])
                except json.JSONDecodeError:
                    return None
    return None


def _timeline_summaries(doc: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """Normalize either bench.py's embedded ``timeline`` block or a
    server ``/timeline.json`` payload to {model: summary}."""
    if "models" in doc and isinstance(doc["models"], dict):
        return doc["models"]  # /timeline.json shape
    return {k: v for k, v in doc.items() if isinstance(v, dict)}


def attribute(bench: Dict[str, Any],
              timeline: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Compute the attribution; returns {model: {...}} (None entries for
    models with no usable data)."""
    tpu_era = bench.get("tpu_era", bench)
    summaries = _timeline_summaries(
        timeline if timeline is not None else bench.get("timeline", {}))
    out: Dict[str, Any] = {}
    for model in MODELS:
        gap = tpu_era.get(f"{model}_pipeline_gap_pct")
        feeder = tpu_era.get(f"{model}_feeder_examples_per_sec")
        pipe = tpu_era.get(f"{model}_pipeline_examples_per_sec")
        dev = tpu_era.get(f"{model}_examples_per_sec_per_chip")
        summary = summaries.get(model) or {}
        shares = {p: float(summary.get("phase_share", {}).get(p, 0.0))
                  for p in WALL_PHASES}
        if not any(shares.values()):
            out[model] = None
            continue
        dominant = max(shares, key=lambda p: shares[p])
        # Fusion depth: rounds predating ISSUE 7 carry no fuse_steps —
        # one record was one step, K=1.
        fuse = float(summary.get("fuse_steps") or 1.0)
        attack = (ATTACK_DEVICE_WAIT_FUSED
                  if dominant in ("device_wait", "dispatch") and fuse > 1
                  else ATTACKS[dominant])
        out[model] = {
            "gap_pct": gap,
            "feeder_examples_per_sec": feeder,
            "pipeline_examples_per_sec": pipe,
            "device_examples_per_sec": dev,
            "steps": summary.get("steps"),
            "dispatches": summary.get("dispatches"),
            "fuse_steps": fuse,
            "phase_share": shares,
            "phase_ms": summary.get("phase_ms", {}),
            "dominant": dominant,
            "dominant_share": shares[dominant],
            "attack": attack,
            "residual": _residual(summary, dev),
        }
    return out


def _residual(summary: Dict[str, Any], dev: Any) -> Optional[Dict[str, Any]]:
    """Decompose the GAP itself, not the wall: subtract the estimated
    pure device-execution time (examples / device ceiling) from
    device_wait, leaving ``device_excess`` — the dispatch/sync overhead
    step fusion attacks.  Pre-fusion rounds showed device_wait at ~99%
    of the wall even when most of it was honest execution; this view
    says how much of the residual is actually attackable."""
    steps = summary.get("steps")
    examples = summary.get("examples")
    phase_ms = summary.get("phase_ms", {})
    if not (isinstance(dev, (int, float)) and dev > 0 and steps
            and examples):
        return None
    exec_ms_per_step = (examples / steps) / dev * 1e3
    per_step = {p: float(phase_ms.get(p, 0.0)) / steps for p in WALL_PHASES}
    comps = {
        "host_wait": per_step["host_wait"],
        "h2d": per_step["h2d"],
        # dispatch + device_wait together hold the device-side wall (a
        # synchronous-dispatch backend bills execution to the former, an
        # async one to the latter); what exceeds the estimated pure
        # execution is the attackable overhead.
        "device_excess": max(per_step["dispatch"] + per_step["device_wait"]
                             - exec_ms_per_step, 0.0),
    }
    total = sum(comps.values())
    if total <= 0:
        return None
    dominant = max(comps, key=lambda p: comps[p])
    return {
        "exec_ms_per_step_est": round(exec_ms_per_step, 3),
        "ms_per_step": {p: round(v, 3) for p, v in comps.items()},
        "share": {p: round(v / total, 4) for p, v in comps.items()},
        "dominant": dominant,
        "dominant_share": round(comps[dominant] / total, 4),
    }


def _round_stats(bench: Dict[str, Any], model: str,
                 attr: Optional[Dict]) -> Optional[Dict]:
    """One model's comparable numbers from a round: gap/rates straight
    from ``tpu_era`` (available even for rounds that predate the step
    timeline), the dominant-component attribution when the round has
    one."""
    tpu_era = bench.get("tpu_era", bench)
    gap = tpu_era.get(f"{model}_pipeline_gap_pct")
    pipe = tpu_era.get(f"{model}_pipeline_examples_per_sec")
    if gap is None and pipe is None and attr is None:
        return None
    return {
        "gap_pct": gap,
        "pipeline_examples_per_sec": pipe,
        "feeder_examples_per_sec":
            tpu_era.get(f"{model}_feeder_examples_per_sec"),
        "attribution": attr,
    }


def compare(old_bench: Dict[str, Any],
            new_bench: Dict[str, Any]) -> Dict[str, Any]:
    """Per-model before/after of two rounds: gap delta + dominant shift.

    The one-command check for a perf PR (ISSUE 5 satellite): did the gap
    close, and did the bottleneck move to the next component?
    """
    old_attr = attribute(old_bench)
    new_attr = attribute(new_bench)
    out: Dict[str, Any] = {}
    for model in MODELS:
        o = _round_stats(old_bench, model, old_attr.get(model))
        n = _round_stats(new_bench, model, new_attr.get(model))
        if o is None and n is None:
            out[model] = None
            continue
        entry: Dict[str, Any] = {"old": o, "new": n}
        if o and n:
            og, ng = o.get("gap_pct"), n.get("gap_pct")
            if isinstance(og, (int, float)) and isinstance(ng, (int, float)):
                entry["gap_delta_pct"] = round(ng - og, 1)
            op, np_ = (o.get("pipeline_examples_per_sec"),
                       n.get("pipeline_examples_per_sec"))
            if isinstance(op, (int, float)) and isinstance(np_, (int, float)) \
                    and op > 0:
                entry["realized_speedup"] = round(np_ / op, 3)
            oa, na = o.get("attribution"), n.get("attribution")
            if oa and na:
                entry["dominant_shift"] = (oa["dominant"], na["dominant"])
                entry["fuse_steps_shift"] = (oa.get("fuse_steps", 1.0),
                                             na.get("fuse_steps", 1.0))
        out[model] = entry
    return out


def render_compare(result: Dict[str, Any]) -> str:
    lines = []
    for model in MODELS:
        r = result.get(model)
        if r is None:
            lines.append(f"{model}: no data in either round")
            continue
        o, n = r.get("old"), r.get("new")
        if not (o and n):
            which = "old" if not o else "new"
            lines.append(f"{model}: no usable data in the {which} round")
            continue

        def g(e):
            v = e.get("gap_pct")
            return f"{v:.1f}%" if isinstance(v, (int, float)) else "?"

        delta = r.get("gap_delta_pct")
        arrow = (f" ({delta:+.1f} pts)"
                 if isinstance(delta, (int, float)) else "")
        lines.append(f"{model}: pipeline gap {g(o)} -> {g(n)}{arrow}")
        lines.append(
            f"  realized: {_fmt_rate(o['pipeline_examples_per_sec'])} -> "
            f"{_fmt_rate(n['pipeline_examples_per_sec'])}"
            + (f" ({r['realized_speedup']:.2f}x)"
               if "realized_speedup" in r else ""))
        oa, na = o.get("attribution"), n.get("attribution")
        if "dominant_shift" in r:
            od, nd = r["dominant_shift"]
            if od == nd:
                lines.append(
                    f"  dominant component: {od} "
                    f"({oa['dominant_share'] * 100:.1f}% -> "
                    f"{na['dominant_share'] * 100:.1f}% of step wall)")
            else:
                lines.append(
                    f"  dominant component shifted: {od} "
                    f"({oa['dominant_share'] * 100:.1f}%) -> {nd} "
                    f"({na['dominant_share'] * 100:.1f}%)")
        elif na:
            lines.append(
                f"  dominant component (new round): {na['dominant']} "
                f"({na['dominant_share'] * 100:.1f}% of step wall; "
                "old round has no timeline)")
        if "fuse_steps_shift" in r:
            ok_, nk = r["fuse_steps_shift"]
            if ok_ != nk:
                lines.append(
                    f"  fusion depth: K={ok_:.0f} -> K={nk:.0f}")
        if na and na.get("residual"):
            lines.append("  residual per step (new round, vs device "
                         "ceiling): " + _fmt_residual(na["residual"]))
        if na:
            lines.append(f"  next attack: {na['attack']}")
    return "\n".join(lines)


def _fmt_residual(res: Dict[str, Any]) -> str:
    return " | ".join(
        f"{p} {res['share'][p] * 100:.1f}%"
        for p in ("host_wait", "h2d", "device_excess"))


def _fmt_rate(v: Any) -> str:
    return f"{v:,.0f} ex/s" if isinstance(v, (int, float)) else "?"


def render(result: Dict[str, Any]) -> str:
    lines = []
    for model in MODELS:
        r = result.get(model)
        if r is None:
            lines.append(f"{model}: no timeline data (run bench.py, or "
                         "point --timeline at a training process's "
                         "/timeline.json)")
            continue
        gap = r["gap_pct"]
        head = f"{model}: pipeline gap " + (
            f"{gap:.1f}%" if isinstance(gap, (int, float)) else "?")
        if r["feeder_examples_per_sec"] or r["pipeline_examples_per_sec"]:
            head += (f" (feeder {_fmt_rate(r['feeder_examples_per_sec'])}"
                     f" -> realized "
                     f"{_fmt_rate(r['pipeline_examples_per_sec'])}"
                     f", device ceiling "
                     f"{_fmt_rate(r['device_examples_per_sec'])})")
        lines.append(head)
        shares = r["phase_share"]
        lines.append("  step-time decomposition: " + " | ".join(
            f"{p} {shares[p] * 100:.1f}%" for p in WALL_PHASES))
        lines.append(f"  dominant: {r['dominant']} "
                     f"({r['dominant_share'] * 100:.1f}% of step wall, "
                     f"over {r['steps']} steps)")
        if r.get("fuse_steps", 1) > 1:
            lines.append(
                f"  fusion depth: K={r['fuse_steps']:.0f} "
                f"({r['dispatches']} dispatches over {r['steps']} steps)")
        if r.get("residual"):
            lines.append("  residual per step (vs device ceiling): "
                         + _fmt_residual(r["residual"]))
        lines.append(f"  attack: {r['attack']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="attribute the feeder-vs-realized pipeline gap")
    ap.add_argument("bench", nargs="?", default="-",
                    help="bench.py round artifact (JSON file, '-' stdin)")
    ap.add_argument("--compare", nargs=2, default=None,
                    metavar=("OLD", "NEW"),
                    help="compare two rounds: per-model gap delta and "
                         "dominant-component shift (ignores the "
                         "positional bench argument)")
    ap.add_argument("--timeline", default=None, metavar="FILE|URL",
                    help="step-timeline source overriding the bench "
                         "artifact's embedded block (a /timeline.json "
                         "URL or a saved payload)")
    ap.add_argument("--json", action="store_true",
                    help="emit the attribution as JSON instead of text")
    args = ap.parse_args(argv)

    if args.compare:
        if args.timeline:
            ap.error("--timeline cannot be combined with --compare "
                     "(each round's timeline comes from its own artifact)")
        result = compare(load_json(args.compare[0]),
                         load_json(args.compare[1]))
        if args.json:
            print(json.dumps(result, indent=2))
        else:
            print(render_compare(result))
        return 0 if any(
            isinstance(result.get(m), dict)
            and result[m].get("old") and result[m].get("new")
            for m in MODELS) else 1

    bench = load_json(args.bench)
    timeline = load_json(args.timeline) if args.timeline else None
    result = attribute(bench, timeline)
    if args.json:
        print(json.dumps(result, indent=2))
    else:
        print(render(result))
    # Non-zero when NOTHING could be attributed: a wired-up bench must
    # never silently print two "no data" stanzas and exit 0.
    return 0 if any(result.get(m) for m in MODELS) else 1


if __name__ == "__main__":
    sys.exit(main())
