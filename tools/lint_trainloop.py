#!/usr/bin/env python
"""Static check: deep-model train loops ride the prefetched input pipeline.

ISSUE 5 rewired the two-tower and DLRM training loops onto
``data/prefetch.py``'s :class:`DevicePrefetcher`: batch padding, dtype
conversion and the H2D transfer run on a background prep thread so the
transfer overlaps device compute.  That perf win only stays won if
nothing regresses it — a NEW model (or a refactor of an existing one)
whose step loop calls ``jnp.asarray`` / ``jax.device_put`` /
``put_sharded`` inline re-serializes H2D after the device sync and
silently reopens the feeder-vs-realized gap BENCH_r05 measured.  This
lint locks the invariant in (same pattern as ``tools/lint_dispatch.py``;
a tier-1 test runs it in CI):

1. Every module in ``predictionio_tpu/models/`` that defines a
   ``_train_attempt`` function (the supervised-training-loop convention)
   must construct a ``DevicePrefetcher`` inside it.
2. No ``for``-loop body inside such a function may call a staging
   primitive (``jnp.asarray`` / ``jnp.array`` / ``jax.device_put`` /
   ``put_sharded``) — staging belongs in the prep closure handed to the
   prefetcher, where it runs off the step loop.

ISSUE 7 fused the train steps into per-window ``lax.scan`` dispatches,
adding two invariants of its own:

3. No host sync (``float()`` / ``.block_until_ready()`` /
   ``device_get``) inside a ``lax.scan`` body anywhere in ``models/``:
   a sync inside the scan body either fails to trace or — worse, via a
   callback — re-serializes the very dispatch cadence the fusion
   removed.
4. Supervision sits at the fusion boundary: inside ``_train_attempt``,
   ``watchdog.arm``/``disarm`` and the ``guard.check*`` family must be
   called from the dispatch loop itself — present in the step loop, and
   never from a nested function (a prep closure or scan body would run
   them off the boundary, or per sub-step).

Usage: ``python tools/lint_trainloop.py [root]`` — prints violations and
exits non-zero when any exist.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List

# The supervised train-loop entry point both deep models use; any future
# model following the convention is auto-covered.
_LOOP_FN = "_train_attempt"
# Files that MUST define a prefetched _train_attempt (a rename would
# otherwise silently drop them out of rule 1's reach).
_REQUIRED = ("two_tower.py", "dlrm.py")
# Staging entry points that must construct a DevicePrefetcher even
# though they are not step loops (ISSUE 13 satellite: ALS bucket
# staging rides the SHARED input path, not a private transfer loop).
_STAGING_FNS = {"als.py": "_device_buckets"}
# Host→device staging primitives banned from step-loop bodies.
_BANNED_ATTRS = {"asarray", "array", "device_put"}
_BANNED_NAMES = {"put_sharded", "device_put"}
# Host-sync primitives banned from lax.scan bodies (rule 3).
_SYNC_ATTRS = {"block_until_ready", "device_get"}
_SYNC_NAMES = {"float", "device_get"}
# Supervision calls that must sit at the fusion boundary (rule 4).
_BOUNDARY_RECEIVERS = ("watchdog", "guard")


def _is_staging_call(node: ast.Call) -> str:
    """Name of the banned staging primitive this call is, or ''."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in _BANNED_ATTRS:
        # jnp.asarray / jax.device_put / np-level aliases all count: any
        # of them materializes a device buffer on the calling thread.
        if isinstance(f.value, ast.Name) and f.value.id in (
                "jnp", "jax", "jax_numpy"):
            return f"{f.value.id}.{f.attr}"
    if isinstance(f, ast.Name) and f.id in _BANNED_NAMES:
        return f.id
    return ""


def _constructs_prefetcher(fn: ast.FunctionDef) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else "")
            if name == "DevicePrefetcher":
                return True
    return False


def _loop_staging_calls(fn: ast.FunctionDef) -> List[ast.Call]:
    """Staging calls lexically inside any for/while loop of ``fn``
    (including loops in nested helpers — a nested generator staging
    inline has the same serializing effect)."""
    bad: List[ast.Call] = []
    for node in ast.walk(fn):
        if not isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and _is_staging_call(sub):
                bad.append(sub)
    return bad


def _is_sync_call(node: ast.Call) -> str:
    """Name of the host-sync primitive this call is, or ''."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in _SYNC_ATTRS:
        # x.block_until_ready() / jax.block_until_ready(x) /
        # jax.device_get(x) all force a host round-trip.
        return f.attr
    if isinstance(f, ast.Name) and f.id in _SYNC_NAMES:
        return f.id
    return ""


def _scan_bodies(tree: ast.AST) -> List[ast.AST]:
    """The function bodies passed to ``lax.scan`` calls: a Name first
    argument resolves against every FunctionDef of that name in the
    module (nested defs included — the models define scan bodies inline
    inside their fused jit entry points); a Lambda is taken as-is."""
    defs: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
    bodies: List[ast.AST] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute) and f.attr == "scan"):
            continue
        recv = f.value
        is_lax = (isinstance(recv, ast.Name) and recv.id == "lax") or (
            isinstance(recv, ast.Attribute) and recv.attr == "lax")
        if not is_lax:
            continue
        first = node.args[0]
        if isinstance(first, ast.Lambda):
            bodies.append(first)
        elif isinstance(first, ast.Name):
            bodies.extend(defs.get(first.id, ()))
    return bodies


def _is_boundary_call(node: ast.Call) -> str:
    """``watchdog.arm``/``disarm`` / ``guard.check*``-style call, or ''."""
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id in _BOUNDARY_RECEIVERS:
        if f.value.id == "watchdog" and f.attr in ("arm", "disarm"):
            return f"watchdog.{f.attr}"
        if f.value.id == "guard" and f.attr.startswith("check"):
            return f"guard.{f.attr}"
    return ""


def _nested_function_nodes(fn: ast.AST) -> set:
    """ids of every node inside a function defined WITHIN ``fn``."""
    inner: set = set()
    for node in ast.walk(fn):
        if node is fn or not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        for sub in ast.walk(node):
            inner.add(id(sub))
    return inner


def check_source(source: str, filename: str,
                 require_prefetcher: bool = False,
                 require_staging_fn: str = "") -> List[str]:
    """Violations in one module's source (path:line prefixed strings)."""
    violations: List[str] = []
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as e:
        return [f"{filename}:{e.lineno}: unparseable: {e.msg}"]
    if require_staging_fn:
        # Rule 5: named staging entry points ride the shared input path.
        staging = [n for n in ast.walk(tree)
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                   and n.name == require_staging_fn]
        if not staging:
            violations.append(
                f"{filename}:1: no {require_staging_fn} function — the "
                f"shared-staging convention (and this lint's coverage) "
                f"requires one")
        for fn in staging:
            if not _constructs_prefetcher(fn):
                violations.append(
                    f"{filename}:{fn.lineno}: {fn.name} does not "
                    f"construct a DevicePrefetcher — bucket staging must "
                    f"ride the shared input path (data/prefetch.py) so "
                    f"prefetch metrics and overlap cover it, not a "
                    f"private transfer loop")
    # Rule 3: host syncs inside lax.scan bodies (anywhere in the module).
    for body in _scan_bodies(tree):
        for sub in ast.walk(body):
            if isinstance(sub, ast.Call) and _is_sync_call(sub):
                violations.append(
                    f"{filename}:{sub.lineno}: lax.scan body calls "
                    f"{_is_sync_call(sub)} — a host sync inside the "
                    f"fused window re-serializes the dispatch cadence "
                    f"step fusion exists to remove")
    loops = [n for n in ast.walk(tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
             and n.name == _LOOP_FN]
    if require_prefetcher and not loops:
        return [f"{filename}:1: no {_LOOP_FN} function — the supervised "
                f"train-loop convention (and this lint's coverage) "
                f"requires one"]
    for fn in loops:
        if not _constructs_prefetcher(fn):
            violations.append(
                f"{filename}:{fn.lineno}: {fn.name} does not construct a "
                f"DevicePrefetcher — the batch stream must ride the "
                f"prefetched input pipeline (data/prefetch.py), not "
                f"stage inline")
        for call in _loop_staging_calls(fn):
            violations.append(
                f"{filename}:{call.lineno}: {fn.name} stages a batch "
                f"inside the step loop ({_is_staging_call(call)}) — "
                f"H2D serializes after the device sync; move staging "
                f"into the DevicePrefetcher prep/put functions")
        # Rule 4: supervision at the fusion boundary.
        nested = _nested_function_nodes(fn)
        in_loop: set = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                for sub in ast.walk(node):
                    in_loop.add(id(sub))
        seen_in_loop: set = set()
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call):
                continue
            name = _is_boundary_call(sub)
            if not name:
                continue
            if id(sub) in nested:
                violations.append(
                    f"{filename}:{sub.lineno}: {fn.name} calls {name} "
                    f"from a nested function — supervision belongs at "
                    f"the fusion boundary (the dispatch loop body), not "
                    f"inside a prep closure or scan body")
            elif id(sub) in in_loop:
                seen_in_loop.add("guard" if name.startswith("guard")
                                 else name)
        if require_prefetcher:
            # Presence is demanded only of the deep models rule 1 names:
            # helper/experimental loops may legitimately run without
            # supervision, but the production loops may not lose it.
            for required, what in (("watchdog.arm", "watchdog.arm"),
                                   ("guard", "a guard.check* call")):
                if required not in seen_in_loop:
                    violations.append(
                        f"{filename}:{fn.lineno}: {fn.name} never calls "
                        f"{what} inside its step loop — fused dispatches "
                        f"must arm the watchdog (K-scaled) and check the "
                        f"loss vector at every fusion boundary")
    return violations


def check(root: Path | str | None = None) -> List[str]:
    """Violations across every model module under ``root``."""
    root = Path(root) if root else Path(__file__).resolve().parents[1]
    models_dir = root / "predictionio_tpu" / "models"
    violations: List[str] = []
    for path in sorted(models_dir.glob("*.py")):
        violations.extend(check_source(
            path.read_text(encoding="utf-8"), str(path),
            require_prefetcher=path.name in _REQUIRED,
            require_staging_fn=_STAGING_FNS.get(path.name, "")))
    return violations


def main(argv: List[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    violations = check(argv[0] if argv else None)
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} train-loop-lint violation(s).",
              file=sys.stderr)
        return 1
    print("lint_trainloop: deep-model train loops ride DevicePrefetcher.")
    return 0


if __name__ == "__main__":
    sys.exit(main())
