#!/usr/bin/env python
"""Static check: deep-model train loops ride the prefetched input pipeline.

ISSUE 5 rewired the two-tower and DLRM training loops onto
``data/prefetch.py``'s :class:`DevicePrefetcher`: batch padding, dtype
conversion and the H2D transfer run on a background prep thread so the
transfer overlaps device compute.  That perf win only stays won if
nothing regresses it — a NEW model (or a refactor of an existing one)
whose step loop calls ``jnp.asarray`` / ``jax.device_put`` /
``put_sharded`` inline re-serializes H2D after the device sync and
silently reopens the feeder-vs-realized gap BENCH_r05 measured.  This
lint locks the invariant in (same pattern as ``tools/lint_dispatch.py``;
a tier-1 test runs it in CI):

1. Every module in ``predictionio_tpu/models/`` that defines a
   ``_train_attempt`` function (the supervised-training-loop convention)
   must construct a ``DevicePrefetcher`` inside it.
2. No ``for``-loop body inside such a function may call a staging
   primitive (``jnp.asarray`` / ``jnp.array`` / ``jax.device_put`` /
   ``put_sharded``) — staging belongs in the prep closure handed to the
   prefetcher, where it runs off the step loop.

Usage: ``python tools/lint_trainloop.py [root]`` — prints violations and
exits non-zero when any exist.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List

# The supervised train-loop entry point both deep models use; any future
# model following the convention is auto-covered.
_LOOP_FN = "_train_attempt"
# Files that MUST define a prefetched _train_attempt (a rename would
# otherwise silently drop them out of rule 1's reach).
_REQUIRED = ("two_tower.py", "dlrm.py")
# Host→device staging primitives banned from step-loop bodies.
_BANNED_ATTRS = {"asarray", "array", "device_put"}
_BANNED_NAMES = {"put_sharded", "device_put"}


def _is_staging_call(node: ast.Call) -> str:
    """Name of the banned staging primitive this call is, or ''."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in _BANNED_ATTRS:
        # jnp.asarray / jax.device_put / np-level aliases all count: any
        # of them materializes a device buffer on the calling thread.
        if isinstance(f.value, ast.Name) and f.value.id in (
                "jnp", "jax", "jax_numpy"):
            return f"{f.value.id}.{f.attr}"
    if isinstance(f, ast.Name) and f.id in _BANNED_NAMES:
        return f.id
    return ""


def _constructs_prefetcher(fn: ast.FunctionDef) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else "")
            if name == "DevicePrefetcher":
                return True
    return False


def _loop_staging_calls(fn: ast.FunctionDef) -> List[ast.Call]:
    """Staging calls lexically inside any for/while loop of ``fn``
    (including loops in nested helpers — a nested generator staging
    inline has the same serializing effect)."""
    bad: List[ast.Call] = []
    for node in ast.walk(fn):
        if not isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and _is_staging_call(sub):
                bad.append(sub)
    return bad


def check_source(source: str, filename: str,
                 require_prefetcher: bool = False) -> List[str]:
    """Violations in one module's source (path:line prefixed strings)."""
    violations: List[str] = []
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as e:
        return [f"{filename}:{e.lineno}: unparseable: {e.msg}"]
    loops = [n for n in ast.walk(tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
             and n.name == _LOOP_FN]
    if require_prefetcher and not loops:
        return [f"{filename}:1: no {_LOOP_FN} function — the supervised "
                f"train-loop convention (and this lint's coverage) "
                f"requires one"]
    for fn in loops:
        if not _constructs_prefetcher(fn):
            violations.append(
                f"{filename}:{fn.lineno}: {fn.name} does not construct a "
                f"DevicePrefetcher — the batch stream must ride the "
                f"prefetched input pipeline (data/prefetch.py), not "
                f"stage inline")
        for call in _loop_staging_calls(fn):
            violations.append(
                f"{filename}:{call.lineno}: {fn.name} stages a batch "
                f"inside the step loop ({_is_staging_call(call)}) — "
                f"H2D serializes after the device sync; move staging "
                f"into the DevicePrefetcher prep/put functions")
    return violations


def check(root: Path | str | None = None) -> List[str]:
    """Violations across every model module under ``root``."""
    root = Path(root) if root else Path(__file__).resolve().parents[1]
    models_dir = root / "predictionio_tpu" / "models"
    violations: List[str] = []
    for path in sorted(models_dir.glob("*.py")):
        violations.extend(check_source(
            path.read_text(encoding="utf-8"), str(path),
            require_prefetcher=path.name in _REQUIRED))
    return violations


def main(argv: List[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    violations = check(argv[0] if argv else None)
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} train-loop-lint violation(s).",
              file=sys.stderr)
        return 1
    print("lint_trainloop: deep-model train loops ride DevicePrefetcher.")
    return 0


if __name__ == "__main__":
    sys.exit(main())
