#!/usr/bin/env python
"""Static check: every server frontend handler rides BaseHandler.dispatch.

PR 3 folded the deadline-scope / shed / tracing / Retry-After transport
plumbing into ``server/http.py`` ``BaseHandler.dispatch`` and made all
four frontends ride it.  That dedup only stays true if nothing regresses
it: a NEW frontend whose ``do_GET`` writes the response directly (or
subclasses ``BaseHTTPRequestHandler`` without ``BaseHandler``) silently
loses deadlines, load shedding, request ids, and tracing.  This lint
locks the invariant in (ISSUE 4 satellite; a tier-1 test runs it in CI):

1. Every ``ClassDef`` in ``predictionio_tpu/server/*.py`` that subclasses
   ``BaseHTTPRequestHandler`` (directly or by name) must instead derive
   from ``BaseHandler``.
2. Every ``do_<METHOD>`` method of a ``BaseHandler`` subclass must call
   ``self.dispatch(...)``.
3. No ``do_<METHOD>`` body may call ``self.send_response`` /
   ``self.wfile.write`` directly — replying outside ``dispatch``/
   ``respond`` bypasses the shared headers.
4. (ISSUE 6) No request-handler function (``do_*``, ``pio_handle``, or a
   server's ``handle``) may call ``.query(...)``/``.query_batch(...)``
   directly — the model is reached ONLY through the serving scheduler
   (``predictionio_tpu/serving``), so every query rides admission
   control, the deadline-aware micro-batcher, and its metrics.  A
   handler that dispatches directly silently forfeits coalescing AND
   admission control under load.

Usage: ``python tools/lint_dispatch.py [root]`` — prints violations and
exits non-zero when any exist.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List

# Handler base classes considered "rides the shared stack".
_GOOD_BASES = {"BaseHandler"}
# Subclassing these directly is the violation rule 1 catches.
_RAW_BASES = {"BaseHTTPRequestHandler", "SimpleHTTPRequestHandler"}
# Rule 4: functions on the request path (any server's handler surface).
_HANDLER_FN_NAMES = {"pio_handle", "handle"}
# Rule 4: the model-dispatch methods only the serving scheduler may call.
_DIRECT_DISPATCH = {"query", "query_batch"}


def _base_names(cls: ast.ClassDef) -> List[str]:
    out = []
    for b in cls.bases:
        if isinstance(b, ast.Name):
            out.append(b.id)
        elif isinstance(b, ast.Attribute):
            out.append(b.attr)
    return out


def _calls_self_dispatch(fn: ast.FunctionDef) -> bool:
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "dispatch"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"):
            return True
    return False


def _direct_write_calls(fn: ast.FunctionDef) -> List[str]:
    bad = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call) or \
                not isinstance(node.func, ast.Attribute):
            continue
        a = node.func.attr
        v = node.func.value
        if a == "send_response" and isinstance(v, ast.Name) \
                and v.id == "self":
            bad.append("self.send_response")
        if a == "write" and isinstance(v, ast.Attribute) \
                and v.attr == "wfile" and isinstance(v.value, ast.Name) \
                and v.value.id == "self":
            bad.append("self.wfile.write")
    return bad


def _direct_dispatch_calls(fn: ast.FunctionDef) -> List[str]:
    """Rule 4: ``<anything>.query(...)`` / ``<anything>.query_batch(...)``
    calls inside a request-handler function."""
    bad = []
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _DIRECT_DISPATCH):
            bad.append(f".{node.func.attr}")
    return bad


def _is_handler_fn(fn: ast.FunctionDef) -> bool:
    return fn.name.startswith("do_") or fn.name in _HANDLER_FN_NAMES


def check_source(source: str, filename: str) -> List[str]:
    """Violations in one module's source (path:line prefixed strings)."""
    violations: List[str] = []
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as e:
        return [f"{filename}:{e.lineno}: unparseable: {e.msg}"]
    for node in ast.walk(tree):
        # Rule 4 applies to EVERY handler-surface function, whether or
        # not it lives in a BaseHandler subclass (the servers' `handle`
        # methods are plain class methods the Handler delegates to).
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and _is_handler_fn(node):
            for call in _direct_dispatch_calls(node):
                violations.append(
                    f"{filename}:{node.lineno}: {node.name} calls "
                    f"{call}(...) directly — the model is reached only "
                    f"through the serving scheduler "
                    f"(ServingScheduler.submit_and_wait)")
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        bases = _base_names(node)
        if node.name in _GOOD_BASES:
            continue  # BaseHandler itself is THE sanctioned raw subclass
        if any(b in _RAW_BASES for b in bases):
            violations.append(
                f"{filename}:{node.lineno}: class {node.name} subclasses "
                f"a raw http.server handler — derive from "
                f"server.http.BaseHandler so deadlines/shed/tracing apply")
            continue
        if not any(b in _GOOD_BASES for b in bases):
            continue
        for item in node.body:
            if not isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if not item.name.startswith("do_"):
                continue
            if not _calls_self_dispatch(item):
                violations.append(
                    f"{filename}:{item.lineno}: {node.name}.{item.name} "
                    f"does not call self.dispatch(...) — the request "
                    f"bypasses deadline scope, shedding, and tracing")
            for call in _direct_write_calls(item):
                violations.append(
                    f"{filename}:{item.lineno}: {node.name}.{item.name} "
                    f"calls {call} directly — reply through dispatch/"
                    f"respond instead")
    return violations


def check(root: Path | str | None = None) -> List[str]:
    """Violations across every server frontend module under ``root``."""
    root = Path(root) if root else Path(__file__).resolve().parents[1]
    server_dir = root / "predictionio_tpu" / "server"
    violations: List[str] = []
    for path in sorted(server_dir.glob("*.py")):
        violations.extend(
            check_source(path.read_text(encoding="utf-8"), str(path)))
    return violations


def main(argv: List[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    violations = check(argv[0] if argv else None)
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} dispatch-lint violation(s).",
              file=sys.stderr)
        return 1
    print("lint_dispatch: all server frontends ride BaseHandler.dispatch.")
    return 0


if __name__ == "__main__":
    sys.exit(main())
