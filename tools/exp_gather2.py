#!/usr/bin/env python
"""Gather mechanism shootout (scratch)."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

R1, R2 = 4, 20


def slope(fn, *args):
    def run(n):
        t0 = time.perf_counter()
        out = fn(jnp.int32(n), jnp.float32(0.0), *args)
        float(jnp.sum(out))
        return time.perf_counter() - t0
    run(R1)
    t1 = run(R1); t2 = run(R2)
    return (t2 - t1) / (R2 - R1) * 1e3


I, K = 59_047, 64
R, L = 20_000, 256
NNZ = R * L
rng = np.random.default_rng(0)
Y32 = jnp.asarray(rng.standard_normal((I, K), dtype=np.float32))
Y16 = Y32.astype(jnp.bfloat16)
idx = jnp.asarray((rng.zipf(1.25, size=(R, L)) % I).astype(np.int32))
idx_head = jnp.minimum(idx, 2047)


@jax.jit
def rep_gather(n, zero, Y, ix):
    def body(_, c):
        f = (Y + c.astype(Y.dtype) * zero.astype(Y.dtype))[ix]
        return jnp.sum(f.astype(jnp.float32)) * 1e-20
    return jax.lax.fori_loop(0, n, body, jnp.float32(0.0))


TILE_R = 8


def make_kernel(mode):
    def _gk(idx_ref, y_ref, o_ref, scratch):
        l = idx_ref.shape[1]
        for r in range(TILE_R):
            if mode == "take":
                scratch[:] = jnp.take(y_ref[:], idx_ref[r], axis=0,
                                      fill_value=0)
            elif mode == "loop8":
                def body(j, _):
                    for u in range(8):
                        scratch[j * 8 + u] = y_ref[idx_ref[r, j * 8 + u]]
                    return 0
                jax.lax.fori_loop(0, l // 8, body, 0)
            else:
                def body(j, _):
                    scratch[j] = y_ref[idx_ref[r, j]]
                    return 0
                jax.lax.fori_loop(0, l, body, 0)
            o_ref[r] = jnp.sum(scratch[:], axis=0)
    return _gk


def pallas_gather(mode, smem_idx=True):
    @jax.jit
    def f(ix, y):
        r, l = ix.shape
        return pl.pallas_call(
            make_kernel(mode),
            grid=(r // TILE_R,),
            in_specs=[
                pl.BlockSpec((TILE_R, l), lambda i: (i, 0),
                             memory_space=pltpu.SMEM if smem_idx else None),
                pl.BlockSpec((y.shape[0], y.shape[1]), lambda i: (0, 0)),
            ],
            out_specs=pl.BlockSpec((TILE_R, K), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((r, K), jnp.float32),
            scratch_shapes=[pltpu.VMEM((l, K), jnp.float32)],
        )(ix, y)
    return f


def rep_pallas(mode, smem_idx=True):
    g = pallas_gather(mode, smem_idx)
    @jax.jit
    def f(n, zero, ix, y):
        def body(_, c):
            o = g(ix, y + c * zero)
            return jnp.sum(o) * 1e-20
        return jax.lax.fori_loop(0, n, body, jnp.float32(0.0))
    return f


def main():
    which = sys.argv[1:] or ["xla", "take", "loop8"]
    if "xla" in which:
        ms = slope(rep_gather, Y32, idx)
        print(f"xla f32 zipf : {ms:8.2f} ms  {NNZ*K*4/ms/1e6:7.1f} GB/s "
              f"{NNZ/ms/1e6:6.2f} Gnnz/s")
        ms = slope(rep_gather, Y16, idx)
        print(f"xla bf16 zipf: {ms:8.2f} ms  {NNZ*K*2/ms/1e6:7.1f} GB/s "
              f"{NNZ/ms/1e6:6.2f} Gnnz/s")
        ms = slope(rep_gather, Y32, idx_head)
        print(f"xla f32 head : {ms:8.2f} ms  {NNZ*K*4/ms/1e6:7.1f} GB/s "
              f"{NNZ/ms/1e6:6.2f} Gnnz/s")
    if "take" in which:
        try:
            ms = slope(rep_pallas("take"), idx, Y32)
            print(f"pl take      : {ms:8.2f} ms  {NNZ/ms/1e6:6.2f} Gnnz/s")
        except Exception as e:
            print(f"pl take      : FAIL {type(e).__name__}: {str(e)[:200]}")
    if "loop8" in which:
        try:
            ms = slope(rep_pallas("loop8"), idx, Y32)
            print(f"pl loop8     : {ms:8.2f} ms  {NNZ/ms/1e6:6.2f} Gnnz/s")
        except Exception as e:
            print(f"pl loop8     : FAIL {type(e).__name__}: {str(e)[:200]}")


if __name__ == "__main__":
    main()
