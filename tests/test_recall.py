"""Online retrieval-recall observability (ISSUE 16): shadow exact
re-rank sampling, per-rung recall scorecards, and the gate-wired recall
drift detector.

Acceptance spine: a healthy server's online sampled recall@10 sits
within tolerance of its own baked scorecard baseline; a regression vs
that baseline trips on BOTH windows and folds ``recall_regression``
into the ``/quality.json`` gate the daemon/rollout already poll;
``PIO_RECALL=off`` registers zero instruments and can never block a
promotion; the scorecard rides both wrappers' pickles (old pickles
backfill); a corpus-fingerprint mismatch degrades to reporting-only;
the fleet merge carries the new fields with worst-instance (MIN)
semantics and never silently drops a key.  Detector tests ride
injectable clocks — zero wall sleeps.
"""

import dataclasses
import json
import pickle
import time
from urllib.request import Request, urlopen

import numpy as np
import pytest

from predictionio_tpu.controller import EngineVariant, RuntimeContext
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import App, get_storage
from predictionio_tpu.obs import get_registry
from predictionio_tpu.obs.quality import merge_quality
from predictionio_tpu.obs.recall import (
    RecallConfig,
    RecallDetector,
    RecallMonitor,
    RecallScorecard,
    build_recall_scorecard,
    resolve_recall_scorecard,
)
from predictionio_tpu.obs import waterfall as wfm
from predictionio_tpu.retrieval import Retriever, cached_retriever
from predictionio_tpu.retrieval.ivf import build_ivf, corpus_fingerprint
from predictionio_tpu.retrieval.pq import build_pq
from predictionio_tpu.workflow.core_workflow import load_models, run_train

RECALL_METRICS = (
    "pio_retrieval_recall",
    "pio_retrieval_recall_baseline",
    "pio_retrieval_recall_captures_total",
    "pio_retrieval_recall_scanned_fraction",
    "pio_retrieval_recall_shortlist_saturation",
    "pio_retrieval_recall_cell_miss",
    "pio_retrieval_recall_tripped",
    "pio_retrieval_recall_reporting_only",
)


def _cfg(**kw) -> RecallConfig:
    base = dict(sample=1.0, k=10, fast_window=64, reservoir=256,
                min_samples=10, tolerance=0.05, recovery_s=30.0)
    base.update(kw)
    return RecallConfig(**base)


def _corpus(n=3000, d=16, seed=0):
    rng = np.random.default_rng(seed)
    it = rng.standard_normal((n, d)).astype(np.float32)
    qv = rng.standard_normal((max(n // 10, 64), d)).astype(np.float32)
    return it, qv


def _structures(it, nlist=32, m=4):
    ivf = build_ivf(it, nlist=nlist, force=True)
    pq = build_pq(it, m=m, ivf=ivf)
    return ivf, pq


# ==========================================================================
# Scorecard build + resolve
# ==========================================================================

class TestRecallScorecard:
    def test_build_covers_rungs_and_pins_fingerprint(self):
        it, qv = _corpus()
        ivf, pq = _structures(it)
        sc = build_recall_scorecard(qv, it, ivf=ivf, pq=pq, seed=0,
                                    name="t")
        assert set(sc.recall) == {"ivf", "ivf_pq", "pq_flat"}
        for table in sc.recall.values():
            assert set(table) == {1, 10}
            for v in table.values():
                assert 0.0 <= v <= 1.0
        assert sc.fingerprint == corpus_fingerprint(it)
        assert sc.n_queries > 0
        # exact-k lookup plus the nearest-k fallback
        assert sc.expected("ivf", 10) == sc.recall["ivf"][10]
        assert sc.expected("ivf", 50) == sc.recall["ivf"][10]
        assert sc.expected("nope", 10) is None

    def test_build_seeded_deterministic(self):
        it, qv = _corpus()
        ivf, _ = _structures(it)
        a = build_recall_scorecard(qv, it, ivf=ivf, seed=3)
        b = build_recall_scorecard(qv, it, ivf=ivf, seed=3)
        assert a.recall == b.recall

    def test_no_approximate_structure_builds_none(self):
        # tiny corpora serve exact — nothing to monitor, no scorecard
        it, qv = _corpus(n=100)
        assert build_recall_scorecard(qv, it) is None

    def test_pickle_round_trip(self):
        it, qv = _corpus()
        ivf, _ = _structures(it)
        sc = build_recall_scorecard(qv, it, ivf=ivf, seed=0)
        clone = pickle.loads(pickle.dumps(sc))
        assert clone == sc
        assert clone.expected("ivf", 10) == sc.recall["ivf"][10]

    def test_resolve_fingerprint_mismatch_reporting_only(self):
        it, qv = _corpus()
        ivf, _ = _structures(it)
        sc = build_recall_scorecard(qv, it, ivf=ivf, seed=0)
        w = type("W", (), {"recall": sc, "item_vecs": it})()
        got, reason = resolve_recall_scorecard([w])
        assert got is sc and reason is None
        w.item_vecs = it * 2.0   # corpus mutated after training
        got, reason = resolve_recall_scorecard([w])
        assert got is None and reason == "fingerprint_mismatch"
        assert resolve_recall_scorecard([object()]) == (
            None, "no_scorecard")


# ==========================================================================
# Wrapper serialization (both templates)
# ==========================================================================

TT_VARIANT = {
    "id": "default",
    "engineFactory": "predictionio_tpu.templates.twotower:engine",
    "datasource": {"params": {"appName": "app"}},
    "algorithms": [{"name": "twotower",
                    "params": {"embedDim": 8, "hiddenDims": [16],
                               "outDim": 8, "epochs": 2, "batchSize": 32,
                               "seed": 1}}],
}


@pytest.fixture()
def ctx(pio_home):
    return RuntimeContext.create(storage=get_storage())


def _mk_app(ctx, name="app"):
    storage = ctx.storage
    app_id = storage.get_apps().insert(App(id=None, name=name))
    storage.get_events().init(app_id)
    return app_id


def _view(u, i):
    return Event(event="view", entity_type="user", entity_id=f"u{u}",
                 target_entity_type="item", target_entity_id=f"i{i}")


def _seed_views(ctx, app_id, n_users=10, n_items=40):
    evs = [_view(u, i) for u in range(n_users) for i in range(n_items)
           if i % 2 == u % 2]
    ctx.storage.get_events().insert_batch(evs, app_id)


def _tt():
    from predictionio_tpu.templates.twotower import engine

    return engine(), EngineVariant.from_dict(TT_VARIANT)


def _ivf_env(monkeypatch):
    # Tiny-corpus escape hatch: force the train-time IVF build below the
    # production threshold so the approximate rung (and therefore the
    # recall scorecard) exists at test scale.
    monkeypatch.setenv("PIO_IVF", "on")
    monkeypatch.setenv("PIO_IVF_MIN_ITEMS", "16")


class TestScorecardOnWrappers:
    def test_twotower_train_bakes_recall_and_pickle_keeps_it(
            self, ctx, monkeypatch):
        _ivf_env(monkeypatch)
        app_id = _mk_app(ctx)
        _seed_views(ctx, app_id)
        eng, variant = _tt()
        iid = run_train(eng, variant, ctx)
        wrapper = load_models(
            eng, ctx.storage.get_engine_instances().get(iid), ctx)[0]
        sc = wrapper.recall
        assert isinstance(sc, RecallScorecard)
        assert "ivf" in sc.recall
        assert sc.fingerprint == corpus_fingerprint(
            np.ascontiguousarray(wrapper.item_vecs, dtype=np.float32))
        clone = pickle.loads(pickle.dumps(wrapper))
        assert clone.recall == sc     # model+scorecard = ONE artifact
        got, reason = resolve_recall_scorecard([clone])
        assert got == sc and reason is None

    def test_als_wrapper_carries_and_pickles_recall(self):
        from predictionio_tpu.data.event import BiMap
        from predictionio_tpu.models.als import ALSModel
        from predictionio_tpu.templates.recommendation.engine import (
            ALSModelWrapper,
        )

        it, qv = _corpus(n=400, d=8, seed=5)
        ivf = build_ivf(it, nlist=8, force=True)
        sc = build_recall_scorecard(qv, it, ivf=ivf, seed=0, name="als")
        w = ALSModelWrapper(
            model=ALSModel(user_factors=qv, item_factors=it, rank=8,
                           implicit=True),
            user_index=BiMap({f"u{i}": i for i in range(len(qv))}),
            item_index=BiMap({f"i{i}": i for i in range(len(it))}),
            ivf=ivf, recall=sc)
        clone = pickle.loads(pickle.dumps(w))
        assert clone.recall == sc

    def test_old_pickles_backfill_recall_on_both_wrappers(self):
        from predictionio_tpu.templates.recommendation.engine import (
            ALSModelWrapper,
        )
        from predictionio_tpu.templates.twotower.engine import (
            TwoTowerModelWrapper,
        )

        for cls in (TwoTowerModelWrapper, ALSModelWrapper):
            # a pre-ISSUE-16 pickle: required fields only, no 'recall'
            state = {f.name: None for f in dataclasses.fields(cls)
                     if f.default is dataclasses.MISSING}
            assert "recall" not in state
            w = cls.__new__(cls)
            w.__setstate__(state)
            assert w.recall is None, cls.__name__


# ==========================================================================
# Detector (fake clock, zero wall sleeps)
# ==========================================================================

def _scorecard(baseline=0.9, rungs=("ivf",)):
    return RecallScorecard(
        recall={r: {1: baseline, 10: baseline} for r in rungs},
        n_queries=128, fingerprint="fp")


class TestRecallDetector:
    def test_healthy_stream_never_trips(self):
        det = RecallDetector(_cfg(), _scorecard(0.9), clock=lambda: 0.0)
        for _ in range(200):
            det.add("ivf", 0.9)
        s = det.tick(force=True)
        assert not s["tripped"]
        assert s["rungs"]["ivf"]["recallFast"] == pytest.approx(0.9)
        assert s["rungs"]["ivf"]["baseline"] == pytest.approx(0.9)

    def test_regression_trips_on_both_windows(self):
        det = RecallDetector(_cfg(), _scorecard(0.9), clock=lambda: 0.0)
        for _ in range(200):
            det.add("ivf", 0.6)
        s = det.tick(force=True)
        assert s["tripped"]
        assert s["rungs"]["ivf"]["tripped"]

    def test_fast_burst_alone_does_not_trip(self):
        # the slow reservoir still holds mostly-healthy mass: one bad
        # burst must not read as a generation-wide regression
        det = RecallDetector(_cfg(reservoir=2000), _scorecard(0.9),
                             clock=lambda: 0.0)
        for _ in range(1500):
            det.add("ivf", 0.9)
        for _ in range(80):      # fills the fast window only
            det.add("ivf", 0.2)
        s = det.tick(force=True)
        r = s["rungs"]["ivf"]
        assert r["recallFast"] < 0.9 - 0.05
        assert r["recallSlow"] > 0.9 - 0.05
        assert not s["tripped"]

    def test_cold_rung_pass_through(self):
        det = RecallDetector(_cfg(min_samples=100), _scorecard(0.9),
                             clock=lambda: 0.0)
        for _ in range(50):      # badly regressed but below the floor
            det.add("ivf", 0.1)
        s = det.tick(force=True)
        assert s["insufficient"] and not s["tripped"]

    def test_hysteresis_clears_only_after_dwell(self):
        t = [0.0]
        det = RecallDetector(_cfg(recovery_s=30.0, fast_window=50,
                                  reservoir=50),
                             _scorecard(0.9), clock=lambda: t[0])
        for _ in range(60):
            det.add("ivf", 0.5)
        assert det.tick(force=True)["tripped"]
        for _ in range(200):     # recovered: both windows refill healthy
            det.add("ivf", 0.9)
        t[0] += 2.0
        assert det.tick(force=True)["tripped"], "dwell must hold"
        t[0] += 31.0
        assert not det.tick(force=True)["tripped"]

    def test_missing_scorecard_reporting_only(self):
        det = RecallDetector(_cfg(), None, reporting_reason="no_scorecard",
                             clock=lambda: 0.0)
        for _ in range(100):
            det.add("ivf", 0.0)
        s = det.tick(force=True)
        assert s["reportingOnly"] and s["reason"] == "no_scorecard"
        assert not s["tripped"]

    def test_per_rung_isolation(self):
        det = RecallDetector(_cfg(), _scorecard(0.9, ("ivf", "ivf_pq")),
                             clock=lambda: 0.0)
        for _ in range(100):
            det.add("ivf", 0.9)
            det.add("ivf_pq", 0.4)
        s = det.tick(force=True)
        assert not s["rungs"]["ivf"]["tripped"]
        assert s["rungs"]["ivf_pq"]["tripped"]
        assert s["tripped"]


# ==========================================================================
# Monitor: capture path, kill switch, gate folding
# ==========================================================================

class _Wrap:
    def __init__(self, it, ivf=None, pq=None, sc=None):
        self.item_vecs = it
        self.recall = sc
        self._r = Retriever(it, ivf=ivf, pq=pq, name="t")
        # Register in the facade's retriever cache like a real wrapper
        # would, so `arm_on_create` sees it as already-built.
        cached_retriever(self, lambda: self._r)

    def retriever(self):
        return cached_retriever(self, lambda: self._r)


def _drive(retriever, qv, n, batch=4, u=0.0, rung="ivf_pq",
           monkeypatch=None):
    if monkeypatch is not None:
        monkeypatch.setenv("PIO_RETRIEVAL_RUNG", rung)
    for i in range(n):
        sink = wfm.Waterfall()
        sink.sample_u = u
        with wfm.dispatch_sink(sink):
            s, ids, info = retriever.topk(
                qv[(i * batch) % len(qv):(i * batch) % len(qv) + batch],
                10)
    return info


class TestRecallMonitor:
    def test_capture_score_payload_healthy(self, pio_home, monkeypatch):
        it, qv = _corpus()
        ivf, pq = _structures(it)
        sc = build_recall_scorecard(qv, it, ivf=ivf, pq=pq, seed=0)
        w = _Wrap(it, ivf, pq, sc)
        mon = RecallMonitor(_cfg(min_samples=5))
        mon.on_generation(1, [w])
        assert w._r.recall_hook is not None
        info = _drive(w._r, qv, 30, monkeypatch=monkeypatch)
        assert info["rung"] == "ivf_pq"
        while mon.drain_once():
            pass
        doc = mon.payload()
        row = doc["rungs"]["ivf_pq"]
        assert row["nFast"] >= 5 and row["baseline"] is not None
        # live recall of the same structures matches their own baseline
        assert abs(row["recallFast"] - row["baseline"]) < 0.1
        assert doc["verdict"] == "healthy" and not doc["tripped"]
        # miss attribution + scanned fraction populated for the PQ rung
        assert row["scannedFraction"] is not None
        assert row["cellMiss"] is not None
        assert row["shortlistSaturation"] is not None
        mon.close()

    def test_unsampled_requests_never_enqueue(self, pio_home,
                                              monkeypatch):
        it, qv = _corpus()
        ivf, pq = _structures(it)
        w = _Wrap(it, ivf, pq, build_recall_scorecard(qv, it, ivf=ivf,
                                                      pq=pq))
        mon = RecallMonitor(_cfg(sample=0.05))
        mon.on_generation(1, [w])
        _drive(w._r, qv, 10, u=0.5, monkeypatch=monkeypatch)  # u > rate
        assert mon.drain_once() == 0
        # and with no active waterfall at all (sample_u None) — no-op
        monkeypatch.setenv("PIO_RETRIEVAL_RUNG", "ivf_pq")
        w._r.topk(qv[:4], 10)
        assert mon.drain_once() == 0
        mon.close()

    def test_queue_bound_drops_never_blocks(self, pio_home,
                                            monkeypatch):
        it, qv = _corpus()
        ivf, pq = _structures(it)
        w = _Wrap(it, ivf, pq, None)
        mon = RecallMonitor(_cfg(queue=2))
        mon.on_generation(1, [w])
        # stall the worker by submitting faster than we drain: call the
        # hook directly so nothing drains in between
        hook = w._r.recall_hook
        sink = wfm.Waterfall()
        sink.sample_u = 0.0
        plan = type("P", (), {"rung": "ivf", "k": 10, "nprobe": 2,
                              "rerank": 0})()
        mon._thread = type("T", (), {"is_alive": lambda self: True})()
        with wfm.dispatch_sink(sink):
            for _ in range(5):
                hook(w._r, plan, qv[:1],
                     np.zeros((1, 10), np.int32), 100)
        reg = get_registry()
        assert reg.get("pio_retrieval_recall_captures_total") \
            .value(result="dropped") == 3
        mon._thread = None
        mon.close()

    def test_generation_swap_detaches_old_hook_and_drops_stale(
            self, pio_home, monkeypatch):
        it, qv = _corpus()
        ivf, pq = _structures(it)
        w1, w2 = _Wrap(it, ivf, pq, None), _Wrap(it, ivf, pq, None)
        mon = RecallMonitor(_cfg())
        mon.on_generation(1, [w1])
        mon._thread = type("T", (), {"is_alive": lambda self: True})()
        _drive(w1._r, qv, 2, monkeypatch=monkeypatch)   # queued, gen 1
        mon.on_generation(2, [w2])                       # swap clears
        assert w1._r.recall_hook is None
        assert w2._r.recall_hook is not None
        assert mon.drain_once() == 0                     # queue cleared
        # a capture from the OLD retriever after the swap is stale
        _drive(w1._r, qv, 1, monkeypatch=monkeypatch)
        w1._r.recall_hook = mon._capture   # simulate late-armed hook
        _drive(w1._r, qv, 1, monkeypatch=monkeypatch)
        assert mon.drain_once() == 1
        assert get_registry().get("pio_retrieval_recall_captures_total") \
            .value(result="stale") == 1
        mon._thread = None
        mon.close()

    def test_arming_never_forces_retriever_creation(self, pio_home,
                                                    monkeypatch):
        # Retriever creation (and with it index fingerprint validation)
        # is lazy on the first query; the monitor must observe, not
        # change, that — it arms via arm_on_create, which fires only
        # when the facade builds the retriever.
        it, qv = _corpus()
        ivf, pq = _structures(it)

        class LazyWrap:
            built = 0

            def __init__(self):
                self.item_vecs = it
                self.recall = None

            def retriever(self):
                def build():
                    self.built += 1
                    return Retriever(it, ivf=ivf, pq=pq, name="lazy")

                return cached_retriever(self, build)

        w = LazyWrap()
        mon = RecallMonitor(_cfg())
        mon.on_generation(1, [w])
        assert w.built == 0            # model load builds nothing
        r = w.retriever()              # first query builds → arm fires
        assert w.built == 1
        assert r.recall_hook is not None
        # a pending arm for a swapped-out generation must no-op
        w2 = LazyWrap()
        mon.on_generation(2, [w2])
        mon.on_generation(3, [])
        assert w2.retriever().recall_hook is None
        mon.close()

    def test_kill_switch_registers_zero_instruments(self, pio_home,
                                                    monkeypatch):
        monkeypatch.setenv("PIO_RECALL", "off")
        it, qv = _corpus()
        ivf, pq = _structures(it)
        w = _Wrap(it, ivf, pq, None)
        mon = RecallMonitor()
        assert not mon.enabled
        mon.on_generation(1, [w])
        assert w._r.recall_hook is None      # hook never armed
        assert mon.payload() == {"enabled": False}
        doc = {"enabled": True, "verdict": "healthy",
               "gate": {"enabled": True, "rollback": False,
                        "reasons": []}}
        assert mon.augment_quality(doc) is doc   # passes UNTOUCHED
        mon.close()
        reg = get_registry()
        for name in RECALL_METRICS:
            assert reg.get(name) is None, name

    def test_augment_folds_gate_and_respects_gate_switch(
            self, pio_home, monkeypatch):
        it, qv = _corpus()
        ivf, pq = _structures(it)
        sc = _scorecard(0.95, ("ivf_pq",))
        sc.fingerprint = None
        w = _Wrap(it, ivf, pq, sc)
        mon = RecallMonitor(_cfg(min_samples=5))
        mon.on_generation(3, [w])
        # the real structures recall ~0.6 against a 0.95 baseline: rot
        _drive(w._r, qv, 30, monkeypatch=monkeypatch)
        while mon.drain_once():
            pass
        quality = {"enabled": True, "verdict": "healthy",
                   "gate": {"enabled": True, "rollback": False,
                            "reasons": []}}
        out = mon.augment_quality(dict(quality))
        assert out["recall"]["tripped"]
        assert out["gate"]["rollback"]
        assert "recall_regression" in out["gate"]["reasons"]
        assert out["verdict"] == "degraded"
        mon.close()
        # PIO_RECALL_GATE=off: reports, never gates
        mon2 = RecallMonitor(_cfg(min_samples=5, gate=False))
        mon2.on_generation(3, [w])
        _drive(w._r, qv, 30, monkeypatch=monkeypatch)
        while mon2.drain_once():
            pass
        out2 = mon2.augment_quality(dict(quality))
        assert out2["recall"]["tripped"]
        assert not out2["gate"]["rollback"]
        assert out2["verdict"] == "healthy"
        mon2.close()

    def test_fingerprint_mismatch_is_reporting_only_never_gates(
            self, pio_home, monkeypatch):
        it, qv = _corpus()
        ivf, pq = _structures(it)
        sc = _scorecard(0.99, ("ivf_pq",))
        sc.fingerprint = "not-the-corpus"
        w = _Wrap(it, ivf, pq, sc)
        mon = RecallMonitor(_cfg(min_samples=5))
        mon.on_generation(1, [w])
        _drive(w._r, qv, 30, monkeypatch=monkeypatch)
        while mon.drain_once():
            pass
        doc = mon.payload()
        assert doc["reportingOnly"]
        assert doc["reason"] == "fingerprint_mismatch"
        assert doc["verdict"] == "reporting_only"
        assert not doc["tripped"]
        out = mon.augment_quality({"enabled": True, "verdict": "healthy",
                                   "gate": {"enabled": True,
                                            "rollback": False,
                                            "reasons": []}})
        assert not out["gate"]["rollback"]
        mon.close()

    def test_quality_layer_off_still_publishes_a_gate(self, pio_home,
                                                      monkeypatch):
        it, qv = _corpus()
        ivf, pq = _structures(it)
        w = _Wrap(it, ivf, pq, None)
        mon = RecallMonitor(_cfg())
        mon.on_generation(2, [w])
        out = mon.augment_quality({"enabled": False})
        assert out["enabled"] and not out["qualityLayerEnabled"]
        assert out["gate"]["rollback"] is False
        assert out["recall"]["enabled"]
        mon.close()


# ==========================================================================
# Fleet merge: schema stability + worst-instance semantics
# ==========================================================================

def _doc_keys(doc, prefix=""):
    out = set()
    for k, v in doc.items():
        out.add(prefix + k)
        if isinstance(v, dict):
            out |= _doc_keys(v, prefix + k + ".")
    return out


def _recall_doc(fast, slow, baseline, captured=10, tripped=False):
    return {
        "enabled": True,
        "verdict": "degraded" if tripped else "healthy",
        "recall": {
            "enabled": True, "tripped": tripped, "reportingOnly": False,
            "captured": captured, "scored": captured, "dropped": 0,
            "rungs": {"ivf_pq": {
                "recallFast": fast, "recallSlow": slow,
                "baseline": baseline, "nFast": captured,
                "nSlow": captured, "tripped": tripped,
                "shortlistSaturation": 0.1, "cellMiss": 0.2,
                "scannedFraction": 0.05}},
        },
        "gate": {"enabled": True, "rollback": tripped,
                 "reasons": ["recall_regression"] if tripped else []},
    }


class TestRecallFleetMerge:
    def test_merge_never_silently_drops_recall_fields(self, pio_home):
        d1 = _recall_doc(0.9, 0.92, 0.95)
        d2 = _recall_doc(0.6, 0.65, 0.95, tripped=True)
        merged = merge_quality([d1, d2])
        missing = (_doc_keys(d1) | _doc_keys(d2)) - _doc_keys(merged)
        assert not missing, f"fleet merge dropped fields: {missing}"

    def test_worst_instance_semantics(self, pio_home):
        d1 = _recall_doc(0.9, 0.92, 0.95, captured=10)
        d2 = _recall_doc(0.6, 0.65, 0.93, captured=7, tripped=True)
        merged = merge_quality([d1, d2])
        row = merged["recall"]["rungs"]["ivf_pq"]
        # recall takes the WORST instance (min), counts sum
        assert row["recallFast"] == pytest.approx(0.6)
        assert row["recallSlow"] == pytest.approx(0.65)
        assert row["baseline"] == pytest.approx(0.93)
        assert row["nFast"] == 17
        assert merged["recall"]["captured"] == 17
        # one rotten replica surfaces fleet-wide
        assert merged["recall"]["tripped"]
        assert merged["gate"]["rollback"]
        assert "recall_regression" in merged["gate"]["reasons"]
        assert merged["verdict"] == "degraded"

    def test_union_of_keys_with_pre_recall_instance(self, pio_home):
        # an older instance without the recall block: the key survives
        old = {"enabled": True, "verdict": "healthy",
               "gate": {"enabled": True, "rollback": False,
                        "reasons": []}}
        new = _recall_doc(0.9, 0.92, 0.95)
        merged = merge_quality([old, new])
        assert "recall" in merged
        assert merged["recall"]["rungs"]["ivf_pq"]["recallFast"] \
            == pytest.approx(0.9)

    def test_live_monitor_payload_survives_merge(self, pio_home,
                                                 monkeypatch):
        it, qv = _corpus()
        ivf, pq = _structures(it)
        sc = build_recall_scorecard(qv, it, ivf=ivf, pq=pq, seed=0)
        w = _Wrap(it, ivf, pq, sc)
        mon = RecallMonitor(_cfg(min_samples=5))
        mon.on_generation(1, [w])
        _drive(w._r, qv, 20, monkeypatch=monkeypatch)
        while mon.drain_once():
            pass
        doc = mon.augment_quality({"enabled": True, "verdict": "healthy",
                                   "gate": {"enabled": True,
                                            "rollback": False,
                                            "reasons": []}})
        merged = merge_quality([doc, json.loads(json.dumps(doc))])
        assert not (_doc_keys(doc) - _doc_keys(merged))
        assert merged["recall"]["rungs"]["ivf_pq"]["recallFast"] \
            == doc["recall"]["rungs"]["ivf_pq"]["recallFast"]
        mon.close()

    def test_lint_rule5_recall_metrics_only_in_recall_module(self):
        import tools.lint_metrics as lint

        bad = ("import x\n"
               "reg.gauge('pio_retrieval_recall_rogue', 'h', ())\n")
        v = lint.check_source(bad, "predictionio_tpu/server/foo.py", {})
        assert any("rule 5" in s for s in v)
        ok = lint.check_source(bad, "predictionio_tpu/obs/recall.py", {})
        assert not any("rule 5" in s for s in ok)
        # other pio_retrieval_* families are NOT captured by rule 5
        fine = ("import x\n"
                "reg.counter('pio_retrieval_requests_total', 'h', ())\n")
        assert not any(
            "rule 5" in s for s in lint.check_source(
                fine, "predictionio_tpu/retrieval/__init__.py", {}))
        # and the real tree passes wholesale
        assert lint.check() == []


# ==========================================================================
# Live e2e: healthy server, shared draw, kill switch on the wire
# ==========================================================================

def _http(base, method, path, payload=None):
    data = json.dumps(payload).encode() if payload is not None else None
    req = Request(base + path, data=data, method=method,
                  headers={"Content-Type": "application/json"})
    with urlopen(req, timeout=15) as resp:
        return resp.status, json.loads(resp.read() or b"{}")


class TestRecallE2E:
    def test_healthy_server_online_recall_matches_baseline(
            self, ctx, monkeypatch):
        _ivf_env(monkeypatch)
        monkeypatch.setenv("PIO_RETRIEVAL_RUNG", "ivf")
        monkeypatch.setenv("PIO_RECALL_SAMPLE", "1.0")
        monkeypatch.setenv("PIO_RECALL_MIN_SAMPLES", "10")
        monkeypatch.setenv("PIO_RECALL_FAST_WINDOW", "48")
        app_id = _mk_app(ctx)
        _seed_views(ctx, app_id)
        eng, variant = _tt()
        run_train(eng, variant, ctx)
        from predictionio_tpu.server import EngineServer

        srv = EngineServer(eng, variant, ctx.storage, host="127.0.0.1",
                           port=0)
        srv.start()
        base = f"http://127.0.0.1:{srv.port}"
        try:
            for k in range(40):
                st, _ = _http(base, "POST", "/queries.json",
                              {"user": f"u{k % 10}", "num": 3})
                assert st == 200
            # off-thread worker: wait for the queue to drain
            doc = None
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                st, doc = _http(base, "GET", "/quality.json")
                assert st == 200
                rec = doc.get("recall") or {}
                row = (rec.get("rungs") or {}).get("ivf")
                if row and row["nFast"] >= 10 \
                        and rec.get("captured") == rec.get("scored"):
                    break
                time.sleep(0.1)
            rec = doc["recall"]
            row = rec["rungs"]["ivf"]
            assert row["baseline"] is not None
            # online recall@10 within tolerance of the baked baseline
            assert row["recallFast"] >= row["baseline"] \
                - rec["tolerance"]
            assert not rec["tripped"]
            assert rec["verdict"] == "healthy"
            assert not doc["gate"]["rollback"]
            # the exposition carries the single-owner gauge family
            st, _ = _http(base, "GET", "/quality.json")
            reg = get_registry()
            fam = reg.get("pio_retrieval_recall")
            assert fam is not None
        finally:
            srv.stop()

    def test_kill_switch_on_live_server(self, ctx, monkeypatch):
        _ivf_env(monkeypatch)
        monkeypatch.setenv("PIO_RETRIEVAL_RUNG", "ivf")
        monkeypatch.setenv("PIO_RECALL", "off")
        app_id = _mk_app(ctx)
        _seed_views(ctx, app_id)
        eng, variant = _tt()
        run_train(eng, variant, ctx)
        from predictionio_tpu.server import EngineServer

        srv = EngineServer(eng, variant, ctx.storage, host="127.0.0.1",
                           port=0)
        srv.start()
        base = f"http://127.0.0.1:{srv.port}"
        try:
            for k in range(10):
                st, _ = _http(base, "POST", "/queries.json",
                              {"user": f"u{k % 10}", "num": 3})
                assert st == 200
            st, doc = _http(base, "GET", "/quality.json")
            assert st == 200
            # no recall block, no gate contribution, zero instruments
            assert "recall" not in doc
            assert not doc["gate"]["rollback"]
            reg = get_registry()
            for name in RECALL_METRICS:
                assert reg.get(name) is None, name
        finally:
            srv.stop()
