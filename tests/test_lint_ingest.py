"""tools/lint_ingest.py: the ingest plane stays batched, segment files
stay behind SegmentStore.

ISSUE 17 satellite — the bulk endpoint and the columnar segment store
only keep their guarantees while nobody reintroduces a per-row ingest
loop or a second ad-hoc segment reader/writer; both regressions fail
tier-1 structurally.
"""

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO / "tools"))

import lint_ingest  # noqa: E402


def test_tree_is_clean():
    assert lint_ingest.check(REPO) == []


def test_detects_create_event_in_ingest_plane():
    src = """
def relay(client, payload):
    client.create_event(**payload)
"""
    violations = lint_ingest.check_source(
        src, "t.py", ("webhooks", "forwarder.py"), in_ingest_plane=True)
    assert len(violations) == 1
    assert "create_batch" in violations[0]


def test_create_event_allowed_outside_plane():
    src = "def go(c, p):\n    c.create_event(**p)\n"
    assert lint_ingest.check_source(
        src, "sdk.py", ("predictionio_tpu", "sdk.py"),
        in_ingest_plane=False) == []


def test_detects_insert_loop_direct_chain():
    src = """
def land(storage, events, app_id):
    for ev in events:
        storage.get_events().insert(ev, app_id)
"""
    violations = lint_ingest.check_source(
        src, "t.py", ("server", "event_server.py"), in_ingest_plane=True)
    assert len(violations) == 1
    assert "loop" in violations[0]


def test_detects_insert_loop_split_chain():
    src = """
def land(storage, events, app_id):
    repo = storage.get_events()
    for ev in events:
        repo.insert(ev, app_id)
"""
    violations = lint_ingest.check_source(
        src, "t.py", ("server", "event_server.py"), in_ingest_plane=True)
    assert len(violations) == 1


def test_single_insert_outside_loop_passes():
    # one row landing one row is fine — only the LOOP is the regression
    src = """
def land_one(storage, ev, app_id):
    storage.get_events().insert(ev, app_id)
"""
    assert lint_ingest.check_source(
        src, "t.py", ("server", "event_server.py"),
        in_ingest_plane=True) == []


def test_helper_defined_in_loop_is_not_a_loop_call():
    src = """
def build(storage, app_ids):
    fns = []
    for app_id in app_ids:
        def _f(ev, a=app_id):
            return storage.get_events().insert(ev, a)
        fns.append(_f)
    return fns
"""
    assert lint_ingest.check_source(
        src, "t.py", ("server", "event_server.py"),
        in_ingest_plane=True) == []


def test_detects_raw_segment_open():
    src = """
def peek(path):
    with open(path + ".seg", "rb") as f:
        return f.read()
"""
    violations = lint_ingest.check_source(
        src, "t.py", ("refresh", "daemon.py"), in_ingest_plane=False)
    assert len(violations) == 1
    assert "SegmentStore" in violations[0]


def test_detects_fstring_segment_open():
    src = """
def peek(d, seq):
    return open(f"{d}/seg-{seq}.seg", "rb").read()
"""
    violations = lint_ingest.check_source(
        src, "t.py", ("server", "event_server.py"), in_ingest_plane=True)
    assert len(violations) == 1


def test_columnar_may_open_segments():
    src = "def rd(p):\n    return open(str(p) + '.seg', 'rb').read()\n"
    assert lint_ingest.check_source(
        src, "columnar.py", ("data", "columnar.py"),
        in_ingest_plane=False) == []
