"""tools/lint_metrics.py: the metrics schema stays fleet-merge-stable.

ISSUE 9 satellite — the fleet aggregator merges /metrics expositions by
TYPE (counters sum, histogram buckets add per-le, gauges keep an
instance label).  That merge is only correct while every metric is
pio_-prefixed, literally named, registered with ONE (kind, label-set)
schema, and histograms declare schema-stable buckets.  This test runs
the lint over the real tree and pins each rule against synthetic
violations.
"""

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO / "tools"))

import lint_metrics  # noqa: E402


def test_tree_is_clean():
    assert lint_metrics.check(REPO) == []


def test_detects_computed_metric_name():
    src = """
from predictionio_tpu.obs import get_registry
name = "pio_" + kind
get_registry().counter(name, "computed name")
"""
    violations = lint_metrics.check_source(src, "bad.py")
    assert len(violations) == 1
    assert "not a string literal" in violations[0]


def test_detects_missing_pio_prefix():
    src = """
from predictionio_tpu.obs import get_registry
get_registry().gauge("requests_total", "bare name")
"""
    violations = lint_metrics.check_source(src, "bad.py")
    assert len(violations) == 1
    assert "pio_ prefix" in violations[0]


def test_detects_non_literal_labelnames():
    src = """
from predictionio_tpu.obs import get_registry
labels = ("model",)
get_registry().counter("pio_x_total", "h", labels)
"""
    violations = lint_metrics.check_source(src, "bad.py")
    assert len(violations) == 1
    assert "labelnames" in violations[0]


def test_detects_kind_and_label_schema_collisions():
    src = """
from predictionio_tpu.obs import get_registry
get_registry().counter("pio_x_total", "h", ("model",))
get_registry().gauge("pio_x_total", "h")
get_registry().counter("pio_x_total", "h", ("model", "rung"))
"""
    violations = lint_metrics.check_source(src, "bad.py")
    assert any("already a counter" in v for v in violations)
    assert any("one (name, label-set) schema" in v for v in violations)


def test_cross_module_collision_caught_via_shared_registry():
    registry = {}
    a = lint_metrics.check_source(
        'r.histogram("pio_y_ms", "h", ("stage",))', "a.py", registry)
    b = lint_metrics.check_source(
        'r.histogram("pio_y_ms", "h", ("model",))', "b.py", registry)
    assert a == []
    assert len(b) == 1 and "a.py" in b[0]


def test_histogram_bucket_rules():
    # literal tuple: fine; UPPERCASE module constant: fine;
    # runtime-computed: violation; differing literals: violation.
    registry = {}
    assert lint_metrics.check_source(
        'r.histogram("pio_b_ms", "h", (), buckets=(1.0, 5.0))',
        "a.py", registry) == []
    assert lint_metrics.check_source(
        'r.histogram("pio_c_ms", "h", (), buckets=LATENCY_BUCKETS)',
        "a.py", registry) == []
    v = lint_metrics.check_source(
        'r.histogram("pio_d_ms", "h", (), buckets=make_buckets())',
        "a.py", registry)
    assert len(v) == 1 and "computed at runtime" in v[0]
    v = lint_metrics.check_source(
        'r.histogram("pio_b_ms", "h", (), buckets=(1.0, 9.0))',
        "b.py", registry)
    assert len(v) == 1 and "differ" in v[0]


def test_main_exit_codes(tmp_path, capsys):
    pkg = tmp_path / "predictionio_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        'r.counter("no_prefix_total", "h")\n', encoding="utf-8")
    assert lint_metrics.main([str(tmp_path)]) == 1
    out = capsys.readouterr()
    assert "pio_ prefix" in out.out
    (pkg / "mod.py").write_text(
        'r.counter("pio_ok_total", "h")\n', encoding="utf-8")
    assert lint_metrics.main([str(tmp_path)]) == 0
