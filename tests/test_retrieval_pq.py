"""Quantized corpora (ISSUE 13): PQ round-trips, scan parity, recall
with exact re-rank, the fingerprint tripwire, and generation atomicity.

The contract under test: PQ codes ORDER a shortlist, the exact re-rank
DECIDES the top-k — so recall@10 ≥ 0.95 on the synthetic clustered
corpus at defaults, every scan backend (Pallas-interpret kernel, XLA
gather fallback, host numpy) ranks identically, and a codebook that
does not fingerprint-match the served corpus is dropped loudly with
exact serving continuing.  Server-level tests prove staged reload,
canary rejection and rollback each leave index+codes+model consistent.
CPU-only; the Pallas kernel runs in interpret mode on tiny shapes.
"""

import os
import pickle

import jax.numpy as jnp
import numpy as np
import pytest

from predictionio_tpu.retrieval import (
    PQCodebook,
    Retriever,
    build_ivf,
    build_pq,
    build_train_pq,
    corpus_fingerprint,
)
from predictionio_tpu.retrieval.pq import (
    decode_pq,
    lut_tables,
    pq_build_config,
    quantize_int8,
    search_ivf_pq_host,
    search_pq_host,
)


def _clustered_corpus(n=4000, d=16, n_clusters=40, seed=0, n_q=64):
    """Well-separated direction clusters + queries near members — the
    same shape test_retrieval.py uses for the IVF recall pin."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(n_clusters, d)).astype(np.float32)
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    assign = rng.integers(0, n_clusters, n)
    items = centers[assign] + 0.15 * rng.normal(size=(n, d)).astype(
        np.float32)
    items /= np.linalg.norm(items, axis=1, keepdims=True)
    q_src = rng.integers(0, n, n_q)
    queries = items[q_src] + 0.05 * rng.normal(size=(n_q, d)).astype(
        np.float32)
    queries /= np.linalg.norm(queries, axis=1, keepdims=True)
    return queries.astype(np.float32), items.astype(np.float32)


def _exact_ids(queries, items, k):
    s = queries @ items.T
    return np.argsort(-s, axis=1, kind="stable")[:, :k]


def _recall(ids, want, k=10):
    hit = sum(len(set(ids[b, :k]) & set(want[b])) for b in
              range(len(want)))
    return hit / want.size


# -- codebook build / encode-decode ------------------------------------------


class TestBuild:
    def test_encode_decode_error_bound(self):
        """Residual PQ reconstruction beats coarse-only, and the LUT
        score error is bounded by ||q||·||x - x̂|| per item."""
        q, items = _clustered_corpus(n=2000)
        pq = build_pq(items, m=4)
        dec = decode_pq(pq)
        assert dec.shape == items.shape
        res_err = np.linalg.norm(items - dec, axis=1)
        coarse_only = np.linalg.norm(
            items - pq.coarse[pq.codes[:, 0].astype(int)], axis=1)
        assert res_err.mean() < 0.5 * coarse_only.mean()
        # score bound: |q·x − lut_sum| ≤ ‖q‖·‖x−x̂‖ (Cauchy-Schwarz)
        luts = lut_tables(pq, q[:4])
        acc = luts[:, 0, :][:, pq.codes[:, 0]]
        for m in range(1, pq.n_tables):
            acc = acc + luts[:, m, :][:, pq.codes[:, m]]
        exact = q[:4] @ items.T
        qn = np.linalg.norm(q[:4], axis=1)[:, None]
        assert (np.abs(exact - acc) <= qn * res_err[None, :] + 1e-4).all()

    def test_lut_sum_equals_q_dot_decode(self):
        """The asymmetric LUT score of item n is EXACTLY q·decode(n)."""
        q, items = _clustered_corpus(n=800)
        pq = build_pq(items, m=8)
        luts = lut_tables(pq, q[:8])
        want = q[:8] @ decode_pq(pq).T
        acc = luts[:, 0, :][:, pq.codes[:, 0]]
        for m in range(1, pq.n_tables):
            acc = acc + luts[:, m, :][:, pq.codes[:, m]]
        np.testing.assert_allclose(acc, want, rtol=1e-4, atol=1e-4)

    def test_bytes_per_item_and_m_resolution(self):
        _, items = _clustered_corpus(n=600)
        pq = build_pq(items, m=4)
        assert pq.bytes_per_item() == 5       # coarse byte + 4 codes
        assert pq.codes.dtype == np.uint8
        # m rounds DOWN to a divisor of D (d=16: 5 → 4)
        pq5 = build_pq(items[:300], m=5)
        assert pq5.m == 4 and pq5.dsub == 4

    def test_coarse_book_rides_ivf_centroids(self):
        """nlist ≤ 256: the residual coarse book derives from the IVF
        centroids — PQ sits on top of the existing coarse structure."""
        _, items = _clustered_corpus(n=1200)
        ivf = build_ivf(items, nlist=12, force=True)
        pq = build_pq(items, m=4, ivf=ivf)
        assert pq.n_coarse == 12
        # refined but seeded from the 12 cells: assignments must cover
        # only the real rows, never the zero padding
        assert pq.codes[:, 0].max() < 12
        assert (np.abs(pq.coarse[12:]) == 0).all()

    def test_build_config_policy(self, monkeypatch):
        monkeypatch.setenv("PIO_PQ_MIN_ITEMS", "1000")
        monkeypatch.delenv("PIO_PQ", raising=False)
        build, m, min_items = pq_build_config(999, 32)
        assert (build, min_items) == (False, 1000)
        # the threshold is the contract, PIO_PQ=on included
        monkeypatch.setenv("PIO_PQ", "on")
        assert pq_build_config(999, 32)[0] is False
        build, m, _ = pq_build_config(1000, 32)
        assert build and m == 8               # ~D/4
        monkeypatch.setenv("PIO_PQ_M", "16")
        assert pq_build_config(1000, 32)[1] == 16
        monkeypatch.setenv("PIO_PQ_M", "junk")
        assert pq_build_config(1000, 32)[1] == 8  # loud fallback
        monkeypatch.setenv("PIO_PQ", "off")
        assert pq_build_config(10 ** 7, 32)[0] is False

    def test_unrecognized_pio_pq_warns_and_autos(
            self, monkeypatch, caplog):
        """A typo'd opt-out (PIO_PQ=0ff) must not silently build-and-
        serve codes the operator tried to disable."""
        import logging

        monkeypatch.setenv("PIO_PQ", "0ff")
        monkeypatch.setenv("PIO_PQ_MIN_ITEMS", "100")
        with caplog.at_level(logging.WARNING,
                             logger="predictionio_tpu.retrieval.pq"):
            build, m, _ = pq_build_config(1000, 32)
        assert build  # auto semantics
        assert any("PIO_PQ" in rec.getMessage()
                   for rec in caplog.records)

    def test_build_train_pq_seedless_is_deterministic(self, monkeypatch):
        monkeypatch.setenv("PIO_PQ_MIN_ITEMS", "1")
        _, items = _clustered_corpus(n=500)
        a = build_train_pq(items, name="t")
        b = build_train_pq(items, name="t")
        np.testing.assert_array_equal(a.codes, b.codes)
        np.testing.assert_array_equal(a.codebooks, b.codebooks)

    def test_int8_quantize_round_trip(self):
        _, items = _clustered_corpus(n=300)
        q8, scale = quantize_int8(items)
        assert q8.dtype == np.int8 and scale.shape == (300,)
        back = q8.astype(np.float32) * scale[:, None]
        # symmetric per-row quantization: worst error ≤ scale/2 per dim
        assert (np.abs(back - items) <= scale[:, None] * 0.5 + 1e-7).all()
        zero_row = quantize_int8(np.zeros((1, 4), np.float32))
        assert (zero_row[0] == 0).all() and zero_row[1][0] == 1.0


# -- scan parity: kernel ≡ XLA fallback ≡ host numpy -------------------------


class TestScanParity:
    def _luts_codes(self, n=1500, k=23):
        q, items = _clustered_corpus(n=n, n_q=8)
        pq = build_pq(items, m=4)
        luts = lut_tables(pq, q)
        codes_sn = np.ascontiguousarray(pq.codes.T)
        acc = luts[:, 0, :][:, pq.codes[:, 0]]
        for m in range(1, pq.n_tables):
            acc = acc + luts[:, m, :][:, pq.codes[:, m]]
        ref = np.argsort(-acc, axis=1, kind="stable")[:, :k]
        return luts, codes_sn, acc, ref

    def test_kernel_xla_host_agree(self):
        from predictionio_tpu.ops.pallas_kernels import (
            pq_scan_pallas,
            pq_scan_xla,
        )

        luts, codes_sn, acc, ref = self._luts_codes()
        k = ref.shape[1]
        sk, ik = pq_scan_pallas(jnp.asarray(luts), jnp.asarray(codes_sn),
                                k, interpret=True)
        sx, ix = pq_scan_xla(jnp.asarray(luts), jnp.asarray(codes_sn),
                             k, chunk=512)
        for b in range(len(ref)):
            assert set(np.asarray(ik)[b]) == set(ref[b])
            assert set(np.asarray(ix)[b]) == set(ref[b])
        want_s = np.sort(np.take_along_axis(acc, ref, 1), axis=1)
        np.testing.assert_allclose(np.sort(np.asarray(sk), 1), want_s,
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.sort(np.asarray(sx), 1), want_s,
                                   rtol=1e-5, atol=1e-5)

    def test_n_valid_masks_padding_columns(self):
        from predictionio_tpu.ops.pallas_kernels import (
            pq_scan_pallas,
            pq_scan_xla,
        )

        luts, codes_sn, acc, _ = self._luts_codes(n=900)
        for fn, kw in ((pq_scan_pallas, {"interpret": True}),
                       (pq_scan_xla, {"chunk": 256})):
            _, ids = fn(jnp.asarray(luts), jnp.asarray(codes_sn), 9,
                        n_valid=700, **kw)
            assert int(np.asarray(ids).max()) < 700

    def test_device_search_matches_host(self):
        from predictionio_tpu.retrieval.pq import (
            search_ivf_pq_device,
            search_pq_device,
        )

        q, items = _clustered_corpus(n=2000, n_q=8)
        ivf = build_ivf(items, nlist=20, force=True)
        pq = build_pq(items, m=4, ivf=ivf)
        r = Retriever(items, ivf=ivf, pq=pq, name="t-par")
        s, i, sc = search_pq_device(
            pq, q, 10, 40, jit_cache={}, consts=r.pq_device_arrays(),
            rerank_consts=r.rerank_arrays())
        sh, ih, sch = search_pq_host(pq, items, q, 10, 40)
        np.testing.assert_array_equal(np.sort(i, 1), np.sort(ih, 1))
        np.testing.assert_allclose(np.sort(s, 1), np.sort(sh, 1),
                                   rtol=1e-5, atol=1e-5)
        assert sc == sch
        sv, iv, scv = search_ivf_pq_device(
            ivf, pq, q, 10, 6, 40, jit_cache={},
            ivf_consts=r.ivf_device_arrays(),
            pq_consts=r.pq_device_arrays(),
            rerank_consts=r.rerank_arrays())
        svh, ivh, scvh = search_ivf_pq_host(ivf, pq, items, q, 10, 6, 40)
        np.testing.assert_array_equal(np.sort(iv, 1), np.sort(ivh, 1))
        assert scv == scvh


# -- recall with exact re-rank (acceptance) ----------------------------------


class TestRecall:
    def test_recall_at_10_with_rerank(self, monkeypatch):
        """Acceptance: recall@10 ≥ 0.95 at defaults on the clustered
        corpus, both PQ rungs, while ivf_pq scans a fraction of rows."""
        monkeypatch.delenv("PIO_IVF_NPROBE", raising=False)
        monkeypatch.delenv("PIO_PQ_RERANK", raising=False)
        q, items = _clustered_corpus()
        want = _exact_ids(q, items, 10)
        ivf = build_ivf(items, force=True)
        pq = build_pq(items, m=4, ivf=ivf)
        s, i, _ = search_pq_host(pq, items, q, 10, 40)
        assert _recall(i, want) >= 0.95
        r = Retriever(items, ivf=ivf, pq=pq, name="t-recall")
        p = r.plan(len(q), 10)
        assert p.rung == "ivf_pq" and p.rerank == 40
        scores, ids, info = r.topk(q, 10)
        assert _recall(ids, want) >= 0.95
        assert info["candidates"] < 0.5 * len(q) * len(items)
        # the returned scores are EXACT inner products, not LUT scores
        got = np.take_along_axis(q @ items.T, ids, axis=1)
        np.testing.assert_allclose(scores, got, rtol=1e-4, atol=1e-4)

    def test_rerank_knob(self, monkeypatch):
        q, items = _clustered_corpus(n=600, n_clusters=10)
        pq = build_pq(items, m=4)
        r = Retriever(items, pq=pq, name="t-rr")
        assert r.plan(1, 10).rung == "pq_flat"
        assert r.plan(1, 10).rerank == 40            # 4·k default
        monkeypatch.setenv("PIO_PQ_RERANK", "7")     # clamped to ≥ k
        assert r.plan(1, 10).rerank == 10
        monkeypatch.setenv("PIO_PQ_RERANK", "200")
        assert r.plan(1, 10).rerank == 200
        monkeypatch.setenv("PIO_PQ_RERANK", "junk")
        assert r.plan(1, 10).rerank == 40            # loud fallback

    def test_corpus_dtype_rerank_overlap(self, monkeypatch):
        """bf16/int8 re-rank corpora keep the same top-10 on the
        clustered corpus (scores shift within quantization error)."""
        from predictionio_tpu.retrieval.pq import search_pq_device

        q, items = _clustered_corpus(n=800, n_q=8)
        pq = build_pq(items, m=4)
        outs = {}
        for dt in ("f32", "bf16", "int8"):
            monkeypatch.setenv("PIO_CORPUS_DTYPE", dt)
            r = Retriever(items, pq=pq, name=f"t-dt-{dt}")
            _, ids, _ = search_pq_device(
                pq, q, 10, 40, jit_cache={},
                consts=r.pq_device_arrays(),
                rerank_consts=r.rerank_arrays())
            outs[dt] = ids
        for dt in ("bf16", "int8"):
            overlap = np.mean([
                len(set(outs[dt][b]) & set(outs["f32"][b])) / 10
                for b in range(len(q))])
            assert overlap >= 0.9, (dt, overlap)

    def test_unknown_corpus_dtype_warns_and_serves_f32(
            self, monkeypatch, caplog):
        import logging

        monkeypatch.setenv("PIO_CORPUS_DTYPE", "fp4")
        _, items = _clustered_corpus(n=300, n_clusters=5)
        r = Retriever(items, name="t-baddt")
        with caplog.at_level(logging.WARNING,
                             logger="predictionio_tpu.retrieval"):
            vecs, scales = r.rerank_arrays()
        assert scales is None
        assert any("PIO_CORPUS_DTYPE" in rec.getMessage()
                   for rec in caplog.records)


# -- facade routing ----------------------------------------------------------


class TestRouting:
    def _pq_retriever(self, with_ivf=True, name="t-route"):
        q, items = _clustered_corpus(n=600, n_clusters=10)
        ivf = build_ivf(items, nlist=8, force=True) if with_ivf else None
        pq = build_pq(items, m=4, ivf=ivf)
        return q, items, Retriever(items, ivf=ivf, pq=pq, name=name)

    def test_auto_prefers_ivf_pq_then_pq_flat(self):
        _, _, r = self._pq_retriever(with_ivf=True, name="t-auto1")
        assert r.plan(4, 10).rung == "ivf_pq"
        _, _, r2 = self._pq_retriever(with_ivf=False, name="t-auto2")
        assert r2.plan(4, 10).rung == "pq_flat"

    def test_exclude_pins_exact_rung(self):
        q, items, r = self._pq_retriever(name="t-excl")
        assert r.plan(1, 10, has_exclude=True).rung in ("host", "device")
        excl = np.zeros((1, len(items)), dtype=bool)
        top = _exact_ids(q[:1], items, 1)[0, 0]
        excl[0, top] = True
        _, ids, info = r.topk(q[:1], 10, exclude=excl)
        assert info["rung"] in ("host", "device")
        assert top not in ids[0]

    def test_forced_pq_without_codebook_degrades_loudly(
            self, monkeypatch, caplog):
        import logging

        _, items = _clustered_corpus(n=300, n_clusters=5)
        monkeypatch.setenv("PIO_RETRIEVAL_RUNG", "pq_flat")
        r = Retriever(items, name="t-nopq")
        with caplog.at_level(logging.WARNING,
                             logger="predictionio_tpu.retrieval"):
            p = r.plan(1, 10)
        assert p.rung == "host"
        assert any("pq_flat" in rec.getMessage()
                   for rec in caplog.records)

    def test_forced_ivf_pq_without_index_serves_pq_flat(
            self, monkeypatch):
        _, _, r = self._pq_retriever(with_ivf=False, name="t-noivf")
        monkeypatch.setenv("PIO_RETRIEVAL_RUNG", "ivf_pq")
        assert r.plan(1, 10).rung == "pq_flat"

    def test_pq_rungs_agree_with_exact(self, monkeypatch):
        q, items, r = self._pq_retriever(name="t-agree")
        want = _exact_ids(q, items, 10)
        for rung in ("ivf_pq", "pq_flat"):
            monkeypatch.setenv("PIO_RETRIEVAL_RUNG", rung)
            _, ids, info = r.topk(q[:8], 10)
            assert info["rung"] == rung
            assert _recall(ids, want[:8]) >= 0.95, rung


# -- the tripwire ------------------------------------------------------------


class TestTripwire:
    def test_mismatched_codebook_dropped_loudly(self, pio_home):
        """Codes from generation N next to generation N+1 vectors are
        dropped (exact serving continues, counter increments) — results
        are never silently wrong."""
        from predictionio_tpu.obs import get_registry

        q, items_n = _clustered_corpus(n=600, n_clusters=6, seed=1)
        _, items_n1 = _clustered_corpus(n=600, n_clusters=6, seed=2)
        stale = build_pq(items_n, m=4)
        r = Retriever(items_n1, pq=stale, name="t-mix")
        assert r.pq_codebook() is None
        _, ids, info = r.topk(q, 10)
        assert info["rung"] not in ("ivf_pq", "pq_flat")
        np.testing.assert_array_equal(
            np.sort(ids, axis=1),
            np.sort(_exact_ids(q, items_n1, 10), axis=1))
        c = get_registry().counter("pio_retrieval_pq_rejected_total",
                                   "", ("corpus",))
        assert c.value(corpus="t-mix") == 1

    def test_matching_codebook_survives(self):
        _, items = _clustered_corpus(n=600, n_clusters=6)
        pq = build_pq(items, m=4)
        r = Retriever(items, pq=pq, name="t-ok")
        assert r.pq_codebook() is pq
        assert pq.fingerprint == corpus_fingerprint(items)

    def test_wrapper_pickle_carries_codes(self):
        """Model, index and codes are ONE artifact: the pickle
        round-trip the generation swap moves keeps them consistent."""
        from predictionio_tpu.data.event import BiMap
        from predictionio_tpu.templates.twotower.engine import (
            TwoTowerModelWrapper,
        )

        _, items = _clustered_corpus(n=600, n_clusters=6)
        w = TwoTowerModelWrapper(
            user_vecs=np.ones((1, items.shape[1]), np.float32),
            item_vecs=items,
            user_index=BiMap.string_int(["u0"]),
            item_index=BiMap.string_int(
                [f"i{j}" for j in range(len(items))]),
            ivf=build_ivf(items, nlist=6, force=True),
            pq=build_pq(items, m=4))
        w2 = pickle.loads(pickle.dumps(w))
        assert w2.pq is not None and w2.ivf is not None
        r = Retriever(w2.item_vecs, ivf=w2.ivf, pq=w2.pq, name="t-pkl")
        assert r.pq_codebook() is w2.pq
        assert r.ivf_index() is w2.ivf

    def test_old_pickle_without_pq_backfills(self):
        """A pre-ISSUE-13 wrapper pickle loads with pq=None and serves
        exact — upgrades never require a retrain."""
        from types import SimpleNamespace

        from predictionio_tpu.data.event import BiMap
        from predictionio_tpu.templates.recommendation.engine import (
            ALSModelWrapper,
        )

        _, items = _clustered_corpus(n=64, d=8, n_clusters=4)
        w = ALSModelWrapper(
            model=SimpleNamespace(user_factors=items[:8],
                                  item_factors=items, implicit=False),
            user_index=BiMap({f"u{j}": j for j in range(8)}),
            item_index=BiMap({f"i{j}": j for j in range(64)}))
        state = w.__getstate__()
        state.pop("pq", None)  # simulate an old generation's pickle
        w2 = ALSModelWrapper.__new__(ALSModelWrapper)
        w2.__setstate__(state)
        assert getattr(w2, "pq", "missing") is None


# -- server-level generation atomicity (acceptance) --------------------------


def _trained_pq_server(storage, monkeypatch, n_items=64):
    """ALS engine server with IVF+PQ forced on (tiny thresholds)."""
    from predictionio_tpu.controller import EngineVariant, RuntimeContext
    from predictionio_tpu.data.event import DataMap, Event
    from predictionio_tpu.data.storage import App
    from predictionio_tpu.server import EngineServer
    from predictionio_tpu.templates.recommendation import engine
    from predictionio_tpu.workflow.core_workflow import run_train

    monkeypatch.setenv("PIO_IVF", "on")
    monkeypatch.setenv("PIO_IVF_MIN_ITEMS", "10")
    monkeypatch.setenv("PIO_PQ", "on")
    monkeypatch.setenv("PIO_PQ_MIN_ITEMS", "10")
    ctx = RuntimeContext.create(storage=storage)
    app_id = storage.get_apps().insert(App(id=None, name="pqapp"))
    storage.get_events().init(app_id)
    rng = np.random.default_rng(7)
    storage.get_events().insert_batch(
        [Event(event="rate", entity_type="user", entity_id=f"u{u}",
               target_entity_type="item", target_entity_id=f"i{i}",
               properties=DataMap({"rating": float(r)}))
         for u, i, r in zip(rng.integers(0, 30, 600),
                            rng.integers(0, n_items, 600),
                            rng.integers(1, 6, 600))], app_id)
    variant = EngineVariant.from_dict({
        "engineFactory":
            "predictionio_tpu.templates.recommendation:engine",
        "datasource": {"params": {"appName": "pqapp"}},
        "algorithms": [{"name": "als",
                        "params": {"rank": 4, "numIterations": 2}}],
    })
    eng = engine()
    run_train(eng, variant, ctx)
    srv = EngineServer(eng, variant, storage, host="127.0.0.1", port=0)
    return srv, eng, variant, ctx, app_id


def _assert_pq_consistent(wrapper):
    """The served codes MUST fingerprint-match the served vectors."""
    r = wrapper.retriever()
    pq = r.pq_codebook()
    idx = r.ivf_index()
    assert pq is not None, "PQ codebook missing from serving wrapper"
    assert idx is not None, "IVF index missing from serving wrapper"
    host = wrapper.host_factors()[1]
    fp = corpus_fingerprint(host)
    assert pq.fingerprint == fp
    assert idx.fingerprint == fp
    return pq


def test_reload_canary_rollback_swap_codes_with_model(
        pio_home, monkeypatch):
    """ISSUE 13 acceptance: staged reload, canary rejection and rollback
    each leave index+codes+model consistent — a rollback never serves
    generation-N vectors through generation-N+1 codes, and a rejected
    candidate never replaces the serving generation's codes."""
    from predictionio_tpu.data.event import DataMap, Event
    from predictionio_tpu.data.storage import get_storage
    from predictionio_tpu.workflow.core_workflow import run_train

    storage = get_storage()
    srv, eng, variant, ctx, app_id = _trained_pq_server(
        storage, monkeypatch)
    fp1 = _assert_pq_consistent(srv._models[0]).fingerprint

    # Canary rejection: a NaN candidate model 409s and the SERVING
    # generation (model AND codes) stays untouched.
    from predictionio_tpu.server import engine_server as es_mod
    from predictionio_tpu.workflow import core_workflow

    real_load = core_workflow.load_models

    def poisoned_load(engine, instance, c=None):
        models = real_load(engine, instance, c)
        m = models[0]
        uf = np.asarray(m.model.user_factors).copy()
        uf[0, 0] = np.nan
        m.model.user_factors = uf
        return models

    monkeypatch.setattr(es_mod, "load_models", poisoned_load)
    st, body = srv.handle("POST", "/reload", b"")
    assert st == 409, body
    monkeypatch.setattr(es_mod, "load_models", real_load)
    assert _assert_pq_consistent(srv._models[0]).fingerprint == fp1

    # Generation 2: more events → new factors → NEW fingerprint; the
    # reload carries its OWN codes.
    rng = np.random.default_rng(11)
    storage.get_events().insert_batch(
        [Event(event="rate", entity_type="user", entity_id=f"u{u}",
               target_entity_type="item", target_entity_id=f"i{i}",
               properties=DataMap({"rating": float(r)}))
         for u, i, r in zip(rng.integers(0, 30, 200),
                            rng.integers(0, 64, 200),
                            rng.integers(1, 6, 200))], app_id)
    run_train(eng, variant, ctx)
    st, body = srv.handle("POST", "/reload", b"")
    assert st == 200
    fp2 = _assert_pq_consistent(srv._models[0]).fingerprint
    assert fp2 != fp1

    # Rollback: generation 1's model AND generation 1's codes return
    # together, and it serves through the quantized rung.
    st, body = srv.handle("POST", "/admin/rollback", b"")
    assert st == 200
    assert _assert_pq_consistent(srv._models[0]).fingerprint == fp1
    monkeypatch.setenv("PIO_RETRIEVAL_RUNG", "ivf_pq")
    st, body = srv.handle("POST", "/queries.json",
                          b'{"user": "u1", "num": 3}')
    assert st == 200 and body["itemScores"]


def test_pq_rides_train_and_serves(pio_home, monkeypatch):
    """End-to-end: `pio train` builds codes under the env policy,
    serving auto-routes the quantized rung, results match exact."""
    from predictionio_tpu.data.storage import get_storage

    storage = get_storage()
    srv, *_ = _trained_pq_server(storage, monkeypatch)
    w = srv._models[0]
    _assert_pq_consistent(w)
    # auto routing picks ivf_pq (codebook + index both valid)
    assert w.retriever().plan(4, 5).rung == "ivf_pq"
    st, body = srv.handle("POST", "/queries.json",
                          b'{"user": "u2", "num": 5}')
    assert st == 200 and len(body["itemScores"]) == 5
    # the answered scores are exact reconstructions, not LUT scores
    uf, itf = w.host_factors()
    exact = uf[w.user_index["u2"]] @ itf.T
    for hit in body["itemScores"]:
        col = w.item_index[hit["item"]]
        np.testing.assert_allclose(hit["score"], exact[col], rtol=1e-3)


def test_fingerprint_mismatch_serves_exact_on_live_server(
        pio_home, monkeypatch):
    """Acceptance: a mismatched codebook on a LIVE server degrades to
    exact serving with the counter incremented — never silently wrong
    results, never a 5xx."""
    from predictionio_tpu.data.storage import get_storage
    from predictionio_tpu.obs import get_registry

    storage = get_storage()
    srv, *_ = _trained_pq_server(storage, monkeypatch)
    w = srv._models[0]
    _, other = _clustered_corpus(n=len(w.item_index),
                                 d=w.model.item_factors.shape[1],
                                 n_clusters=6, seed=9)
    w.pq = build_pq(other, m=2)       # stale codes, wrong fingerprint
    w.ivf = None
    st, body = srv.handle("POST", "/queries.json",
                          b'{"user": "u2", "num": 5}')
    assert st == 200 and len(body["itemScores"]) == 5
    c = get_registry().counter("pio_retrieval_pq_rejected_total",
                               "", ("corpus",))
    assert c.value(corpus="als") == 1
    uf, itf = w.host_factors()
    exact = uf[w.user_index["u2"]] @ itf.T
    want = set(np.argsort(-exact)[:5])
    got = {w.item_index[h["item"]] for h in body["itemScores"]}
    assert got == want
