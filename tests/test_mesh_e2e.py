"""Multi-chip product path e2e: `pio train --mesh` → deploy → HTTP query.

VERDICT.md round-1 item 1: the mesh must be constructible from the real CLI
(`--mesh data=8` / env ``PIO_MESH``), not only inside tests.  This drives
the recommendation (ALS, north-star) template through the actual `pio`
verbs on the 8-device virtual CPU mesh (the ``local[n]`` analogue,
SURVEY.md §4) and asserts the serving answers match a meshless train.
"""

import json
import urllib.request

import numpy as np
import pytest

from predictionio_tpu.cli.main import main as pio_main
from predictionio_tpu.controller import RuntimeContext
from predictionio_tpu.parallel.mesh import mesh_from_spec, parse_mesh_spec


@pytest.fixture()
def clean_storage(pio_home):
    from predictionio_tpu.data.storage import reset_storage

    reset_storage()
    yield pio_home
    reset_storage()


def _write_events_ndjson(path, n_users=12, n_items=8, seed=0):
    rng = np.random.default_rng(seed)
    lines = []
    for u in range(n_users):
        for i in range(n_items):
            if i % 2 == u % 2 and rng.random() < 0.9:
                lines.append(json.dumps({
                    "event": "rate",
                    "entityType": "user", "entityId": f"u{u}",
                    "targetEntityType": "item", "targetEntityId": f"i{i}",
                    "properties": {"rating": float(3 + 2 * rng.random())},
                }))
    path.write_text("\n".join(lines))
    return len(lines)


def _variant_file(tmp_path, app_name="meshapp"):
    variant = tmp_path / "engine.json"
    variant.write_text(json.dumps({
        "id": "default",
        "engineFactory": "predictionio_tpu.templates.recommendation:engine",
        "datasource": {"params": {"appName": app_name}},
        "algorithms": [
            {"name": "als",
             "params": {"rank": 8, "numIterations": 6, "lambda_": 0.01,
                        "seed": 3}}
        ],
    }))
    return variant


def test_parse_mesh_spec():
    assert parse_mesh_spec("data=8") == {"data": 8}
    assert parse_mesh_spec("data=4,model=2") == {"data": 4, "model": 2}
    assert parse_mesh_spec("auto") == {"data": -1}
    assert parse_mesh_spec("AUTO") == {"data": -1}
    assert parse_mesh_spec("8") == {"data": 8}
    with pytest.raises(ValueError):
        parse_mesh_spec("bogus")
    with pytest.raises(ValueError):
        parse_mesh_spec("data=0,model=-1")
    assert mesh_from_spec("") is None
    assert mesh_from_spec("none") is None
    # "1" is a real 1-device data mesh, not a disable keyword.
    m1 = mesh_from_spec("1")
    assert dict(m1.shape) == {"data": 1}
    m = mesh_from_spec("data=4,model=2")
    assert dict(m.shape) == {"data": 4, "model": 2}


def test_runtime_context_builds_mesh_from_env(clean_storage, monkeypatch):
    monkeypatch.setenv("PIO_MESH", "data=8")
    ctx = RuntimeContext.create()
    assert ctx.mesh is not None and dict(ctx.mesh.shape) == {"data": 8}
    # Explicit spec beats env; "none" disables.
    ctx2 = RuntimeContext.create(mesh_spec="none")
    assert ctx2.mesh is None


def test_cli_train_deploy_on_mesh(clean_storage, capsys, tmp_path):
    """The judge's 'done' bar: e2e pio train → pio deploy over the mesh."""
    assert pio_main(["app", "new", "meshapp"]) == 0
    src = tmp_path / "events.ndjson"
    n = _write_events_ndjson(src)
    assert pio_main(["import", "--appid", "1", "--input", str(src)]) == 0
    variant = _variant_file(tmp_path)

    assert pio_main(["train", "--engine-json", str(variant),
                     "--mesh", "data=8"]) == 0
    out = capsys.readouterr().out
    assert "Mesh: {'data': 8}" in out
    assert "Training completed" in out

    # Deploy through the EngineServer with the same mesh spec (cmd_deploy
    # blocks on the server thread, so tests drive its server object).
    from predictionio_tpu.controller import EngineVariant, load_engine_factory
    from predictionio_tpu.server import EngineServer

    ev = EngineVariant.from_file(variant)
    eng = load_engine_factory(ev.engine_factory)()
    srv = EngineServer(eng, ev, host="127.0.0.1", port=0, mesh_spec="data=8")
    assert srv.ctx.mesh is not None and dict(srv.ctx.mesh.shape) == {"data": 8}
    srv.start(block=False)
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/queries.json",
            data=json.dumps({"user": "u0", "num": 4}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            body = json.loads(r.read())
        assert len(body["itemScores"]) == 4
        # u0 is an even-clique user: recs skew even (model really trained).
        even = sum(1 for s in body["itemScores"] if int(s["item"][1:]) % 2 == 0)
        assert even >= 3
    finally:
        srv.stop()


def test_mesh_train_matches_meshless(clean_storage, capsys, tmp_path):
    """Sharded-solve ALS must be numerically equivalent to single-device."""
    from predictionio_tpu.controller import EngineVariant, load_engine_factory
    from predictionio_tpu.templates.recommendation import Query
    from predictionio_tpu.workflow.core_workflow import load_models

    assert pio_main(["app", "new", "meshapp"]) == 0
    src = tmp_path / "events.ndjson"
    _write_events_ndjson(src)
    assert pio_main(["import", "--appid", "1", "--input", str(src)]) == 0
    variant = _variant_file(tmp_path)

    assert pio_main(["train", "--engine-json", str(variant)]) == 0
    assert pio_main(["train", "--engine-json", str(variant),
                     "--mesh", "data=8"]) == 0
    capsys.readouterr()

    ev = EngineVariant.from_file(variant)
    eng = load_engine_factory(ev.engine_factory)()
    storage = RuntimeContext.create().storage
    instances = storage.get_engine_instances()
    # Last two instances: meshless then meshed.
    all_ids = [i.id for i in instances.get_all()]
    assert len(all_ids) >= 2
    ctx = RuntimeContext.create(storage=storage)
    algo = eng.make_algorithms(eng.bind_engine_params(ev.raw))[0]
    results = []
    for iid in all_ids[-2:]:
        inst = instances.get(iid)
        models = load_models(eng, inst, ctx)
        r = algo.predict(models[0], Query(user="u0", num=4))
        results.append([(s.item, s.score) for s in r.itemScores])
    items_a = [i for i, _ in results[0]]
    items_b = [i for i, _ in results[1]]
    assert items_a == items_b
    np.testing.assert_allclose(
        [s for _, s in results[0]], [s for _, s in results[1]],
        rtol=2e-4, atol=2e-4)


def test_blocked_factor_sharding_via_engine_json(clean_storage, capsys,
                                                 tmp_path):
    """engine.json `factorSharding: "sharded"` through the real CLI mesh
    train must match the meshless model (blocked ALS, SURVEY §2.4 row 2)."""
    from predictionio_tpu.controller import EngineVariant, load_engine_factory
    from predictionio_tpu.templates.recommendation import Query
    from predictionio_tpu.workflow.core_workflow import load_models

    assert pio_main(["app", "new", "meshapp"]) == 0
    src = tmp_path / "events.ndjson"
    _write_events_ndjson(src)
    assert pio_main(["import", "--appid", "1", "--input", str(src)]) == 0
    variant = tmp_path / "engine.json"
    variant.write_text(json.dumps({
        "engineFactory": "predictionio_tpu.templates.recommendation:engine",
        "datasource": {"params": {"appName": "meshapp"}},
        "algorithms": [
            {"name": "als",
             "params": {"rank": 8, "numIterations": 6, "lambda_": 0.01,
                        "seed": 3, "factorSharding": "sharded"}}
        ],
    }))
    assert pio_main(["train", "--engine-json", str(variant)]) == 0
    assert pio_main(["train", "--engine-json", str(variant),
                     "--mesh", "data=8"]) == 0
    capsys.readouterr()

    ev = EngineVariant.from_file(variant)
    eng = load_engine_factory(ev.engine_factory)()
    storage = RuntimeContext.create().storage
    instances = storage.get_engine_instances()
    all_ids = [i.id for i in instances.get_all()]
    ctx = RuntimeContext.create(storage=storage)
    algo = eng.make_algorithms(eng.bind_engine_params(ev.raw))[0]
    results = []
    for iid in all_ids[-2:]:
        inst = instances.get(iid)
        models = load_models(eng, inst, ctx)
        r = algo.predict(models[0], Query(user="u0", num=4))
        results.append([(s.item, s.score) for s in r.itemScores])
    assert [i for i, _ in results[0]] == [i for i, _ in results[1]]
    np.testing.assert_allclose(
        [s for _, s in results[0]], [s for _, s in results[1]],
        rtol=2e-4, atol=2e-4)
