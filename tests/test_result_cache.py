"""Serve-side result cache (ISSUE 20): generation-keyed fast path.

Acceptance spine: semantically-identical queries share ONE cache entry
(canonical serialization); the LRU honors entry AND byte bounds;
negative entries expire on their own short TTL (injectable clock, zero
wall sleeps); promotion/rollback invalidate/revalidate by construction
because the generation fingerprint IS the key — including a mid-flight
swap, where a fill under the batcher-stamped OLD generation lands under
the OLD fingerprint, never the new one; the shared fleet tier lets
instance B hit an entry instance A filled, degrades to LRU-only on KV
blips, and NEVER shares negatives; the live server serves zero
stale-generation responses and zero non-2xx across a promotion under
concurrent load; and a ~95%-hit-rate drive still feeds the quality
layer's PSI windows at the configured sample rate.
"""

import dataclasses
import json
import threading
import time
from urllib.request import Request, urlopen

import pytest

from predictionio_tpu.controller import EngineVariant, RuntimeContext
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import App, get_storage
from predictionio_tpu.data.storage.memory import MemoryKV
from predictionio_tpu.obs import get_registry
from predictionio_tpu.serving.result_cache import (
    RESULT_CACHE_METRICS,
    ResultCache,
    ResultCacheConfig,
    canonical_query,
    query_defaults,
)
from predictionio_tpu.workflow.core_workflow import run_train

TT_VARIANT = {
    "id": "default",
    "engineFactory": "predictionio_tpu.templates.twotower:engine",
    "datasource": {"params": {"appName": "app"}},
    "algorithms": [{"name": "twotower",
                    "params": {"embedDim": 8, "hiddenDims": [16],
                               "outDim": 8, "epochs": 2, "batchSize": 32,
                               "seed": 1}}],
}

POS = {"itemScores": [{"item": "i1", "score": 1.5}]}
NEG = {"itemScores": []}


@pytest.fixture(autouse=True)
def _iso(pio_home):
    """Every test gets a fresh process-wide registry + storage — the
    counter assertions below are exact, not delta-based."""
    yield


@dataclasses.dataclass
class Q:
    user: str
    num: int = 10
    exclude: list = dataclasses.field(default_factory=list)


def _cache(clock=None, **cfg):
    kw = {"clock": clock} if clock is not None else {}
    c = ResultCache(ResultCacheConfig(**cfg), **kw)
    c.on_generation(1, "fpA")
    return c


# ==========================================================================
# Canonical serialization: ONE key per semantic query
# ==========================================================================

class TestCanonicalQuery:
    def test_key_order_never_matters(self):
        assert canonical_query({"num": 3, "user": "u1"}) \
            == canonical_query({"user": "u1", "num": 3})

    def test_explicit_default_strips_to_the_same_entry(self):
        """``{"user": "u1"}`` and ``{"user": "u1", "num": 10}`` are the
        same question when 10 is the dataclass default."""
        assert canonical_query(Q("u1")) == canonical_query(Q("u1", 10))
        d = query_defaults(Q)
        assert canonical_query({"user": "u1"}, d) \
            == canonical_query({"user": "u1", "num": 10}, d)
        # a NON-default value keys distinctly
        assert canonical_query(Q("u1", 5)) != canonical_query(Q("u1"))

    def test_integral_floats_normalize(self):
        """JSON clients that send ``num: 10.0`` mean ``num: 10``."""
        d = query_defaults(Q)
        assert canonical_query({"user": "u1", "num": 10.0}, d) \
            == canonical_query({"user": "u1"}, d)
        assert canonical_query({"user": "u1", "num": 3.0}, d) \
            == canonical_query({"user": "u1", "num": 3}, d)

    def test_default_factory_container_strips(self):
        assert canonical_query(Q("u1", exclude=[])) \
            == canonical_query(Q("u1"))

    def test_exclude_carrying_queries_key_distinctly(self):
        """Per-request exclude sets are part of the question — same
        exclude shares an entry, different exclude does not."""
        a = canonical_query(Q("u1", exclude=["i1"]))
        b = canonical_query(Q("u1", exclude=["i1"]))
        c = canonical_query(Q("u1", exclude=["i2"]))
        assert a == b
        assert a != c
        assert a != canonical_query(Q("u1"))

    def test_uncacheable_shapes_raise(self):
        with pytest.raises(TypeError):
            canonical_query("not a query")
        with pytest.raises(TypeError):
            json.loads(canonical_query({"user": object()}))


# ==========================================================================
# LRU bounds: entries AND bytes
# ==========================================================================

class TestBounds:
    def test_entry_bound_evicts_lru(self):
        c = _cache(max_entries=3)
        for u in range(4):
            c.fill(canonical_query({"user": f"u{u}"}), POS, 1)
        assert c.lookup(canonical_query({"user": "u0"})) is None
        assert c.lookup(canonical_query({"user": "u3"})) is not None
        assert c.snapshot()["entries"] == 3
        reg = get_registry()
        assert reg.get("pio_result_cache_evictions_total").total() >= 1

    def test_lookup_refreshes_recency(self):
        c = _cache(max_entries=3)
        for u in range(3):
            c.fill(canonical_query({"user": f"u{u}"}), POS, 1)
        assert c.lookup(canonical_query({"user": "u0"})) is not None
        c.fill(canonical_query({"user": "u3"}), POS, 1)
        # u1 (least recent) was evicted; the touched u0 survived
        assert c.lookup(canonical_query({"user": "u0"})) is not None
        assert c.lookup(canonical_query({"user": "u1"})) is None

    def test_byte_bound_evicts(self):
        big = {"itemScores": [{"item": "i" * 64, "score": 1.0}
                              for _ in range(16)]}
        one = len(json.dumps(big, separators=(",", ":")))
        c = _cache(max_entries=1000, max_bytes=3 * one)
        for u in range(4):
            c.fill(canonical_query({"user": f"u{u}"}), big, 1)
        snap = c.snapshot()
        assert snap["bytes"] <= 3 * one
        assert snap["entries"] < 4
        assert c.lookup(canonical_query({"user": "u0"})) is None

    def test_oversized_entry_never_sticks(self):
        c = _cache(max_bytes=8)
        c.fill(canonical_query({"user": "u0"}), POS, 1)
        assert c.snapshot()["entries"] == 0
        assert c.lookup(canonical_query({"user": "u0"})) is None


# ==========================================================================
# Negative caching: short independent TTL, injectable clock, NO sleeps
# ==========================================================================

class TestNegativeTTL:
    def test_negative_expires_positive_does_not(self):
        t = [0.0]
        c = _cache(clock=lambda: t[0], neg_ttl_s=5.0)
        pos_k = canonical_query({"user": "known"})
        neg_k = canonical_query({"user": "unknown"})
        assert c.fill(pos_k, POS, 1) == "positive"
        assert c.fill(neg_k, NEG, 1) == "negative"
        t[0] = 4.9
        hit = c.lookup(neg_k)
        assert hit is not None and hit.negative
        t[0] = 5.1
        assert c.lookup(neg_k) is None          # expired + retired
        assert c.lookup(pos_k) is not None      # positives have no TTL
        assert c.snapshot()["entries"] == 1

    def test_expired_negative_refill_restarts_ttl(self):
        t = [0.0]
        c = _cache(clock=lambda: t[0], neg_ttl_s=5.0)
        k = canonical_query({"user": "u"})
        c.fill(k, NEG, 1)
        t[0] = 6.0
        assert c.lookup(k) is None
        c.fill(k, NEG, 1)
        t[0] = 10.0
        assert c.lookup(k) is not None


# ==========================================================================
# Generation keying: swap invalidates, rollback revalidates, mid-flight
# fills land under the STAMPED generation
# ==========================================================================

class TestGenerationKeying:
    def test_swap_misses_rollback_revalidates(self):
        c = _cache()
        k = canonical_query({"user": "u1"})
        c.fill(k, POS, 1)
        assert c.lookup(k) is not None
        c.on_generation(2, "fpB")           # promotion: new fingerprint
        assert c.lookup(k) is None
        c.on_generation(3, "fpA")           # rollback: old id restored
        hit = c.lookup(k)
        assert hit is not None and hit.generation == 1

    def test_midflight_fill_lands_under_stamped_generation(self):
        """A dispatch stamped generation 1 that hands back AFTER the swap
        to generation 2 must fill under generation 1's fingerprint —
        never the current one."""
        c = _cache()
        c.on_generation(2, "fpB")
        k = canonical_query({"user": "u1"})
        assert c.fill(k, POS, 1) == "positive"   # stamped gen, pre-swap
        assert c.lookup(k) is None               # current fp is fpB
        c.on_generation(3, "fpA")
        assert c.lookup(k) is not None           # it sat under fpA

    def test_unknown_generation_drops_the_fill(self):
        c = _cache()
        k = canonical_query({"user": "u1"})
        assert c.fill(k, POS, 99) == "dropped"
        assert c.fill(k, POS, None) == "dropped"
        assert c.lookup(k) is None
        reg = get_registry()
        assert reg.get(
            "pio_result_cache_fills_total").value(kind="dropped") == 2

    def test_gen_map_is_bounded(self):
        c = _cache()
        for g in range(2, 20):
            c.on_generation(g, f"fp{g}")
        k = canonical_query({"user": "u1"})
        assert c.fill(k, POS, 1) == "dropped"    # aged out of the map
        assert c.fill(k, POS, 19) == "positive"

    def test_unserializable_result_drops(self):
        c = _cache()
        assert c.fill(canonical_query({"user": "u"}),
                      {"x": object()}, 1) == "dropped"

    def test_disabled_cache_registers_zero_instruments(self):
        from predictionio_tpu.obs.metrics import MetricsRegistry

        reg = MetricsRegistry()
        c = ResultCache(ResultCacheConfig(enabled=False), registry=reg)
        c.on_generation(1, "fp")
        assert c.lookup("{}") is None
        assert c.fill("{}", POS, 1) == "disabled"
        for name in RESULT_CACHE_METRICS:
            assert reg.get(name) is None, name
        # late enablement (bench A/B) registers on first use
        c.set_enabled(True)
        assert reg.get("pio_result_cache_hits_total") is not None


# ==========================================================================
# Shared fleet tier: B hits A's fill; blips degrade; negatives stay local
# ==========================================================================

def _shared_pair(kv, clock=None):
    kw = {"clock": clock} if clock is not None else {}
    cfg = ResultCacheConfig(shared=True)
    a = ResultCache(cfg, kv=kv, **kw)
    b = ResultCache(cfg, kv=kv, **kw)
    a.on_generation(1, "fpX")
    b.on_generation(7, "fpX")   # same instance id, different local gen
    return a, b


class _BlippyKV:
    """KV that fails on demand and counts traffic."""

    def __init__(self):
        self.kv = MemoryKV()
        self.fail = False
        self.gets = 0

    def get(self, ns, key):
        self.gets += 1
        if self.fail:
            raise ConnectionError("kv down")
        return self.kv.get(ns, key)

    def put(self, ns, key, value):
        if self.fail:
            raise ConnectionError("kv down")
        return self.kv.put(ns, key, value)

    def prune(self, ns, keep):
        return self.kv.prune(ns, keep)


class TestSharedTier:
    def test_instance_b_hits_what_a_filled(self):
        kv = MemoryKV()
        a, b = _shared_pair(kv)
        k = canonical_query({"user": "u1"})
        assert a.fill(k, POS, 1) == "positive"
        hit = b.lookup(k)
        assert hit is not None and hit.tier == "shared"
        assert hit.result == POS
        # adopted into B's local LRU: the next hit skips the KV
        assert b.lookup(k).tier == "local"

    def test_negatives_are_never_shared(self):
        kv = MemoryKV()
        a, b = _shared_pair(kv)
        k = canonical_query({"user": "ghost"})
        assert a.fill(k, NEG, 1) == "negative"
        assert a.lookup(k) is not None           # local negative hit
        assert b.lookup(k) is None               # not fleet truth

    def test_fingerprint_scopes_the_namespace(self):
        kv = MemoryKV()
        a, b = _shared_pair(kv)
        b.on_generation(8, "fpOTHER")
        k = canonical_query({"user": "u1"})
        a.fill(k, POS, 1)
        assert b.lookup(k) is None

    def test_blip_degrades_with_cooldown_then_recovers(self):
        t = [0.0]
        kv = _BlippyKV()
        a, b = _shared_pair(kv, clock=lambda: t[0])
        k = canonical_query({"user": "u1"})
        a.fill(k, POS, 1)
        kv.fail = True
        assert b.lookup(k) is None               # degraded, not raised
        n = kv.gets
        assert b.lookup(k) is None               # cooldown: no KV call
        assert kv.gets == n
        reg = get_registry()
        assert reg.get(
            "pio_result_cache_shared_errors_total").total() >= 1
        kv.fail = False
        t[0] = 31.0                              # past the cooldown
        assert b.lookup(k) is not None
        assert kv.gets > n

    def test_foreign_bytes_in_namespace_read_as_miss(self):
        kv = MemoryKV()
        a, b = _shared_pair(kv)
        k = canonical_query({"user": "u1"})
        kv.put(a._ns("fpX"), a._shared_key(k), b"not json at all")
        assert b.lookup(k) is None


# ==========================================================================
# Live server: the seam end-to-end
# ==========================================================================

@pytest.fixture()
def ctx(pio_home):
    return RuntimeContext.create(storage=get_storage())


def _mk_app(ctx, name="app"):
    app_id = ctx.storage.get_apps().insert(App(id=None, name=name))
    ctx.storage.get_events().init(app_id)
    return app_id


def _seed_views(ctx, app_id, n_users=10, n_items=6):
    evs = [Event(event="view", entity_type="user", entity_id=f"u{u}",
                 target_entity_type="item", target_entity_id=f"i{i}")
           for u in range(n_users) for i in range(n_items)
           if i % 2 == u % 2]
    ctx.storage.get_events().insert_batch(evs, app_id)


def _tt():
    from predictionio_tpu.templates.twotower import engine

    return engine(), EngineVariant.from_dict(TT_VARIANT)


def _trained_server(ctx):
    from predictionio_tpu.server import EngineServer

    app_id = _mk_app(ctx)
    _seed_views(ctx, app_id)
    eng, variant = _tt()
    run_train(eng, variant, ctx)
    return EngineServer(eng, variant, ctx.storage, host="127.0.0.1",
                        port=0), eng, variant


def _q(srv, user="u1", num=3, **extra):
    """(status, result-dict) for one handler-level query.  Hits come back
    as pre-serialized bytes (the raw transport path); normalize so tests
    compare documents either way."""
    body = json.dumps({"user": user, "num": num, **extra}).encode()
    out = srv.handle("POST", "/queries.json", body)
    status, payload = out[0], out[1]
    if isinstance(payload, (bytes, bytearray)):
        payload = json.loads(payload.decode("utf-8"))
    return status, payload


class TestServerSeam:
    def test_repeat_query_hits_and_snapshot_reports(self, ctx):
        srv, _, _ = _trained_server(ctx)
        try:
            st1, r1 = _q(srv)
            st2, r2 = _q(srv)
            assert st1 == st2 == 200
            assert r1 == r2
            reg = get_registry()
            assert reg.get("pio_result_cache_hits_total").total() >= 1
            st, root = srv.handle("GET", "/", b"")
            snap = root["resultCache"]
            assert snap["hits"] >= 1 and snap["fingerprint"]
            st, stats = srv.handle("GET", "/stats.json", b"")
            assert stats["resultCache"]["hits"] >= 1
            # the waterfall family carries the cache stage
            from predictionio_tpu.obs.waterfall import (
                ATTESTED_STAGES,
                SERVE_STAGES,
                WALL_STAGES,
            )

            for stages in (SERVE_STAGES, WALL_STAGES, ATTESTED_STAGES):
                assert "cache" in stages
        finally:
            srv.stop()

    def test_semantically_equal_http_queries_share_one_entry(self, ctx):
        """An omitted ``num`` and an explicit ``num=10`` (the dataclass
        default) are the same question on the wire."""
        srv, _, _ = _trained_server(ctx)
        try:
            st, _ = srv.handle("POST", "/queries.json",
                               json.dumps({"user": "u1"}).encode())
            assert st == 200
            reg = get_registry()
            before = reg.get("pio_result_cache_hits_total").total()
            st, _ = _q(srv, user="u1", num=10)   # default, explicit
            assert st == 200
            assert reg.get(
                "pio_result_cache_hits_total").total() == before + 1
        finally:
            srv.stop()

    def test_reload_invalidates_rollback_revalidates(self, ctx):
        srv, eng, variant = _trained_server(ctx)
        try:
            _q(srv)                              # fill under gen 1
            run_train(eng, variant, ctx)         # a second instance
            st, body = srv.handle("POST", "/reload", b"")
            assert st == 200
            reg = get_registry()
            misses0 = reg.get("pio_result_cache_misses_total").total()
            _q(srv)                              # new fp: MUST miss
            assert reg.get(
                "pio_result_cache_misses_total").total() == misses0 + 1
            st, _ = srv.handle("POST", "/admin/rollback", b"")
            assert st == 200
            hits0 = reg.get("pio_result_cache_hits_total").total()
            _q(srv)                              # old fp restored: hit
            assert reg.get(
                "pio_result_cache_hits_total").total() == hits0 + 1
        finally:
            srv.stop()

    def test_kill_switch_bypasses_and_registers_nothing(
            self, ctx, monkeypatch):
        monkeypatch.setenv("PIO_RESULT_CACHE", "off")
        srv, _, _ = _trained_server(ctx)
        try:
            st1, _ = _q(srv)
            st2, _ = _q(srv)
            assert st1 == st2 == 200
            reg = get_registry()
            for name in RESULT_CACHE_METRICS:
                assert reg.get(name) is None, name
            st, root = srv.handle("GET", "/", b"")
            assert root["resultCache"]["enabled"] is False
        finally:
            srv.stop()


# ==========================================================================
# Promotion atomicity under concurrent live-HTTP load (PR-4 harness)
# ==========================================================================

def _http_query(base, user, num=3):
    req = Request(base + "/queries.json",
                  data=json.dumps({"user": user, "num": num}).encode(),
                  method="POST",
                  headers={"Content-Type": "application/json"})
    with urlopen(req, timeout=15) as resp:
        return resp.status, dict(resp.headers), json.loads(resp.read())


def _serve_gen(headers):
    sid = headers.get("X-PIO-Serve-Id") or ""
    if sid.startswith("g") and "-" in sid:
        return int(sid[1:sid.index("-")])
    return None


class TestPromotionAtomicity:
    def test_no_stale_generation_served_across_swap(
            self, ctx, monkeypatch):
        """Drive Zipf-ish repeats while a promotion swaps generations:
        zero non-2xx, and every request SENT after the reload returned
        carries the post-swap generation — a pre-swap cache entry can
        never leak through, because the fingerprint key changed."""
        monkeypatch.setenv("PIO_QUALITY_SAMPLE", "1.0")
        srv, eng, variant = _trained_server(ctx)
        srv.start()
        base = f"http://127.0.0.1:{srv.port}"
        stop = threading.Event()
        errors = []
        statuses = []

        def drive(i):
            k = 0
            while not stop.is_set():
                try:
                    st, headers, _ = _http_query(base, f"u{k % 4}")
                    statuses.append(st)
                except Exception as e:     # noqa: BLE001
                    errors.append(repr(e))
                k += 1

        threads = [threading.Thread(target=drive, args=(i,))
                   for i in range(4)]
        try:
            for t in threads:
                t.start()
            # warm the cache on generation 1
            deadline = time.monotonic() + 10.0
            reg = get_registry()
            while time.monotonic() < deadline:
                fam = reg.get("pio_result_cache_hits_total")
                if fam is not None and fam.total() >= 8:
                    break
                time.sleep(0.01)
            run_train(eng, variant, ctx)
            st, _, _ = _reload(base)
            assert st == 200
            # every request sent AFTER the reload returned must serve
            # the post-swap generation
            post_gens = set()
            for k in range(12):
                st, headers, _ = _http_query(base, f"u{k % 4}")
                assert st == 200
                g = _serve_gen(headers)
                assert g is not None
                post_gens.add(g)
            assert post_gens == {2}, post_gens
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
            srv.stop()
        assert not errors, errors
        assert statuses and all(s == 200 for s in statuses)
        # and the cache DID participate (this was a hot drive)
        assert get_registry().get(
            "pio_result_cache_hits_total").total() >= 8


def _reload(base):
    req = Request(base + "/reload", data=b"", method="POST")
    with urlopen(req, timeout=60) as resp:
        return resp.status, dict(resp.headers), json.loads(resp.read())


# ==========================================================================
# Quality layer keeps seeing scores at a 95% hit rate
# ==========================================================================

class TestQualityFeedOnHits:
    def test_hit_heavy_drive_feeds_psi_at_sample_rate(
            self, ctx, monkeypatch):
        """A ~95%-hit-rate drive must still append to the prediction
        record stream at the configured sample rate — hits carry the
        filled response's serve-id semantics instead of starving the
        drift windows."""
        monkeypatch.setenv("PIO_QUALITY_SAMPLE", "1.0")
        srv, _, _ = _trained_server(ctx)
        try:
            reg = get_registry()
            n = 60
            for k in range(n):
                st, _ = _q(srv, user=f"u{k % 3}")   # 3 keys, 57 hits
                assert st == 200
            sampled = reg.get("pio_quality_sampled_total")
            assert sampled is not None
            assert sampled.total() >= n * 0.95
            hits = reg.get("pio_result_cache_hits_total").total()
            assert hits >= n - 3 - 5   # genuinely hit-heavy drive
            # hit-path serves carry generation-attributed serve ids
            st, doc = srv.handle("GET", "/quality.json", b"")
            assert st == 200
            assert doc["sampling"]["sampledTotal"] >= n * 0.95
        finally:
            srv.stop()
