"""Markov chain model (reference: e2 MarkovChain)."""

import numpy as np
import jax.numpy as jnp

from predictionio_tpu.models.markov_chain import (
    predict_next,
    train_markov_chain,
)


def test_transition_probabilities():
    # 0→1 twice, 0→2 once, 1→0 always.
    prev = np.array([0, 0, 0, 1, 1])
    nxt = np.array([1, 1, 2, 0, 0])
    m = train_markov_chain(prev, nxt, 3)
    t = np.asarray(m.transition)
    np.testing.assert_allclose(t[0], [0, 2 / 3, 1 / 3], rtol=1e-6)
    np.testing.assert_allclose(t[1], [1, 0, 0], rtol=1e-6)
    np.testing.assert_allclose(t[2], [0, 0, 0], atol=1e-9)  # unseen row


def test_smoothing():
    m = train_markov_chain(np.array([0]), np.array([1]), 2, smoothing=1.0)
    t = np.asarray(m.transition)
    np.testing.assert_allclose(t[0], [1 / 3, 2 / 3], rtol=1e-6)
    np.testing.assert_allclose(t[1], [0.5, 0.5], rtol=1e-6)


def test_predict_next_topk():
    prev = np.array([0] * 10)
    nxt = np.array([2] * 7 + [1] * 3)
    m = train_markov_chain(prev, nxt, 3)
    probs, ids = predict_next(m, jnp.asarray([0]), 2)
    assert list(np.asarray(ids[0])) == [2, 1]
    np.testing.assert_allclose(np.asarray(probs[0]), [0.7, 0.3], rtol=1e-6)
