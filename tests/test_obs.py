"""obs/ unit tests: registry semantics, renderer validity, tracing,
pipeline probe.

``parse_prometheus`` doubles as the suite's Prometheus text-format
validator (no prometheus_client in the image): strict line grammar,
TYPE-before-samples, cumulative ``le`` buckets, ``+Inf`` == ``_count``.
test_servers.py imports it to validate live ``/metrics`` output.
"""

import json
import math
import re
import threading

import pytest

from predictionio_tpu.obs import (
    MetricsRegistry,
    PipelineProbe,
    TraceRecorder,
    get_recorder,
    get_registry,
    phase,
    reset_observability,
    sanitize_trace_id,
    span,
    trace,
)

# -- Prometheus text-format parser/validator --------------------------------

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_SAMPLE_RE = re.compile(
    rf"^({_NAME})(?:\{{(.*)\}})? (-?(?:[0-9.]+(?:[eE][+-]?[0-9]+)?|Inf)|\+Inf|NaN)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"(?:,|$)')


def _parse_value(s: str) -> float:
    if s == "+Inf":
        return math.inf
    if s == "-Inf":
        return -math.inf
    if s == "NaN":
        return math.nan
    return float(s)


def parse_prometheus(text: str):
    """Validate + parse exposition text → {name: [(labels_dict, value)]}.

    Raises AssertionError on any malformed line, samples without a
    preceding # TYPE, non-cumulative histogram buckets, or +Inf bucket
    disagreeing with _count.
    """
    samples = {}
    types = {}
    assert text.endswith("\n"), "exposition must end with a newline"
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            assert kind in ("counter", "gauge", "histogram", "summary",
                            "untyped"), f"bad TYPE line: {line!r}"
            types[name] = kind
            continue
        assert not line.startswith("#"), f"unknown comment: {line!r}"
        m = _SAMPLE_RE.match(line)
        assert m, f"malformed sample line: {line!r}"
        name, labels_raw, value = m.group(1), m.group(2), m.group(3)
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        assert base in types or name in types, \
            f"sample {name!r} has no # TYPE"
        labels = {}
        if labels_raw:
            consumed = sum(len(mm.group(0)) for mm in
                           _LABEL_RE.finditer(labels_raw))
            assert consumed == len(labels_raw), \
                f"malformed labels: {labels_raw!r}"
            for mm in _LABEL_RE.finditer(labels_raw):
                labels[mm.group(1)] = mm.group(2)
        samples.setdefault(name, []).append((labels, _parse_value(value)))
    # histogram invariants
    for name, kind in types.items():
        if kind != "histogram":
            continue
        series = {}
        for labels, v in samples.get(f"{name}_bucket", []):
            key = tuple(sorted((k, lv) for k, lv in labels.items()
                               if k != "le"))
            le = math.inf if labels["le"] == "+Inf" else float(labels["le"])
            series.setdefault(key, []).append((le, v))
        counts = {tuple(sorted(labels.items())): v
                  for labels, v in samples.get(f"{name}_count", [])}
        for key, bs in series.items():
            bs.sort()
            cums = [v for _, v in bs]
            assert cums == sorted(cums), f"{name}{key}: buckets not cumulative"
            assert bs[-1][0] == math.inf, f"{name}{key}: no +Inf bucket"
            assert bs[-1][1] == counts[key], \
                f"{name}{key}: +Inf bucket != _count"
    return samples


# -- registry ---------------------------------------------------------------

class TestRegistry:
    def test_counter_labels_and_values(self):
        reg = MetricsRegistry()
        c = reg.counter("pio_t_total", "t", ("status",))
        c.inc(status="200")
        c.inc(2, status="404")
        assert c.value(status="200") == 1
        assert c.value(status="404") == 2
        assert c.total() == 3
        with pytest.raises(ValueError):
            c.inc(status="200", extra="nope")
        with pytest.raises(ValueError):
            c.inc(-1, status="200")

    def test_get_or_create_and_mismatch(self):
        reg = MetricsRegistry()
        a = reg.counter("pio_x_total", "x")
        assert reg.counter("pio_x_total") is a
        with pytest.raises(ValueError):
            reg.gauge("pio_x_total")
        with pytest.raises(ValueError):
            reg.counter("pio_x_total", labelnames=("other",))
        with pytest.raises(ValueError):
            reg.counter("0bad name")
        h = reg.histogram("pio_x_ms", buckets=(1, 10))
        assert reg.histogram("pio_x_ms", buckets=(1, 10)) is h
        with pytest.raises(ValueError):
            reg.histogram("pio_x_ms", buckets=(5, 50))

    def test_phase_records_even_on_exception(self):
        reset_observability()
        with pytest.raises(RuntimeError):
            with trace("workflow.train"):
                with phase("train.datasource"):
                    raise RuntimeError("boom")
        h = get_registry().get("pio_train_phase_ms")
        assert h.count(phase="train.datasource") == 1

    def test_label_escaping_round_trips(self):
        reg = MetricsRegistry()
        c = reg.counter("pio_esc_total", "h", ("route",))
        nasty = 'a"b\\c\nd'
        c.inc(route=nasty)
        text = reg.render()
        assert '\\"' in text and "\\\\" in text and "\\n" in text
        samples = parse_prometheus(text)
        (labels, value), = samples["pio_esc_total"]
        assert value == 1
        # unescape what the renderer escaped — must round-trip
        unescaped = (labels["route"].replace("\\\\", "\x00")
                     .replace('\\"', '"').replace("\\n", "\n")
                     .replace("\x00", "\\"))
        assert unescaped == nasty

    def test_histogram_buckets_and_quantiles(self):
        reg = MetricsRegistry()
        h = reg.histogram("pio_h_ms", "h", buckets=(1, 10, 100))
        for v in (0.5, 5, 5, 50, 500):
            h.observe(v)
        assert h.count() == 5
        assert h.sum() == 560.5
        samples = parse_prometheus(reg.render())
        le_counts = {labels["le"]: v
                     for labels, v in samples["pio_h_ms_bucket"]}
        assert le_counts == {"1": 1, "10": 3, "100": 4, "+Inf": 5}
        # interpolated median lands inside the (1, 10] bucket
        assert 1 <= h.quantile(0.5) <= 10
        # +Inf-bucket quantiles report the top finite bound
        assert h.quantile(0.999) == 100

    def test_concurrent_increments_lose_nothing(self):
        reg = MetricsRegistry()
        c = reg.counter("pio_c_total", "c", ("worker",))
        h = reg.histogram("pio_ch_ms", "h")
        n_threads, per = 8, 500

        def work(i):
            for _ in range(per):
                c.inc(worker=str(i % 2))
                h.observe(1.0)

        ts = [threading.Thread(target=work, args=(i,))
              for i in range(n_threads)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert c.total() == n_threads * per
        assert h.count() == n_threads * per

    def test_unlabelled_counter_renders_bare(self):
        reg = MetricsRegistry()
        reg.counter("pio_bare_total", "b").inc()
        assert "pio_bare_total 1\n" in reg.render()

    def test_gauge_set_inc_dec(self):
        reg = MetricsRegistry()
        g = reg.gauge("pio_g", "g")
        g.set(5)
        g.inc(2)
        g.dec()
        assert g.value() == 6

    def test_render_is_valid_when_empty_and_after_reset(self):
        reg = MetricsRegistry()
        reg.counter("pio_a_total", "a", ("x",))
        parse_prometheus(reg.render())
        reg.reset()
        assert reg.render() == "\n" or parse_prometheus(reg.render()) == {}


# -- tracing ----------------------------------------------------------------

class TestTracing:
    def setup_method(self):
        reset_observability()

    def test_span_tree_and_ring(self):
        with trace("root", trace_id="tid-1", a=1) as t:
            with span("child1"):
                with span("grand"):
                    pass
            with span("child2", algo="als"):
                pass
        assert t.duration_ms is not None
        docs = get_recorder().recent(5)
        assert docs and docs[0]["traceId"] == "tid-1"
        names = [s["name"] for s in docs[0]["spans"]]
        assert names == ["child1", "child2"]
        assert docs[0]["spans"][0]["spans"][0]["name"] == "grand"
        assert docs[0]["spans"][1]["attrs"] == {"algo": "als"}

    def test_span_outside_trace_records_nothing(self):
        with span("orphan") as s:
            pass
        assert s.duration_ms is not None
        assert get_recorder().recent(5) == []

    def test_nested_trace_degrades_to_span(self):
        with trace("outer"):
            with trace("inner"):
                pass
        docs = get_recorder().recent(5)
        assert len(docs) == 1
        assert [s["name"] for s in docs[0]["spans"]] == ["inner"]

    def test_jsonl_export(self, tmp_path, monkeypatch):
        out = tmp_path / "traces.jsonl"
        monkeypatch.setenv("PIO_TRACE_FILE", str(out))
        with trace("one"):
            pass
        with trace("two"):
            with span("s"):
                pass
        lines = [json.loads(line) for line in
                 out.read_text().strip().splitlines()]
        assert [d["name"] for d in lines] == ["one", "two"]
        assert all("traceId" in d and "durationMs" in d for d in lines)

    def test_ring_is_bounded(self):
        rec = TraceRecorder(ring_size=3)
        for i in range(5):
            with trace(f"t{i}", recorder=rec):
                pass
        assert [d["name"] for d in rec.recent(10)] == ["t4", "t3", "t2"]

    def test_slow_trace_logs_warning(self, caplog):
        import logging

        with caplog.at_level(logging.WARNING,
                             logger="predictionio_tpu.obs.trace"):
            with trace("fast", slow_ms=10000):
                pass
            assert not caplog.records
            with trace("slow", slow_ms=0.0000001):
                pass
        assert any("slow" in r.message for r in caplog.records)

    def test_sanitize_trace_id(self):
        assert sanitize_trace_id(None) is None
        assert sanitize_trace_id("") is None
        assert sanitize_trace_id("ab-c_1.2:3") == "ab-c_1.2:3"
        # CRLF and header-splitting characters are stripped
        assert sanitize_trace_id("a\r\nSet-Cookie: x") == "aSet-Cookie:x"
        assert sanitize_trace_id("\r\n") is None
        assert len(sanitize_trace_id("x" * 500)) == 128

    def test_phase_records_span_and_histogram(self):
        reset_observability()
        with trace("workflow.train"):
            with phase("train.datasource"):
                pass
        h = get_registry().get("pio_train_phase_ms")
        assert h.count(phase="train.datasource") == 1
        doc = get_recorder().recent(1)[0]
        assert doc["spans"][0]["name"] == "train.datasource"


# -- pipeline probe ---------------------------------------------------------

class TestPipelineProbe:
    def test_decomposition_counts(self):
        reg = MetricsRegistry()
        probe = PipelineProbe("toy", registry=reg)
        batches = [([1, 2], [3, 4]), ([5], [6])]
        seen = []
        for b in probe.iter_host(iter(batches)):
            with probe.h2d():
                staged = b
            probe.sync()
            seen.append(staged)
            probe.dispatched({"step": len(seen)}, examples=len(b[0]))
        probe.finish()
        assert seen == batches
        assert reg.get("pio_train_steps_total").value(model="toy") == 2
        assert reg.get("pio_train_examples_total").value(model="toy") == 3
        assert reg.get("pio_train_host_wait_ms").count(model="toy") == 2
        assert reg.get("pio_train_h2d_ms").count(model="toy") == 2
        # one-step lag: first sync is a no-op, finish drains the last
        assert reg.get("pio_train_device_wait_ms").count(model="toy") == 2
        parse_prometheus(reg.render())
