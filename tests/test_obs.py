"""obs/ unit tests: registry semantics, renderer validity, tracing,
pipeline probe.

``parse_prometheus`` doubles as the suite's Prometheus text-format
validator (no prometheus_client in the image): strict line grammar,
TYPE-before-samples, cumulative ``le`` buckets, ``+Inf`` == ``_count``.
test_servers.py imports it to validate live ``/metrics`` output.
"""

import importlib.util
import json
import math
import pathlib
import re
import threading

import pytest

from predictionio_tpu.obs import (
    CompileTracker,
    DeviceMemorySampler,
    MetricsRegistry,
    PipelineProbe,
    StepTimeline,
    TraceRecorder,
    get_recorder,
    get_registry,
    get_timeline,
    phase,
    publish_event,
    reset_observability,
    sanitize_trace_id,
    set_timeline,
    span,
    trace,
)

# -- Prometheus text-format parser/validator --------------------------------

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_SAMPLE_RE = re.compile(
    rf"^({_NAME})(?:\{{(.*)\}})? (-?(?:[0-9.]+(?:[eE][+-]?[0-9]+)?|Inf)|\+Inf|NaN)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"(?:,|$)')
_VALUE = r"(?:-?(?:[0-9.]+(?:[eE][+-]?[0-9]+)?|Inf)|\+Inf|NaN)"
# OpenMetrics exemplar suffix: ` # {labels} value` (ISSUE 9: histogram
# buckets carry the trace id of the last observation that landed there).
_EXEMPLAR_RE = re.compile(rf" # \{{((?:[^\"}}]|\"(?:[^\"\\]|\\.)*\")*)\}} ({_VALUE})$")


def _parse_value(s: str) -> float:
    if s == "+Inf":
        return math.inf
    if s == "-Inf":
        return -math.inf
    if s == "NaN":
        return math.nan
    return float(s)


def parse_prometheus(text: str):
    """Validate + parse exposition text → {name: [(labels_dict, value)]}.

    Raises AssertionError on any malformed line, samples without a
    preceding # TYPE, non-cumulative histogram buckets, or +Inf bucket
    disagreeing with _count.
    """
    samples = {}
    types = {}
    assert text.endswith("\n"), "exposition must end with a newline"
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            assert kind in ("counter", "gauge", "histogram", "summary",
                            "untyped"), f"bad TYPE line: {line!r}"
            types[name] = kind
            continue
        assert not line.startswith("#"), f"unknown comment: {line!r}"
        em = _EXEMPLAR_RE.search(line)
        if em:
            line = line[:em.start()]
        m = _SAMPLE_RE.match(line)
        assert m, f"malformed sample line: {line!r}"
        name, labels_raw, value = m.group(1), m.group(2), m.group(3)
        if em:
            # Exemplars are legal only on histogram bucket samples, and
            # their labelset must itself be well-formed.
            assert name.endswith("_bucket"), \
                f"exemplar on non-bucket sample: {line!r}"
            ex_labels = em.group(1)
            consumed = sum(len(mm.group(0)) for mm in
                           _LABEL_RE.finditer(ex_labels))
            assert consumed == len(ex_labels), \
                f"malformed exemplar labels: {ex_labels!r}"
            _parse_value(em.group(2))
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        assert base in types or name in types, \
            f"sample {name!r} has no # TYPE"
        labels = {}
        if labels_raw:
            consumed = sum(len(mm.group(0)) for mm in
                           _LABEL_RE.finditer(labels_raw))
            assert consumed == len(labels_raw), \
                f"malformed labels: {labels_raw!r}"
            for mm in _LABEL_RE.finditer(labels_raw):
                labels[mm.group(1)] = mm.group(2)
        samples.setdefault(name, []).append((labels, _parse_value(value)))
    # histogram invariants
    for name, kind in types.items():
        if kind != "histogram":
            continue
        series = {}
        for labels, v in samples.get(f"{name}_bucket", []):
            key = tuple(sorted((k, lv) for k, lv in labels.items()
                               if k != "le"))
            le = math.inf if labels["le"] == "+Inf" else float(labels["le"])
            series.setdefault(key, []).append((le, v))
        counts = {tuple(sorted(labels.items())): v
                  for labels, v in samples.get(f"{name}_count", [])}
        for key, bs in series.items():
            bs.sort()
            cums = [v for _, v in bs]
            assert cums == sorted(cums), f"{name}{key}: buckets not cumulative"
            assert bs[-1][0] == math.inf, f"{name}{key}: no +Inf bucket"
            assert bs[-1][1] == counts[key], \
                f"{name}{key}: +Inf bucket != _count"
    return samples


# -- registry ---------------------------------------------------------------

class TestRegistry:
    def test_counter_labels_and_values(self):
        reg = MetricsRegistry()
        c = reg.counter("pio_t_total", "t", ("status",))
        c.inc(status="200")
        c.inc(2, status="404")
        assert c.value(status="200") == 1
        assert c.value(status="404") == 2
        assert c.total() == 3
        with pytest.raises(ValueError):
            c.inc(status="200", extra="nope")
        with pytest.raises(ValueError):
            c.inc(-1, status="200")

    def test_get_or_create_and_mismatch(self):
        reg = MetricsRegistry()
        a = reg.counter("pio_x_total", "x")
        assert reg.counter("pio_x_total") is a
        with pytest.raises(ValueError):
            reg.gauge("pio_x_total")
        with pytest.raises(ValueError):
            reg.counter("pio_x_total", labelnames=("other",))
        with pytest.raises(ValueError):
            reg.counter("0bad name")
        h = reg.histogram("pio_x_ms", buckets=(1, 10))
        assert reg.histogram("pio_x_ms", buckets=(1, 10)) is h
        with pytest.raises(ValueError):
            reg.histogram("pio_x_ms", buckets=(5, 50))

    def test_phase_records_even_on_exception(self):
        reset_observability()
        with pytest.raises(RuntimeError):
            with trace("workflow.train"):
                with phase("train.datasource"):
                    raise RuntimeError("boom")
        h = get_registry().get("pio_train_phase_ms")
        assert h.count(phase="train.datasource") == 1

    def test_label_escaping_round_trips(self):
        reg = MetricsRegistry()
        c = reg.counter("pio_esc_total", "h", ("route",))
        nasty = 'a"b\\c\nd'
        c.inc(route=nasty)
        text = reg.render()
        assert '\\"' in text and "\\\\" in text and "\\n" in text
        samples = parse_prometheus(text)
        (labels, value), = samples["pio_esc_total"]
        assert value == 1
        # unescape what the renderer escaped — must round-trip
        unescaped = (labels["route"].replace("\\\\", "\x00")
                     .replace('\\"', '"').replace("\\n", "\n")
                     .replace("\x00", "\\"))
        assert unescaped == nasty

    def test_histogram_buckets_and_quantiles(self):
        reg = MetricsRegistry()
        h = reg.histogram("pio_h_ms", "h", buckets=(1, 10, 100))
        for v in (0.5, 5, 5, 50, 500):
            h.observe(v)
        assert h.count() == 5
        assert h.sum() == 560.5
        samples = parse_prometheus(reg.render())
        le_counts = {labels["le"]: v
                     for labels, v in samples["pio_h_ms_bucket"]}
        assert le_counts == {"1": 1, "10": 3, "100": 4, "+Inf": 5}
        # interpolated median lands inside the (1, 10] bucket
        assert 1 <= h.quantile(0.5) <= 10
        # +Inf-bucket quantiles report the top finite bound
        assert h.quantile(0.999) == 100

    def test_concurrent_increments_lose_nothing(self):
        reg = MetricsRegistry()
        c = reg.counter("pio_c_total", "c", ("worker",))
        h = reg.histogram("pio_ch_ms", "h")
        n_threads, per = 8, 500

        def work(i):
            for _ in range(per):
                c.inc(worker=str(i % 2))
                h.observe(1.0)

        ts = [threading.Thread(target=work, args=(i,))
              for i in range(n_threads)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert c.total() == n_threads * per
        assert h.count() == n_threads * per

    def test_unlabelled_counter_renders_bare(self):
        reg = MetricsRegistry()
        reg.counter("pio_bare_total", "b").inc()
        assert "pio_bare_total 1\n" in reg.render()

    def test_gauge_set_inc_dec(self):
        reg = MetricsRegistry()
        g = reg.gauge("pio_g", "g")
        g.set(5)
        g.inc(2)
        g.dec()
        assert g.value() == 6

    def test_render_is_valid_when_empty_and_after_reset(self):
        reg = MetricsRegistry()
        reg.counter("pio_a_total", "a", ("x",))
        parse_prometheus(reg.render())
        reg.reset()
        assert reg.render() == "\n" or parse_prometheus(reg.render()) == {}


# -- tracing ----------------------------------------------------------------

class TestTracing:
    def setup_method(self):
        reset_observability()

    def test_span_tree_and_ring(self):
        with trace("root", trace_id="tid-1", a=1) as t:
            with span("child1"):
                with span("grand"):
                    pass
            with span("child2", algo="als"):
                pass
        assert t.duration_ms is not None
        docs = get_recorder().recent(5)
        assert docs and docs[0]["traceId"] == "tid-1"
        names = [s["name"] for s in docs[0]["spans"]]
        assert names == ["child1", "child2"]
        assert docs[0]["spans"][0]["spans"][0]["name"] == "grand"
        assert docs[0]["spans"][1]["attrs"] == {"algo": "als"}

    def test_span_outside_trace_records_nothing(self):
        with span("orphan") as s:
            pass
        assert s.duration_ms is not None
        assert get_recorder().recent(5) == []

    def test_nested_trace_degrades_to_span(self):
        with trace("outer"):
            with trace("inner"):
                pass
        docs = get_recorder().recent(5)
        assert len(docs) == 1
        assert [s["name"] for s in docs[0]["spans"]] == ["inner"]

    def test_jsonl_export(self, tmp_path, monkeypatch):
        out = tmp_path / "traces.jsonl"
        monkeypatch.setenv("PIO_TRACE_FILE", str(out))
        with trace("one"):
            pass
        with trace("two"):
            with span("s"):
                pass
        lines = [json.loads(line) for line in
                 out.read_text().strip().splitlines()]
        assert [d["name"] for d in lines] == ["one", "two"]
        assert all("traceId" in d and "durationMs" in d for d in lines)

    def test_ring_is_bounded(self):
        rec = TraceRecorder(ring_size=3)
        for i in range(5):
            with trace(f"t{i}", recorder=rec):
                pass
        assert [d["name"] for d in rec.recent(10)] == ["t4", "t3", "t2"]

    def test_slow_trace_logs_warning(self, caplog):
        import logging

        with caplog.at_level(logging.WARNING,
                             logger="predictionio_tpu.obs.trace"):
            with trace("fast", slow_ms=10000):
                pass
            assert not caplog.records
            with trace("slow", slow_ms=0.0000001):
                pass
        assert any("slow" in r.message for r in caplog.records)

    def test_sanitize_trace_id(self):
        assert sanitize_trace_id(None) is None
        assert sanitize_trace_id("") is None
        assert sanitize_trace_id("ab-c_1.2:3") == "ab-c_1.2:3"
        # CRLF and header-splitting characters are stripped
        assert sanitize_trace_id("a\r\nSet-Cookie: x") == "aSet-Cookie:x"
        assert sanitize_trace_id("\r\n") is None
        assert len(sanitize_trace_id("x" * 500)) == 128

    def test_phase_records_span_and_histogram(self):
        reset_observability()
        with trace("workflow.train"):
            with phase("train.datasource"):
                pass
        h = get_registry().get("pio_train_phase_ms")
        assert h.count(phase="train.datasource") == 1
        doc = get_recorder().recent(1)[0]
        assert doc["spans"][0]["name"] == "train.datasource"


# -- pipeline probe ---------------------------------------------------------

class TestPipelineProbe:
    def test_decomposition_counts(self):
        reg = MetricsRegistry()
        probe = PipelineProbe("toy", registry=reg,
                              timeline=StepTimeline(capacity=16))
        batches = [([1, 2], [3, 4]), ([5], [6])]
        seen = []
        for b in probe.iter_host(iter(batches)):
            with probe.h2d():
                staged = b
            probe.sync()
            seen.append(staged)
            probe.dispatched({"step": len(seen)}, examples=len(b[0]))
        probe.finish()
        assert seen == batches
        assert reg.get("pio_train_steps_total").value(model="toy") == 2
        assert reg.get("pio_train_examples_total").value(model="toy") == 3
        assert reg.get("pio_train_host_wait_ms").count(model="toy") == 2
        assert reg.get("pio_train_h2d_ms").count(model="toy") == 2
        # one-step lag: first sync is a no-op, finish drains the last
        assert reg.get("pio_train_device_wait_ms").count(model="toy") == 2
        parse_prometheus(reg.render())

    def test_probe_feeds_timeline_per_step(self):
        reg = MetricsRegistry()
        tl = StepTimeline(capacity=16)
        probe = PipelineProbe("toy", registry=reg, timeline=tl)
        for b in probe.iter_host(iter([([1, 2],), ([3],)])):
            with probe.h2d():
                pass
            probe.sync()
            probe.dispatched({"x": 1}, examples=len(b[0]))
        probe.finish()
        steps = tl.recent(10, model="toy")
        assert len(steps) == 2
        # most recent first; step ids increase; every phase recorded
        assert [r["step"] for r in steps] == [2, 1]
        assert steps[0]["examples"] == 1 and steps[1]["examples"] == 2
        for r in steps:
            for k in ("hostWaitMs", "h2dMs", "deviceWaitMs",
                      "deviceStepMs", "startS"):
                assert r[k] >= 0


# -- runtime introspection ---------------------------------------------------

class _FakeJit:
    """Stands in for a jax.jit wrapper: compiles (cache grows) whenever
    called with an unseen arg 'shape'."""

    def __init__(self):
        self.cache = set()
        self.calls = 0

    def _cache_size(self):
        return len(self.cache)

    def __call__(self, x):
        self.calls += 1
        self.cache.add(x)
        return x * 2


class TestCompileTracker:
    def setup_method(self):
        reset_observability()

    def test_counts_only_compiling_calls(self):
        reg = get_registry()
        tracker = CompileTracker(warn_threshold=99)
        fn = tracker.wrap("toy.step", _FakeJit())
        assert fn(1) == 2
        assert fn(1) == 2    # cache hit: no compile
        assert fn(2) == 4    # new "shape": compile
        c = reg.get("pio_xla_compile_total")
        assert c.value(fn="toy.step") == 2
        assert reg.get("pio_xla_compile_ms").count(fn="toy.step") == 2
        parse_prometheus(reg.render())

    def test_compile_event_lands_in_trace_ring(self):
        tracker = CompileTracker(warn_threshold=99)
        fn = tracker.wrap("toy.step", _FakeJit())
        fn(1)
        docs = get_recorder().recent(5)
        assert docs and docs[0]["name"] == "xla.compile"
        assert docs[0]["attrs"]["fn"] == "toy.step"

    def test_compile_inside_open_trace_attaches_to_request(self):
        tracker = CompileTracker(warn_threshold=99)
        fn = tracker.wrap("toy.step", _FakeJit())
        with trace("http.request", trace_id="req-9"):
            fn(1)
        doc, = get_recorder().recent(5)
        assert doc["traceId"] == "req-9"
        names = [s["name"] for s in doc.get("spans", [])]
        assert "xla.compile" in names  # "recompiled here"

    def test_shape_churn_warning_past_threshold(self, caplog):
        import logging

        tracker = CompileTracker(warn_threshold=2)
        fn = tracker.wrap("churny.step", _FakeJit())
        with caplog.at_level(logging.WARNING,
                             logger="predictionio_tpu.obs.runtime"):
            fn(1)
            fn(2)
            assert not caplog.records  # at threshold: still quiet
            fn(3)
        assert any("shape churn" in r.message and "churny.step" in r.message
                   for r in caplog.records)

    def test_unwrappable_fn_passes_through(self):
        tracker = CompileTracker(warn_threshold=99)
        fn = tracker.wrap("plain", lambda x: x + 1)  # no _cache_size
        assert fn(1) == 2
        c = get_registry().get("pio_xla_compile_total")
        assert c is None or c.value(fn="plain") == 0


class _FakeDevice:
    def __init__(self, platform, id, stats):
        self.platform = platform
        self.id = id
        self._stats = stats

    def memory_stats(self):
        return self._stats


class _FakeArray:
    def __init__(self, nbytes, device):
        self.nbytes = nbytes
        self._device = device

    def devices(self):
        return {self._device}


class TestDeviceMemorySampler:
    def setup_method(self):
        reset_observability()

    def test_sample_exports_gauges_and_tracks_peak(self):
        t = [100.0]
        stats = {"bytes_in_use": 1000, "peak_bytes_in_use": 1500,
                 "bytes_limit": 4000}
        dev = _FakeDevice("tpu", 0, stats)
        sampler = DeviceMemorySampler(
            interval_s=0, devices_fn=lambda: [dev],
            live_arrays_fn=lambda: [], clock=lambda: t[0])
        out = sampler.sample_once()
        assert out["tpu:0"]["bytes_in_use"] == 1000
        g = get_registry().get("pio_device_mem_bytes")
        assert g.value(device="tpu:0", kind="bytes_in_use") == 1000
        assert g.value(device="tpu:0", kind="bytes_limit") == 4000
        peak = get_registry().get("pio_device_mem_peak_bytes")
        # the window peaks over OUR bytes_in_use samples; the allocator's
        # monotone peak_bytes_in_use must NOT leak in (it would defeat
        # reset_peak) — it stays visible as its own kind gauge
        assert peak.value(device="tpu:0") == 1000
        assert g.value(device="tpu:0", kind="peak_bytes_in_use") == 1500
        # memory falls; the peak gauge must NOT fall with it
        stats["bytes_in_use"] = 200
        stats["peak_bytes_in_use"] = 0
        sampler.sample_once()
        assert peak.value(device="tpu:0") == 1000
        # fresh train run: window resets, next sample re-establishes
        sampler.reset_peak()
        sampler.sample_once()
        assert peak.value(device="tpu:0") == 200
        parse_prometheus(get_registry().render())

    def test_live_array_fallback_for_statless_backends(self):
        dev = _FakeDevice("cpu", 0, None)
        arrays = [_FakeArray(64, dev), _FakeArray(36, dev)]
        sampler = DeviceMemorySampler(
            interval_s=0, devices_fn=lambda: [dev],
            live_arrays_fn=lambda: arrays)
        out = sampler.sample_once()
        assert out["cpu:0"]["live_bytes"] == 100
        g = get_registry().get("pio_device_mem_bytes")
        assert g.value(device="cpu:0", kind="live_bytes") == 100
        assert g.value(device="cpu:0", kind="live_arrays") == 2
        # live_bytes stands in for bytes_in_use in the peak window
        assert get_registry().get(
            "pio_device_mem_peak_bytes").value(device="cpu:0") == 100

    def test_interval_zero_disables_thread(self):
        sampler = DeviceMemorySampler(interval_s=0,
                                      devices_fn=lambda: [])
        assert sampler.start() is False

    def test_device_enumeration_failure_is_quiet(self):
        def boom():
            raise RuntimeError("tunnel down")

        sampler = DeviceMemorySampler(interval_s=0, devices_fn=boom,
                                      live_arrays_fn=lambda: [])
        assert sampler.sample_once() == {}


class TestStepTimeline:
    def test_ring_bounds_and_summary_shares(self):
        tl = StepTimeline(capacity=3)
        for i in range(5):
            tl.record("m", host_wait_ms=10, h2d_ms=30, device_wait_ms=60,
                      device_step_ms=70, examples=8, start_s=1000.0 + i)
        assert len(tl.recent(10)) == 3  # bounded
        s = tl.summary("m")
        assert s["steps"] == 3 and s["examples"] == 24
        assert s["phase_ms"]["h2d"] == 90
        assert abs(s["phase_share"]["host_wait"] - 0.1) < 1e-6
        assert abs(s["phase_share"]["device_wait"] - 0.6) < 1e-6
        # device_step is overlapped: tracked in phase_ms, not in shares
        assert "device_step" not in s["phase_share"]

    def test_models_filter(self):
        tl = StepTimeline(capacity=8)
        tl.record("a", host_wait_ms=1)
        tl.record("b", h2d_ms=2)
        assert tl.models() == ["a", "b"]
        assert [r["model"] for r in tl.recent(10, model="a")] == ["a"]

    def test_chrome_trace_export(self):
        tl = StepTimeline(capacity=8)
        tl.record("m", host_wait_ms=1.0, h2d_ms=2.0, device_wait_ms=3.0,
                  device_step_ms=4.0, start_s=123.0, examples=8)
        doc = tl.to_chrome_trace()
        events = doc["traceEvents"]
        assert {e["ph"] for e in events} == {"M", "X"}
        xs = [e for e in events if e["ph"] == "X"]
        assert {e["name"] for e in xs} == {"host_wait", "h2d",
                                           "device_wait", "device_step"}
        # host-lane phases tile sequentially from the step start
        by_name = {e["name"]: e for e in xs}
        assert by_name["h2d"]["ts"] == pytest.approx(
            by_name["host_wait"]["ts"] + by_name["host_wait"]["dur"])
        assert by_name["device_step"]["tid"] != by_name["host_wait"]["tid"]
        json.dumps(doc)  # must be directly serializable

    def test_process_timeline_swap(self):
        prev = set_timeline(StepTimeline(capacity=4))
        try:
            get_timeline().record("x", host_wait_ms=1)
            assert get_timeline().models() == ["x"]
        finally:
            set_timeline(prev)


class TestPublishEvent:
    def setup_method(self):
        reset_observability()

    def test_standalone_event_records_trace(self):
        publish_event("breaker.transition", breaker="b", to="open")
        doc, = get_recorder().recent(5)
        assert doc["name"] == "breaker.transition"
        assert doc["attrs"]["to"] == "open"

    def test_event_inside_trace_attaches_as_child(self):
        with trace("http.request", trace_id="t1"):
            publish_event("spill.append", token="tok", events=3)
        doc, = get_recorder().recent(5)
        assert doc["traceId"] == "t1"
        assert [s["name"] for s in doc["spans"]] == ["spill.append"]


# -- attribute_gap tool ------------------------------------------------------

def _load_attribute_gap():
    path = (pathlib.Path(__file__).resolve().parents[1]
            / "tools" / "attribute_gap.py")
    spec = importlib.util.spec_from_file_location("attribute_gap", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestAttributeGap:
    BENCH = {
        "tpu_era": {
            "two_tower_examples_per_sec_per_chip": 1_000_000.0,
            "two_tower_feeder_examples_per_sec": 800_000.0,
            "two_tower_pipeline_examples_per_sec": 540_000.0,
            "two_tower_pipeline_gap_pct": 46.0,
            "dlrm_examples_per_sec_per_chip": 2_000_000.0,
            "dlrm_feeder_examples_per_sec": 900_000.0,
            "dlrm_pipeline_examples_per_sec": 260_000.0,
            "dlrm_pipeline_gap_pct": 87.0,
        },
        "timeline": {
            "two_tower": {"steps": 6, "examples": 100,
                          "phase_ms": {"host_wait": 10, "h2d": 70,
                                       "device_wait": 20,
                                       "device_step": 25},
                          "phase_share": {"host_wait": 0.1, "h2d": 0.7,
                                          "device_wait": 0.2}},
            "dlrm": {"steps": 6, "examples": 100,
                     "phase_ms": {"host_wait": 65, "h2d": 20,
                                  "device_wait": 15, "device_step": 10},
                     "phase_share": {"host_wait": 0.65, "h2d": 0.2,
                                     "device_wait": 0.15}},
        },
    }

    def test_dominant_component_and_attack(self):
        mod = _load_attribute_gap()
        res = mod.attribute(self.BENCH)
        assert res["two_tower"]["dominant"] == "h2d"
        assert "buffer" in res["two_tower"]["attack"]
        assert res["dlrm"]["dominant"] == "host_wait"
        assert "feeder" in res["dlrm"]["attack"]

    def test_render_prints_both_models_with_shares(self, capsys, tmp_path):
        mod = _load_attribute_gap()
        f = tmp_path / "round.json"
        f.write_text(json.dumps(self.BENCH))
        assert mod.main([str(f)]) == 0
        out = capsys.readouterr().out
        assert "two_tower" in out and "dlrm" in out
        assert "dominant: h2d" in out and "dominant: host_wait" in out
        assert "70.0%" in out  # the share of step time is printed

    def test_external_timeline_overrides_and_server_shape(self, tmp_path):
        mod = _load_attribute_gap()
        # /timeline.json server shape: summaries under "models"
        timeline = {"models": {
            "two_tower": {"steps": 2,
                          "phase_ms": {"host_wait": 5, "h2d": 1,
                                       "device_wait": 94},
                          "phase_share": {"host_wait": 0.05, "h2d": 0.01,
                                          "device_wait": 0.94}}}}
        res = mod.attribute(self.BENCH, timeline)
        assert res["two_tower"]["dominant"] == "device_wait"
        assert "fusion" in res["two_tower"]["attack"]
        assert res["dlrm"] is None  # absent from the override

    def test_no_data_exits_nonzero(self, capsys, tmp_path):
        mod = _load_attribute_gap()
        f = tmp_path / "round.json"
        f.write_text(json.dumps({"tpu_era": {}}))
        assert mod.main([str(f)]) == 1
        assert "no timeline data" in capsys.readouterr().out


# -- overlapped input pipeline (ISSUE 5): probe + timeline + HBM guard ------

class _FakePrefetched:
    """Stands in for data.prefetch.PrefetchedBatch (duck-typed)."""

    def __init__(self, step, args, examples, h2d_ms, staged_s):
        self.step = step
        self.args = args
        self.examples = examples
        self.h2d_ms = h2d_ms
        self.staged_s = staged_s


class TestPrefetchedProbe:
    def test_overlap_attribution_and_dispatch_stamp(self):
        reg = MetricsRegistry()
        tl = StepTimeline(capacity=16)
        probe = PipelineProbe("toy", registry=reg, timeline=tl)
        batches = [_FakePrefetched(k, ("a",), 4, 12.5, 1000.0 + k)
                   for k in (1, 2)]
        for b in probe.iter_prefetched(iter(batches)):
            probe.sync()
            probe.dispatched({"s": b.step}, examples=b.examples)
        probe.finish()
        # staging lands in the overlap window, not the h2d wall component
        assert reg.get("pio_train_h2d_overlap_ms").count(model="toy") == 2
        assert reg.get("pio_train_h2d_ms").count(model="toy") == 0
        recs = tl.recent(10, model="toy")
        assert len(recs) == 2
        for r in recs:
            assert r["h2dOverlapMs"] == pytest.approx(12.5)
            assert r["h2dMs"] == 0.0
            assert r["dispatchS"] > 0          # true dispatch wall clock
            assert r["stagedS"] >= 1000.0
        s = tl.summary("toy")
        assert s["phase_ms"]["h2d_overlap"] == pytest.approx(25.0)
        # overlapped staging is excluded from the wall decomposition
        assert "h2d_overlap" not in s["phase_share"]
        parse_prometheus(reg.render())

    def test_chrome_export_uses_dispatch_and_prefetch_lane(self):
        tl = StepTimeline(capacity=8)
        tl.record("m", host_wait_ms=1.0, h2d_overlap_ms=4.0,
                  device_wait_ms=3.0, device_step_ms=9.0,
                  start_s=100.0, dispatch_s=100.005, staged_s=99.999,
                  examples=8)
        doc = tl.to_chrome_trace()
        xs = {e["name"]: e for e in doc["traceEvents"] if e["ph"] == "X"}
        # the device lane starts at the recorded dispatch, not the
        # step start
        assert xs["device_step"]["ts"] == pytest.approx(100.005e6)
        # overlapped staging draws on its own lane, ending at stagedS
        pf = xs["h2d_overlap"]
        assert pf["tid"] == 2
        assert pf["ts"] + pf["dur"] == pytest.approx(99.999e6)
        lanes = {e["args"]["name"] for e in doc["traceEvents"]
                 if e["name"] == "thread_name"}
        assert lanes == {"host", "device", "prefetch"}
        json.dumps(doc)

    def test_chrome_export_without_dispatch_falls_back(self):
        tl = StepTimeline(capacity=8)
        tl.record("m", host_wait_ms=1.0, device_step_ms=2.0, start_s=50.0)
        doc = tl.to_chrome_trace()
        xs = {e["name"]: e for e in doc["traceEvents"] if e["ph"] == "X"}
        assert xs["device_step"]["ts"] == pytest.approx(50.0e6)
        lanes = {e["args"]["name"] for e in doc["traceEvents"]
                 if e["name"] == "thread_name"}
        assert lanes == {"host", "device"}  # no prefetch lane if unused


class TestHbmHeadroomWarning:
    def setup_method(self):
        reset_observability()

    def _sampler(self, stats):
        dev = _FakeDevice("tpu", 0, stats)
        return DeviceMemorySampler(interval_s=0, devices_fn=lambda: [dev],
                                   live_arrays_fn=lambda: [])

    def test_warns_once_per_window_above_fraction(self, caplog):
        stats = {"bytes_in_use": 950, "bytes_limit": 1000}
        sampler = self._sampler(stats)
        with caplog.at_level("WARNING"):
            sampler.sample_once()
            sampler.sample_once()  # second crossing must not re-warn
        warns = [r for r in caplog.records if "HBM headroom" in r.message]
        assert len(warns) == 1
        assert "PIO_PREFETCH_DEPTH" in warns[0].message
        c = get_registry().get("pio_hbm_headroom_warn_total")
        assert c.value(device="tpu:0") == 1

    def test_below_fraction_is_silent(self, caplog):
        sampler = self._sampler({"bytes_in_use": 500, "bytes_limit": 1000})
        with caplog.at_level("WARNING"):
            sampler.sample_once()
        assert not [r for r in caplog.records
                    if "HBM headroom" in r.message]

    def test_reset_peak_rearms_the_warning(self, caplog):
        stats = {"bytes_in_use": 950, "bytes_limit": 1000}
        sampler = self._sampler(stats)
        with caplog.at_level("WARNING"):
            sampler.sample_once()
            sampler.reset_peak()  # new train run -> fresh guard
            sampler.sample_once()
        warns = [r for r in caplog.records if "HBM headroom" in r.message]
        assert len(warns) == 2
        assert get_registry().get(
            "pio_hbm_headroom_warn_total").value(device="tpu:0") == 2

    def test_fraction_env_override_and_disable(self, caplog, monkeypatch):
        stats = {"bytes_in_use": 700, "bytes_limit": 1000}
        monkeypatch.setenv("PIO_HBM_WARN_FRACTION", "0.5")
        with caplog.at_level("WARNING"):
            self._sampler(stats).sample_once()
        assert [r for r in caplog.records if "HBM headroom" in r.message]
        caplog.clear()
        monkeypatch.setenv("PIO_HBM_WARN_FRACTION", "0")  # disabled
        with caplog.at_level("WARNING"):
            self._sampler(stats).sample_once()
        assert not [r for r in caplog.records
                    if "HBM headroom" in r.message]

    def test_no_limit_no_warning(self, caplog):
        # CPU live-array fallback has no bytes_limit: never warns
        dev = _FakeDevice("cpu", 0, None)
        sampler = DeviceMemorySampler(
            interval_s=0, devices_fn=lambda: [dev],
            live_arrays_fn=lambda: [_FakeArray(900, dev)])
        with caplog.at_level("WARNING"):
            sampler.sample_once()
        assert not [r for r in caplog.records
                    if "HBM headroom" in r.message]

    def test_headroom_exceeded_latches_the_run_peak(self):
        # The fusion autotuner probes BETWEEN windows — in the memory
        # trough.  headroom_exceeded must answer from the run PEAK the
        # sampler observed (here: a mid-window sample), not the
        # instantaneous trough, or the tuner grows straight past the
        # limit into an OOM.  reset_peak (a new train run) re-arms it.
        stats = {"bytes_in_use": 950, "bytes_limit": 1000}
        sampler = self._sampler(stats)
        sampler.sample_once()  # mid-window: the peak
        stats["bytes_in_use"] = 100  # trough at the round boundary
        assert sampler.headroom_exceeded() is True
        sampler.reset_peak()
        assert sampler.headroom_exceeded() is False
        assert sampler.headroom_exceeded(fraction=0.05) is True


class TestAttributeGapCompare:
    OLD = {
        "tpu_era": {
            "two_tower_pipeline_examples_per_sec": 500_000.0,
            "two_tower_pipeline_gap_pct": 45.9,
            "two_tower_feeder_examples_per_sec": 900_000.0,
            "dlrm_pipeline_examples_per_sec": 120_000.0,
            "dlrm_pipeline_gap_pct": 87.0,
        },
        "timeline": {
            "two_tower": {"steps": 4,
                          "phase_ms": {"host_wait": 10, "h2d": 70,
                                       "device_wait": 20},
                          "phase_share": {"host_wait": 0.1, "h2d": 0.7,
                                          "device_wait": 0.2}},
        },
    }
    NEW = {
        "tpu_era": {
            "two_tower_pipeline_examples_per_sec": 800_000.0,
            "two_tower_pipeline_gap_pct": 12.0,
            "two_tower_feeder_examples_per_sec": 900_000.0,
            "dlrm_pipeline_examples_per_sec": 300_000.0,
            "dlrm_pipeline_gap_pct": 40.0,
        },
        "timeline": {
            "two_tower": {"steps": 4,
                          "phase_ms": {"host_wait": 10, "h2d": 2,
                                       "device_wait": 88,
                                       "h2d_overlap": 60},
                          "phase_share": {"host_wait": 0.1, "h2d": 0.02,
                                          "device_wait": 0.88}},
        },
    }

    def test_gap_delta_and_dominant_shift(self):
        mod = _load_attribute_gap()
        res = mod.compare(self.OLD, self.NEW)
        tt = res["two_tower"]
        assert tt["gap_delta_pct"] == pytest.approx(-33.9)
        assert tt["realized_speedup"] == pytest.approx(1.6)
        assert tt["dominant_shift"] == ("h2d", "device_wait")
        # dlrm has gap numbers but no timeline in either round:
        # compared on gaps alone, no dominant shift
        assert res["dlrm"]["gap_delta_pct"] == pytest.approx(-47.0)
        assert "dominant_shift" not in res["dlrm"]

    def test_render_and_cli_exit_code(self, capsys, tmp_path):
        mod = _load_attribute_gap()
        old_f = tmp_path / "old.json"
        new_f = tmp_path / "new.json"
        old_f.write_text(json.dumps(self.OLD))
        new_f.write_text(json.dumps(self.NEW))
        assert mod.main(["--compare", str(old_f), str(new_f)]) == 0
        out = capsys.readouterr().out
        assert "45.9% -> 12.0% (-33.9 pts)" in out
        assert "dominant component shifted: h2d" in out
        assert "87.0% -> 40.0% (-47.0 pts)" in out

    def test_driver_capture_with_truncated_tail_unwraps(self, tmp_path):
        mod = _load_attribute_gap()
        # a driver round whose tail was truncated mid-JSON (as committed
        # BENCH_r05.json is): the tpu_era block is still rescued
        inner = json.dumps(self.OLD)
        # leading garbage + the object body minus its opening brace: no
        # line parses whole, so the brace-scan rescue must kick in
        wrapped = {"n": 5, "cmd": "python bench.py", "rc": 0,
                   "tail": 'g": {"x": 1}}, ' + inner[1:]}
        f = tmp_path / "r.json"
        f.write_text(json.dumps(wrapped))
        doc = mod.load_json(str(f))
        assert doc["tpu_era"]["two_tower_pipeline_gap_pct"] == 45.9

    def test_compare_nothing_usable_exits_nonzero(self, tmp_path):
        mod = _load_attribute_gap()
        a = tmp_path / "a.json"
        b = tmp_path / "b.json"
        a.write_text(json.dumps({"tpu_era": {}}))
        b.write_text(json.dumps({"tpu_era": {}}))
        assert mod.main(["--compare", str(a), str(b)]) == 1
