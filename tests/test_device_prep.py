"""Device-side bucketing (ops/device_prep.py) vs the host-numpy oracle.

The device path must produce byte-identical bucket CONTENTS (same entries
per entity, same within-row event order, same split-segment layout) as
``bucket_by_length``; only row/slot ordering metadata may differ, and the
ALS consumer is invariant to that by construction (row_ids route scatter).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from predictionio_tpu.models.als import ALSConfig, train_als, rmse
from predictionio_tpu.ops.device_prep import (
    build_buckets, degree_histogram, plan_buckets,
)
from predictionio_tpu.ops.ragged import bucket_by_length


def _coo(seed=3, n_rows=400, n_cols=300, n=20_000, zipf=1.3):
    rng = np.random.default_rng(seed)
    rows = (rng.zipf(zipf, n) % n_rows).astype(np.int32)
    cols = rng.integers(0, n_cols, n).astype(np.int32)
    vals = rng.random(n).astype(np.float32)
    return rows, cols, vals


def _device_side(rows, cols, vals, n_rows, split_above):
    counts = jnp.zeros(n_rows, jnp.int32).at[jnp.asarray(rows)].add(1)
    hist, n_over, n_part = degree_histogram(counts, split_above)
    plan = plan_buckets(hist, n_over, n_part, n_rows,
                        split_above=split_above, pad_rows_to=8)
    return build_buckets(jnp.asarray(rows), jnp.asarray(cols),
                         jnp.asarray(vals), plan)


class TestDeviceBucketEquivalence:
    @pytest.mark.parametrize("split_above", [64, 8192])
    def test_matches_host_oracle(self, split_above):
        rows, cols, vals = _coo()
        n_rows = 400
        host = bucket_by_length(rows.astype(np.int64), cols.astype(np.int64),
                                vals, n_rows, split_above=split_above,
                                pad_rows_to=8)
        plain, split = _device_side(rows, cols, vals, n_rows, split_above)
        host_plain = [p for p in host if not p.split]
        assert len(host_plain) == len(plain)
        for hp, dp in zip(host_plain, plain):
            idx, val, msk, rid = [np.asarray(x) for x in dp]
            hmap = {int(r): i for i, r in enumerate(hp.row_ids) if r >= 0}
            dmap = {int(r): i for i, r in enumerate(rid) if r >= 0}
            assert set(hmap) == set(dmap)
            for r in hmap:
                hi, di = hmap[r], dmap[r]
                assert np.array_equal(hp.indices[hi][hp.mask[hi]],
                                      idx[di][msk[di]])
                assert np.array_equal(hp.values[hi][hp.mask[hi]],
                                      val[di][msk[di]])
        host_split = [p for p in host if p.split]
        if not host_split:
            assert split is None
            return
        hs = host_split[0]
        assert len(split) == 1  # unchunked plan: one merged block
        didx, dval, dmsk, dseg, dent = [np.asarray(x) for x in split[0]]
        for e_h, ent_id in enumerate(hs.ent_ids):
            if ent_id < 0:
                continue
            h_rows = np.where(hs.seg_ids == e_h)[0]
            h_seq = np.concatenate(
                [hs.indices[r][hs.mask[r]] for r in h_rows])
            (e_d,) = np.where(dent == ent_id)
            d_rows = np.where(dseg == e_d[0])[0]
            d_seq = np.concatenate([didx[r][dmsk[r]] for r in d_rows])
            assert np.array_equal(h_seq, d_seq)

    def test_nnz_conserved(self):
        rows, cols, vals = _coo(seed=7)
        plain, split = _device_side(rows, cols, vals, 400, 64)
        tot = sum(int(np.asarray(p[2]).sum()) for p in plain)
        if split is not None:
            tot += sum(int(np.asarray(c[2]).sum()) for c in split)
        assert tot == len(rows)

    def test_no_split_when_all_short(self):
        rows = np.arange(100, dtype=np.int32)
        cols = np.arange(100, dtype=np.int32)
        vals = np.ones(100, np.float32)
        plain, split = _device_side(rows, cols, vals, 100, 4096)
        assert split is None
        assert sum(int(np.asarray(p[2]).sum()) for p in plain) == 100


class TestTrainWithDevicePrep:
    def test_train_converges_like_host_path(self):
        """Same data through both prep paths → same fit quality.

        Inits differ (host numpy rng vs device PRNG) so factors are not
        bitwise comparable; RMSE after a few sweeps must match closely.
        """
        rng = np.random.default_rng(0)
        n_u, n_i, n = 120, 80, 4000
        true_u = rng.standard_normal((n_u, 4))
        true_i = rng.standard_normal((n_i, 4))
        users = rng.integers(0, n_u, n)
        items = (rng.zipf(1.4, n) % n_i).astype(np.int64)
        ratings = np.sum(true_u[users] * true_i[items], axis=1).astype(
            np.float32)
        cfg_host = ALSConfig(rank=8, iterations=6, reg=0.05, seed=1,
                             device_prep=False, split_above=64)
        cfg_dev = ALSConfig(rank=8, iterations=6, reg=0.05, seed=1,
                            device_prep=True, split_above=64)
        m_host = train_als(users, items, ratings, n_u, n_i, cfg_host)
        m_dev = train_als(users, items, ratings, n_u, n_i, cfg_dev)
        r_host = rmse(m_host, users, items, ratings)
        r_dev = rmse(m_dev, users, items, ratings)
        assert abs(r_host - r_dev) < 0.05 * max(r_host, 0.1)

    def test_chunking_path(self):
        """A tiny max_block_floats forces bucket chunking on device."""
        rows, cols, vals = _coo(seed=5, n_rows=64, n_cols=64, n=6000,
                                zipf=1.2)
        cfg = ALSConfig(rank=8, iterations=2, reg=0.05, seed=1,
                        device_prep=True, split_above=32,
                        max_block_floats=1 << 14)
        m = train_als(rows, cols, vals, 64, 64, cfg)
        assert np.isfinite(np.asarray(m.user_factors)).all()
        assert np.isfinite(np.asarray(m.item_factors)).all()


class TestPlanShapeLockstep:
    def test_plan_bucket_shapes_match_build(self):
        """_plan_bucket_shapes (the loop pre-warm's shape oracle) must stay
        in lock-step with what the prep path actually emits — the pre-warm
        compiles the training loop from these shapes BEFORE prep runs, and
        a drift would silently turn the overlapped compile into a wasted
        one plus a second, serial compile."""
        from predictionio_tpu.models.als import (
            _plan_bucket_shapes, _plan_side, prepare_als_inputs,
        )

        rows, cols, vals = _coo(seed=7, n_rows=96, n_cols=64, n=9000,
                                zipf=1.2)
        cfg = ALSConfig(rank=8, iterations=1, seed=1, device_prep=True,
                        split_above=32, max_block_floats=1 << 14)
        inputs = prepare_als_inputs(rows, cols, vals, 96, 64, cfg)
        plan_u = _plan_side(jnp.asarray(rows, jnp.int32), 96, cfg)
        plan_i = _plan_side(jnp.asarray(cols, jnp.int32), 64, cfg)
        for plan, buckets, specs in (
                (plan_u, inputs.user_buckets, inputs.chunk_specs[0]),
                (plan_i, inputs.item_buckets, inputs.chunk_specs[1])):
            shapes, spec_pred = _plan_bucket_shapes(plan)
            assert spec_pred == specs
            assert len(shapes) == len(buckets)
            for pred, real in zip(shapes, buckets):
                assert pred[0] == real[0]  # kind
                assert len(pred) == len(real)
                for s, a in zip(pred[1:], real[1:]):
                    assert s.shape == a.shape, (s.shape, a.shape)
                    assert s.dtype == a.dtype, (s.dtype, a.dtype)
        # At least one merged bucket must have been exercised.
        assert any(b[0] == "merged" for b in inputs.user_buckets)

    def test_host_stats_match_device_stats(self):
        """_plan_side(host_rows=...) must yield the IDENTICAL BucketPlan
        to the device stats path — the plan keys the build/warm caches and
        any drift would silently compile two programs per dataset."""
        from predictionio_tpu.models.als import _plan_side

        rows, _, _ = _coo(seed=11, n_rows=200, n_cols=50, n=30_000, zipf=1.2)
        cfg = ALSConfig(rank=8, split_above=64, max_block_floats=1 << 14)
        dev_plan = _plan_side(jnp.asarray(rows, jnp.int32), 200, cfg)
        host_plan = _plan_side(jnp.asarray(rows, jnp.int32), 200, cfg,
                               host_rows=rows)
        assert dev_plan == host_plan

    def test_loop_warm_executable_delivered_and_used(self):
        """The plan-shape pre-warm must deliver a usable executable whose
        statics match what train_als_prepared resolves — otherwise the
        cold-start overlap silently degrades to a second compile."""
        from predictionio_tpu.models.als import (
            _resolve_loop_statics, prepare_als_inputs, train_als_prepared,
        )

        rows, cols, vals = _coo(seed=9, n_rows=64, n_cols=48, n=5000,
                                zipf=1.2)
        cfg = ALSConfig(rank=8, iterations=2, seed=1, device_prep=True,
                        split_above=32, max_block_floats=1 << 14)
        inputs = prepare_als_inputs(rows, cols, vals, 64, 48, cfg)
        assert inputs.loop_warm is not None
        warm = inputs.loop_warm.result(timeout=120)
        assert warm is not None, "pre-warm compile failed"
        statics, exe = warm
        live = _resolve_loop_statics(cfg, inputs.user_buckets,
                                     inputs.item_buckets, inputs.chunk_specs)
        assert statics == live == inputs.loop_warm_statics
        # and the train path accepts these inputs end-to-end
        m = train_als_prepared(inputs, cfg)
        assert np.isfinite(np.asarray(m.user_factors)).all()
