"""ISSUE 7: K-step fused training dispatch + HBM-guided autotuner.

The acceptance pins: K>1 fused training is BITWISE-equal to K=1 on CPU
for both deep models (params, opt_state, loss trace); a checkpoint
resume landing mid-window replays the remainder at the base shape; a
NaN at slot k of a fused window still rolls back to a finite
checkpoint.  The autotuner grows fusion depth until the (injected) HBM
headroom guardrail pushes back, then backs off one notch and pins.
"""

import numpy as np
import pytest

from predictionio_tpu.data.fusion import (
    FusionAutotuner,
    FusionPlan,
    batch_autoscale_enabled,
    crossed_save_point,
    fuse_steps_config,
    slot_steps,
)
from predictionio_tpu.data.prefetch import DevicePrefetcher


def _tree_equal(a, b):
    import jax

    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        assert np.array_equal(np.asarray(x), np.asarray(y)), \
            "fused training diverged bitwise from the per-step path"


def _loss_trace_equal(seq, fused):
    """The per-step loss TRACE pins to <= 1 ulp instead of bitwise: the
    model state (params/opt_state — the semantics) is strictly bitwise,
    but XLA CPU may fuse the scalar loss output of a rolled scan body
    differently from the standalone step program (e.g. the final
    reduction/divide feeding the stacked ys buffer), which lands the
    scalar 1 ulp off on data-dependent rounding boundaries.  Verified
    empirically: the slot that differs moves with the data, while the
    gradient path (and thus the state) stays bitwise-identical."""
    a = np.asarray(seq, np.float32).view(np.int32).astype(np.int64)
    b = np.asarray(fused, np.float32).view(np.int32).astype(np.int64)
    assert a.shape == b.shape
    assert np.max(np.abs(a - b)) <= 1, \
        f"loss trace differs by more than 1 ulp: {seq} vs {fused}"


def _tt_cfg(**kw):
    from predictionio_tpu.models import two_tower as tt

    kw.setdefault("batch_size", 32)
    kw.setdefault("epochs", 2)
    return tt.TwoTowerConfig(n_users=24, n_items=12, embed_dim=8,
                             hidden_dims=(16,), out_dim=8, seed=5, **kw)


def _tt_data(n=200, seed=7):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 24, n), rng.integers(0, 12, n)


def _dlrm_cfg(**kw):
    from predictionio_tpu.models import dlrm

    kw.setdefault("batch_size", 16)
    kw.setdefault("epochs", 2)
    return dlrm.DLRMConfig(vocab_sizes=(50, 30), n_dense=3, embed_dim=8,
                           bottom_mlp=(16, 8), top_mlp=(16, 8), seed=3,
                           **kw)


def _dlrm_data(n=150, seed=11):
    rng = np.random.default_rng(seed)
    cfg = _dlrm_cfg()
    dense = rng.standard_normal((n, 3)).astype(np.float32)
    cat = np.stack([rng.integers(0, v, n) for v in cfg.vocab_sizes], axis=1)
    labels = (rng.random(n) < 0.4).astype(np.float32)
    return dense, cat, labels


# -- bitwise equality: K sequential steps == one fused scan ------------------

class TestBitwiseEquality:
    def test_two_tower_fused_impl_matches_sequential(self):
        import jax.numpy as jnp

        from predictionio_tpu.models import two_tower as tt

        cfg = _tt_cfg(epochs=1)
        rng = np.random.default_rng(0)
        K, bs = 4, cfg.batch_size
        u = rng.integers(0, 24, (K, bs)).astype(np.int32)
        i = rng.integers(0, 12, (K, bs)).astype(np.int32)
        w = np.ones((K, bs), np.float32)

        seq = tt.init_state(cfg)
        losses_seq = []
        for k in range(K):
            seq, loss = tt.train_step(seq, jnp.asarray(u[k]),
                                      jnp.asarray(i[k]), jnp.asarray(w[k]),
                                      cfg)
            losses_seq.append(float(loss))

        fused, losses = tt.train_steps_fused(
            tt.init_state(cfg), jnp.asarray(u), jnp.asarray(i),
            jnp.asarray(w), cfg)
        _tree_equal(seq.params, fused.params)
        _tree_equal(seq.opt_state, fused.opt_state)
        assert int(seq.step) == int(fused.step) == K
        _loss_trace_equal(losses_seq, np.asarray(losses))

    def test_dlrm_fused_impl_matches_sequential(self):
        import jax.numpy as jnp

        from predictionio_tpu.models import dlrm

        cfg = _dlrm_cfg(epochs=1)
        rng = np.random.default_rng(1)
        K, bs = 4, cfg.batch_size
        d = rng.standard_normal((K, bs, 3)).astype(np.float32)
        c = np.stack([rng.integers(0, v, (K, bs))
                      for v in cfg.vocab_sizes], axis=2)
        cg = (c.astype(np.int64) + cfg.offsets[None, None, :]).astype(
            np.int32)
        y = (rng.random((K, bs)) < 0.4).astype(np.float32)
        w = np.ones((K, bs), np.float32)

        seq = dlrm.init_state(cfg)
        losses_seq = []
        for k in range(K):
            seq, loss = dlrm.train_step(
                seq, jnp.asarray(d[k]), jnp.asarray(cg[k]),
                jnp.asarray(y[k]), jnp.asarray(w[k]), cfg)
            losses_seq.append(float(loss))

        fused, losses = dlrm.train_steps_fused(
            dlrm.init_state(cfg), jnp.asarray(d), jnp.asarray(cg),
            jnp.asarray(y), jnp.asarray(w), cfg)
        _tree_equal(seq.params, fused.params)
        _tree_equal(seq.opt_state, fused.opt_state)
        _loss_trace_equal(losses_seq, np.asarray(losses))

    def test_two_tower_train_k4_equals_k1(self):
        from predictionio_tpu.models import two_tower as tt

        users, items = _tt_data()
        cfg = _tt_cfg()
        a = tt.train(users, items, cfg, data_source="numpy", fuse_steps=1)
        b = tt.train(users, items, cfg, data_source="numpy", fuse_steps=4)
        _tree_equal(a.params, b.params)
        _tree_equal(a.opt_state, b.opt_state)
        assert int(a.step) == int(b.step)

    def test_dlrm_train_k4_equals_k1(self):
        from predictionio_tpu.models import dlrm

        dense, cat, labels = _dlrm_data()
        cfg = _dlrm_cfg()
        a = dlrm.train(dense, cat, labels, cfg, data_source="numpy",
                       fuse_steps=1)
        b = dlrm.train(dense, cat, labels, cfg, data_source="numpy",
                       fuse_steps=4)
        _tree_equal(a.params, b.params)
        _tree_equal(a.opt_state, b.opt_state)
        assert int(a.step) == int(b.step)


# -- superbatch staging (prefetcher) -----------------------------------------

def _batches(n, size=4):
    return [(np.full(size, k, np.int64),) for k in range(1, n + 1)]


def _identity(x):
    return x


class TestSuperbatchStaging:
    def test_stacks_k_batches_with_leading_axis(self):
        with DevicePrefetcher(iter(_batches(8)), _identity,
                              put_fn=_identity, fuse_steps=4) as pf:
            got = list(pf)
        assert [(b.step, b.steps, b.k) for b in got] == [(4, 4, 4),
                                                         (8, 4, 4)]
        assert got[0].args[0].shape == (4, 4)
        assert np.array_equal(got[0].args[0][:, 0], [1, 2, 3, 4])
        assert got[0].examples == 16

    def test_fused_put_fn_receives_the_superbatch(self):
        seen = {"fused": 0, "single": 0}

        def put(arrays):
            seen["single"] += 1
            return arrays

        def fused_put(arrays):
            seen["fused"] += 1
            return arrays

        with DevicePrefetcher(iter(_batches(9)), _identity, put_fn=put,
                              fused_put_fn=fused_put, fuse_steps=4) as pf:
            got = list(pf)
        # 2 fused windows + 1 tail batch at the base shape
        assert seen == {"fused": 2, "single": 1}
        assert [(b.steps, b.k) for b in got] == [(4, 4), (4, 4), (1, 1)]

    def test_batch_scale_concatenates_per_slot(self):
        with DevicePrefetcher(iter(_batches(8)), _identity,
                              put_fn=_identity, fuse_steps=2,
                              batch_scale=2) as pf:
            got = list(pf)
        # 8 raw batches = 2 windows of (2 slots x 2 concatenated batches)
        assert [(b.step, b.steps, b.k) for b in got] == [(4, 4, 2),
                                                         (8, 4, 2)]
        b = got[0]
        assert b.args[0].shape == (2, 8)
        assert np.array_equal(b.args[0][0], [1, 1, 1, 1, 2, 2, 2, 2])
        assert np.array_equal(b.args[0][1], [3, 3, 3, 3, 4, 4, 4, 4])

    def test_mid_window_resume_replays_remainder_unfused(self):
        # skip=5 with K=4: steps 6,7,8 replay at the base shape so the
        # next window starts on the absolute boundary (9..12).
        with DevicePrefetcher(iter(_batches(12)), _identity,
                              put_fn=_identity, fuse_steps=4,
                              skip_steps=5) as pf:
            got = list(pf)
        assert [(b.step, b.steps, b.k) for b in got] == [
            (6, 1, 1), (7, 1, 1), (8, 1, 1), (12, 4, 4)]

    def test_tail_flush_emits_base_shapes(self):
        with DevicePrefetcher(iter(_batches(6)), _identity,
                              put_fn=_identity, fuse_steps=4) as pf:
            got = list(pf)
        assert [(b.step, b.steps, b.k) for b in got] == [
            (4, 4, 4), (5, 1, 1), (6, 1, 1)]

    def test_live_plan_retarget_applies_at_next_window(self):
        plan = FusionPlan(1)
        out = []
        with DevicePrefetcher(iter(_batches(12)), _identity,
                              put_fn=_identity, fuse_plan=plan,
                              depth=1) as pf:
            for b in pf:
                out.append((b.step, b.steps, b.k))
                if b.step == 2:
                    plan.set(fuse_steps=4)
        # the retarget lands once already-staged singles drain: at least
        # one fused window appears, and every raw batch is consumed
        # exactly once in order
        assert any(k == 4 for (_, _, k) in out)
        assert sum(steps for (_, steps, _) in out) == 12
        assert out[-1][0] == 12


# -- fusion-boundary bookkeeping ---------------------------------------------

class TestBoundaryHelpers:
    def test_crossed_save_point_reduces_to_modulo_for_k1(self):
        for step in range(1, 20):
            assert crossed_save_point(step, 1, 5) == (step % 5 == 0)

    def test_crossed_save_point_fused_window(self):
        assert crossed_save_point(8, 4, 6)        # 5..8 crosses 6
        assert not crossed_save_point(4, 4, 6)    # 1..4 crosses nothing
        assert crossed_save_point(12, 4, 6)       # 9..12 lands ON 12
        assert not crossed_save_point(16, 4, 6)   # 13..16 crosses nothing
        assert not crossed_save_point(8, 4, 0)    # disabled cadence

    def test_slot_steps(self):
        class B:
            step, steps, k = 12, 4, 4

        assert slot_steps(B) == [9, 10, 11, 12]

        class C:
            step, steps, k = 16, 8, 2  # batch_scale 4

        assert slot_steps(C) == [12, 16]

    def test_fuse_steps_config(self, monkeypatch):
        monkeypatch.delenv("PIO_FUSE_STEPS", raising=False)
        assert fuse_steps_config() == (1, False)
        monkeypatch.setenv("PIO_FUSE_STEPS", "8")
        assert fuse_steps_config() == (8, False)
        monkeypatch.setenv("PIO_FUSE_STEPS", "auto")
        assert fuse_steps_config() == (1, True)
        monkeypatch.setenv("PIO_FUSE_STEPS", "junk")
        assert fuse_steps_config() == (1, False)
        # explicit value overrides the environment
        assert fuse_steps_config(4) == (4, False)
        assert fuse_steps_config("auto") == (1, True)

    def test_batch_autoscale_env(self, monkeypatch):
        monkeypatch.delenv("PIO_BATCH_AUTOSCALE", raising=False)
        assert not batch_autoscale_enabled()
        monkeypatch.setenv("PIO_BATCH_AUTOSCALE", "on")
        assert batch_autoscale_enabled()


# -- divergence on the per-step loss vector ----------------------------------

class TestLossVectorCheck:
    def test_nan_slot_attributes_the_right_step(self):
        from predictionio_tpu.resilience.supervision import (
            DivergenceGuard,
            RollbackRequested,
        )

        guard = DivergenceGuard("toy", max_rollbacks=1)
        guard.check_vector([1.0, 2.0, 3.0, 4.0], [5, 6, 7, 8])  # clean
        with pytest.raises(RollbackRequested) as e:
            guard.check_vector([1.0, float("nan"), 3.0, float("nan")],
                               [5, 6, 7, 8])
        assert e.value.step == 6  # FIRST bad slot names the step

    def test_scalar_loss_still_works(self):
        from predictionio_tpu.resilience.supervision import (
            DivergenceGuard,
            TrainDiverged,
        )

        guard = DivergenceGuard("toy", max_rollbacks=0)
        guard.check_vector(np.float32(1.5), [3])
        with pytest.raises(TrainDiverged):
            guard.check_vector(np.float32("nan"), [3])


class TestWatchdogScale:
    def test_deadline_scales_with_fused_steps(self):
        from predictionio_tpu.resilience.supervision import StepWatchdog

        t = [0.0]
        fired = []
        wd = StepWatchdog("toy", timeout_s=10.0, clock=lambda: t[0],
                          abort_fn=lambda: fired.append(True),
                          poll_interval_s=0)
        wd.arm(1, scale=4)  # 4 fused steps -> 40 s budget
        t[0] = 35.0
        assert not wd.poll() and not fired
        t[0] = 41.0
        assert wd.poll() and fired


# -- end-to-end: NaN at slot k of a fused window, mid-window resume ----------

class TestFusedSupervision:
    def test_nan_at_slot_k_rolls_back_to_finite_checkpoint(
            self, tmp_path, monkeypatch):
        import jax
        import jax.numpy as jnp

        from predictionio_tpu.models import two_tower as tt

        users, items = _tt_data()
        cfg = _tt_cfg()
        clean = tt.train(users, items, cfg, data_source="numpy",
                         fuse_steps=1)

        real_fused = tt.train_steps_fused
        counter = {"n": 0, "injected": False}

        def nan_at_slot(state, u, i, w, c):
            s2, losses = real_fused(state, u, i, w, c)
            counter["n"] += 1
            if counter["n"] == 2 and not counter["injected"]:
                counter["injected"] = True
                poisoned = jax.tree.map(lambda x: x * jnp.nan, s2.params)
                losses = losses.at[2].set(jnp.nan)  # NaN at slot 3 of K
                return tt.TwoTowerState(poisoned, s2.opt_state,
                                        s2.step), losses
            return s2, losses

        monkeypatch.setattr(tt, "train_steps_fused", nan_at_slot)
        out = tt.train(users, items, cfg, checkpoint_dir=tmp_path / "ck",
                       save_every=4, data_source="numpy", fuse_steps=4)
        # rolled back to a finite boundary checkpoint, replayed, and the
        # result matches the clean unfused run bitwise
        assert np.isfinite(np.asarray(out.params["user_embed"])).all()
        _tree_equal(clean.params, out.params)

    def test_preempted_k1_run_resumes_fused_bitwise(self, monkeypatch,
                                                    tmp_path):
        from predictionio_tpu.models import two_tower as tt
        from predictionio_tpu.resilience import supervision

        users, items = _tt_data()
        cfg = _tt_cfg()
        clean = tt.train(users, items, cfg, data_source="numpy",
                         fuse_steps=1)

        real_step = tt.train_step
        calls = {"n": 0}

        def preempt_at_5(state, u, i, w, c):
            out = real_step(state, u, i, w, c)
            calls["n"] += 1
            if calls["n"] == 5:
                supervision.request_preemption()
            return out

        monkeypatch.setattr(tt, "train_step", preempt_at_5)
        supervision.clear_preemption()
        try:
            with pytest.raises(supervision.TrainPreempted):
                tt.train(users, items, cfg,
                         checkpoint_dir=tmp_path / "ck", save_every=1,
                         data_source="numpy", fuse_steps=1)
        finally:
            supervision.clear_preemption()
            monkeypatch.setattr(tt, "train_step", real_step)

        # Resume the K=1-checkpointed run (stopped at step 5 — mid-window
        # for K=4): the prefetcher replays 6..8 at the base shape, then
        # dispatches aligned fused windows; the result is bitwise-equal
        # to the uninterrupted unfused run.
        out = tt.train(users, items, cfg, checkpoint_dir=tmp_path / "ck",
                       save_every=1, data_source="numpy", fuse_steps=4)
        _tree_equal(clean.params, out.params)
        _tree_equal(clean.opt_state, out.opt_state)


# -- the autotuner -----------------------------------------------------------

class _ScriptedSampler:
    """headroom_exceeded() pops scripted verdicts (False once empty)."""

    def __init__(self, verdicts):
        self.verdicts = list(verdicts)

    def headroom_exceeded(self):
        return self.verdicts.pop(0) if self.verdicts else False


class TestFusionAutotuner:
    def _tuner(self, plan, verdicts, **kw):
        from predictionio_tpu.obs.metrics import MetricsRegistry

        kw.setdefault("round_windows", 1)
        return FusionAutotuner("toy", plan,
                               sampler=_ScriptedSampler(verdicts),
                               registry=MetricsRegistry(), **kw)

    def test_grows_until_guardrail_then_backs_off_one_notch_and_pins(self):
        plan = FusionPlan(1)
        tuner = self._tuner(plan, [False, False, True],
                            max_fuse_steps=32)
        tuner.on_window()
        assert plan.get() == (2, 1)
        tuner.on_window()
        assert plan.get() == (4, 1)
        tuner.on_window()  # guardrail -> back off to 2 and pin
        assert plan.get() == (2, 1)
        assert tuner.pinned
        tuner.on_window()
        assert plan.get() == (2, 1)  # pinned: no further probes

    def test_caps_at_max_without_pushback(self):
        plan = FusionPlan(1)
        tuner = self._tuner(plan, [], max_fuse_steps=4, batch_scale=False)
        for _ in range(5):
            tuner.on_window()
        assert plan.get() == (4, 1)
        assert tuner.pinned

    def test_batch_scale_grows_after_fuse_cap_when_enabled(self):
        plan = FusionPlan(1)
        tuner = self._tuner(plan, [], max_fuse_steps=2, batch_scale=True,
                            max_batch_scale=4)
        tuner.on_window()
        assert plan.get() == (2, 1)
        tuner.on_window()
        assert plan.get() == (2, 2)
        tuner.on_window()
        assert plan.get() == (2, 4)
        tuner.on_window()
        assert plan.get() == (2, 4) and tuner.pinned

    def test_backoff_unwinds_batch_scale_first(self):
        plan = FusionPlan(4, 2)
        tuner = self._tuner(plan, [True], max_fuse_steps=4,
                            batch_scale=True)
        tuner.on_window()
        assert plan.get() == (4, 1)  # the last-grown dimension backs off
        assert tuner.pinned

    def test_round_cadence(self):
        plan = FusionPlan(1)
        tuner = self._tuner(plan, [False, False], round_windows=3)
        tuner.on_window()
        tuner.on_window()
        assert plan.get() == (1, 1)  # mid-round: no decision yet
        tuner.on_window()
        assert plan.get() == (2, 1)


# -- probe / timeline steps plumbing ----------------------------------------

def test_probe_attributes_dispatch_wall_to_k_steps():
    from predictionio_tpu.obs.metrics import MetricsRegistry
    from predictionio_tpu.obs.pipeline import PipelineProbe
    from predictionio_tpu.obs.runtime import StepTimeline

    reg = MetricsRegistry()
    tl = StepTimeline(capacity=64)
    probe = PipelineProbe("toy", registry=reg, timeline=tl)
    for batch in probe.iter_host(iter([1, 2])):
        probe.sync()
        probe.dispatched(np.zeros(2), examples=8, steps=4)
    probe.finish()
    s = tl.summary("toy")
    assert s["dispatches"] == 2
    assert s["steps"] == 8
    assert s["fuse_steps"] == 4.0
    assert reg.get("pio_train_steps_total").value(model="toy") == 8
