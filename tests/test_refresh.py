"""Online learning (ISSUE 10): event-delta warm-start refresh, serve-time
ALS fold-in, and canaried continuous promotion.

Acceptance spine: ingest events → follow-mode refresh → the warm-started
generation serves measurably different (fresher) results than the prior
generation, promotion rides the staged-reload canary gate, an injected
divergent refresh is rejected/rolled back with the old generation still
serving, warm-start from the serialized carry is bitwise-equal to
continued training on CPU, and an ALS fold-in user receives
non-cold-start recommendations without a retrain.
"""

import datetime as dt
import json
import threading
import time
from urllib.request import Request, urlopen

import numpy as np
import pytest

from predictionio_tpu.controller import (
    EngineVariant,
    RuntimeContext,
    WarmStartFallback,
)
from predictionio_tpu.data.event import DataMap, Event
from predictionio_tpu.data.storage import App, get_storage
from predictionio_tpu.refresh import (
    RefreshConfig,
    WarmStartContext,
    data_watermark,
    staleness_s,
)
from predictionio_tpu.refresh.daemon import (
    HttpPromoter,
    PromotionRejected,
    RefreshDaemon,
)
from predictionio_tpu.workflow.core_workflow import load_models, run_train

UTC = dt.timezone.utc


# -- engines ---------------------------------------------------------------

TT_VARIANT = {
    "id": "default",
    "engineFactory": "predictionio_tpu.templates.twotower:engine",
    "datasource": {"params": {"appName": "app"}},
    "algorithms": [{"name": "twotower",
                    "params": {"embedDim": 8, "hiddenDims": [16],
                               "outDim": 8, "epochs": 2, "batchSize": 32,
                               "seed": 1}}],
}

ALS_VARIANT = {
    "id": "default",
    "engineFactory": "predictionio_tpu.templates.recommendation:engine",
    "datasource": {"params": {"appName": "app"}},
    "algorithms": [{"name": "als",
                    "params": {"rank": 8, "numIterations": 6,
                               "lambda_": 0.01, "seed": 3}}],
}


@pytest.fixture()
def ctx(pio_home):
    return RuntimeContext.create(storage=get_storage())


def _mk_app(ctx, name="app"):
    storage = ctx.storage
    app_id = storage.get_apps().insert(App(id=None, name=name))
    storage.get_events().init(app_id)
    return app_id


def _view(u, i, when=None):
    kw = {"event_time": when} if when is not None else {}
    return Event(event="view", entity_type="user", entity_id=f"u{u}",
                 target_entity_type="item", target_entity_id=f"i{i}", **kw)


def _rate(u, i, rating, when=None):
    kw = {"event_time": when} if when is not None else {}
    return Event(event="rate", entity_type="user", entity_id=f"u{u}",
                 target_entity_type="item", target_entity_id=f"i{i}",
                 properties=DataMap({"rating": float(rating)}), **kw)


def _seed_clique_views(ctx, app_id, n_users=10, n_items=6):
    evs = [_view(u, i) for u in range(n_users) for i in range(n_items)
           if i % 2 == u % 2]
    ctx.storage.get_events().insert_batch(evs, app_id)
    return len(evs)


def _seed_clique_rates(ctx, app_id, n_users=12, n_items=8, seed=0):
    rng = np.random.default_rng(seed)
    evs = [_rate(u, i, 3 + 2 * rng.random())
           for u in range(n_users) for i in range(n_items)
           if i % 2 == u % 2]
    ctx.storage.get_events().insert_batch(evs, app_id)
    return len(evs)


def _tt():
    from predictionio_tpu.templates.twotower import engine

    return engine(), EngineVariant.from_dict(TT_VARIANT)


def _als():
    from predictionio_tpu.templates.recommendation import engine

    return engine(), EngineVariant.from_dict(ALS_VARIANT)


def _warm_ctx(ctx, eng, variant, instance, **kw):
    return WarmStartContext(
        instance=instance,
        models=load_models(eng, instance, ctx),
        start_time=data_watermark(instance),
        **kw)


# ==========================================================================
# Watermarks + windowed reads
# ==========================================================================

class TestWatermarkWindows:
    def test_full_train_records_watermark(self, ctx):
        app_id = _mk_app(ctx)
        _seed_clique_rates(ctx, app_id)
        eng, variant = _als()
        before = dt.datetime.now(UTC)
        iid = run_train(eng, variant, ctx)
        inst = ctx.storage.get_engine_instances().get(iid)
        assert inst.env["refreshMode"] == "full"
        wm = data_watermark(inst)
        assert wm is not None
        assert before <= wm <= dt.datetime.now(UTC)

    def test_until_bound_excludes_future_events(self, ctx):
        """An event stamped past the watermark belongs to the NEXT
        generation — the full read is until-bounded too."""
        app_id = _mk_app(ctx)
        _seed_clique_views(ctx, app_id)
        ctx.storage.get_events().insert(
            _view(0, 99, when=dt.datetime.now(UTC) + dt.timedelta(hours=1)),
            app_id)
        eng, variant = _tt()
        iid = run_train(eng, variant, ctx)
        w = load_models(eng, ctx.storage.get_engine_instances().get(iid),
                        ctx)[0]
        assert "i99" not in w.item_index

    def test_windows_chain_without_gap_or_overlap(self, ctx):
        """gen1 full + gen2 warm cover every event exactly once: the
        warm generation's example count equals the TOTAL corpus."""
        app_id = _mk_app(ctx)
        n1 = _seed_clique_views(ctx, app_id)
        eng, variant = _tt()
        iid1 = run_train(eng, variant, ctx)
        inst1 = ctx.storage.get_engine_instances().get(iid1)
        # delta: stamped between the two watermarks (ingest wall clock)
        delta = [_view(0, 9), _view(2, 9), _view(99, 9), _view(99, 0)]
        ctx.storage.get_events().insert_batch(delta, app_id)
        warm = _warm_ctx(ctx, eng, variant, inst1, eval_tolerance=10.0)
        iid2 = run_train(eng, variant, ctx, warm_from=warm)
        inst2 = ctx.storage.get_engine_instances().get(iid2)
        assert inst2.env["refreshMode"] == "warm"
        assert inst2.env["warmStartFrom"] == iid1
        w2 = load_models(eng, inst2, ctx)[0]
        assert w2.n_examples == n1 + len(delta)
        # fresher: entities first seen in the delta are servable now
        assert "u99" in w2.user_index and "i9" in w2.item_index

    def test_windowed_event_store_clamps_explicit_bounds(self, ctx):
        from predictionio_tpu.data.store import WindowedEventStore

        app_id = _mk_app(ctx)
        t0 = dt.datetime(2026, 1, 1, tzinfo=UTC)
        ctx.storage.get_events().insert_batch(
            [_view(1, 1, when=t0),
             _view(1, 2, when=t0 + dt.timedelta(days=1)),
             _view(1, 3, when=t0 + dt.timedelta(days=2))], app_id)
        win = WindowedEventStore(ctx.storage,
                                 t0 + dt.timedelta(hours=12),
                                 t0 + dt.timedelta(days=1, hours=12))
        # window applies when the caller passes no bounds
        assert [e.target_entity_id for e in win.find("app")] == ["i2"]
        # a caller bound OUTSIDE the window is clamped to it
        got = list(win.find("app", start_time=t0 - dt.timedelta(days=9),
                            until_time=t0 + dt.timedelta(days=9)))
        assert [e.target_entity_id for e in got] == ["i2"]
        # a NARROWER caller bound inside the window is kept
        got = list(win.find("app",
                            until_time=t0 + dt.timedelta(hours=13)))
        assert got == []
        assert win.find_columnar("app").num_rows == 1

    def test_windowed_aggregate_properties_is_cumulative(self, ctx):
        """$set/$unset state accumulates from t=0: a delta-scoped read
        must still see properties written BEFORE the window (only the
        until bound applies) — otherwise a warm run's datasource sees
        phantom-empty entities."""
        from predictionio_tpu.data.store import WindowedEventStore

        app_id = _mk_app(ctx)
        t0 = dt.datetime(2026, 1, 1, tzinfo=UTC)
        ctx.storage.get_events().insert_batch([
            Event(event="$set", entity_type="item", entity_id="i1",
                  properties=DataMap({"color": "red"}), event_time=t0),
            Event(event="$set", entity_type="item", entity_id="i2",
                  properties=DataMap({"color": "blue"}),
                  event_time=t0 + dt.timedelta(days=2)),
        ], app_id)
        win = WindowedEventStore(ctx.storage,
                                 t0 + dt.timedelta(days=1),
                                 t0 + dt.timedelta(days=3))
        props = win.aggregate_properties("app", "item")
        assert set(props) == {"i1", "i2"}, \
            "pre-window $set state must stay visible"
        # the until bound still applies
        early = WindowedEventStore(ctx.storage, t0 + dt.timedelta(days=1),
                                   t0 + dt.timedelta(days=1, hours=1))
        assert set(early.aggregate_properties("app", "item")) == {"i1"}


# ==========================================================================
# Warm-start bitwise + state growth
# ==========================================================================

class TestWarmStartState:
    def _data(self, rng, n, n_users=20, n_items=12):
        return (rng.integers(0, n_users, n).astype(np.int64),
                rng.integers(0, n_items, n).astype(np.int64))

    def test_host_roundtrip_continuation_is_bitwise(self, pio_home):
        """Acceptance pin: continuing training from the SERIALIZED carry
        (host-numpy snapshot, what the wrapper pickles) is bitwise what
        continuing in-process would produce — the checkpoint loses
        nothing."""
        from predictionio_tpu.models import two_tower as tt

        cfg = tt.TwoTowerConfig(n_users=20, n_items=12, embed_dim=8,
                                hidden_dims=(16,), out_dim=8,
                                batch_size=16, epochs=1, seed=7)
        rng = np.random.default_rng(0)
        u1, i1 = self._data(rng, 96)
        u2, i2 = self._data(rng, 48)
        base = tt.train(u1, i1, cfg)
        snap = tt.state_to_host(base)
        # in-process continuation
        a = tt.train(u2, i2, cfg, warm_state=tt.state_from_host(
            tt.state_to_host(base)))
        # continuation from the serialized snapshot (fresh buffers)
        b = tt.train(u2, i2, cfg, warm_state=tt.state_from_host(snap))
        import jax

        for la, lb in zip(jax.tree.leaves(a.params),
                          jax.tree.leaves(b.params)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
        # 96/16 = 6 base steps + 48/16 = 3 continuation steps
        assert int(a.step) == int(b.step) == int(base.step) + 3 == 9

    def test_grow_state_preserves_rows_and_moments(self, pio_home):
        import dataclasses as dc

        import jax

        from predictionio_tpu.models import two_tower as tt

        cfg = tt.TwoTowerConfig(n_users=10, n_items=6, embed_dim=8,
                                hidden_dims=(16,), out_dim=8,
                                batch_size=16, epochs=1, seed=7)
        rng = np.random.default_rng(1)
        u, i = self._data(rng, 64, 10, 6)
        st = tt.train(u, i, cfg)
        grown_cfg = dc.replace(cfg, n_users=13, n_items=7)
        g = tt.grow_state(tt.state_from_host(tt.state_to_host(st)),
                          grown_cfg)
        assert g.params["user_embed"].shape == (13, 8)
        assert g.params["item_embed"].shape == (7, 8)
        np.testing.assert_array_equal(
            np.asarray(g.params["user_embed"][:10]),
            np.asarray(st.params["user_embed"]))
        # optimizer moments: old rows carried, new rows zero, step kept
        mus_old = [x for x in jax.tree.leaves(st.opt_state)
                   if getattr(x, "shape", ()) == (10, 8)]
        mus_new = [x for x in jax.tree.leaves(g.opt_state)
                   if getattr(x, "shape", ()) == (13, 8)]
        assert mus_old and len(mus_old) == len(mus_new)
        for old, new in zip(mus_old, mus_new):
            np.testing.assert_array_equal(np.asarray(new[:10]),
                                          np.asarray(old))
            assert not np.asarray(new[10:]).any()
        assert int(g.step) == int(st.step)
        # growing to the SAME sizes is the identity
        same = tt.grow_state(tt.state_from_host(tt.state_to_host(st)), cfg)
        np.testing.assert_array_equal(np.asarray(same.params["user_embed"]),
                                      np.asarray(st.params["user_embed"]))


# ==========================================================================
# Fallback gates
# ==========================================================================

class TestWarmFallbacks:
    def _gen1(self, ctx):
        app_id = _mk_app(ctx)
        _seed_clique_views(ctx, app_id)
        eng, variant = _tt()
        iid = run_train(eng, variant, ctx)
        inst = ctx.storage.get_engine_instances().get(iid)
        ctx.storage.get_events().insert_batch(
            [_view(0, 9), _view(99, 9)], app_id)
        return app_id, eng, variant, inst

    @staticmethod
    def _walk_spans(doc):
        stack = [doc]
        while stack:
            d = stack.pop()
            yield d
            stack.extend(d.get("spans", []))

    def _assert_fallback(self, ctx, eng, variant, warm, reason_fragment):
        iid = run_train(eng, variant, ctx, warm_from=warm)
        inst = ctx.storage.get_engine_instances().get(iid)
        assert inst.status == "COMPLETED"
        assert inst.env["refreshMode"] == "full_fallback"
        from predictionio_tpu.obs import get_recorder

        # the fallback annotation attaches inside the workflow.train
        # trace tree (publish_event child-span semantics)
        events = [s for doc in get_recorder().recent(50)
                  for s in self._walk_spans(doc)
                  if s["name"] == "refresh.warm_fallback"]
        assert events, "fallback must land a trace event"
        assert reason_fragment in events[-1]["attrs"]["reason"]
        return inst

    def _als_gen1(self, ctx):
        app_id = _mk_app(ctx)
        _seed_clique_rates(ctx, app_id)
        eng, variant = _als()
        iid1 = run_train(eng, variant, ctx)
        inst1 = ctx.storage.get_engine_instances().get(iid1)
        ctx.storage.get_events().insert(_rate(0, 1, 5.0), app_id)
        return app_id, eng, variant, inst1

    def test_als_rank_change_falls_back(self, ctx):
        app_id, eng, variant, inst1 = self._als_gen1(ctx)
        warm = _warm_ctx(ctx, eng, variant, inst1)
        v2 = json.loads(json.dumps(ALS_VARIANT))
        v2["algorithms"][0]["params"]["rank"] = 16
        inst2 = self._assert_fallback(ctx, eng, EngineVariant.from_dict(v2),
                                      warm, "config changed")
        # the fallback still covers the delta: it IS a fresh full corpus
        assert data_watermark(inst2) > data_watermark(inst1)

    def test_als_eval_regression_falls_back(self, ctx):
        # tolerance -1 → allowed regression threshold 0: the sweep's
        # residual on the delta sample reads as a regression — pins the
        # ALS eval gate path itself
        app_id, eng, variant, inst1 = self._als_gen1(ctx)
        warm = _warm_ctx(ctx, eng, variant, inst1, eval_tolerance=-1.0)
        self._assert_fallback(ctx, eng, variant, warm, "regressed")

    def test_als_unsized_carry_falls_back(self, ctx):
        """A pre-ISSUE-17 pickle has no n_examples — the fraction gate
        cannot be computed, so the carry declines instead of guessing."""
        app_id, eng, variant, inst1 = self._als_gen1(ctx)
        warm = _warm_ctx(ctx, eng, variant, inst1)
        warm.models[0].n_examples = 0
        self._assert_fallback(ctx, eng, variant, warm, "vs 0 trained")

    def test_oversized_delta_falls_back(self, ctx):
        app_id, eng, variant, inst = self._gen1(ctx)
        warm = _warm_ctx(ctx, eng, variant, inst, max_delta_fraction=0.0)
        self._assert_fallback(ctx, eng, variant, warm, "too large")

    def test_eval_regression_falls_back(self, ctx):
        app_id, eng, variant, inst = self._gen1(ctx)
        # a diverse delta (distinct items → nonzero in-batch loss), and
        # tolerance -1 → allowed regression threshold is 0: any positive
        # post-continuation loss reads as a regression — the gate path
        # itself is what this pins
        ctx.storage.get_events().insert_batch(
            [_view(u, i) for u, i in ((1, 0), (3, 2), (5, 4), (7, 1))],
            app_id)
        warm = _warm_ctx(ctx, eng, variant, inst, eval_tolerance=-1.0)
        self._assert_fallback(ctx, eng, variant, warm, "regressed")

    def test_config_change_falls_back(self, ctx):
        app_id, eng, variant, inst = self._gen1(ctx)
        warm = _warm_ctx(ctx, eng, variant, inst)
        v2 = json.loads(json.dumps(TT_VARIANT))
        v2["algorithms"][0]["params"]["embedDim"] = 16
        self._assert_fallback(ctx, eng, EngineVariant.from_dict(v2), warm,
                              "config changed")

    def test_missing_carry_falls_back(self, ctx):
        app_id, eng, variant, inst = self._gen1(ctx)
        warm = _warm_ctx(ctx, eng, variant, inst)
        warm.models[0].train_state = None
        self._assert_fallback(ctx, eng, variant, warm, "no train state")

    def test_mixed_engine_is_all_or_nothing(self, ctx):
        """One algorithm declining aborts the WHOLE warm attempt — a
        generation is one consistent data window."""
        eng, variant = _tt()
        app_id = _mk_app(ctx)
        _seed_clique_views(ctx, app_id)
        iid = run_train(eng, variant, ctx)
        inst = ctx.storage.get_engine_instances().get(iid)
        ctx.storage.get_events().insert(_view(0, 1), app_id)
        warm = _warm_ctx(ctx, eng, variant, inst, eval_tolerance=10.0)

        class Declines:
            def warm_start(self, *a, **k):
                raise WarmStartFallback("nope")

        # engine.train with warm must propagate the fallback, not return
        # a half-warm model list
        params = eng.bind_engine_params(variant.raw)
        warm.models = [warm.models[0]]
        real = eng.make_algorithms

        def fake_algos(ep):
            return [Declines()]

        eng.make_algorithms = fake_algos
        try:
            with pytest.raises(WarmStartFallback):
                eng.train(RuntimeContext.create(storage=ctx.storage),
                          params, warm=warm)
        finally:
            eng.make_algorithms = real


# ==========================================================================
# ALS delta warm-start (ISSUE 17)
# ==========================================================================

class TestALSWarmStart:
    def _gen1(self, ctx):
        app_id = _mk_app(ctx)
        _seed_clique_rates(ctx, app_id)
        eng, variant = _als()
        iid = run_train(eng, variant, ctx)
        inst = ctx.storage.get_engine_instances().get(iid)
        return app_id, eng, variant, inst

    def test_warm_refresh_moves_only_delta_touched_rows(self, ctx):
        """Factor-init + reduced-sweep retrain end-to-end: the warm
        generation completes as ``warm``, the delta-touched user's factor
        row moves, every untouched row carries over bit-for-bit, and the
        new taste is immediately servable."""
        app_id, eng, variant, inst1 = self._gen1(ctx)
        models1 = load_models(eng, inst1, ctx)
        algo = eng.make_algorithms(eng.bind_engine_params(variant.raw))[0]
        # u0 (even clique) suddenly loves ODD items, hard
        ctx.storage.get_events().insert_batch(
            [_rate(0, 1, 5.0), _rate(0, 3, 5.0), _rate(0, 5, 5.0)], app_id)
        warm = _warm_ctx(ctx, eng, variant, inst1)
        iid2 = run_train(eng, variant, ctx, warm_from=warm)
        inst2 = ctx.storage.get_engine_instances().get(iid2)
        assert inst2.status == "COMPLETED"
        assert inst2.env["refreshMode"] == "warm"
        assert data_watermark(inst2) > data_watermark(inst1)
        w1, w2 = models1[0], load_models(eng, inst2, ctx)[0]
        uf1, if1 = w1.host_factors()
        uf2, if2 = w2.host_factors()
        u_rows = dict(w1.user_index.items())
        i_rows = dict(w1.item_index.items())
        moved_u = {u_rows["u0"]}
        moved_i = {i_rows[f"i{j}"] for j in (1, 3, 5)}
        for r in range(uf1.shape[0]):
            if r in moved_u:
                assert not np.array_equal(uf2[r], uf1[r])
            else:
                np.testing.assert_array_equal(uf2[r], uf1[r])
        for r in range(if1.shape[0]):
            if r not in moved_i:
                np.testing.assert_array_equal(if2[r], if1[r])
        assert w2.n_examples == w1.n_examples + 3
        # the new taste serves: an odd item reaches u0's top-3
        from predictionio_tpu.templates.recommendation import Query

        top = algo.predict(w2, Query(user="u0", num=3)).itemScores
        assert any(int(s.item[1:]) % 2 == 1 for s in top)

    def test_warm_refresh_grows_union_index_for_new_entities(self, ctx):
        """Delta-new user AND item get fresh appended rows; the new user
        is non-cold immediately after the warm refresh."""
        app_id, eng, variant, inst1 = self._gen1(ctx)
        ctx.storage.get_events().insert_batch(
            [_rate(99, 0, 5.0), _rate(99, 2, 5.0),
             _rate(99, 99, 4.0)], app_id)  # u99 and i99 are brand new
        warm = _warm_ctx(ctx, eng, variant, inst1)
        iid2 = run_train(eng, variant, ctx, warm_from=warm)
        inst2 = ctx.storage.get_engine_instances().get(iid2)
        assert inst2.env["refreshMode"] == "warm"
        w1 = load_models(eng, inst1, ctx)[0]
        w2 = load_models(eng, inst2, ctx)[0]
        assert "u99" in dict(w2.user_index.items())
        assert "i99" in dict(w2.item_index.items())
        # union-extend: previous ids keep their exact rows
        assert dict(w2.user_index.items())["u99"] == len(w1.user_index)
        for key, row in w1.item_index.items():
            assert dict(w2.item_index.items())[key] == row
        algo = eng.make_algorithms(eng.bind_engine_params(variant.raw))[0]
        from predictionio_tpu.templates.recommendation import Query

        res = algo.predict(w2, Query(user="u99", num=4))
        assert len(res.itemScores) == 4  # non-cold without a full retrain


# ==========================================================================
# ALS serve-time fold-in
# ==========================================================================

class TestFoldIn:
    def _trained(self, ctx):
        app_id = _mk_app(ctx)
        _seed_clique_rates(ctx, app_id)
        eng, variant = _als()
        iid = run_train(eng, variant, ctx)
        inst = ctx.storage.get_engine_instances().get(iid)
        models = load_models(eng, inst, ctx)  # post_load attaches events
        algo = eng.make_algorithms(eng.bind_engine_params(ALS_VARIANT))[0]
        return app_id, eng, variant, models[0], algo

    def test_fold_in_matches_training_solve(self, pio_home):
        """fold_in of a training user's OWN events against the final item
        factors lands close to that user's trained factor (the same
        normal equation the last user sweep solved)."""
        from predictionio_tpu.models import als as als_lib

        rng = np.random.default_rng(0)
        n_u, n_i, d = 30, 20, 400
        us = rng.integers(0, n_u, d)
        its = rng.integers(0, n_i, d)
        rs = rng.integers(1, 6, d).astype(np.float32)
        cfg = als_lib.ALSConfig(rank=8, iterations=12, reg=0.05, seed=1)
        model = als_lib.train_als(us, its, rs, n_u, n_i, cfg)
        itf = np.asarray(model.item_factors)
        uf = np.asarray(model.user_factors)
        sel = us == 3
        vec = als_lib.fold_in(itf, its[sel], rs[sel], reg=cfg.reg)
        cos = float(vec @ uf[3] /
                    (np.linalg.norm(vec) * np.linalg.norm(uf[3]) + 1e-12))
        assert cos > 0.98, cos

    def test_unseen_user_gets_non_cold_start_recs(self, ctx):
        from predictionio_tpu.obs import get_registry
        from predictionio_tpu.templates.recommendation import Query

        app_id, eng, variant, w, algo = self._trained(ctx)
        for i in (0, 2, 4):
            ctx.storage.get_events().insert(_rate("new", i, 5.0), app_id)
        # fold-in user replaces their cold-start empty answer
        res = algo.batch_predict(w, [(0, Query(user="unew", num=4))])
        scores = res[0][1].itemScores
        assert scores, "fold-in user must receive recommendations"
        even = sum(1 for s in scores if int(s.item[1:]) % 2 == 0)
        assert even >= 3, scores
        # repeat visitor rides the cache — no second solve
        algo.batch_predict(w, [(0, Query(user="unew", num=4))])
        c = get_registry().get("pio_fold_in_total")
        assert c.value(result="solved") == 1
        assert c.value(result="cached") >= 1

    def test_user_with_no_events_stays_cold(self, ctx):
        from predictionio_tpu.obs import get_registry
        from predictionio_tpu.templates.recommendation import Query

        app_id, eng, variant, w, algo = self._trained(ctx)
        res = algo.batch_predict(w, [(0, Query(user="ughost", num=4))])
        assert res[0][1].itemScores == []
        c = get_registry().get("pio_fold_in_total")
        assert c.value(result="no_events") == 1
        # the negative outcome is cached too: a repeat unknown-user query
        # must not pay a second event-store read on the serving path
        res = algo.batch_predict(w, [(0, Query(user="ughost", num=4))])
        assert res[0][1].itemScores == []
        assert c.value(result="no_events") == 1
        assert c.value(result="cached") >= 1

    def test_fold_in_off_switch(self, ctx, monkeypatch):
        from predictionio_tpu.templates.recommendation import Query

        app_id, eng, variant, w, algo = self._trained(ctx)
        ctx.storage.get_events().insert(_rate("new", 0, 5.0), app_id)
        monkeypatch.setenv("PIO_FOLD_IN", "off")
        res = algo.batch_predict(w, [(0, Query(user="unew", num=4))])
        assert res[0][1].itemScores == []

    def test_cache_is_bounded(self, ctx, monkeypatch):
        from predictionio_tpu.templates.recommendation import Query

        app_id, eng, variant, w, algo = self._trained(ctx)
        for uname in ("a", "b", "c"):
            ctx.storage.get_events().insert(_rate(uname, 0, 4.0), app_id)
        monkeypatch.setenv("PIO_FOLD_IN_CACHE", "2")
        for uname in ("a", "b", "c"):
            algo.batch_predict(w, [(0, Query(user=f"u{uname}", num=2))])
        assert len(w._fold_cache) == 2

    def test_fold_cache_does_not_survive_pickle(self, ctx):
        import pickle

        app_id, eng, variant, w, algo = self._trained(ctx)
        ctx.storage.get_events().insert(_rate("new", 0, 4.0), app_id)
        assert w.fold_in_user("unew") is not None
        clone = pickle.loads(pickle.dumps(w))
        assert len(clone._fold_cache) == 0
        assert getattr(clone, "_event_store", None) is None


# ==========================================================================
# Daemon + canaried promotion
# ==========================================================================

class _FakePromoter:
    canary_window_s = 1.0

    def __init__(self, verdict="promoted", ctx=None):
        self.promoted = []
        self.watched = 0
        self.verdict = verdict
        self.ctx = ctx

    def promote(self, instance_id):
        self.promoted.append(instance_id)
        return {"engineInstanceId": instance_id}

    def canary_watch(self):
        self.watched += 1
        return self.verdict

    def served_watermark(self):
        # mirrors a live server that loaded what promote() was given
        if self.ctx is None or not self.promoted:
            return None
        inst = self.ctx.storage.get_engine_instances().get(
            self.promoted[-1])
        return data_watermark(inst) if inst else None


class TestDaemon:
    def _daemon(self, ctx, eng, variant, **kw):
        return RefreshDaemon(eng, variant, ctx,
                             config=RefreshConfig(interval_s=0.01), **kw)

    def test_cycle_trains_promotes_and_publishes(self, ctx):
        from predictionio_tpu.obs import get_registry

        app_id = _mk_app(ctx)
        _seed_clique_rates(ctx, app_id)
        eng, variant = _als()
        promoter = _FakePromoter(ctx=ctx)
        d = self._daemon(ctx, eng, variant, promoter=promoter)
        out1 = d.run_once()
        assert out1["result"] == "full"          # no previous generation
        assert promoter.promoted == [out1["instance"]]
        ctx.storage.get_events().insert(_rate(0, 1, 5.0), app_id)
        out2 = d.run_once()
        assert out2["result"] == "warm"  # ALS continues the generation
        assert promoter.promoted[-1] == out2["instance"]
        assert promoter.watched == 2
        reg = get_registry()
        runs = reg.get("pio_refresh_runs_total")
        assert runs.value(result="full") == 1
        assert runs.value(result="warm") == 1
        promos = reg.get("pio_refresh_promotions_total")
        assert promos.value(result="promoted") == 2
        # staleness gauge: everything ingested before the watermark is
        # servable → 0
        assert reg.get("pio_refresh_staleness_s").value() == 0.0

    def test_failed_cycle_records_and_continues(self, ctx, monkeypatch):
        from predictionio_tpu.obs import get_registry

        app_id = _mk_app(ctx)
        eng, variant = _als()   # no events → the datasource raises
        promoter = _FakePromoter()
        d = self._daemon(ctx, eng, variant, promoter=promoter)
        out = d.run_once()
        assert out["result"] == "failed"
        assert promoter.promoted == []
        assert get_registry().get("pio_refresh_runs_total") \
            .value(result="failed") == 1

    def test_follow_paces_and_stops(self, ctx):
        app_id = _mk_app(ctx)
        _seed_clique_rates(ctx, app_id)
        eng, variant = _als()
        d = self._daemon(ctx, eng, variant)
        waits = []

        def fake_sleep(s):
            waits.append(s)
            if len(waits) >= 2:
                d.stop()

        cycles = d.follow(sleep=fake_sleep)
        # cycle, sleep, cycle, sleep(sets stop) → loop exits at the check
        assert cycles == 2 and len(waits) == 2
        assert all(w >= 0 for w in waits)

    def test_staleness_reports_served_not_trained_on_rollback(self, ctx):
        """A rejected/rolled-back promotion leaves the OLD watermark
        serving — the staleness gauge must report that gap, not the
        freshness of the instance nobody serves."""
        from predictionio_tpu.obs import get_registry

        app_id = _mk_app(ctx)
        _seed_clique_rates(ctx, app_id)
        eng, variant = _als()
        promoter = _FakePromoter(verdict="rolled_back", ctx=ctx)
        d = self._daemon(ctx, eng, variant, promoter=promoter)
        out1 = d.run_once()
        old_wm = data_watermark(
            ctx.storage.get_engine_instances().get(out1["instance"]))
        # pin the "server" to generation 1 regardless of later promotes
        promoter.served_watermark = lambda: old_wm
        ctx.storage.get_events().insert(_rate(0, 1, 5.0), app_id)
        out2 = d.run_once()
        assert out2["promotion"] == "rolled_back"
        s = get_registry().get("pio_refresh_staleness_s").value()
        assert s > 0.0, "gauge must show the served (old) generation's gap"

    def test_staleness_measures_unservable_ingest(self, ctx):
        app_id = _mk_app(ctx)
        _seed_clique_rates(ctx, app_id)
        eng, variant = _als()
        d = self._daemon(ctx, eng, variant)
        out = d.run_once()
        inst = ctx.storage.get_engine_instances().get(out["instance"])
        # events landing AFTER the promoted watermark are not servable
        late = dt.datetime.now(UTC) + dt.timedelta(seconds=0)
        ctx.storage.get_events().insert(_rate(0, 1, 5.0, when=late), app_id)
        d._publish_staleness(inst)
        from predictionio_tpu.obs import get_registry

        s = get_registry().get("pio_refresh_staleness_s").value()
        assert s > 0.0
        # unit helper semantics
        assert staleness_s(None, dt.datetime.now(UTC)) is None
        t = dt.datetime.now(UTC)
        assert staleness_s(t, t + dt.timedelta(seconds=5)) == 0.0
        assert staleness_s(t + dt.timedelta(seconds=5), t) == 5.0


def _http(base, method, path):
    req = Request(base + path, method=method)
    with urlopen(req, timeout=10) as resp:
        return resp.status, json.loads(resp.read() or b"{}")


class TestServerPromotionE2E:
    """The acceptance spine against a LIVE engine server over HTTP."""

    def _server(self, ctx, eng, variant):
        from predictionio_tpu.server import EngineServer

        srv = EngineServer(eng, variant, ctx.storage, host="127.0.0.1",
                           port=0)
        srv.start(block=False)
        return srv, f"http://127.0.0.1:{srv.port}"

    def test_warm_refresh_promotes_and_serves_fresher_results(self, ctx):
        """ingest → refresh → the warm generation, promoted through the
        canary gate, serves entities the old generation could not."""
        app_id = _mk_app(ctx)
        _seed_clique_views(ctx, app_id)
        eng, variant = _tt()
        run_train(eng, variant, ctx)
        srv, base = self._server(ctx, eng, variant)
        try:
            st, body = _http(base, "GET", "/")
            gen1 = body["modelGeneration"]
            wm1 = body["dataWatermark"]
            assert wm1 is not None
            # the not-yet-refreshed server cold-starts the new user
            st, body = _http_query(base, {"user": "u99", "num": 3})
            assert st == 200 and body["itemScores"] == []
            # ingest the delta: new user u99 + new item i9
            ctx.storage.get_events().insert_batch(
                [_view(0, 9), _view(2, 9), _view(99, 9), _view(99, 0)],
                app_id)
            cfg = RefreshConfig(interval_s=0.01, eval_tolerance=10.0,
                                canary_window_s=0.0)
            promoter = HttpPromoter(base, canary_window_s=0.0)
            d = RefreshDaemon(eng, variant, ctx, config=cfg,
                              promoter=promoter)
            out = d.run_once()
            assert out["result"] == "warm"
            assert out["promotion"] == "promoted"
            st, body = _http(base, "GET", "/")
            assert body["modelGeneration"] == gen1 + 1
            assert body["engineInstanceId"] == out["instance"]
            assert body["dataWatermark"] > wm1
            assert body["refreshMode"] == "warm"
            # fresher answers: the delta user now gets their delta item
            st, body = _http_query(base, {"user": "u99", "num": 3})
            assert st == 200
            items = [s["item"] for s in body["itemScores"]]
            assert "i9" in items
        finally:
            srv.stop()

    def test_divergent_refresh_is_rejected_old_generation_serves(
            self, ctx, monkeypatch):
        """Injected divergent refresh: the staged-reload gate rejects the
        NaN candidate (409) and the old generation keeps answering."""
        app_id = _mk_app(ctx)
        _seed_clique_rates(ctx, app_id)
        eng, variant = _als()
        run_train(eng, variant, ctx)
        srv, base = self._server(ctx, eng, variant)
        try:
            serving_before = srv._instance.id
            ctx.storage.get_events().insert(_rate(0, 1, 5.0), app_id)
            # poison the SERVER's candidate load: whatever the refresh
            # trained comes up non-finite — the validation stage must
            # catch it at the gate
            from predictionio_tpu.server import engine_server as es_mod

            real_load = es_mod.load_models

            def poisoned(engine, instance, c=None):
                models = real_load(engine, instance, c)
                uf = np.asarray(models[0].model.user_factors).copy()
                uf[0, 0] = np.nan
                models[0].model.user_factors = uf
                return models

            monkeypatch.setattr(es_mod, "load_models", poisoned)
            promoter = HttpPromoter(base, canary_window_s=0.0)
            d = RefreshDaemon(eng, variant, ctx,
                              config=RefreshConfig(interval_s=0.01),
                              promoter=promoter)
            out = d.run_once()
            assert out["promotion"] == "rejected"
            assert srv._instance.id == serving_before
            st, body = _http_query(base, {"user": "u1", "num": 2})
            assert st == 200 and body["itemScores"]
            from predictionio_tpu.obs import get_registry

            assert get_registry().get("pio_refresh_promotions_total") \
                .value(result="rejected") == 1
        finally:
            srv.stop()

    def test_slo_burn_in_canary_window_rolls_back(self, ctx, monkeypatch):
        """A promotion whose canary window sees the SLO burning is rolled
        back over the same gate — the previous generation serves again."""
        app_id = _mk_app(ctx)
        _seed_clique_rates(ctx, app_id)
        eng, variant = _als()
        run_train(eng, variant, ctx)
        srv, base = self._server(ctx, eng, variant)
        try:
            gen1_instance = srv._instance.id
            ctx.storage.get_events().insert(_rate(0, 1, 5.0), app_id)
            promoter = HttpPromoter(base, canary_window_s=5.0,
                                    canary_poll_s=0.01)
            monkeypatch.setattr(
                promoter, "slo_state",
                lambda: {"degraded": True, "burn": {}, "threshold": 14.4})
            d = RefreshDaemon(eng, variant, ctx,
                              config=RefreshConfig(interval_s=0.01),
                              promoter=promoter)
            out = d.run_once()
            assert out["promotion"] == "rolled_back"
            # the rollback restored the pre-promotion generation
            assert srv._instance.id == gen1_instance
            st, body = _http_query(base, {"user": "u1", "num": 2})
            assert st == 200 and body["itemScores"]
        finally:
            srv.stop()


def _http_query(base, q):
    req = Request(base + "/queries.json", data=json.dumps(q).encode(),
                  method="POST",
                  headers={"Content-Type": "application/json"})
    with urlopen(req, timeout=10) as resp:
        return resp.status, json.loads(resp.read() or b"{}")


# ==========================================================================
# Event-server ingest watermark gauge
# ==========================================================================

class TestIngestWatermarkGauge:
    def _server(self, pio_home):
        from predictionio_tpu.data.storage import AccessKey
        from predictionio_tpu.server import EventServer

        storage = get_storage()
        app_id = storage.get_apps().insert(App(id=None, name="gapp"))
        storage.get_events().init(app_id)
        key = storage.get_access_keys().insert(
            AccessKey(key="", app_id=app_id))
        srv = EventServer(storage=storage)
        return srv, storage, app_id, key

    def test_gauge_tracks_stored_event_time(self, pio_home):
        from predictionio_tpu.obs import get_registry

        srv, storage, app_id, key = self._server(pio_home)
        t = "2026-03-01T12:00:00Z"
        st, body = srv.handle(
            "POST", "/events.json", {"accessKey": [key]},
            json.dumps({"event": "view", "entityType": "user",
                        "entityId": "u1", "targetEntityType": "item",
                        "targetEntityId": "i1", "eventTime": t}).encode())
        assert st == 201
        g = get_registry().get("pio_events_latest_ts")
        want = dt.datetime(2026, 3, 1, 12, tzinfo=UTC).timestamp()
        assert g.value(app=str(app_id)) == pytest.approx(want)
        # an OLDER event must not move the watermark backwards
        st, _ = srv.handle(
            "POST", "/events.json", {"accessKey": [key]},
            json.dumps({"event": "view", "entityType": "user",
                        "entityId": "u1",
                        "eventTime": "2020-01-01T00:00:00Z"}).encode())
        assert st == 201
        assert g.value(app=str(app_id)) == pytest.approx(want)

    def test_gauge_seeds_from_store_on_restart(self, pio_home):
        """A fresh server process reports the STORE-wide watermark, not
        just its own ingest, as soon as an app is touched."""
        from predictionio_tpu.obs import get_registry
        from predictionio_tpu.server import EventServer

        srv, storage, app_id, key = self._server(pio_home)
        future = dt.datetime(2029, 6, 1, tzinfo=UTC)
        storage.get_events().insert(_view(1, 1, when=future), app_id)
        srv2 = EventServer(storage=storage)
        st, _ = srv2.handle(
            "POST", "/events.json", {"accessKey": [key]},
            json.dumps({"event": "view", "entityType": "user",
                        "entityId": "u1"}).encode())
        assert st == 201
        g = get_registry().get("pio_events_latest_ts")
        assert g.value(app=str(app_id)) == pytest.approx(future.timestamp())

    def test_restart_seed_covers_named_channels(self, pio_home):
        """The app-level gauge must not regress after a restart just
        because the newest event lives in a NAMED channel."""
        from predictionio_tpu.data.storage import Channel
        from predictionio_tpu.obs import get_registry
        from predictionio_tpu.server import EventServer

        srv, storage, app_id, key = self._server(pio_home)
        ch_id = storage.get_channels().insert(
            Channel(id=None, name="live", app_id=app_id))
        storage.get_events().init(app_id, ch_id)
        newest = dt.datetime(2029, 9, 1, tzinfo=UTC)
        storage.get_events().insert(_view(1, 1, when=newest), app_id,
                                    channel_id=ch_id)
        srv2 = EventServer(storage=storage)
        st, _ = srv2.handle(
            "POST", "/events.json", {"accessKey": [key]},
            json.dumps({"event": "view", "entityType": "user",
                        "entityId": "u1"}).encode())
        assert st == 201
        g = get_registry().get("pio_events_latest_ts")
        assert g.value(app=str(app_id)) == pytest.approx(newest.timestamp())

    def test_batch_ingest_advances_gauge(self, pio_home):
        from predictionio_tpu.obs import get_registry

        srv, storage, app_id, key = self._server(pio_home)
        batch = [{"event": "view", "entityType": "user", "entityId": "u1",
                  "eventTime": f"2026-04-0{d}T00:00:00Z"} for d in (1, 3, 2)]
        st, body = srv.handle("POST", "/batch/events.json",
                              {"accessKey": [key]},
                              json.dumps(batch).encode())
        assert st == 200 and all(r["status"] == 201 for r in body)
        g = get_registry().get("pio_events_latest_ts")
        want = dt.datetime(2026, 4, 3, tzinfo=UTC).timestamp()
        assert g.value(app=str(app_id)) == pytest.approx(want)

    def test_pio_status_prints_watermark(self, capsys):
        from predictionio_tpu.cli.main import _print_serving_snapshot

        lines = [
            'pio_events_latest_ts{app="7"} 1.7720640e+09',
            "pio_refresh_staleness_s 12.5",
            'pio_refresh_runs_total{result="warm"} 3',
        ]
        _print_serving_snapshot(lines)
        out = capsys.readouterr().out
        assert "events latest [app 7]" in out
        assert "refresh staleness: 12.5s" in out
        assert "warm=3" in out
