"""Config layering tests (reference: Storage env parsing + pio-env template)."""

from pathlib import Path

from predictionio_tpu.config import load_config


def test_defaults(pio_home):
    cfg = load_config()
    assert cfg.home == pio_home
    assert cfg.repositories["METADATA"].source == "SQLITE"
    assert cfg.repositories["EVENTDATA"].source == "SQLITE"
    assert cfg.repositories["MODELDATA"].source == "LOCALFS"
    assert cfg.source_for("metadata").type == "sqlite"
    assert cfg.source_for("MODELDATA").type == "localfs"
    assert Path(cfg.source_for("METADATA").path).is_relative_to(pio_home)


def test_env_overrides(pio_home, monkeypatch):
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE", "PARQUET")
    monkeypatch.setenv("PIO_STORAGE_SOURCES_PARQUET_PATH", "/data/ev")
    cfg = load_config()
    src = cfg.source_for("EVENTDATA")
    assert src.type == "parquetlog"
    assert src.path == "/data/ev"


def test_toml_layer(pio_home, monkeypatch):
    toml = pio_home / "pio-env.toml"
    toml.write_text(
        """
[storage.repositories.eventdata]
source = "PARQUET"
[storage.sources.PARQUET]
type = "parquetlog"
path = "/toml/events"
"""
    )
    cfg = load_config()
    assert cfg.source_for("EVENTDATA").path == "/toml/events"
    # env beats TOML
    monkeypatch.setenv("PIO_STORAGE_SOURCES_PARQUET_PATH", "/env/wins")
    cfg2 = load_config()
    assert cfg2.source_for("EVENTDATA").path == "/env/wins"


def test_custom_source_definition(pio_home, monkeypatch):
    monkeypatch.setenv("PIO_STORAGE_SOURCES_MYDB_TYPE", "sqlite")
    monkeypatch.setenv("PIO_STORAGE_SOURCES_MYDB_PATH", "/custom/db.sqlite")
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_METADATA_SOURCE", "MYDB")
    cfg = load_config()
    assert cfg.source_for("METADATA").path == "/custom/db.sqlite"
