"""tools/lint_cache.py: ONE result-cache seam on the serve path.

ISSUE 20 satellite — locks in the tentpole's invalidation-by-construction
guarantee: engine query results reach the transport only through the
cache facade's lookup/fill seam, no handler-side memoization survives a
generation swap, and the ``pio_result_cache_*`` family registers only in
``serving/result_cache.py``.
"""

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO / "tools"))

import lint_cache  # noqa: E402


def test_tree_is_clean():
    assert lint_cache.check(REPO) == []


def test_detects_submit_without_lookup_or_fill():
    """Rule 1: a submit_and_wait in the engine server that skips either
    half of the seam is flagged — one violation per missing half."""
    src = """
class EngineServer:
    def handle(self, method, path, body):
        result = self.scheduler.submit_and_wait("default", body)
        return 200, result
"""
    violations = lint_cache.check_source(
        src, "predictionio_tpu/server/engine_server.py")
    assert len(violations) == 2
    assert any("lookup" in v for v in violations)
    assert any("fill" in v for v in violations)


def test_seam_ordering_matters():
    """A lookup AFTER the submit (or a fill before) is not a seam."""
    src = """
class EngineServer:
    def handle(self, method, path, body):
        self.result_cache.fill(canon, None, 1)
        result = self.scheduler.submit_and_wait("default", body)
        self.result_cache.lookup(canon)
        return 200, result
"""
    violations = lint_cache.check_source(
        src, "predictionio_tpu/server/engine_server.py")
    assert len(violations) == 2


def test_proper_seam_is_clean():
    src = """
class EngineServer:
    def handle(self, method, path, body):
        hit = self.result_cache.lookup(canon)
        if hit is not None:
            return 200, hit.result
        result = self.scheduler.submit_and_wait("default", body)
        self.result_cache.fill(canon, result, gen)
        return 200, result
"""
    assert lint_cache.check_source(
        src, "predictionio_tpu/server/engine_server.py") == []


def test_seam_rule_only_binds_the_engine_server():
    """The scheduler's own internals (and other servers) call
    submit_and_wait legitimately without the seam."""
    src = """
class Driver:
    def run(self, q):
        return self.scheduler.submit_and_wait("default", q)
"""
    assert lint_cache.check_source(
        src, "predictionio_tpu/serving/__init__.py") == []


def test_detects_functools_memoization_on_serve_path():
    """Rule 2: lru_cache/functools.cache on server/ or serving/ code is
    a generation-blind cache that survives a swap."""
    src = """
import functools

@functools.lru_cache(maxsize=256)
def serve_one(q):
    return {"itemScores": []}

@functools.cache
def serve_two(q):
    return {}
"""
    violations = lint_cache.check_source(
        src, "predictionio_tpu/server/helper.py")
    assert len(violations) == 2
    assert all("generation" in v for v in violations)
    # the cache module itself may use whatever it likes
    assert lint_cache.check_source(
        src, "predictionio_tpu/serving/result_cache.py") == []
    # and code OFF the serve path is out of scope
    assert lint_cache.check_source(
        src, "predictionio_tpu/workflow/helper.py") == []


def test_bare_lru_cache_import_is_flagged():
    src = """
from functools import lru_cache

@lru_cache()
def serve(q):
    return {}
"""
    violations = lint_cache.check_source(
        src, "predictionio_tpu/serving/helper.py")
    assert len(violations) == 1


def test_detects_result_cache_metric_outside_owner_module():
    """Rule 3: single-owner pio_result_cache_* family."""
    src = """
def register(reg):
    reg.counter("pio_result_cache_hits_total", "rogue", ("tier",))
"""
    violations = lint_cache.check_source(
        src, "predictionio_tpu/server/engine_server.py")
    assert any("rule 3" in v for v in violations)
    assert lint_cache.check_source(
        src, "predictionio_tpu/serving/result_cache.py") == []


def test_main_exit_codes(tmp_path, capsys):
    assert lint_cache.main([str(REPO)]) == 0
    server_dir = tmp_path / "predictionio_tpu" / "server"
    server_dir.mkdir(parents=True)
    (server_dir / "bad.py").write_text(
        "import functools\n\n@functools.lru_cache\ndef f(q):\n"
        "    return {}\n")
    assert lint_cache.main([str(tmp_path)]) == 1
