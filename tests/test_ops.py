"""ops layer: ragged padding, batched solves, top-k (vs numpy oracles)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from predictionio_tpu.ops import (
    Padded,
    batched_ridge_solve,
    bucket_by_length,
    chunked_top_k,
    gram,
    pad_ragged,
    top_k_scores,
)
from predictionio_tpu.ops.topk import sharded_top_k
from predictionio_tpu.parallel.mesh import make_mesh


class TestPadRagged:
    def test_roundtrip(self):
        rows = np.array([0, 0, 2, 2, 2, 1])
        cols = np.array([5, 7, 1, 2, 3, 9])
        vals = np.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0], dtype=np.float32)
        p = pad_ragged(rows, cols, vals, n_rows=3)
        # Width = max row len (3) rounded up to the sublane granule (8).
        assert p.shape == (3, 8)
        assert p.mask.sum() == 6
        # Row 0: two entries in insertion order.
        assert list(p.indices[0][p.mask[0]]) == [5, 7]
        assert list(p.values[2][p.mask[2]]) == [3.0, 4.0, 5.0]

    def test_truncation_keeps_latest(self):
        rows = np.zeros(5, dtype=np.int64)
        cols = np.arange(5)
        p = pad_ragged(rows, cols, None, n_rows=1, max_len=3)
        assert list(p.indices[0][p.mask[0]]) == [2, 3, 4]
        assert not p.mask[0, 3:].any()  # aligned tail is masked padding

    def test_empty_rows_and_row_padding(self):
        p = pad_ragged(np.array([1]), np.array([0]), None, n_rows=3, pad_rows_to=4)
        assert p.indices.shape[0] == 4
        assert p.mask.sum() == 1

    def test_bucketing_partitions_rows(self):
        rng = np.random.default_rng(0)
        n_rows = 50
        lens = rng.integers(0, 40, n_rows)
        rows = np.repeat(np.arange(n_rows), lens)
        cols = rng.integers(0, 100, rows.shape[0])
        vals = rng.standard_normal(rows.shape[0]).astype(np.float32)
        buckets = bucket_by_length(rows, cols, vals, n_rows, bucket_bounds=(4, 16))
        seen = np.concatenate([b.row_ids[b.row_ids >= 0] for b in buckets])
        assert sorted(seen.tolist()) == list(range(n_rows))
        total = sum(int(b.mask.sum()) for b in buckets)
        assert total == rows.shape[0]
        for b in buckets:  # every real row's entries survive bucketing
            for r_local, r_global in enumerate(b.row_ids):
                if r_global < 0:
                    continue
                expect = set(cols[rows == r_global].tolist())
                got = set(b.indices[r_local][b.mask[r_local]].tolist())
                assert got == expect

    def test_split_above_partials_cover_exactly(self):
        """Zipf-head splitting: partial rows jointly hold every entry once."""
        rng = np.random.default_rng(1)
        n_rows = 12
        lens = np.concatenate([rng.integers(1, 6, 10), [40, 97]])
        rows = np.repeat(np.arange(n_rows), lens)
        cols = rng.integers(0, 50, rows.shape[0])
        vals = rng.standard_normal(rows.shape[0]).astype(np.float32)
        buckets = bucket_by_length(rows, cols, vals, n_rows,
                                   bucket_bounds=(8,), split_above=16)
        split = [b for b in buckets if b.split]
        assert len(split) == 1
        sb = split[0]
        assert sb.shape[1] == 16  # partial rows capped at split_above
        # Entities 10 (len 40 -> 3 partials) and 11 (len 97 -> 7 partials).
        for ent, exp_parts in ((10, 3), (11, 7)):
            part_rows = np.where(sb.row_ids == ent)[0]
            assert len(part_rows) == exp_parts
            got = sb.indices[part_rows][sb.mask[part_rows]]
            np.testing.assert_array_equal(np.sort(got),
                                          np.sort(cols[rows == ent]))
        # seg_ids map partials of one entity to one slot; ent_ids invert it.
        for ent in (10, 11):
            slots = set(sb.seg_ids[sb.row_ids == ent].tolist())
            assert len(slots) == 1
            assert sb.ent_ids[slots.pop()] == ent
        # Non-split buckets cover the small entities.
        seen = np.concatenate([b.row_ids[b.row_ids >= 0] for b in buckets
                               if not b.split])
        assert sorted(seen.tolist()) == list(range(10))

    def test_split_above_row_padding(self):
        rows = np.repeat([0], 33)
        cols = np.arange(33)
        buckets = bucket_by_length(rows, cols, None, 1, bucket_bounds=(8,),
                                   split_above=8, pad_rows_to=4)
        sb = [b for b in buckets if b.split][0]
        assert sb.shape[0] % 4 == 0 and len(sb.ent_ids) % 4 == 0
        assert int(sb.mask.sum()) == 33


class TestLinalg:
    def test_ridge_solve_matches_numpy(self):
        rng = np.random.default_rng(1)
        m = rng.standard_normal((4, 6, 3)).astype(np.float32)
        a = np.einsum("blk,blm->bkm", m, m)
        b = rng.standard_normal((4, 3)).astype(np.float32)
        x = batched_ridge_solve(jnp.asarray(a), jnp.asarray(b), 0.1)
        for i in range(4):
            expect = np.linalg.solve(a[i] + 0.1 * np.eye(3), b[i])
            np.testing.assert_allclose(np.asarray(x[i]), expect, rtol=1e-4, atol=1e-4)

    def test_gram(self):
        y = np.arange(12, dtype=np.float32).reshape(4, 3)
        np.testing.assert_allclose(np.asarray(gram(jnp.asarray(y))), y.T @ y, rtol=1e-6)


class TestTopK:
    def _setup(self):
        rng = np.random.default_rng(2)
        q = rng.standard_normal((3, 8)).astype(np.float32)
        items = rng.standard_normal((64, 8)).astype(np.float32)
        return q, items

    def test_matches_numpy(self):
        q, items = self._setup()
        s, i = top_k_scores(jnp.asarray(q), jnp.asarray(items), 5)
        scores = q @ items.T
        expect = np.argsort(-scores, axis=1)[:, :5]
        np.testing.assert_array_equal(np.asarray(i), expect)

    def test_exclusion(self):
        q, items = self._setup()
        scores = q @ items.T
        top1 = np.argmax(scores, axis=1)
        excl = np.zeros((3, 64), dtype=bool)
        excl[np.arange(3), top1] = True
        _, i = top_k_scores(jnp.asarray(q), jnp.asarray(items), 5,
                            exclude=jnp.asarray(excl))
        assert not any(top1[b] in np.asarray(i[b]) for b in range(3))

    def test_chunked_matches_dense(self):
        q, items = self._setup()
        s1, i1 = top_k_scores(jnp.asarray(q), jnp.asarray(items), 7)
        s2, i2 = chunked_top_k(jnp.asarray(q), jnp.asarray(items), 7, chunk=16)
        np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_sharded_matches_dense(self):
        q, items = self._setup()
        mesh = make_mesh({"data": 8})
        s1, i1 = top_k_scores(jnp.asarray(q), jnp.asarray(items), 5)
        s2, i2 = sharded_top_k(mesh, "data", jnp.asarray(q), jnp.asarray(items), 5)
        np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
