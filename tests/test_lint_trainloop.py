"""tools/lint_trainloop.py: deep-model train loops ride DevicePrefetcher.

ISSUE 5 satellite — locks in the overlapped input pipeline: a model whose
step loop stages batches inline (``jnp.asarray`` / ``jax.device_put`` /
``put_sharded`` after the device sync) silently re-serializes H2D and
reopens the feeder-vs-realized gap BENCH_r05 measured.  Tier-1 fails it.
"""

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO / "tools"))

import lint_trainloop  # noqa: E402


def test_tree_is_clean():
    assert lint_trainloop.check(REPO) == []


def test_detects_inline_staging_in_step_loop():
    src = """
import jax.numpy as jnp

def _train_attempt(data, cfg):
    with DevicePrefetcher(iter(data), lambda b: b) as pf:
        for batch in pf:
            args = jnp.asarray(batch)          # <- serialized H2D
            state = step(state, args)
"""
    violations = lint_trainloop.check_source(src, "model.py")
    assert len(violations) == 1
    assert "jnp.asarray" in violations[0]
    assert "step loop" in violations[0]


def test_detects_missing_prefetcher():
    src = """
def _train_attempt(data, cfg):
    for batch in iter(data):
        state = step(state, batch)
"""
    violations = lint_trainloop.check_source(src, "model.py")
    assert len(violations) == 1
    assert "DevicePrefetcher" in violations[0]


def test_staging_in_prep_closure_is_allowed():
    src = """
import numpy as np

def _train_attempt(data, cfg):
    def prep(b):
        return np.concatenate([b, np.zeros(4, np.float32)])

    def put(arrays):
        return put_sharded(arrays, mesh, sh)   # outside any loop: fine

    with DevicePrefetcher(iter(data), prep, put_fn=put) as pf:
        for batch in pf:
            state = step(state, *batch.args)
"""
    assert lint_trainloop.check_source(src, "model.py") == []


def test_device_put_and_put_sharded_banned_in_loop():
    src = """
import jax

def _train_attempt(data, cfg):
    pf = DevicePrefetcher(iter(data), lambda b: b)
    while True:
        a = jax.device_put(next(pf))
        b = put_sharded(a, mesh, sh)
"""
    violations = lint_trainloop.check_source(src, "model.py")
    assert len(violations) == 2
    assert any("jax.device_put" in v for v in violations)
    assert any("put_sharded" in v for v in violations)


def test_required_files_must_define_train_attempt():
    violations = lint_trainloop.check_source(
        "def train(x):\n    return x\n", "two_tower.py",
        require_prefetcher=True)
    assert len(violations) == 1
    assert "_train_attempt" in violations[0]


def test_scan_body_host_sync_banned():
    src = """
import jax
from jax import lax

def _fused(state, xs):
    def body(carry, x):
        new = step(carry, x)
        loss = float(new[1])                  # <- host sync in scan body
        jax.device_get(new[0])                # <- and another
        return new, loss
    return lax.scan(body, state, xs)
"""
    violations = lint_trainloop.check_source(src, "model.py")
    assert len(violations) == 2
    assert any("float" in v for v in violations)
    assert any("device_get" in v for v in violations)
    assert all("scan body" in v for v in violations)


def test_scan_body_block_until_ready_banned_via_jax_lax():
    src = """
import jax

def _fused(state, xs):
    def body(carry, x):
        carry = step(carry, x)
        carry[0].block_until_ready()          # <- host sync in scan body
        return carry, carry[1]
    return jax.lax.scan(body, state, xs)
"""
    violations = lint_trainloop.check_source(src, "model.py")
    assert len(violations) == 1
    assert "block_until_ready" in violations[0]


def test_scan_body_without_syncs_is_clean():
    src = """
from jax import lax

def _fused(state, xs):
    def body(carry, x):
        return step(carry, x)
    return lax.scan(body, state, xs)
"""
    assert lint_trainloop.check_source(src, "model.py") == []


def test_supervision_in_nested_function_flagged():
    src = """
def _train_attempt(data, cfg, guard, watchdog):
    def prep(b):
        guard.check(b, 0)                     # <- off the boundary
        return b

    with DevicePrefetcher(iter(data), prep) as pf:
        for batch in pf:
            watchdog.arm(batch.step, scale=batch.steps)
            state, losses = step(state, *batch.args)
            guard.check_vector(losses, [batch.step])
            watchdog.disarm()
"""
    violations = lint_trainloop.check_source(src, "model.py")
    assert len(violations) == 1
    assert "nested function" in violations[0]
    assert "guard.check" in violations[0]


def test_required_loop_missing_boundary_supervision_flagged():
    src = """
def _train_attempt(data, cfg):
    with DevicePrefetcher(iter(data), lambda b: b) as pf:
        for batch in pf:
            state = step(state, *batch.args)
"""
    violations = lint_trainloop.check_source(src, "two_tower.py",
                                             require_prefetcher=True)
    assert len(violations) == 2
    assert any("watchdog.arm" in v for v in violations)
    assert any("guard.check" in v for v in violations)


def test_host_numpy_in_loops_is_fine():
    src = """
import numpy as np

def _train_attempt(data, cfg):
    def epochs():
        for epoch in range(3):
            yield np.asarray(data[epoch], np.int64)   # host-side: fine

    with DevicePrefetcher(epochs(), lambda b: b) as pf:
        for batch in pf:
            state = step(state, *batch.args)
"""
    assert lint_trainloop.check_source(src, "model.py") == []
