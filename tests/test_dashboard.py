"""Dashboard server: instance listings (reference: tools/dashboard)."""

import json
import urllib.request

import pytest

from predictionio_tpu.data.storage import get_storage
from predictionio_tpu.server.dashboard import DashboardServer


@pytest.fixture()
def dash(pio_home):
    import datetime as dt

    from predictionio_tpu.data.storage import EngineInstance

    storage = get_storage()
    storage.get_engine_instances().insert(EngineInstance(
        id=None, status="COMPLETED",
        start_time=dt.datetime.now(dt.timezone.utc),
        end_time=dt.datetime.now(dt.timezone.utc),
        engine_id="x", engine_version="1", engine_variant="default",
        engine_factory="pkg.mod:engine",
        datasource_params="{}", preparator_params="{}",
        algorithms_params="[]", serving_params="{}"))
    srv = DashboardServer(storage=storage, host="127.0.0.1", port=0)
    srv.start()
    yield srv
    srv.stop()


def test_html_index(dash):
    with urllib.request.urlopen(f"http://127.0.0.1:{dash.port}/", timeout=10) as r:
        body = r.read().decode()
    assert "pkg.mod:engine" in body and "COMPLETED" in body


def test_json_listing(dash):
    url = f"http://127.0.0.1:{dash.port}/engine_instances.json"
    with urllib.request.urlopen(url, timeout=10) as r:
        rows = json.loads(r.read())
    assert len(rows) == 1 and rows[0]["status"] == "COMPLETED"


def test_404(dash):
    import urllib.error

    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(f"http://127.0.0.1:{dash.port}/nope", timeout=10)
    assert ei.value.code == 404
