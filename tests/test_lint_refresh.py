"""tools/lint_refresh.py: refresh promotes through the staged-reload gate.

ISSUE 10 satellite — a continuously-retraining daemon must never grow a
shortcut around the PR-4 promotion machinery: direct model-store writes
and out-of-server generation swaps fail tier-1.
"""

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO / "tools"))

import lint_refresh  # noqa: E402


def test_tree_is_clean():
    assert lint_refresh.check(REPO) == []


def test_detects_direct_model_store_write():
    src = """
def sneak(storage, blob):
    storage.get_models().insert(blob)
"""
    violations = lint_refresh.check_source(
        src, "t.py", ("refresh", "daemon.py"), in_refresh=False)
    assert len(violations) == 1
    assert "staged-reload gate" in violations[0]


def test_detects_split_chain_model_store_write():
    src = """
def sneak(storage, blob):
    repo = storage.get_models()
    repo.insert(blob)
"""
    violations = lint_refresh.check_source(
        src, "t.py", ("cli", "main.py"), in_refresh=False)
    assert len(violations) == 1


def test_sanctioned_writers_pass():
    src = "def persist(storage, m):\n    storage.get_models().insert(m)\n"
    assert lint_refresh.check_source(
        src, "core_workflow.py", ("workflow", "core_workflow.py"),
        in_refresh=False) == []
    # storage backends implement the repository itself
    assert lint_refresh.check_source(
        src, "memory.py", ("storage", "memory.py"), in_refresh=False) == []


def test_detects_generation_swap_outside_server():
    src = """
def hot_swap(srv, models):
    srv._models = models
    srv._generation += 1
"""
    violations = lint_refresh.check_source(
        src, "t.py", ("refresh", "daemon.py"), in_refresh=False)
    assert len(violations) == 2
    assert all("engine_server" in v for v in violations)


def test_self_generation_state_is_fine_anywhere():
    # a class managing ITS OWN fields of the same name is not a swap of
    # the engine server's state
    src = """
class Thing:
    def __init__(self):
        self._models = []
        self._generation = 0
"""
    assert lint_refresh.check_source(
        src, "t.py", ("serving", "queue.py"), in_refresh=False) == []


def test_engine_server_itself_passes():
    src = "def swap(srv, m):\n    srv._models = m\n"
    assert lint_refresh.check_source(
        src, "engine_server.py", ("server", "engine_server.py"),
        in_refresh=False) == []


def test_refresh_package_forbidden_names():
    src = """
from predictionio_tpu.resilience.supervision import validate_model_finite

def diy_gate(storage, models):
    validate_model_finite(models)
    storage.get_models()
"""
    violations = lint_refresh.check_source(
        src, "daemon.py", ("refresh", "daemon.py"), in_refresh=True)
    names = "\n".join(violations)
    assert "validate_model_finite" in names
    assert "get_models" in names


def test_cli_exit_codes(tmp_path):
    assert lint_refresh.main([str(REPO)]) == 0
    pkg = tmp_path / "predictionio_tpu" / "refresh"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(
        "def f(storage, m):\n    storage.get_models().insert(m)\n")
    assert lint_refresh.main([str(tmp_path)]) == 1


# -- rule 4 (ISSUE 15): promote loops only inside fleet/ --------------------

def test_detects_promote_loop_outside_fleet():
    src = """
def push_everywhere(urls, instance_id):
    for url in urls:
        HttpPromoter(url).promote(instance_id)
"""
    violations = lint_refresh.check_source(
        src, "t.py", ("refresh", "daemon.py"), in_refresh=False)
    assert len(violations) == 1
    assert "RolloutController" in violations[0]


def test_detects_promote_comprehension_outside_fleet():
    src = """
def push_everywhere(promoters, iid):
    return [p.promote(iid) for p in promoters]
"""
    violations = lint_refresh.check_source(
        src, "t.py", ("cli", "main.py"), in_refresh=False)
    assert len(violations) == 1


def test_single_promote_outside_loop_is_fine():
    # the refresh daemon's one promote per cycle is legal — run_once is
    # CALLED from a loop, but the call is not lexically inside one
    src = """
def _promote(self, instance_id):
    self.promoter.promote(instance_id)
"""
    assert lint_refresh.check_source(
        src, "t.py", ("refresh", "daemon.py"), in_refresh=False) == []


def test_promote_in_helper_defined_inside_loop_is_fine():
    # a function DEFINED in a loop body resets the loop context
    src = """
def build(urls):
    out = []
    for url in urls:
        def one(iid, _u=url):
            return HttpPromoter(_u).promote(iid)
        out.append(one)
    return out
"""
    assert lint_refresh.check_source(
        src, "t.py", ("refresh", "daemon.py"), in_refresh=False) == []


def test_fleet_package_may_loop_promote():
    src = """
def unwind(promoters, iid):
    for p in promoters:
        p.promote(iid)
"""
    assert lint_refresh.check_source(
        src, "t.py", ("fleet", "rollout.py"), in_refresh=False,
        in_fleet=True) == []
