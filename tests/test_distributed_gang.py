"""A REAL two-process CPU gang through ``initialize_distributed``.

Round-2 verdict item 6: ``jax.distributed.initialize`` had never actually
executed — every test ran single-process, so the code path past the
``coordinator_address is None`` early-return was dead.  Here two
subprocesses form a gang on localhost (CPU backend), assert
``process_count() == 2``, and run one cross-process ``psum`` over a
2-device mesh (1 CPU device per process), checking the reduced value.

Reference: SURVEY.md §2.5 — multi-host slice bring-up is a first-class
deliverable; this is its smallest honest exercise.
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
import jax.numpy as jnp
from predictionio_tpu.parallel.distributed import (
    initialize_distributed, is_multi_host, process_count, process_index,
)

active = initialize_distributed()
assert active, "PIO_COORDINATOR_ADDRESS was set; gang must form"
assert process_count() == 2, process_count()
assert is_multi_host()
rank = process_index()
assert rank == int(os.environ["PIO_PROCESS_ID"])

# One cross-process collective: each process contributes (rank + 1) from
# its single local device; psum over the global 2-device mesh = 3.
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental import multihost_utils
import numpy as np

devs = np.array(jax.devices())  # 2 global devices, 1 per process
assert devs.size == 2, devs
mesh = Mesh(devs, ("data",))
local = jnp.asarray([float(rank + 1)])

with mesh:
    from jax.experimental.shard_map import shard_map
    out = jax.jit(shard_map(
        lambda x: jax.lax.psum(x, "data"),
        mesh=mesh, in_specs=P("data"), out_specs=P("data"),
    ))(multihost_utils.host_local_array_to_global_array(
        local, mesh, P("data")))
    got = multihost_utils.global_array_to_host_local_array(
        out, mesh, P("data"))
assert float(np.asarray(got)[0]) == 3.0, np.asarray(got)

# A REAL distributed train: ALS on the 2-device data mesh across both
# processes (solve rows sharded, factors replicated).  Every process
# computes the same input from a shared seed; prepare_als_inputs routes
# placement through parallel.mesh.put_sharded, which contributes only
# this process's addressable shards.  Factors must match the meshless
# single-process computation.
from predictionio_tpu.models.als import ALSConfig, train_als

drng = np.random.default_rng(7)
n_u, n_i, n_r = 16, 12, 160
au = drng.integers(0, n_u, n_r)
ai = drng.integers(0, n_i, n_r)
ar = drng.integers(1, 6, n_r).astype(np.float32)
cfg = ALSConfig(rank=4, iterations=2, seed=0, split_above=64)
dist_model = train_als(au, ai, ar, n_u, n_i, cfg, mesh=mesh)
ref_model = train_als(au, ai, ar, n_u, n_i, cfg, mesh=None)
np.testing.assert_allclose(np.asarray(dist_model.user_factors),
                           np.asarray(ref_model.user_factors),
                           rtol=1e-5, atol=1e-6)

# Blocked (factor-sharded) ALS across the REAL gang: the persistent
# factor matrices live row-sharded across the two processes (round-4
# blueprint item — SURVEY §2.4 row 2), so each host only addresses its
# half; gather the global result to compare against meshless.
from jax.experimental.multihost_utils import process_allgather

bcfg = ALSConfig(rank=4, iterations=2, seed=0, split_above=64,
                 factor_sharding="sharded")
bmodel = train_als(au, ai, ar, n_u, n_i, bcfg, mesh=mesh)
assert bmodel.user_factors.sharding.spec[0] == "data", \
    bmodel.user_factors.sharding
buf = process_allgather(bmodel.user_factors, tiled=True)
np.testing.assert_allclose(np.asarray(buf),
                           np.asarray(ref_model.user_factors),
                           rtol=1e-5, atol=1e-6)

# Windowed blocked ALS across the REAL gang (round 5): per-chunk factor
# gathers run as masked local takes + psum over the 2-process data axis;
# shape chosen so user-side windows engage (items touched << n_items).
from predictionio_tpu.models.als import prepare_als_inputs, train_als_prepared

wn_i = 300
wi = drng.integers(0, 20, n_r)
wcfg = ALSConfig(rank=4, iterations=2, seed=0, split_above=64,
                 bucket_bounds=(16,), factor_sharding="sharded",
                 gather_window=True)
winp = prepare_als_inputs(au, wi, ar, n_u, wn_i, wcfg, mesh=mesh)
assert any(b[0].endswith("_w") for b in winp.user_buckets), \
    [b[0] for b in winp.user_buckets]
wmodel = train_als_prepared(winp, wcfg)
wref = train_als(au, wi, ar, n_u, wn_i,
                 ALSConfig(rank=4, iterations=2, seed=0, split_above=64,
                           bucket_bounds=(16,)), mesh=None)
wuf = process_allgather(wmodel.user_factors, tiled=True)
np.testing.assert_allclose(np.asarray(wuf)[:n_u],
                           np.asarray(wref.user_factors),
                           rtol=1e-4, atol=1e-5)
print(f"RANK{rank}_OK", flush=True)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.xfail(
    reason="jax CPU backend: 'Multiprocess computations aren't implemented "
           "on the CPU backend' (XlaRuntimeError) — the gang forms, the "
           "psum needs a real accelerator collective",
    strict=False)
def test_two_process_gang_forms_and_psums(tmp_path):
    port = _free_port()
    env_base = {
        **os.environ,
        "PIO_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        "PIO_NUM_PROCESSES": "2",
        "PYTHONPATH": os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
    }
    procs = []
    for rank in range(2):
        env = {**env_base, "PIO_PROCESS_ID": str(rank)}
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _WORKER], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = []
    for rank, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=150)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail(f"rank {rank} timed out forming the gang")
        outs.append((p.returncode, out, err))
    for rank, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"rank {rank} failed:\n{err[-3000:]}"
        assert f"RANK{rank}_OK" in out
