"""Columnar P-path transforms: correctness vs the slow-path oracle + scale.

VERDICT.md round-1 item 4: the template DataSources must stop doing
per-event ``json.loads`` loops.  These tests pin the Arrow-kernel helpers
against a row-by-row oracle and prove the read path is loop-free at scale.
"""

import json
import time

import numpy as np
import pyarrow as pa
import pytest

from predictionio_tpu.data.columnar import (
    bool_property,
    encode_ids,
    event_mask,
    numeric_property,
)


def _table(n=1000, seed=0):
    rng = np.random.default_rng(seed)
    users = [f"u{int(x)}" for x in rng.integers(0, 50, n)]
    events = rng.choice(["rate", "buy", "view"], n).tolist()
    props = []
    for i in range(n):
        if events[i] == "rate":
            props.append(json.dumps({"rating": float(rng.integers(1, 11)) / 2,
                                     "clicked": bool(rng.random() < 0.5)}))
        elif events[i] == "buy":
            props.append(json.dumps({"clicked": True}))
        else:
            props.append(None)
    return pa.table({"entity_id": users, "event": events,
                     "properties_json": props})


class TestEncodeIds:
    def test_matches_first_seen_order(self):
        t = _table(400)
        codes, bimap = encode_ids(t.column("entity_id"))
        rows = t.column("entity_id").to_pylist()
        # Oracle: BiMap.string_int semantics (first-seen contiguous ints).
        seen = {}
        for r in rows:
            seen.setdefault(r, len(seen))
        assert dict(zip(bimap, (bimap[k] for k in bimap))) == seen
        np.testing.assert_array_equal(codes, [seen[r] for r in rows])

    def test_chunked_input(self):
        t1, t2 = _table(100, seed=1), _table(100, seed=2)
        chunked = pa.chunked_array([t1.column("entity_id").combine_chunks(),
                                    t2.column("entity_id").combine_chunks()])
        codes, bimap = encode_ids(chunked)
        assert len(codes) == 200
        rows = chunked.to_pylist()
        assert all(bimap.inverse[c] == r for c, r in zip(codes[:20], rows[:20]))


class TestProperties:
    def test_numeric_matches_json_loads(self):
        t = _table(500)
        got = numeric_property(t, "rating", default=-1.0)
        for i, pr in enumerate(t.column("properties_json").to_pylist()):
            want = json.loads(pr).get("rating", -1.0) if pr else -1.0
            assert got[i] == pytest.approx(want), i

    def test_numeric_handles_exponents_and_negatives(self):
        props = [json.dumps({"x": v}) for v in (-1.5, 2e3, 0.5, -3e-2, 7)]
        t = pa.table({"properties_json": props})
        np.testing.assert_allclose(numeric_property(t, "x"),
                                   [-1.5, 2e3, 0.5, -3e-2, 7])

    def test_bool_matches_json_loads(self):
        t = _table(500)
        got = bool_property(t, "clicked")
        for i, pr in enumerate(t.column("properties_json").to_pylist()):
            want = bool(pr and json.loads(pr).get("clicked") in (True, 1, 1.0))
            assert bool(got[i]) == want, (i, pr)

    def test_key_is_regex_escaped(self):
        t = pa.table({"properties_json": [json.dumps({"a.b": 3.0,
                                                      "axb": 9.0})]})
        np.testing.assert_allclose(numeric_property(t, "a.b"), [3.0])


class TestEventMask:
    def test_mask(self):
        t = _table(300)
        got = event_mask(t, ["rate", "buy"])
        want = [e in ("rate", "buy") for e in t.column("event").to_pylist()]
        np.testing.assert_array_equal(got, want)


def test_scale_smoke():
    """2M events through the full columnar transform stack in seconds —
    the loop-free guarantee the ML-25M north star depends on."""
    n = 2_000_000
    rng = np.random.default_rng(7)
    users = pa.array((rng.integers(0, 160_000, n)).astype(str))
    items = pa.array((rng.integers(0, 59_000, n)).astype(str))
    ratings_str = [f'{{"rating": {r}}}' for r in (0.5, 1.5, 3.0, 4.5, 5.0)]
    props = pa.array(np.array(ratings_str, dtype=object)[
        rng.integers(0, 5, n)].tolist())
    events = pa.array(np.array(["rate", "buy"], dtype=object)[
        rng.integers(0, 2, n)].tolist())
    t = pa.table({"entity_id": users, "target_entity_id": items,
                  "event": events, "properties_json": props})
    t0 = time.perf_counter()
    ucodes, uindex = encode_ids(t.column("entity_id"))
    icodes, _ = encode_ids(t.column("target_entity_id"))
    vals = numeric_property(t, "rating", default=0.0)
    mask = event_mask(t, ["rate"])
    dt = time.perf_counter() - t0
    assert len(ucodes) == n and len(vals) == n and mask.sum() > 0
    assert len(uindex) <= 160_000
    # Generous bound: the round-1 loop took minutes at this size.
    assert dt < 20.0, f"columnar transforms too slow: {dt:.1f}s"


class TestNumericPropertyEdgeCases:
    """Round-2 advisor: nested keys / string-numbers must not mis-extract."""

    def _col(self, rows):
        import pyarrow as pa
        return pa.array(rows, type=pa.string())

    def test_nested_same_name_key_is_not_matched(self):
        from predictionio_tpu.data.columnar import numeric_property
        col = self._col([
            '{"meta": {"rating": 1}, "rating": 5}',
            '{"rating": 3}',
            '{"meta": {"rating": 9}}',  # no TOP-LEVEL rating → default
        ])
        out = numeric_property(col, "rating", default=-1.0)
        assert out.tolist() == [5.0, 3.0, -1.0]

    def test_string_encoded_number_coerces(self):
        from predictionio_tpu.data.columnar import numeric_property
        col = self._col(['{"rating": "4.5"}', '{"rating": 2}'])
        out = numeric_property(col, "rating", default=0.0)
        assert out.tolist() == [4.5, 2.0]

    def test_key_text_inside_string_value(self):
        from predictionio_tpu.data.columnar import numeric_property
        col = self._col([
            '{"note": "my \\"rating\\": 3 memo", "rating": 4}',
            '{"note": "contains \\"rating\\": 7 only"}',
        ])
        out = numeric_property(col, "rating", default=0.0)
        assert out.tolist() == [4.0, 0.0]

    def test_non_numeric_and_bool_values_default(self):
        from predictionio_tpu.data.columnar import numeric_property
        col = self._col(['{"rating": true, "x": {"rating": 2}}',
                         '{"rating": null, "y": {"rating": 1}}'])
        out = numeric_property(col, "rating", default=-2.0)
        assert out.tolist() == [-2.0, -2.0]

    def test_flat_key_before_nested_value_stays_correct(self):
        from predictionio_tpu.data.columnar import numeric_property
        col = self._col(['{"rating": 4, "ctx": {"rating": 9, "z": 1}}',
                         '{"ctx": {"rating": 9}, "rating": 2}'])
        out = numeric_property(col, "rating", default=0.0)
        assert out.tolist() == [4.0, 2.0]


class TestDictionaryFastPaths:
    """Dictionary-encoded input (a parquet training scan) must behave
    exactly like dense strings — including a FILTERED scan whose stored
    dictionary still lists values no surviving row references."""

    def test_encode_ids_dictionary_matches_dense(self):
        vals = ["u3", "u1", "u3", "u2", "u1", "u3"]
        dense_codes, dense_map = encode_ids(pa.array(vals))
        dict_codes, dict_map = encode_ids(pa.array(vals).dictionary_encode())
        assert dense_codes.tolist() == dict_codes.tolist()
        assert dict(dense_map) == dict(dict_map)

    def test_encode_ids_filtered_dictionary_compacts(self):
        # dictionary has 4 entries; only 2 appear in the indices (as after
        # a .filter() on a dictionary column) — the BiMap must not invent
        # the missing entities, and codes must be first-appearance order
        d = pa.DictionaryArray.from_arrays(
            pa.array([2, 0, 2, 0], type=pa.int32()),
            pa.array(["a", "b", "c", "d"]))
        codes, bimap = encode_ids(d)
        assert codes.tolist() == [0, 1, 0, 1]
        assert dict(bimap) == {"c": 0, "a": 1}

    def test_encode_ids_dictionary_not_in_first_appearance_order(self):
        # all entries present but stored order != first-appearance order
        d = pa.DictionaryArray.from_arrays(
            pa.array([1, 0, 1, 0], type=pa.int32()),
            pa.array(["x", "y"]))
        codes, bimap = encode_ids(d)
        assert codes.tolist() == [0, 1, 0, 1]
        assert dict(bimap) == {"y": 0, "x": 1}

    def test_numeric_property_dictionary_matches_dense(self):
        raw = ['{"rating": 4.5}', '{"rating": 1.0}', "{}",
               '{"rating": 4.5}', None]
        dense = numeric_property(pa.array(raw, type=pa.string()), "rating",
                                 default=-1.0)
        asdict = numeric_property(
            pa.array(raw, type=pa.string()).dictionary_encode(), "rating",
            default=-1.0)
        assert dense.tolist() == asdict.tolist()

    def test_bool_property_dictionary_matches_dense(self):
        raw = ['{"clicked": true}', '{"clicked": false}', "{}", None,
               '{"clicked": 1}']
        dense = bool_property(pa.array(raw, type=pa.string()), "clicked")
        asdict = bool_property(
            pa.array(raw, type=pa.string()).dictionary_encode(), "clicked")
        assert dense.tolist() == asdict.tolist()

    def test_encode_ids_rejects_nulls(self):
        with pytest.raises(ValueError, match="null"):
            encode_ids(pa.array(["a", None, "b"]))
        with pytest.raises(ValueError, match="null"):
            encode_ids(pa.array(["a", None, "b"]).dictionary_encode())
