"""Columnar segment store (ISSUE 17): CRC-framed blocks, torn-tail
recovery, crash-safe seal/compaction, coverage honesty, and the
WindowedEventStore delta read that rides it."""

import datetime as dt
import json
from types import SimpleNamespace

import pyarrow as pa
import pytest

from predictionio_tpu.data import DataMap, Event
from predictionio_tpu.data.columnar import (
    SEGMENT_SUFFIX,
    SegmentDiskPressure,
    SegmentStore,
    recover_segment_tail,
    resolve_segment_root,
)
from predictionio_tpu.resilience import faults
from predictionio_tpu.resilience.faults import FaultInjected

UTC = dt.timezone.utc
APP = 7


def _ev(i, t_s, name="view"):
    return Event(
        event=name,
        entity_type="user",
        entity_id=f"u{i}",
        target_entity_type="item",
        target_entity_id=f"i{i}",
        properties=DataMap({}),
        event_time=dt.datetime.fromtimestamp(t_s, UTC),
    )


def _store(root, clk, **kw):
    kw.setdefault("roll_bytes", 1 << 30)
    kw.setdefault("roll_s", 1e9)
    kw.setdefault("grace_s", 0.0)
    kw.setdefault("compact_trigger", 0)  # tests drive compaction directly
    return SegmentStore(root, clock=lambda: clk.t, **kw)


def _seg_files(root):
    return sorted(p.name for p in (root / "app_7" / "default").iterdir()
                  if p.suffix == SEGMENT_SUFFIX)


def _manifest(root):
    return json.loads((root / "app_7" / "default" / "manifest.json")
                      .read_text())


@pytest.fixture()
def clk():
    return SimpleNamespace(t=1000.0)


# --------------------------------------------------------------------------
# Roundtrip + coverage honesty
# --------------------------------------------------------------------------


def test_append_seal_read_roundtrip(tmp_path, clk):
    st = _store(tmp_path, clk)
    st.append_events(APP, None, [_ev(0, 1001), _ev(1, 1002)])
    st.append_events(APP, None, [_ev(2, 1003, name="buy")])
    clk.t = 1100.0
    st.seal_all()
    got = st.read_window(APP, None, int(1000e6), 1 << 62)
    assert got is not None
    table, covered = got
    assert covered == int(1100e6)
    assert table.num_rows == 3
    # filters are find_columnar parity
    table, _ = st.read_window(APP, None, int(1000e6), 1 << 62,
                              event_names=["buy"])
    assert table.num_rows == 1
    table, _ = st.read_window(APP, None, int(1000e6), 1 << 62,
                              entity_id="u0")
    assert table.num_rows == 1
    # a read starting BELOW the floor cannot be proven — full fallback
    assert st.read_window(APP, None, int(900e6), 1 << 62) is None


def test_unsealed_rows_are_never_claimed(tmp_path, clk):
    st = _store(tmp_path, clk)
    st.append_events(APP, None, [_ev(0, 1001)])
    clk.t = 1100.0
    st.seal_all()
    st.append_events(APP, None, [_ev(1, 1150)])  # active, not sealed
    got = st.read_window(APP, None, int(1000e6), 1 << 62)
    table, covered = got
    assert covered == int(1100e6)  # coverage stops at the active window
    assert table.num_rows == 1  # the active row is the PRIMARY's to serve


def test_late_event_ratchets_floor(tmp_path, clk):
    """An event older than the open window would falsify the sealed
    ranges' completeness claim — the floor ratchets up (coverage wiped)
    rather than lie; reads fall back to the primary store."""
    st = _store(tmp_path, clk)
    st.append_events(APP, None, [_ev(0, 1001)])
    clk.t = 1100.0
    st.seal_all()
    assert st.read_window(APP, None, int(1000e6), 1 << 62) is not None
    st.append_events(APP, None, [_ev(1, 1050)])  # 1050 < window start 1100
    assert _manifest(tmp_path)["floorUs"] == int(1100e6)
    assert st.read_window(APP, None, int(1000e6), 1 << 62) is None


def test_straggler_teed_into_next_window_is_still_found(tmp_path, clk):
    """Rows land by DATA range, not window label: an event teed slightly
    after its stamp (but still >= window start) seals into the next
    window; the read must overlap by min/max, not the label."""
    st = _store(tmp_path, clk)
    st.append_events(APP, None, [_ev(0, 1001)])
    clk.t = 1100.0
    st.seal_all()
    # stamped inside window 2, sealed in window 2 — plus one stamped
    # EXACTLY at a boundary the first window claimed up to
    st.append_events(APP, None, [_ev(1, 1100), _ev(2, 1150)])
    clk.t = 1200.0
    st.seal_all()
    table, covered = st.read_window(APP, None, int(1000e6), 1 << 62)
    assert covered == int(1200e6) and table.num_rows == 3


# --------------------------------------------------------------------------
# Torn tails + CRC
# --------------------------------------------------------------------------


def test_torn_tail_truncated_counted_idempotent(tmp_path, clk):
    st = _store(tmp_path, clk)
    st.append_events(APP, None, [_ev(0, 1001)])
    st.append_events(APP, None, [_ev(1, 1002)])
    clk.t = 1100.0
    st.seal_all()
    seg = tmp_path / "app_7" / "default" / _seg_files(tmp_path)[0]
    good = seg.read_bytes()
    # a torn write: half the last block's bytes survived the crash
    seg.write_bytes(good[: len(good) - 7])
    rec = recover_segment_tail(seg)
    assert rec["blocks"] == 1 and rec["rows"] == 1
    assert rec["torn_bytes"] > 0
    assert seg.stat().st_size == rec["valid_bytes"]
    rec2 = recover_segment_tail(seg)  # second pass: clean, no-op
    assert rec2["torn_bytes"] == 0 and rec2["blocks"] == 1


def test_corrupt_crc_stops_scan(tmp_path, clk):
    st = _store(tmp_path, clk)
    st.append_events(APP, None, [_ev(0, 1001)])
    st.append_events(APP, None, [_ev(1, 1002)])
    clk.t = 1100.0
    st.seal_all()
    seg = tmp_path / "app_7" / "default" / _seg_files(tmp_path)[0]
    raw = bytearray(seg.read_bytes())
    raw[-3] ^= 0xFF  # flip a bit inside the LAST block's crc
    seg.write_bytes(bytes(raw))
    rec = recover_segment_tail(seg, truncate=False)
    assert rec["blocks"] == 1  # scan stopped at the bad CRC


def test_damaged_sealed_segment_means_full_fallback(tmp_path, clk):
    """A sealed file whose recoverable rows disagree with the manifest is
    a broken completeness claim — the reader answers None (primary-store
    fallback), never a silently short slice."""
    st = _store(tmp_path, clk)
    st.append_events(APP, None, [_ev(0, 1001), _ev(1, 1002)])
    st.append_events(APP, None, [_ev(2, 1003)])
    clk.t = 1100.0
    st.seal_all()
    seg = tmp_path / "app_7" / "default" / _seg_files(tmp_path)[0]
    seg.write_bytes(seg.read_bytes()[:-5])
    assert st.read_window(APP, None, int(1000e6), 1 << 62) is None


def test_crashed_active_window_is_discarded_at_open(tmp_path, clk):
    """kill -9 with an open active window: the tail is recovered and
    MEASURED, then discarded — its window was never claimed and the
    primary store is authoritative, so salvaging rows that raced the
    crash could break a later seal's completeness claim."""
    st = _store(tmp_path, clk)
    st.append_events(APP, None, [_ev(0, 1001)])
    clk.t = 1100.0
    st.seal_all()
    st.append_events(APP, None, [_ev(1, 1150)])
    # simulate kill -9: no seal, no close — reopen the dir cold
    st2 = _store(tmp_path, clk)
    st2._dir(APP, None)  # triggers _load_and_recover
    leftovers = [p for p in (tmp_path / "app_7" / "default").iterdir()
                 if p.suffix == ".tmp"]
    assert leftovers == []
    table, covered = st2.read_window(APP, None, int(1000e6), 1 << 62)
    assert table.num_rows == 1 and covered == int(1100e6)


# --------------------------------------------------------------------------
# Compaction: merge + crash at every commit boundary
# --------------------------------------------------------------------------


def _three_small_segments(tmp_path, clk):
    st = _store(tmp_path, clk)
    for k in range(3):
        st.append_events(APP, None,
                         [_ev(2 * k, 1001 + 100 * k),
                          _ev(2 * k + 1, 1002 + 100 * k)])
        clk.t = 1100.0 + 100 * k
        st.seal_all()
    assert len(_seg_files(tmp_path)) == 3
    return st


def test_compaction_merges_and_preserves_reads(tmp_path, clk):
    st = _three_small_segments(tmp_path, clk)
    before, cov_before = st.read_window(APP, None, int(1000e6), 1 << 62)
    stats = st.compact(APP, None)
    assert stats == {"runs": 1, "segments_in": 3, "segments_out": 1}
    assert len(_seg_files(tmp_path)) == 1
    m = _manifest(tmp_path)
    assert [e["file"] for e in m["segments"]] == _seg_files(tmp_path)
    after, cov_after = st.read_window(APP, None, int(1000e6), 1 << 62)
    assert cov_after == cov_before
    assert after.sort_by("event_time_us").equals(
        before.sort_by("event_time_us"))


@pytest.mark.parametrize("point", ["segment.compact",
                                   "segment.compact.commit",
                                   "segment.compact.cleanup"])
def test_compaction_crash_leaves_one_readable_set(tmp_path, clk, point):
    """Kill compaction at each boundary: after 'restart' (fresh store →
    orphan sweep) the manifest references exactly the files on disk and
    the read answers ALL six rows — old set or new set, never both,
    never neither."""
    st = _three_small_segments(tmp_path, clk)
    try:
        faults.install(f"{point}:error:1.0")
        with pytest.raises(FaultInjected):
            st.compact(APP, None)
    finally:
        faults.clear()
    st2 = _store(tmp_path, clk)
    st2._dir(APP, None)  # restart: sweep whatever the crash stranded
    m = _manifest(tmp_path)
    assert [e["file"] for e in m["segments"]] == _seg_files(tmp_path)
    table, covered = st2.read_window(APP, None, int(1000e6), 1 << 62)
    assert table.num_rows == 6, f"rows lost after crash at {point}"
    assert sorted(table.column("entity_id").to_pylist()) == \
        [f"u{i}" for i in range(6)]


# --------------------------------------------------------------------------
# Kill at every fsync boundary of the append/seal path
# --------------------------------------------------------------------------


@pytest.mark.parametrize("point", ["segment.append", "segment.seal",
                                   "segment.manifest"])
def test_seal_path_crash_never_overclaims(tmp_path, clk, point):
    """Crash the writer at each append/seal/manifest boundary.  The
    invariant is HONESTY, not durability: whatever survived, a reopened
    store either proves coverage (and then has every claimed row) or
    declines — the sealed generation A stays intact either way."""
    st = _store(tmp_path, clk)
    st.append_events(APP, None, [_ev(0, 1001)])  # generation A
    clk.t = 1100.0
    st.seal_all()
    try:
        faults.install(f"{point}:error:1.0")
        with pytest.raises(FaultInjected):
            st.append_events(APP, None, [_ev(1, 1150)])
            clk.t = 1200.0
            st.seal_all()
    finally:
        faults.clear()
    st2 = _store(tmp_path, clk)
    st2._dir(APP, None)
    m = _manifest(tmp_path)
    assert [e["file"] for e in m["segments"]] == _seg_files(tmp_path)
    got = st2.read_window(APP, None, int(1000e6), 1 << 62)
    assert got is not None
    table, covered = got
    claimed = table.filter(
        pa.compute.less(table.column("event_time_us"), int(1100e6)))
    assert claimed.num_rows == 1  # generation A never lost or duplicated
    # and nothing beyond what the manifest claims is served
    assert covered <= m["activeStartUs"]


def test_disk_pressure_raises_before_write(tmp_path, clk):
    st = _store(tmp_path, clk, min_free_bytes=1 << 60)
    with pytest.raises(SegmentDiskPressure):
        st.append_events(APP, None, [_ev(0, 1001)])
    st2 = _store(tmp_path, clk, min_free_bytes=1)
    st2.append_events(APP, None, [_ev(0, 1001)])  # plenty free → fine


def test_resolve_segment_root_env(tmp_path, monkeypatch):
    monkeypatch.setenv("PIO_SEGMENT_DIR", str(tmp_path / "x"))
    assert resolve_segment_root() == tmp_path / "x"
    monkeypatch.setenv("PIO_SEGMENTS", "off")
    assert resolve_segment_root() is None
    monkeypatch.delenv("PIO_SEGMENTS")
    monkeypatch.delenv("PIO_SEGMENT_DIR")
    monkeypatch.setenv("PIO_HOME", str(tmp_path / "home"))
    assert resolve_segment_root() == tmp_path / "home" / "segments"


# --------------------------------------------------------------------------
# The delta read that rides it (WindowedEventStore)
# --------------------------------------------------------------------------


def test_windowed_delta_read_serves_covered_prefix_from_segments(
        pio_home, tmp_path, monkeypatch, clk):
    """End-to-end read path: primary store + teed segments.  The
    windowed read must return EXACTLY what a pure primary read returns —
    segment slice for the covered prefix, primary tail for the rest."""
    from predictionio_tpu.data.storage import App, get_storage
    from predictionio_tpu.data.store import EventStore, WindowedEventStore

    seg_root = tmp_path / "segs"
    monkeypatch.setenv("PIO_SEGMENT_DIR", str(seg_root))
    storage = get_storage()
    app_id = storage.get_apps().insert(App(id=None, name="segapp"))
    storage.get_events().init(app_id)
    covered = [_ev(i, 1001 + i) for i in range(10)]
    tail = [_ev(100 + i, 2010 + i) for i in range(3)]
    storage.get_events().insert_batch(covered + tail, app_id)
    # tee ONLY the covered prefix (the tail is "younger than the last
    # seal" — exactly the real server's steady state)
    st = _store(seg_root, clk)
    st.append_events(app_id, None, covered)
    clk.t = 2000.0
    st.seal_all()

    start = dt.datetime.fromtimestamp(1000, UTC)
    windowed = WindowedEventStore(storage, start, None)
    got = windowed.find_columnar("segapp")
    want = EventStore(storage).find_columnar("segapp", start_time=start)
    assert got.num_rows == want.num_rows == 13
    assert got.column("entity_id").to_pylist() == \
        want.column("entity_id").to_pylist()
    # prove the slice actually came from segments: poison the primary
    # window the segments cover and read again — identical rows
    sliced = windowed._segment_slice(
        "segapp", None, {"start_time": start, "until_time": None})
    assert sliced is not None and sliced[0].num_rows == 10

    # and with segments disabled the same read falls back cleanly
    monkeypatch.setenv("PIO_SEGMENTS", "off")
    fallback = WindowedEventStore(storage, start, None)
    tbl = fallback.find_columnar("segapp")
    assert tbl.num_rows == 13
