"""Sequence-parallel attention == full attention (exactness tests)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from predictionio_tpu.parallel.mesh import make_mesh
from predictionio_tpu.parallel.ring import (
    local_attention,
    ring_attention,
    ulysses_attention,
)


def _qkv(seed=0, b=2, s=32, h=4, d=8):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)  # noqa: E731
    return mk(), mk(), mk()


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({"sequence": 8})


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full(mesh, causal):
    q, k, v = _qkv()
    full = local_attention(q, k, v, causal=causal)
    ring = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(full),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full(mesh, causal):
    q, k, v = _qkv(seed=1, h=8)
    full = local_attention(q, k, v, causal=causal)
    uly = ulysses_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(uly), np.asarray(full),
                               rtol=2e-4, atol=2e-5)


def test_ring_grads_flow(mesh):
    q, k, v = _qkv(seed=2, s=16)
    mesh2 = make_mesh({"sequence": 8})

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh2) ** 2)

    def loss_full(q, k, v):
        return jnp.sum(local_attention(q, k, v) ** 2)

    g_ring = jax.grad(loss_ring)(q, k, v)
    g_full = jax.grad(loss_full)(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_full),
                               rtol=1e-3, atol=1e-4)


def test_causal_first_token_attends_self_only(mesh):
    q, k, v = _qkv(seed=3)
    out = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out[:, 0]), np.asarray(v[:, 0]),
                               rtol=1e-5, atol=1e-6)
