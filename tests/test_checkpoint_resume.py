"""Checkpoint/resume: crash mid-train → resume → identical final model.

The reference cannot do this (SURVEY.md §5.4: a killed `pio train` restarts
from scratch); this is the rebuild's fault-injection test (§5.3).
"""

import numpy as np
import pytest

from predictionio_tpu.models import two_tower as tt


def _data(seed=0, n_users=16, n_items=8):
    rng = np.random.default_rng(seed)
    users = rng.integers(0, n_users, 200)
    items = rng.integers(0, n_items, 200)
    return users, items


def _cfg(**kw):
    base = dict(n_users=16, n_items=8, embed_dim=8, hidden_dims=(16,),
                out_dim=8, batch_size=32, epochs=3, seed=7)
    base.update(kw)
    return tt.TwoTowerConfig(**base)


def test_uninterrupted_checkpointing_matches_plain(tmp_path):
    users, items = _data()
    cfg = _cfg()
    s_plain = tt.train(users, items, cfg)
    s_ckpt = tt.train(users, items, cfg, checkpoint_dir=tmp_path / "ck",
                      save_every=4)
    np.testing.assert_allclose(np.asarray(s_plain.params["user_embed"]),
                               np.asarray(s_ckpt.params["user_embed"]),
                               rtol=1e-6)


def test_crash_and_resume_equivalence(tmp_path, monkeypatch):
    users, items = _data(seed=1)
    cfg = _cfg(seed=9)
    expected = tt.train(users, items, cfg)

    # Fault injection: die after 9 train steps (mid-epoch-2).
    real_step = tt.train_step
    calls = {"n": 0}

    def dying_step(*args, **kw):
        calls["n"] += 1
        if calls["n"] > 9:
            raise RuntimeError("injected trainer crash")
        return real_step(*args, **kw)

    ck = tmp_path / "ck"
    monkeypatch.setattr(tt, "train_step", dying_step)
    with pytest.raises(RuntimeError, match="injected"):
        tt.train(users, items, cfg, checkpoint_dir=ck, save_every=3)
    monkeypatch.setattr(tt, "train_step", real_step)

    resumed = tt.train(users, items, cfg, checkpoint_dir=ck, save_every=3)
    np.testing.assert_allclose(np.asarray(expected.params["user_embed"]),
                               np.asarray(resumed.params["user_embed"]),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(np.asarray(expected.params["item_embed"]),
                               np.asarray(resumed.params["item_embed"]),
                               rtol=1e-6, atol=1e-7)
    assert int(resumed.step) == int(expected.step)


def test_completed_run_clears_checkpoints(tmp_path, monkeypatch):
    """Completion deletes the checkpoints, so a retrain actually retrains.

    Round-3 advisor (medium): leaving the final-step checkpoint behind made
    the next `pio train` over the same dir fast-forward past its whole loop
    and silently return the stale factors.
    """
    users, items = _data(seed=2)
    cfg = _cfg(seed=11)
    ck = tmp_path / "ck"
    first = tt.train(users, items, cfg, checkpoint_dir=ck, save_every=1)
    leftover = [p for p in ck.iterdir() if p.name.isdigit()]
    assert leftover == [], "completed run must clear its checkpoint steps"

    real_step = tt.train_step
    calls = {"n": 0}

    def counting_step(*args, **kw):
        calls["n"] += 1
        return real_step(*args, **kw)

    monkeypatch.setattr(tt, "train_step", counting_step)
    again = tt.train(users, items, cfg, checkpoint_dir=ck, save_every=1)
    assert calls["n"] > 0, "retrain over a completed dir must actually train"
    np.testing.assert_allclose(np.asarray(first.params["user_embed"]),
                               np.asarray(again.params["user_embed"]),
                               rtol=1e-7)


def test_fingerprint_mismatch_discards_stale_checkpoints(tmp_path, monkeypatch):
    """Checkpoints from a different config/data are discarded, not resumed."""
    from predictionio_tpu.models import als as als_lib

    rng = np.random.default_rng(3)
    users = rng.integers(0, 40, 1200)
    items = (rng.zipf(1.4, 1200) % 30).astype(np.int64)
    ratings = rng.integers(1, 6, 1200).astype(np.float32)
    ck = tmp_path / "als"

    cfg_a = als_lib.ALSConfig(rank=8, iterations=8, reg=0.05, seed=4,
                              split_above=64)
    real_loop = als_lib._train_loop
    calls = {"n": 0}

    def dying_loop(*args, **kw):
        calls["n"] += 1
        if calls["n"] > 2:
            raise RuntimeError("injected ALS crash")
        return real_loop(*args, **kw)

    monkeypatch.setattr(als_lib, "_train_loop", dying_loop)
    with pytest.raises(RuntimeError, match="injected"):
        als_lib.train_als(users, items, ratings, 40, 30, cfg_a,
                          checkpoint_dir=ck, save_every=2)
    monkeypatch.setattr(als_lib, "_train_loop", real_loop)

    # Retrain with a DIFFERENT config over the same dir: the mid-train
    # checkpoints above must not leak into this run.
    cfg_b = als_lib.ALSConfig(rank=8, iterations=6, reg=0.2, seed=5,
                              split_above=64)
    plain = als_lib.train_als(users, items, ratings, 40, 30, cfg_b)
    resumed = als_lib.train_als(users, items, ratings, 40, 30, cfg_b,
                                checkpoint_dir=ck, save_every=2)
    np.testing.assert_array_equal(np.asarray(plain.user_factors),
                                  np.asarray(resumed.user_factors))


class TestALSResume:
    """Round-2 verdict item 5: the north-star engine must survive a kill."""

    def _coo(self):
        rng = np.random.default_rng(3)
        users = rng.integers(0, 40, 1200)
        items = (rng.zipf(1.4, 1200) % 30).astype(np.int64)
        ratings = rng.integers(1, 6, 1200).astype(np.float32)
        return users, items, ratings

    def test_chunked_sweeps_bitwise_equal_to_plain(self, tmp_path):
        from predictionio_tpu.models import als as als_lib

        users, items, ratings = self._coo()
        cfg = als_lib.ALSConfig(rank=8, iterations=7, reg=0.05, seed=4,
                                split_above=64)
        plain = als_lib.train_als(users, items, ratings, 40, 30, cfg)
        ck = als_lib.train_als(users, items, ratings, 40, 30, cfg,
                               checkpoint_dir=tmp_path / "als", save_every=2)
        np.testing.assert_array_equal(np.asarray(plain.user_factors),
                                      np.asarray(ck.user_factors))
        np.testing.assert_array_equal(np.asarray(plain.item_factors),
                                      np.asarray(ck.item_factors))

    def test_killed_train_resumes_bitwise(self, tmp_path, monkeypatch):
        from predictionio_tpu.models import als as als_lib

        users, items, ratings = self._coo()
        cfg = als_lib.ALSConfig(rank=8, iterations=8, reg=0.05, seed=4,
                                split_above=64)
        expected = als_lib.train_als(users, items, ratings, 40, 30, cfg)

        real_loop = als_lib._train_loop
        calls = {"n": 0}

        def dying_loop(*args, **kw):
            calls["n"] += 1
            if calls["n"] > 2:  # die after 2 chunks (4 of 8 sweeps saved)
                raise RuntimeError("injected ALS crash")
            return real_loop(*args, **kw)

        ck = tmp_path / "als"
        monkeypatch.setattr(als_lib, "_train_loop", dying_loop)
        with pytest.raises(RuntimeError, match="injected"):
            als_lib.train_als(users, items, ratings, 40, 30, cfg,
                              checkpoint_dir=ck, save_every=2)
        monkeypatch.setattr(als_lib, "_train_loop", real_loop)
        resumed = als_lib.train_als(users, items, ratings, 40, 30, cfg,
                                    checkpoint_dir=ck, save_every=2)
        np.testing.assert_array_equal(np.asarray(expected.user_factors),
                                      np.asarray(resumed.user_factors))
        np.testing.assert_array_equal(np.asarray(expected.item_factors),
                                      np.asarray(resumed.item_factors))


class TestDLRMResume:
    def _data(self):
        rng = np.random.default_rng(5)
        n = 400
        dense = rng.random((n, 4), np.float32)
        cat = np.stack([rng.integers(0, 20, n),
                        rng.integers(0, 10, n)], axis=1)
        labels = rng.integers(0, 2, n).astype(np.float32)
        return dense, cat, labels

    def test_killed_train_resumes_to_same_params(self, tmp_path, monkeypatch):
        from predictionio_tpu.models import dlrm as dlrm_lib

        dense, cat, labels = self._data()
        cfg = dlrm_lib.DLRMConfig(
            vocab_sizes=(20, 10), n_dense=4, embed_dim=8,
            bottom_mlp=(16, 8), top_mlp=(16, 8),
            batch_size=64, epochs=2, seed=6)
        expected = dlrm_lib.train(dense, cat, labels, cfg)

        real_step = dlrm_lib.train_step
        calls = {"n": 0}

        def dying_step(*args, **kw):
            calls["n"] += 1
            if calls["n"] > 7:
                raise RuntimeError("injected DLRM crash")
            return real_step(*args, **kw)

        ck = tmp_path / "dlrm"
        monkeypatch.setattr(dlrm_lib, "train_step", dying_step)
        with pytest.raises(RuntimeError, match="injected"):
            dlrm_lib.train(dense, cat, labels, cfg, checkpoint_dir=ck,
                           save_every=3)
        monkeypatch.setattr(dlrm_lib, "train_step", real_step)
        resumed = dlrm_lib.train(dense, cat, labels, cfg, checkpoint_dir=ck,
                                 save_every=3)
        import jax

        for e_leaf, r_leaf in zip(jax.tree_util.tree_leaves(expected.params),
                                  jax.tree_util.tree_leaves(resumed.params)):
            np.testing.assert_allclose(np.asarray(e_leaf),
                                       np.asarray(r_leaf),
                                       rtol=1e-6, atol=1e-7)
