"""Checkpoint/resume: crash mid-train → resume → identical final model.

The reference cannot do this (SURVEY.md §5.4: a killed `pio train` restarts
from scratch); this is the rebuild's fault-injection test (§5.3).
"""

import numpy as np
import pytest

from predictionio_tpu.models import two_tower as tt


def _data(seed=0, n_users=16, n_items=8):
    rng = np.random.default_rng(seed)
    users = rng.integers(0, n_users, 200)
    items = rng.integers(0, n_items, 200)
    return users, items


def _cfg(**kw):
    base = dict(n_users=16, n_items=8, embed_dim=8, hidden_dims=(16,),
                out_dim=8, batch_size=32, epochs=3, seed=7)
    base.update(kw)
    return tt.TwoTowerConfig(**base)


def test_uninterrupted_checkpointing_matches_plain(tmp_path):
    users, items = _data()
    cfg = _cfg()
    s_plain = tt.train(users, items, cfg)
    s_ckpt = tt.train(users, items, cfg, checkpoint_dir=tmp_path / "ck",
                      save_every=4)
    np.testing.assert_allclose(np.asarray(s_plain.params["user_embed"]),
                               np.asarray(s_ckpt.params["user_embed"]),
                               rtol=1e-6)


def test_crash_and_resume_equivalence(tmp_path, monkeypatch):
    users, items = _data(seed=1)
    cfg = _cfg(seed=9)
    expected = tt.train(users, items, cfg)

    # Fault injection: die after 9 train steps (mid-epoch-2).
    real_step = tt.train_step
    calls = {"n": 0}

    def dying_step(*args, **kw):
        calls["n"] += 1
        if calls["n"] > 9:
            raise RuntimeError("injected trainer crash")
        return real_step(*args, **kw)

    ck = tmp_path / "ck"
    monkeypatch.setattr(tt, "train_step", dying_step)
    with pytest.raises(RuntimeError, match="injected"):
        tt.train(users, items, cfg, checkpoint_dir=ck, save_every=3)
    monkeypatch.setattr(tt, "train_step", real_step)

    resumed = tt.train(users, items, cfg, checkpoint_dir=ck, save_every=3)
    np.testing.assert_allclose(np.asarray(expected.params["user_embed"]),
                               np.asarray(resumed.params["user_embed"]),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(np.asarray(expected.params["item_embed"]),
                               np.asarray(resumed.params["item_embed"]),
                               rtol=1e-6, atol=1e-7)
    assert int(resumed.step) == int(expected.step)


def test_resume_skips_completed_work(tmp_path):
    """A finished run's checkpoint makes a re-run a no-op fast-forward."""
    users, items = _data(seed=2)
    cfg = _cfg(seed=11)
    first = tt.train(users, items, cfg, checkpoint_dir=tmp_path / "ck",
                     save_every=1)
    again = tt.train(users, items, cfg, checkpoint_dir=tmp_path / "ck",
                     save_every=1)
    np.testing.assert_allclose(np.asarray(first.params["user_embed"]),
                               np.asarray(again.params["user_embed"]),
                               rtol=1e-7)
