"""Pallas fused gram kernel == einsum oracle (interpret mode on CPU)."""

import numpy as np
import jax.numpy as jnp

from predictionio_tpu.ops.pallas_kernels import (
    fused_gram_vector,
    fused_gram_vector_pallas,
    fused_gram_vector_xla,
)


def _inputs(seed=0, r=6, l=16, k=8):
    rng = np.random.default_rng(seed)
    f = rng.standard_normal((r, l, k)).astype(np.float32)
    w = np.abs(rng.standard_normal((r, l))).astype(np.float32)
    c = rng.standard_normal((r, l)).astype(np.float32)
    return jnp.asarray(f), jnp.asarray(w), jnp.asarray(c)


def test_pallas_matches_einsum():
    f, w, c = _inputs()
    a1, b1 = fused_gram_vector_xla(f, w, c)
    a2, b2 = fused_gram_vector_pallas(f, w, c, interpret=True)
    np.testing.assert_allclose(np.asarray(a1), np.asarray(a2),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(b1), np.asarray(b2),
                               rtol=1e-5, atol=1e-5)


def test_matches_numpy_oracle():
    f, w, c = _inputs(seed=1, r=3, l=5, k=4)
    a, b = fused_gram_vector_pallas(f, w, c, interpret=True)
    fn, wn, cn = map(np.asarray, (f, w, c))
    for r in range(3):
        expect_a = (fn[r] * wn[r][:, None]).T @ fn[r]
        expect_b = fn[r].T @ cn[r]
        np.testing.assert_allclose(np.asarray(a[r]), expect_a, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(b[r]), expect_b, rtol=1e-5)


def test_dispatcher_cpu_path():
    f, w, c = _inputs(seed=2)
    a, b = fused_gram_vector(f, w, c)  # auto: einsum on CPU
    a2, b2 = fused_gram_vector_xla(f, w, c)
    np.testing.assert_allclose(np.asarray(a), np.asarray(a2), rtol=1e-6)


def test_gj_ridge_solve_matches_numpy():
    """Gauss-Jordan batched solve == numpy direct solve (interpret mode)."""
    from predictionio_tpu.ops.pallas_kernels import ridge_solve_gj_pallas

    rng = np.random.default_rng(3)
    B, K = 5, 8
    y = rng.standard_normal((B, K + 3, K)).astype(np.float32)
    a = np.einsum("blk,blm->bkm", y, y)
    b = rng.standard_normal((B, K)).astype(np.float32)
    reg = np.abs(rng.standard_normal(B)).astype(np.float32) + 0.5
    x = ridge_solve_gj_pallas(jnp.asarray(a), jnp.asarray(b),
                              jnp.asarray(reg), interpret=True)
    want = np.stack([np.linalg.solve(a[i] + reg[i] * np.eye(K), b[i])
                     for i in range(B)])
    np.testing.assert_allclose(np.asarray(x), want, rtol=2e-4, atol=2e-4)


def test_gj_solver_in_train_als():
    """solver="gj" end-to-end (interpret) == cholesky path."""
    from predictionio_tpu.models.als import ALSConfig, train_als

    rng = np.random.default_rng(5)
    users = rng.integers(0, 12, 60)
    items = rng.integers(0, 9, 60)
    ratings = rng.integers(1, 6, 60).astype(np.float32)
    base = dict(rank=4, iterations=2, reg=0.1, seed=2, gram_dtype="float32")
    m_ch = train_als(users, items, ratings, 12, 9,
                     ALSConfig(**base, solver="cholesky"))
    m_gj = train_als(users, items, ratings, 12, 9,
                     ALSConfig(**base, solver="gj"))
    np.testing.assert_allclose(np.asarray(m_ch.user_factors),
                               np.asarray(m_gj.user_factors),
                               rtol=1e-3, atol=1e-3)


def test_ridge_solve_lu_matches_oracle():
    """Shrinking-elimination solver (the TPU auto path) vs numpy."""
    import numpy as np
    import jax.numpy as jnp

    from predictionio_tpu.ops.pallas_kernels import ridge_solve_lu_pallas

    rng = np.random.default_rng(3)
    B, K = 67, 32
    M = rng.standard_normal((B, K, K)).astype(np.float32)
    A = M @ M.transpose(0, 2, 1) + 2 * np.eye(K, dtype=np.float32)
    b = rng.standard_normal((B, K)).astype(np.float32)
    reg = rng.random(B).astype(np.float32) + 0.1
    x = np.asarray(ridge_solve_lu_pallas(
        jnp.asarray(A), jnp.asarray(b), jnp.asarray(reg), interpret=True))
    ref = np.stack([np.linalg.solve(A[i] + reg[i] * np.eye(K), b[i])
                    for i in range(B)])
    np.testing.assert_allclose(x, ref, rtol=2e-4, atol=2e-4)


# -- fused corpus-score + running top-K (ISSUE 8) ----------------------------


def _topk_inputs(b=3, n=700, d=16, seed=4):
    rng = np.random.default_rng(seed)
    q = rng.standard_normal((b, d)).astype(np.float32)
    items = rng.standard_normal((n, d)).astype(np.float32)
    return q, items


def _oracle_ids(q, items, k):
    return np.argsort(-(q @ items.T), axis=1, kind="stable")[:, :k]


def test_fused_topk_kernel_matches_oracle():
    """Interpret-mode kernel vs numpy: same id SET and sorted scores
    (tie order may differ from lax.top_k — documented contract)."""
    from predictionio_tpu.ops.pallas_kernels import fused_topk_pallas

    q, items = _topk_inputs()
    s, i = fused_topk_pallas(jnp.asarray(q), jnp.asarray(items), 10,
                             tile=256, interpret=True)
    s, i = np.asarray(s), np.asarray(i)
    want = _oracle_ids(q, items, 10)
    np.testing.assert_array_equal(np.sort(i, axis=1),
                                  np.sort(want, axis=1))
    np.testing.assert_allclose(
        s, np.take_along_axis(q @ items.T, want, axis=1), rtol=1e-5)
    assert (np.diff(s, axis=1) <= 1e-6).all()  # sorted descending


def test_fused_topk_kernel_tail_tile_and_n_valid():
    """A corpus that does not divide the tile reads an OOB-padded tail
    block; n_valid additionally masks trailing padding rows — neither
    may ever win a slot."""
    from predictionio_tpu.ops.pallas_kernels import fused_topk_pallas

    q, items = _topk_inputs(n=600)
    items[500:] = 50.0  # poison rows past n_valid
    s, i = fused_topk_pallas(jnp.asarray(q), jnp.asarray(items), 8,
                             tile=256, n_valid=500, interpret=True)
    i = np.asarray(i)
    assert int(i.max()) < 500
    want = _oracle_ids(q, items[:500], 8)
    np.testing.assert_array_equal(np.sort(i, axis=1),
                                  np.sort(want, axis=1))


def test_fused_topk_dispatcher_cpu_falls_back_to_chunked():
    from predictionio_tpu.ops.pallas_kernels import fused_topk

    q, items = _topk_inputs(n=300)
    s, i = fused_topk(jnp.asarray(q), jnp.asarray(items), 7)
    want = _oracle_ids(q, items, 7)
    np.testing.assert_array_equal(np.sort(np.asarray(i), axis=1),
                                  np.sort(want, axis=1))
    # k=0 / k>n edge behavior mirrors the facade contract
    s0, i0 = fused_topk(jnp.asarray(q), jnp.asarray(items), 0)
    assert s0.shape == (3, 0) and i0.shape == (3, 0)
