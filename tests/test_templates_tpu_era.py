"""TPU-era templates: two-tower retrieval + DLRM CTR ranking, end-to-end."""

import numpy as np
import pytest

from predictionio_tpu.controller import EngineVariant, RuntimeContext
from predictionio_tpu.data.event import DataMap, Event
from predictionio_tpu.data.storage import App, get_storage
from predictionio_tpu.workflow.core_workflow import load_models, run_train


@pytest.fixture()
def ctx(pio_home):
    return RuntimeContext.create(storage=get_storage())


def _seed_views(ctx, n_users=24, n_items=12, seed=0):
    storage = ctx.storage
    app_id = storage.get_apps().insert(App(id=None, name="testapp"))
    storage.get_events().init(app_id)
    rng = np.random.default_rng(seed)
    ev = storage.get_events()
    for u in range(n_users):
        pool = [i for i in range(n_items) if i % 2 == u % 2]
        for i in rng.choice(pool, size=6, replace=True):
            ev.insert(Event(event="view", entity_type="user", entity_id=f"u{u}",
                            target_entity_type="item", target_entity_id=f"i{i}"),
                      app_id)
    return app_id


class TestTwoTowerTemplate:
    VARIANT = {
        "engineFactory": "predictionio_tpu.templates.twotower:engine",
        "datasource": {"params": {"appName": "testapp"}},
        "algorithms": [{"name": "twotower",
                        "params": {"embedDim": 16, "hiddenDims": [32],
                                   "outDim": 16, "epochs": 60,
                                   "learningRate": 0.003, "batchSize": 64,
                                   "seed": 1}}],
    }

    def test_train_and_predict(self, ctx):
        from predictionio_tpu.templates.twotower import Query, engine

        _seed_views(ctx)
        eng = engine()
        variant = EngineVariant.from_dict(self.VARIANT)
        iid = run_train(eng, variant, ctx)
        inst = ctx.storage.get_engine_instances().get(iid)
        assert inst.status == "COMPLETED"
        models = load_models(eng, inst, ctx)
        algo = eng.make_algorithms(eng.bind_engine_params(self.VARIANT))[0]
        res = algo.predict(models[0], Query(user="u0", num=5))
        assert len(res.itemScores) == 5
        even = sum(1 for s in res.itemScores if int(s.item[1:]) % 2 == 0)
        assert even >= 4
        assert algo.predict(models[0], Query(user="ghost")).itemScores == []

    def test_batch_predict_matches_per_query(self, ctx):
        """ISSUE 6: the vectorized serving path (one top_k_scores for the
        whole cohort, pow2-padded) must agree with predict() per query —
        unknown users included — so the micro-batcher changes latency,
        never answers."""
        import numpy as np

        from predictionio_tpu.templates.twotower import Query, engine

        _seed_views(ctx)
        eng = engine()
        variant = EngineVariant.from_dict(self.VARIANT)
        inst = ctx.storage.get_engine_instances().get(
            run_train(eng, variant, ctx))
        models = load_models(eng, inst, ctx)
        algo = eng.make_algorithms(eng.bind_engine_params(self.VARIANT))[0]
        queries = [Query(user="u0", num=5), Query(user="ghost", num=3),
                   Query(user="u1", num=2), Query(user="u2", num=12)]
        batched = dict(algo.batch_predict(models[0],
                                          list(enumerate(queries))))
        for i, q in enumerate(queries):
            single = algo.predict(models[0], q)
            assert [s.item for s in batched[i].itemScores] == \
                [s.item for s in single.itemScores]
            assert np.allclose(
                [s.score for s in batched[i].itemScores],
                [s.score for s in single.itemScores], atol=1e-5)


class TestDLRMTemplate:
    VARIANT = {
        "engineFactory": "predictionio_tpu.templates.dlrm:engine",
        "datasource": {"params": {"appName": "testapp", "nDense": 2,
                                  "userVocab": 128, "itemVocab": 64}},
        "algorithms": [{"name": "dlrm",
                        "params": {"embedDim": 8, "bottomMlp": [16, 8],
                                   "topMlp": [16], "epochs": 8,
                                   "batchSize": 128, "userVocab": 128,
                                   "itemVocab": 64, "seed": 2}}],
    }

    def _seed_impressions(self, ctx, n=600, seed=0):
        storage = ctx.storage
        app_id = storage.get_apps().insert(App(id=None, name="testapp"))
        storage.get_events().init(app_id)
        rng = np.random.default_rng(seed)
        ev = storage.get_events()
        for _ in range(n):
            u = rng.integers(0, 20)
            i = rng.integers(0, 10)
            # Even items get clicked far more often.
            p = 0.8 if i % 2 == 0 else 0.1
            ev.insert(
                Event(event="impression", entity_type="user", entity_id=f"u{u}",
                      target_entity_type="item", target_entity_id=f"i{i}",
                      properties=DataMap({
                          "clicked": bool(rng.random() < p),
                          "dense": [float(rng.random()), 1.0]})),
                app_id)
        return app_id

    def test_train_and_rank(self, ctx):
        from predictionio_tpu.templates.dlrm import Query, engine

        self._seed_impressions(ctx)
        eng = engine()
        variant = EngineVariant.from_dict(self.VARIANT)
        iid = run_train(eng, variant, ctx)
        inst = ctx.storage.get_engine_instances().get(iid)
        assert inst.status == "COMPLETED"
        models = load_models(eng, inst, ctx)
        algo = eng.make_algorithms(eng.bind_engine_params(self.VARIANT))[0]
        res = algo.predict(models[0], Query(
            user="u0", items=["i0", "i1", "i2", "i3"], dense=[0.5, 1.0]))
        assert len(res.itemScores) == 4
        scores = {s.item: s.score for s in res.itemScores}
        # Clicky (even) items outrank sticky (odd) ones.
        assert (scores["i0"] + scores["i2"]) / 2 > (scores["i1"] + scores["i3"]) / 2
        # Ranked descending.
        vals = [s.score for s in res.itemScores]
        assert vals == sorted(vals, reverse=True)
