"""Admin REST server (reference: tools/admin, pio adminserver)."""

import json
import urllib.error
import urllib.request

import pytest

from predictionio_tpu.data.storage import get_storage
from predictionio_tpu.server.admin import AdminServer


@pytest.fixture()
def admin(pio_home):
    srv = AdminServer(storage=get_storage(), host="127.0.0.1", port=0)
    srv.start()
    yield srv
    srv.stop()


def _req(method, url, body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read() or b"null")
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, json.loads(payload) if payload else None


def test_app_crud(admin):
    base = f"http://127.0.0.1:{admin.port}"
    s, body = _req("POST", f"{base}/v1/cmd/app", {"name": "a1"})
    assert s == 201 and body["accessKey"]
    s, body = _req("POST", f"{base}/v1/cmd/app", {"name": "a1"})
    assert s == 409
    s, apps = _req("GET", f"{base}/v1/cmd/app")
    assert s == 200 and apps[0]["name"] == "a1" and apps[0]["accessKeys"]
    s, _ = _req("DELETE", f"{base}/v1/cmd/app/a1/data")
    assert s == 200
    s, _ = _req("DELETE", f"{base}/v1/cmd/app/a1")
    assert s == 200
    s, apps = _req("GET", f"{base}/v1/cmd/app")
    assert apps == []
    s, _ = _req("DELETE", f"{base}/v1/cmd/app/ghost")
    assert s == 404


# -- on-demand profiler capture (obs.profiler) ------------------------------

@pytest.fixture()
def fake_profiler():
    """Swap in an injectable ProfilerSession; yields a mutable backend
    spec the test can point at success/failure behaviors."""
    from predictionio_tpu.obs import profiler as profiler_mod

    calls = {"started": [], "stopped": 0, "fail": None}

    def start_fn(path):
        if calls["fail"] is not None:
            raise calls["fail"]
        calls["started"].append(path)

    def stop_fn():
        calls["stopped"] += 1

    class _NoopTimer:
        def __init__(self, *a, **k):
            self.daemon = True

        def start(self):
            pass

        def cancel(self):
            pass

    session = profiler_mod.ProfilerSession(
        start_fn=start_fn, stop_fn=stop_fn,
        timer_factory=lambda *a, **k: _NoopTimer())
    prev = profiler_mod.set_profiler(session)
    yield calls
    profiler_mod.set_profiler(prev)


@pytest.mark.profiling
def test_profile_degrades_to_501_when_platform_cannot_capture(
        admin, fake_profiler):
    """The tier-1-safe smoke: an uncapturable platform answers a clear
    501, never a crash/500 — and arms nothing."""
    from predictionio_tpu.obs.profiler import ProfilerUnavailable

    fake_profiler["fail"] = ProfilerUnavailable("no profiler plugin here")
    base = f"http://127.0.0.1:{admin.port}"
    s, body = _req("POST", f"{base}/admin/profile?duration_ms=50")
    assert s == 501
    assert "profiler capture unavailable" in body["message"]
    assert fake_profiler["started"] == []
    # and the session is NOT stuck busy after the failure
    s, body = _req("GET", f"{base}/admin/profile")
    assert s == 200 and body["active"] is False


@pytest.mark.profiling
def test_profile_capture_roundtrip_and_busy(admin, fake_profiler,
                                            tmp_path):
    from predictionio_tpu.obs.profiler import get_profiler

    base = f"http://127.0.0.1:{admin.port}"
    out = str(tmp_path / "prof")
    s, body = _req("POST",
                   f"{base}/admin/profile?duration_ms=1000&out={out}")
    assert s == 200 and body["status"] == "profiling"
    assert body["path"] == out
    assert fake_profiler["started"] == [out]
    s, body = _req("GET", f"{base}/admin/profile")
    assert s == 200 and body["active"] is True
    # second capture while armed: 409, not a second start
    s, body = _req("POST", f"{base}/admin/profile?duration_ms=1000")
    assert s == 409
    assert len(fake_profiler["started"]) == 1
    # manual stop (the timer is a no-op fake) finishes the session
    assert get_profiler().stop() == out
    assert fake_profiler["stopped"] == 1
    s, body = _req("GET", f"{base}/admin/profile")
    assert s == 200 and body["active"] is False and body["lastPath"] == out


@pytest.mark.profiling
def test_profile_rejects_bad_duration(admin, fake_profiler):
    base = f"http://127.0.0.1:{admin.port}"
    for bad in ("abc", "-5", "0"):
        s, body = _req("POST", f"{base}/admin/profile?duration_ms={bad}")
        assert s == 400, bad
    assert fake_profiler["started"] == []


def test_timeline_endpoint_on_admin(admin):
    from predictionio_tpu.obs import get_timeline

    get_timeline().record("toy", host_wait_ms=1.0, h2d_ms=2.0,
                          device_wait_ms=3.0, device_step_ms=4.0)
    base = f"http://127.0.0.1:{admin.port}"
    s, body = _req("GET", f"{base}/timeline.json")
    assert s == 200
    assert body["steps"][0]["model"] == "toy"
    assert body["models"]["toy"]["steps"] == 1
    s, chrome = _req("GET", f"{base}/timeline.json?format=chrome")
    assert s == 200 and any(e["ph"] == "X" for e in chrome["traceEvents"])


# -- profiler artifact download (ISSUE 9 satellite) -------------------------

def _get_raw(url):
    req = urllib.request.Request(url, method="GET")
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


@pytest.mark.profiling
def test_profile_artifact_download_roundtrip(admin, fake_profiler,
                                             tmp_path):
    """The capture path is SERVER-local; GET /admin/profile/artifact
    ships it as a tar.gz so fleet operators never need box access."""
    import io
    import tarfile

    from predictionio_tpu.obs.profiler import get_profiler

    base = f"http://127.0.0.1:{admin.port}"
    # nothing captured yet: a clear 404, not a crash
    s, _, body = _get_raw(f"{base}/admin/profile/artifact")
    assert s == 404 and b"no finished" in body
    out = tmp_path / "prof"
    out.mkdir()
    (out / "trace.json.gz").write_bytes(b"fake-xplane-bytes")
    s, _ = _req("POST", f"{base}/admin/profile?duration_ms=1000&out={out}")
    assert s == 200
    # while the capture is running the archive is still being written
    s, _, _ = _get_raw(f"{base}/admin/profile/artifact")
    assert s == 409
    assert get_profiler().stop() == str(out)
    s, headers, data = _get_raw(f"{base}/admin/profile/artifact")
    assert s == 200
    assert headers["Content-Type"] == "application/gzip"
    with tarfile.open(fileobj=io.BytesIO(data), mode="r:gz") as tar:
        names = tar.getnames()
        member = tar.extractfile("prof/trace.json.gz")
        assert member.read() == b"fake-xplane-bytes"
    assert "prof" in names


@pytest.mark.profiling
def test_pio_profile_out_downloads_the_archive(admin, fake_profiler,
                                               tmp_path, monkeypatch):
    """`pio profile --url ... --out FILE` waits the window out and pulls
    the archive down over HTTP (no server-box access)."""
    import argparse
    import tarfile

    from predictionio_tpu.cli.main import cmd_profile
    from predictionio_tpu.obs.profiler import get_profiler

    capture_dir = tmp_path / "cap"
    capture_dir.mkdir()
    (capture_dir / "xplane.pb").write_bytes(b"pb")
    monkeypatch.setenv("PIO_PROFILE_OUT", str(capture_dir))

    # finish the session the moment the CLI polls it: the no-op fake
    # timer never fires, so stop() here plays the role of the window
    # closing on the server.
    import threading

    def _stop_soon():
        get_profiler().stop()

    t = threading.Timer(0.1, _stop_soon)
    t.start()
    dest = tmp_path / "got.tar.gz"
    args = argparse.Namespace(duration_ms=10,
                              url=f"http://127.0.0.1:{admin.port}",
                              out=str(dest))
    try:
        assert cmd_profile(args) == 0
    finally:
        t.cancel()
    assert dest.exists()
    with tarfile.open(dest, mode="r:gz") as tar:
        assert any(n.endswith("xplane.pb") for n in tar.getnames())
