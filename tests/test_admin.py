"""Admin REST server (reference: tools/admin, pio adminserver)."""

import json
import urllib.error
import urllib.request

import pytest

from predictionio_tpu.data.storage import get_storage
from predictionio_tpu.server.admin import AdminServer


@pytest.fixture()
def admin(pio_home):
    srv = AdminServer(storage=get_storage(), host="127.0.0.1", port=0)
    srv.start()
    yield srv
    srv.stop()


def _req(method, url, body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read() or b"null")
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, json.loads(payload) if payload else None


def test_app_crud(admin):
    base = f"http://127.0.0.1:{admin.port}"
    s, body = _req("POST", f"{base}/v1/cmd/app", {"name": "a1"})
    assert s == 201 and body["accessKey"]
    s, body = _req("POST", f"{base}/v1/cmd/app", {"name": "a1"})
    assert s == 409
    s, apps = _req("GET", f"{base}/v1/cmd/app")
    assert s == 200 and apps[0]["name"] == "a1" and apps[0]["accessKeys"]
    s, _ = _req("DELETE", f"{base}/v1/cmd/app/a1/data")
    assert s == 200
    s, _ = _req("DELETE", f"{base}/v1/cmd/app/a1")
    assert s == 200
    s, apps = _req("GET", f"{base}/v1/cmd/app")
    assert apps == []
    s, _ = _req("DELETE", f"{base}/v1/cmd/app/ghost")
    assert s == 404
