"""CLI verb tests (reference: tools Console verb dispatch, SURVEY.md §2.1)."""

import json

import pytest

from predictionio_tpu.cli.main import main


@pytest.fixture()
def clean_storage(pio_home):
    from predictionio_tpu.data.storage import reset_storage

    reset_storage()
    yield pio_home
    reset_storage()


def run(capsys, *argv):
    code = main(list(argv))
    out = capsys.readouterr().out
    return code, out


def test_app_lifecycle(clean_storage, capsys):
    code, out = run(capsys, "app", "new", "myapp")
    assert code == 0 and "Access Key:" in out
    code, out = run(capsys, "app", "list")
    assert "myapp" in out
    code, out = run(capsys, "accesskey", "new", "myapp", "view", "buy")
    assert code == 0 and "restricted" in out
    code, out = run(capsys, "app", "channel-new", "myapp", "live")
    assert code == 0
    with pytest.raises(SystemExit):
        run(capsys, "app", "channel-new", "myapp", "bad name!")
    code, out = run(capsys, "app", "delete", "myapp", "-f")
    assert code == 0


def test_import_export_roundtrip(clean_storage, capsys, tmp_path):
    run(capsys, "app", "new", "impapp")
    src = tmp_path / "events.ndjson"
    src.write_text(
        "\n".join(
            json.dumps(
                {"event": "rate", "entityType": "user", "entityId": f"u{i}",
                 "targetEntityType": "item", "targetEntityId": "i1",
                 "properties": {"rating": float(i)},
                 "eventTime": f"2026-01-0{i+1}T00:00:00Z"}
            )
            for i in range(3)
        )
    )
    code, out = run(capsys, "import", "--appid", "1", "--input", str(src))
    assert code == 0 and "Imported 3 events" in out
    dst = tmp_path / "out.ndjson"
    code, out = run(capsys, "export", "--appid", "1", "--output", str(dst))
    assert code == 0 and "Exported 3 events" in out
    lines = [json.loads(l) for l in dst.read_text().splitlines()]
    assert [l["entityId"] for l in lines] == ["u0", "u1", "u2"]
    assert lines[0]["properties"]["rating"] == 0.0


def test_import_resume_from_line(clean_storage, capsys, tmp_path,
                                 monkeypatch):
    """A parse error mid-file leaves earlier CHUNK-boundary commits in
    the store and reports the exact resume point; re-running with the
    reported --from-line imports the rest WITHOUT duplicating the
    committed prefix."""
    import re

    from predictionio_tpu.cli import main as cli_main

    monkeypatch.setattr(cli_main, "IMPORT_CHUNK", 2)
    run(capsys, "app", "new", "resapp")
    src = tmp_path / "events.ndjson"
    good = [json.dumps({"event": "rate", "entityType": "user",
                        "entityId": f"u{i}"}) for i in range(5)]
    # lines 1-2 commit as one chunk; line 3 is malformed
    src.write_text("\n".join(good[:2] + ["NOT JSON"] + good[2:]))
    with pytest.raises(SystemExit):
        run(capsys, "import", "--appid", "1", "--input", str(src))
    err = capsys.readouterr().err
    assert "2 event(s) up to line 2 were already imported" in err
    m = re.search(r"--from-line (\d+)", err)
    assert m and m.group(1) == "3"
    # fix the bad line IN PLACE and re-run with the reported resume point
    src.write_text("\n".join(good[:2] + [good[4]] + good[2:]))
    code, out = run(capsys, "import", "--appid", "1", "--input",
                    str(src), "--from-line", m.group(1))
    assert code == 0 and "Imported 4 events" in out
    dst = tmp_path / "out.ndjson"
    run(capsys, "export", "--appid", "1", "--output", str(dst))
    ids = [json.loads(l)["entityId"] for l in dst.read_text().splitlines()]
    # 2 committed before the error + 4 on resume, no duplicates of u0/u1
    assert sorted(ids) == ["u0", "u1", "u2", "u3", "u4", "u4"]


def test_train_via_cli(clean_storage, capsys, tmp_path):
    variant = tmp_path / "engine.json"
    variant.write_text(json.dumps({
        "engineFactory": "tests.test_controller_workflow:fake_engine",
        "datasource": {"params": {"n": 4}},
        "algorithms": [{"name": "mul", "params": {"factor": 2}}],
    }))
    code, out = run(capsys, "train", "--engine-json", str(variant))
    assert code == 0 and "Training completed" in out


def test_status(clean_storage, capsys):
    code, out = run(capsys, "status")
    assert code == 0
    assert "METADATA" in out and "sanity check OK" in out


def test_bad_engine_json(clean_storage, capsys):
    with pytest.raises(SystemExit):
        run(capsys, "train", "--engine-json", "/nonexistent/engine.json")


def test_build_validates_engine_json(clean_storage, capsys, tmp_path):
    ej = tmp_path / "engine.json"
    ej.write_text(json.dumps({
        "engineFactory": "predictionio_tpu.templates.recommendation:engine",
        "datasource": {"params": {"appName": "x"}},
        "algorithms": [{"name": "als", "params": {"rank": 4}}],
    }))
    code, out = run(capsys, "build", "--engine-json", str(ej))
    assert code == 0 and "Build successful" in out


def test_build_rejects_bad_params(clean_storage, capsys, tmp_path):
    ej = tmp_path / "engine.json"
    ej.write_text(json.dumps({
        "engineFactory": "predictionio_tpu.templates.recommendation:engine",
        "datasource": {"params": {"appName": "x"}},
        "algorithms": [{"name": "als", "params": {"rankk": 4}}],
    }))
    code, _ = run(capsys, "build", "--engine-json", str(ej))
    assert code == 1


def test_channel_lifecycle(clean_storage, capsys):
    run(capsys, "app", "new", "capp")
    code, out = run(capsys, "app", "channel-new", "capp", "mobile")
    assert code == 0 and "mobile" in out
    code, out = run(capsys, "app", "channel-delete", "capp", "mobile")
    assert code == 0


def test_template_get_scaffolds(tmp_path, pio_home, capsys):
    from predictionio_tpu.cli.main import main

    dst = tmp_path / "myengine"
    rc = main(["template", "get", "recommendation", str(dst)])
    assert rc == 0
    assert (dst / "engine.json").exists()
    out = capsys.readouterr().out
    assert "recommendation" in out


def test_template_get_unknown_lists_available(tmp_path, pio_home, capsys):
    import pytest
    from predictionio_tpu.cli.main import main

    with pytest.raises(SystemExit):
        main(["template", "get", "nosuch", str(tmp_path / "x")])
    err = capsys.readouterr().err
    assert "recommendation" in err and "dlrm" in err


def test_cli_eval_end_to_end(tmp_path, pio_home, capsys):
    """`pio eval` drives the shared-prep sweep and writes the evaluation
    instance + JSON results (reference: RunEvaluation)."""
    import json as _json

    import numpy as np
    from predictionio_tpu.cli.main import main
    from predictionio_tpu.data.event import DataMap, Event
    from predictionio_tpu.data.storage import App, get_storage

    storage = get_storage()
    app_id = storage.get_apps().insert(App(id=None, name="testapp"))
    storage.get_events().init(app_id)
    evs = [Event(event="rate", entity_type="user", entity_id=f"u{u % 12}",
                 target_entity_type="item", target_entity_id=f"i{(u + d) % 8}",
                 properties=DataMap({"rating": float(1 + d % 5)}))
           for u in range(12) for d in range(6)]
    storage.get_events().insert_batch(evs, app_id)
    out_json = tmp_path / "eval.json"
    rc = main([
        "eval",
        "predictionio_tpu.templates.recommendation.evaluation:evaluation",
        "predictionio_tpu.templates.recommendation.evaluation:"
        "default_params_generator",
        "--output-json", str(out_json),
    ])
    assert rc == 0
    res = _json.loads(out_json.read_text())
    assert "bestScore" in res and len(res["candidates"]) == 2
    insts = storage.get_evaluation_instances().get_completed()
    assert len(insts) == 1


# -- pio spill: manual journal ops (ISSUE 4 satellite) -----------------------

class TestSpillCli:
    def _journal_with_backlog(self, spill_dir, storage, app_id):
        """Write a journal with 2 pending records + 1 dead letter, as a
        crashed event server would leave behind."""
        from predictionio_tpu.resilience.spill import SpillJournal

        j = SpillJournal(spill_dir)
        ev = {"event": "rate", "entityType": "user", "entityId": "u1",
              "targetEntityType": "item", "targetEntityId": "i1",
              "properties": {"rating": 4.0},
              "eventTime": "2026-01-02T03:04:05.000Z"}
        j.append([ev, ev], app_id, None, token="tok-a")
        j.append([ev], app_id, None, token="tok-b")
        # a dead-letter file left by a previous replay (written directly:
        # dead_letter() on a live journal also advances the offset, which
        # is not the state a crashed server leaves behind)
        with open(j.dead_path, "a", encoding="utf-8") as f:
            f.write(json.dumps({
                "reason": "EventValidationError: missing event",
                "token": "tok-dead", "appId": app_id, "channelId": None,
                "events": [{"entityType": "user", "entityId": "broken"}],
            }) + "\n")
        j.close()

    def test_inspect_reports_pending_and_dead(self, clean_storage, capsys,
                                              tmp_path):
        from predictionio_tpu.data.storage import App, get_storage

        storage = get_storage()
        app_id = storage.get_apps().insert(App(id=None, name="spillapp"))
        storage.get_events().init(app_id)
        d = tmp_path / "spill"
        self._journal_with_backlog(d, storage, app_id)
        code, out = run(capsys, "spill", "inspect", "--dir", str(d))
        assert code == 0
        assert "2 record(s) / 3 event(s)" in out
        assert "dead-lettered: 1 record(s) / 1 event(s)" in out
        assert "tok-a, tok-b" in out

    def test_drain_replays_into_storage(self, clean_storage, capsys,
                                        tmp_path):
        from predictionio_tpu.data.storage import App, get_storage
        from predictionio_tpu.resilience.spill import journal_summary

        storage = get_storage()
        app_id = storage.get_apps().insert(App(id=None, name="spillapp"))
        storage.get_events().init(app_id)
        d = tmp_path / "spill"
        self._journal_with_backlog(d, storage, app_id)
        code, out = run(capsys, "spill", "drain", "--dir", str(d))
        assert code == 0 and "Replayed 3 event(s)" in out
        stored = list(storage.get_events().find(app_id, None, limit=None))
        assert len(stored) == 3
        assert journal_summary(d)["pendingEvents"] == 0
        # drain is idempotent: nothing left, still exit 0
        code, out = run(capsys, "spill", "drain", "--dir", str(d))
        assert code == 0 and "Replayed 0 event(s)" in out
        assert len(list(storage.get_events().find(app_id, None,
                                                  limit=None))) == 3

    def test_requeue_dead_then_drain(self, clean_storage, capsys, tmp_path):
        from predictionio_tpu.data.storage import App, get_storage
        from predictionio_tpu.resilience.spill import journal_summary

        storage = get_storage()
        app_id = storage.get_apps().insert(App(id=None, name="spillapp"))
        storage.get_events().init(app_id)
        d = tmp_path / "spill"
        self._journal_with_backlog(d, storage, app_id)
        code, out = run(capsys, "spill", "requeue-dead", "--dir", str(d))
        assert code == 0 and "Requeued 1" in out
        s = journal_summary(d)
        assert s["deadRecords"] == 0 and s["pendingEvents"] == 4
        # the requeued record is invalid (missing "event") — a drain
        # dead-letters it again instead of wedging behind it
        code, out = run(capsys, "spill", "drain", "--dir", str(d))
        assert code == 0
        assert journal_summary(d)["deadRecords"] == 1

    def test_drain_refuses_locked_journal(self, clean_storage, capsys,
                                          tmp_path):
        from predictionio_tpu.resilience.spill import SpillJournal

        d = tmp_path / "spill"
        live = SpillJournal(d)  # simulates the running event server
        try:
            with pytest.raises(SystemExit):
                main(["spill", "drain", "--dir", str(d)])
            err = capsys.readouterr().err
            assert "locked by a running event server" in err
        finally:
            live.close()
