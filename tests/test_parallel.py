"""Mesh/sharding tests on the 8-device virtual CPU mesh (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec

from predictionio_tpu.parallel import (
    AXIS_DATA,
    AXIS_MODEL,
    batch_sharding,
    make_mesh,
    replicated,
    sharding,
)
from predictionio_tpu.parallel.collectives import collective_microbench


def test_virtual_device_count():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"


def test_make_mesh_default():
    m = make_mesh()
    assert m.axis_names == (AXIS_DATA,)
    assert m.shape[AXIS_DATA] == 8


def test_make_mesh_2d_and_wildcard():
    m = make_mesh({AXIS_DATA: 4, AXIS_MODEL: 2})
    assert m.shape == {AXIS_DATA: 4, AXIS_MODEL: 2}
    m2 = make_mesh({AXIS_DATA: -1, AXIS_MODEL: 2})
    assert m2.shape[AXIS_DATA] == 4


def test_make_mesh_errors():
    with pytest.raises(ValueError, match="need"):
        make_mesh({AXIS_DATA: 16})  # oversubscribed
    # Undersubscribed is fine: take a device prefix (`--mesh data=3`).
    assert dict(make_mesh({AXIS_DATA: 3}).shape) == {AXIS_DATA: 3}
    with pytest.raises(ValueError, match="divisible"):
        make_mesh({AXIS_DATA: -1, AXIS_MODEL: 3})
    with pytest.raises(ValueError, match="one mesh axis"):
        make_mesh({AXIS_DATA: -1, AXIS_MODEL: -1})


def test_sharded_matmul_matches_single_device():
    """pjit over the mesh computes the same result as one device."""
    m = make_mesh({AXIS_DATA: 4, AXIS_MODEL: 2})
    rng = np.random.default_rng(0)
    a = rng.normal(size=(32, 16)).astype(np.float32)
    b = rng.normal(size=(16, 8)).astype(np.float32)
    a_sh = jax.device_put(a, sharding(m, AXIS_DATA, None))
    b_sh = jax.device_put(b, sharding(m, None, AXIS_MODEL))

    @jax.jit
    def matmul(x, y):
        return x @ y

    out = matmul(a_sh, b_sh)
    # sharded reduction order differs from single-device accumulation
    np.testing.assert_allclose(np.asarray(out), a @ b, rtol=1e-4, atol=1e-5)
    assert not out.is_fully_replicated or out.sharding.is_fully_replicated


def test_batch_sharding_and_replicated():
    m = make_mesh()
    x = jnp.arange(16.0).reshape(16, 1)
    xs = jax.device_put(x, batch_sharding(m))
    assert xs.sharding.spec == PartitionSpec(AXIS_DATA)
    r = jax.device_put(x, replicated(m))
    assert r.sharding.is_fully_replicated


def test_psum_semantics_on_mesh():
    """shard_map + psum over the data axis == global sum (the treeAggregate
    analogue, SURVEY.md §2.4 'hierarchical reduction')."""
    from functools import partial

    m = make_mesh()
    x = jnp.ones((8, 4))
    xs = jax.device_put(x, batch_sharding(m))

    from predictionio_tpu.parallel.compat import shard_map

    @partial(shard_map, mesh=m, in_specs=PartitionSpec(AXIS_DATA),
             out_specs=PartitionSpec())
    def global_sum(v):
        return jax.lax.psum(v.sum(keepdims=True), AXIS_DATA)

    out = global_sum(xs)
    assert float(out.ravel()[0]) == 32.0


def test_collective_microbench_runs():
    m = make_mesh()
    res = collective_microbench(m, size_mb=0.25, iters=2)
    assert set(res) == {"all_reduce", "all_gather", "all_to_all"}
    for v in res.values():
        assert v["seconds"] > 0 and v["algo_bw_gbps"] > 0
