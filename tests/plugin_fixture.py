"""Test fixture for the server plugin seam (loaded via
PIO_EVENTSERVER_PLUGINS / PIO_ENGINESERVER_PLUGINS as
``tests.plugin_fixture:make_plugin``)."""

from predictionio_tpu.server.plugins import ServerPlugin

# module-level so tests can reach the instance the env-driven loader made
LAST = None


class CountingPlugin(ServerPlugin):
    name = "counting"

    def __init__(self):
        self.started_with = None
        self.requests = []

    def start(self, server):
        self.started_with = server

    def on_request(self, route, status, ms):
        self.requests.append((route, status, ms))
        return {"X-Plugin-Count": str(len(self.requests))}

    def stop(self):
        self.started_with = None


def make_plugin():
    global LAST
    LAST = CountingPlugin()
    return LAST
