"""DASE controller + workflow tests with fake engines.

Reference: core/src/test/scala fake-DASE suites ("FakeWorkflow",
EngineTest, JsonExtractorSuite — SURVEY.md §4 "engine-workflow fakes").
"""

import dataclasses
import json
from typing import List, Optional, Tuple

import pytest

from predictionio_tpu.controller import (
    Algorithm,
    AverageMetric,
    DataSource,
    EmptyParams,
    Engine,
    EngineParams,
    EngineParamsGenerator,
    EngineVariant,
    Evaluation,
    FirstServing,
    IdentityPreparator,
    Params,
    ParamsBindingError,
    PersistentModel,
    Preparator,
    RuntimeContext,
    Serving,
    bind_params,
    load_engine_factory,
)
from predictionio_tpu.data.storage import Storage
from predictionio_tpu.workflow import load_models, run_evaluation, run_train


# -- params binding ---------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class AlgoParams(Params):
    rank: int
    reg: float = 0.1
    name: str = "als"
    seeds: Tuple[int, ...] = (1, 2)
    nested: Optional["SubParams"] = None


@dataclasses.dataclass(frozen=True)
class SubParams(Params):
    depth: int = 1


class TestParamsBinding:
    def test_basic_and_defaults(self):
        p = bind_params(AlgoParams, {"rank": 8})
        assert p.rank == 8 and p.reg == 0.1 and p.seeds == (1, 2)

    def test_float_accepts_int(self):
        assert bind_params(AlgoParams, {"rank": 8, "reg": 1}).reg == 1.0

    def test_strict_unknown_keys(self):
        with pytest.raises(ParamsBindingError, match="unknown keys"):
            bind_params(AlgoParams, {"rank": 8, "typo": 1})

    def test_missing_required(self):
        with pytest.raises(ParamsBindingError, match="required"):
            bind_params(AlgoParams, {})

    def test_type_mismatch(self):
        with pytest.raises(ParamsBindingError):
            bind_params(AlgoParams, {"rank": "eight"})
        with pytest.raises(ParamsBindingError):
            bind_params(AlgoParams, {"rank": True})

    def test_nested_and_optional(self):
        p = bind_params(AlgoParams, {"rank": 1, "nested": {"depth": 3}})
        assert p.nested == SubParams(depth=3)
        assert bind_params(AlgoParams, {"rank": 1, "nested": None}).nested is None

    def test_tuple_coercion(self):
        assert bind_params(AlgoParams, {"rank": 1, "seeds": [5, 6]}).seeds == (5, 6)


# -- fake DASE engine -------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class FakeDSParams(Params):
    n: int = 10


class FakeDataSource(DataSource):
    params_class = FakeDSParams

    def read_training(self, ctx):
        return list(range(self.params.n))

    def read_eval(self, ctx):
        # two folds; queries are ints, actual = query * 2
        folds = []
        for fold in range(2):
            td = list(range(self.params.n))
            qa = [(q, q * 2) for q in range(3)]
            folds.append((td, {"fold": fold}, qa))
        return folds


class DoublePreparator(Preparator):
    def prepare(self, ctx, td):
        return [x * 2 for x in td]


@dataclasses.dataclass(frozen=True)
class MulParams(Params):
    factor: int = 1


class MulAlgorithm(Algorithm):
    """model = factor * sum(pd); predict(q) = model_factor * q."""

    params_class = MulParams

    def train(self, ctx, pd):
        return {"factor": self.params.factor, "total": sum(pd)}

    def predict(self, model, query):
        return model["factor"] * query


def fake_engine() -> Engine:
    return Engine(
        datasource_class=FakeDataSource,
        preparator_class=DoublePreparator,
        algorithm_classes={"mul": MulAlgorithm},
        serving_class=FirstServing,
    )


VARIANT = {
    "engineFactory": "tests.test_controller_workflow:fake_engine",
    "id": "test-variant",
    "datasource": {"params": {"n": 4}},
    "algorithms": [{"name": "mul", "params": {"factor": 3}}],
}


@pytest.fixture()
def ctx(pio_home):
    from predictionio_tpu.data.storage import reset_storage

    reset_storage()
    yield RuntimeContext.create()
    reset_storage()


class TestEngine:
    def test_bind_engine_params(self):
        e = fake_engine()
        ep = e.bind_engine_params(VARIANT)
        assert ep.datasource_params == FakeDSParams(n=4)
        assert ep.algorithms_params == (("mul", MulParams(factor=3)),)

    def test_unknown_algorithm(self):
        e = fake_engine()
        with pytest.raises(ParamsBindingError, match="Unknown algorithm"):
            e.bind_engine_params({**VARIANT, "algorithms": [{"name": "nope"}]})

    def test_train(self, ctx):
        e = fake_engine()
        models = e.train(ctx, e.bind_engine_params(VARIANT))
        # td = [0..3], prepared doubles → sum=12
        assert models == [{"factor": 3, "total": 12}]

    def test_eval(self, ctx):
        e = fake_engine()
        folds = e.eval(ctx, e.bind_engine_params(VARIANT))
        assert len(folds) == 2
        info, qpa = folds[0]
        assert info == {"fold": 0}
        assert qpa == [(0, 0, 0), (1, 3, 2), (2, 6, 4)]

    def test_load_engine_factory(self):
        f = load_engine_factory("tests.test_controller_workflow:fake_engine")
        assert isinstance(f(), Engine)
        f2 = load_engine_factory("tests.test_controller_workflow.fake_engine")
        assert isinstance(f2(), Engine)
        with pytest.raises(ParamsBindingError):
            load_engine_factory("tests.test_controller_workflow:nope")
        with pytest.raises(ParamsBindingError):
            load_engine_factory("no.such.module:f")


class TestRunTrain:
    def test_lifecycle_and_model_roundtrip(self, ctx):
        e = fake_engine()
        variant = EngineVariant.from_dict(VARIANT)
        iid = run_train(e, variant, ctx)
        inst = ctx.storage.get_engine_instances().get(iid)
        assert inst.status == "COMPLETED"
        assert inst.end_time is not None
        assert inst.engine_variant == "test-variant"
        assert json.loads(inst.algorithms_params) == [
            {"name": "mul", "params": {"factor": 3}}
        ]
        # latest-completed resolution, like pio deploy does
        latest = ctx.storage.get_engine_instances().get_latest_completed(
            inst.engine_id, inst.engine_version, inst.engine_variant
        )
        assert latest.id == iid
        models = load_models(e, inst, ctx)
        assert models == [{"factor": 3, "total": 12}]

    def test_failure_marks_instance(self, ctx):
        class BoomAlgorithm(MulAlgorithm):
            def train(self, ctx, pd):
                raise RuntimeError("boom")

        e = Engine(FakeDataSource, DoublePreparator, {"mul": BoomAlgorithm})
        variant = EngineVariant.from_dict(VARIANT)
        with pytest.raises(RuntimeError, match="boom"):
            run_train(e, variant, ctx)
        all_inst = ctx.storage.get_engine_instances().get_all()
        assert len(all_inst) == 1 and all_inst[0].status == "FAILED"


class SquaredError(AverageMetric):
    def calculate_one(self, q, p, a):
        return -float((p - a) ** 2)  # higher is better


class SweepGenerator(EngineParamsGenerator):
    @property
    def engine_params_list(self):
        e = fake_engine()
        out = []
        for factor in (1, 2, 3):
            out.append(
                e.bind_engine_params(
                    {**VARIANT, "algorithms": [{"name": "mul", "params": {"factor": factor}}]}
                )
            )
        return out


class TestRunEvaluation:
    def test_sweep_picks_best(self, ctx):
        # actual = 2*q, predict = factor*q → factor=2 is optimal
        e = fake_engine()
        evaluation = Evaluation(engine=e, metric=SquaredError())
        iid, result = run_evaluation(evaluation, SweepGenerator(), ctx)
        assert result.best_index == 1
        assert result.best_score == 0.0
        best_algo = dict(result.best_engine_params.algorithms_params)
        assert best_algo["mul"] == MulParams(factor=2)
        inst = ctx.storage.get_evaluation_instances().get(iid)
        assert inst.status == "EVALCOMPLETED"
        parsed = json.loads(inst.evaluator_results_json)
        assert parsed["bestIndex"] == 1
        assert len(parsed["candidates"]) == 3
        assert ctx.storage.get_evaluation_instances().get_completed()[0].id == iid


class SelfSavingModel(PersistentModel):
    """Exercises the PersistentModel path end-to-end."""

    def __init__(self, value):
        self.value = value

    def save(self, instance_id, ctx):
        ctx.storage.get_models().insert(
            __import__("predictionio_tpu.data.storage", fromlist=["Model"]).Model(
                id=f"custom-{instance_id}", models=str(self.value).encode()
            )
        )
        return True

    @classmethod
    def load(cls, instance_id, params, ctx):
        blob = ctx.storage.get_models().get(f"custom-{instance_id}")
        return cls(int(blob.models.decode()))


class PersistentAlgorithm(MulAlgorithm):
    def train(self, ctx, pd):
        return SelfSavingModel(sum(pd))

    def predict(self, model, query):
        return model.value


class TestPersistentModel:
    def test_custom_persistence_roundtrip(self, ctx):
        e = Engine(FakeDataSource, DoublePreparator, {"mul": PersistentAlgorithm})
        variant = EngineVariant.from_dict(VARIANT)
        iid = run_train(e, variant, ctx)
        inst = ctx.storage.get_engine_instances().get(iid)
        models = load_models(e, inst, ctx)
        assert isinstance(models[0], SelfSavingModel)
        assert models[0].value == 12
