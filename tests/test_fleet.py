"""ISSUE 15 — fleet-scale serving plane.

Three tentpole pieces under test:

- **Shared spill backplane**: the storage-backed queue's lease/ack
  contract (pinned identical across sqlite / memory / pioserver), the
  drainer-crash chaos spine (a peer replays an expired lease with zero
  lost and zero duplicated events, by idempotency token), the PIO_FAULTS
  ``spillq.*`` seams, and the event server's shared-first /
  local-journal-fallback spill routing.
- **Rollout controller**: wave parsing, live multi-server wave
  promotion, halt-on-fleet-burn with WHOLE-fleet rollback, dead-instance
  and 409 skip-and-report, and deterministic resume/unwind from the
  journaled wave state.
- **Durable fold-in cache**: instance B answers a visitor instance A
  solved, without touching the event store; plus the item-side fold-in
  satellite and the eval-sweep preemption-resume satellite.

Fake clocks drive every lease-expiry and bake-window path — no wall
sleeps anywhere but the live-HTTP server round-trips themselves.
"""

import json
import os
import pickle
import threading
from urllib.request import Request, urlopen

import numpy as np
import pytest

from predictionio_tpu.controller import EngineVariant, RuntimeContext
from predictionio_tpu.data.event import DataMap, Event
from predictionio_tpu.data.json_support import event_from_json
from predictionio_tpu.data.storage import App, get_storage
from predictionio_tpu.resilience import faults, idempotency_key
from predictionio_tpu.resilience.shared_spill import (
    LeaseDrainer,
    SharedSpillQueue,
    resolve_spill_backend,
)
from predictionio_tpu.workflow.core_workflow import load_models, run_train


# ==========================================================================
# Shared queue contract — identical semantics across backends
# ==========================================================================


def _sqlite_queues(tmp_path):
    from predictionio_tpu.data.storage.sqlite import SQLiteClient

    return SQLiteClient(str(tmp_path / "q.db")).spill_queues()


def _memory_queues(tmp_path):
    from predictionio_tpu.data.storage.memory import MemorySpillQueues

    return MemorySpillQueues()


@pytest.fixture(params=["sqlite", "memory"])
def queues(request, tmp_path, pio_home):
    return {"sqlite": _sqlite_queues,
            "memory": _memory_queues}[request.param](tmp_path)


class TestQueueContract:
    def test_enqueue_is_token_idempotent(self, queues):
        a = queues.enqueue("events", {"token": "t1"}, token="t1",
                           events=2, now_s=10.0)
        b = queues.enqueue("events", {"token": "t1"}, token="t1",
                           events=2, now_s=11.0)
        assert a == b
        st = queues.stats("events", now_s=12.0)
        assert st["pending"] == 1 and st["pendingEvents"] == 2

    def test_lease_is_exclusive_until_expiry(self, queues):
        queues.enqueue("events", {"token": "t1"}, token="t1", now_s=10.0)
        got = queues.lease("events", "A", 5, ttl_s=30, now_s=11.0)
        assert len(got) == 1 and got[0].attempts == 1
        # B cannot claim under A's unexpired lease
        assert queues.lease("events", "B", 5, ttl_s=30, now_s=20.0) == []
        # past expiry B takes over, bumping attempts
        stolen = queues.lease("events", "B", 5, ttl_s=30, now_s=42.0)
        assert len(stolen) == 1 and stolen[0].attempts == 2
        # A's ack now reports the lost lease instead of deleting B's work
        assert queues.ack("events", [got[0].id], "A") == 0
        assert queues.ack("events", [stolen[0].id], "B") == 1
        assert queues.stats("events", now_s=43.0)["pending"] == 0

    def test_nack_releases_immediately(self, queues):
        queues.enqueue("events", {"token": "t1"}, token="t1", now_s=1.0)
        got = queues.lease("events", "A", 5, ttl_s=1000, now_s=2.0)
        assert queues.nack("events", [got[0].id], "A") == 1
        # pending again without waiting out the (long) TTL
        assert len(queues.lease("events", "B", 5, ttl_s=10,
                                now_s=3.0)) == 1

    def test_dead_letter_and_requeue(self, queues):
        queues.enqueue("events", {"token": "t1"}, token="t1",
                       events=3, now_s=1.0)
        got = queues.lease("events", "A", 5, ttl_s=30, now_s=2.0)
        assert queues.dead_letter("events", got[0].id, "A", "poison")
        st = queues.stats("events", now_s=3.0)
        assert st["dead"] == 1 and st["deadEvents"] == 3
        assert queues.peek("events", state="dead")[0].reason == "poison"
        assert queues.requeue_dead("events") == 3
        st = queues.stats("events", now_s=4.0)
        assert st["pending"] == 1 and st["dead"] == 0

    def test_fifo_order_and_expired_stat(self, queues):
        for i in range(3):
            queues.enqueue("events", {"i": i}, token=f"t{i}",
                           now_s=float(i))
        got = queues.lease("events", "A", 2, ttl_s=5, now_s=10.0)
        assert [r.payload["i"] for r in got] == [0, 1]
        st = queues.stats("events", now_s=100.0)
        assert st["expired"] == 2 and st["pending"] == 1


class _HostedBackplane:
    """Minimal storage façade for StorageServer: events + spill queue +
    KV, all memory-backed (the server-side half of the chaos tests)."""

    def __init__(self):
        from predictionio_tpu.data.storage import memory as m

        self._events = m.MemoryEvents()
        self._queues = m.MemorySpillQueues()
        self._kv = m.MemoryKV()

    def get_events(self):
        return self._events

    def get_spill_queues(self):
        return self._queues

    def get_kv(self):
        return self._kv

    def __getattr__(self, name):
        if name.startswith("get_"):
            return lambda: None
        raise AttributeError(name)


@pytest.fixture()
def remote_backplane(pio_home):
    from predictionio_tpu.data.storage.remote import (
        RemoteClient,
        StorageServer,
    )

    hosted = _HostedBackplane()
    srv = StorageServer(hosted, host="127.0.0.1", port=0)
    srv.start()
    client = RemoteClient("127.0.0.1", srv.port)
    client.events().init(1)
    yield hosted, client
    client.close()
    srv.stop()


class TestQueueContractRemote:
    def test_lease_ack_round_trip_over_rpc(self, remote_backplane):
        _, client = remote_backplane
        q = client.spill_queues()
        q.enqueue("events", {"token": "t1", "events": [{"x": 1}]},
                  token="t1", events=1, now_s=5.0)
        got = q.lease("events", "A", 5, ttl_s=30, now_s=6.0)
        assert len(got) == 1 and got[0].payload["events"] == [{"x": 1}]
        assert q.ack("events", [got[0].id], "A") == 1
        assert q.stats("events", now_s=7.0)["pending"] == 0


# ==========================================================================
# Chaos spine: drainer crash mid-lease → peer replays exactly once
# ==========================================================================


def _record(i, n_events=1):
    evs = [{"event": "rate", "entityType": "user", "entityId": f"u{i}",
            "targetEntityType": "item", "targetEntityId": f"i{k}",
            "properties": {"rating": 4}} for k in range(n_events)]
    return {"token": f"tok{i}", "appId": 1, "channelId": None,
            "events": evs}


def _rpc_insert_fn(client):
    """The replay write, exactly as the event server issues it: the
    record's pinned token + the original event set, over RPC — the
    server-side dedup window is what turns redelivery into
    exactly-once."""
    repo = client.events()

    def insert(payload):
        evs = [event_from_json(e) for e in payload["events"]]
        with idempotency_key(payload["token"]):
            repo.insert_batch(evs, payload["appId"],
                              payload.get("channelId"))
    return insert


class _QueueView:
    """A SharedSpillQueue whose clock a test advances by hand.  The stub
    storage wraps the repo through the fault seam exactly like
    ``Storage.get_spill_queues`` does, so ``spillq.*`` rules fire."""

    def __init__(self, client, now=1000.0):
        from predictionio_tpu.resilience.faults import wrap_spill_queues

        class _S:
            def get_spill_queues(self_inner):
                return wrap_spill_queues(client.spill_queues())

        self.now = [now]
        self.q = SharedSpillQueue(_S(), clock=lambda: self.now[0])


class TestDrainerCrashChaos:
    def test_peer_replays_expired_lease_exactly_once(self,
                                                     remote_backplane):
        """THE acceptance e2e (1): drainer A crashes mid-lease after
        landing PART of its batch; B takes the expired lease over and
        replays everything — every event in the store exactly once,
        because B's re-inserts carry A's pinned tokens and the RPC dedup
        window answers them without re-executing."""
        hosted, client = remote_backplane
        view = _QueueView(client)
        q = view.q
        for i in range(6):
            q.append(_record(i)["events"], 1, None, token=f"tok{i}")
        assert q.depth() == 6

        insert = _rpc_insert_fn(client)
        # Drainer A leases everything, lands records 0-2, then "crashes"
        # (no ack, no nack — the lease just stops being renewed).
        leased = q.lease("A", 100, ttl_s=30)
        assert len(leased) == 6
        for rec in leased[:3]:
            insert(rec.payload)
        assert len(list(client.events().find(1))) == 3

        # B before expiry: nothing claimable.
        assert q.lease("B", 100, ttl_s=30) == []

        # Lease expires; B drains the whole batch — including the three
        # records A already landed.
        view.now[0] += 31.0
        drainer_b = LeaseDrainer(q, insert, owner="B", lease_ttl_s=30)
        landed = drainer_b.drain_once()
        assert landed == 6
        assert q.depth() == 0

        evs = list(client.events().find(1))
        assert len(evs) == 6, "zero lost AND zero duplicated"
        assert sorted(e.entity_id for e in evs) == \
            sorted(f"u{i}" for i in range(6))

    def test_storage_error_mid_ack_is_replayed_not_lost(
            self, remote_backplane):
        """PIO_FAULTS spillq.ack:error — the drainer's ack fails AFTER
        the inserts landed; the records stay leased, expire, and the
        next drain re-replays them (dedup'd) instead of losing or
        double-counting them."""
        hosted, client = remote_backplane
        view = _QueueView(client)
        q = view.q
        q.append(_record(0)["events"], 1, None, token="tok0")
        insert = _rpc_insert_fn(client)
        drainer = LeaseDrainer(q, insert, owner="A", lease_ttl_s=30)

        faults.install("spillq.ack:error:1.0:1")
        try:
            drainer.drain_once()
        finally:
            faults.clear()
        # landed but still queued (leased) — not lost
        assert len(list(client.events().find(1))) == 1
        assert q.depth() == 1
        view.now[0] += 31.0
        assert drainer.drain_once() == 1
        assert q.depth() == 0
        assert len(list(client.events().find(1))) == 1  # no duplicate

    def test_lease_steal_fault_point_fires(self, remote_backplane):
        _, client = remote_backplane
        view = _QueueView(client)
        view.q.append(_record(0)["events"], 1, None, token="tok0")
        faults.install("spillq.lease:error:1.0:1")
        try:
            with pytest.raises(ConnectionError):
                view.q.lease("A", 5, 30)
        finally:
            faults.clear()

    def test_poison_record_dead_letters_without_wedging(
            self, remote_backplane):
        _, client = remote_backplane
        view = _QueueView(client)
        q = view.q
        q.append([{"not": "an event"}], 1, None, token="bad")
        q.append(_record(1)["events"], 1, None, token="tok1")
        drainer = LeaseDrainer(q, _rpc_insert_fn(client), owner="A",
                               lease_ttl_s=30)
        assert drainer.drain_once() == 1  # good record landed
        st = q.stats()
        assert st["dead"] == 1 and q.depth() == 0
        # operator requeues after fixing the cause
        assert q.requeue_dead() == 1


# ==========================================================================
# Event server routing: shared-first, local journal as spill-of-the-spill
# ==========================================================================


def _event_stack(shared: bool):
    from predictionio_tpu.data.storage import AccessKey
    from predictionio_tpu.server.event_server import EventServer

    storage = get_storage()
    app_id = storage.get_apps().insert(App(id=None, name="spillapp"))
    storage.get_events().init(app_id)
    key = storage.get_access_keys().insert(
        AccessKey(key="", app_id=app_id))
    srv = EventServer(
        storage=storage, host="127.0.0.1", port=0,
        spill_backend="shared" if shared else "local",
        replay_wait=lambda ev, t: ev.wait(0.01) or True,   # parked
        drain_wait=lambda ev, t: ev.wait(0.01) or True)    # parked
    return srv, key, app_id, storage


def _post_event(srv, key, user="u1"):
    return srv.handle(
        "POST", "/events.json", {"accessKey": [key]},
        json.dumps({"event": "rate", "entityType": "user",
                    "entityId": user, "targetEntityType": "item",
                    "targetEntityId": "i1",
                    "properties": {"rating": 3}}).encode())


class TestEventServerSharedSpill:
    def test_resolve_backend_precedence(self, pio_home, monkeypatch):
        assert resolve_spill_backend(None, "sqlite") == "local"
        assert resolve_spill_backend(None, "pioserver") == "shared"
        assert resolve_spill_backend("shared", "sqlite") == "shared"
        assert resolve_spill_backend("local", "pioserver") == "local"
        monkeypatch.setenv("PIO_SPILL_BACKEND", "shared")
        assert resolve_spill_backend(None, "sqlite") == "shared"
        assert resolve_spill_backend("bogus", "sqlite") == "local"

    def test_outage_spills_shared_then_drains(self, pio_home):
        srv, key, app_id, storage = _event_stack(shared=True)
        try:
            faults.install("storage.create:error:1.0")
            st, body = _post_event(srv, key)
            assert st == 202 and body["token"]
            assert srv.shared_spill.depth() == 1
            assert srv.spill.depth() == 0  # shared took it
            faults.clear()
            assert srv._lease_drainer.drain_once() == 1
            assert srv.shared_spill.depth() == 0
            assert len(list(storage.get_events().find(app_id))) == 1
        finally:
            faults.clear()
            srv.stop()

    def test_storage_outage_degrades_to_local_journal(self, pio_home):
        """When storage ITSELF is the outage the shared enqueue fails
        too — the record must land in the local journal, never vanish."""
        srv, key, app_id, storage = _event_stack(shared=True)
        try:
            faults.install(
                "storage.create:error:1.0,spillq.enqueue:error:1.0")
            st, body = _post_event(srv, key)
            assert st == 202
            assert srv.spill.depth() == 1  # the spill-of-the-spill
            faults.clear()
            assert srv._replay.drain_once() == 1
            assert len(list(storage.get_events().find(app_id))) == 1
        finally:
            faults.clear()
            srv.stop()

    def test_ready_reports_both_depths(self, pio_home):
        srv, key, *_ = _event_stack(shared=True)
        try:
            st, body = srv.handle("GET", "/ready", {}, b"")
            assert body["spillBackend"] == "shared"
            assert body["sharedSpillDepth"] == 0
            assert body["spillQueueDepth"] == 0
        finally:
            srv.stop()

    def test_cached_depth_converges_after_peer_drains(self, pio_home):
        """A's /ready depth is cached (never a storage RPC on the probe
        path) and must RECONCILE at A's next drainer tick after a PEER
        drained the queue — no phantom backlog forever."""
        srv_a, key, app_id, storage = _event_stack(shared=True)
        srv_b = None
        try:
            faults.install("storage.create:error:1.0")
            assert _post_event(srv_a, key)[0] == 202
            faults.clear()
            _, body = srv_a.handle("GET", "/ready", {}, b"")
            assert body["sharedSpillDepth"] == 1  # incremental bump
            from predictionio_tpu.server.event_server import EventServer

            srv_b = EventServer(
                storage=storage, host="127.0.0.1", port=0,
                spill_backend="shared",
                replay_wait=lambda ev, t: ev.wait(0.01) or True,
                drain_wait=lambda ev, t: ev.wait(0.01) or True)
            assert srv_b._lease_drainer.drain_once() == 1  # peer drains
            # A's next tick leases nothing but still refreshes the view
            assert srv_a._lease_drainer.drain_once() == 0
            _, body = srv_a.handle("GET", "/ready", {}, b"")
            assert body["sharedSpillDepth"] == 0
        finally:
            faults.clear()
            srv_a.stop()
            if srv_b is not None:
                srv_b.stop()

    def test_any_instance_drains_a_crashed_peers_spill(self, pio_home):
        """Two event servers, one shared queue: A spills and 'crashes'
        (stops); B's drainer replays A's events."""
        srv_a, key, app_id, storage = _event_stack(shared=True)
        faults.install("storage.create:error:1.0")
        try:
            st, _ = _post_event(srv_a, key, user="uA")
            assert st == 202 and srv_a.shared_spill.depth() == 1
        finally:
            faults.clear()
        srv_a.stop()  # crash: the record is in the SHARED queue
        from predictionio_tpu.server.event_server import EventServer

        srv_b = EventServer(
            storage=storage, host="127.0.0.1", port=0,
            spill_backend="shared",
            replay_wait=lambda ev, t: ev.wait(0.01) or True,
            drain_wait=lambda ev, t: ev.wait(0.01) or True)
        try:
            assert srv_b._lease_drainer.drain_once() == 1
            evs = list(storage.get_events().find(app_id))
            assert [e.entity_id for e in evs] == ["uA"]
        finally:
            srv_b.stop()


# ==========================================================================
# Rollout controller
# ==========================================================================


from predictionio_tpu.fleet import (  # noqa: E402
    FleetPromoter,
    RolloutConfig,
    RolloutController,
    parse_waves,
)


class TestWaveParsing:
    def test_mixed_counts_and_percentages(self):
        assert parse_waves("1,25%,100%", 8) == [1, 2, 8]
        assert parse_waves("1,25%,100%", 3) == [1, 3]
        assert parse_waves("2,50%", 10) == [2, 5, 10]

    def test_appends_full_fleet_wave(self):
        assert parse_waves("1", 4) == [1, 4]

    def test_monotonic_and_clamped(self):
        assert parse_waves("3,1,2,100%", 4) == [3, 4]
        assert parse_waves("99", 4) == [4]

    def test_rejects_garbage(self):
        with pytest.raises(ValueError):
            parse_waves("0", 4)
        with pytest.raises(ValueError):
            parse_waves("150%", 4)
        with pytest.raises(ValueError):
            parse_waves("abc", 4)


ALS_VARIANT = {
    "engineFactory": "predictionio_tpu.templates.recommendation:engine",
    "datasource": {"params": {"appName": "fleetapp"}},
    "algorithms": [{"name": "als",
                    "params": {"rank": 8, "numIterations": 2,
                               "seed": 3}}],
}


def _trained_fleet_stack(n_generations=1):
    from predictionio_tpu.templates.recommendation import engine

    storage = get_storage()
    ctx = RuntimeContext.create(storage=storage)
    app_id = storage.get_apps().insert(App(id=None, name="fleetapp"))
    storage.get_events().init(app_id)
    rng = np.random.default_rng(0)
    evs = [Event(event="rate", entity_type="user", entity_id=f"u{u}",
                 target_entity_type="item", target_entity_id=f"i{i}",
                 properties=DataMap({"rating": float(r)}))
           for u, i, r in zip(rng.integers(0, 30, 1200),
                              rng.integers(0, 40, 1200),
                              rng.integers(1, 6, 1200))]
    storage.get_events().insert_batch(evs, app_id)
    eng = engine()
    variant = EngineVariant.from_dict(ALS_VARIANT)
    iids = [run_train(eng, variant, ctx) for _ in range(n_generations)]
    return eng, variant, ctx, app_id, iids


def _fleet_servers(eng, variant, storage, n=3):
    from predictionio_tpu.server import EngineServer

    servers = [EngineServer(eng, variant, storage, host="127.0.0.1",
                            port=0) for _ in range(n)]
    for s in servers:
        s.start(block=False)
    return servers, [f"http://127.0.0.1:{s.port}" for s in servers]


def _cfg(tmp_path, **kw):
    kw.setdefault("waves", "1,100%")
    kw.setdefault("bake_s", 0.2)
    kw.setdefault("poll_s", 0.02)
    kw.setdefault("state_path", str(tmp_path / "rollout.json"))
    return RolloutConfig(**kw)


class TestRolloutE2E:
    def test_wave_promotes_whole_fleet_generation_atomically(
            self, pio_home, tmp_path):
        eng, variant, ctx, _, (i1,) = _trained_fleet_stack(1)
        servers, urls = _fleet_servers(eng, variant, ctx.storage)
        i2 = run_train(eng, variant, ctx)  # candidate generation
        try:
            ctl = RolloutController(urls, _cfg(tmp_path))
            state = ctl.run()
            assert state["status"] == "promoted"
            assert state["target"] == i2
            assert state["waveCounts"] == [1, 3]
            for u in urls:
                assert ctl.served_instance(u) == i2
            # journal is terminal + readable
            saved = json.loads((tmp_path / "rollout.json").read_text())
            assert saved["status"] == "promoted"
            assert saved["preRollout"][urls[0]] == i1  # pre-swap snapshot
        finally:
            for s in servers:
                s.stop()

    def test_halt_on_canary_burn_rolls_back_every_promoted_instance(
            self, pio_home, tmp_path):
        """THE acceptance e2e (2): wave 1 promotes the canary; its SLO
        degrades; the controller halts BEFORE wave 2 and rolls the
        canary back — pre-promotion generation serving everywhere, the
        other instances never touched."""
        from predictionio_tpu.obs.fleet import FleetAggregator

        eng, variant, ctx, _, (i1,) = _trained_fleet_stack(1)
        servers, urls = _fleet_servers(eng, variant, ctx.storage)
        i2 = run_train(eng, variant, ctx)  # candidate generation
        promoted_urls = []

        def fetch(url):
            base = url.rsplit("/", 1)[0]
            with urlopen(url, timeout=10) as r:
                text = r.read().decode()
            if url.endswith("/stats.json") and base in promoted_urls:
                doc = json.loads(text)
                doc.setdefault("slo", {})["degraded"] = True
                return json.dumps(doc)
            return text

        class Ctl(RolloutController):
            def _promote_instance(self, url, target):
                out = super()._promote_instance(url, target)
                if out[0] == "ok":
                    promoted_urls.append(url)
                return out

        try:
            ctl = Ctl(urls, _cfg(tmp_path),
                      aggregator=FleetAggregator(urls, fetch=fetch))
            state = ctl.run(i2)
            assert state["status"] == "rolled_back"
            assert state["promoted"] == [urls[0]]
            assert state["rolledBack"] == [urls[0]]
            assert "slo burn" in state["haltReason"]
            # the whole fleet serves the pre-promotion generation
            for u in urls:
                assert ctl.served_instance(u) == i1
            assert state["postRollback"] == {u: i1 for u in urls}
        finally:
            for s in servers:
                s.stop()

    def test_dead_instance_and_409_skip_and_report(self, pio_home,
                                                   tmp_path):
        eng, variant, ctx, _, (i1,) = _trained_fleet_stack(1)
        servers, urls = _fleet_servers(eng, variant, ctx.storage, n=2)
        i2 = run_train(eng, variant, ctx)
        dead = "http://127.0.0.1:9"  # discard port: never connects
        try:
            ctl = RolloutController(
                urls + [dead], _cfg(tmp_path, waves="100%",
                                    reload_timeout_s=5.0))
            state = ctl.run(i2)
            assert state["status"] == "promoted"
            assert sorted(state["promoted"]) == sorted(urls)
            assert "unreachable" in state["skipped"][dead]
            # an unknown target on live servers → 409 skip, not a wedge
            state2 = ctl.run("no-such-instance")
            assert state2["status"] == "failed"
            assert all("rejected" in v
                       for u, v in state2["skipped"].items()
                       if u != dead)
            for u in urls:  # nobody loaded anything new
                assert ctl.served_instance(u) == i2
        finally:
            for s in servers:
                s.stop()

    def test_preempted_controller_resumes_deterministically(
            self, pio_home, tmp_path):
        """Kill the controller after wave 1; a fresh controller resumes
        from the journal, re-verifies served instances, and finishes the
        remaining waves without re-promoting the canary."""
        eng, variant, ctx, _, (i1,) = _trained_fleet_stack(1)
        servers, urls = _fleet_servers(eng, variant, ctx.storage)
        i2 = run_train(eng, variant, ctx)

        class Preempted(RuntimeError):
            pass

        class DiesAfterWave1(RolloutController):
            def _bake(self, state):
                raise Preempted()  # killed mid-bake, journal on disk

        try:
            ctl = DiesAfterWave1(urls, _cfg(tmp_path))
            with pytest.raises(Preempted):
                ctl.run(i2)
            saved = json.loads((tmp_path / "rollout.json").read_text())
            assert saved["status"] == "in_progress"
            assert saved["promoted"] == [urls[0]]

            reload_counts = {}

            class Counting(RolloutController):
                def _promote_instance(self, url, target):
                    reload_counts[url] = reload_counts.get(url, 0) + 1
                    return super()._promote_instance(url, target)

            ctl2 = Counting(urls, _cfg(tmp_path))
            state = ctl2.resume()
            assert state["status"] == "promoted"
            assert reload_counts.get(urls[0]) is None  # not re-promoted
            for u in urls:
                assert ctl2.served_instance(u) == i2
        finally:
            for s in servers:
                s.stop()

    def test_preempted_controller_unwinds_on_request(self, pio_home,
                                                     tmp_path):
        eng, variant, ctx, _, (i1,) = _trained_fleet_stack(1)
        servers, urls = _fleet_servers(eng, variant, ctx.storage)
        i2 = run_train(eng, variant, ctx)

        class Preempted(RuntimeError):
            pass

        class DiesAfterWave1(RolloutController):
            def _bake(self, state):
                raise Preempted()

        try:
            with pytest.raises(Preempted):
                DiesAfterWave1(urls, _cfg(tmp_path)).run(i2)
            state = RolloutController(urls, _cfg(tmp_path)).resume(
                unwind=True)
            assert state["status"] == "rolled_back"
            for u in urls:
                assert RolloutController(
                    urls, _cfg(tmp_path)).served_instance(u) == i1
        finally:
            for s in servers:
                s.stop()

    def test_fleet_promoter_drives_rollout_for_the_daemon(
            self, pio_home, tmp_path):
        from predictionio_tpu.refresh import RefreshConfig
        from predictionio_tpu.refresh.daemon import RefreshDaemon

        eng, variant, ctx, _, (i1,) = _trained_fleet_stack(1)
        servers, urls = _fleet_servers(eng, variant, ctx.storage, n=2)
        try:
            # multi-URL promote_url → the daemon builds a FleetPromoter
            d = RefreshDaemon(
                eng, variant, ctx,
                config=RefreshConfig(interval_s=0.01,
                                     promote_url=",".join(urls)))
            assert isinstance(d.promoter, FleetPromoter)
            d.promoter.config = _cfg(tmp_path)
            d.promoter.canary_window_s = 0.2
            d.promoter._factory = lambda: RolloutController(
                urls, _cfg(tmp_path))
            out = d.run_once()
            assert out["promotion"] == "promoted"
            i3 = out["instance"]
            for u in urls:
                assert RolloutController(
                    urls, _cfg(tmp_path)).served_instance(u) == i3
            # the staleness anchor: oldest served watermark is readable
            assert d.promoter.served_watermark() is not None
        finally:
            for s in servers:
                s.stop()


class TestReloadTarget:
    def test_reload_accepts_explicit_instance_id(self, pio_home):
        eng, variant, ctx, _, (i1, i2) = _trained_fleet_stack(2)
        servers, urls = _fleet_servers(eng, variant, ctx.storage, n=1)
        try:
            # pin BACK to the older instance explicitly
            req = Request(urls[0] + "/reload",
                          data=json.dumps(
                              {"engineInstanceId": i1}).encode(),
                          method="POST",
                          headers={"Content-Type": "application/json"})
            with urlopen(req, timeout=60) as resp:
                body = json.loads(resp.read())
            assert body["engineInstanceId"] == i1
            # unknown target → 409 rejected, last-good keeps serving
            from urllib.error import HTTPError

            req = Request(urls[0] + "/reload",
                          data=b'{"engineInstanceId": "nope"}',
                          method="POST",
                          headers={"Content-Type": "application/json"})
            with pytest.raises(HTTPError) as ei:
                urlopen(req, timeout=60)
            assert ei.value.code == 409
            with urlopen(urls[0] + "/", timeout=10) as resp:
                assert json.loads(
                    resp.read())["engineInstanceId"] == i1
        finally:
            for s in servers:
                s.stop()


# ==========================================================================
# Durable fold-in cache (tentpole c) + item-side fold-in satellite
# ==========================================================================


class TestDurableFoldInCache:
    def _stack_with_new_user(self):
        eng, variant, ctx, app_id, (iid,) = _trained_fleet_stack(1)
        ctx.storage.get_events().insert_batch(
            [Event(event="rate", entity_type="user", entity_id="newuser",
                   target_entity_type="item", target_entity_id=f"i{i}",
                   properties=DataMap({"rating": 5.0}))
             for i in range(5)], app_id)
        inst = ctx.storage.get_engine_instances().get(iid)
        return eng, ctx, inst

    @staticmethod
    def _metric(result):
        from predictionio_tpu.obs import get_registry

        c = get_registry().get("pio_fold_in_total")
        return c.series().get((result,), 0) if c else 0

    def test_instance_b_hits_what_instance_a_solved(self, pio_home):
        """THE acceptance e2e (3), wrapper level: A solves, B answers
        from the shared KV — even with B's event store broken."""
        eng, ctx, inst = self._stack_with_new_user()
        wrap_a = load_models(eng, inst, ctx)[0]
        wrap_b = load_models(eng, inst, ctx)[0]
        assert wrap_a._shared_kv is not None

        vec_a = wrap_a.fold_in_user("newuser")
        assert vec_a is not None and self._metric("solved") == 1

        class Boom:
            def find_by_entity(self, *a, **k):
                raise AssertionError("B must not read the event store")

        wrap_b._event_store = Boom()
        vec_b = wrap_b.fold_in_user("newuser")
        assert vec_b is not None and np.allclose(vec_a, vec_b)
        assert self._metric("shared") == 1

    def test_shared_cache_survives_instance_restart(self, pio_home):
        """A restarted instance (fresh wrapper) warms from the fleet's
        work instead of re-solving."""
        eng, ctx, inst = self._stack_with_new_user()
        load_models(eng, inst, ctx)[0].fold_in_user("newuser")
        fresh = load_models(eng, inst, ctx)[0]  # "restart"
        assert fresh.fold_in_user("newuser") is not None
        assert self._metric("shared") == 1
        assert self._metric("solved") == 1  # solved exactly once

    def test_different_factors_never_share(self, pio_home):
        """Entries are fingerprint-keyed: a different generation's
        factors must miss and re-solve."""
        eng, variant, ctx, app_id, (i1,) = _trained_fleet_stack(1)
        ctx.storage.get_events().insert_batch(
            [Event(event="rate", entity_type="user", entity_id="newuser",
                   target_entity_type="item", target_entity_id="i1",
                   properties=DataMap({"rating": 5.0}))], app_id)
        inst1 = ctx.storage.get_engine_instances().get(i1)
        w1 = load_models(eng, inst1, ctx)[0]
        assert w1.fold_in_user("newuser") is not None
        i2 = run_train(eng, variant, ctx)  # retrain → new factors
        inst2 = ctx.storage.get_engine_instances().get(i2)
        w2 = load_models(eng, inst2, ctx)[0]
        assert w2._fold_ns() != w1._fold_ns()
        assert w2.fold_in_user("newuser") is not None
        assert self._metric("solved") == 2 and self._metric("shared") == 0

    def test_kill_switch_and_kv_blip_degrade_cleanly(self, pio_home,
                                                     monkeypatch):
        eng, ctx, inst = self._stack_with_new_user()
        wrap = load_models(eng, inst, ctx)[0]
        monkeypatch.setenv("PIO_FOLD_IN_SHARED", "off")
        assert wrap.fold_in_user("newuser") is not None
        assert self._metric("solved") == 1
        # fresh wrapper: with sharing off it must re-solve, not hit
        wrap2 = load_models(eng, inst, ctx)[0]
        assert wrap2.fold_in_user("newuser") is not None
        assert self._metric("solved") == 2 and self._metric("shared") == 0
        monkeypatch.delenv("PIO_FOLD_IN_SHARED")

        class BoomKV:
            def get(self, *a):
                raise RuntimeError("kv down")

            def put(self, *a):
                raise RuntimeError("kv down")

        wrap3 = load_models(eng, inst, ctx)[0]
        wrap3._shared_kv = BoomKV()
        assert wrap3.fold_in_user("newuser") is not None  # still answers

    def test_max_age_gate_re_solves_stale_entries(self, pio_home,
                                                  monkeypatch):
        """The stored solve time is load-bearing: with
        PIO_FOLD_IN_SHARED_MAX_AGE_S set, an entry solved longer ago
        reads as a miss and the visitor re-solves (anchor is SOLVE age —
        an idle user's old events must not permanently expire their
        entry)."""
        eng, ctx, inst = self._stack_with_new_user()
        wrap_a = load_models(eng, inst, ctx)[0]
        assert wrap_a.fold_in_user("newuser") is not None
        # the solve just happened: a generous age accepts, a tiny one
        # rejects
        monkeypatch.setenv("PIO_FOLD_IN_SHARED_MAX_AGE_S", "3600")
        wrap_b = load_models(eng, inst, ctx)[0]
        assert wrap_b.fold_in_user("newuser") is not None
        assert self._metric("shared") == 1
        monkeypatch.setenv("PIO_FOLD_IN_SHARED_MAX_AGE_S", "0.000001")
        wrap_c = load_models(eng, inst, ctx)[0]
        assert wrap_c.fold_in_user("newuser") is not None
        assert self._metric("shared") == 1    # gate rejected the entry
        assert self._metric("solved") == 2    # ...so C re-solved

    def test_negative_outcomes_are_not_shared(self, pio_home):
        eng, variant, ctx, app_id, (iid,) = _trained_fleet_stack(1)
        inst = ctx.storage.get_engine_instances().get(iid)
        wrap = load_models(eng, inst, ctx)[0]
        assert wrap.fold_in_user("ghost") is None
        kv = ctx.storage.get_kv()
        assert kv.get(wrap._fold_ns(), "ghost") is None

    def test_live_http_fold_in_shared_across_two_servers(self, pio_home):
        """Live-HTTP flavor of acceptance e2e (3): query the new user on
        server A, then on server B — B's answer comes from the shared
        cache (counter), and both rank identically."""
        eng, variant, ctx, app_id, (iid,) = _trained_fleet_stack(1)
        ctx.storage.get_events().insert_batch(
            [Event(event="rate", entity_type="user", entity_id="newuser",
                   target_entity_type="item", target_entity_id=f"i{i}",
                   properties=DataMap({"rating": 5.0}))
             for i in range(5)], app_id)
        servers, urls = _fleet_servers(eng, variant, ctx.storage, n=2)

        def query(base):
            req = Request(base + "/queries.json",
                          data=json.dumps({"user": "newuser",
                                           "num": 3}).encode(),
                          headers={"Content-Type": "application/json"})
            with urlopen(req, timeout=30) as resp:
                return json.loads(resp.read())

        try:
            ra = query(urls[0])
            assert ra["itemScores"], "fold-in must answer, not cold-start"
            assert self._metric("solved") == 1
            rb = query(urls[1])
            assert [s["item"] for s in rb["itemScores"]] == \
                [s["item"] for s in ra["itemScores"]]
            assert self._metric("shared") == 1
            assert self._metric("solved") == 1  # B did NOT re-solve
        finally:
            for s in servers:
                s.stop()


class TestItemSideFoldIn:
    def _stack(self):
        from predictionio_tpu.templates.similarproduct import engine

        storage = get_storage()
        ctx = RuntimeContext.create(storage=storage)
        app_id = storage.get_apps().insert(App(id=None, name="spapp"))
        storage.get_events().init(app_id)
        # clique: even users view even items, odd view odd
        evs = [Event(event="view", entity_type="user",
                     entity_id=f"u{u}", target_entity_type="item",
                     target_entity_id=f"i{i}")
               for u in range(10) for i in range(8) if i % 2 == u % 2]
        storage.get_events().insert_batch(evs, app_id)
        variant = EngineVariant.from_dict({
            "engineFactory":
                "predictionio_tpu.templates.similarproduct:engine",
            "datasource": {"params": {"appName": "spapp"}},
            "algorithms": [{"name": "als",
                            "params": {"rank": 8, "numIterations": 6,
                                       "seed": 3}}],
        })
        eng = engine()
        iid = run_train(eng, variant, ctx)
        return eng, variant, ctx, app_id, iid

    @staticmethod
    def _metric(result):
        from predictionio_tpu.obs import get_registry

        c = get_registry().get("pio_fold_in_items_total")
        return c.series().get((result,), 0) if c else 0

    def test_new_item_folds_in_and_ranks_its_cohort(self, pio_home):
        eng, variant, ctx, app_id, iid = self._stack()
        # new item i100, viewed by EVEN (cohort-0) users
        ctx.storage.get_events().insert_batch(
            [Event(event="view", entity_type="user", entity_id=f"u{u}",
                   target_entity_type="item", target_entity_id="i100")
             for u in (0, 2, 4, 6)], app_id)
        inst = ctx.storage.get_engine_instances().get(iid)
        model = load_models(eng, inst, ctx)[0]
        algo = eng.algorithm_classes["als"](None)
        from predictionio_tpu.templates.similarproduct.engine import Query

        res = algo.predict(model, Query(items=["i100"], num=4))
        assert res.itemScores, "a viewed new item must not stay cold"
        assert self._metric("solved") == 1
        # the folded factor lands in the even cohort
        top = [s.item for s in res.itemScores]
        even_hits = sum(1 for it in top if int(it[1:]) % 2 == 0)
        assert even_hits >= 3, top
        # repeat query rides the bounded cache
        algo.predict(model, Query(items=["i100"], num=4))
        assert self._metric("cached") >= 1
        assert self._metric("solved") == 1

    def test_unknown_item_without_views_stays_cold(self, pio_home):
        eng, variant, ctx, app_id, iid = self._stack()
        inst = ctx.storage.get_engine_instances().get(iid)
        model = load_models(eng, inst, ctx)[0]
        algo = eng.algorithm_classes["als"](None)
        from predictionio_tpu.templates.similarproduct.engine import Query

        res = algo.predict(model, Query(items=["i999"], num=4))
        assert res.itemScores == []
        assert self._metric("no_events") == 1

    def test_kill_switch_disables_item_fold_in(self, pio_home,
                                               monkeypatch):
        eng, variant, ctx, app_id, iid = self._stack()
        ctx.storage.get_events().insert_batch(
            [Event(event="view", entity_type="user", entity_id="u0",
                   target_entity_type="item",
                   target_entity_id="i100")], app_id)
        monkeypatch.setenv("PIO_FOLD_IN", "off")
        inst = ctx.storage.get_engine_instances().get(iid)
        model = load_models(eng, inst, ctx)[0]
        algo = eng.algorithm_classes["als"](None)
        from predictionio_tpu.templates.similarproduct.engine import Query

        res = algo.predict(model, Query(items=["i100"], num=4))
        assert res.itemScores == []

    def test_old_pickle_backfills_and_declines(self, pio_home):
        """A pre-ISSUE-15 pickle (no user factors) loads and simply
        declines item fold-in."""
        eng, variant, ctx, app_id, iid = self._stack()
        inst = ctx.storage.get_engine_instances().get(iid)
        model = load_models(eng, inst, ctx)[0]
        state = model.__getstate__()
        for k in ("user_factors", "user_index", "app_name",
                  "fold_event_names", "reg", "alpha"):
            state.pop(k, None)
        old = pickle.loads(pickle.dumps(state))
        revived = type(model).__new__(type(model))
        revived.__setstate__(old)
        assert revived.user_factors is None
        assert revived.fold_in_item("i100") is None


# ==========================================================================
# Eval-sweep preemption resume (satellite)
# ==========================================================================


class TestEvalCheckpointResume:
    def _eval_pieces(self):
        from predictionio_tpu.templates.recommendation import engine

        storage = get_storage()
        ctx = RuntimeContext.create(storage=storage)
        app_id = storage.get_apps().insert(App(id=None, name="evapp"))
        storage.get_events().init(app_id)
        rng = np.random.default_rng(0)
        evs = [Event(event="rate", entity_type="user",
                     entity_id=f"u{u}", target_entity_type="item",
                     target_entity_id=f"i{i}",
                     properties=DataMap({"rating": float(r)}))
               for u, i, r in zip(rng.integers(0, 20, 600),
                                  rng.integers(0, 25, 600),
                                  rng.integers(1, 6, 600))]
        storage.get_events().insert_batch(evs, app_id)
        eng = engine()
        candidates = [
            eng.bind_engine_params({
                "datasource": {"params": {"appName": "evapp",
                                          "evalK": 2}},
                "algorithms": [{"name": "als",
                                "params": {"rank": r, "numIterations": 2,
                                           "seed": 3}}]})
            for r in (4, 6)
        ]
        return eng, ctx, candidates

    def test_preempted_sweep_resumes_from_completed_units(
            self, pio_home, tmp_path, monkeypatch):
        from predictionio_tpu.controller.engine import EvalCheckpoint
        from predictionio_tpu.resilience import supervision
        from predictionio_tpu.resilience.supervision import TrainPreempted

        eng, ctx, candidates = self._eval_pieces()
        baseline = eng.eval_multi(ctx, candidates)

        ck = EvalCheckpoint(tmp_path / "evalck")
        calls = {"n": 0}

        def preempt_after_two():
            calls["n"] += 1
            return calls["n"] > 2

        monkeypatch.setattr(supervision, "preemption_requested",
                            preempt_after_two)
        with pytest.raises(TrainPreempted):
            eng.eval_multi(ctx, candidates, checkpoint=ck)
        done_before = ck.completed()
        assert 0 < done_before < 4  # partial progress persisted

        monkeypatch.setattr(supervision, "preemption_requested",
                            lambda: False)
        trains = {"n": 0}
        from predictionio_tpu.templates.recommendation.engine import (
            ALSAlgorithm,
        )

        real_train = ALSAlgorithm.train

        def counting_train(self, ctx_, pd):
            trains["n"] += 1
            return real_train(self, ctx_, pd)

        monkeypatch.setattr(ALSAlgorithm, "train", counting_train)
        resumed = eng.eval_multi(ctx, candidates, checkpoint=ck)
        # only the un-checkpointed units retrained
        assert trains["n"] == 4 - done_before
        assert ck.completed() == 4
        # scores from the resumed sweep match the uninterrupted one
        from predictionio_tpu.templates.recommendation.evaluation import (
            PrecisionAtK,
        )

        metric = PrecisionAtK(k=3)
        for cand in range(2):
            assert metric.calculate(resumed[cand]) == pytest.approx(
                metric.calculate(baseline[cand]))

    def test_run_evaluation_marks_preempted_and_resumes(
            self, pio_home, tmp_path, monkeypatch):
        from predictionio_tpu.resilience import supervision
        from predictionio_tpu.resilience.supervision import TrainPreempted
        from predictionio_tpu.templates.recommendation.evaluation import (
            ParamsList,
            RecommendationEvaluation,
        )
        from predictionio_tpu.workflow.core_workflow import run_evaluation

        eng, ctx, candidates = self._eval_pieces()
        evaluation = RecommendationEvaluation(k=3)
        generator = ParamsList(candidates)
        ck_dir = str(tmp_path / "evalck2")

        calls = {"n": 0}
        monkeypatch.setattr(supervision, "preemption_requested",
                            lambda: (calls.__setitem__("n",
                                                       calls["n"] + 1)
                                     or calls["n"] > 1))
        with pytest.raises(TrainPreempted):
            run_evaluation(evaluation, generator, ctx,
                           checkpoint_dir=ck_dir)
        rows = ctx.storage.get_evaluation_instances().get_all()
        assert any(r.status == "EVALPREEMPTED" for r in rows)

        monkeypatch.setattr(supervision, "preemption_requested",
                            lambda: False)
        iid, result = run_evaluation(evaluation, generator, ctx,
                                     checkpoint_dir=ck_dir)
        assert result.best_score is not None
        # checkpoint cleared once the sweep landed
        from predictionio_tpu.controller.engine import EvalCheckpoint

        assert EvalCheckpoint(ck_dir).completed() == 0
        inst = ctx.storage.get_evaluation_instances().get(iid)
        assert inst.status == "EVALCOMPLETED"
