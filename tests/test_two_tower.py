"""Two-tower retrieval model: learning signal + mesh equivalence."""

import numpy as np
import jax
import jax.numpy as jnp

from predictionio_tpu.models.two_tower import (
    TwoTowerConfig,
    encode_items,
    encode_users,
    init_state,
    retrieve,
    train,
    train_step,
)
from predictionio_tpu.parallel.mesh import make_mesh


def _clique_data(n_users=32, n_items=16, per_user=6, seed=0):
    """Even users interact with even items, odd with odd."""
    rng = np.random.default_rng(seed)
    users, items = [], []
    for u in range(n_users):
        pool = [i for i in range(n_items) if i % 2 == u % 2]
        for i in rng.choice(pool, size=per_user, replace=True):
            users.append(u)
            items.append(int(i))
    return np.array(users), np.array(items)


def test_training_learns_cliques():
    users, items = _clique_data()
    cfg = TwoTowerConfig(n_users=32, n_items=16, embed_dim=16,
                         hidden_dims=(32,), out_dim=16, batch_size=64,
                         epochs=30, learning_rate=3e-3, seed=1)
    state = train(users, items, cfg)
    _, ids = retrieve(state.params, jnp.asarray([0, 1]), cfg.n_items, 5)
    even_hits = sum(1 for i in np.asarray(ids[0]) if i % 2 == 0)
    odd_hits = sum(1 for i in np.asarray(ids[1]) if i % 2 == 1)
    assert even_hits >= 4
    assert odd_hits >= 4


def test_loss_decreases():
    users, items = _clique_data()
    cfg = TwoTowerConfig(n_users=32, n_items=16, embed_dim=8, hidden_dims=(16,),
                         out_dim=8, batch_size=64, epochs=1, seed=2)
    state = init_state(cfg)
    u = users[:64]
    i = items[:64]
    w = np.ones(64, np.float32)
    losses = []
    for _ in range(20):
        # fresh device buffers per call: train_step donates its batch
        # tensors, so a reused jnp array would be a deleted buffer on
        # donation-capable backends
        state, loss = train_step(state, jnp.asarray(u), jnp.asarray(i),
                                 jnp.asarray(w), cfg)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9


def test_mesh_run_matches_single_device():
    users, items = _clique_data(seed=3)
    cfg = TwoTowerConfig(n_users=32, n_items=16, embed_dim=8, hidden_dims=(16,),
                         out_dim=8, batch_size=64, epochs=2, seed=4)
    s1 = train(users, items, cfg)
    mesh = make_mesh({"data": 4, "model": 2})
    s2 = train(users, items, cfg, mesh=mesh)
    np.testing.assert_allclose(
        np.asarray(s1.params["user_embed"]),
        np.asarray(s2.params["user_embed"]), rtol=2e-2, atol=2e-3)


def test_encoders_normalized():
    cfg = TwoTowerConfig(n_users=8, n_items=8, embed_dim=8, hidden_dims=(),
                         out_dim=8)
    state = init_state(cfg)
    u = encode_users(state.params, jnp.arange(8))
    v = encode_items(state.params, jnp.arange(8))
    np.testing.assert_allclose(np.linalg.norm(np.asarray(u), axis=1), 1.0, atol=1e-3)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(v), axis=1), 1.0, atol=1e-3)
