"""Unit tests for Event/DataMap/PropertyMap/BiMap.

Modeled on the reference's DataMapSpec / BiMapSpec / EventValidation suites
(data/src/test/scala — SURVEY.md §4).
"""

import datetime as dt

import numpy as np
import pytest

from predictionio_tpu.data import (
    BiMap,
    DataMap,
    DataMapError,
    Event,
    EventValidationError,
    aggregate_properties,
    validate_event,
)
from predictionio_tpu.data.json_support import (
    event_from_json,
    event_to_json,
    parse_iso8601,
)

UTC = dt.timezone.utc


def ts(s):
    return dt.datetime.fromisoformat(s).replace(tzinfo=UTC)


class TestDataMap:
    def test_typed_getters(self):
        dm = DataMap({"a": 1, "b": "x", "c": 2.5, "d": True, "e": ["p", "q"], "f": [1, 2.5]})
        assert dm.get_int("a") == 1
        assert dm.get_string("b") == "x"
        assert dm.get_double("c") == 2.5
        assert dm.get_double("a") == 1.0
        assert dm.get_boolean("d") is True
        assert dm.get_string_list("e") == ["p", "q"]
        assert dm.get_double_list("f") == [1.0, 2.5]

    def test_missing_and_mistyped(self):
        dm = DataMap({"a": 1, "n": None})
        with pytest.raises(DataMapError):
            dm.get_string("missing")
        with pytest.raises(DataMapError):
            dm.get_string("a")
        with pytest.raises(DataMapError):
            dm.get_int("n")
        with pytest.raises(DataMapError):
            dm.get_int("a2")

    def test_bool_is_not_int(self):
        dm = DataMap({"d": True})
        with pytest.raises(DataMapError):
            dm.get_int("d")

    def test_opt_getters(self):
        dm = DataMap({"a": 1, "n": None})
        assert dm.opt_int("a") == 1
        assert dm.opt_int("n") is None
        assert dm.opt_int("missing") is None
        assert dm.opt_string_list("missing") is None

    def test_union_and_subtract(self):
        a = DataMap({"x": 1, "y": 2})
        b = DataMap({"y": 3, "z": 4})
        assert a.union(b).to_dict() == {"x": 1, "y": 3, "z": 4}
        assert a.subtract_keys(["y"]).to_dict() == {"x": 1}

    def test_mapping_protocol(self):
        dm = DataMap({"x": 1})
        assert "x" in dm and len(dm) == 1 and list(dm) == ["x"]
        assert dm == DataMap({"x": 1})
        assert dm == {"x": 1}


class TestBiMap:
    def test_string_int_contiguous_first_seen(self):
        bm = BiMap.string_int(["u3", "u1", "u3", "u2", "u1"])
        assert bm["u3"] == 0 and bm["u1"] == 1 and bm["u2"] == 2
        assert len(bm) == 3

    def test_inverse(self):
        bm = BiMap.string_int(["a", "b"])
        assert bm.inverse[0] == "a" and bm.inverse[1] == "b"
        assert bm.inverse.inverse["a"] == 0

    def test_unique_values_required(self):
        with pytest.raises(ValueError):
            BiMap({"a": 1, "b": 1})

    def test_to_numpy_keys(self):
        bm = BiMap.string_int(["b", "a", "c"])
        np.testing.assert_array_equal(bm.to_numpy_keys(), np.array(["b", "a", "c"]))


class TestValidation:
    def _ev(self, **kw):
        base = dict(event="rate", entity_type="user", entity_id="u1")
        base.update(kw)
        return Event(**base)

    def test_valid_plain_event(self):
        validate_event(self._ev(target_entity_type="item", target_entity_id="i1"))

    def test_empty_fields_rejected(self):
        for kw in ({"event": ""}, {"entity_type": ""}, {"entity_id": ""}):
            with pytest.raises(EventValidationError):
                validate_event(self._ev(**kw))

    def test_unknown_reserved_event_rejected(self):
        with pytest.raises(EventValidationError):
            validate_event(self._ev(event="$bogus"))

    def test_set_ok_unset_needs_props(self):
        validate_event(self._ev(event="$set", properties=DataMap({"a": 1})))
        with pytest.raises(EventValidationError):
            validate_event(self._ev(event="$unset"))

    def test_reserved_event_cannot_target(self):
        with pytest.raises(EventValidationError):
            validate_event(
                self._ev(event="$set", properties=DataMap({"a": 1}),
                         target_entity_type="item", target_entity_id="i1")
            )

    def test_target_fields_come_together(self):
        with pytest.raises(EventValidationError):
            validate_event(self._ev(target_entity_type="item"))

    def test_pio_prefix_reserved(self):
        with pytest.raises(EventValidationError):
            validate_event(self._ev(properties=DataMap({"pio_score": 1})))


class TestAggregateProperties:
    def _set(self, t, props):
        return Event(event="$set", entity_type="user", entity_id="u1",
                     properties=DataMap(props), event_time=ts(t))

    def _unset(self, t, keys):
        return Event(event="$unset", entity_type="user", entity_id="u1",
                     properties=DataMap({k: None for k in keys}), event_time=ts(t))

    def _delete(self, t):
        return Event(event="$delete", entity_type="user", entity_id="u1",
                     event_time=ts(t))

    def test_last_write_wins_in_event_time_order(self):
        # Deliberately out of order: fold must sort by event_time.
        evs = [
            self._set("2026-01-03T00:00:00", {"a": 3}),
            self._set("2026-01-01T00:00:00", {"a": 1, "b": "x"}),
            self._set("2026-01-02T00:00:00", {"a": 2, "c": True}),
        ]
        pm = aggregate_properties(evs)
        assert pm.to_dict() == {"a": 3, "b": "x", "c": True}
        assert pm.first_updated == ts("2026-01-01T00:00:00")
        assert pm.last_updated == ts("2026-01-03T00:00:00")

    def test_unset_removes_keys(self):
        evs = [
            self._set("2026-01-01T00:00:00", {"a": 1, "b": 2}),
            self._unset("2026-01-02T00:00:00", ["a"]),
        ]
        pm = aggregate_properties(evs)
        assert pm.to_dict() == {"b": 2}
        assert pm.last_updated == ts("2026-01-02T00:00:00")

    def test_delete_resets_entity(self):
        evs = [
            self._set("2026-01-01T00:00:00", {"a": 1}),
            self._delete("2026-01-02T00:00:00"),
        ]
        assert aggregate_properties(evs) is None
        evs.append(self._set("2026-01-03T00:00:00", {"z": 9}))
        pm = aggregate_properties(evs)
        assert pm.to_dict() == {"z": 9}
        assert pm.first_updated == ts("2026-01-03T00:00:00")

    def test_never_set_is_none(self):
        ev = Event(event="view", entity_type="user", entity_id="u1")
        assert aggregate_properties([ev]) is None


class TestJsonCodec:
    def test_roundtrip(self):
        src = {
            "event": "buy",
            "entityType": "user",
            "entityId": "u7",
            "targetEntityType": "item",
            "targetEntityId": "i3",
            "properties": {"price": 9.99, "tags": ["a"]},
            "eventTime": "2026-07-01T12:34:56.789+00:00",
        }
        ev = event_from_json(src)
        assert ev.event_time == ts("2026-07-01T12:34:56.789")
        out = event_to_json(ev)
        for k in ("event", "entityType", "entityId", "targetEntityType",
                  "targetEntityId", "properties"):
            assert out[k] == src[k]
        assert out["eventTime"].startswith("2026-07-01T12:34:56.789")

    def test_z_suffix_and_naive_default_utc(self):
        assert parse_iso8601("2026-01-01T00:00:00Z") == ts("2026-01-01T00:00:00")
        assert parse_iso8601("2026-01-01T00:00:00") == ts("2026-01-01T00:00:00")
        offset = parse_iso8601("2026-01-01T02:00:00+02:00")
        assert offset == ts("2026-01-01T00:00:00")

    def test_missing_required_field(self):
        with pytest.raises(EventValidationError):
            event_from_json({"event": "x", "entityType": "user"})

    def test_invalid_reserved_event_via_json(self):
        with pytest.raises(EventValidationError):
            event_from_json({"event": "$nope", "entityType": "user", "entityId": "u"})

    def test_defaults_event_time_now(self):
        ev = event_from_json({"event": "view", "entityType": "u", "entityId": "1"})
        assert ev.event_time.tzinfo is not None
