"""End-to-end recommendation template: events → train workflow → predict.

Mirrors the reference's quickstart integration scenario (SURVEY.md §4):
app new → import events → train → query assertions, minus HTTP.
"""

import numpy as np
import pytest

from predictionio_tpu.controller import EngineVariant, RuntimeContext
from predictionio_tpu.data.event import DataMap, Event
from predictionio_tpu.data.storage import App, Storage, get_storage
from predictionio_tpu.templates.recommendation import Query, engine
from predictionio_tpu.workflow.core_workflow import load_models, run_train


@pytest.fixture()
def ctx(pio_home):
    storage = get_storage()
    return RuntimeContext.create(storage=storage)


def _seed_events(ctx, app_name="testapp", n_users=12, n_items=8, seed=0):
    storage: Storage = ctx.storage
    app_id = storage.get_apps().insert(App(id=None, name=app_name))
    storage.get_events().init(app_id)
    rng = np.random.default_rng(seed)
    events = storage.get_events()
    # Two taste cliques: even users like even items, odd like odd.
    for u in range(n_users):
        for i in range(n_items):
            if i % 2 == u % 2 and rng.random() < 0.9:
                events.insert(
                    Event(
                        event="rate",
                        entity_type="user",
                        entity_id=f"u{u}",
                        target_entity_type="item",
                        target_entity_id=f"i{i}",
                        properties=DataMap({"rating": float(3 + 2 * rng.random())}),
                    ),
                    app_id,
                )
    # A couple of implicit buys.
    events.insert(
        Event(event="buy", entity_type="user", entity_id="u0",
              target_entity_type="item", target_entity_id="i2"),
        app_id,
    )
    return app_id


VARIANT = {
    "id": "default",
    "engineFactory": "predictionio_tpu.templates.recommendation:engine",
    "datasource": {"params": {"appName": "testapp"}},
    "algorithms": [
        {"name": "als",
         "params": {"rank": 8, "numIterations": 8, "lambda_": 0.01, "seed": 3}}
    ],
}


def test_train_and_predict(ctx):
    _seed_events(ctx)
    eng = engine()
    variant = EngineVariant.from_dict(VARIANT)
    instance_id = run_train(eng, variant, ctx)
    instance = ctx.storage.get_engine_instances().get(instance_id)
    assert instance.status == "COMPLETED"

    models = load_models(eng, instance, ctx)
    algo = eng.make_algorithms(eng.bind_engine_params(VARIANT))[0]
    result = algo.predict(models[0], Query(user="u0", num=4))
    assert len(result.itemScores) == 4
    # u0 is an even-clique user: top recs should skew even.
    even = sum(1 for s in result.itemScores if int(s.item[1:]) % 2 == 0)
    assert even >= 3
    assert result.itemScores[0].score >= result.itemScores[-1].score


def test_unknown_user_empty_result(ctx):
    _seed_events(ctx)
    eng = engine()
    instance_id = run_train(eng, EngineVariant.from_dict(VARIANT), ctx)
    instance = ctx.storage.get_engine_instances().get(instance_id)
    models = load_models(eng, instance, ctx)
    algo = eng.make_algorithms(eng.bind_engine_params(VARIANT))[0]
    assert algo.predict(models[0], Query(user="nobody")).itemScores == []


def test_no_events_fails_instance(ctx):
    storage: Storage = ctx.storage
    app_id = storage.get_apps().insert(App(id=None, name="testapp"))
    storage.get_events().init(app_id)
    eng = engine()
    with pytest.raises(ValueError):
        run_train(eng, EngineVariant.from_dict(VARIANT), ctx)
    insts = storage.get_engine_instances().get_all()
    assert insts and insts[0].status == "FAILED"


def test_batch_predict_matches_single(ctx):
    _seed_events(ctx)
    eng = engine()
    instance_id = run_train(eng, EngineVariant.from_dict(VARIANT), ctx)
    instance = ctx.storage.get_engine_instances().get(instance_id)
    models = load_models(eng, instance, ctx)
    algo = eng.make_algorithms(eng.bind_engine_params(VARIANT))[0]
    queries = [(0, Query(user="u0", num=3)), (1, Query(user="u1", num=3)),
               (2, Query(user="ghost", num=3))]
    batch = dict(algo.batch_predict(models[0], queries))
    single0 = algo.predict(models[0], Query(user="u0", num=3))
    assert [s.item for s in batch[0].itemScores] == [s.item for s in single0.itemScores]
    assert batch[2].itemScores == []


def test_rate_without_rating_dropped(pio_home):
    """Decided semantic (PARITY.md): malformed rate events are dropped,
    not trained as rating 0.0 — and training proceeds."""
    import numpy as np
    from predictionio_tpu.controller import RuntimeContext
    from predictionio_tpu.data.event import DataMap, Event
    from predictionio_tpu.data.storage import App, get_storage
    from predictionio_tpu.templates.recommendation.engine import (
        DataSourceParams, RecommendationDataSource,
    )

    storage = get_storage()
    app_id = storage.get_apps().insert(App(id=None, name="dropapp"))
    storage.get_events().init(app_id)
    ev = storage.get_events()
    good = [Event(event="rate", entity_type="user", entity_id=f"u{i}",
                  target_entity_type="item", target_entity_id=f"i{i}",
                  properties=DataMap({"rating": float(1 + i % 5)}))
            for i in range(20)]
    bad = [Event(event="rate", entity_type="user", entity_id="u0",
                 target_entity_type="item", target_entity_id="i1",
                 properties=DataMap({})),        # no rating at all
           Event(event="rate", entity_type="user", entity_id="u1",
                 target_entity_type="item", target_entity_id="i2",
                 properties=DataMap({"rating": "not-a-number"}))]
    ev.insert_batch(good + bad, app_id)
    ds = RecommendationDataSource(DataSourceParams(appName="dropapp"))
    ctx = RuntimeContext.create(storage=storage)
    data = ds.read_training(ctx)
    assert len(data.ratings) == 20          # the two malformed rows gone
    assert np.isfinite(data.ratings).all()
    assert (data.ratings > 0).all()


def test_query_num_zero_returns_empty(trained_rec_engine=None):
    """num=0 must yield an empty result, not the whole catalog."""
    import numpy as np
    from predictionio_tpu.ops.topk import host_top_k

    q = np.ones((1, 4), np.float32)
    items = np.ones((10, 4), np.float32)
    s, i = host_top_k(q, items, 0)
    assert s.shape == (1, 0) and i.shape == (1, 0)
    s, i = host_top_k(q, items, -3)
    assert s.shape == (1, 0)


def test_device_mips_paths_match_host(ctx, monkeypatch):
    """Corpora that outgrow the host fast path serve on the device
    (VERDICT r4 item 6): the plain, chunked, and sharded device MIPS
    paths must return the same ranking as host_top_k."""
    _seed_events(ctx)
    eng = engine()
    instance_id = run_train(eng, EngineVariant.from_dict(VARIANT), ctx)
    instance = ctx.storage.get_engine_instances().get(instance_id)
    models = load_models(eng, instance, ctx)
    algo = eng.make_algorithms(eng.bind_engine_params(VARIANT))[0]
    q = [(0, Query(user="u0", num=3)), (1, Query(user="u3", num=3))]
    host = dict(algo.batch_predict(models[0], q))

    # force the device route (plain one-matmul path first)
    monkeypatch.setenv("PIO_SERVE_HOST_MACS", "0")
    plain = dict(algo.batch_predict(models[0], q))
    # then the chunked path (chunk threshold below the corpus size)
    monkeypatch.setenv("PIO_SERVE_CHUNK_ABOVE", "1")
    chunked = dict(algo.batch_predict(models[0], q))
    for got in (plain, chunked):
        for i in (0, 1):
            assert [s.item for s in got[i].itemScores] == \
                [s.item for s in host[i].itemScores]
    # B=1 predict flows through the same routing
    single = algo.predict(models[0], Query(user="u0", num=3))
    assert [s.item for s in single.itemScores] == \
        [s.item for s in host[0].itemScores]


def test_sharded_corpus_serving_matches_host(ctx, monkeypatch):
    """Serving-time re-parallelization (SURVEY §3.2): load_models with a
    serving mesh re-shards a large corpus over the data axis (post_load
    hook), predict then routes through sharded_top_k — and must agree
    with the host ranking, including the masking of mesh-padding rows."""
    from jax.sharding import NamedSharding

    from predictionio_tpu.parallel.mesh import make_mesh

    _seed_events(ctx)
    eng = engine()
    instance_id = run_train(eng, EngineVariant.from_dict(VARIANT), ctx)
    instance = ctx.storage.get_engine_instances().get(instance_id)
    # every corpus counts as "large" so the reload re-shards it
    monkeypatch.setenv("PIO_SERVE_SHARD_ABOVE", "1")
    mesh = make_mesh({"data": 8})
    ctx_mesh = RuntimeContext.create(storage=ctx.storage, mesh=mesh)
    models = load_models(eng, instance, ctx_mesh)
    itf = models[0].model.item_factors
    assert isinstance(itf.sharding, NamedSharding) \
        and itf.sharding.spec[0] == "data", "post_load must re-shard"
    assert itf.shape[0] % 8 == 0  # padded to divide the axis
    algo = eng.make_algorithms(eng.bind_engine_params(VARIANT))[0]
    q = [(0, Query(user="u0", num=4)), (1, Query(user="u1", num=4))]
    host = dict(algo.batch_predict(models[0], q))
    monkeypatch.setenv("PIO_SERVE_HOST_MACS", "0")
    dev = dict(algo.batch_predict(models[0], q))
    for i in (0, 1):
        assert [s.item for s in dev[i].itemScores] == \
            [s.item for s in host[i].itemScores]
