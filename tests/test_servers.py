"""Event Server + Engine Server over real HTTP (reference §3.2/§3.3 parity)."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from predictionio_tpu.controller import EngineVariant, RuntimeContext
from predictionio_tpu.data.storage import AccessKey, App, Channel, get_storage
from predictionio_tpu.server import EngineServer, EventServer
from predictionio_tpu.templates.recommendation import engine
from predictionio_tpu.workflow.core_workflow import run_train


def _req(method, url, body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.loads(resp.read() or b"null")
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, json.loads(payload) if payload else None


@pytest.fixture()
def event_server(pio_home):
    storage = get_storage()
    app_id = storage.get_apps().insert(App(id=None, name="app1"))
    storage.get_events().init(app_id)
    key = storage.get_access_keys().insert(AccessKey(key="", app_id=app_id))
    srv = EventServer(storage=storage, host="127.0.0.1", port=0)
    srv.start()
    yield srv, key, storage, app_id
    srv.stop()


class TestEventServer:
    def test_alive(self, event_server):
        srv, *_ = event_server
        status, body = _req("GET", f"http://127.0.0.1:{srv.port}/")
        assert (status, body) == (200, {"status": "alive"})

    def test_ingest_and_query_roundtrip(self, event_server):
        srv, key, *_ = event_server
        base = f"http://127.0.0.1:{srv.port}"
        ev = {"event": "rate", "entityType": "user", "entityId": "u1",
              "targetEntityType": "item", "targetEntityId": "i1",
              "properties": {"rating": 4.5},
              "eventTime": "2026-01-02T03:04:05.000Z"}
        status, body = _req("POST", f"{base}/events.json?accessKey={key}", ev)
        assert status == 201 and body["eventId"]
        event_id = body["eventId"]

        status, one = _req("GET", f"{base}/events/{event_id}.json?accessKey={key}")
        assert status == 200
        assert one["event"] == "rate"
        assert one["properties"]["rating"] == 4.5
        assert one["eventTime"].startswith("2026-01-02T03:04:05")

        status, found = _req(
            "GET", f"{base}/events.json?accessKey={key}&entityId=u1")
        assert status == 200 and len(found) == 1

        status, _ = _req("DELETE", f"{base}/events/{event_id}.json?accessKey={key}")
        assert status == 200
        status, _ = _req("GET", f"{base}/events/{event_id}.json?accessKey={key}")
        assert status == 404

    def test_batch_ingest(self, event_server):
        srv, key, *_ = event_server
        base = f"http://127.0.0.1:{srv.port}"
        batch = [
            {"event": "buy", "entityType": "user", "entityId": f"u{i}",
             "targetEntityType": "item", "targetEntityId": "i1"}
            for i in range(3)
        ] + [{"entityType": "user", "entityId": "broken"}]  # missing "event"
        status, results = _req("POST", f"{base}/batch/events.json?accessKey={key}", batch)
        assert status == 200
        assert [r["status"] for r in results] == [201, 201, 201, 400]

    def test_batch_size_limit(self, event_server):
        srv, key, *_ = event_server
        base = f"http://127.0.0.1:{srv.port}"
        batch = [{"event": "e", "entityType": "t", "entityId": "x"}] * 51
        status, _ = _req("POST", f"{base}/batch/events.json?accessKey={key}", batch)
        assert status == 400

    def test_auth_rejected(self, event_server):
        srv, *_ = event_server
        base = f"http://127.0.0.1:{srv.port}"
        ev = {"event": "rate", "entityType": "user", "entityId": "u1"}
        assert _req("POST", f"{base}/events.json?accessKey=WRONG", ev)[0] == 401
        assert _req("POST", f"{base}/events.json", ev)[0] == 401

    def test_event_allowlist(self, event_server):
        srv, _, storage, app_id = event_server
        limited = storage.get_access_keys().insert(
            AccessKey(key="", app_id=app_id, events=("view",)))
        base = f"http://127.0.0.1:{srv.port}"
        ok = {"event": "view", "entityType": "user", "entityId": "u1"}
        bad = {"event": "rate", "entityType": "user", "entityId": "u1"}
        assert _req("POST", f"{base}/events.json?accessKey={limited}", ok)[0] == 201
        assert _req("POST", f"{base}/events.json?accessKey={limited}", bad)[0] == 403

    def test_channel_ingest(self, event_server):
        srv, key, storage, app_id = event_server
        chan_id = storage.get_channels().insert(
            Channel(id=None, name="mobile", app_id=app_id))
        storage.get_events().init(app_id, chan_id)  # as `pio app channel-new` does
        base = f"http://127.0.0.1:{srv.port}"
        ev = {"event": "view", "entityType": "user", "entityId": "u9"}
        s, _ = _req("POST", f"{base}/events.json?accessKey={key}&channel=mobile", ev)
        assert s == 201
        # Default channel read does NOT see it (empty match = 200 []);
        # channel read does.
        s, none = _req("GET", f"{base}/events.json?accessKey={key}&entityId=u9")
        assert s == 200 and none == []
        s, found = _req(
            "GET", f"{base}/events.json?accessKey={key}&entityId=u9&channel=mobile")
        assert s == 200 and len(found) == 1
        s, _ = _req("POST", f"{base}/events.json?accessKey={key}&channel=nope", ev)
        assert s == 400

    def test_stats_and_metrics(self, event_server):
        srv, key, *_ = event_server
        base = f"http://127.0.0.1:{srv.port}"
        ev = {"event": "view", "entityType": "user", "entityId": "u1"}
        _req("POST", f"{base}/events.json?accessKey={key}", ev)
        status, stats = _req("GET", f"{base}/stats.json")
        assert status == 200 and stats["eventCounts"].get("view") == 1
        req = urllib.request.Request(f"{base}/metrics")
        with urllib.request.urlopen(req, timeout=10) as resp:
            text = resp.read().decode()
            assert resp.headers["Content-Type"].startswith("text/plain")
        assert "pio_event_requests_total" in text
        # the exposition must be valid Prometheus text (strict parser)
        from tests.test_obs import parse_prometheus

        samples = parse_prometheus(text)
        assert ({"status": "201"}, 1.0) in samples["pio_event_requests_total"]
        assert ({"event": "view"}, 1.0) in samples["pio_event_events_total"]
        assert samples["pio_event_request_latency_ms_count"][0][1] >= 1

    def test_request_id_round_trips(self, event_server):
        srv, *_ = event_server
        base = f"http://127.0.0.1:{srv.port}"
        req = urllib.request.Request(f"{base}/",
                                     headers={"X-Request-ID": "client-id-42"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.headers["X-Request-ID"] == "client-id-42"
        # absent → server generates one and still returns it
        with urllib.request.urlopen(f"{base}/", timeout=10) as resp:
            gen = resp.headers["X-Request-ID"]
        assert gen and len(gen) == 32 and gen != "client-id-42"
        # hostile ids are sanitized, not echoed raw
        req = urllib.request.Request(
            f"{base}/", headers={"X-Request-ID": "a\tb c"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.headers["X-Request-ID"] == "abc"

    def test_traces_json_records_requests(self, event_server):
        import time

        srv, key, *_ = event_server
        base = f"http://127.0.0.1:{srv.port}"
        ev = {"event": "view", "entityType": "user", "entityId": "u1"}
        _req("POST", f"{base}/events.json?accessKey={key}&", ev)
        # per-request traces require auth (unlike the aggregate /metrics)
        assert _req("GET", f"{base}/traces.json")[0] == 401
        # the trace is recorded just AFTER the response bytes go out
        posts = []
        for _ in range(50):
            status, body = _req("GET", f"{base}/traces.json?accessKey={key}")
            assert status == 200
            posts = [t for t in body["traces"]
                     if t["attrs"].get("path") == "/events.json"]
            if posts:
                break
            time.sleep(0.02)
        assert posts, "POST /events.json trace never reached the ring"
        t = posts[0]
        assert t["name"] == "http.request"
        assert t["attrs"]["server"] == "event"
        assert t["attrs"]["status"] == 201
        names = [s["name"] for s in t["spans"]]
        assert names == ["http.read", "http.handle", "http.respond"]


@pytest.fixture()
def deployed(pio_home):
    storage = get_storage()
    ctx = RuntimeContext.create(storage=storage)
    app_id = storage.get_apps().insert(App(id=None, name="testapp"))
    storage.get_events().init(app_id)
    from predictionio_tpu.data.event import DataMap, Event

    rng = np.random.default_rng(0)
    for u in range(10):
        for i in range(8):
            if i % 2 == u % 2 and rng.random() < 0.95:
                storage.get_events().insert(
                    Event(event="rate", entity_type="user", entity_id=f"u{u}",
                          target_entity_type="item", target_entity_id=f"i{i}",
                          properties=DataMap({"rating": 4.0})), app_id)
    variant = EngineVariant.from_dict({
        "engineFactory": "predictionio_tpu.templates.recommendation:engine",
        "datasource": {"params": {"appName": "testapp"}},
        "algorithms": [{"name": "als", "params": {"rank": 4, "numIterations": 5}}],
    })
    eng = engine()
    run_train(eng, variant, ctx)
    srv = EngineServer(eng, variant, storage, host="127.0.0.1", port=0)
    srv.start()
    yield srv, storage, ctx, eng, variant
    srv.stop()


class TestEngineServer:
    def test_status_page(self, deployed):
        srv, *_ = deployed
        status, body = _req("GET", f"http://127.0.0.1:{srv.port}/")
        assert status == 200
        assert body["status"] == "alive" and body["engineInstanceId"]

    def test_query(self, deployed):
        srv, *_ = deployed
        status, body = _req("POST", f"http://127.0.0.1:{srv.port}/queries.json",
                            {"user": "u0", "num": 3})
        assert status == 200
        assert len(body["itemScores"]) == 3
        items = [s["item"] for s in body["itemScores"]]
        assert all(int(i[1:]) % 2 == 0 for i in items)  # u0 is even-clique

    def test_query_binding_error(self, deployed):
        srv, *_ = deployed
        status, body = _req("POST", f"http://127.0.0.1:{srv.port}/queries.json",
                            {"nope": 1})
        assert status == 400

    def test_reload_picks_up_retrain(self, deployed):
        srv, storage, ctx, eng, variant = deployed
        old = srv._instance.id
        run_train(eng, variant, ctx)
        status, body = _req("POST", f"http://127.0.0.1:{srv.port}/reload")
        assert status == 200
        assert body["engineInstanceId"] != old

    def test_metrics_track_queries(self, deployed):
        srv, *_ = deployed
        _req("POST", f"http://127.0.0.1:{srv.port}/queries.json",
             {"user": "u0", "num": 2})
        req = urllib.request.Request(f"http://127.0.0.1:{srv.port}/metrics")
        with urllib.request.urlopen(req, timeout=10) as resp:
            text = resp.read().decode()
        assert "pio_query_requests_total 1" in text
        from tests.test_obs import parse_prometheus

        samples = parse_prometheus(text)
        assert samples["pio_query_latency_ms_count"][0][1] == 1
        # the registry is process-wide: training-phase series from the
        # fixture's run_train surface in the SERVING exposition too
        assert any(lb.get("phase") == "train.algorithm"
                   for lb, _ in samples.get("pio_train_phase_ms_count", []))

    def test_metrics_expose_runtime_introspection(self, deployed):
        """ISSUE 3 acceptance: a live engine server's /metrics carries
        the compile-tracking and device-memory instrument families."""
        srv, *_ = deployed
        req = urllib.request.Request(f"http://127.0.0.1:{srv.port}/metrics")
        with urllib.request.urlopen(req, timeout=10) as resp:
            text = resp.read().decode()
        assert "pio_xla_compile_total" in text
        assert "pio_device_mem_bytes" in text
        from tests.test_obs import parse_prometheus

        samples = parse_prometheus(text)
        # CPU backend has no allocator stats, but the live-array
        # fallback gives real series (the loaded model's arrays).
        assert any(lb.get("kind") == "live_bytes" and v > 0
                   for lb, v in samples.get("pio_device_mem_bytes", []))

    def test_timeline_endpoint(self, deployed):
        from predictionio_tpu.obs import get_timeline

        srv, *_ = deployed
        get_timeline().record("toy", host_wait_ms=1, h2d_ms=2,
                              device_wait_ms=3, device_step_ms=4,
                              examples=8)
        base = f"http://127.0.0.1:{srv.port}"
        status, body = _req("GET", f"{base}/timeline.json")
        assert status == 200 and body["steps"][0]["model"] == "toy"
        status, body = _req("GET",
                            f"{base}/timeline.json?format=summary&model=toy")
        assert status == 200
        assert body["models"]["toy"]["phase_ms"]["h2d"] == 2
        status, chrome = _req("GET", f"{base}/timeline.json?format=chrome")
        assert status == 200
        assert any(e["ph"] == "X" for e in chrome["traceEvents"])

    def test_stats_json_view(self, deployed):
        srv, *_ = deployed
        _req("POST", f"http://127.0.0.1:{srv.port}/queries.json",
             {"user": "u0", "num": 2})
        status, stats = _req("GET",
                             f"http://127.0.0.1:{srv.port}/stats.json")
        assert status == 200
        assert stats["requestCount"] == 1 and stats["errorCount"] == 0
        assert stats["latencyMs"]["p50"] >= 0

    def test_query_trace_covers_wall_time(self, deployed, tmp_path,
                                          monkeypatch):
        """Acceptance: a served query's trace decomposes into spans with
        no large unattributed gap, and exports as JSONL."""
        import json as _json
        import time

        trace_file = tmp_path / "traces.jsonl"
        monkeypatch.setenv("PIO_TRACE_FILE", str(trace_file))
        srv, *_ = deployed
        # Coverage is about the DISPATCH path's spans: cache hits on the
        # repeated query answer in sub-millisecond walls where fixed
        # inter-span gaps dominate the ratio, so bypass the cache here.
        srv.result_cache.set_enabled(False)
        # several queries: the first pays bytecode/jit warm-up; the
        # steady-state ones must hit the 95% attribution target
        for _ in range(8):
            status, _ = _req("POST",
                             f"http://127.0.0.1:{srv.port}/queries.json",
                             {"user": "u0", "num": 3})
            assert status == 200
        docs = []
        for _ in range(50):
            if trace_file.exists():
                docs = [_json.loads(line) for line in
                        trace_file.read_text().strip().splitlines()]
                if sum(d["attrs"].get("path") == "/queries.json"
                       for d in docs) >= 8:
                    break
            time.sleep(0.02)
        traces = [d for d in docs
                  if d["attrs"].get("path") == "/queries.json"]
        assert traces, "no /queries.json trace reached PIO_TRACE_FILE"
        t = traces[-1]
        assert t["attrs"]["server"] == "engine"
        # spans (read+handle+respond) cover >= 95% of request wall time at
        # steady state; every request, warm-up included, stays gap-small
        covs = [sum(s["durationMs"] for s in d["spans"]) / d["durationMs"]
                for d in traces]
        assert max(covs) >= 0.95, f"no query reached 95% coverage: {covs}"
        # the floor guards against a SYSTEMIC gap; a single request losing
        # its timeslice to the scheduler mid-flight (shared-core CI) is
        # measurement noise, so the worst sample is excluded
        assert sorted(covs)[1] >= 0.80, f"large unattributed gap: {covs}"
        # ISSUE 6: the predict itself runs on the batcher thread; the
        # request's span tree carries the batcher.dispatch JOIN event,
        # and the dispatch is its own root trace keyed by batch_id.
        handle = next(s for s in t["spans"] if s["name"] == "http.handle")
        joins = [s for s in handle.get("spans", [])
                 if s["name"] == "batcher.dispatch"]
        assert joins, "request span lost its batcher.dispatch join event"
        ev = joins[0]["attrs"]
        assert ev["batch_size"] >= 1 and ev["generation"] >= 1
        dispatches = [d for d in docs if d.get("name") == "batcher.dispatch"
                      and d["attrs"].get("batch_id") == ev["batch_id"]]
        assert dispatches, "no batcher.dispatch root trace for the batch"
        assert dispatches[0]["attrs"]["model"] == "default"

    def test_engine_request_id_round_trips(self, deployed):
        srv, *_ = deployed
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/",
            headers={"X-Request-ID": "q-7"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.headers["X-Request-ID"] == "q-7"


def test_dc_to_json_matches_asdict_on_wire():
    """The serving fast converter must keep dataclasses.asdict's JSON
    contract for nested dataclasses in lists, tuples and dict values
    (tuples become JSON arrays either way)."""
    import dataclasses
    import json
    from typing import Dict, List, Tuple

    from predictionio_tpu.server.engine_server import _dc_to_json

    @dataclasses.dataclass
    class Inner:
        a: int

    @dataclasses.dataclass
    class Outer:
        xs: Tuple[Inner, ...]
        ys: List[Inner]
        d: Dict[str, Inner]
        n: Inner
        s: str

    o = Outer(xs=(Inner(1), Inner(2)), ys=[Inner(5)], d={"k": Inner(3)},
              n=Inner(4), s="z")
    assert json.dumps(_dc_to_json(o), sort_keys=True) == \
        json.dumps(dataclasses.asdict(o), sort_keys=True)


class TestServerPluginSeam:
    """SURVEY §5.1: EngineServerPlugin/EventServerPlugin equivalents —
    env-discovered request instrumentation invoked per request with
    (route, status, ms), able to inject response headers, active over
    the python HTTP transport (native covered in test_native.py)."""

    def test_event_server_plugin_counts_and_injects(self, pio_home,
                                                    monkeypatch):
        import urllib.request

        import tests.plugin_fixture as pf
        from predictionio_tpu.data.storage import get_storage
        from predictionio_tpu.data.storage.base import AccessKey, App
        from predictionio_tpu.server.event_server import EventServer

        monkeypatch.setenv("PIO_EVENTSERVER_PLUGINS",
                           "tests.plugin_fixture:make_plugin")
        storage = get_storage()
        app_id = storage.get_apps().insert(App(id=None, name="plugapp"))
        storage.get_events().init(app_id)
        key = storage.get_access_keys().insert(AccessKey.generate(app_id))
        srv = EventServer(storage, host="127.0.0.1", port=0)
        plugin = pf.LAST
        assert plugin is not None and plugin.started_with is srv
        srv.start(block=False)
        try:
            ev = {"event": "rate", "entityType": "user", "entityId": "u1"}
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/events.json?accessKey={key}",
                data=json.dumps(ev).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as r:
                assert r.status == 201
                assert r.headers["X-Plugin-Count"] == "1"
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/", timeout=10) as r:
                assert r.headers["X-Plugin-Count"] == "2"
            routes = [r[0] for r in plugin.requests]
            assert routes == ["POST /events.json", "GET /"]
            assert all(isinstance(r[2], float) for r in plugin.requests)
        finally:
            srv.stop()
        # stop() runs the plugin's shutdown hook (lifecycle contract)
        assert plugin.started_with is None

    def test_metrics_plugin_matches_builtin_counters(self, pio_home):
        """The MetricsPlugin exemplar and the built-in instrumentation
        feed the SAME registry and must agree on totals — proving the
        plugin path reports identically to the built-in path."""
        from predictionio_tpu.data.storage import get_storage
        from predictionio_tpu.obs import get_registry
        from predictionio_tpu.server.event_server import EventServer
        from predictionio_tpu.server.plugins import (
            MetricsPlugin, PluginManager,
        )

        srv = EventServer(get_storage(), host="127.0.0.1", port=0,
                          plugins=PluginManager([MetricsPlugin()]))
        srv.start(block=False)
        try:
            for _ in range(3):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/", timeout=10)
            _req("GET", f"http://127.0.0.1:{srv.port}/nope.json")
        finally:
            srv.stop()
        reg = get_registry()
        builtin = reg.get("pio_event_requests_total")
        plugin = reg.get("pio_plugin_requests_total")
        assert builtin.total() == plugin.total() == 4
        assert plugin.value(route="GET /", status="200") == 3
        assert plugin.value(route="GET /nope.json", status="401") == 1
        # one exposition carries both
        from tests.test_obs import parse_prometheus

        samples = parse_prometheus(reg.render())
        assert "pio_plugin_requests_total" in samples
        assert "pio_event_requests_total" in samples

    def test_plugin_failure_does_not_break_requests(self, pio_home,
                                                    monkeypatch):
        import urllib.request

        from predictionio_tpu.data.storage import get_storage
        from predictionio_tpu.server.event_server import EventServer
        from predictionio_tpu.server.plugins import (
            PluginManager, ServerPlugin,
        )

        class Exploding(ServerPlugin):
            def on_request(self, route, status, ms):
                raise RuntimeError("boom")

        class Injecting(ServerPlugin):
            def on_request(self, route, status, ms):
                # CRLF in values must not smuggle extra headers
                return {"X-Safe": "a\r\nX-Evil: yes"}

        srv = EventServer(get_storage(), host="127.0.0.1", port=0,
                          plugins=PluginManager([Exploding(), Injecting()]))
        srv.start(block=False)
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/", timeout=10) as r:
                assert r.status == 200
                assert "X-Evil" not in r.headers
                assert r.headers["X-Safe"] == "a  X-Evil: yes"
        finally:
            srv.stop()
