"""Event Server + Engine Server over real HTTP (reference §3.2/§3.3 parity)."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from predictionio_tpu.controller import EngineVariant, RuntimeContext
from predictionio_tpu.data.storage import AccessKey, App, Channel, get_storage
from predictionio_tpu.server import EngineServer, EventServer
from predictionio_tpu.templates.recommendation import engine
from predictionio_tpu.workflow.core_workflow import run_train


def _req(method, url, body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.loads(resp.read() or b"null")
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, json.loads(payload) if payload else None


@pytest.fixture()
def event_server(pio_home):
    storage = get_storage()
    app_id = storage.get_apps().insert(App(id=None, name="app1"))
    storage.get_events().init(app_id)
    key = storage.get_access_keys().insert(AccessKey(key="", app_id=app_id))
    srv = EventServer(storage=storage, host="127.0.0.1", port=0)
    srv.start()
    yield srv, key, storage, app_id
    srv.stop()


class TestEventServer:
    def test_alive(self, event_server):
        srv, *_ = event_server
        status, body = _req("GET", f"http://127.0.0.1:{srv.port}/")
        assert (status, body) == (200, {"status": "alive"})

    def test_ingest_and_query_roundtrip(self, event_server):
        srv, key, *_ = event_server
        base = f"http://127.0.0.1:{srv.port}"
        ev = {"event": "rate", "entityType": "user", "entityId": "u1",
              "targetEntityType": "item", "targetEntityId": "i1",
              "properties": {"rating": 4.5},
              "eventTime": "2026-01-02T03:04:05.000Z"}
        status, body = _req("POST", f"{base}/events.json?accessKey={key}", ev)
        assert status == 201 and body["eventId"]
        event_id = body["eventId"]

        status, one = _req("GET", f"{base}/events/{event_id}.json?accessKey={key}")
        assert status == 200
        assert one["event"] == "rate"
        assert one["properties"]["rating"] == 4.5
        assert one["eventTime"].startswith("2026-01-02T03:04:05")

        status, found = _req(
            "GET", f"{base}/events.json?accessKey={key}&entityId=u1")
        assert status == 200 and len(found) == 1

        status, _ = _req("DELETE", f"{base}/events/{event_id}.json?accessKey={key}")
        assert status == 200
        status, _ = _req("GET", f"{base}/events/{event_id}.json?accessKey={key}")
        assert status == 404

    def test_batch_ingest(self, event_server):
        srv, key, *_ = event_server
        base = f"http://127.0.0.1:{srv.port}"
        batch = [
            {"event": "buy", "entityType": "user", "entityId": f"u{i}",
             "targetEntityType": "item", "targetEntityId": "i1"}
            for i in range(3)
        ] + [{"entityType": "user", "entityId": "broken"}]  # missing "event"
        status, results = _req("POST", f"{base}/batch/events.json?accessKey={key}", batch)
        assert status == 200
        assert [r["status"] for r in results] == [201, 201, 201, 400]

    def test_batch_size_limit(self, event_server):
        srv, key, *_ = event_server
        base = f"http://127.0.0.1:{srv.port}"
        batch = [{"event": "e", "entityType": "t", "entityId": "x"}] * 51
        status, _ = _req("POST", f"{base}/batch/events.json?accessKey={key}", batch)
        assert status == 400

    def test_auth_rejected(self, event_server):
        srv, *_ = event_server
        base = f"http://127.0.0.1:{srv.port}"
        ev = {"event": "rate", "entityType": "user", "entityId": "u1"}
        assert _req("POST", f"{base}/events.json?accessKey=WRONG", ev)[0] == 401
        assert _req("POST", f"{base}/events.json", ev)[0] == 401

    def test_event_allowlist(self, event_server):
        srv, _, storage, app_id = event_server
        limited = storage.get_access_keys().insert(
            AccessKey(key="", app_id=app_id, events=("view",)))
        base = f"http://127.0.0.1:{srv.port}"
        ok = {"event": "view", "entityType": "user", "entityId": "u1"}
        bad = {"event": "rate", "entityType": "user", "entityId": "u1"}
        assert _req("POST", f"{base}/events.json?accessKey={limited}", ok)[0] == 201
        assert _req("POST", f"{base}/events.json?accessKey={limited}", bad)[0] == 403

    def test_channel_ingest(self, event_server):
        srv, key, storage, app_id = event_server
        chan_id = storage.get_channels().insert(
            Channel(id=None, name="mobile", app_id=app_id))
        storage.get_events().init(app_id, chan_id)  # as `pio app channel-new` does
        base = f"http://127.0.0.1:{srv.port}"
        ev = {"event": "view", "entityType": "user", "entityId": "u9"}
        s, _ = _req("POST", f"{base}/events.json?accessKey={key}&channel=mobile", ev)
        assert s == 201
        # Default channel read does NOT see it (empty match = 200 []);
        # channel read does.
        s, none = _req("GET", f"{base}/events.json?accessKey={key}&entityId=u9")
        assert s == 200 and none == []
        s, found = _req(
            "GET", f"{base}/events.json?accessKey={key}&entityId=u9&channel=mobile")
        assert s == 200 and len(found) == 1
        s, _ = _req("POST", f"{base}/events.json?accessKey={key}&channel=nope", ev)
        assert s == 400

    def test_stats_and_metrics(self, event_server):
        srv, key, *_ = event_server
        base = f"http://127.0.0.1:{srv.port}"
        ev = {"event": "view", "entityType": "user", "entityId": "u1"}
        _req("POST", f"{base}/events.json?accessKey={key}", ev)
        status, stats = _req("GET", f"{base}/stats.json")
        assert status == 200 and stats["eventCounts"].get("view") == 1
        req = urllib.request.Request(f"{base}/metrics")
        with urllib.request.urlopen(req, timeout=10) as resp:
            text = resp.read().decode()
        assert "pio_event_requests_total" in text


@pytest.fixture()
def deployed(pio_home):
    storage = get_storage()
    ctx = RuntimeContext.create(storage=storage)
    app_id = storage.get_apps().insert(App(id=None, name="testapp"))
    storage.get_events().init(app_id)
    from predictionio_tpu.data.event import DataMap, Event

    rng = np.random.default_rng(0)
    for u in range(10):
        for i in range(8):
            if i % 2 == u % 2 and rng.random() < 0.95:
                storage.get_events().insert(
                    Event(event="rate", entity_type="user", entity_id=f"u{u}",
                          target_entity_type="item", target_entity_id=f"i{i}",
                          properties=DataMap({"rating": 4.0})), app_id)
    variant = EngineVariant.from_dict({
        "engineFactory": "predictionio_tpu.templates.recommendation:engine",
        "datasource": {"params": {"appName": "testapp"}},
        "algorithms": [{"name": "als", "params": {"rank": 4, "numIterations": 5}}],
    })
    eng = engine()
    run_train(eng, variant, ctx)
    srv = EngineServer(eng, variant, storage, host="127.0.0.1", port=0)
    srv.start()
    yield srv, storage, ctx, eng, variant
    srv.stop()


class TestEngineServer:
    def test_status_page(self, deployed):
        srv, *_ = deployed
        status, body = _req("GET", f"http://127.0.0.1:{srv.port}/")
        assert status == 200
        assert body["status"] == "alive" and body["engineInstanceId"]

    def test_query(self, deployed):
        srv, *_ = deployed
        status, body = _req("POST", f"http://127.0.0.1:{srv.port}/queries.json",
                            {"user": "u0", "num": 3})
        assert status == 200
        assert len(body["itemScores"]) == 3
        items = [s["item"] for s in body["itemScores"]]
        assert all(int(i[1:]) % 2 == 0 for i in items)  # u0 is even-clique

    def test_query_binding_error(self, deployed):
        srv, *_ = deployed
        status, body = _req("POST", f"http://127.0.0.1:{srv.port}/queries.json",
                            {"nope": 1})
        assert status == 400

    def test_reload_picks_up_retrain(self, deployed):
        srv, storage, ctx, eng, variant = deployed
        old = srv._instance.id
        run_train(eng, variant, ctx)
        status, body = _req("POST", f"http://127.0.0.1:{srv.port}/reload")
        assert status == 200
        assert body["engineInstanceId"] != old

    def test_metrics_track_queries(self, deployed):
        srv, *_ = deployed
        _req("POST", f"http://127.0.0.1:{srv.port}/queries.json",
             {"user": "u0", "num": 2})
        req = urllib.request.Request(f"http://127.0.0.1:{srv.port}/metrics")
        with urllib.request.urlopen(req, timeout=10) as resp:
            text = resp.read().decode()
        assert "pio_query_requests_total 1" in text


def test_dc_to_json_matches_asdict_on_wire():
    """The serving fast converter must keep dataclasses.asdict's JSON
    contract for nested dataclasses in lists, tuples and dict values
    (tuples become JSON arrays either way)."""
    import dataclasses
    import json
    from typing import Dict, List, Tuple

    from predictionio_tpu.server.engine_server import _dc_to_json

    @dataclasses.dataclass
    class Inner:
        a: int

    @dataclasses.dataclass
    class Outer:
        xs: Tuple[Inner, ...]
        ys: List[Inner]
        d: Dict[str, Inner]
        n: Inner
        s: str

    o = Outer(xs=(Inner(1), Inner(2)), ys=[Inner(5)], d={"k": Inner(3)},
              n=Inner(4), s="z")
    assert json.dumps(_dc_to_json(o), sort_keys=True) == \
        json.dumps(dataclasses.asdict(o), sort_keys=True)


class TestServerPluginSeam:
    """SURVEY §5.1: EngineServerPlugin/EventServerPlugin equivalents —
    env-discovered request instrumentation invoked per request with
    (route, status, ms), able to inject response headers, active over
    the python HTTP transport (native covered in test_native.py)."""

    def test_event_server_plugin_counts_and_injects(self, pio_home,
                                                    monkeypatch):
        import urllib.request

        import tests.plugin_fixture as pf
        from predictionio_tpu.data.storage import get_storage
        from predictionio_tpu.data.storage.base import AccessKey, App
        from predictionio_tpu.server.event_server import EventServer

        monkeypatch.setenv("PIO_EVENTSERVER_PLUGINS",
                           "tests.plugin_fixture:make_plugin")
        storage = get_storage()
        app_id = storage.get_apps().insert(App(id=None, name="plugapp"))
        storage.get_events().init(app_id)
        key = storage.get_access_keys().insert(AccessKey.generate(app_id))
        srv = EventServer(storage, host="127.0.0.1", port=0)
        plugin = pf.LAST
        assert plugin is not None and plugin.started_with is srv
        srv.start(block=False)
        try:
            ev = {"event": "rate", "entityType": "user", "entityId": "u1"}
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/events.json?accessKey={key}",
                data=json.dumps(ev).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as r:
                assert r.status == 201
                assert r.headers["X-Plugin-Count"] == "1"
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/", timeout=10) as r:
                assert r.headers["X-Plugin-Count"] == "2"
            routes = [r[0] for r in plugin.requests]
            assert routes == ["POST /events.json", "GET /"]
            assert all(isinstance(r[2], float) for r in plugin.requests)
        finally:
            srv.stop()
        # stop() runs the plugin's shutdown hook (lifecycle contract)
        assert plugin.started_with is None

    def test_plugin_failure_does_not_break_requests(self, pio_home,
                                                    monkeypatch):
        import urllib.request

        from predictionio_tpu.data.storage import get_storage
        from predictionio_tpu.server.event_server import EventServer
        from predictionio_tpu.server.plugins import (
            PluginManager, ServerPlugin,
        )

        class Exploding(ServerPlugin):
            def on_request(self, route, status, ms):
                raise RuntimeError("boom")

        class Injecting(ServerPlugin):
            def on_request(self, route, status, ms):
                # CRLF in values must not smuggle extra headers
                return {"X-Safe": "a\r\nX-Evil: yes"}

        srv = EventServer(get_storage(), host="127.0.0.1", port=0,
                          plugins=PluginManager([Exploding(), Injecting()]))
        srv.start(block=False)
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/", timeout=10) as r:
                assert r.status == 200
                assert "X-Evil" not in r.headers
                assert r.headers["X-Safe"] == "a  X-Evil: yes"
        finally:
            srv.stop()
