"""naive_bayes + linear models vs sklearn-free numpy oracles."""

import numpy as np
import jax.numpy as jnp

from predictionio_tpu.models import linear as lr_lib
from predictionio_tpu.models import naive_bayes as nb_lib
from predictionio_tpu.parallel.mesh import make_mesh


def _blobs(seed=0, n=240, d=3, c=3):
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((c, d)) * 4
    y = np.repeat(np.arange(c), n // c)
    x = centers[y] + rng.standard_normal((n, d))
    return x.astype(np.float32), y


class TestNaiveBayes:
    def test_multinomial_matches_oracle(self):
        rng = np.random.default_rng(1)
        x = rng.poisson(3, (60, 4)).astype(np.float32)
        y = rng.integers(0, 2, 60)
        m = nb_lib.train_multinomial(x, y, 2, alpha=1.0)
        # Oracle: standard smoothed count ratios.
        for c in range(2):
            counts = x[y == c].sum(axis=0) + 1.0
            expect = np.log(counts / counts.sum())
            np.testing.assert_allclose(np.asarray(m.feature_log_prob[c]),
                                       expect, rtol=1e-5)
            np.testing.assert_allclose(float(m.class_log_prior[c]),
                                       np.log((y == c).mean()), rtol=1e-5)

    def test_gaussian_classifies_blobs(self):
        x, y = _blobs()
        m = nb_lib.train_gaussian(x, y, 3)
        pred = np.asarray(nb_lib.predict_log_proba(m, jnp.asarray(x))).argmax(1)
        assert (pred == y).mean() > 0.85

    def test_mesh_equivalence(self):
        x, y = _blobs(seed=2)
        m1 = nb_lib.train_multinomial(np.abs(x), y, 3)
        mesh = make_mesh({"data": 8})
        m2 = nb_lib.train_multinomial(np.abs(x), y, 3, mesh=mesh)
        np.testing.assert_allclose(np.asarray(m1.feature_log_prob),
                                   np.asarray(m2.feature_log_prob),
                                   rtol=1e-5, atol=1e-6)


class TestLogisticRegression:
    def test_separable_blobs(self):
        x, y = _blobs(seed=3)
        cfg = lr_lib.LogisticRegressionConfig(n_classes=3, steps=300,
                                              learning_rate=0.3)
        m = lr_lib.train(x, y, cfg)
        pred = np.asarray(lr_lib.predict_proba(m, jnp.asarray(x))).argmax(1)
        assert (pred == y).mean() > 0.85

    def test_probabilities_normalized(self):
        x, y = _blobs(seed=4)
        cfg = lr_lib.LogisticRegressionConfig(n_classes=3, steps=50)
        m = lr_lib.train(x, y, cfg)
        p = np.asarray(lr_lib.predict_proba(m, jnp.asarray(x[:5])))
        np.testing.assert_allclose(p.sum(axis=1), 1.0, rtol=1e-5)

    def test_regularization_shrinks_weights(self):
        x, y = _blobs(seed=5)
        cfg0 = lr_lib.LogisticRegressionConfig(n_classes=3, steps=200, reg=0.0)
        cfg1 = lr_lib.LogisticRegressionConfig(n_classes=3, steps=200, reg=0.5)
        w0 = np.abs(np.asarray(lr_lib.train(x, y, cfg0).weights)).sum()
        w1 = np.abs(np.asarray(lr_lib.train(x, y, cfg1).weights)).sum()
        assert w1 < w0
