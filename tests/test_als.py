"""ALS model: convergence, exactness vs a numpy oracle, mesh equivalence.

The oracle re-implements the per-entity normal equations directly from the
Hu-Koren-Volinsky / ALS-WR math the reference's MLlib ALS computes
(SURVEY.md §2.2) — if the padded/bucketed XLA path diverges from the naive
loop, these fail.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from predictionio_tpu.models.als import (
    ALSConfig,
    ALSModel,
    predict_scores,
    recommend,
    rmse,
    train_als,
)
from predictionio_tpu.parallel.mesh import make_mesh


def _toy(seed=0, n_users=30, n_items=20, rank_true=3, density=0.5):
    """Low-rank synthetic ratings."""
    rng = np.random.default_rng(seed)
    u = rng.standard_normal((n_users, rank_true))
    v = rng.standard_normal((n_items, rank_true))
    full = u @ v.T
    mask = rng.random((n_users, n_items)) < density
    users, items = np.nonzero(mask)
    return users, items, full[users, items].astype(np.float32)


def _numpy_als_side(indices_per_row, vals_per_row, y, reg, implicit, alpha):
    """Naive per-row normal equations (the oracle)."""
    k = y.shape[1]
    yty = y.T @ y
    out = np.zeros((len(indices_per_row), k), dtype=np.float64)
    for r, (idx, vals) in enumerate(zip(indices_per_row, vals_per_row)):
        n = max(len(idx), 1)
        if implicit:
            w = alpha * np.abs(np.asarray(vals))
            p = (np.asarray(vals) > 0).astype(np.float64)
            f = y[idx]
            a = yty + (f * w[:, None]).T @ f + reg * n * np.eye(k)
            b = f.T @ ((1.0 + w) * p)
        else:
            f = y[idx]
            a = f.T @ f + reg * n * np.eye(k)
            b = f.T @ np.asarray(vals)
        if len(idx) == 0:
            a = reg * n * np.eye(k) + (yty if implicit else 0)
            b = np.zeros(k)
        out[r] = np.linalg.solve(a, b)
    return out


@pytest.mark.parametrize("implicit", [False, True])
def test_single_step_matches_oracle(implicit):
    users, items, ratings = _toy()
    n_users, n_items = 30, 20
    # gram_dtype f32: this test checks the math against a float64 oracle
    # at tight tolerance; the bf16 speed default is covered by the
    # convergence tests below.
    cfg = ALSConfig(rank=4, iterations=1, reg=0.1, alpha=2.0,
                    implicit=implicit, seed=7, bucket_bounds=(4, 8),
                    gram_dtype="float32")
    model = train_als(users, items, ratings, n_users, n_items, cfg)

    # Expected first-iteration factors from the shared deterministic init
    # (the oracle below re-derives the normal-equation math in numpy).
    from predictionio_tpu.models.als import _init_factors
    uf0, if0 = (np.asarray(a) for a in _init_factors(n_users, n_items, 4, 7))
    by_user = [(items[users == u], ratings[users == u]) for u in range(n_users)]
    uf1 = _numpy_als_side([i for i, _ in by_user], [v for _, v in by_user],
                          if0.astype(np.float64), 0.1, implicit, 2.0)
    by_item = [(users[items == i], ratings[items == i]) for i in range(n_items)]
    if1 = _numpy_als_side([u for u, _ in by_item], [v for _, v in by_item],
                          uf1, 0.1, implicit, 2.0)
    np.testing.assert_allclose(np.asarray(model.user_factors), uf1,
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(model.item_factors), if1,
                               rtol=2e-3, atol=2e-3)


def test_explicit_converges():
    users, items, ratings = _toy(density=0.7)
    cfg = ALSConfig(rank=6, iterations=12, reg=0.01, seed=1)
    model = train_als(users, items, ratings, 30, 20, cfg)
    assert rmse(model, users, items, ratings) < 0.15


def test_implicit_ranks_observed_higher():
    rng = np.random.default_rng(3)
    # Two user cliques each consuming a disjoint item half.
    users, items = [], []
    for u in range(20):
        half = u % 2
        for i in rng.choice(10, size=6, replace=False):
            users.append(u)
            items.append(half * 10 + i)
    users, items = np.array(users), np.array(items)
    cfg = ALSConfig(rank=8, iterations=10, implicit=True, alpha=40.0, reg=0.01)
    model = train_als(users, items, None, 20, 20, cfg)
    s = np.asarray(model.user_factors @ model.item_factors.T)
    own = s[0, :10].mean()
    other = s[0, 10:].mean()
    assert own > other + 0.1


def test_mesh_equivalence():
    """Sharded run == single-device run (the local[n] analogue, SURVEY §4)."""
    users, items, ratings = _toy(seed=5)
    cfg = ALSConfig(rank=4, iterations=3, reg=0.05, seed=9, bucket_bounds=(8,))
    m1 = train_als(users, items, ratings, 30, 20, cfg)
    mesh = make_mesh({"data": 8})
    m2 = train_als(users, items, ratings, 30, 20, cfg, mesh=mesh)
    np.testing.assert_allclose(np.asarray(m1.user_factors),
                               np.asarray(m2.user_factors), rtol=1e-3, atol=1e-3)


def test_blocked_factor_sharded_equivalence():
    """Blueprint blocked ALS (SURVEY §2.4 row 2): row-sharding the
    PERSISTENT factor matrices over the data axis changes placement, not
    math — and the state really stays sharded across sweeps."""
    from jax.sharding import NamedSharding

    users, items, ratings = _toy(seed=5)
    base = dict(rank=4, iterations=3, reg=0.05, seed=9, bucket_bounds=(8,))
    mesh = make_mesh({"data": 8})
    # Mesh-divisible extents: the returned factors keep their sharding.
    m1 = train_als(users, items, ratings, 32, 24, ALSConfig(**base))
    m2 = train_als(users, items, ratings, 32, 24,
                   ALSConfig(**base, factor_sharding="sharded"), mesh=mesh)
    sh = m2.user_factors.sharding
    assert isinstance(sh, NamedSharding) and sh.spec[0] == "data", \
        "blocked mode must keep the factor state row-sharded"
    np.testing.assert_allclose(np.asarray(m1.user_factors),
                               np.asarray(m2.user_factors),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(m1.item_factors),
                               np.asarray(m2.item_factors),
                               rtol=1e-3, atol=1e-3)
    # Non-divisible extents ride the padding path; same math.
    m3 = train_als(users, items, ratings, 30, 20, ALSConfig(**base))
    m4 = train_als(users, items, ratings, 30, 20,
                   ALSConfig(**base, factor_sharding="sharded"), mesh=mesh)
    assert m4.user_factors.shape == (30, 4)
    np.testing.assert_allclose(np.asarray(m3.user_factors),
                               np.asarray(m4.user_factors),
                               rtol=1e-3, atol=1e-3)


def test_blocked_windowed_gather_equivalence():
    """Windowed blocked mode (VERDICT r4 item 2): per-chunk gathers fetch
    only the factor rows the chunk touches, via masked local take + psum
    over the data axis — placement changes, math does not.  The data is
    built so user-side chunks touch <half the item matrix (windows
    engage, asserted) while the item side exceeds the threshold and
    stays on the plain path — both paths in one compiled loop."""
    from predictionio_tpu.models.als import (
        prepare_als_inputs, train_als_prepared,
    )

    rng = np.random.default_rng(11)
    n_u, n_i, nnz = 96, 400, 1500
    users = rng.integers(0, n_u, nnz)
    items = rng.integers(0, 100, nnz)  # only the first 100 of 400 items
    ratings = rng.uniform(1, 5, nnz).astype(np.float32)
    mesh = make_mesh({"data": 8})
    for extra in (dict(), dict(implicit=True, alpha=40.0)):
        base = dict(rank=4, iterations=3, reg=0.05, seed=9,
                    bucket_bounds=(16,), **extra)
        m1 = train_als(users, items, ratings, n_u, n_i, ALSConfig(**base))
        cfg = ALSConfig(**base, factor_sharding="sharded",
                        gather_window=True)
        inputs = prepare_als_inputs(users, items, ratings, n_u, n_i, cfg,
                                    mesh=mesh)
        ukinds = [b[0] for b in inputs.user_buckets]
        ikinds = [b[0] for b in inputs.item_buckets]
        assert any(k.endswith("_w") for k in ukinds), ukinds
        assert not any(k.endswith("_w") for k in ikinds), ikinds
        m2 = train_als_prepared(inputs, cfg)
        np.testing.assert_allclose(np.asarray(m1.user_factors),
                                   np.asarray(m2.user_factors),
                                   rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(np.asarray(m1.item_factors),
                                   np.asarray(m2.item_factors),
                                   rtol=1e-3, atol=1e-3)


def test_factor_sharding_auto_threshold():
    from predictionio_tpu.models.als import _shard_factors

    small = ALSConfig(rank=4)
    assert not _shard_factors(small, 30, 20)
    big = ALSConfig(rank=128, factor_shard_threshold=1 << 20)
    assert _shard_factors(big, 100_000, 50_000)


def test_recommend_excludes_seen():
    users, items, ratings = _toy(density=0.4)
    cfg = ALSConfig(rank=4, iterations=5)
    model = train_als(users, items, ratings, 30, 20, cfg)
    seen = np.zeros((1, 20), dtype=bool)
    seen[0, items[users == 0]] = True
    _, ids = recommend(model, jnp.asarray([0]), 5, seen=jnp.asarray(seen))
    assert not (set(np.asarray(ids)[0].tolist()) & set(items[users == 0].tolist()))


def test_predict_scores_shape():
    users, items, ratings = _toy()
    cfg = ALSConfig(rank=4, iterations=2)
    model = train_als(users, items, ratings, 30, 20, cfg)
    s = predict_scores(model.user_factors, model.item_factors,
                       jnp.asarray([0, 1]), jnp.asarray([3, 4]))
    assert s.shape == (2,)


def test_split_above_matches_unsplit():
    """Segment-summed split path == plain path (exact, not approximate)."""
    users, items, ratings = _toy(density=0.8)
    base = dict(rank=4, iterations=3, reg=0.05, seed=11, gram_dtype="float32",
                bucket_bounds=(4,))
    m_plain = train_als(users, items, ratings, 30, 20,
                        ALSConfig(**base, split_above=None))
    m_split = train_als(users, items, ratings, 30, 20,
                        ALSConfig(**base, split_above=8))
    np.testing.assert_allclose(np.asarray(m_plain.user_factors),
                               np.asarray(m_split.user_factors),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(m_plain.item_factors),
                               np.asarray(m_split.item_factors),
                               rtol=1e-4, atol=1e-4)


def test_split_above_matches_unsplit_on_mesh():
    users, items, ratings = _toy(density=0.8)
    base = dict(rank=4, iterations=2, reg=0.05, seed=11, gram_dtype="float32",
                bucket_bounds=(4,))
    mesh = make_mesh({"data": 8})
    m_plain = train_als(users, items, ratings, 30, 20,
                        ALSConfig(**base, split_above=None))
    m_split = train_als(users, items, ratings, 30, 20,
                        ALSConfig(**base, split_above=8), mesh=mesh)
    np.testing.assert_allclose(np.asarray(m_plain.user_factors),
                               np.asarray(m_split.user_factors),
                               rtol=1e-3, atol=1e-3)


def test_degree_zero_entities_get_near_zero_factors():
    """Pinned semantics (VERDICT.md weak-5): unrated entities solve to the
    ridge solution of an empty system — (lambda I) x = 0 -> x = 0 — so they
    never outrank real recommendations (MLlib simply omits them; scoring
    behavior matches: 0-dot = 0)."""
    users = np.array([0, 0, 1, 1, 2])
    items = np.array([0, 1, 0, 2, 1])
    ratings = np.ones(5, dtype=np.float32)
    # users 3, 4 and item 3 have no ratings at all
    model = train_als(users, items, ratings, 5, 4,
                      ALSConfig(rank=4, iterations=2, reg=0.1, seed=0))
    uf = np.asarray(model.user_factors)
    assert np.abs(uf[3:]).max() < 1e-5
    assert np.abs(np.asarray(model.item_factors)[3]).max() < 1e-5
    # rated rows are non-trivial
    assert np.abs(uf[:3]).max() > 1e-2


def test_split_chunking_matches_unsplit():
    """HBM chunking of split buckets (entity-boundary cuts) stays exact."""
    users, items, ratings = _toy(density=0.9)
    base = dict(rank=4, iterations=3, reg=0.05, seed=13, gram_dtype="float32",
                bucket_bounds=(4,))
    m_plain = train_als(users, items, ratings, 30, 20,
                        ALSConfig(**base, split_above=None))
    # max_block_floats tiny -> every split bucket is forced into chunks.
    m_chunk = train_als(users, items, ratings, 30, 20,
                        ALSConfig(**base, split_above=4,
                                  max_block_floats=4 * 4 * 8))
    np.testing.assert_allclose(np.asarray(m_plain.user_factors),
                               np.asarray(m_chunk.user_factors),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(m_plain.item_factors),
                               np.asarray(m_chunk.item_factors),
                               rtol=1e-4, atol=1e-4)


def test_bf16_gram_quality():
    """bf16 gathered operands (the TPU default) must not hurt fit quality.

    PARITY.md pins this: master factors and accumulation stay f32; only
    the gathered gram/rhs operands are bf16.  RMSE after full training on
    a recoverable low-rank problem must match the f32 path closely.
    """
    rng = np.random.default_rng(7)
    n_u, n_i, n = 80, 60, 3000
    tu = rng.standard_normal((n_u, 4))
    ti = rng.standard_normal((n_i, 4))
    users = rng.integers(0, n_u, n)
    items = rng.integers(0, n_i, n)
    ratings = np.sum(tu[users] * ti[items], axis=1).astype(np.float32)
    f32 = ALSConfig(rank=8, iterations=8, reg=0.05, seed=1,
                    gram_dtype="float32")
    bf16 = ALSConfig(rank=8, iterations=8, reg=0.05, seed=1,
                     gram_dtype="bfloat16")
    m32 = train_als(users, items, ratings, n_u, n_i, f32)
    m16 = train_als(users, items, ratings, n_u, n_i, bf16)
    r32 = rmse(m32, users, items, ratings)
    r16 = rmse(m16, users, items, ratings)
    scale = float(np.sqrt(np.mean(ratings ** 2)))
    assert abs(r16 - r32) < 0.02 * scale, (r32, r16)


def test_fit_bounds_reduces_padding():
    """DP-fitted bounds must never pad more than the fixed defaults and
    must stay sublane-aligned."""
    from predictionio_tpu.ops.ragged import fit_bounds

    rng = np.random.default_rng(0)
    counts = np.concatenate([
        rng.integers(100, 220, 5000),       # user-like bulk
        (rng.zipf(1.3, 500) % 4000) + 1,    # zipf tail
    ])
    bounds = fit_bounds(counts, cap=4096)
    assert all(b % 8 == 0 for b in bounds)
    assert bounds == sorted(set(bounds))

    def padded(bs):
        c = np.minimum(counts, 4096)
        tot, prev = 0, 0
        for b in sorted(bs):
            sel = (c > prev) & (c <= b)
            tot += sel.sum() * b
            prev = b
        assert prev >= c.max()
        return tot

    fixed = [16, 64, 256, 1024, 4096]
    assert padded(bounds) <= padded(fixed)
    assert padded(bounds) <= 1.15 * counts.clip(max=4096).sum()


def test_gather_window_auto_skips_single_device_axis():
    """A 1-device data axis has no cross-shard transient — auto windowing
    must skip (it would only add a second gather level); an explicit
    gather_window=True still forces it (how tests exercise the path)."""
    from predictionio_tpu.models.als import prepare_als_inputs

    rng = np.random.default_rng(3)
    users = rng.integers(0, 64, 800)
    items = rng.integers(0, 20, 800)  # 20 of 400 items → windows viable
    ratings = rng.uniform(1, 5, 800).astype(np.float32)
    mesh1 = make_mesh({"data": 1})
    base = dict(rank=4, iterations=1, seed=0, bucket_bounds=(16,),
                factor_sharding="sharded")
    inp_auto = prepare_als_inputs(users, items, ratings, 64, 400,
                                  ALSConfig(**base), mesh=mesh1)
    assert not any(b[0].endswith("_w") for b in inp_auto.user_buckets)
    inp_forced = prepare_als_inputs(users, items, ratings, 64, 400,
                                    ALSConfig(**base, gather_window=True),
                                    mesh=mesh1)
    assert any(b[0].endswith("_w") for b in inp_forced.user_buckets)


def test_host_layout_rows_sublane_aligned():
    """The host/mesh prep path must keep bucket ROW counts 8-aligned
    (and mesh-divisible): unaligned rows made XLA pad/relayout every
    gathered block in-graph — ~70 ms/iter at the ML-25M shape (round 5).
    Guard the layout invariant, not the timing."""
    from predictionio_tpu.models.als import prepare_als_inputs

    users, items, ratings = _toy(seed=2, n_users=50, n_items=40,
                                 density=0.6)
    cfg = ALSConfig(rank=4, iterations=1, seed=0, device_prep=False)
    inp = prepare_als_inputs(users, items, ratings, 50, 40, cfg, mesh=None)
    for b in (*inp.user_buckets, *inp.item_buckets):
        assert b[1].shape[0] % 8 == 0, (b[0], b[1].shape)
    # a NON-divisor axis (3 of the 8 CPU devices): rows must pad to
    # lcm(sublane, 3) = 24, which only holds if the lcm term survives
    import math

    from predictionio_tpu.ops.ragged import LEN_ALIGN

    mesh = make_mesh({"data": 3})
    inp2 = prepare_als_inputs(users, items, ratings, 50, 40, cfg, mesh=mesh)
    granule = math.lcm(LEN_ALIGN, 3)
    for b in (*inp2.user_buckets, *inp2.item_buckets):
        assert b[1].shape[0] % granule == 0, (b[0], b[1].shape)
