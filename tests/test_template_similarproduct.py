"""Similar-product template: view events → item-factor cosine retrieval."""

import numpy as np
import pytest

from predictionio_tpu.controller import EngineVariant, RuntimeContext
from predictionio_tpu.data.event import DataMap, Event
from predictionio_tpu.data.storage import App, get_storage
from predictionio_tpu.templates.similarproduct import Query, engine
from predictionio_tpu.workflow.core_workflow import load_models, run_train


@pytest.fixture()
def ctx(pio_home):
    return RuntimeContext.create(storage=get_storage())


def _seed(ctx, n_users=24, n_items=12, seed=0):
    storage = ctx.storage
    app_id = storage.get_apps().insert(App(id=None, name="testapp"))
    storage.get_events().init(app_id)
    rng = np.random.default_rng(seed)
    ev = storage.get_events()
    # Co-view structure: even users view even items, odd view odd.  15
    # views per user makes the clique unambiguous for ANY correct implicit
    # ALS (at 5 views the top-4 membership depended on the factor init —
    # even a numpy reference solver only got 3/4).
    for u in range(n_users):
        pool = [i for i in range(n_items) if i % 2 == u % 2]
        for i in rng.choice(pool, size=15, replace=True):
            ev.insert(Event(event="view", entity_type="user", entity_id=f"u{u}",
                            target_entity_type="item", target_entity_id=f"i{i}"),
                      app_id)
    for i in range(n_items):
        ev.insert(Event(event="$set", entity_type="item", entity_id=f"i{i}",
                        properties=DataMap(
                            {"categories": ["even" if i % 2 == 0 else "odd"]})),
                  app_id)
    return app_id


VARIANT = {
    "engineFactory": "predictionio_tpu.templates.similarproduct:engine",
    "datasource": {"params": {"appName": "testapp"}},
    "algorithms": [{"name": "als",
                    "params": {"rank": 8, "numIterations": 10, "alpha": 10.0,
                               "seed": 5}}],
}


def _trained(ctx):
    eng = engine()
    variant = EngineVariant.from_dict(VARIANT)
    iid = run_train(eng, variant, ctx)
    inst = ctx.storage.get_engine_instances().get(iid)
    models = load_models(eng, inst, ctx)
    algo = eng.make_algorithms(eng.bind_engine_params(VARIANT))[0]
    return algo, models[0]


def test_similar_items_share_clique(ctx):
    _seed(ctx)
    algo, model = _trained(ctx)
    res = algo.predict(model, Query(items=["i0"], num=4))
    assert len(res.itemScores) == 4
    assert "i0" not in [s.item for s in res.itemScores]
    even = sum(1 for s in res.itemScores if int(s.item[1:]) % 2 == 0)
    assert even >= 3


def test_category_filter(ctx):
    _seed(ctx)
    algo, model = _trained(ctx)
    res = algo.predict(model, Query(items=["i0"], num=4, categories=["odd"]))
    assert res.itemScores
    assert all(int(s.item[1:]) % 2 == 1 for s in res.itemScores)


def test_white_black_lists(ctx):
    _seed(ctx)
    algo, model = _trained(ctx)
    res = algo.predict(model, Query(items=["i0"], num=4, whiteList=["i2", "i4"]))
    assert {s.item for s in res.itemScores} <= {"i2", "i4"}
    res = algo.predict(model, Query(items=["i0"], num=11, blackList=["i2"]))
    assert "i2" not in [s.item for s in res.itemScores]


def test_unknown_item_empty(ctx):
    _seed(ctx)
    algo, model = _trained(ctx)
    assert algo.predict(model, Query(items=["ghost"])).itemScores == []


def test_multi_item_query(ctx):
    _seed(ctx)
    algo, model = _trained(ctx)
    res = algo.predict(model, Query(items=["i0", "i2"], num=3))
    assert len(res.itemScores) == 3
    assert not {"i0", "i2"} & {s.item for s in res.itemScores}
