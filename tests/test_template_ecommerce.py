"""E-commerce template: ALS + live business rules at serve time."""

import numpy as np
import pytest

from predictionio_tpu.controller import EngineVariant, RuntimeContext
from predictionio_tpu.data.event import DataMap, Event
from predictionio_tpu.data.storage import App, get_storage
from predictionio_tpu.templates.ecommerce import Query, engine
from predictionio_tpu.workflow.core_workflow import load_models, run_train


@pytest.fixture()
def ctx(pio_home):
    return RuntimeContext.create(storage=get_storage())


def _seed(ctx, n_users=20, n_items=10, seed=0):
    storage = ctx.storage
    app_id = storage.get_apps().insert(App(id=None, name="testapp"))
    storage.get_events().init(app_id)
    rng = np.random.default_rng(seed)
    ev = storage.get_events()
    for u in range(n_users):
        pool = [i for i in range(n_items) if i % 2 == u % 2]
        for i in rng.choice(pool, size=4, replace=True):
            ev.insert(Event(event="view", entity_type="user", entity_id=f"u{u}",
                            target_entity_type="item", target_entity_id=f"i{i}"),
                      app_id)
    return app_id


VARIANT = {
    "engineFactory": "predictionio_tpu.templates.ecommerce:engine",
    "datasource": {"params": {"appName": "testapp"}},
    "algorithms": [{"name": "ecomm",
                    "params": {"appName": "testapp", "rank": 8,
                               "numIterations": 8, "alpha": 10.0, "seed": 5}}],
}


def _trained(ctx):
    eng = engine()
    variant = EngineVariant.from_dict(VARIANT)
    iid = run_train(eng, variant, ctx)
    inst = ctx.storage.get_engine_instances().get(iid)
    models = load_models(eng, inst, ctx)
    algo = eng.make_algorithms(eng.bind_engine_params(VARIANT))[0]
    return algo, models[0]


def test_seen_items_excluded(ctx):
    app_id = _seed(ctx)
    algo, model = _trained(ctx)
    seen = {e.target_entity_id
            for e in ctx.storage.get_events().find(
                app_id, entity_id="u0", entity_type="user")}
    res = algo.predict(model, Query(user="u0", num=10))
    assert res.itemScores
    assert not seen & {s.item for s in res.itemScores}


def test_unavailable_items_excluded(ctx):
    app_id = _seed(ctx)
    ctx.storage.get_events().insert(
        Event(event="$set", entity_type="constraint",
              entity_id="unavailableItems",
              properties=DataMap({"items": ["i2", "i4"]})), app_id)
    algo, model = _trained(ctx)
    res = algo.predict(model, Query(user="u0", num=10))
    assert not {"i2", "i4"} & {s.item for s in res.itemScores}


def test_unknown_user_popularity_fallback(ctx):
    _seed(ctx)
    algo, model = _trained(ctx)
    res = algo.predict(model, Query(user="ghost", num=3))
    assert len(res.itemScores) == 3
    # Fallback scores are view counts — descending.
    scores = [s.score for s in res.itemScores]
    assert scores == sorted(scores, reverse=True)


def test_blacklist(ctx):
    _seed(ctx)
    algo, model = _trained(ctx)
    res = algo.predict(model, Query(user="u1", num=10, blackList=["i1"]))
    assert "i1" not in [s.item for s in res.itemScores]
