"""Bulk ingest data plane (ISSUE 17): ``POST /batch/events.json`` —
NDJSON bodies, per-item status, client batch-token exactly-once,
write-path admission (429 + Retry-After), disk-pressure degradation, and
spill-replay of a partially-landed batch."""

import json
import urllib.error
import urllib.request

import pytest

from predictionio_tpu.data.storage import (
    AccessKey,
    App,
    StorageUnavailable,
    get_storage,
)
from predictionio_tpu.resilience import faults
from predictionio_tpu.server.event_server import EventServer, max_batch_size


def _stack(pio_home, **server_kw):
    storage = get_storage()
    app_id = storage.get_apps().insert(App(id=None, name="bulk"))
    storage.get_events().init(app_id)
    key = storage.get_access_keys().insert(AccessKey(key="", app_id=app_id))
    srv = EventServer(storage=storage, host="127.0.0.1", port=0, **server_kw)
    return srv, key, storage, app_id


def _post(srv, key, path, payload, params=None):
    p = {"accessKey": [key]}
    for k, v in (params or {}).items():
        p[k] = [v]
    body = payload if isinstance(payload, bytes) \
        else json.dumps(payload).encode()
    return srv.handle("POST", path, p, body)


def _http_post(url, body, ctype="application/json"):
    req = urllib.request.Request(
        url, data=body, method="POST", headers={"Content-Type": ctype})
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, dict(resp.headers), \
                json.loads(resp.read() or b"null")
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, dict(e.headers), \
            (json.loads(payload) if payload else None)


def _ev(i, name="view"):
    return {"event": name, "entityType": "user", "entityId": f"u{i}",
            "targetEntityType": "item", "targetEntityId": f"i{i}"}


# --------------------------------------------------------------------------
# Batch bodies and per-item status
# --------------------------------------------------------------------------


def test_json_array_batch_per_item_status(pio_home):
    srv, key, storage, app_id = _stack(pio_home)
    try:
        status, results = _post(srv, key, "/batch/events.json",
                                [_ev(0), _ev(1), _ev(2)])
        assert status == 200
        assert [r["status"] for r in results] == [201, 201, 201]
        assert all(r["eventId"] for r in results)
        assert len(list(storage.get_events().find(app_id))) == 3
    finally:
        srv.stop()


def test_ndjson_batch_malformed_line_never_fails_cohort(pio_home):
    """One torn/garbage NDJSON line answers ITS OWN 400; every other
    line still lands 201 — per-item isolation is the whole point of the
    per-line framing."""
    srv, key, storage, app_id = _stack(pio_home)
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        nd = "\n".join([json.dumps(_ev(0)), "{not json", json.dumps(_ev(1)),
                        "", json.dumps(_ev(2))])
        status, _, results = _http_post(
            f"{base}/batch/events.json?accessKey={key}", nd.encode(),
            ctype="application/x-ndjson")
        assert status == 200
        assert [r["status"] for r in results] == [201, 400, 201, 201]
        assert "line 2" in results[1]["message"]
        assert len(list(storage.get_events().find(app_id))) == 3
    finally:
        srv.stop()


def test_ndjson_sniffed_without_content_type(pio_home):
    # first non-space byte != "[" → NDJSON even under a generic type
    srv, key, storage, app_id = _stack(pio_home)
    try:
        nd = json.dumps(_ev(0)) + "\n" + json.dumps(_ev(1))
        status, results = _post(srv, key, "/batch/events.json", nd.encode())
        assert status == 200
        assert [r["status"] for r in results] == [201, 201]
    finally:
        srv.stop()


def test_invalid_item_isolated_valid_cohort_lands(pio_home):
    srv, key, storage, app_id = _stack(pio_home)
    try:
        batch = [_ev(0), {"entityType": "user", "entityId": "nope"}, _ev(1)]
        status, results = _post(srv, key, "/batch/events.json", batch)
        assert status == 200
        assert [r["status"] for r in results] == [201, 400, 201]
        assert len(list(storage.get_events().find(app_id))) == 2
    finally:
        srv.stop()


def test_batch_cap_enforced(pio_home, monkeypatch):
    monkeypatch.setenv("PIO_MAX_BATCH_SIZE", "3")
    assert max_batch_size() == 3
    srv, key, *_ = _stack(pio_home)
    try:
        status, payload = _post(srv, key, "/batch/events.json",
                                [_ev(i) for i in range(4)])
        assert status == 400 and "limit of 3" in payload["message"]
    finally:
        srv.stop()


# --------------------------------------------------------------------------
# Client batch token: exactly-once across retries
# --------------------------------------------------------------------------


def test_batch_token_retry_dedups_row_by_row(pio_home):
    """A client retry with the SAME batchToken (reply lost) re-derives
    the same sub-tokens → same event ids → zero duplicates."""
    srv, key, storage, app_id = _stack(pio_home)
    try:
        batch = [_ev(0), _ev(1), _ev(2)]
        s1, r1 = _post(srv, key, "/batch/events.json", batch,
                       params={"batchToken": "client-tok-1"})
        s2, r2 = _post(srv, key, "/batch/events.json", batch,
                       params={"batchToken": "client-tok-1"})
        assert s1 == s2 == 200
        assert [r["eventId"] for r in r1] == [r["eventId"] for r in r2]
        assert len(list(storage.get_events().find(app_id))) == 3
    finally:
        srv.stop()


def test_bad_batch_token_rejected(pio_home):
    srv, key, *_ = _stack(pio_home)
    try:
        status, payload = _post(srv, key, "/batch/events.json", [_ev(0)],
                                params={"batchToken": "bad token!"})
        assert status == 400 and "batchToken" in payload["message"]
        status, _ = _post(srv, key, "/batch/events.json", [_ev(0)],
                          params={"batchToken": "x" * 121})
        assert status == 400
    finally:
        srv.stop()


def test_spill_replay_of_partially_landed_batch_exactly_once(pio_home):
    """The crash-consistency core: storage 'fails' a batch AFTER
    committing part of it (lost reply).  The spill record carries the
    per-item sub-tokens, so replay re-issues the identical create_batch
    and the already-committed rows dedup away — zero lost, zero
    duplicated."""
    srv, key, storage, app_id = _stack(pio_home, replay_interval_s=3600,
                                       replay_wait=lambda ev, t: ev.wait())
    try:
        events_repo = storage.get_events()
        real = type(events_repo).create_batch
        calls = {"n": 0}

        def flaky(self, evs, app_id_, channel_id=None, tokens=None):
            calls["n"] += 1
            if calls["n"] == 1:
                # commit the FIRST HALF, then "crash" before replying
                real(self, evs[: len(evs) // 2], app_id_, channel_id,
                     tokens=list(tokens)[: len(evs) // 2]
                     if tokens else None)
                raise StorageUnavailable("crashed mid-batch")
            return real(self, evs, app_id_, channel_id, tokens=tokens)

        import unittest.mock as mock

        with mock.patch.object(type(events_repo), "create_batch", flaky):
            status, results = _post(srv, key, "/batch/events.json",
                                    [_ev(i) for i in range(4)],
                                    params={"batchToken": "crashy"})
            assert status == 200
            assert [r["status"] for r in results] == [202] * 4
            assert srv.spill is not None and srv.spill.depth() == 4
            # half landed before the "crash"
            assert len(list(events_repo.find(app_id))) == 2
            assert srv._replay.drain_once() == 4
        landed = list(events_repo.find(app_id))
        assert len(landed) == 4, "replay must fill ONLY the missing rows"
        assert {e.entity_id for e in landed} == {f"u{i}" for i in range(4)}
    finally:
        srv.stop()


# --------------------------------------------------------------------------
# Write-path admission + disk pressure
# --------------------------------------------------------------------------


def test_saturated_plane_answers_429_with_retry_after(pio_home, monkeypatch):
    monkeypatch.setenv("PIO_INGEST_QUEUE_BUDGET", "2")
    srv, key, storage, app_id = _stack(pio_home)
    srv.start()
    try:
        assert srv.ingest_budget == 2
        base = f"http://127.0.0.1:{srv.port}"
        body = json.dumps([_ev(i) for i in range(5)]).encode()
        status, headers, payload = _http_post(
            f"{base}/batch/events.json?accessKey={key}", body)
        assert status == 429
        assert "Retry-After" in headers
        assert float(headers["Retry-After"]) > 0
        assert "PIO_INGEST_QUEUE_BUDGET" in payload["message"]
        # nothing landed, nothing leaked: inflight back to 0 and a batch
        # UNDER budget still goes through
        assert srv._inflight == 0
        status, _, results = _http_post(
            f"{base}/batch/events.json?accessKey={key}",
            json.dumps([_ev(0)]).encode())
        assert status == 200 and results[0]["status"] == 201
    finally:
        srv.stop()


def test_single_event_admission_429(pio_home, monkeypatch):
    """The budget is shared with the spill backlog: a deep journal
    starves single-row admission too (backpressure reaches every write
    entry point)."""
    monkeypatch.setenv("PIO_INGEST_QUEUE_BUDGET", "3")
    srv, key, *_ = _stack(pio_home, replay_interval_s=3600,
                          replay_wait=lambda ev, t: ev.wait())
    try:
        faults.install("storage.create:error:1.0")
        for i in range(3):  # fill the journal to the budget
            status, payload = _post(srv, key, "/events.json", _ev(i))
            assert status == 202
        status, payload = _post(srv, key, "/events.json", _ev(9))
        assert status == 429
        assert "retry later" in payload["message"]
    finally:
        faults.clear()
        srv.stop()


def test_disk_pressure_degrades_ready_not_ingest(pio_home, monkeypatch):
    """PIO_DISK_MIN_FREE_BYTES above the disk's free space: segment tee
    flips off and /ready says degraded — but the PRIMARY ingest path
    keeps answering 201 (segments are derived data)."""
    monkeypatch.setenv("PIO_DISK_MIN_FREE_BYTES", str(1 << 60))
    srv, key, storage, app_id = _stack(pio_home)
    try:
        assert srv.segments is not None
        status, r = _post(srv, key, "/events.json", _ev(0))
        assert status == 201  # ingest unaffected
        status, ready = srv.handle("GET", "/ready", {}, b"")
        assert status == 200  # still routable — only coverage stopped
        assert ready["status"] == "degraded"
        assert ready["diskDegraded"] is True
    finally:
        srv.stop()


def test_ready_reports_segment_counts(pio_home):
    srv, key, storage, app_id = _stack(pio_home)
    try:
        _post(srv, key, "/batch/events.json", [_ev(i) for i in range(3)])
        assert srv.segments is not None
        srv.segments.seal_all()
        status, ready = srv.handle("GET", "/ready", {}, b"")
        assert status == 200 and ready["status"] == "ready"
        assert ready["segmentDirs"] == 1
        assert ready["segmentCount"] == 1
        assert ready["ingestBudget"] == 0 and ready["ingestInflight"] == 0
    finally:
        srv.stop()


def test_ingest_faults_seam_drillable(pio_home):
    """`ingest.*` PIO_FAULTS points: admission and the batch fold are
    drill-able without monkeypatching server internals."""
    srv, key, storage, app_id = _stack(pio_home, spill_dir="off")
    try:
        faults.install("ingest.batch:error:1.0")
        status, results = _post(srv, key, "/batch/events.json", [_ev(0)])
        assert status == 503  # ConnectionError → availability, not a bug
        faults.clear()
        faults.install("ingest.admit:error:1.0")
        status, _ = _post(srv, key, "/events.json", _ev(1))
        assert status == 503
        faults.clear()
        status, results = _post(srv, key, "/batch/events.json", [_ev(2)])
        assert status == 200 and results[0]["status"] == 201
    finally:
        faults.clear()
        srv.stop()
