"""Run supervision & crash-safe model lifecycle (ISSUE 4).

Training side: fake-clock watchdog firing, NaN-injected loss → rollback
→ converges, preemption mid-train → resumed ALS run bitwise-equal to an
uninterrupted one.  Serving side: reload under 100% storage faults fails
closed (last-good keeps serving, /ready stays 200, the failure and the
breaker transitions are observable), canary validation, and the instant
rollback endpoint.  CPU-only, fake clocks, no real sleeps — same
discipline as tests/test_resilience.py.
"""

import json

import numpy as np
import pytest

from predictionio_tpu.resilience import faults
from predictionio_tpu.resilience.supervision import (
    PREEMPTED_EXIT_CODE,
    DivergenceGuard,
    ModelValidationError,
    RollbackRequested,
    StepWatchdog,
    TrainDiverged,
    TrainPreempted,
    clear_preemption,
    install_preemption_handler,
    preemption_requested,
    request_preemption,
    validate_model_finite,
)

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean_supervision_state():
    clear_preemption()
    faults.clear()
    yield
    clear_preemption()
    faults.clear()


# -- step watchdog (fake clock, no sleeps) -----------------------------------

class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def test_watchdog_fires_once_with_metrics_event_and_checkpoint(pio_home):
    from predictionio_tpu.obs import get_recorder, get_registry
    from predictionio_tpu.obs.runtime import StepTimeline

    clock = FakeClock()
    tl = StepTimeline(capacity=8)
    tl.record("two_tower", host_wait_ms=1.0, device_step_ms=5.0, step=41)
    actions = []
    wd = StepWatchdog("two_tower", timeout_s=30.0, clock=clock,
                      checkpoint_fn=lambda: actions.append("ckpt"),
                      abort_fn=lambda: actions.append("abort"),
                      poll_interval_s=0, timeline=tl)
    assert wd.enabled
    wd.arm(42)
    assert wd.poll() is False  # not yet expired
    clock.t += 31.0
    assert wd.poll() is True
    # checkpoint flushed BEFORE abort
    assert actions == ["ckpt", "abort"]
    assert wd.poll() is False, "fires exactly once per armed step"
    counter = get_registry().counter(
        "pio_watchdog_fired_total", "", ("fn",))
    assert counter.value(fn="two_tower") == 1
    # trace-ring event carries the last step-timeline entry (published
    # outside any trace → standalone single-span trace doc)
    traces = get_recorder().recent(10)
    fired = [t for t in traces if t["name"] == "watchdog.fired"]
    assert fired and fired[0]["attrs"]["step"] == 42
    assert json.loads(fired[0]["attrs"]["lastStep"])["step"] == 41


def test_watchdog_disarm_prevents_firing(pio_home):
    clock = FakeClock()
    fired = []
    wd = StepWatchdog("als", timeout_s=10.0, clock=clock,
                      abort_fn=lambda: fired.append(1), poll_interval_s=0)
    wd.arm(1)
    wd.disarm()
    clock.t += 1000.0
    assert wd.poll() is False and not fired


def test_watchdog_disabled_without_env(pio_home, monkeypatch):
    monkeypatch.delenv("PIO_STEP_TIMEOUT_S", raising=False)
    wd = StepWatchdog("dlrm", poll_interval_s=0)
    assert not wd.enabled
    wd.arm(1)  # no-op
    assert wd.poll() is False


# -- PIO_STEP_TIMEOUT_KILL hard escalation (ISSUE 10 satellite) --------------

def test_kill_escalates_when_abort_cannot_unwind(pio_home):
    """A fired watchdog whose abort never unwinds (runtime wedged in a C
    call) hard-kills after the grace period — exactly once, with the
    metric and trace event."""
    from predictionio_tpu.obs import get_recorder, get_registry

    clock = FakeClock()
    actions = []
    wd = StepWatchdog("als", timeout_s=10.0, kill_grace_s=20.0, clock=clock,
                      abort_fn=lambda: actions.append("abort"),
                      kill_fn=lambda: actions.append("KILL"),
                      poll_interval_s=0)
    wd.arm(5)
    clock.t += 11.0
    assert wd.poll() is True          # soft fire
    assert actions == ["abort"]
    clock.t += 19.0                    # inside the grace window
    assert wd.poll() is False
    assert actions == ["abort"]
    clock.t += 2.0                     # grace expired, still not unwound
    assert wd.poll() is True
    assert actions == ["abort", "KILL"]
    assert wd.poll() is False, "kills exactly once"
    assert actions == ["abort", "KILL"]
    counter = get_registry().counter(
        "pio_watchdog_killed_total", "", ("fn",))
    assert counter.value(fn="als") == 1
    killed = [t for t in get_recorder().recent(10)
              if t["name"] == "watchdog.killed"]
    assert killed and killed[0]["attrs"]["graceS"] == 20.0


def test_kill_stands_down_when_run_unwinds(pio_home):
    """stop() (the training loop's finally) IS the unwind signal: a run
    the soft abort successfully tore down never escalates."""
    clock = FakeClock()
    actions = []
    wd = StepWatchdog("als", timeout_s=10.0, kill_grace_s=20.0, clock=clock,
                      abort_fn=lambda: actions.append("abort"),
                      kill_fn=lambda: actions.append("KILL"),
                      poll_interval_s=0)
    wd.arm(5)
    clock.t += 11.0
    assert wd.poll() is True
    wd.stop()                          # the abort unwound the loop
    clock.t += 1000.0
    assert wd.poll() is False
    assert actions == ["abort"]


def test_kill_disabled_by_default(pio_home, monkeypatch):
    """No PIO_STEP_TIMEOUT_KILL → never escalates, however long the
    wedge lasts (the pre-ISSUE-10 behavior is the default)."""
    monkeypatch.delenv("PIO_STEP_TIMEOUT_KILL", raising=False)
    clock = FakeClock()
    actions = []
    wd = StepWatchdog("als", timeout_s=10.0, clock=clock,
                      abort_fn=lambda: actions.append("abort"),
                      kill_fn=lambda: actions.append("KILL"),
                      poll_interval_s=0)
    assert wd.kill_grace_s == 0.0
    wd.arm(5)
    clock.t += 11.0
    assert wd.poll() is True
    clock.t += 1e6
    assert wd.poll() is False
    assert actions == ["abort"]


def test_kill_grace_reads_env(pio_home, monkeypatch):
    monkeypatch.setenv("PIO_STEP_TIMEOUT_KILL", "45")
    wd = StepWatchdog("als", timeout_s=1.0, poll_interval_s=0)
    assert wd.kill_grace_s == 45.0
    monkeypatch.setenv("PIO_STEP_TIMEOUT_KILL", "nonsense")
    wd = StepWatchdog("als", timeout_s=1.0, poll_interval_s=0)
    assert wd.kill_grace_s == 0.0


# -- divergence guard --------------------------------------------------------

def test_guard_allows_finite_and_bounds_rollbacks(pio_home):
    g = DivergenceGuard("tt", max_rollbacks=2)
    g.check(0.5, 1)  # finite: silent
    with pytest.raises(RollbackRequested):
        g.check(float("nan"), 2)
    with pytest.raises(RollbackRequested):
        g.check(float("inf"), 3)
    with pytest.raises(TrainDiverged) as ei:
        g.check(float("nan"), 4)
    assert "rollback" in str(ei.value)
    from predictionio_tpu.obs import get_registry

    c = get_registry().counter("pio_train_divergence_total", "", ("fn",))
    assert c.value(fn="tt") == 3


def test_validate_model_finite_walks_wrapper_objects(pio_home):
    class Wrapper:
        def __init__(self, arr):
            self.nested = {"factors": [arr]}

    validate_model_finite(Wrapper(np.ones((3, 2), np.float32)))
    bad = np.ones((3, 2), np.float32)
    bad[1, 1] = np.nan
    with pytest.raises(ModelValidationError, match="non-finite"):
        validate_model_finite(Wrapper(bad))
    # integer arrays are exempt (nothing to be non-finite)
    validate_model_finite(Wrapper(np.ones((2,), np.int32)))


# -- NaN injection → rollback → converges ------------------------------------

def _tt_data():
    rng = np.random.default_rng(0)
    return rng.integers(0, 16, 200), rng.integers(0, 8, 200)


def _tt_cfg():
    from predictionio_tpu.models import two_tower as tt

    return tt.TwoTowerConfig(n_users=16, n_items=8, embed_dim=8,
                             hidden_dims=(16,), out_dim=8, batch_size=32,
                             epochs=2, seed=7)


def test_nan_injected_loss_rolls_back_and_converges(pio_home, tmp_path,
                                                    monkeypatch):
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.models import two_tower as tt

    users, items = _tt_data()
    cfg = _tt_cfg()
    clean = tt.train(users, items, cfg)

    real_step = tt.train_step
    state_counter = {"n": 0, "injected": False}

    def nan_once(state, u, i, w, c):
        s2, loss = real_step(state, u, i, w, c)
        state_counter["n"] += 1
        if state_counter["n"] == 5 and not state_counter["injected"]:
            state_counter["injected"] = True
            poisoned = jax.tree.map(lambda x: x * jnp.nan, s2.params)
            return (tt.TwoTowerState(poisoned, s2.opt_state, s2.step),
                    jnp.float32(jnp.nan))
        return s2, loss

    monkeypatch.setattr(tt, "train_step", nan_once)
    out = tt.train(users, items, cfg, checkpoint_dir=tmp_path / "ck",
                   save_every=3)
    # The run completed, the model is finite, and the replayed steps
    # reproduce the clean result — the NaN state was never kept.
    assert np.isfinite(np.asarray(out.params["user_embed"])).all()
    np.testing.assert_allclose(np.asarray(clean.params["user_embed"]),
                               np.asarray(out.params["user_embed"]),
                               rtol=1e-5, atol=1e-6)


def test_persistent_divergence_raises_without_persisting(pio_home,
                                                         monkeypatch):
    import jax.numpy as jnp

    from predictionio_tpu.models import two_tower as tt

    users, items = _tt_data()
    cfg = _tt_cfg()
    real_step = tt.train_step

    def always_nan(state, u, i, w, c):
        s2, _ = real_step(state, u, i, w, c)
        return s2, jnp.float32(jnp.nan)

    monkeypatch.setattr(tt, "train_step", always_nan)
    with pytest.raises(TrainDiverged):
        tt.train(users, items, cfg)


def test_als_divergence_without_checkpoints_is_terminal(pio_home,
                                                        monkeypatch):
    from predictionio_tpu.models import als as als_lib

    rng = np.random.default_rng(3)
    users = rng.integers(0, 20, 400)
    items = rng.integers(0, 15, 400)
    ratings = rng.integers(1, 6, 400).astype(np.float32)
    cfg = als_lib.ALSConfig(rank=4, iterations=2, seed=4, split_above=64)

    real_loop = als_lib._train_loop

    def nan_loop(uf0, itf0, *a, **k):
        uf, itf = real_loop(uf0, itf0, *a, **k)
        return uf * np.nan, itf

    monkeypatch.setattr(als_lib, "_train_loop", nan_loop)
    with pytest.raises(TrainDiverged):
        als_lib.train_als(users, items, ratings, 20, 15, cfg)


# -- preemption --------------------------------------------------------------

def test_sigterm_handler_sets_preemption_flag(pio_home):
    import os
    import signal

    installed = install_preemption_handler()
    assert installed
    try:
        assert not preemption_requested()
        os.kill(os.getpid(), signal.SIGTERM)
        # synchronous on the main thread: the handler ran on return
        assert preemption_requested()
    finally:
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        clear_preemption()
    assert PREEMPTED_EXIT_CODE == 143


def test_preempted_als_resumes_bitwise_equal(pio_home, tmp_path,
                                             monkeypatch):
    from predictionio_tpu.models import als as als_lib

    rng = np.random.default_rng(3)
    users = rng.integers(0, 40, 1200)
    items = (rng.zipf(1.4, 1200) % 30).astype(np.int64)
    ratings = rng.integers(1, 6, 1200).astype(np.float32)
    cfg = als_lib.ALSConfig(rank=8, iterations=6, reg=0.05, seed=4,
                            split_above=64)
    expected = als_lib.train_als(users, items, ratings, 40, 30, cfg)

    # "SIGTERM" lands between sweep chunks: the flag is what the signal
    # handler sets; raising it from inside the loop is the same path
    # without the cross-test hazard of a real signal.
    real_loop = als_lib._train_loop
    calls = {"n": 0}

    def preempting_loop(*a, **k):
        calls["n"] += 1
        if calls["n"] == 2:
            request_preemption()
        return real_loop(*a, **k)

    ck = tmp_path / "als"
    monkeypatch.setattr(als_lib, "_train_loop", preempting_loop)
    with pytest.raises(TrainPreempted) as ei:
        als_lib.train_als(users, items, ratings, 40, 30, cfg,
                          checkpoint_dir=ck, save_every=2)
    assert ei.value.checkpointed
    monkeypatch.setattr(als_lib, "_train_loop", real_loop)
    clear_preemption()

    resumed = als_lib.train_als(users, items, ratings, 40, 30, cfg,
                                checkpoint_dir=ck, save_every=2)
    np.testing.assert_array_equal(np.asarray(expected.user_factors),
                                  np.asarray(resumed.user_factors))
    np.testing.assert_array_equal(np.asarray(expected.item_factors),
                                  np.asarray(resumed.item_factors))


def test_preempted_run_marks_instance_preempted(pio_home, tmp_path,
                                                monkeypatch):
    """run_train records status=PREEMPTED (not FAILED) and the CLI's
    documented exit code is distinct from failure."""
    import os

    from predictionio_tpu.controller import EngineVariant, RuntimeContext
    from predictionio_tpu.data.event import DataMap, Event
    from predictionio_tpu.data.storage import App, get_storage
    from predictionio_tpu.models import als as als_lib
    from predictionio_tpu.templates.recommendation import engine
    from predictionio_tpu.workflow.core_workflow import run_train

    storage = get_storage()
    ctx = RuntimeContext.create(storage=storage)
    app_id = storage.get_apps().insert(App(id=None, name="papp"))
    storage.get_events().init(app_id)
    rng = np.random.default_rng(0)
    storage.get_events().insert_batch(
        [Event(event="rate", entity_type="user", entity_id=f"u{u}",
               target_entity_type="item", target_entity_id=f"i{i}",
               properties=DataMap({"rating": float(r)}))
         for u, i, r in zip(rng.integers(0, 20, 300),
                            rng.integers(0, 15, 300),
                            rng.integers(1, 6, 300))], app_id)
    variant = EngineVariant.from_dict({
        "engineFactory": "predictionio_tpu.templates.recommendation:engine",
        "datasource": {"params": {"appName": "papp"}},
        "algorithms": [{"name": "als",
                        "params": {"rank": 4, "numIterations": 4}}],
    })
    monkeypatch.setenv("PIO_CHECKPOINT_DIR", str(tmp_path / "ck"))
    monkeypatch.setenv("PIO_CHECKPOINT_EVERY", "1")

    real_loop = als_lib._train_loop

    def preempting_loop(*a, **k):
        request_preemption()
        return real_loop(*a, **k)

    monkeypatch.setattr(als_lib, "_train_loop", preempting_loop)
    with pytest.raises(TrainPreempted):
        run_train(engine(), variant, ctx)
    rows = storage.get_engine_instances().get_all()
    assert [r.status for r in rows] == ["PREEMPTED"]
    assert os.path.isdir(tmp_path / "ck" / "als")


# -- serving: staged reload / fail-closed / rollback -------------------------

def _trained_server(storage, n_events=400, breaker=None):
    from predictionio_tpu.controller import EngineVariant, RuntimeContext
    from predictionio_tpu.data.event import DataMap, Event
    from predictionio_tpu.data.storage import App
    from predictionio_tpu.server import EngineServer
    from predictionio_tpu.templates.recommendation import engine
    from predictionio_tpu.workflow.core_workflow import run_train

    ctx = RuntimeContext.create(storage=storage)
    app_id = storage.get_apps().insert(App(id=None, name="sapp"))
    storage.get_events().init(app_id)
    rng = np.random.default_rng(1)
    storage.get_events().insert_batch(
        [Event(event="rate", entity_type="user", entity_id=f"u{u}",
               target_entity_type="item", target_entity_id=f"i{i}",
               properties=DataMap({"rating": float(r)}))
         for u, i, r in zip(rng.integers(0, 30, n_events),
                            rng.integers(0, 20, n_events),
                            rng.integers(1, 6, n_events))], app_id)
    variant = EngineVariant.from_dict({
        "engineFactory": "predictionio_tpu.templates.recommendation:engine",
        "datasource": {"params": {"appName": "sapp"}},
        "algorithms": [{"name": "als",
                        "params": {"rank": 4, "numIterations": 2}}],
    })
    eng = engine()
    iid = run_train(eng, variant, ctx)
    srv = EngineServer(eng, variant, storage, host="127.0.0.1", port=0,
                       breaker=breaker)
    return srv, eng, variant, ctx, iid


def test_reload_under_total_storage_outage_serves_last_good(pio_home):
    """ISSUE 4 acceptance: storage 100% faulted → reload fails closed,
    /queries.json answers from the last-good model with zero non-2xx,
    /ready stays 200, pio_model_reload_total{result="failed"} and the
    breaker transition are observable."""
    from predictionio_tpu.data.storage import get_storage
    from predictionio_tpu.obs import get_registry
    from predictionio_tpu.resilience.policy import CircuitBreaker
    from predictionio_tpu.data.storage import (
        StorageUnavailable,
    )

    breaker = CircuitBreaker(
        "modeldata", failure_threshold=2, recovery_time_s=60.0,
        failure_types=(StorageUnavailable, ConnectionError))
    srv, *_ = _trained_server(get_storage(), breaker=breaker)
    gen0 = srv._generation
    faults.install("storage.find:error:1.0")
    try:
        st, _body = srv.handle("POST", "/reload", b"")
        assert st == 503
        # predicts never touch storage: zero non-2xx during the outage
        for u in range(10):
            st, body = srv.handle(
                "POST", "/queries.json",
                json.dumps({"user": f"u{u}", "num": 3}).encode())
            assert st == 200 and "itemScores" in body
        st, body = srv.handle("GET", "/ready", b"")
        assert st == 200 and body["status"] == "ready"
        # second failure trips the threshold-2 breaker → open, and the
        # next reload sheds WITHOUT touching storage
        st, _ = srv.handle("POST", "/reload", b"")
        assert st == 503
        assert breaker.state == "open"
        st, _ = srv.handle("POST", "/reload", b"")
        assert st == 503
    finally:
        faults.clear()
    assert srv._generation == gen0, "failed reloads must not bump the gen"
    reg = get_registry()
    c = reg.counter("pio_model_reload_total", "", ("result",))
    assert c.value(result="failed") >= 2
    t = reg.counter("pio_breaker_transitions_total", "", ("breaker", "to"))
    assert t.value(breaker="modeldata", to="open") == 1
    st, body = srv.handle("GET", "/", b"")
    assert body["breaker"] == "open"
    assert body["lastReload"]["result"] == "failed"


def test_reload_swaps_and_rollback_restores_previous_generation(pio_home):
    from predictionio_tpu.data.storage import get_storage
    from predictionio_tpu.workflow.core_workflow import run_train

    srv, eng, variant, ctx, iid1 = _trained_server(get_storage())
    iid2 = run_train(eng, variant, ctx)
    st, body = srv.handle("POST", "/reload", b"")
    assert st == 200 and body["engineInstanceId"] == iid2
    assert body["generation"] == 2
    st, body = srv.handle("POST", "/admin/rollback", b"")
    assert st == 200 and body["engineInstanceId"] == iid1
    assert body["generation"] == 3
    # rollback of the rollback returns to iid2
    st, body = srv.handle("POST", "/admin/rollback", b"")
    assert st == 200 and body["engineInstanceId"] == iid2
    # queries keep working on the rolled-to generation
    st, body = srv.handle("POST", "/queries.json",
                          json.dumps({"user": "u1", "num": 2}).encode())
    assert st == 200


def test_rollback_without_previous_generation_409s(pio_home):
    from predictionio_tpu.data.storage import get_storage

    srv, *_ = _trained_server(get_storage())
    st, body = srv.handle("POST", "/admin/rollback", b"")
    assert st == 409 and "roll back" in body["message"]


def test_canary_query_gates_reload(pio_home, monkeypatch):
    """A candidate that cannot answer the golden queries is rejected
    (409) and the last-good generation keeps serving."""
    from predictionio_tpu.data.storage import get_storage
    from predictionio_tpu.workflow.core_workflow import run_train

    srv, eng, variant, ctx, iid1 = _trained_server(get_storage())
    run_train(eng, variant, ctx)
    # a malformed canary (missing required "user" field) fails binding
    monkeypatch.setenv("PIO_CANARY_QUERIES",
                       json.dumps([{"nope": True}]))
    st, body = srv.handle("POST", "/reload", b"")
    assert st == 409 and "canary" in body["message"]
    assert srv._instance.id == iid1, "last-good must keep serving"
    # a valid canary passes
    monkeypatch.setenv("PIO_CANARY_QUERIES",
                       json.dumps([{"user": "u1", "num": 2}]))
    st, body = srv.handle("POST", "/reload", b"")
    assert st == 200


def test_finite_validation_rejects_nan_model(pio_home, monkeypatch):
    """A persisted model with NaN factors never reaches the swap."""
    from predictionio_tpu.data.storage import get_storage
    from predictionio_tpu.workflow import core_workflow
    from predictionio_tpu.workflow.core_workflow import run_train

    srv, eng, variant, ctx, iid1 = _trained_server(get_storage())
    run_train(eng, variant, ctx)

    real_load = core_workflow.load_models

    def poisoned_load(engine, instance, c=None):
        models = real_load(engine, instance, c)
        m = models[0]
        uf = np.asarray(m.model.user_factors).copy()
        uf[0, 0] = np.nan
        m.model.user_factors = uf
        return models

    # engine_server imported load_models by name — patch it there
    from predictionio_tpu.server import engine_server as es_mod

    monkeypatch.setattr(es_mod, "load_models", poisoned_load)
    st, body = srv.handle("POST", "/reload", b"")
    assert st == 409 and "non-finite" in body["message"]
    assert srv._instance.id == iid1


def test_status_page_reports_generation_and_reload(pio_home):
    from predictionio_tpu.data.storage import get_storage

    srv, *_ = _trained_server(get_storage())
    st, body = srv.handle("GET", "/", b"")
    assert st == 200
    assert body["modelGeneration"] == 1
    assert body["lastReload"]["result"] == "ok"
    assert body["rollbackAvailable"] is False
    assert body["breaker"] == "closed"


def test_pio_status_serving_snapshot_parses_metrics(capsys):
    from predictionio_tpu.cli.main import _print_serving_snapshot

    _print_serving_snapshot([
        "# HELP pio_model_generation gen",
        "pio_model_generation 4",
        'pio_model_reload_total{result="ok"} 3',
        'pio_model_reload_total{result="failed"} 2',
        'pio_breaker_state{breaker="modeldata"} 2',
        'pio_breaker_state{breaker="eventdata"} 0',
        'pio_watchdog_fired_total{fn="als"} 1',
    ])
    out = capsys.readouterr().out
    assert "model generation 4" in out
    assert "failed=2, ok=3" in out
    assert "breaker [modeldata]: open" in out
    assert "breaker [eventdata]: closed" in out
    assert "watchdog fired [als]: 1" in out
