"""Storage contract tests — one spec, every backend.

Reference: data/.../storage/LEventsSpec / PEventsSpec run against multiple
backends via env selection (SURVEY.md §4 "storage-contract tests").  Here
pytest parametrization replaces env selection.
"""

import datetime as dt
import json

import pytest

from predictionio_tpu.data import DataMap, Event
from predictionio_tpu.data.storage import StorageError
from predictionio_tpu.data.storage.base import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    Model,
)

UTC = dt.timezone.utc


def ts(s):
    return dt.datetime.fromisoformat(s).replace(tzinfo=UTC)


def _hosted(client):
    """Storage-like adapter exposing one SQLiteClient's repositories to a
    StorageServer (shared by the remote-backend tests)."""

    class Hosted:
        get_events = staticmethod(client.events)
        get_apps = staticmethod(client.apps)
        get_access_keys = staticmethod(client.access_keys)
        get_channels = staticmethod(client.channels)
        get_engine_instances = staticmethod(client.engine_instances)
        get_evaluation_instances = staticmethod(client.evaluation_instances)
        get_models = staticmethod(client.models)

    return Hosted


# --------------------------------------------------------------------------
# Events contract
# --------------------------------------------------------------------------

def _remote_pair(tmp_path):
    """An OUT-OF-PROCESS-shaped backend: sqlite behind the TCP storage
    server, reached through RemoteClient — the same traits over the wire
    (round-2 verdict item 4: pluggability proven by a second
    process-external backend)."""
    from predictionio_tpu.data.storage.remote import RemoteClient, StorageServer
    from predictionio_tpu.data.storage.sqlite import SQLiteClient

    client = SQLiteClient(str(tmp_path / "served.db"))

    Hosted = _hosted(client)

    srv = StorageServer(Hosted, host="127.0.0.1", port=0)
    srv.start()
    remote = RemoteClient("127.0.0.1", srv.port)

    def cleanup():
        remote.close()
        srv.stop()
        client.close()

    return remote, cleanup


@pytest.fixture(params=["memory", "sqlite", "parquetlog", "pioserver"])
def events_backend(request, tmp_path):
    if request.param == "memory":
        from predictionio_tpu.data.storage.memory import MemoryEvents

        yield MemoryEvents()
    elif request.param == "sqlite":
        from predictionio_tpu.data.storage.sqlite import SQLiteClient

        client = SQLiteClient(str(tmp_path / "ev.db"))
        yield client.events()
        client.close()
    elif request.param == "pioserver":
        remote, cleanup = _remote_pair(tmp_path)
        yield remote.events()
        cleanup()
    else:
        from predictionio_tpu.data.storage.parquet_events import ParquetEvents

        yield ParquetEvents(str(tmp_path / "events"))


def _mk(event, eid, t, etype="user", target=None, props=None):
    return Event(
        event=event,
        entity_type=etype,
        entity_id=eid,
        target_entity_type="item" if target else None,
        target_entity_id=target,
        properties=DataMap(props or {}),
        event_time=ts(t),
    )


APP = 7


class TestEventsContract:
    def test_requires_init(self, events_backend):
        with pytest.raises(StorageError):
            list(events_backend.find(APP))

    def test_insert_get_delete(self, events_backend):
        ev = events_backend
        ev.init(APP)
        eid = ev.insert(_mk("rate", "u1", "2026-01-01T00:00:00", target="i1",
                            props={"rating": 4.5}), APP)
        got = ev.get(eid, APP)
        assert got is not None
        assert got.event == "rate" and got.entity_id == "u1"
        assert got.target_entity_id == "i1"
        assert got.properties.get_double("rating") == 4.5
        assert got.event_time == ts("2026-01-01T00:00:00")
        assert ev.delete(eid, APP) is True
        assert ev.get(eid, APP) is None
        assert ev.delete(eid, APP) is False

    def test_find_filters_and_order(self, events_backend):
        ev = events_backend
        ev.init(APP)
        ev.insert_batch(
            [
                _mk("view", "u1", "2026-01-01T00:00:00", target="i1"),
                _mk("buy", "u1", "2026-01-02T00:00:00", target="i2"),
                _mk("view", "u2", "2026-01-03T00:00:00", target="i1"),
                _mk("view", "u1", "2026-01-04T00:00:00", target="i3"),
            ],
            APP,
        )
        all_ev = list(ev.find(APP))
        assert [e.event_time for e in all_ev] == sorted(e.event_time for e in all_ev)
        assert len(all_ev) == 4
        u1 = list(ev.find(APP, entity_type="user", entity_id="u1"))
        assert len(u1) == 3
        views = list(ev.find(APP, event_names=["view"]))
        assert len(views) == 3
        window = list(
            ev.find(APP, start_time=ts("2026-01-02T00:00:00"),
                    until_time=ts("2026-01-04T00:00:00"))
        )
        assert [e.event for e in window] == ["buy", "view"]
        tgt = list(ev.find(APP, target_entity_type="item", target_entity_id="i1"))
        assert len(tgt) == 2
        newest = list(ev.find(APP, limit=2, reversed=True))
        assert [e.event_time for e in newest] == [ts("2026-01-04T00:00:00"),
                                                  ts("2026-01-03T00:00:00")]

    def test_time_window_boundary_inclusivity(self, events_backend):
        """ISSUE 10 satellite: the refresh loop's gap/overlap-free window
        contract — ``start_time`` INCLUSIVE, ``until_time`` EXCLUSIVE —
        pinned identical across every backend.  A generation trained
        with ``until_time=W`` plus a delta trained with
        ``start_time=W`` must cover every event exactly once, including
        one stamped exactly at W."""
        ev = events_backend
        ev.init(APP)
        ev.insert_batch(
            [
                _mk("a", "u1", "2026-01-01T00:00:00"),
                _mk("b", "u1", "2026-01-02T00:00:00"),   # exactly at W
                _mk("c", "u1", "2026-01-03T00:00:00"),
            ],
            APP,
        )
        w = ts("2026-01-02T00:00:00")
        before = [e.event for e in ev.find(APP, until_time=w)]
        after = [e.event for e in ev.find(APP, start_time=w)]
        assert before == ["a"], "until_time must be EXCLUSIVE"
        assert after == ["b", "c"], "start_time must be INCLUSIVE"
        assert sorted(before + after) == ["a", "b", "c"]  # no gap/overlap
        # the columnar (training) read follows the same contract
        tbl = ev.find_columnar(APP, start_time=w)
        assert tbl.num_rows == 2
        tbl = ev.find_columnar(APP, until_time=w)
        assert tbl.num_rows == 1

    # -- bulk-ingest create_batch contract (ISSUE 17) ------------------

    def test_create_batch_lands_all_rows(self, events_backend):
        ev = events_backend
        ev.init(APP)
        ids = ev.create_batch(
            [
                _mk("view", "u1", "2026-01-01T00:00:00", target="i1"),
                _mk("buy", "u2", "2026-01-02T00:00:00", target="i2"),
            ],
            APP,
            tokens=["tokA.0", "tokA.1"],
        )
        assert len(ids) == 2 and len(set(ids)) == 2
        got = [ev.get(i, APP) for i in ids]
        assert [g.event for g in got] == ["view", "buy"]
        assert len(list(ev.find(APP))) == 2

    def test_create_batch_replay_is_idempotent(self, events_backend):
        """The exactly-once core: replaying the SAME sub-tokens (a client
        retry after a crashed reply, a journal replay after restart)
        lands each row at most once and returns the same ids."""
        ev = events_backend
        ev.init(APP)
        events = [
            _mk("view", "u1", "2026-01-01T00:00:00", target="i1"),
            _mk("buy", "u2", "2026-01-02T00:00:00", target="i2"),
        ]
        toks = ["replay.0", "replay.1"]
        first = ev.create_batch(events, APP, tokens=toks)
        second = ev.create_batch(events, APP, tokens=toks)
        assert first == second
        assert len(list(ev.find(APP))) == 2

    def test_create_batch_partial_landing_replays_per_item(
            self, events_backend):
        """A crash can leave HALF a batch committed (the reply was lost
        either way).  Dedup is per-item, not per-batch: the replay must
        fill in only the missing rows."""
        ev = events_backend
        ev.init(APP)
        events = [
            _mk("view", "u1", "2026-01-01T00:00:00", target="i1"),
            _mk("buy", "u2", "2026-01-02T00:00:00", target="i2"),
        ]
        toks = ["part.0", "part.1"]
        # simulate the partial landing: only item 0 committed
        ev.create_batch(events[:1], APP, tokens=toks[:1])
        assert len(list(ev.find(APP))) == 1
        ids = ev.create_batch(events, APP, tokens=toks)
        assert len(ids) == 2
        all_ev = list(ev.find(APP))
        assert len(all_ev) == 2, "replay must add ONLY the missing row"
        assert sorted(e.event for e in all_ev) == ["buy", "view"]

    def test_create_batch_without_tokens_still_lands(self, events_backend):
        # tokens are optional — an untokened call degrades to plain
        # multi-row insert semantics (at-least-once, server-generated ids)
        ev = events_backend
        ev.init(APP)
        ids = ev.create_batch(
            [_mk("view", "u1", "2026-01-01T00:00:00", target="i1")], APP)
        assert len(ids) == 1
        assert ev.get(ids[0], APP).event == "view"

    def test_time_window_naive_bounds_mean_utc(self, events_backend):
        """A NAIVE window bound means the same instant as the aware-UTC
        stamp on every backend (the shared epoch_us rule) — a daemon
        passing datetime.utcnow() must not shift or crash anywhere."""
        ev = events_backend
        ev.init(APP)
        ev.insert_batch(
            [
                _mk("a", "u1", "2026-01-01T00:00:00"),
                _mk("b", "u1", "2026-01-02T00:00:00"),
            ],
            APP,
        )
        naive = dt.datetime(2026, 1, 2)  # no tzinfo → means UTC
        assert [e.event for e in ev.find(APP, start_time=naive)] == ["b"]
        assert [e.event for e in ev.find(APP, until_time=naive)] == ["a"]

    def test_equal_event_times_order_by_creation(self, events_backend):
        """Ties on event_time order by creation_time everywhere — the
        watermark contract needs ONE deterministic order, not a
        per-backend one."""
        ev = events_backend
        ev.init(APP)
        t = ts("2026-01-01T00:00:00")
        for name, created in (("first", "2026-01-01T10:00:00"),
                              ("second", "2026-01-01T11:00:00")):
            ev.insert(Event(event=name, entity_type="user", entity_id="u1",
                            event_time=t, creation_time=ts(created)), APP)
        assert [e.event for e in ev.find(APP)] == ["first", "second"]
        assert [e.event for e in ev.find(APP, reversed=True)] == \
            ["second", "first"]

    def test_latest_event_time(self, events_backend):
        """Ingest high-watermark (ISSUE 10): max event_time, None when
        empty, channel-scoped — every backend."""
        ev = events_backend
        ev.init(APP)
        assert ev.latest_event_time(APP) is None
        ev.insert_batch(
            [
                _mk("a", "u1", "2026-01-02T00:00:00"),
                _mk("b", "u1", "2026-01-05T00:00:00"),
                _mk("c", "u1", "2026-01-03T00:00:00"),
            ],
            APP,
        )
        assert ev.latest_event_time(APP) == ts("2026-01-05T00:00:00")
        ev.init(APP, channel_id=2)
        assert ev.latest_event_time(APP, 2) is None
        ev.insert(_mk("d", "u1", "2026-02-01T00:00:00"), APP, channel_id=2)
        assert ev.latest_event_time(APP, 2) == ts("2026-02-01T00:00:00")
        assert ev.latest_event_time(APP) == ts("2026-01-05T00:00:00")

    def test_channel_isolation(self, events_backend):
        ev = events_backend
        ev.init(APP)
        ev.init(APP, channel_id=2)
        ev.insert(_mk("view", "u1", "2026-01-01T00:00:00"), APP)
        ev.insert(_mk("buy", "u1", "2026-01-02T00:00:00"), APP, channel_id=2)
        assert [e.event for e in ev.find(APP)] == ["view"]
        assert [e.event for e in ev.find(APP, channel_id=2)] == ["buy"]

    def test_remove(self, events_backend):
        ev = events_backend
        ev.init(APP)
        ev.insert(_mk("view", "u1", "2026-01-01T00:00:00"), APP)
        assert ev.remove(APP) is True
        with pytest.raises(StorageError):
            list(ev.find(APP))

    def test_find_columnar(self, events_backend):
        ev = events_backend
        ev.init(APP)
        ev.insert_batch(
            [
                _mk("rate", "u1", "2026-01-01T00:00:00", target="i1", props={"r": 1.0}),
                _mk("rate", "u2", "2026-01-02T00:00:00", target="i2", props={"r": 2.0}),
            ],
            APP,
        )
        table = ev.find_columnar(APP, event_names=["rate"])
        assert table.num_rows == 2
        assert table.column("entity_id").to_pylist() == ["u1", "u2"]
        props = [json.loads(p) for p in table.column("properties_json").to_pylist()]
        assert [p["r"] for p in props] == [1.0, 2.0]

    def test_find_columnar_unordered_and_projected(self, events_backend):
        ev = events_backend
        ev.init(APP)
        ev.insert_batch(
            [
                _mk("rate", "u2", "2026-01-02T00:00:00", target="i2", props={"r": 2.0}),
                _mk("rate", "u1", "2026-01-01T00:00:00", target="i1", props={"r": 1.0}),
                _mk("view", "u3", "2026-01-03T00:00:00", target="i1"),
            ],
            APP,
        )
        # projection returns exactly the named columns (in that order)
        t = ev.find_columnar(APP, event_names=["rate"],
                             columns=["entity_id", "properties_json"])
        assert t.column_names == ["entity_id", "properties_json"]
        assert sorted(t.column("entity_id").to_pylist()) == ["u1", "u2"]
        # unordered returns the same ROWS, any order
        t2 = ev.find_columnar(APP, event_names=["rate"], ordered=False,
                              columns=["entity_id"])
        assert sorted(t2.column("entity_id").to_pylist()) == ["u1", "u2"]
        # ordered remains the default and sorts by event time
        t3 = ev.find_columnar(APP, event_names=["rate"])
        assert t3.column("entity_id").to_pylist() == ["u1", "u2"]

    def test_insert_columnar(self, events_backend):
        import pyarrow as pa

        ev = events_backend
        ev.init(APP)
        n = ev.insert_columnar(
            pa.table({
                "event": ["rate", "rate", "buy"],
                "entity_type": ["user"] * 3,
                "entity_id": ["u1", "u2", "u1"],
                "target_entity_type": ["item"] * 3,
                "target_entity_id": ["i1", "i2", "i3"],
                "properties_json": ['{"rating": 4.5}', '{"rating": 3.0}', None],
                "event_time_us": [1_700_000_000_000_000 + i for i in range(3)],
            }),
            APP,
        )
        assert n == 3
        got = list(ev.find(APP))
        assert len(got) == 3
        assert sorted(e.event for e in got) == ["buy", "rate", "rate"]
        rate1 = next(e for e in got if e.entity_id == "u1" and e.event == "rate")
        assert rate1.properties.get_double("rating") == 4.5
        assert rate1.event_time is not None
        # ids are store-assigned, unique, and get() resolves them
        ids = {e.event_id for e in got}
        assert len(ids) == 3 and None not in ids
        some = next(iter(ids))
        assert ev.get(some, APP) is not None
        # the bulk rows coexist with row-path inserts on the same scan
        ev.insert(_mk("rate", "u9", "2026-01-05T00:00:00", target="i9",
                      props={"rating": 1.0}), APP)
        t = ev.find_columnar(APP, event_names=["rate"], ordered=False,
                             columns=["entity_id", "properties_json"])
        assert sorted(t.column("entity_id").to_pylist()) == ["u1", "u2", "u9"]
        from predictionio_tpu.data.columnar import numeric_property
        vals = numeric_property(t, "rating")
        assert sorted(vals.tolist()) == [1.0, 3.0, 4.5]

    def test_insert_columnar_validates(self, events_backend):
        import pyarrow as pa

        ev = events_backend
        ev.init(APP)
        with pytest.raises(StorageError):
            ev.insert_columnar(pa.table({"event": ["x"]}), APP)
        with pytest.raises(StorageError):
            ev.insert_columnar(
                pa.table({"event": ["x"], "entity_type": ["u"],
                          "entity_id": ["1"], "bogus": ["y"]}), APP)
        # nulls in a required column are rejected per the event contract
        with pytest.raises(StorageError):
            ev.insert_columnar(
                pa.table({"event": ["x", None], "entity_type": ["u", "u"],
                          "entity_id": ["1", "2"]}), APP)
        # per-row null event times get the server-clock default
        n = ev.insert_columnar(
            pa.table({"event": ["x", "y"], "entity_type": ["u", "u"],
                      "entity_id": ["1", "2"],
                      "event_time_us": pa.array([1_700_000_000_000_000,
                                                 None])}), APP)
        assert n == 2
        assert all(e.event_time is not None for e in ev.find(APP))

    def test_aggregate_properties(self, events_backend):
        ev = events_backend
        ev.init(APP)
        ev.insert_batch(
            [
                _mk("$set", "i1", "2026-01-01T00:00:00", etype="item",
                    props={"cat": "a", "price": 10}),
                _mk("$set", "i1", "2026-01-02T00:00:00", etype="item", props={"price": 12}),
                _mk("$set", "i2", "2026-01-01T00:00:00", etype="item", props={"cat": "b"}),
                _mk("$delete", "i2", "2026-01-03T00:00:00", etype="item"),
                _mk("view", "u1", "2026-01-02T00:00:00"),
            ],
            APP,
        )
        props = ev.aggregate_properties(APP, entity_type="item")
        assert set(props) == {"i1"}
        assert props["i1"].to_dict() == {"cat": "a", "price": 12}


# --------------------------------------------------------------------------
# Metadata contract
# --------------------------------------------------------------------------

@pytest.fixture(params=["memory", "sqlite", "pioserver"])
def meta_backend(request, tmp_path):
    if request.param == "pioserver":
        remote, cleanup = _remote_pair(tmp_path)

        class B:
            apps = remote.apps()
            keys = remote.access_keys()
            channels = remote.channels()
            instances = remote.engine_instances()
            models = remote.models()

        yield B
        cleanup()
    elif request.param == "memory":
        from predictionio_tpu.data.storage import memory as m

        class B:
            apps = m.MemoryApps()
            keys = m.MemoryAccessKeys()
            channels = m.MemoryChannels()
            instances = m.MemoryEngineInstances()
            models = m.MemoryModels()

        yield B
    else:
        from predictionio_tpu.data.storage.sqlite import SQLiteClient

        client = SQLiteClient(str(tmp_path / "meta.db"))

        class B:
            apps = client.apps()
            keys = client.access_keys()
            channels = client.channels()
            instances = client.engine_instances()
            models = client.models()

        yield B
        client.close()


class TestMetadataContract:
    def test_apps_crud(self, meta_backend):
        apps = meta_backend.apps
        aid = apps.insert(App(id=None, name="myapp", description="d"))
        assert aid is not None
        assert apps.get(aid).name == "myapp"
        assert apps.get_by_name("myapp").id == aid
        assert apps.insert(App(id=None, name="myapp")) is None  # duplicate name
        assert apps.update(App(id=aid, name="renamed", description=None))
        assert apps.get(aid).name == "renamed"
        assert [a.id for a in apps.get_all()] == [aid]
        assert apps.delete(aid) is True
        assert apps.get(aid) is None

    def test_access_keys(self, meta_backend):
        keys = meta_backend.keys
        k = keys.insert(AccessKey(key="", app_id=3, events=("view",)))
        assert k
        got = keys.get(k)
        assert got.app_id == 3 and got.events == ("view",)
        assert keys.get_by_app_id(3)[0].key == k
        assert keys.delete(k) is True
        assert keys.get(k) is None

    def test_channels(self, meta_backend):
        ch = meta_backend.channels
        cid = ch.insert(Channel(id=None, name="live", app_id=3))
        assert cid is not None
        assert ch.get(cid).name == "live"
        # invalid name (too long / bad chars) rejected
        assert ch.insert(Channel(id=None, name="x" * 17, app_id=3)) is None
        assert ch.insert(Channel(id=None, name="bad name", app_id=3)) is None
        # duplicate per app rejected
        assert ch.insert(Channel(id=None, name="live", app_id=3)) is None
        assert [c.id for c in ch.get_by_app_id(3)] == [cid]
        assert ch.delete(cid) is True

    def test_engine_instances_lifecycle(self, meta_backend):
        insts = meta_backend.instances

        def mk(status, t):
            return EngineInstance(
                id=None, status=status, start_time=ts(t), end_time=None,
                engine_id="e1", engine_version="v1", engine_variant="default",
                engine_factory="my.Factory",
                algorithms_params='[{"name":"als","params":{"rank":8}}]',
            )

        i1 = insts.insert(mk("TRAINING", "2026-01-01T00:00:00"))
        i2 = insts.insert(mk("COMPLETED", "2026-01-02T00:00:00"))
        i3 = insts.insert(mk("COMPLETED", "2026-01-03T00:00:00"))
        assert insts.get_latest_completed("e1", "v1", "default").id == i3
        assert [i.id for i in insts.get_completed("e1", "v1", "default")] == [i3, i2]
        inst = insts.get(i1)
        inst.status = "FAILED"
        inst.end_time = ts("2026-01-01T01:00:00")
        assert insts.update(inst)
        assert insts.get(i1).status == "FAILED"
        assert insts.get(i1).end_time == ts("2026-01-01T01:00:00")
        assert json.loads(insts.get(i2).algorithms_params)[0]["params"]["rank"] == 8
        assert insts.delete(i1)

    def test_models_blob(self, meta_backend):
        models = meta_backend.models
        models.insert(Model(id="m1", models=b"\x00\x01binary"))
        assert models.get("m1").models == b"\x00\x01binary"
        models.insert(Model(id="m1", models=b"v2"))  # overwrite
        assert models.get("m1").models == b"v2"
        assert models.delete("m1") is True
        assert models.get("m1") is None


# --------------------------------------------------------------------------
# localfs models + registry
# --------------------------------------------------------------------------

def test_localfs_models(tmp_path):
    from predictionio_tpu.data.storage.localfs_models import LocalFSModels

    m = LocalFSModels(str(tmp_path / "models"))
    m.insert(Model(id="engine/inst1", models=b"blob"))
    assert m.get("engine/inst1").models == b"blob"
    assert m.delete("engine/inst1") is True
    assert m.get("engine/inst1") is None


def test_storage_registry_defaults(pio_home):
    from predictionio_tpu.data.storage import Storage

    s = Storage()
    assert s.verify() == {
        "METADATA": "sqlite", "EVENTDATA": "sqlite", "MODELDATA": "localfs"
    }
    apps = s.get_apps()
    aid = apps.insert(App(id=None, name="regapp"))
    ev = s.get_events()
    ev.init(aid)
    ev.insert(_mk("view", "u1", "2026-01-01T00:00:00"), aid)
    assert len(list(ev.find(aid))) == 1
    s.close()


def test_storage_registry_parquet_eventdata(pio_home, monkeypatch):
    from predictionio_tpu.data.storage import Storage

    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE", "PARQUET")
    s = Storage()
    assert s.verify()["EVENTDATA"] == "parquetlog"
    s.close()


def test_storage_registry_unknown_type(pio_home, monkeypatch):
    from predictionio_tpu.data.storage import Storage

    monkeypatch.setenv("PIO_STORAGE_SOURCES_BOGUS_TYPE", "nosuch")
    monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_METADATA_SOURCE", "BOGUS")
    s = Storage()
    with pytest.raises(StorageError):
        s.get_apps()


def test_pioserver_selected_by_env_alone(pio_home, monkeypatch, tmp_path):
    """The reference's defining storage property: swap to an
    out-of-process backend purely via PIO_STORAGE_* env config."""
    from predictionio_tpu.data.storage import Storage
    from predictionio_tpu.data.storage.remote import StorageServer
    from predictionio_tpu.data.storage.sqlite import SQLiteClient

    client = SQLiteClient(str(tmp_path / "served.db"))

    Hosted = _hosted(client)

    srv = StorageServer(Hosted, host="127.0.0.1", port=0)
    srv.start()
    try:
        monkeypatch.setenv("PIO_STORAGE_SOURCES_REMOTE_TYPE", "pioserver")
        monkeypatch.setenv("PIO_STORAGE_SOURCES_REMOTE_HOSTS", "127.0.0.1")
        monkeypatch.setenv("PIO_STORAGE_SOURCES_REMOTE_PORTS", str(srv.port))
        monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE",
                           "REMOTE")
        monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_METADATA_SOURCE",
                           "REMOTE")
        s = Storage()
        app_id = s.get_apps().insert(App(id=None, name="remoteapp"))
        assert s.get_apps().get_by_name("remoteapp").id == app_id
        ev = s.get_events()
        ev.init(app_id)
        eid = ev.insert(_mk("rate", "u1", "2024-01-01T00:00:00",
                            target="i1", props={"rating": 5}), app_id)
        got = ev.get(eid, app_id)
        assert got.properties["rating"] == 5
        # Data really lives in the SERVED sqlite, not in-process.
        direct = client.events()
        assert direct.get(eid, app_id) is not None
        s.close()
    finally:
        srv.stop()
        client.close()


def test_event_server_over_remote_storage(pio_home, monkeypatch, tmp_path):
    """Deployment-shaped composition: the EVENT server process keeps its
    data in a separate STORAGE server process (upstream: event server ->
    HBase/JDBC).  Ingest over HTTP, verify the bytes landed in the served
    store, then read back through the event server."""
    import urllib.request

    from predictionio_tpu.data.storage import Storage
    from predictionio_tpu.data.storage.base import AccessKey
    from predictionio_tpu.data.storage.remote import StorageServer
    from predictionio_tpu.data.storage.sqlite import SQLiteClient
    from predictionio_tpu.server.event_server import EventServer

    backing = SQLiteClient(str(tmp_path / "backing.db"))

    Hosted = _hosted(backing)

    ss = StorageServer(Hosted, host="127.0.0.1", port=0)
    ss.start()
    try:
        monkeypatch.setenv("PIO_STORAGE_SOURCES_REMOTE_TYPE", "pioserver")
        monkeypatch.setenv("PIO_STORAGE_SOURCES_REMOTE_HOSTS", "127.0.0.1")
        monkeypatch.setenv("PIO_STORAGE_SOURCES_REMOTE_PORTS", str(ss.port))
        monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE",
                           "REMOTE")
        monkeypatch.setenv("PIO_STORAGE_REPOSITORIES_METADATA_SOURCE",
                           "REMOTE")
        storage = Storage()
        from predictionio_tpu.data.storage.base import App

        app_id = storage.get_apps().insert(App(id=None, name="viaremote"))
        storage.get_events().init(app_id)
        key = storage.get_access_keys().insert(AccessKey.generate(app_id))
        es = EventServer(storage, host="127.0.0.1", port=0)
        es.start()
        try:
            url = (f"http://127.0.0.1:{es.port}/events.json"
                   f"?accessKey={key}")
            req = urllib.request.Request(
                url, data=json.dumps({
                    "event": "rate", "entityType": "user", "entityId": "u1",
                    "targetEntityType": "item", "targetEntityId": "i1",
                    "properties": {"rating": 5}}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=20) as r:
                eid = json.loads(r.read())["eventId"]
            # The event physically lives in the BACKING sqlite.
            assert backing.events().get(eid, app_id) is not None
            with urllib.request.urlopen(url + "&limit=-1", timeout=20) as r:
                evs = json.loads(r.read())
            assert len(evs) == 1 and evs[0]["properties"]["rating"] == 5
        finally:
            es.stop()
        storage.close()
    finally:
        ss.stop()
        backing.close()


# --------------------------------------------------------------------------
# Remote streaming + auth (round-4: cursor-paginated scans, shared secret)
# --------------------------------------------------------------------------

class TestRemoteStreaming:
    def test_scan_streams_past_the_reply_cap(self, tmp_path, monkeypatch):
        """A scan bigger than the per-message cap succeeds because it is
        cursor-paginated — the legacy one-shot find RPC on the same data
        blows the cap (round-3 weakness: find materialized everything)."""
        from predictionio_tpu.data.storage import remote as remote_mod

        remote, cleanup = _remote_pair(tmp_path)
        try:
            events = remote.events()
            events.init(APP)
            n = 500
            events.insert_batch(
                [_mk("rate", f"u{j}", "2024-01-01T00:00:00", target=f"i{j}",
                     props={"rating": float(j % 5), "pad": "x" * 200})
                 for j in range(n)], APP)
            # Cap a message at 64 KB: 500 padded events in one reply far
            # exceed it, single 50-event pages (~20 KB) do not.
            monkeypatch.setattr(remote_mod, "_MAX_MESSAGE", 64 << 10)
            got = list(remote.stream_find(APP, _batch=50))
            assert len(got) == n
            assert {e.entity_id for e in got} == {f"u{j}" for j in range(n)}
            with pytest.raises(StorageError):
                remote.call("events.find", APP)  # one-shot blows the cap
        finally:
            monkeypatch.undo()
            cleanup()

    def test_abandoned_scan_frees_the_connection(self, tmp_path):
        remote, cleanup = _remote_pair(tmp_path)
        try:
            events = remote.events()
            events.init(APP)
            events.insert_batch(
                [_mk("view", f"u{j}", "2024-01-01T00:00:00")
                 for j in range(50)], APP)
            it = remote.stream_find(APP, _batch=10)
            next(it), next(it)
            it.close()  # break out mid-scan → find_close + conn back to pool
            # The pinned connection really went back: the idle pool is full
            # again (a leak would pass a weaker serve-more-RPCs check,
            # since _lease mints overflow connections on demand).
            assert len(remote._idle) == remote._pool_size
            assert len(list(events.find(APP))) == 50
            assert len(remote._idle) == remote._pool_size
        finally:
            cleanup()


class TestRemoteAuth:
    def _secure_pair(self, tmp_path, server_secret, client_secret):
        from predictionio_tpu.data.storage.remote import (
            RemoteClient, StorageServer)
        from predictionio_tpu.data.storage.sqlite import SQLiteClient

        client = SQLiteClient(str(tmp_path / "served.db"))
        srv = StorageServer(_hosted(client), host="127.0.0.1", port=0,
                            secret=server_secret)
        srv.start()
        remote = RemoteClient("127.0.0.1", srv.port, secret=client_secret)

        def cleanup():
            remote.close()
            srv.stop()
            client.close()

        return remote, cleanup

    def test_matching_secret_round_trips(self, tmp_path):
        remote, cleanup = self._secure_pair(tmp_path, "hunter2", "hunter2")
        try:
            events = remote.events()
            events.init(APP)
            eid = events.insert(
                _mk("rate", "u1", "2024-01-01T00:00:00", target="i1",
                    props={"rating": 4}), APP)
            assert events.get(eid, APP).properties["rating"] == 4
        finally:
            cleanup()

    def test_client_secret_against_unsecured_server(self, tmp_path):
        # Misconfiguration (server started without --secret) must not
        # produce cryptic RPC failures: the server acks the handshake.
        remote, cleanup = self._secure_pair(tmp_path, None, "hunter2")
        try:
            events = remote.events()
            events.init(APP)
            eid = events.insert(
                _mk("rate", "u1", "2024-01-01T00:00:00", target="i1",
                    props={"rating": 3}), APP)
            assert events.get(eid, APP).properties["rating"] == 3
        finally:
            cleanup()

    def test_wrong_secret_rejected(self, tmp_path):
        from predictionio_tpu.data.storage.remote import RemoteBackendError

        remote, cleanup = self._secure_pair(tmp_path, "hunter2", "wrong")
        try:
            with pytest.raises(RemoteBackendError, match="auth"):
                remote.events().get("nope", APP)
        finally:
            cleanup()

    def test_missing_secret_rejected(self, tmp_path):
        from predictionio_tpu.data.storage.remote import RemoteBackendError

        remote, cleanup = self._secure_pair(tmp_path, "hunter2", None)
        try:
            with pytest.raises(RemoteBackendError):
                remote.events().get("nope", APP)
        finally:
            cleanup()
