"""Fault-matrix tests for the resilience subsystem.

Tier-1 safe: CPU only, fake clocks injected into RetryPolicy /
CircuitBreaker (no real sleeps beyond tiny replay-poll ticks), every
fault cleared after each test.
"""

import json
import os
import socket
import struct
import threading
import time
from types import SimpleNamespace

import pytest

from predictionio_tpu.data.storage import (
    AccessKey,
    App,
    StorageUnavailable,
    get_storage,
)
from predictionio_tpu.obs import get_registry
from predictionio_tpu.resilience import faults, idempotency_key
from predictionio_tpu.resilience.deadline import (
    DeadlineExceeded,
    deadline_scope,
    remaining_ms,
)
from predictionio_tpu.resilience.policy import (
    CircuitBreaker,
    CircuitOpenError,
    RetryPolicy,
)
from predictionio_tpu.resilience.spill import SpillJournal
from predictionio_tpu.server.event_server import EventServer

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clear_faults():
    yield
    faults.clear()


# --------------------------------------------------------------------------
# RetryPolicy (fake sleep — no real waiting)
# --------------------------------------------------------------------------


class _Retriable(RuntimeError):
    retriable = True


def test_retry_policy_exponential_jittered_backoff():
    slept = []
    policy = RetryPolicy(max_attempts=4, base_delay_ms=100, multiplier=2.0,
                         jitter=0.25, sleep=slept.append)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 4:
            raise _Retriable("transient")
        return "ok"

    assert policy.run(flaky) == "ok"
    assert calls["n"] == 4 and len(slept) == 3
    for i, s in enumerate(slept):  # seconds; nominal 0.1 * 2^i ± 25%
        nominal = 0.1 * (2 ** i)
        assert nominal * 0.74 <= s <= nominal * 1.26


def test_retry_policy_deadline_refuses_to_sleep_past_budget():
    """A backoff (or a server Retry-After hint far larger than any
    budget) that would sleep past deadline_ts re-raises immediately."""
    slept = []
    policy = RetryPolicy(max_attempts=5, base_delay_ms=100, jitter=0,
                         sleep=slept.append)
    now = [0.0]

    class Hinted(RuntimeError):
        retriable = True
        retry_after_s = 30.0

    with pytest.raises(Hinted):
        policy.run(lambda: (_ for _ in ()).throw(Hinted()),
                   deadline_ts=0.2, clock=lambda: now[0])
    assert slept == []  # 30s hint vs 200ms budget: fail now, don't sleep

    # fits-in-budget backoffs still sleep
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 2:
            raise _Retriable("once")
        return "ok"

    assert policy.run(flaky, deadline_ts=10.0,
                      clock=lambda: now[0]) == "ok"
    assert slept == [0.1]


def test_retry_policy_honors_retry_after_and_gives_up():
    slept = []
    policy = RetryPolicy(max_attempts=2, base_delay_ms=100,
                         sleep=slept.append)

    class Hinted(RuntimeError):
        retriable = True
        retry_after_s = 7.5

    with pytest.raises(Hinted):
        policy.run(lambda: (_ for _ in ()).throw(Hinted()))
    assert slept == [7.5]  # server hint replaces computed backoff

    # non-retriable errors propagate immediately (no sleeps)
    slept.clear()
    with pytest.raises(ValueError):
        policy.run(lambda: (_ for _ in ()).throw(ValueError("client bug")))
    assert slept == []


# --------------------------------------------------------------------------
# CircuitBreaker (fake clock — the schedule is proved without sleeping)
# --------------------------------------------------------------------------


def test_breaker_opens_half_opens_and_recloses_on_schedule(pio_home):
    now = [1000.0]
    br = CircuitBreaker("t", failure_threshold=3, recovery_time_s=30.0,
                        failure_types=(ConnectionError,),
                        clock=lambda: now[0])
    gauge = get_registry().get("pio_breaker_state")

    def boom():
        raise ConnectionError("down")

    assert br.state == "closed" and gauge.value(breaker="t") == 0
    for _ in range(2):
        with pytest.raises(ConnectionError):
            br.call(boom)
    assert br.state == "closed"  # below threshold
    with pytest.raises(ConnectionError):
        br.call(boom)
    assert br.state == "open" and gauge.value(breaker="t") == 2
    with pytest.raises(CircuitOpenError) as ei:
        br.call(lambda: "never runs")
    assert 0 < ei.value.retry_after_s <= 30.0

    now[0] += 29.0
    assert br.state == "open"  # not yet
    now[0] += 1.5
    assert br.state == "half-open" and gauge.value(breaker="t") == 1
    # failed probe re-opens and restarts the recovery clock
    with pytest.raises(ConnectionError):
        br.call(boom)
    assert br.state == "open"
    now[0] += 30.5
    assert br.state == "half-open"
    assert br.call(lambda: "ok") == "ok"  # successful probe closes
    assert br.state == "closed" and gauge.value(breaker="t") == 0


def test_breaker_ignores_non_availability_errors():
    br = CircuitBreaker("sel", failure_threshold=1,
                        failure_types=(ConnectionError,))
    with pytest.raises(ValueError):
        br.call(lambda: (_ for _ in ()).throw(ValueError("bad request")))
    assert br.state == "closed"


# --------------------------------------------------------------------------
# Fault-plan grammar
# --------------------------------------------------------------------------


def test_fault_plan_grammar():
    plan = faults.parse_plan(
        "storage.create:error:0.3,storage.find:delay:200ms,"
        "rpc.recv:error:1.0:2,slowpoke:delay:1.5s:0.5:7")
    kinds = [(r.match, r.kind, r.probability, r.delay_ms, r.max_count)
             for r in plan.rules]
    assert kinds == [
        ("storage.create", "error", 0.3, 0.0, None),
        ("storage.find", "delay", 1.0, 200.0, None),
        ("rpc.recv", "error", 1.0, 0.0, 2),
        ("slowpoke", "delay", 0.5, 1500.0, 7),
    ]
    for bad in ("nocolon", "x:teleport", "x:delay"):
        with pytest.raises(ValueError):
            faults.parse_plan(bad)


def test_fault_point_glob_and_max_count(pio_home):
    naps = []
    plan = faults.FaultPlan(
        [faults.FaultRule("storage.*", "error", max_count=2),
         faults.FaultRule("rpc.send", "delay", delay_ms=30)],
        sleep=naps.append)
    faults.install(plan)
    with pytest.raises(faults.FaultInjected):
        faults.fault_point("storage.create")
    with pytest.raises(faults.FaultInjected):
        faults.fault_point("storage.find")
    faults.fault_point("storage.create")  # rule exhausted: no-op
    faults.fault_point("rpc.send")
    assert naps == [0.03]
    assert get_registry().get(
        "pio_faults_injected_total").total() == 3


# --------------------------------------------------------------------------
# Deadlines
# --------------------------------------------------------------------------


def test_deadline_scope_nests_to_minimum():
    assert remaining_ms() is None
    with deadline_scope(60_000):
        outer = remaining_ms()
        assert outer is not None and outer <= 60_000
        with deadline_scope(1_000_000):  # inner CANNOT extend the budget
            assert remaining_ms() <= 60_000
        with deadline_scope(10):
            assert remaining_ms() <= 10
    assert remaining_ms() is None


def _bare_engine_server():
    """An EngineServer skeleton with no trained instance — resilience
    routes (/ready, deadline shed) must not require a training run."""
    from predictionio_tpu.server.engine_server import (
        EngineServer,
        _QueryMetrics,
    )

    srv = EngineServer.__new__(EngineServer)
    srv.stats = _QueryMetrics()
    srv._swap_lock = threading.Lock()
    srv._instance = None
    srv._serving = None
    srv._algorithms = []
    srv._models = []
    srv._loaded_at = None
    srv._init_lifecycle_state()  # staged-reload state (ISSUE 4)
    srv.variant = SimpleNamespace(engine_factory="f", variant_id="v")
    srv.engine = SimpleNamespace(query_class=None)
    return srv


class _MustNotRun:
    def supplement(self, q):  # pragma: no cover - the test asserts this
        raise AssertionError("algorithm path ran past an expired deadline")

    serve = supplement
    predict = supplement


def test_deadline_exceeded_sheds_before_the_algorithm(pio_home):
    srv = _bare_engine_server()
    srv._instance = SimpleNamespace(id="i1")
    srv._serving = _MustNotRun()
    srv._algorithms = [_MustNotRun()]
    srv._models = [None]
    with deadline_scope(0):
        status, payload = srv.handle("POST", "/queries.json",
                                     json.dumps({"q": 1}).encode())
    assert status == 504
    assert "deadline" in payload["message"].lower()
    assert get_registry().get("pio_deadline_shed_total").value(
        server="engine") == 1
    # with budget left the same request executes (and here, explodes)
    status, _ = srv.handle("POST", "/queries.json", b"{}")
    assert status == 500


def test_engine_ready_reflects_model_load(pio_home):
    srv = _bare_engine_server()
    status, payload = srv.handle("GET", "/ready", b"")
    assert (status, payload["status"]) == (503, "unavailable")
    srv._instance = SimpleNamespace(id="i1")
    srv._serving = object()
    status, payload = srv.handle("GET", "/ready", b"")
    assert (status, payload["engineInstanceId"]) == (200, "i1")


def test_engine_maps_dead_storage_to_503(pio_home):
    """A remote storage backend that exhausted its retries surfaces as
    StorageUnavailable — an availability 503, not a 500 bug report."""
    srv = _bare_engine_server()

    class DeadStorage:
        def get_engine_instances(self):
            raise StorageUnavailable("storage server unreachable")

    srv.storage = DeadStorage()
    srv.requested_instance_id = "i1"
    status, payload = srv.handle("POST", "/reload", b"")
    assert status == 503
    assert "unavailable" in payload["message"].lower()


# --------------------------------------------------------------------------
# Event server degradation (fault matrix)
# --------------------------------------------------------------------------


def _event_stack(pio_home, **server_kw):
    storage = get_storage()
    app_id = storage.get_apps().insert(App(id=None, name="resil"))
    storage.get_events().init(app_id)
    key = storage.get_access_keys().insert(AccessKey(key="", app_id=app_id))
    srv = EventServer(storage=storage, host="127.0.0.1", port=0, **server_kw)
    return srv, key, storage, app_id


def _post(srv, key, path, payload):
    return srv.handle("POST", path, {"accessKey": [key]},
                      json.dumps(payload).encode())


def test_mid_batch_outage_answers_every_item(pio_home):
    """(a) A storage outage mid-batch yields explicit per-item 503s (spill
    disabled) — never a partial silent drop, and the invalid item still
    gets its own 400."""
    srv, key, *_ = _event_stack(pio_home, spill_dir="off")
    try:
        batch = [
            {"event": "buy", "entityType": "user", "entityId": "u0"},
            {"event": "buy", "entityType": "user", "entityId": "u1"},
            {"entityType": "user", "entityId": "broken"},  # no "event"
            {"event": "buy", "entityType": "user", "entityId": "u2"},
        ]
        faults.install("storage.create:error:1.0")
        status, results = _post(srv, key, "/batch/events.json", batch)
        assert status == 200
        assert [r["status"] for r in results] == [503, 503, 400, 503]
        # single-event POST degrades to a plain 503 without a journal
        status, _ = _post(srv, key, "/events.json",
                          {"event": "buy", "entityType": "user",
                           "entityId": "u9"})
        assert status == 503
        faults.clear()
        assert list(get_storage().get_events().find(1)) == []
    finally:
        srv.stop()


def test_full_outage_spills_200_events_then_replays_exactly_once(pio_home):
    """(b) + acceptance: a 200-event ingest during a total storage outage
    loses nothing — every event is journaled with 202, and after the
    fault clears the replay worker lands exactly 200 events (no dupes),
    with pio_spill_queue_depth draining to 0.

    Deflaked (ISSUE 9 satellite): the breaker AND the replay worker's
    tick wait both ride injectable clocks now, so the drain is driven
    deterministically from the test thread (``drain_once``) with ZERO
    wall-clock sleeps/polls — the old version raced real replay-interval
    ticks against a real breaker-recovery timer and occasionally lost
    under full-suite load."""
    clock = SimpleNamespace(t=0.0)
    breaker = CircuitBreaker(
        "eventdata", failure_threshold=2, recovery_time_s=0.04,
        failure_types=(StorageUnavailable, ConnectionError),
        clock=lambda: clock.t)
    # Park the replay THREAD until stop: the injected wait ignores the
    # interval and blocks on the stop event, so the worker never races
    # the test's own deterministic drain_once() calls.
    srv, key, storage, app_id = _event_stack(
        pio_home, breaker=breaker, replay_interval_s=3600,
        replay_wait=lambda ev, timeout: ev.wait())
    try:
        faults.install("storage.create:error:1.0")
        statuses = []
        for start in range(0, 200, 50):
            batch = [{"event": "view", "entityType": "user",
                      "entityId": f"u{start + i}"} for i in range(50)]
            status, results = _post(srv, key, "/batch/events.json", batch)
            assert status == 200
            statuses.extend(r["status"] for r in results)
        assert statuses == [202] * 200
        assert srv.spill.depth() == 200
        assert breaker.state == "open"  # outage tripped it

        faults.clear()
        # Breaker still open on the fake clock: a drain tick pauses on
        # CircuitOpenError (transient) and loses nothing.
        assert srv._replay.drain_once() == 0
        assert srv.spill.depth() == 200
        # Advance past recovery: half-open lets the drain probe through,
        # the probe lands, the breaker closes, the queue drains fully.
        clock.t += 0.05
        assert srv._replay.drain_once() == 200
        assert srv.spill.depth() == 0
        assert get_registry().get("pio_spill_queue_depth").value() == 0
        assert get_registry().get("pio_spill_replayed_total").value() == 200

        events = list(storage.get_events().find(app_id))
        assert len(events) == 200  # exactly once: no loss, no duplicates
        assert {e.entity_id for e in events} == {f"u{i}" for i in range(200)}
        assert breaker.state == "closed"  # replay drain probed it closed
    finally:
        srv.stop()


def test_spill_journal_survives_restart(pio_home, tmp_path):
    j = SpillJournal(tmp_path / "sp")
    for i in range(3):
        j.append([{"event": "view", "entityType": "u", "entityId": str(i)}],
                 app_id=1, channel_id=None)
    j.mark_replayed(j.peek(1))
    j.close()
    j2 = SpillJournal(tmp_path / "sp")  # crash-restart: offset persisted
    assert j2.depth() == 2
    assert [r["events"][0]["entityId"] for r in j2.peek(10)] == ["1", "2"]
    j2.mark_replayed(j2.peek(10))
    assert j2.depth() == 0
    j2.close()


def test_spill_journal_truncates_torn_tail(pio_home, tmp_path):
    """A crash mid-append leaves a partial trailing line; it was never
    202-acked, so reopening drops it instead of killing the replayer."""
    j = SpillJournal(tmp_path / "sp")
    j.append([{"event": "view", "entityType": "u", "entityId": "whole"}],
             app_id=1, channel_id=None)
    j.close()
    with open(j.path, "a", encoding="utf-8") as f:
        f.write('{"token": "t", "appId": 1, "eve')  # torn mid-write
    j2 = SpillJournal(tmp_path / "sp")
    assert j2.depth() == 1
    recs = j2.peek(10)
    assert [r["events"][0]["entityId"] for r in recs] == ["whole"]
    j2.mark_replayed(recs)
    assert j2.depth() == 0
    j2.close()


def test_spill_journal_clamps_stale_offset(pio_home, tmp_path):
    """A crash between drain-truncate and offset reset must not leave an
    offset pointing past the (now shorter) journal — that would make
    peek() skip every future record forever."""
    j = SpillJournal(tmp_path / "sp")
    j.append([{"event": "view"}], app_id=1, channel_id=None)
    j.close()
    (tmp_path / "sp" / "spill.offset").write_text("999")  # stale
    j2 = SpillJournal(tmp_path / "sp")
    assert j2.depth() == 0  # clamped, not wedged
    j2.append([{"event": "later"}], app_id=1, channel_id=None)
    assert j2.depth() == 1
    assert [r["events"][0]["event"] for r in j2.peek(10)] == ["later"]
    j2.close()


def test_spilled_events_freeze_ingest_timestamps(pio_home):
    """The journal stores the PARSED event (event_to_json), so an event
    POSTed without an explicit eventTime keeps its ingest-time stamp
    through a replay hours later, instead of being re-stamped."""
    srv, key, *_ = _event_stack(pio_home, replay_interval_s=3600)
    try:
        faults.install("storage.create:error:1.0")
        status, _ = _post(srv, key, "/events.json",
                          {"event": "view", "entityType": "u",
                           "entityId": "x"})  # note: no eventTime
        assert status == 202
        rec = srv.spill.peek(1)[0]
        assert rec["events"][0]["eventTime"]  # frozen at ingest
        assert rec["events"][0]["creationTime"]
    finally:
        srv.stop()


def test_poison_record_dead_letters_instead_of_wedging(pio_home, tmp_path):
    """(replay liveness) A record that fails replay with a PERMANENT
    error is dead-lettered so the records behind it still drain;
    transient failures pause the drain without advancing."""
    from predictionio_tpu.resilience.spill import ReplayWorker

    j = SpillJournal(tmp_path / "sp")
    for name in ("ok1", "poison", "ok2"):
        j.append([{"event": name}], app_id=1, channel_id=None)
    landed = []

    def insert(rec):
        name = rec["events"][0]["event"]
        if name == "poison":
            raise ValueError("schema drift")
        landed.append(name)

    worker = ReplayWorker(j, insert, interval_s=3600)
    assert worker.drain_once() == 2
    assert landed == ["ok1", "ok2"]
    assert j.depth() == 0
    assert j.dead_path.exists()
    dead = [json.loads(line) for line in
            j.dead_path.read_text().splitlines()]
    assert [d["events"][0]["event"] for d in dead] == ["poison"]
    assert get_registry().get("pio_spill_dead_lettered_total").value() == 1

    # transient failure: nothing advances, nothing dead-letters
    j.append([{"event": "later"}], app_id=1, channel_id=None)

    def down(rec):
        raise ConnectionError("storage down")

    assert ReplayWorker(j, down, interval_s=3600).drain_once() == 0
    assert j.depth() == 1
    j.close()


def test_reads_shed_503_while_breaker_open(pio_home):
    srv, key, *_ = _event_stack(pio_home, spill_dir="off")
    try:
        faults.install("storage.*:error:1.0")
        for _ in range(srv._breaker.failure_threshold):
            status, _ = srv.handle("GET", "/events.json",
                                   {"accessKey": [key]}, b"")
            assert status == 503
        faults.clear()
        # breaker open: sheds WITHOUT touching storage, readiness flips
        assert srv._breaker.state == "open"
        status, _ = srv.handle("GET", "/events.json",
                               {"accessKey": [key]}, b"")
        assert status == 503
        status, body = srv.handle("GET", "/ready", {}, b"")
        assert (status, body["breaker"]) == (503, "open")
    finally:
        srv.stop()


def test_event_server_deadline_header_sheds_over_http(pio_home):
    srv, key, *_ = _event_stack(pio_home)
    srv.start()
    try:
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/events.json?accessKey={key}",
            data=b'{"event":"view","entityType":"u","entityId":"x"}',
            headers={"Content-Type": "application/json",
                     "X-PIO-Deadline-Ms": "0"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 504
        assert get_registry().get("pio_deadline_shed_total").value(
            server="event") == 1
        # a generous budget flows through to a normal 201
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/events.json?accessKey={key}",
            data=b'{"event":"view","entityType":"u","entityId":"x"}',
            headers={"Content-Type": "application/json",
                     "X-PIO-Deadline-Ms": "30000"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == 201
    finally:
        srv.stop()


def test_degraded_202_and_503_carry_retry_after(pio_home):
    srv, key, *_ = _event_stack(pio_home, replay_interval_s=3600)
    srv.start()
    try:
        import urllib.error
        import urllib.request

        faults.install("storage.create:error:1.0")
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/events.json?accessKey={key}",
            data=b'{"event":"view","entityType":"u","entityId":"x"}',
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == 202
            assert resp.headers["Retry-After"] == str(srv.retry_after_s)
            assert json.loads(resp.read())["token"]
    finally:
        srv.stop()


# --------------------------------------------------------------------------
# RemoteClient: retriable writes via idempotency tokens
# --------------------------------------------------------------------------


@pytest.fixture()
def remote_events(pio_home):
    from predictionio_tpu.data.storage import memory as m
    from predictionio_tpu.data.storage.remote import (
        RemoteClient,
        StorageServer,
    )

    class Hosted:
        def __init__(self):
            self._events = m.MemoryEvents()

        def get_events(self):
            return self._events

        def __getattr__(self, name):
            if name.startswith("get_"):
                return lambda: None
            raise AttributeError(name)

    srv = StorageServer(Hosted(), host="127.0.0.1", port=0)
    srv.start()
    client = RemoteClient("127.0.0.1", srv.port)
    repo = client.events()
    repo.init(1)
    yield repo, client
    client.close()
    srv.stop()


def test_write_retried_after_lost_reply_dedups(remote_events):
    """Acceptance: kill the connection after the server commits — the
    retried write carries the same idempotency token and the server's
    dedup window answers it without re-inserting (count stays 1)."""
    from predictionio_tpu.data.event import DataMap, Event

    repo, _client = remote_events
    # rpc.recv fires AFTER the request hit the wire: the server commits,
    # the client never sees the reply.  Exactly one injection.
    faults.install("rpc.recv:error:1.0:1")
    eid = repo.insert(Event(event="rate", entity_type="user",
                            entity_id="u1", properties=DataMap({})), 1)
    faults.clear()
    assert eid
    events = list(repo.find(1))
    assert len(events) == 1 and events[0].event_id == eid
    assert get_registry().get("pio_rpc_retries_total").value() >= 1


def test_pinned_idempotency_token_spans_connections(remote_events):
    """The spill replay pins its persisted token: issuing the SAME insert
    twice under one token lands exactly one event."""
    from predictionio_tpu.data.event import DataMap, Event

    repo, _client = remote_events
    ev = Event(event="buy", entity_type="user", entity_id="u2",
               properties=DataMap({}))
    with idempotency_key("tok-123"):
        first = repo.insert(ev, 1)
    with idempotency_key("tok-123"):
        second = repo.insert(ev, 1)
    assert first == second
    assert len(list(repo.find(1))) == 1


def test_dedup_window_serializes_inflight_retries():
    """A retry arriving while the ORIGINAL write is still executing must
    wait for it and take the cached reply — not re-execute concurrently
    (the duplicate-insert race for writes slower than the backoff)."""
    from predictionio_tpu.data.storage.remote import _DedupWindow

    w = _DedupWindow()
    assert w.begin("t1") is None  # original claims the token
    got = []
    th = threading.Thread(target=lambda: got.append(w.begin("t1")))
    th.start()
    time.sleep(0.05)
    assert got == []  # retry parked behind the in-flight original
    w.finish("t1", {"ok": 41})
    th.join(5)
    assert got == [{"ok": 41}]
    # failed originals are NOT cached: the retry re-executes
    assert w.begin("t2") is None
    w.finish("t2", None)
    assert w.begin("t2") is None
    w.finish("t2", {"ok": 42})


def test_exhausted_retries_surface_storage_unavailable(pio_home):
    from predictionio_tpu.data.storage.remote import RemoteClient

    # nothing listens on this port; tiny backoff keeps the test fast
    client = RemoteClient("127.0.0.1", 1, timeout=0.2,
                          retry=RetryPolicy(max_attempts=2, base_delay_ms=1))
    with pytest.raises(StorageUnavailable):
        client.call("events.insert", None, 1)
    client.close()


def test_recv_rejects_corrupt_length_prefix():
    from predictionio_tpu.data.storage import remote as r

    a, b = socket.socketpair()
    try:
        # 4 GB length prefix: the client must refuse BEFORE buffering
        b.sendall(struct.pack(">I", 0xFFFFFFFF))
        with pytest.raises(r.RemoteBackendError, match="oversized"):
            r._recv(a)
        # and the tighter auth-time cap rejects merely-large frames too
        a2, b2 = socket.socketpair()
        b2.sendall(struct.pack(">I", 2048) + b"x" * 2048)
        with pytest.raises(r.RemoteBackendError, match="oversized"):
            r._recv(a2, max_len=1 << 10)
        a2.close(), b2.close()
    finally:
        a.close(), b.close()


# --------------------------------------------------------------------------
# SDK: one exception surface
# --------------------------------------------------------------------------


def test_sdk_normalizes_connection_errors():
    from predictionio_tpu.sdk import EventClient, PredictionIOError

    c = EventClient("k", "http://127.0.0.1:1", timeout=0.2)  # refused
    with pytest.raises(PredictionIOError) as ei:
        c.set_user("u1")
    assert ei.value.status is None
    assert ei.value.retriable is True


def test_sdk_retries_connection_failures_with_backoff():
    from predictionio_tpu.sdk import EventClient, PredictionIOError

    slept = []
    c = EventClient("k", "http://127.0.0.1:1", timeout=0.2, retries=2)
    c.retry = RetryPolicy(max_attempts=3, base_delay_ms=10,
                          sleep=slept.append)
    with pytest.raises(PredictionIOError):
        c.set_user("u1")
    assert len(slept) == 2  # three attempts, two backoffs


def test_sdk_deadline_bounds_total_retry_time():
    """The client-declared budget covers the WHOLE call, retries and
    backoff included — each attempt sends the REMAINING budget and the
    call stops (non-retriably) once it is spent."""
    from predictionio_tpu.sdk import EventClient, PredictionIOError

    c = EventClient("k", "http://127.0.0.1:1", timeout=0.2,
                    retries=10, deadline_ms=60)
    c.retry = RetryPolicy(max_attempts=11, base_delay_ms=30, jitter=0)
    t0 = time.monotonic()
    with pytest.raises(PredictionIOError):
        c.set_user("u1")
    # the budget stops the run after ~2 of the 10 allowed 30ms backoffs
    # (the policy refuses to sleep past deadline_ts) — nowhere near the
    # ~300ms of full retries, let alone unbounded Retry-After sleeps
    assert time.monotonic() - t0 < 1.0
    # and a budget already spent before the first attempt fails fast,
    # non-retriably, without touching the network
    c0 = EventClient("k", "http://127.0.0.1:1", timeout=0.2,
                     deadline_ms=0)
    with pytest.raises(PredictionIOError) as ei:
        c0.set_user("u1")
    assert "deadline exhausted" in str(ei.value)
    assert ei.value.retriable is False


def test_sdk_normalizes_server_death_mid_response():
    """A server dying mid-body raises http.client.IncompleteRead, which
    must surface as PredictionIOError like every other transport fault."""
    from predictionio_tpu.sdk import EventClient, PredictionIOError

    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(1)
    port = lsock.getsockname()[1]

    def truncating_server():
        conn, _ = lsock.accept()
        conn.recv(65536)
        conn.sendall(b"HTTP/1.1 200 OK\r\nContent-Length: 100\r\n\r\nshort")
        conn.close()

    th = threading.Thread(target=truncating_server, daemon=True)
    th.start()
    try:
        c = EventClient("k", f"http://127.0.0.1:{port}", timeout=5)
        with pytest.raises(PredictionIOError) as ei:
            c.set_user("u1")
        assert ei.value.status is None and ei.value.retriable is True
    finally:
        lsock.close()


def test_spill_append_failure_rolls_back_cleanly(pio_home, tmp_path,
                                                 monkeypatch):
    """A failed fsync must not leave a half-accounted line that skews
    the position-based replay for records acked AFTER it."""
    import predictionio_tpu.resilience.spill as spill_mod

    j = SpillJournal(tmp_path / "sp")
    real_fsync = os.fsync
    monkeypatch.setattr(spill_mod.os, "fsync",
                        lambda fd: (_ for _ in ()).throw(OSError("ENOSPC")))
    with pytest.raises(OSError):
        j.append([{"event": "lost"}], app_id=1, channel_id=None)
    monkeypatch.setattr(spill_mod.os, "fsync", real_fsync)
    assert j.depth() == 0  # rolled back: the 503'd write left no trace
    j.append([{"event": "kept"}], app_id=1, channel_id=None)
    recs = j.peek(10)
    assert [r["events"][0]["event"] for r in recs] == ["kept"]
    j.mark_replayed(recs)
    assert j.depth() == 0
    j.close()


def test_spill_journal_second_instance_diverts(pio_home, tmp_path):
    """The journal format assumes one appender: a second instance on the
    same directory must divert to a private subdir instead of truncating
    or double-replaying under the first."""
    a = SpillJournal(tmp_path / "sp")
    b = SpillJournal(tmp_path / "sp")
    assert b.dir != a.dir and b.dir.parent == a.dir
    a.append([{"event": "av"}], app_id=1, channel_id=None)
    b.append([{"event": "bv"}], app_id=1, channel_id=None)
    assert [r["events"][0]["event"] for r in a.peek(10)] == ["av"]
    assert [r["events"][0]["event"] for r in b.peek(10)] == ["bv"]
    a.close()
    b.close()
    c = SpillJournal(tmp_path / "sp")  # lock released: adopts the main dir
    assert c.dir == a.dir and c.depth() == 1
    c.close()
