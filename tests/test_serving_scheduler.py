"""Serving scheduler (ISSUE 6): admission, micro-batching, autotune.

The deadline-window unit tests drive the batcher's gather/dispatch logic
directly with an injectable clock and a fake engine — zero wall sleeps,
the same discipline as tests/test_supervision.py.  One threaded
integration class exercises the real dispatcher thread and the engine
server's HTTP surface (429 + Retry-After, batcher metrics, retained-
previous eviction).
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from predictionio_tpu.obs import get_registry
from predictionio_tpu.resilience.deadline import (
    DeadlineExceeded,
    deadline_scope,
)
from predictionio_tpu.serving import (
    MicroBatcher,
    ModelQueue,
    Pending,
    QueueFull,
    SchedulerClosed,
    SchedulerConfig,
    ServingScheduler,
    WindowAutotuner,
)


class FakeClock:
    """now() is a dial; wait() advances it by the timeout and reports
    'no arrival' — a gather window passes with zero wall time."""

    def __init__(self, t=0.0):
        self.t = t
        self.waits = []

    def now(self):
        return self.t

    def wait(self, cond, timeout):
        self.waits.append(timeout)
        if timeout is not None:
            self.t += timeout
        return False


class FakeEngine:
    """Records each dispatch (queries, generation at snapshot).  The
    generation is snapshotted ONCE per call under a lock — the same
    contract as EngineServer._dispatch_batch — and ``swap_mid_dispatch``
    simulates a staged reload landing while the batch is in flight."""

    def __init__(self):
        self.generation = 1
        self.calls = []
        self.swap_mid_dispatch = False
        self._lock = threading.Lock()

    def dispatch(self, queries):
        with self._lock:
            gen = self.generation
            if self.swap_mid_dispatch:
                self.generation += 1  # the "reload" lands mid-batch
        self.calls.append((list(queries), gen))
        return [(q, gen) for q in queries], gen


def _batcher(engine=None, clock=None, depth=16, window_s=0.010,
             max_size=8, autotuner=None):
    engine = engine or FakeEngine()
    clock = clock or FakeClock()
    q = ModelQueue("m", depth)
    b = MicroBatcher("m", q, engine.dispatch, window_s=window_s,
                     max_size=max_size, clock=clock, autotuner=autotuner)
    return engine, clock, q, b


class TestDeadlineAwareWindow:
    def test_window_closes_early_under_deadline_pressure(self, pio_home):
        """A member with little slack pulls the close forward: the batch
        dispatches while the constrained request can still answer in
        time, instead of holding it for the full window."""
        engine, clock, q, b = _batcher(window_s=0.010)
        b._est_dispatch_s = 0.004  # EWMA: dispatch costs ~4ms
        tight = Pending("tight", clock.now(), deadline_s=0.006)
        loose = Pending("loose", clock.now(), deadline_s=None)
        q.put(tight)
        q.put(loose)
        batch = b.gather()
        assert {e.query for e in batch} == {"tight", "loose"}
        # window must have closed at deadline-est (6-4=2ms), NOT at 10ms
        assert clock.t == pytest.approx(0.002)
        n = b.dispatch(batch)
        assert n == 2
        assert len(engine.calls) == 1  # ONE coalesced dispatch
        assert tight.result == ("tight", 1)
        assert tight.error is None  # answered inside its budget

    def test_no_deadline_runs_the_full_window(self, pio_home):
        engine, clock, q, b = _batcher(window_s=0.010)
        q.put(Pending("a", clock.now()))
        batch = b.gather()
        assert clock.t == pytest.approx(0.010)
        assert len(batch) == 1

    def test_full_batch_skips_the_window(self, pio_home):
        engine, clock, q, b = _batcher(window_s=0.010, max_size=3)
        for i in range(3):
            q.put(Pending(i, clock.now()))
        batch = b.gather()
        assert len(batch) == 3
        assert clock.t == 0.0  # max_size reached: no window wait at all

    def test_lone_client_stream_stops_paying_the_window(self, pio_home):
        """Two consecutive singleton gathers prove the stream is a lone
        client: further singles dispatch immediately (no window tax), and
        the first multi-entry scoop re-arms the window."""
        engine, clock, q, b = _batcher(window_s=0.010)
        for _ in range(2):  # singles pay the window while streak builds
            q.put(Pending("s", clock.now()))
            t0 = clock.t
            b.gather()
            assert clock.t == pytest.approx(t0 + 0.010)
        q.put(Pending("s", clock.now()))
        t0 = clock.t
        assert len(b.gather()) == 1
        assert clock.t == t0  # streak >= 2: no window wait
        q.put(Pending("a", clock.now()))
        q.put(Pending("b", clock.now()))
        assert len(b.gather()) == 2  # scoop still coalesces concurrency
        q.put(Pending("s", clock.now()))
        t0 = clock.t
        b.gather()
        assert clock.t == pytest.approx(t0 + 0.010)  # window re-armed

    def test_zero_window_still_coalesces_the_backlog(self, pio_home):
        """Entries already queued batch for free — a zero window means
        'never WAIT for arrivals', not 'never batch': under overload the
        backlog coalesces with no added latency."""
        engine, clock, q, b = _batcher(window_s=0.0, max_size=8)
        for i in range(5):
            q.put(Pending(i, clock.now()))
        batch = b.gather()
        assert len(batch) == 5
        assert clock.t == 0.0  # zero wall/window time spent

    def test_expired_entries_shed_before_device_work(self, pio_home):
        """An entry whose deadline passed while queued is 504-shed pre-
        dispatch: the engine never sees it, the live cohort still runs."""
        engine, clock, q, b = _batcher()
        clock.t = 1.0
        dead = Pending("dead", 0.0, deadline_s=0.5)     # expired at t=1
        live = Pending("live", 0.9, deadline_s=None)
        b.dispatch([dead, live])
        assert isinstance(dead.error, DeadlineExceeded)
        assert live.result == ("live", 1)
        assert engine.calls == [(["live"], 1)]
        shed = get_registry().get("pio_queue_shed_total")
        assert shed.value(model="m", reason="expired") == 1

    def test_abandoned_entries_dropped_silently(self, pio_home):
        engine, clock, q, b = _batcher()
        gone = Pending("gone", 0.0)
        assert gone.abandon()  # the waiter walked (its deadline fired)
        b.dispatch([gone])
        assert engine.calls == []  # nothing live: no dispatch at all

    def test_failed_singleton_is_not_dispatched_twice(self, pio_home):
        """A failed batch of ONE must answer with the original error —
        re-dispatching the identical call would double the device work
        for the same outcome (and every inline-mode error with it)."""

        class Boom:
            calls = 0

            def dispatch(self, queries):
                Boom.calls += 1
                raise ValueError("kaput")

        q = ModelQueue("m", 4)
        b = MicroBatcher("m", q, Boom().dispatch, clock=FakeClock())
        solo = Pending("q", 0.0)
        b.dispatch([solo])
        assert isinstance(solo.error, ValueError)
        assert Boom.calls == 1

    def test_batch_error_isolates_per_member(self, pio_home):
        """One poisoned query 400s itself, not its cohort."""

        class Picky:
            def __init__(self):
                self.calls = 0

            def dispatch(self, queries):
                self.calls += 1
                if "bad" in queries:
                    raise ValueError("cannot bind 'bad'")
                return [q.upper() for q in queries], 3

        eng = Picky()
        clock = FakeClock()
        q = ModelQueue("m", 8)
        b = MicroBatcher("m", q, eng.dispatch, clock=clock)
        good, bad = Pending("ok", 0.0), Pending("bad", 0.0)
        b.dispatch([good, bad])
        assert good.result == "OK"
        assert isinstance(bad.error, ValueError)
        assert eng.calls == 3  # 1 batch attempt + 2 isolated retries


class TestGenerationAtomicity:
    def test_batch_never_spans_a_mid_flight_swap(self, pio_home):
        """A reload landing mid-dispatch must not split the batch: every
        member is answered by the ONE generation snapshotted at dispatch
        entry, and the NEXT batch picks up the new generation."""
        engine, clock, q, b = _batcher()
        engine.swap_mid_dispatch = True
        first = [Pending(f"a{i}", 0.0) for i in range(4)]
        b.dispatch(first)
        gens = {e.result[1] for e in first}
        assert gens == {1}, f"batch split across generations: {gens}"
        second = [Pending(f"b{i}", 0.0) for i in range(4)]
        b.dispatch(second)
        assert {e.result[1] for e in second} == {2}
        assert [g for _, g in engine.calls] == [1, 2]

    def test_concurrent_reloads_never_split_any_batch(self, pio_home):
        """Threaded version: submitters + a reload thread against the
        real dispatcher thread; every recorded dispatch must be answered
        by exactly one generation (consistency, not timing, is asserted)."""
        engine = FakeEngine()
        sched = ServingScheduler(SchedulerConfig(
            window_ms=2.0, max_batch=8, queue_depth=64, autotune=False))
        sched.register("m", engine.dispatch)
        stop = threading.Event()

        def reloader():
            while not stop.is_set():
                with engine._lock:
                    engine.generation += 1

        results = []
        res_lock = threading.Lock()

        def submitter(base):
            for i in range(16):
                r = sched.submit_and_wait("m", f"{base}-{i}")
                with res_lock:
                    results.append(r)

        rt = threading.Thread(target=reloader)
        rt.start()
        threads = [threading.Thread(target=submitter, args=(t,))
                   for t in range(4)]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            stop.set()
            rt.join()
            sched.close()
        assert len(results) == 64
        for queries, gen in engine.calls:
            answered = [g for rq, g in results if rq in queries]
            assert set(answered) == {gen}, \
                f"batch {queries} answered by generations {set(answered)}"


class TestAdmission:
    def test_queue_full_rejects(self, pio_home):
        q = ModelQueue("m", 2)
        q.put(Pending("a", 0.0))
        q.put(Pending("b", 0.0))
        with pytest.raises(QueueFull):
            q.put(Pending("c", 0.0))

    def test_abandoned_corpses_free_admission_slots(self, pio_home):
        """Entries whose waiter walked (deadline) must not hold queue
        slots against live traffic while a slow dispatch is in flight:
        a full-looking queue of corpses compacts at admission."""
        q = ModelQueue("m", 2)
        dead1, dead2 = Pending("d1", 0.0), Pending("d2", 0.0)
        q.put(dead1)
        q.put(dead2)
        assert dead1.abandon() and dead2.abandon()
        live = Pending("live", 0.0)
        q.put(live)  # corpses swept, slot freed — no QueueFull
        assert len(q) == 1

    def test_batch_retry_sheds_expired_members(self, pio_home):
        """The per-member retry after a failed batch re-checks budgets:
        a member that expired during the failed attempt sheds 504
        instead of burning a doomed device dispatch."""

        clock = FakeClock()
        calls = []

        def flaky(queries):
            calls.append(list(queries))
            if len(calls) == 1:
                clock.t = 1.0  # the failed attempt burns doomed's budget
                raise ConnectionError("backend blip")
            return [q.upper() for q in queries], 1

        q = ModelQueue("m", 8)
        b = MicroBatcher("m", q, flaky, clock=clock)
        doomed = Pending("dead", 0.0, deadline_s=0.5)
        alive = Pending("ok", 0.0, deadline_s=None)
        clock.t = 0.3  # doomed still in budget when the batch forms
        b.dispatch([doomed, alive])
        assert isinstance(doomed.error, DeadlineExceeded)
        assert alive.result == "OK"
        assert calls == [["dead", "ok"], ["ok"]]  # no doomed re-dispatch

    def test_per_model_isolation(self, pio_home):
        """Model A at capacity must not poison model B's admission."""
        qa, qb = ModelQueue("a", 1), ModelQueue("b", 1)
        qa.put(Pending("x", 0.0))
        with pytest.raises(QueueFull):
            qa.put(Pending("y", 0.0))
        qb.put(Pending("z", 0.0))  # unaffected
        assert len(qb) == 1

    def test_scheduler_per_model_isolation_end_to_end(self, pio_home):
        engine = FakeEngine()
        sched = ServingScheduler(SchedulerConfig(
            enabled=False, queue_depth=0))  # depth 0: reject everything
        sched.register("full", engine.dispatch)
        sched2 = ServingScheduler(SchedulerConfig(enabled=False,
                                                  queue_depth=4))
        sched2.register("open", engine.dispatch)
        with pytest.raises(QueueFull):
            sched.submit_and_wait("full", "q")
        assert sched2.submit_and_wait("open", "q") == ("q", 1)

    def test_inline_mode_dispatches_and_counts(self, pio_home):
        """PIO_BATCH_ENABLED=off: same scheduler surface, caller-thread
        dispatch, admission + metrics still live."""
        engine = FakeEngine()
        sched = ServingScheduler(SchedulerConfig(enabled=False,
                                                 queue_depth=4))
        sched.register("m", engine.dispatch)
        assert sched.submit_and_wait("m", "q1") == ("q1", 1)
        snap = sched.snapshot()["m"]
        assert snap["batching"] is False
        assert snap["requests"] == 1 and snap["dispatches"] == 1
        sched.close()

    def test_inline_expired_deadline_sheds_504(self, pio_home):
        engine = FakeEngine()
        sched = ServingScheduler(SchedulerConfig(enabled=False,
                                                 queue_depth=4))
        sched.register("m", engine.dispatch)
        with deadline_scope(0):
            with pytest.raises(DeadlineExceeded):
                sched.submit_and_wait("m", "q")
        assert engine.calls == []  # shed BEFORE the engine
        sched.close()

    def test_closed_scheduler_rejects(self, pio_home):
        engine = FakeEngine()
        sched = ServingScheduler(SchedulerConfig(enabled=False))
        sched.register("m", engine.dispatch)
        sched.close()
        with pytest.raises(SchedulerClosed):
            sched.submit_and_wait("m", "q")

    def test_config_from_env(self, pio_home, monkeypatch):
        monkeypatch.setenv("PIO_BATCH_ENABLED", "off")
        monkeypatch.setenv("PIO_QUEUE_DEPTH", "7")
        monkeypatch.setenv("PIO_BATCH_WINDOW_MS", "3.5")
        monkeypatch.setenv("PIO_BATCH_MAX", "bogus")  # falls to default
        cfg = SchedulerConfig.from_env()
        assert (cfg.enabled, cfg.queue_depth, cfg.window_ms,
                cfg.max_batch) == (False, 7, 3.5, 64)
        # flag overrides beat env
        cfg = SchedulerConfig.from_env(queue_depth=9)
        assert cfg.queue_depth == 9


class TestAutotuner:
    def _pair(self):
        engine, clock, q, b = _batcher(window_s=0.004, max_size=8)
        tuner = WindowAutotuner("m", 100.0, window_max_s=0.020,
                                max_size_cap=64)
        return b, tuner

    def test_over_target_shrinks_window_then_batch(self, pio_home):
        b, tuner = self._pair()
        tuner.retune(b, p99_ms=400.0)
        assert b.window_s == pytest.approx(0.002)
        tuner.retune(b, p99_ms=400.0)
        assert b.window_s == pytest.approx(0.001)
        for _ in range(8):  # halving must SNAP to the floor, not decay
            tuner.retune(b, p99_ms=400.0)
            if b.window_s == 0.0:
                break
        assert b.window_s == 0.0    # window at floor: batch is next...
        b._est_dispatch_s = 0.050   # ...and the dispatch IS slow (50ms)
        tuner.retune(b, p99_ms=400.0)
        assert b.max_size == 4

    def test_backlog_latency_never_shrinks_the_batch(self, pio_home):
        """Over-target p99 with a FAST dispatch means offered load >
        capacity — shrinking the batch would cut throughput and make the
        backlog worse, so the tuner floors instead."""
        b, tuner = self._pair()
        b.set_knobs(window_s=0.0)
        b._est_dispatch_s = 0.003  # 3ms dispatch << 100ms target
        tuner.retune(b, p99_ms=400.0)
        assert b.max_size == 8  # untouched
        acts = get_registry().get("pio_batch_autotune_total")
        assert acts.value(model="m", action="floor") == 1

    def test_under_target_grows_batch_then_window(self, pio_home):
        b, tuner = self._pair()
        tuner.retune(b, p99_ms=10.0)
        assert b.max_size == 16  # restore batching headroom first
        b.set_knobs(max_size=64)
        w0 = b.window_s
        tuner.retune(b, p99_ms=10.0)
        assert b.window_s > w0

    def test_hysteresis_band_holds(self, pio_home):
        b, tuner = self._pair()
        w0, m0 = b.window_s, b.max_size
        tuner.retune(b, p99_ms=80.0)  # between 60 and 100
        assert (b.window_s, b.max_size) == (w0, m0)
        acts = get_registry().get("pio_batch_autotune_total")
        assert acts.value(model="m", action="hold") == 1

    def test_after_dispatch_retunes_on_interval(self, pio_home):
        engine, clock, q, b = _batcher(window_s=0.004)
        tuner = WindowAutotuner("m", 100.0, interval=4)
        b.autotuner = tuner
        for _ in range(400):
            tuner.observe(500.0)  # way over target
        for _ in range(4):
            tuner.after_dispatch(b)
        assert b.window_s < 0.004
        assert tuner.last_p99_ms == pytest.approx(500.0)


@pytest.fixture()
def trained(pio_home):
    """A small trained ALS engine + its storage (the HTTP integration
    substrate; mirrors test_servers.deployed but keeps server
    construction in the tests so they can pass scheduler configs/env)."""
    from predictionio_tpu.controller import EngineVariant, RuntimeContext
    from predictionio_tpu.data.event import DataMap, Event
    from predictionio_tpu.data.storage import App, get_storage
    from predictionio_tpu.templates.recommendation import engine
    from predictionio_tpu.workflow.core_workflow import run_train

    storage = get_storage()
    ctx = RuntimeContext.create(storage=storage)
    app_id = storage.get_apps().insert(App(id=None, name="schedapp"))
    storage.get_events().init(app_id)
    rng = np.random.default_rng(0)
    for u in range(8):
        for i in range(6):
            if rng.random() < 0.8:
                storage.get_events().insert(
                    Event(event="rate", entity_type="user",
                          entity_id=f"u{u}", target_entity_type="item",
                          target_entity_id=f"i{i}",
                          properties=DataMap({"rating": 4.0})), app_id)
    variant = EngineVariant.from_dict({
        "engineFactory": "predictionio_tpu.templates.recommendation:engine",
        "datasource": {"params": {"appName": "schedapp"}},
        "algorithms": [{"name": "als",
                        "params": {"rank": 4, "numIterations": 3}}],
    })
    eng = engine()
    run_train(eng, variant, ctx)
    return eng, variant, storage, ctx


def _post(url, body):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, dict(resp.headers), \
                json.loads(resp.read() or b"null")
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, dict(e.headers), \
            json.loads(payload) if payload else None


class TestEngineServerIntegration:
    def test_queries_coalesce_over_http(self, trained, monkeypatch):
        """Concurrent POST /queries.json share dispatches: requests >
        dispatches once clients overlap (the tentpole, end to end).

        The result cache is disabled: this test pins the BATCHER path
        (repeated users would otherwise hit the cache and never reach
        the scheduler's admission)."""
        from predictionio_tpu.server import EngineServer

        monkeypatch.setenv("PIO_RESULT_CACHE", "0")
        eng, variant, storage, _ = trained
        srv = EngineServer(eng, variant, storage, host="127.0.0.1", port=0,
                           scheduler_config=SchedulerConfig(
                               window_ms=10.0, max_batch=16,
                               queue_depth=64, autotune=False))
        srv.start()
        try:
            statuses = []
            lock = threading.Lock()

            def one(i):
                s, _, body = _post(
                    f"http://127.0.0.1:{srv.port}/queries.json",
                    {"user": f"u{i % 8}", "num": 2})
                with lock:
                    statuses.append((s, body))

            threads = [threading.Thread(target=one, args=(i,))
                       for i in range(12)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert all(s == 200 for s, _ in statuses)
            snap = srv.scheduler.snapshot()["default"]
            assert snap["requests"] == 12
            assert snap["dispatches"] < 12, \
                "no coalescing happened at 12-way concurrency"
        finally:
            srv.stop()

    def test_late_2xx_rewritten_to_504_with_attestation(self, trained):
        """The transport's late-response shed (never-late-200): a
        handler that answers 200 past its budget is rewritten to 504,
        and the X-PIO-Deadline-Remaining-Ms attestation carries the
        same reading the verdict used."""
        import time as _time

        from predictionio_tpu.server import EngineServer

        eng, variant, storage, _ = trained
        srv = EngineServer(eng, variant, storage, host="127.0.0.1", port=0)
        srv.start()
        try:
            real_handle = srv.handle

            def slow_handle(method, path, body, params=None):
                _time.sleep(0.05)  # blows the 20ms budget below
                return 200, {"ok": 1}

            srv.handle = slow_handle
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/queries.json", data=b"{}",
                method="POST", headers={"X-PIO-Deadline-Ms": "20"})
            try:
                with urllib.request.urlopen(req, timeout=10) as resp:
                    status, headers = resp.status, resp.headers
            except urllib.error.HTTPError as e:
                status, headers = e.code, e.headers
            assert status == 504
            assert float(headers["X-PIO-Deadline-Remaining-Ms"]) <= 0
            assert get_registry().get("pio_deadline_shed_total").value(
                server="engine") >= 1
            # no deadline header → no gate, no attestation
            srv.handle = real_handle
            status, headers, _body = _post(
                f"http://127.0.0.1:{srv.port}/queries.json",
                {"user": "u0", "num": 2})
            assert status == 200
            assert "X-PIO-Deadline-Remaining-Ms" not in headers
        finally:
            srv.stop()

    def test_admission_full_answers_429_with_retry_after(self, trained):
        from predictionio_tpu.server import EngineServer

        eng, variant, storage, _ = trained
        srv = EngineServer(eng, variant, storage, host="127.0.0.1", port=0,
                           scheduler_config=SchedulerConfig(
                               enabled=False, queue_depth=0))
        srv.start()
        try:
            status, headers, body = _post(
                f"http://127.0.0.1:{srv.port}/queries.json",
                {"user": "u0", "num": 2})
            assert status == 429
            assert "Retry-After" in headers
            assert "full" in body["message"] or "limit" in body["message"]
            assert get_registry().get(
                "pio_queue_rejected_total").value(model="default") == 1
        finally:
            srv.stop()

    def test_batcher_surfaces_in_metrics_stats_and_status(self, trained):
        from predictionio_tpu.server import EngineServer

        eng, variant, storage, _ = trained
        srv = EngineServer(eng, variant, storage, host="127.0.0.1", port=0)
        srv.start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            assert _post(f"{base}/queries.json",
                         {"user": "u0", "num": 2})[0] == 200
            with urllib.request.urlopen(f"{base}/metrics",
                                        timeout=10) as resp:
                text = resp.read().decode()
            for family in ("pio_batch_size_bucket", "pio_queue_wait_ms",
                           "pio_batch_dispatch_total",
                           "pio_batch_dispatches_per_request",
                           "pio_batch_window_ms", "pio_queue_depth"):
                assert family in text, f"{family} missing from /metrics"
            with urllib.request.urlopen(f"{base}/stats.json",
                                        timeout=10) as resp:
                stats = json.loads(resp.read())
            assert stats["batcher"]["default"]["requests"] >= 1
            with urllib.request.urlopen(base, timeout=10) as resp:
                front = json.loads(resp.read())
            assert front["batcher"]["default"]["queueLimit"] >= 1
        finally:
            srv.stop()


class TestRetainedPreviousEviction:
    def _reloaded_server(self, trained, monkeypatch, **env):
        from predictionio_tpu.server import EngineServer
        from predictionio_tpu.workflow.core_workflow import run_train

        for k, v in env.items():
            monkeypatch.setenv(k, v)
        eng, variant, storage, ctx = trained
        srv = EngineServer(eng, variant, storage, host="127.0.0.1", port=0)
        run_train(eng, variant, ctx)   # a second instance to reload into
        srv.reload()
        return srv

    def test_retain_off_never_holds_a_second_generation(self, trained,
                                                        monkeypatch):
        srv = self._reloaded_server(trained, monkeypatch,
                                    PIO_RETAIN_PREVIOUS="off")
        try:
            assert srv._previous is None
            status, payload = srv.handle("GET", "/", b"")
            assert payload["rollbackAvailable"] is False
            status, payload = srv.handle("POST", "/admin/rollback", b"")
            assert status == 409
        finally:
            srv.stop()

    def test_rollback_inside_ttl_then_eviction_after(self, trained,
                                                     monkeypatch):
        """The satellite's pin: within the TTL the canary window is
        intact (rollback works); once the timer fires the previous
        generation is dropped and rollback answers 409."""
        srv = self._reloaded_server(trained, monkeypatch,
                                    PIO_RETAIN_PREVIOUS_TTL_S="300")
        try:
            assert srv._previous is not None
            assert srv._evict_timer is not None  # TTL armed
            gen_before = srv._generation
            # INSIDE the TTL: rollback still works (and re-arms)
            status, _ = srv.handle("POST", "/admin/rollback", b"")
            assert status == 200
            assert srv._generation == gen_before + 1
            # the timer fires (driven directly — no wall wait)
            assert srv._evict_previous(srv._generation) is True
            assert srv._previous is None
            reg = get_registry()
            assert reg.get(
                "pio_model_previous_evicted_total").value() == 1
            assert reg.get("pio_model_previous_retained").value() == 0
            # AFTER eviction: nothing to roll back to
            status, _ = srv.handle("POST", "/admin/rollback", b"")
            assert status == 409
        finally:
            srv.stop()

    def test_stale_eviction_timer_is_a_noop(self, trained, monkeypatch):
        """A timer armed for generation N must not evict the previous
        slot after a newer swap owns it."""
        srv = self._reloaded_server(trained, monkeypatch,
                                    PIO_RETAIN_PREVIOUS_TTL_S="300")
        try:
            stale_gen = srv._generation
            srv.rollback()  # newer swap: previous slot re-owned
            assert srv._evict_previous(stale_gen) is False
            assert srv._previous is not None
        finally:
            srv.stop()
