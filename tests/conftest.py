"""Test bootstrap: force an 8-device virtual CPU mesh before JAX initializes.

This is the rebuild's analogue of the reference's Spark ``local[n]`` test
substrate (SURVEY.md §4): real sharding/collective semantics, one process,
no accelerator.  Must run before any ``import jax`` resolves a backend.
"""

import os

# Hard override: the deploy environment pre-sets JAX_PLATFORMS to the TPU
# plugin AND initializes the backend from sitecustomize at interpreter start,
# so setting env vars here is not enough — clear the initialized backends,
# then re-select CPU.  Clear must come BEFORE the config update.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

try:  # private API — guard so a jax upgrade degrades to the env-var path
    from jax._src import xla_bridge

    if xla_bridge.backends_are_initialized():
        xla_bridge._clear_backends()
except (ImportError, AttributeError):
    pass
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture()
def pio_home(tmp_path, monkeypatch):
    """Isolated PIO_HOME per test (fresh storage singleton both sides).

    Also resets the process-wide observability state (metrics registry +
    trace ring): servers share ONE registry by design, so without a reset
    each test would see the previous tests' counts.
    """
    from predictionio_tpu.data.storage import reset_storage
    from predictionio_tpu.obs import reset_observability

    home = tmp_path / "pio_home"
    home.mkdir()
    monkeypatch.setenv("PIO_HOME", str(home))
    for k in list(os.environ):
        if k.startswith("PIO_STORAGE_"):
            monkeypatch.delenv(k, raising=False)
    reset_storage()
    reset_observability()
    yield home
    reset_storage()
    reset_observability()
    # Pay the GC debt at the TEST boundary, deterministically: live-HTTP
    # tests (fleet, refresh, servers) churn whole server stacks + model
    # arrays, and an automatic collection landing mid-request in a LATER
    # timing-sensitive test (e.g. the 95%-trace-coverage pin) reads as a
    # phantom unattributed gap on this 1-core box.
    import gc

    gc.collect()
