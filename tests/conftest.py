"""Test bootstrap: force an 8-device virtual CPU mesh before JAX initializes.

This is the rebuild's analogue of the reference's Spark ``local[n]`` test
substrate (SURVEY.md §4): real sharding/collective semantics, one process,
no accelerator.  Must run before any ``import jax`` resolves a backend.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import pytest  # noqa: E402


@pytest.fixture()
def pio_home(tmp_path, monkeypatch):
    """Isolated PIO_HOME per test."""
    home = tmp_path / "pio_home"
    home.mkdir()
    monkeypatch.setenv("PIO_HOME", str(home))
    for k in list(os.environ):
        if k.startswith("PIO_STORAGE_"):
            monkeypatch.delenv(k, raising=False)
    return home
