"""Every shipped example engine.json binds against its engine factory."""

import json
from pathlib import Path

import pytest

from predictionio_tpu.controller import EngineVariant, load_engine_factory

EXAMPLES = sorted(
    (Path(__file__).resolve().parents[1] / "examples").glob("*/engine.json"))


@pytest.mark.parametrize("path", EXAMPLES, ids=lambda p: p.parent.name)
def test_example_binds(path):
    variant = EngineVariant.from_file(path)
    engine = load_engine_factory(variant.engine_factory)()
    params = engine.bind_engine_params(variant.raw)
    assert params.algorithms_params
    assert engine.query_class is not None


def test_examples_cover_all_templates():
    names = {p.parent.name for p in EXAMPLES}
    assert names == {"recommendation", "classification", "similarproduct",
                     "ecommerce", "twotower", "dlrm"}
