"""tools/lint_dispatch.py: every server frontend rides BaseHandler.dispatch.

ISSUE 4 satellite — locks in PR 3's transport dedup: a new frontend that
bypasses dispatch (losing deadlines/shed/tracing) fails tier-1.
"""

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO / "tools"))

import lint_dispatch  # noqa: E402


def test_tree_is_clean():
    assert lint_dispatch.check(REPO) == []


def test_detects_handler_bypassing_dispatch():
    src = """
from predictionio_tpu.server.http import BaseHandler

class Sneaky(BaseHandler):
    def do_GET(self):
        self.send_response(200)
        self.wfile.write(b"{}")

    def do_POST(self):
        self.dispatch("POST")
"""
    violations = lint_dispatch.check_source(src, "sneaky.py")
    assert len(violations) == 3  # no dispatch + send_response + wfile.write
    assert any("do_GET" in v and "dispatch" in v for v in violations)
    assert any("send_response" in v for v in violations)
    assert any("wfile.write" in v for v in violations)
    assert not any("do_POST" in v for v in violations)


def test_detects_raw_basehttprequesthandler_subclass():
    src = """
from http.server import BaseHTTPRequestHandler

class Rogue(BaseHTTPRequestHandler):
    def do_GET(self):
        self.send_response(200)
"""
    violations = lint_dispatch.check_source(src, "rogue.py")
    assert len(violations) == 1
    assert "raw http.server handler" in violations[0]


def test_nested_handler_classes_are_checked():
    """The real frontends define their Handler inside _make_handler —
    the walker must reach nested ClassDefs."""
    src = """
def _make_handler(server_self):
    class Handler(BaseHandler):
        def do_GET(self):
            self.wfile.write(b"hi")
    return Handler
"""
    violations = lint_dispatch.check_source(src, "nested.py")
    assert any("Handler.do_GET" in v for v in violations)


def test_detects_direct_model_dispatch_from_handlers():
    """ISSUE 6 rule 4: handlers reach the model ONLY through the serving
    scheduler — a do_*/pio_handle/handle body calling .query()/
    .query_batch() is flagged wherever the class lives."""
    src = """
class SomeServer:
    def handle(self, method, path, body):
        if path == "/queries.json":
            return 200, self.query_batch([1])
        return 200, self.engine.query({"u": 1})

    def _dispatch_batch(self, qs):
        return self.query_batch(qs), 1  # NOT a handler: sanctioned
"""
    violations = lint_dispatch.check_source(src, "srv.py")
    assert len(violations) == 2
    assert all("serving scheduler" in v for v in violations)
    assert any(".query_batch" in v for v in violations)
    assert any(".query(" in v for v in violations)


def test_handler_via_scheduler_is_clean():
    src = """
class SomeServer:
    def handle(self, method, path, body):
        return 200, self.scheduler.submit_and_wait("default", body)

class Handler(BaseHandler):
    def do_POST(self):
        self.dispatch("POST")
"""
    assert lint_dispatch.check_source(src, "srv.py") == []


def test_main_exit_codes(tmp_path, capsys):
    assert lint_dispatch.main([str(REPO)]) == 0
    server_dir = tmp_path / "predictionio_tpu" / "server"
    server_dir.mkdir(parents=True)
    (server_dir / "bad.py").write_text(
        "class H(BaseHandler):\n    def do_GET(self):\n        pass\n")
    assert lint_dispatch.main([str(tmp_path)]) == 1
