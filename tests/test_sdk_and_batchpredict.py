"""Client SDK round-trips + `pio batchpredict` CLI."""

import json

import numpy as np
import pytest

from predictionio_tpu.controller import EngineVariant, RuntimeContext
from predictionio_tpu.data.event import DataMap, Event
from predictionio_tpu.data.storage import AccessKey, App, get_storage
from predictionio_tpu.sdk import EngineClient, EventClient, PredictionIOError
from predictionio_tpu.server import EngineServer, EventServer
from predictionio_tpu.templates.recommendation import engine
from predictionio_tpu.workflow.core_workflow import run_train


@pytest.fixture()
def event_stack(pio_home):
    storage = get_storage()
    app_id = storage.get_apps().insert(App(id=None, name="app1"))
    storage.get_events().init(app_id)
    key = storage.get_access_keys().insert(AccessKey(key="", app_id=app_id))
    srv = EventServer(storage=storage, host="127.0.0.1", port=0)
    srv.start()
    yield srv, key, storage, app_id
    srv.stop()


class TestEventClient:
    def test_crud_roundtrip(self, event_stack):
        srv, key, *_ = event_stack
        c = EventClient(key, f"http://127.0.0.1:{srv.port}")
        eid = c.record_user_action_on_item("rate", "u1", "i1",
                                           {"rating": 4.5})
        got = c.get_event(eid)
        assert got["event"] == "rate" and got["properties"]["rating"] == 4.5
        assert c.find_events(entityId="u1")
        c.delete_event(eid)
        with pytest.raises(PredictionIOError):
            c.get_event(eid)

    def test_batch_and_helpers(self, event_stack):
        srv, key, *_ = event_stack
        c = EventClient(key, f"http://127.0.0.1:{srv.port}")
        res = c.create_events([
            {"event": "view", "entityType": "user", "entityId": "u1",
             "targetEntityType": "item", "targetEntityId": "i1"},
            {"event": "view", "entityType": "user", "entityId": "u2",
             "targetEntityType": "item", "targetEntityId": "i2"},
        ])
        assert [r.status for r in res] == [201, 201]
        assert all(r.stored and r.event_id == str(r) for r in res)
        c.set_user("u3", {"age": 30})
        assert c.find_events(entityId="u3")[0]["properties"]["age"] == 30

    def test_bad_key(self, event_stack):
        srv, *_ = event_stack
        c = EventClient("WRONG", f"http://127.0.0.1:{srv.port}")
        with pytest.raises(PredictionIOError) as ei:
            c.set_user("u")
        assert ei.value.status == 401

    def test_create_event_typed_result(self, event_stack):
        """ROADMAP follow-on (e): the result says durably-stored vs
        journaled, while staying the old plain-string shape."""
        from predictionio_tpu.sdk import EventResult

        srv, key, *_ = event_stack
        c = EventClient(key, f"http://127.0.0.1:{srv.port}")
        r = c.create_event("rate", "user", "u9", "item", "i9",
                           {"rating": 3.0})
        assert isinstance(r, EventResult) and isinstance(r, str)
        assert r.stored and r.status == 201
        assert r.event_id == str(r) and r.token is None
        assert c.get_event(r)["event"] == "rate"  # str compat: r IS the id

    def test_create_event_spill_result(self, event_stack, monkeypatch):
        """A storage outage degrades to 202 + token: .stored is False and
        the token is NOT presented as an event id."""
        from predictionio_tpu.data.storage import StorageUnavailable

        srv, key, *_ = event_stack
        c = EventClient(key, f"http://127.0.0.1:{srv.port}")
        c.set_user("warm")  # prime the auth cache before the outage
        events = srv.storage.get_events()

        def down(*a, **k):
            raise StorageUnavailable("event store down")

        monkeypatch.setattr(type(events), "insert", down)
        r = c.create_event("rate", "user", "u1", "item", "i1")
        assert not r.stored and r.status == 202
        assert r.token == str(r) and r.event_id is None


def _train_reco(ctx):
    storage = ctx.storage
    app_id = storage.get_apps().insert(App(id=None, name="testapp"))
    storage.get_events().init(app_id)
    rng = np.random.default_rng(0)
    for u in range(10):
        for i in range(8):
            if i % 2 == u % 2 and rng.random() < 0.95:
                storage.get_events().insert(
                    Event(event="rate", entity_type="user", entity_id=f"u{u}",
                          target_entity_type="item", target_entity_id=f"i{i}",
                          properties=DataMap({"rating": 4.0})), app_id)
    variant_dict = {
        "engineFactory": "predictionio_tpu.templates.recommendation:engine",
        "datasource": {"params": {"appName": "testapp"}},
        "algorithms": [{"name": "als", "params": {"rank": 4, "numIterations": 5}}],
    }
    variant = EngineVariant.from_dict(variant_dict)
    eng = engine()
    run_train(eng, variant, ctx)
    return eng, variant, variant_dict


def test_engine_client(pio_home):
    storage = get_storage()
    ctx = RuntimeContext.create(storage=storage)
    eng, variant, _ = _train_reco(ctx)
    srv = EngineServer(eng, variant, storage, host="127.0.0.1", port=0)
    srv.start()
    try:
        c = EngineClient(f"http://127.0.0.1:{srv.port}")
        assert c.status()["status"] == "alive"
        res = c.send_query({"user": "u0", "num": 3})
        assert len(res["itemScores"]) == 3
    finally:
        srv.stop()


def test_cli_batchpredict(pio_home, tmp_path):
    from predictionio_tpu.cli.main import main

    storage = get_storage()
    ctx = RuntimeContext.create(storage=storage)
    _, _, variant_dict = _train_reco(ctx)
    ej = tmp_path / "engine.json"
    ej.write_text(json.dumps(variant_dict))
    qfile = tmp_path / "queries.ndjson"
    qfile.write_text("\n".join(
        json.dumps({"user": f"u{i}", "num": 2}) for i in range(5)))
    out = tmp_path / "preds.ndjson"
    rc = main(["batchpredict", "--engine-json", str(ej),
               "--input", str(qfile), "--output", str(out)])
    assert rc == 0
    lines = [json.loads(l) for l in out.read_text().splitlines()]
    assert len(lines) == 5
    assert all(len(l["prediction"]["itemScores"]) == 2 for l in lines)
    assert lines[0]["query"]["user"] == "u0"
